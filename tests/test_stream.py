"""End-to-end streaming tests (ISSUE 14 tentpole): incremental
FETCH-while-RUNNING delivery through the bounded per-query ring
(service/stream.py), producer backpressure against the byte cap,
STREAM_STALLED slow-consumer aborts (CANCELLED-class, never a breaker
strike), resume / double-FETCH byte consistency, drain integration,
and the router's windowed credit relay."""

import socket
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.batch import ColumnBatch
from blaze_tpu.config import EngineConfig, set_config
from blaze_tpu.errors import ErrorClass, classify
from blaze_tpu.exprs import Col
from blaze_tpu.ops import FilterExec, MemoryScanExec
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.plan.serde import task_to_proto
from blaze_tpu.router import Router, RouterServer
from blaze_tpu.router.failover import failover_action
from blaze_tpu.router.proxy import RouterVerbBackend
from blaze_tpu.runtime.gateway import TaskGatewayServer
from blaze_tpu.service import QueryService, QueryState, ServiceClient
from blaze_tpu.service.stream import (
    StreamBuffer,
    StreamSpliceError,
    StreamStalled,
)
from blaze_tpu.service.wire import ServiceError
from blaze_tpu.testing import chaos
from blaze_tpu.testing.chaos import Fault
from tests.test_router import Fleet, wait_done
from tests.test_service import GatedScan, wait_for


class GatedBatches(MemoryScanExec):
    """Deterministic multi-batch producer: holds at the start gate,
    then yields its fixed batches in order - the streaming tests'
    knob for 'execution is provably in progress when X happens'."""

    def __init__(self, batches, start=None):
        super().__init__([list(batches)], batches[0].schema)
        self.start_gate = start

    def execute(self, partition, ctx):
        if self.start_gate is not None:
            self.start_gate.wait(10)
        yield from self.partitions[0]


def int_batches(n=6, rows=20_000):
    return [
        ColumnBatch.from_pydict(
            {"a": np.arange(i * rows, (i + 1) * rows, dtype=np.int64)}
        )
        for i in range(n)
    ]


@pytest.fixture
def parquet_blob(tmp_path):
    """Serializable multi-part plan: small batch_size so one file
    becomes many stream parts."""
    set_config(EngineConfig(batch_size=512))
    rng = np.random.default_rng(19)
    p = str(tmp_path / "s.parquet")
    pq.write_table(
        pa.table({
            "k": pa.array(rng.integers(0, 50, 20_000), pa.int32()),
            "v": pa.array(rng.random(20_000), pa.float64()),
        }),
        p,
    )
    plan = FilterExec(
        ParquetScanExec([[FileRange(p)]]), Col("v") >= 0.0
    )
    yield task_to_proto(plan, 0)
    set_config(EngineConfig())


# ---------------------------------------------------------------------------
# ring unit behavior
# ---------------------------------------------------------------------------


def test_ring_fills_while_running_without_consumer():
    """No consumer attached = legacy behavior: the producer never
    blocks and parts accumulate for a later FETCH."""
    with QueryService(max_concurrency=1, enable_cache=False,
                      stream_buffer_bytes=1_000) as svc:
        # cap (1KB) far below total batch bytes: only an attached
        # consumer may gate the producer, never result() callers
        q = svc.submit_plan(
            MemoryScanExec([int_batches(4)], int_batches(1)[0].schema)
        )
        batches = svc.result(q.query_id, timeout=30)
        assert sum(rb.num_rows for rb in batches) == 4 * 20_000
        assert q.stream.finished
        assert q.stream.total_parts() == 4
        assert q.stream.backpressure_waits == 0


def test_backpressure_pins_high_water_at_cap():
    """An attached consumer slower than the producer parks the
    producer at the byte cap: buffered bytes never exceed
    cap-plus-one-part, and the wait is counted."""
    batches = int_batches(6)
    # each part materializes as ~20k int64 rows ~= 160KB of Arrow;
    # the cap leaves room for one part, never two
    part_bytes = 20_000 * 8
    cap = int(part_bytes * 1.25)
    start = threading.Event()
    with QueryService(max_concurrency=1, enable_cache=False,
                      stream_buffer_bytes=cap,
                      stream_stall_s=30.0) as svc:
        q = svc.submit_plan(GatedBatches(batches, start=start),
                            use_cache=False)
        sb = q.stream
        sb.attach()
        start.set()
        assert wait_for(lambda: sb.backpressure_waits > 0)
        assert not q.done  # producer parked mid-execution
        got = []
        i = 0
        while True:
            kind, payload = sb.next_ready(i, timeout=5.0)
            if kind == "part":
                got.append(payload)
                sb.mark_consumed(i)
                i += 1
            elif kind == "finished":
                break
            else:
                raise AssertionError(f"unexpected {kind}: {payload}")
        assert len(got) == 6
        assert wait_for(lambda: q.state is QueryState.DONE)
        assert sb.high_water <= cap + 2 * part_bytes
        assert svc.obs_counters["stream_backpressure_waits"] > 0
        st = svc.stats()["streaming"]
        assert st["enabled"] and st["buffer_high_water_bytes"] > 0
        # ring drained + slot released: nothing left reserved
        assert wait_for(
            lambda: svc.admission.stats()["reserved_bytes"] == 0
        )


def test_stalled_consumer_aborts_stream_stalled():
    """A consumer that attaches and then stops draining past the
    stall budget gets the query aborted STREAM_STALLED: CANCELLED
    terminal, preset classified error, ring and reservation freed."""
    batches = int_batches(6)
    part_bytes = 20_000 * 8
    cap = part_bytes + 1_000
    start = threading.Event()
    with QueryService(max_concurrency=1, enable_cache=False,
                      stream_buffer_bytes=cap,
                      stream_stall_s=0.4) as svc:
        q = svc.submit_plan(GatedBatches(batches, start=start),
                            use_cache=False)
        sb = q.stream
        sb.attach()
        start.set()
        kind, _ = sb.next_ready(0, timeout=5.0)
        assert kind == "part"
        sb.mark_consumed(0)
        # ... and never ask for another: the producer parks at the
        # cap, waits out the 0.4s budget, and aborts
        assert wait_for(lambda: q.done, timeout=10.0)
        assert q.state is QueryState.CANCELLED
        assert q.error.startswith("STREAM_STALLED")
        assert q.error_class == ErrorClass.CANCELLED.value
        assert sb.aborted == "STREAM_STALLED"
        assert sb.pending_bytes == 0
        assert svc.obs_counters["stream_stalls"] >= 1
        assert sb.high_water <= cap + 2 * part_bytes
        assert wait_for(
            lambda: svc.admission.stats()["reserved_bytes"] == 0
        )


def test_stream_stalled_is_never_a_breaker_strike():
    """Taxonomy pin: STREAM_STALLED is CANCELLED-class, and the
    router failover ladder surfaces CANCELLED instead of striking the
    replica's breaker - a slow CLIENT must never quarantine a healthy
    replica."""
    exc = StreamStalled("q-1")
    assert classify(exc) is ErrorClass.CANCELLED
    assert failover_action(ErrorClass.CANCELLED.value) == "surface"
    # splice divergence is the client's plan problem, also no strike
    assert classify(StreamSpliceError("x")) is ErrorClass.PLAN_INVALID
    assert failover_action(ErrorClass.PLAN_INVALID.value) == "surface"


def test_rollback_preserves_delivered_prefix_and_replay_verifies():
    """A failed attempt truncates only UNDELIVERED parts; the retry
    replays the delivered prefix and must match byte-for-byte."""

    class Q:
        cancel_requested = False

        @staticmethod
        def deadline_exceeded():
            return False

        @staticmethod
        def request_cancel(reason=None):
            pass

    rbs = [
        pa.record_batch([pa.array([i, i + 1])], names=["a"])
        for i in range(4)
    ]
    sb = StreamBuffer(1 << 20, 30.0)
    sb.attach()
    sb.put(Q, rbs[0])
    sb.put(Q, rbs[1])
    sb.mark_consumed(0)  # part 0 delivered - the floor
    sb.rollback(0)       # attempt failed: truncate undelivered
    assert sb.total_parts() == 1 and sb.consumed == 1
    sb.put(Q, rbs[0])    # replay verifies against delivered prefix
    sb.put(Q, rbs[2])    # then extends
    assert sb.total_parts() == 2
    # divergence on the delivered prefix is a splice break
    sb.rollback(0)
    with pytest.raises(StreamSpliceError):
        sb.put(Q, rbs[3])
    assert sb.aborted == "SPLICE_BROKEN"


# ---------------------------------------------------------------------------
# wire tier: FETCH-while-RUNNING
# ---------------------------------------------------------------------------


def test_fetch_delivers_first_part_while_running():
    """The tentpole: a FETCH issued against a RUNNING query starts
    yielding parts before execution finishes."""
    release = threading.Event()
    plan = GatedScan(release)
    with QueryService(max_concurrency=1, enable_cache=False) as svc:
        with TaskGatewayServer(service=svc) as srv:
            q = svc.submit_plan(plan, use_cache=False)
            assert wait_for(plan.started.wait, timeout=5.0)
            with ServiceClient(*srv.address) as c:
                it = c.fetch_stream(q.query_id)
                first = next(it)
                # the part is in hand and the query is still running
                assert first.num_rows >= 1
                assert not q.done
                assert q.state is QueryState.RUNNING
                release.set()
                rest = list(it)
            assert wait_for(lambda: q.state is QueryState.DONE)
            assert len(rest) + 1 == q.stream.total_parts()
            # live_parts made it onto the stream span's tags
            assert q.stream.consumed == q.stream.total_parts()


def test_double_fetch_of_live_stream_byte_identical():
    """Two concurrent FETCHes of one in-progress stream each get the
    complete part sequence: the ring retains consumed parts (it IS
    the resume source), so a second consumer starts from part 0."""
    release = threading.Event()
    plan = GatedScan(release)
    with QueryService(max_concurrency=1, enable_cache=False) as svc:
        with TaskGatewayServer(service=svc) as srv:
            q = svc.submit_plan(plan, use_cache=False)
            assert wait_for(plan.started.wait, timeout=5.0)
            got = {}

            def fetch(name, first_seen):
                with ServiceClient(*srv.address) as c:
                    parts = []
                    for rb in c.fetch_stream(q.query_id):
                        parts.append(rb)
                        if len(parts) == 1:
                            first_seen.set()
                    got[name] = parts

            seen_a, seen_b = threading.Event(), threading.Event()
            ta = threading.Thread(target=fetch, args=("a", seen_a))
            tb = threading.Thread(target=fetch, args=("b", seen_b))
            ta.start()
            assert seen_a.wait(5.0)  # a is mid-stream...
            tb.start()               # ...when b attaches
            assert seen_b.wait(5.0)
            release.set()
            ta.join(10)
            tb.join(10)
            assert not ta.is_alive() and not tb.is_alive()
    ta_tbl = pa.Table.from_batches(got["a"])
    tb_tbl = pa.Table.from_batches(got["b"])
    assert ta_tbl.equals(tb_tbl)
    assert len(got["a"]) == len(got["b"])


def test_attached_disconnect_mid_stream_cancels_and_frees(
    parquet_blob,
):
    """Session semantics over an in-progress stream: the client
    vanishing mid-FETCH of an ATTACHED query fires cancel-on-
    disconnect - the execution stops, the ring is freed, and the
    admission reservation returns to zero."""
    with QueryService(max_concurrency=1, enable_cache=False,
                      stream_buffer_bytes=16_000,
                      stream_stall_s=30.0) as svc:
        with TaskGatewayServer(service=svc) as srv:
            c = ServiceClient(*srv.address)
            st = c.submit(parquet_blob)  # attached
            qid = st["query_id"]
            it = c.fetch_stream(qid)
            next(it)  # one part in hand, producer parked at the cap
            q = svc.get(qid)
            assert not q.done
            c.close()  # vanish mid-stream
            assert wait_for(lambda: q.done, timeout=10.0)
            assert q.state is QueryState.CANCELLED
            assert q.stream.pending_bytes == 0
            assert q.stream.aborted is not None
            assert wait_for(
                lambda: svc.admission.stats()["reserved_bytes"] == 0
            )


def test_orphan_reap_with_partially_delivered_stream(parquet_blob):
    """serve --orphan-ttl: a detached query whose consumer read a
    part prefix and vanished is still an orphan once terminal and
    idle - the sweep reaps it and a late FETCH answers classified
    UNKNOWN, never a hang or a truncated stream."""
    with QueryService(max_concurrency=1, enable_cache=False,
                      orphan_ttl_s=0.3) as svc:
        with TaskGatewayServer(service=svc) as srv:
            with ServiceClient(*srv.address) as c:
                st = c.submit(parquet_blob, detach=True)
                qid = st["query_id"]
                it = c.fetch_stream(qid)
                next(it)  # partial delivery, then abandon
                it.close()
            q = svc.get(qid)
            assert wait_for(lambda: q.done, timeout=10.0)
            assert not q.fetched  # the stream never completed
            assert wait_for(
                lambda: svc.obs_counters["orphans_reaped"] >= 1,
                timeout=10.0,
            )
            with ServiceClient(*srv.address) as c2:
                with pytest.raises(ServiceError) as ei:
                    c2.fetch(qid)
            assert ei.value.state == "UNKNOWN"


def test_drain_waits_for_open_stream(parquet_blob):
    """Rolling-restart contract: a drain with an open in-progress
    stream holds until the consumer finishes pulling parts, then
    completes - the stream is never severed by the drain itself."""
    release = threading.Event()
    plan = GatedScan(release)
    with QueryService(max_concurrency=1, enable_cache=False) as svc:
        with TaskGatewayServer(service=svc) as srv:
            q = svc.submit_plan(plan, use_cache=False)
            assert wait_for(plan.started.wait, timeout=5.0)
            parts = []
            mid_stream = threading.Event()

            def consume():
                with ServiceClient(*srv.address) as c:
                    for rb in c.fetch_stream(q.query_id):
                        parts.append(rb)
                        mid_stream.set()

            tc = threading.Thread(target=consume)
            tc.start()
            assert mid_stream.wait(5.0)
            drained = []
            td = threading.Thread(
                target=lambda: drained.append(
                    svc.drain(timeout_s=15.0)
                )
            )
            td.start()
            time.sleep(0.3)
            # stream still open: the drain must be holding
            assert td.is_alive() and not drained
            # ... and refusing new submits while it holds
            q2 = svc.submit_plan(GatedScan(threading.Event()))
            assert q2.state is QueryState.REJECTED_OVERLOADED
            assert q2.error.startswith("DRAINING")
            release.set()
            tc.join(10)
            td.join(15)
            assert drained == [True]
            assert len(parts) == q.stream.total_parts()


# ---------------------------------------------------------------------------
# chaos seams
# ---------------------------------------------------------------------------


def test_stream_consume_drop_resumes_byte_identical(parquet_blob):
    """stream.consume DROP: the CLIENT connection dies after part 3
    is in hand; reconnect + re-FETCH resumes from the delivered
    prefix and the assembled table matches a clean run exactly."""
    with QueryService(max_concurrency=1, enable_cache=False) as svc:
        with TaskGatewayServer(service=svc) as srv:
            with ServiceClient(*srv.address) as c:
                baseline = pa.Table.from_batches(c.run(parquet_blob))
            with chaos.active(
                [Fault("stream.consume", klass="DROP",
                       partition=3, times=1)],
                seed=11,
            ) as plan:
                with ServiceClient(*srv.address) as c2:
                    st = c2.submit(parquet_blob, detach=True)
                    got = pa.Table.from_batches(
                        list(c2.fetch_stream(st["query_id"]))
                    )
                assert plan.fired("stream.consume") == 1
    assert got.equals(baseline)


def test_stream_consume_stall_slows_but_completes(parquet_blob):
    """stream.consume STALL: a slow consumer (well inside the stall
    budget) only delays delivery - same bytes, stream completes."""
    with QueryService(max_concurrency=1, enable_cache=False,
                      stream_stall_s=30.0) as svc:
        with TaskGatewayServer(service=svc) as srv:
            with ServiceClient(*srv.address) as c:
                baseline = pa.Table.from_batches(c.run(parquet_blob))
            with chaos.active(
                [Fault("stream.consume", klass="STALL",
                       stall_s=0.05, times=3)],
                seed=5,
            ) as plan:
                with ServiceClient(*srv.address) as c2:
                    got = pa.Table.from_batches(c2.run(parquet_blob))
                assert plan.fired("stream.consume") == 3
    assert got.equals(baseline)


# ---------------------------------------------------------------------------
# router tier: windowed credit relay
# ---------------------------------------------------------------------------


def router_dataset(tmp_path):
    rng = np.random.default_rng(29)
    p = str(tmp_path / "r.parquet")
    pq.write_table(
        pa.table({
            "k": pa.array(rng.integers(0, 40, 12_000), pa.int32()),
            "v": pa.array(rng.random(12_000), pa.float64()),
        }),
        p,
    )
    plan = FilterExec(
        ParquetScanExec([[FileRange(p)]]), Col("v") >= 0.0
    )
    return task_to_proto(plan, 0)


def test_router_windowed_relay_byte_identical(tmp_path):
    """The windowed relay forwards the same raw part bytes the
    replica produced: a table fetched through the router equals one
    fetched directly, and the streaming knobs surface in stats."""
    set_config(EngineConfig(batch_size=512))
    try:
        blob = router_dataset(tmp_path)
        with Fleet(router_kw={"stream_window": 3}) as fl:
            with RouterServer(fl.router) as rs:
                with ServiceClient(*rs.address) as c:
                    got = pa.Table.from_batches(c.run(blob))
            direct_svc, direct_srv = fl.by_id[fl.specs[0]]
            with ServiceClient(*direct_srv.address) as c:
                direct = pa.Table.from_batches(c.run(blob))
            st = fl.router.stats()["router"]
            assert st["streaming"]["window"] == 3
            assert "stream_window_waits" in st
        assert got.equals(direct)
    finally:
        set_config(EngineConfig())


def test_router_relay_survives_replica_drop_mid_stream(tmp_path):
    """gateway.stream DROP during the router's downstream FETCH: the
    windowed reader surfaces the transport error, the ladder re-
    FETCHes (replica still routable), and the client's table is
    byte-complete with the delivered prefix verified."""
    set_config(EngineConfig(batch_size=512))
    try:
        blob = router_dataset(tmp_path)
        with Fleet(router_kw={"stream_window": 4}) as fl:
            with RouterServer(fl.router) as rs:
                with ServiceClient(*rs.address) as c:
                    baseline = pa.Table.from_batches(c.run(blob))
                with chaos.active(
                    [Fault("gateway.stream", klass="DROP",
                           partition=1, times=1)],
                    seed=13,
                ) as plan:
                    with ServiceClient(*rs.address) as c2:
                        st = c2.submit(blob)
                        got = pa.Table.from_batches(
                            c2.fetch(st["query_id"])
                        )
                    assert plan.fired("gateway.stream") == 1
        assert got.equals(baseline)
    finally:
        set_config(EngineConfig())


def test_router_relay_stall_budget_aborts_slow_client():
    """RouterVerbBackend.fetch: a client that stops accepting bytes
    past stream_stall_s gets the relay aborted with a counted stall
    and a ConnectionError (connection teardown, no ERR frame, no
    breaker involvement)."""
    router = Router([], start=False, stream_stall_s=0.4)
    try:
        payload = b"\x00" * (1 << 20)
        router.stream_parts = (
            lambda qid, timeout_ms: iter([payload] * 64)
        )
        backend = RouterVerbBackend(router)
        s_srv, s_cli = socket.socketpair()
        s_srv.setsockopt(
            socket.SOL_SOCKET, socket.SO_SNDBUF, 16_384
        )
        errs = []

        def run():
            try:
                backend.fetch(s_srv, "q-stall", 0)
            except Exception as e:  # noqa: BLE001 - under test
                errs.append(e)

        t = threading.Thread(target=run)
        t.start()
        t.join(timeout=10.0)  # never read from s_cli
        assert not t.is_alive()
        assert errs and isinstance(errs[0], ConnectionError)
        assert "stalled" in str(errs[0])
        assert router.counters["stream_stalls"] == 1
        s_srv.close()
        s_cli.close()
    finally:
        router.close()
