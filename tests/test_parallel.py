"""Distributed tier tests on the virtual 8-device CPU mesh: exchange
operators (file tier), all_to_all repartition, sharded group-by (ICI
tier)."""

import numpy as np
import pyarrow as pa
import pytest

import jax
import jax.numpy as jnp

from blaze_tpu import ColumnBatch
from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.exprs.ir import bind
from blaze_tpu.ops import (
    AggMode,
    ExecContext,
    HashAggregateExec,
    MemoryScanExec,
)
from blaze_tpu.parallel import (
    BroadcastExchangeExec,
    CoalescedShuffleReader,
    ShuffleExchangeExec,
    get_mesh,
)
from blaze_tpu.parallel.repartition import all_to_all_repartition
from blaze_tpu.parallel.sharded import DistAgg, DistributedGroupBy
from blaze_tpu.runtime.executor import run_plan


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def multi_partition_scan(n_parts=4, rows_per=100):
    parts = []
    schema = None
    for p in range(n_parts):
        cb = ColumnBatch.from_pydict(
            {
                "k": [(p * rows_per + i) % 10 for i in range(rows_per)],
                "v": [p * rows_per + i for i in range(rows_per)],
            }
        )
        schema = cb.schema
        parts.append([cb])
    return MemoryScanExec(parts, schema)


def test_shuffle_exchange_end_to_end(tmp_path):
    scan = multi_partition_scan()
    ex = ShuffleExchangeExec(
        scan, [Col("k")], 5, shuffle_dir=str(tmp_path)
    )
    # distributed two-phase aggregate across the exchange
    final = HashAggregateExec(
        ex,
        keys=[(Col("k"), "k")],
        aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
              (AggExpr(AggFn.COUNT_STAR, None), "n")],
        mode=AggMode.COMPLETE,
    )
    out = run_plan(final).to_pydict()
    got = dict(zip(out["k"], out["s"]))
    all_rows = [(i % 10, i) for i in range(400)]
    exp = {}
    for k, v in all_rows:
        exp[k] = exp.get(k, 0) + v
    assert got == exp
    assert sum(out["n"]) == 400


def test_coalesced_reader(tmp_path):
    scan = multi_partition_scan()
    ex = ShuffleExchangeExec(
        scan, [Col("k")], 8, shuffle_dir=str(tmp_path)
    )
    rd = CoalescedShuffleReader(ex, [(0, 4), (4, 8)])
    assert rd.partition_count == 2
    total = sum(
        b.num_rows
        for p in range(2)
        for b in rd.execute(p, ExecContext())
    )
    assert total == 400


def test_broadcast_exchange():
    scan = multi_partition_scan(2, 10)
    bc = BroadcastExchangeExec(scan, num_partitions=3)
    ctx = ExecContext()
    rows_per_consumer = [
        sum(b.num_rows for b in bc.execute(p, ctx)) for p in range(3)
    ]
    assert rows_per_consumer == [20, 20, 20]  # full copy everywhere


def test_all_to_all_repartition():
    mesh = get_mesh()
    n_dev, cap = 8, 32
    rng = np.random.default_rng(3)
    vals = jnp.asarray(rng.integers(0, 1000, (n_dev, cap)))
    target = jnp.asarray(rng.integers(0, n_dev, (n_dev, cap)),
                         dtype=jnp.int32)
    live = jnp.asarray(rng.random((n_dev, cap)) < 0.7)
    (out_vals,), out_live = all_to_all_repartition(
        mesh, [vals], target, live
    )
    # every live row lands on its target device exactly once
    v_np, t_np, l_np = map(np.asarray, (vals, target, live))
    ov, ol = np.asarray(out_vals), np.asarray(out_live)
    for d in range(n_dev):
        expected = sorted(v_np[l_np & (t_np == d)].tolist())
        got = sorted(ov[d][ol[d]].tolist())
        assert got == expected, d


def test_distributed_group_by():
    mesh = get_mesh()
    n_dev, cap = 8, 64
    rng = np.random.default_rng(11)
    keys = rng.integers(0, 13, (n_dev, cap)).astype(np.int64)
    vals = rng.integers(0, 100, (n_dev, cap)).astype(np.int64)
    num_rows = rng.integers(10, cap + 1, n_dev).astype(np.int32)

    from blaze_tpu.types import DataType, Field, Schema

    schema = Schema(
        [Field("k", DataType.int64()), Field("v", DataType.int64())]
    )
    gb = DistributedGroupBy(
        mesh, schema,
        keys=[Col("k")],
        aggs=[DistAgg(AggFn.SUM, Col("v")),
              DistAgg(AggFn.COUNT_STAR, None),
              DistAgg(AggFn.MIN, Col("v")),
              DistAgg(AggFn.AVG, Col("v"))],
        filter_pred=Col("v") >= 10,
    )
    key_out, agg_out, counts = gb(
        [jnp.asarray(keys), jnp.asarray(vals)], jnp.asarray(num_rows)
    )
    # flatten device-owned groups
    got = {}
    ko = np.asarray(key_out[0])
    sums, cnts, mins, avgs = map(np.asarray, agg_out)
    cn = np.asarray(counts)
    for d in range(n_dev):
        for g in range(int(cn[d])):
            k = int(ko[d, g])
            assert k not in got, "group split across devices"
            got[k] = (
                int(sums[d, g]), int(cnts[d, g]), int(mins[d, g]),
                float(avgs[d, g]),
            )
    # differential reference
    exp = {}
    for d in range(n_dev):
        for i in range(int(num_rows[d])):
            if vals[d, i] < 10:
                continue
            k = int(keys[d, i])
            s, c, m = exp.get(k, (0, 0, 10**9))
            exp[k] = (s + int(vals[d, i]), c + 1,
                      min(m, int(vals[d, i])))
    exp_full = {
        k: (s, c, m, s / c) for k, (s, c, m) in exp.items()
    }
    assert set(got) == set(exp_full)
    for k in exp_full:
        assert got[k][:3] == exp_full[k][:3], k
        np.testing.assert_allclose(got[k][3], exp_full[k][3])


def test_distributed_broadcast_join():
    from blaze_tpu.parallel.sharded import DistributedBroadcastJoin
    from blaze_tpu.types import DataType, Field, Schema

    mesh = get_mesh()
    n_dev, p_cap, b_cap = 8, 32, 8
    rng = np.random.default_rng(21)
    # build: 8*8 slots, unique keys 0..n_build-1 scattered over shards
    build_rows = rng.integers(2, b_cap + 1, n_dev).astype(np.int32)
    all_keys = rng.permutation(500)[: int(build_rows.sum())]
    bk = np.zeros((n_dev, b_cap), dtype=np.int64)
    bv = np.zeros((n_dev, b_cap), dtype=np.int64)
    it = iter(all_keys)
    for d in range(n_dev):
        for i in range(int(build_rows[d])):
            k = int(next(it))
            bk[d, i] = k
            bv[d, i] = k * 100
    probe_rows = rng.integers(5, p_cap + 1, n_dev).astype(np.int32)
    pk = rng.integers(0, 500, (n_dev, p_cap)).astype(np.int64)
    pv = rng.integers(0, 10, (n_dev, p_cap)).astype(np.int64)

    p_schema = Schema([Field("pk", DataType.int64()),
                       Field("pv", DataType.int64())])
    b_schema = Schema([Field("bk", DataType.int64()),
                       Field("bv", DataType.int64())])
    from blaze_tpu.exprs import Col

    j = DistributedBroadcastJoin(
        mesh, p_schema, b_schema, Col("pk"), Col("bk")
    )
    hit, build_out = j(
        [jnp.asarray(pk), jnp.asarray(pv)], jnp.asarray(probe_rows),
        [jnp.asarray(bk), jnp.asarray(bv)], jnp.asarray(build_rows),
    )
    hit = np.asarray(hit)
    got_bv = np.asarray(build_out[1])
    key_set = set(int(k) for k in all_keys)
    for d in range(n_dev):
        for i in range(int(probe_rows[d])):
            expected = int(pk[d, i]) in key_set
            assert bool(hit[d, i]) == expected, (d, i)
            if expected:
                assert int(got_bv[d, i]) == int(pk[d, i]) * 100
        assert not hit[d, int(probe_rows[d]):].any()


def test_mesh_group_by_exec():
    from blaze_tpu.parallel.mesh_ops import MeshGroupByExec
    from blaze_tpu.runtime.executor import run_plan

    scan = multi_partition_scan(6, 80)  # 6 partitions <= 8 devices
    op = MeshGroupByExec(
        scan,
        keys=[(Col("k"), "k")],
        aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
              (AggExpr(AggFn.COUNT_STAR, None), "n")],
    )
    out = run_plan(op).to_pandas().sort_values("k").reset_index(drop=True)
    import pandas as pd

    rows = [(i % 10, i) for i in range(480)]
    ref = (
        pd.DataFrame(rows, columns=["k", "v"])
        .groupby("k")
        .agg(s=("v", "sum"), n=("v", "size"))
        .reset_index()
    )
    np.testing.assert_array_equal(out["k"], ref["k"])
    np.testing.assert_array_equal(out["s"], ref["s"])
    np.testing.assert_array_equal(out["n"], ref["n"])


def test_all_to_all_repartition_slack_and_skew_retry():
    """Slack-sized buckets shrink the exchanged footprint; pathological
    skew (every row to one device) overflows them and the retry at
    worst-case capacity keeps the result exact."""
    mesh = get_mesh()
    n_dev, cap = 8, 512
    rng = np.random.default_rng(7)
    vals = jnp.asarray(rng.integers(0, 1000, (n_dev, cap)))
    live = jnp.ones((n_dev, cap), dtype=bool)

    # uniform targets: slack path, exchanged rows per shard shrink
    # (slack must cover the max-of-buckets statistical spread)
    target_u = jnp.asarray(
        rng.integers(0, n_dev, (n_dev, cap)), dtype=jnp.int32
    )
    (out_u,), live_u = all_to_all_repartition(
        mesh, [vals], target_u, live, slack=2.0
    )
    assert out_u.shape[1] < n_dev * cap  # slack buckets, not worst-case
    v_np, t_np = np.asarray(vals), np.asarray(target_u)
    for d in range(n_dev):
        expected = sorted(v_np[t_np == d].tolist())
        got = sorted(
            np.asarray(out_u)[d][np.asarray(live_u)[d]].tolist()
        )
        assert got == expected, d

    # full skew: everything to device 3 -> overflow -> retry, exact
    target_s = jnp.full((n_dev, cap), 3, dtype=jnp.int32)
    (out_s,), live_s = all_to_all_repartition(
        mesh, [vals], target_s, live, slack=2.0
    )
    got3 = sorted(np.asarray(out_s)[3][np.asarray(live_s)[3]].tolist())
    assert got3 == sorted(v_np.reshape(-1).tolist())
    for d in range(n_dev):
        if d != 3:
            assert not np.asarray(live_s)[d].any()


def test_lower_to_mesh_complete_aggregate():
    """planner.distribute.lower_to_mesh sends a COMPLETE grouped
    aggregate (the shape a decoded single-stage TaskDefinition carries)
    to MeshGroupByExec, and the mesh result matches the per-partition
    engine result merged in pandas."""
    from blaze_tpu.parallel.mesh_ops import MeshGroupByExec
    from blaze_tpu.planner.distribute import lower_to_mesh

    scan = multi_partition_scan(n_parts=8, rows_per=300)
    plan = HashAggregateExec(
        scan,
        keys=[(Col("k"), "k")],
        aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
              (AggExpr(AggFn.COUNT_STAR, None), "n")],
        mode=AggMode.COMPLETE,
    )
    lowered = lower_to_mesh(plan)
    assert isinstance(lowered, MeshGroupByExec)
    got = (
        run_plan(lowered).to_pandas().sort_values("k")
        .reset_index(drop=True)
    )
    df = run_plan(scan).to_pandas()
    want = (
        df.groupby("k").agg(s=("v", "sum"), n=("v", "size"))
        .reset_index().sort_values("k").reset_index(drop=True)
    )
    np.testing.assert_array_equal(got["k"], want["k"])
    np.testing.assert_allclose(got["s"], want["s"])
    np.testing.assert_array_equal(got["n"], want["n"])


def test_lower_to_mesh_exchange_sandwich_and_fallback():
    """The FINAL-over-hash-exchange-over-PARTIAL sandwich that
    insert_exchanges plants lowers to ONE MeshGroupByExec; string-keyed
    aggregates stay on the file-shuffle tier (tryConvert fallback)."""
    from blaze_tpu.exprs.ir import AggExpr as _AE
    from blaze_tpu.parallel.mesh_ops import MeshGroupByExec
    from blaze_tpu.planner.distribute import insert_exchanges, lower_to_mesh

    scan = multi_partition_scan(n_parts=4, rows_per=200)
    plan = HashAggregateExec(
        scan,
        keys=[(Col("k"), "k")],
        aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
              (AggExpr(AggFn.MAX, Col("v")), "m")],
        mode=AggMode.COMPLETE,
    )
    import tempfile

    sandwich = insert_exchanges(plan, 4,
                                shuffle_dir=tempfile.mkdtemp())
    # sanity: insert_exchanges really made FINAL / exchange / PARTIAL
    assert sandwich.mode is AggMode.FINAL
    lowered = lower_to_mesh(sandwich)
    assert isinstance(lowered, MeshGroupByExec)
    got = (
        run_plan(lowered).to_pandas().sort_values("k")
        .reset_index(drop=True)
    )
    df = run_plan(multi_partition_scan(n_parts=4,
                                       rows_per=200)).to_pandas()
    want = (
        df.groupby("k").agg(s=("v", "sum"), m=("v", "max"))
        .reset_index().sort_values("k").reset_index(drop=True)
    )
    np.testing.assert_allclose(got["s"], want["s"])
    np.testing.assert_array_equal(got["m"], want["m"])

    # string keys gate out (host hashing tier): node left untouched
    strings = pa.record_batch(
        {"name": pa.array(["a", "b", "a", "c"]).dictionary_encode(),
         "v": pa.array([1, 2, 3, 4], type=pa.int64())}
    )
    cb = ColumnBatch.from_arrow(strings)
    splan = HashAggregateExec(
        MemoryScanExec([[cb]], cb.schema),
        keys=[(Col("name"), "name")],
        aggs=[(AggExpr(AggFn.SUM, Col("v")), "s")],
        mode=AggMode.COMPLETE,
    )
    assert lower_to_mesh(splan) is splan
