"""Object-store seam + AQE shuffle-reader spec tests."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import ColumnBatch
from blaze_tpu.exprs import Col
from blaze_tpu.io.object_store import (
    CallbackStore,
    MemoryStore,
    decode_smuggled_path,
    encode_smuggled_path,
    register_store,
)
from blaze_tpu.ops import ExecContext, MemoryScanExec
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.parallel import CoalescedShuffleReader, ShuffleExchangeExec
from blaze_tpu.parallel.exchange import plan_coalesced_partitions
from blaze_tpu.runtime.executor import run_plan


def test_memory_store_scan(tmp_path):
    tbl = pa.table({"a": list(range(50))})
    local = str(tmp_path / "m.parquet")
    pq.write_table(tbl, local)
    store = MemoryStore()
    with open(local, "rb") as f:
        store.put("mem://t/m.parquet", f.read())
    register_store("mem", store)
    scan = ParquetScanExec([[FileRange("mem://t/m.parquet")]])
    out = run_plan(scan)
    assert sorted(out.to_pydict()["a"]) == list(range(50))


def test_callback_store_and_smuggled_paths(tmp_path):
    tbl = pa.table({"a": [1, 2, 3]})
    local = str(tmp_path / "c.parquet")
    pq.write_table(tbl, local)
    reads = []

    def read_range(path, off, length):
        reads.append((path, off, length))
        with open(path, "rb") as f:
            f.seek(off)
            return f.read(length)

    register_store(
        "hdfs", CallbackStore(read_range, lambda p: __import__("os")
                              .path.getsize(p))
    )
    smuggled = encode_smuggled_path("hdfs", local)
    assert decode_smuggled_path(smuggled) == local
    scan = ParquetScanExec([[FileRange(smuggled)]])
    out = run_plan(scan)
    assert out.to_pydict()["a"] == [1, 2, 3]
    assert reads  # IO proxied through the callback


def _exchange(tmp_path, n_parts=6, n_maps=3):
    parts = []
    schema = None
    for m in range(n_maps):
        cb = ColumnBatch.from_pydict(
            {"k": list(range(m * 100, m * 100 + 100))}
        )
        schema = cb.schema
        parts.append([cb])
    scan = MemoryScanExec(parts, schema)
    return ShuffleExchangeExec(
        scan, [Col("k")], n_parts, shuffle_dir=str(tmp_path)
    )


def test_map_output_statistics(tmp_path):
    ex = _exchange(tmp_path)
    ctx = ExecContext()
    stats = ex.map_output_statistics(ctx)
    assert len(stats) == 6
    assert sum(stats) > 0


def test_partial_reducer_spec(tmp_path):
    """Skew split: one reduce partition served by disjoint map ranges
    must reproduce exactly the full partition."""
    ex = _exchange(tmp_path, n_parts=4, n_maps=3)
    ctx = ExecContext()
    full = CoalescedShuffleReader(ex, [(2, 3)])
    all_rows = sorted(
        k for b in full.execute(0, ctx) for k in b.to_pydict()["k"]
    )
    split = CoalescedShuffleReader(
        ex, [(2, 3), (2, 3)], map_ranges=[(0, 1), (1, 3)]
    )
    got = sorted(
        k
        for p in range(2)
        for b in split.execute(p, ctx)
        for k in b.to_pydict()["k"]
    )
    assert got == all_rows and len(all_rows) > 0


def test_plan_coalescing_algorithm():
    sizes = [10, 10, 10, 100, 5, 5, 5, 5]
    ranges = plan_coalesced_partitions(sizes, target_bytes=30)
    # covers all partitions exactly once, in order
    flat = [p for s, e in ranges for p in range(s, e)]
    assert flat == list(range(8))
    # no range (other than singletons forced by big partitions) exceeds 2x
    for s, e in ranges:
        if e - s > 1:
            assert sum(sizes[s:e]) <= 60


def test_plan_display():
    from blaze_tpu.ops import FilterExec, ProjectExec

    scan = MemoryScanExec.from_batches(
        [ColumnBatch.from_pydict({"a": [1]})]
    )
    op = ProjectExec(FilterExec(scan, Col("a") > 0), [(Col("a"), "a")])
    s = op.display()
    assert "ProjectExec" in s and "FilterExec" in s and \
        "MemoryScanExec" in s
    assert s.index("ProjectExec") < s.index("FilterExec")
