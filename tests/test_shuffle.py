"""Shuffle + segmented IPC format tests: round trips, the on-disk contract,
spill merge, partition placement vs Spark's hash semantics."""

import os
import struct

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import ColumnBatch
from blaze_tpu.config import EngineConfig, get_config, set_config
from blaze_tpu.exprs import Col
from blaze_tpu.io.ipc import (
    decode_ipc_parts,
    encode_ipc_segment,
    partition_ranges,
    read_file_segment,
    read_index_file,
)
from blaze_tpu.ops import (
    ExecContext,
    FileSegment,
    IpcReaderExec,
    IpcReadMode,
    IpcWriterExec,
    MemoryScanExec,
    ShuffleWriterExec,
    collect_ipc,
)
from blaze_tpu.exprs.hashing import hash_int_host, hash_long_host


def scan_of(data, **kw):
    return MemoryScanExec.from_batches([ColumnBatch.from_pydict(data, **kw)])


def drain(op, partition, ctx):
    return list(op.execute(partition, ctx))


def test_ipc_part_roundtrip():
    rb = pa.RecordBatch.from_pydict(
        {"a": [1, 2, 3], "s": ["x", None, "zz"]}
    )
    part = encode_ipc_segment(rb)
    # contract: 8-byte LE length prefix + zstd frame
    (length,) = struct.unpack_from("<Q", part, 0)
    assert length == len(part) - 8
    out = list(decode_ipc_parts(part))
    assert len(out) == 1
    assert out[0].to_pydict() == rb.to_pydict()
    # empty batch writes nothing (write_ipc_compressed returns 0)
    assert encode_ipc_segment(rb.slice(0, 0)) == b""


def test_shuffle_write_read_roundtrip(tmp_path):
    data = {"k": list(range(100)), "v": [i * 10 for i in range(100)]}
    op = ShuffleWriterExec(
        scan_of(data), [Col("k")], 4,
        str(tmp_path / "s.data"), str(tmp_path / "s.index"),
    )
    ctx = ExecContext()
    assert drain(op, 0, ctx) == []
    offs = read_index_file(str(tmp_path / "s.index"))
    assert len(offs) == 5 and offs[0] == 0
    # read all partitions back; every row lands exactly once, in the
    # partition Spark murmur3 dictates
    seen = {}
    for p, (off, length) in enumerate(
        partition_ranges(str(tmp_path / "s.index"))
    ):
        for rb in read_file_segment(str(tmp_path / "s.data"), off, length):
            for k, v in zip(*[rb.column(i).to_pylist() for i in range(2)]):
                seen[k] = (p, v)
                h = hash_long_host(k)
                exp_p = np.int32(np.uint32(h & 0xFFFFFFFF)) % 4
                if exp_p < 0:
                    exp_p += 4
                assert p == exp_p, (k, p, exp_p)
    assert len(seen) == 100
    assert all(seen[k][1] == k * 10 for k in seen)


def test_shuffle_string_keys(tmp_path):
    data = {"k": [f"key-{i % 7}" for i in range(50)], "v": list(range(50))}
    op = ShuffleWriterExec(
        scan_of(data), [Col("k")], 8,
        str(tmp_path / "s.data"), str(tmp_path / "s.index"),
    )
    drain(op, 0, ExecContext())
    total = 0
    groups = {}
    for p, (off, length) in enumerate(
        partition_ranges(str(tmp_path / "s.index"))
    ):
        for rb in read_file_segment(str(tmp_path / "s.data"), off, length):
            total += rb.num_rows
            for k in rb.column(0).to_pylist():
                groups.setdefault(k, set()).add(p)
    assert total == 50
    # all rows of one key land in one partition
    assert all(len(ps) == 1 for ps in groups.values())


def test_shuffle_spill_merge(tmp_path):
    """Force spills with a tiny budget; the merged file must still contain
    every row in the right partition order."""
    from blaze_tpu.runtime import memory

    old_pool = memory._POOL
    memory._POOL = memory.MemoryPool(budget=64)  # absurdly small -> spills
    try:
        batches = [
            ColumnBatch.from_pydict(
                {"k": list(range(i * 20, (i + 1) * 20))}
            )
            for i in range(5)
        ]
        scan = MemoryScanExec([batches], batches[0].schema)
        op = ShuffleWriterExec(
            scan, [Col("k")], 3,
            str(tmp_path / "s.data"), str(tmp_path / "s.index"),
        )
        drain(op, 0, ExecContext())
        assert memory._POOL.spill_count > 0
        seen = []
        for off, length in partition_ranges(str(tmp_path / "s.index")):
            for rb in read_file_segment(
                str(tmp_path / "s.data"), off, length
            ):
                seen += rb.column(0).to_pylist()
        assert sorted(seen) == list(range(100))
    finally:
        memory._POOL = old_pool


def test_ipc_reader_modes(tmp_path):
    cb = ColumnBatch.from_pydict({"a": [1, 2, 3]})
    parts = collect_ipc(MemoryScanExec.from_batches([cb]), ExecContext())
    assert len(parts) == 1

    ctx = ExecContext()
    ctx.resources["r"] = [parts]
    rd = IpcReaderExec("r", cb.schema, 1, IpcReadMode.CHANNEL)
    got = [b.to_pydict() for b in rd.execute(0, ctx)]
    assert got == [{"a": [1, 2, 3]}]

    # file segment mode through a shuffle file
    op = ShuffleWriterExec(
        MemoryScanExec.from_batches([cb]), [Col("a")], 2,
        str(tmp_path / "x.data"), str(tmp_path / "x.index"),
    )
    drain(op, 0, ctx)
    segs = [
        [FileSegment(str(tmp_path / "x.data"), off, length)]
        for off, length in partition_ranges(str(tmp_path / "x.index"))
    ]
    rd2 = IpcReaderExec(
        "r2", cb.schema, 2, IpcReadMode.CHANNEL_AND_FILE_SEGMENT
    )
    ctx.resources["r2"] = segs
    rows = []
    for p in range(2):
        for b in rd2.execute(p, ctx):
            rows += b.to_pydict()["a"]
    assert sorted(rows) == [1, 2, 3]


def test_single_partition_mode(tmp_path):
    op = ShuffleWriterExec(
        scan_of({"a": [5, 6]}), [], 1,
        str(tmp_path / "p.data"), str(tmp_path / "p.index"),
        mode="single",
    )
    drain(op, 0, ExecContext())
    (rng,) = partition_ranges(str(tmp_path / "p.index"))
    rows = []
    for rb in read_file_segment(str(tmp_path / "p.data"), *rng):
        rows += rb.column(0).to_pylist()
    assert rows == [5, 6]


def test_round_robin_mode(tmp_path):
    op = ShuffleWriterExec(
        scan_of({"a": list(range(10))}), [], 3,
        str(tmp_path / "rr.data"), str(tmp_path / "rr.index"),
        mode="round_robin",
    )
    drain(op, 0, ExecContext())
    sizes = [
        sum(
            rb.num_rows
            for rb in read_file_segment(str(tmp_path / "rr.data"), o, l)
        )
        for o, l in partition_ranges(str(tmp_path / "rr.index"))
    ]
    assert sum(sizes) == 10
    assert max(sizes) - min(sizes) <= 1  # balanced


def test_ipc_reader_uncompressed_recordbatch():
    """CHANNEL_UNCOMPRESSED: pre-decoded RecordBatches pass straight
    through (the ConvertToNative input path, ipc_reader_exec.rs mode
    CHANNEL_UNCOMPRESSED)."""
    import pyarrow as pa

    rb = pa.RecordBatch.from_pydict({"a": [1, 2], "s": ["x", None]})
    cb = ColumnBatch.from_pydict({"a": [0]})
    ctx = ExecContext()
    ctx.resources["u"] = [[rb]]
    from blaze_tpu.types import from_arrow_schema

    rd = IpcReaderExec(
        "u", from_arrow_schema(rb.schema), 1,
        IpcReadMode.CHANNEL_UNCOMPRESSED,
    )
    out = [b.to_arrow().to_pydict() for b in rd.execute(0, ctx)]
    assert out == [rb.to_pydict()]
    assert ctx.metrics.counters["ipc_rows_read"] == 2


def test_metrics_counters_flow(tmp_path):
    ctx = ExecContext()
    op = ShuffleWriterExec(
        scan_of({"k": list(range(40))}), [Col("k")], 4,
        str(tmp_path / "m.data"), str(tmp_path / "m.index"),
    )
    drain(op, 0, ctx)
    flat = ctx.metrics.flatten()["root"]
    assert flat["shuffle_rows_written"] == 40
    assert flat["shuffle_bytes_written"] > 0


def test_ipc_stream_channel_source(tmp_path):
    """Remote-stream mode: a file-like object of concatenated parts
    decodes incrementally (reference ReadableByteChannel path)."""
    import io

    rbs = [
        pa.RecordBatch.from_pydict({"a": [1, 2]}),
        pa.RecordBatch.from_pydict({"a": [3]}),
    ]
    blob = b"".join(encode_ipc_segment(rb) for rb in rbs)
    ctx = ExecContext()
    ctx.resources["st"] = [[io.BytesIO(blob)]]
    from blaze_tpu.types import from_arrow_schema

    rd = IpcReaderExec(
        "st", from_arrow_schema(rbs[0].schema), 1,
        IpcReadMode.CHANNEL_AND_FILE_SEGMENT,
    )
    rows = [x for b in rd.execute(0, ctx) for x in b.to_pydict()["a"]]
    assert rows == [1, 2, 3]
