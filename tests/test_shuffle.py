"""Shuffle + segmented IPC format tests: round trips, the on-disk contract,
spill merge, partition placement vs Spark's hash semantics."""

import os
import struct

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import ColumnBatch
from blaze_tpu.config import EngineConfig, get_config, set_config
from blaze_tpu.exprs import Col
from blaze_tpu.io.ipc import (
    decode_ipc_parts,
    encode_ipc_segment,
    partition_ranges,
    read_file_segment,
    read_index_file,
)
from blaze_tpu.ops import (
    ExecContext,
    FileSegment,
    IpcReaderExec,
    IpcReadMode,
    IpcWriterExec,
    MemoryScanExec,
    ShuffleWriterExec,
    collect_ipc,
)
from blaze_tpu.exprs.hashing import hash_int_host, hash_long_host


def scan_of(data, **kw):
    return MemoryScanExec.from_batches([ColumnBatch.from_pydict(data, **kw)])


def drain(op, partition, ctx):
    return list(op.execute(partition, ctx))


def test_ipc_part_roundtrip():
    rb = pa.RecordBatch.from_pydict(
        {"a": [1, 2, 3], "s": ["x", None, "zz"]}
    )
    part = encode_ipc_segment(rb)
    # contract: 8-byte LE length prefix + zstd frame
    (length,) = struct.unpack_from("<Q", part, 0)
    assert length == len(part) - 8
    out = list(decode_ipc_parts(part))
    assert len(out) == 1
    assert out[0].to_pydict() == rb.to_pydict()
    # empty batch writes nothing (write_ipc_compressed returns 0)
    assert encode_ipc_segment(rb.slice(0, 0)) == b""


def test_shuffle_write_read_roundtrip(tmp_path):
    data = {"k": list(range(100)), "v": [i * 10 for i in range(100)]}
    op = ShuffleWriterExec(
        scan_of(data), [Col("k")], 4,
        str(tmp_path / "s.data"), str(tmp_path / "s.index"),
    )
    ctx = ExecContext()
    assert drain(op, 0, ctx) == []
    offs = read_index_file(str(tmp_path / "s.index"))
    assert len(offs) == 5 and offs[0] == 0
    # read all partitions back; every row lands exactly once, in the
    # partition Spark murmur3 dictates
    seen = {}
    for p, (off, length) in enumerate(
        partition_ranges(str(tmp_path / "s.index"))
    ):
        for rb in read_file_segment(str(tmp_path / "s.data"), off, length):
            for k, v in zip(*[rb.column(i).to_pylist() for i in range(2)]):
                seen[k] = (p, v)
                h = hash_long_host(k)
                exp_p = np.int32(np.uint32(h & 0xFFFFFFFF)) % 4
                if exp_p < 0:
                    exp_p += 4
                assert p == exp_p, (k, p, exp_p)
    assert len(seen) == 100
    assert all(seen[k][1] == k * 10 for k in seen)


def test_shuffle_string_keys(tmp_path):
    data = {"k": [f"key-{i % 7}" for i in range(50)], "v": list(range(50))}
    op = ShuffleWriterExec(
        scan_of(data), [Col("k")], 8,
        str(tmp_path / "s.data"), str(tmp_path / "s.index"),
    )
    drain(op, 0, ExecContext())
    total = 0
    groups = {}
    for p, (off, length) in enumerate(
        partition_ranges(str(tmp_path / "s.index"))
    ):
        for rb in read_file_segment(str(tmp_path / "s.data"), off, length):
            total += rb.num_rows
            for k in rb.column(0).to_pylist():
                groups.setdefault(k, set()).add(p)
    assert total == 50
    # all rows of one key land in one partition
    assert all(len(ps) == 1 for ps in groups.values())


def test_shuffle_spill_merge(tmp_path):
    """Force spills with a tiny budget; the merged file must still contain
    every row in the right partition order."""
    from blaze_tpu.runtime import memory

    old_pool = memory._POOL
    memory._POOL = memory.MemoryPool(budget=64)  # absurdly small -> spills
    try:
        batches = [
            ColumnBatch.from_pydict(
                {"k": list(range(i * 20, (i + 1) * 20))}
            )
            for i in range(5)
        ]
        scan = MemoryScanExec([batches], batches[0].schema)
        op = ShuffleWriterExec(
            scan, [Col("k")], 3,
            str(tmp_path / "s.data"), str(tmp_path / "s.index"),
        )
        drain(op, 0, ExecContext())
        assert memory._POOL.spill_count > 0
        seen = []
        for off, length in partition_ranges(str(tmp_path / "s.index")):
            for rb in read_file_segment(
                str(tmp_path / "s.data"), off, length
            ):
                seen += rb.column(0).to_pylist()
        assert sorted(seen) == list(range(100))
    finally:
        memory._POOL = old_pool


def test_ipc_reader_modes(tmp_path):
    cb = ColumnBatch.from_pydict({"a": [1, 2, 3]})
    parts = collect_ipc(MemoryScanExec.from_batches([cb]), ExecContext())
    assert len(parts) == 1

    ctx = ExecContext()
    ctx.resources["r"] = [parts]
    rd = IpcReaderExec("r", cb.schema, 1, IpcReadMode.CHANNEL)
    got = [b.to_pydict() for b in rd.execute(0, ctx)]
    assert got == [{"a": [1, 2, 3]}]

    # file segment mode through a shuffle file
    op = ShuffleWriterExec(
        MemoryScanExec.from_batches([cb]), [Col("a")], 2,
        str(tmp_path / "x.data"), str(tmp_path / "x.index"),
    )
    drain(op, 0, ctx)
    segs = [
        [FileSegment(str(tmp_path / "x.data"), off, length)]
        for off, length in partition_ranges(str(tmp_path / "x.index"))
    ]
    rd2 = IpcReaderExec(
        "r2", cb.schema, 2, IpcReadMode.CHANNEL_AND_FILE_SEGMENT
    )
    ctx.resources["r2"] = segs
    rows = []
    for p in range(2):
        for b in rd2.execute(p, ctx):
            rows += b.to_pydict()["a"]
    assert sorted(rows) == [1, 2, 3]


def test_single_partition_mode(tmp_path):
    op = ShuffleWriterExec(
        scan_of({"a": [5, 6]}), [], 1,
        str(tmp_path / "p.data"), str(tmp_path / "p.index"),
        mode="single",
    )
    drain(op, 0, ExecContext())
    (rng,) = partition_ranges(str(tmp_path / "p.index"))
    rows = []
    for rb in read_file_segment(str(tmp_path / "p.data"), *rng):
        rows += rb.column(0).to_pylist()
    assert rows == [5, 6]


def test_round_robin_mode(tmp_path):
    op = ShuffleWriterExec(
        scan_of({"a": list(range(10))}), [], 3,
        str(tmp_path / "rr.data"), str(tmp_path / "rr.index"),
        mode="round_robin",
    )
    drain(op, 0, ExecContext())
    sizes = [
        sum(
            rb.num_rows
            for rb in read_file_segment(str(tmp_path / "rr.data"), o, l)
        )
        for o, l in partition_ranges(str(tmp_path / "rr.index"))
    ]
    assert sum(sizes) == 10
    assert max(sizes) - min(sizes) <= 1  # balanced


def test_ipc_reader_uncompressed_recordbatch():
    """CHANNEL_UNCOMPRESSED: pre-decoded RecordBatches pass straight
    through (the ConvertToNative input path, ipc_reader_exec.rs mode
    CHANNEL_UNCOMPRESSED)."""
    import pyarrow as pa

    rb = pa.RecordBatch.from_pydict({"a": [1, 2], "s": ["x", None]})
    cb = ColumnBatch.from_pydict({"a": [0]})
    ctx = ExecContext()
    ctx.resources["u"] = [[rb]]
    from blaze_tpu.types import from_arrow_schema

    rd = IpcReaderExec(
        "u", from_arrow_schema(rb.schema), 1,
        IpcReadMode.CHANNEL_UNCOMPRESSED,
    )
    out = [b.to_arrow().to_pydict() for b in rd.execute(0, ctx)]
    assert out == [rb.to_pydict()]
    assert ctx.metrics.counters["ipc_rows_read"] == 2


def test_metrics_counters_flow(tmp_path):
    ctx = ExecContext()
    op = ShuffleWriterExec(
        scan_of({"k": list(range(40))}), [Col("k")], 4,
        str(tmp_path / "m.data"), str(tmp_path / "m.index"),
    )
    drain(op, 0, ctx)
    flat = ctx.metrics.flatten()["root"]
    assert flat["shuffle_rows_written"] == 40
    assert flat["shuffle_bytes_written"] > 0


def test_ipc_stream_channel_source(tmp_path):
    """Remote-stream mode: a file-like object of concatenated parts
    decodes incrementally (reference ReadableByteChannel path)."""
    import io

    rbs = [
        pa.RecordBatch.from_pydict({"a": [1, 2]}),
        pa.RecordBatch.from_pydict({"a": [3]}),
    ]
    blob = b"".join(encode_ipc_segment(rb) for rb in rbs)
    ctx = ExecContext()
    ctx.resources["st"] = [[io.BytesIO(blob)]]
    from blaze_tpu.types import from_arrow_schema

    rd = IpcReaderExec(
        "st", from_arrow_schema(rbs[0].schema), 1,
        IpcReadMode.CHANNEL_AND_FILE_SEGMENT,
    )
    rows = [x for b in rd.execute(0, ctx) for x in b.to_pydict()["a"]]
    assert rows == [1, 2, 3]


# ---------------------------------------------------------------------------
# range partitioning (reference ArrowShuffleExchangeExec301.scala:317-357)
# ---------------------------------------------------------------------------

def test_range_partition_ids_bounds_ties_nulls_desc():
    import numpy as np

    from blaze_tpu.ops.shuffle_writer import range_partition_ids

    keys = [np.array([None, 1, 5, 10, 10, 25], dtype=object)]
    bounds = [(5,), (10,)]
    pids = range_partition_ids(keys, bounds, [True])
    # NULL first -> 0; 1 -> 0; 5 (== bound) -> lower partition 0;
    # 10 -> 1 (== second bound); 25 -> 2
    assert pids.tolist() == [0, 0, 0, 1, 1, 2]

    # descending: order reverses (25 sorts first -> partition 0; 1
    # sorts past both bounds -> partition 2); NULL still ranks first
    pids_d = range_partition_ids(keys, [(10,), (5,)], [False])
    assert pids_d.tolist() == [0, 2, 1, 0, 0, 0]

    # two keys, lexicographic
    k2 = [
        np.array([1, 1, 2, 2], dtype=object),
        np.array(["a", "z", "a", "z"], dtype=object),
    ]
    pids2 = range_partition_ids(k2, [(1, "m"), (2, "m")], [True, True])
    assert pids2.tolist() == [0, 1, 1, 2]


def test_compute_range_bounds_quantiles():
    import numpy as np
    import pandas as pd

    from blaze_tpu.ops.shuffle_writer import compute_range_bounds

    df = pd.DataFrame({"k0": np.arange(100)})
    bounds = compute_range_bounds(df, 4, [True])
    assert bounds == [(25,), (50,), (75,)]
    assert compute_range_bounds(df, 1, [True]) == []
    assert compute_range_bounds(df.iloc[:0], 4, [True]) == []


def test_range_exchange_global_sort():
    """Distributed global sort: range exchange + per-partition sort =>
    concatenated output is totally ordered."""
    import numpy as np

    from blaze_tpu.exprs import Col
    from blaze_tpu.ops import SortExec, SortKey
    from blaze_tpu.parallel import ShuffleExchangeExec

    rng = np.random.default_rng(11)
    parts = [
        {"k": rng.integers(0, 1000, 500).tolist(),
         "v": list(range(500))}
        for _ in range(3)
    ]
    batches = [[ColumnBatch.from_pydict(p)] for p in parts]
    scan = MemoryScanExec(batches, ColumnBatch.from_pydict(parts[0]).schema)
    ex = ShuffleExchangeExec(scan, [Col("k")], 4, mode="range")
    ctx = ExecContext()
    all_keys = []
    for p in range(4):
        part_keys = []
        srt = SortExec(ex, [SortKey(Col("k"), True, True)])
        # sort executes per partition; collect partition p
        for cb in srt.execute(p, ctx):
            part_keys += cb.to_arrow().column("k").to_pylist()
        assert part_keys == sorted(part_keys)
        all_keys.append(part_keys)
    flat = [k for part in all_keys for k in part]
    assert flat == sorted(flat)  # global order across partitions
    expect = sorted(k for p in parts for k in p["k"])
    assert flat == expect  # no rows lost or duplicated


def test_range_writer_serde_roundtrip(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.exprs import Col
    from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
    from blaze_tpu.ops.shuffle_writer import ShuffleWriterExec
    from blaze_tpu.plan.serde import plan_from_proto, plan_to_proto

    src = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"k": [3, 1, 2], "v": [1.0, 2.0, 3.0]}), src)
    op = ShuffleWriterExec(
        ParquetScanExec([[FileRange(src)]]), [Col("k")], 3,
        str(tmp_path / "o.data"), str(tmp_path / "o.index"),
        mode="range", range_bounds=[(1,), (2,)],
        sort_ascending=[True],
    )
    back = plan_from_proto(plan_to_proto(op))
    assert back.mode == "range"
    assert back.range_bounds == [(1,), (2,)]
    assert back.sort_ascending == [True]
    # and it runs: write + verify partition ordering via the index
    ctx = ExecContext()
    for _ in back.execute(0, ctx):
        pass
    from blaze_tpu.io.ipc import partition_ranges, read_file_segment

    ranges = partition_ranges(str(tmp_path / "o.index"))
    seen = []
    for off, length in ranges:
        if length:
            for rb in read_file_segment(
                str(tmp_path / "o.data"), off, length
            ):
                seen.append(rb.column("k").to_pylist())
    assert seen == [[1], [2], [3]]


def test_host_writer_interchangeable_with_native(tmp_path):
    """The host-tier writer (ops/host_shuffle, the JVM row-shuffle
    analog) and the native writer must produce interchangeable shuffle
    outputs: identical partition assignment (bit-exact murmur3) and
    identical per-partition row sets under the same reader - the
    reference's both-producers-one-format property
    (ArrowShuffleExternalSorter301.java:141-260)."""
    import pandas as pd
    import pyarrow as pa

    from blaze_tpu.ops.host_shuffle import host_shuffle_write

    rng = np.random.default_rng(5)
    n = 4000
    df = pd.DataFrame({
        "k": rng.integers(-50, 50, n).astype(np.int64),
        "name": pd.array(
            [f"user_{i % 37}" if i % 11 else None for i in range(n)]
        ),
        "v": rng.random(n),
    })
    rb = pa.RecordBatch.from_pandas(df, preserve_index=False)

    # native writer (device hash tier) over the same rows
    cb = ColumnBatch.from_arrow(rb)
    op = ShuffleWriterExec(
        MemoryScanExec([[cb]], cb.schema), [Col("k"), Col("name")], 4,
        str(tmp_path / "n.data"), str(tmp_path / "n.index"),
    )
    assert drain(op, 0, ExecContext()) == []

    # host writer: pyarrow in, no device involvement
    lengths = host_shuffle_write(
        [rb], ["k", "name"], 4,
        str(tmp_path / "h.data"), str(tmp_path / "h.index"),
        spill_dir=str(tmp_path),
    )
    assert len(lengths) == 4 and sum(lengths) > 0

    def rows_by_partition(stem):
        out = []
        for off, length in partition_ranges(
            str(tmp_path / f"{stem}.index")
        ):
            parts = []
            for rb_ in read_file_segment(
                str(tmp_path / f"{stem}.data"), off, length
            ):
                t = pa.Table.from_batches([rb_])
                parts.append(t.to_pandas())
            out.append(
                pd.concat(parts, ignore_index=True)
                if parts else pd.DataFrame(columns=df.columns)
            )
        return out

    native_parts = rows_by_partition("n")
    host_parts = rows_by_partition("h")
    total = 0
    for p, (a, b) in enumerate(zip(native_parts, host_parts)):
        a = a.sort_values(["k", "v"]).reset_index(drop=True)
        b = b.sort_values(["k", "v"]).reset_index(drop=True)
        b = b[a.columns]
        assert len(a) == len(b), p
        total += len(a)
        pd.testing.assert_frame_equal(
            a.astype({"name": "string"}), b.astype({"name": "string"}),
            check_dtype=False,
        )
    assert total == n
