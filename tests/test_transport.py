"""Block transport tests: BlockServer framing/scoping, RemoteSegment
streaming through the channel reader, and the full remote-fetch cluster
exchange over DISJOINT worker data directories (reference remote path:
ArrowBlockStoreShuffleReader301.scala:83-123, ipc_reader_exec.rs:283-326).
"""

import os

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import ColumnBatch
from blaze_tpu.io.ipc import encode_ipc_segment
from blaze_tpu.ops import ExecContext
from blaze_tpu.runtime.transport import (
    BlockServer,
    RemoteSegment,
    open_remote_stream,
)


@pytest.fixture()
def served_dir(tmp_path):
    d = tmp_path / "blocks"
    d.mkdir()
    srv = BlockServer([str(d)]).start()
    yield str(d), srv
    srv.stop()


def test_block_server_range_reads(served_dir):
    d, srv = served_dir
    path = os.path.join(d, "x.data")
    payload = bytes(range(256)) * 10
    with open(path, "wb") as f:
        f.write(payload)
    host, port = srv.address
    s = open_remote_stream(RemoteSegment(host, port, path, 100, 300))
    assert s.read() == payload[100:400]
    s.close()
    # whole file via length -1
    s = open_remote_stream(RemoteSegment(host, port, path, 0, -1))
    assert s.read() == payload
    s.close()


def test_block_server_scoping(served_dir, tmp_path):
    d, srv = served_dir
    outside = tmp_path / "secret.txt"
    outside.write_text("no")
    host, port = srv.address
    with pytest.raises(IOError):
        open_remote_stream(
            RemoteSegment(host, port, str(outside), 0, -1)
        ).read()


def test_remote_segment_through_ipc_reader(served_dir):
    """A RemoteSegment source streams through IpcReaderExec's channel
    decode exactly like the reference's ReadableByteChannel path."""
    from blaze_tpu.ops.ipc_reader import IpcReaderExec, IpcReadMode

    d, srv = served_dir
    rb = pa.record_batch({"a": pa.array([1, 2, 3], pa.int64())})
    seg_bytes = encode_ipc_segment(rb)
    path = os.path.join(d, "s.data")
    with open(path, "wb") as f:
        f.write(b"JUNKHEAD")  # offset support
        f.write(seg_bytes)
    host, port = srv.address
    reader = IpcReaderExec(
        "r1", ColumnBatch.from_arrow(rb).schema, 1,
        IpcReadMode.CHANNEL_AND_FILE_SEGMENT,
    )
    ctx = ExecContext()
    ctx.resources["r1"] = [
        [RemoteSegment(host, port, path, 8, len(seg_bytes))]
    ]
    got = [cb.to_pydict() for cb in reader.execute(0, ctx)]
    assert got == [{"a": [1, 2, 3]}]


def test_remote_cluster_exchange_disjoint_dirs(tmp_path):
    """End-to-end: map tasks write into per-worker PRIVATE dirs; reduce
    reads stream every block over the BlockServers."""
    import pyarrow.parquet as pq

    from blaze_tpu.exprs import Col
    from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
    from blaze_tpu.parallel import RemoteClusterShuffleExchangeExec
    from blaze_tpu.runtime.cluster import MiniCluster

    rng = np.random.default_rng(3)
    files = []
    all_rows = []
    for m in range(2):
        ks = rng.integers(0, 100, 400)
        vs = rng.integers(0, 10**6, 400)
        all_rows += list(zip(ks.tolist(), vs.tolist()))
        p = str(tmp_path / f"in{m}.parquet")
        pq.write_table(
            pa.table({"k": pa.array(ks, pa.int64()),
                      "v": pa.array(vs, pa.int64())}), p,
        )
        files.append(p)
    scan = ParquetScanExec([[FileRange(f)] for f in files])
    with MiniCluster(
        num_workers=2,
        env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
    ) as cluster:
        ex = RemoteClusterShuffleExchangeExec(
            scan, [Col("k")], 4, cluster,
        )
        ctx = ExecContext()
        got = []
        for p in range(4):
            for cb in ex.execute(p, ctx):
                d = cb.to_pydict()
                got += list(zip(d["k"], d["v"]))
        assert sorted(got) == sorted(all_rows)
        # and the stats path works off the metadata
        sizes = ex.map_output_statistics(ctx)
        assert len(sizes) == 4 and sum(sizes) > 0
        # disjointness: the outputs live under per-worker private dirs,
        # not under any driver-chosen shared shuffle dir
        metas = ex._run_map_stage(ctx)
        dirs = {
            os.path.dirname(out["data"])
            for meta in metas for out in meta["outputs"]
        }
        for d in dirs:
            assert "blz-worker" in d


def test_remote_cluster_range_partition_global_sort(tmp_path):
    """Integration of three round-2 tiers: driver-sampled RANGE bounds
    ride the task protos to cluster workers with PRIVATE storage, and
    the network-streamed reduce partitions are totally ordered."""
    import pyarrow.parquet as pq

    from blaze_tpu.exprs import Col
    from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
    from blaze_tpu.parallel import RemoteClusterShuffleExchangeExec
    from blaze_tpu.runtime.cluster import MiniCluster

    rng = np.random.default_rng(17)
    files = []
    all_keys = []
    for m in range(2):
        ks = rng.integers(0, 10**6, 600)
        all_keys += ks.tolist()
        p = str(tmp_path / f"r{m}.parquet")
        pq.write_table(pa.table({"k": pa.array(ks, pa.int64())}), p)
        files.append(p)
    scan = ParquetScanExec([[FileRange(f)] for f in files])
    with MiniCluster(
        num_workers=2,
        env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
    ) as cluster:
        ex = RemoteClusterShuffleExchangeExec(
            scan, [Col("k")], 4, cluster, mode="range",
        )
        ctx = ExecContext()
        partitions = []
        for p in range(4):
            part = []
            for cb in ex.execute(p, ctx):
                part += cb.to_pydict()["k"]
            partitions.append(part)
    # ranges are totally ordered across partitions (chained over
    # non-empty partitions so an empty one can't mask misordering);
    # union exact
    flat = []
    last_max = None
    for part in partitions:
        if part:
            if last_max is not None:
                assert last_max <= min(part)
            last_max = max(part)
        flat += part
    assert sorted(flat) == sorted(all_keys)


class TestRemoteObjectStore:
    """blz:// remote FS behind the scheme registry (VERDICT r2 Missing
    #7): ranged reads + stat over the block protocol, parquet scans
    through it, retry hardening for transient failures."""

    def test_parquet_scan_over_remote_store(self, tmp_path):
        import numpy as np
        import pandas as pd
        import pyarrow.parquet as pq

        from blaze_tpu.exprs import AggExpr, AggFn, Col
        from blaze_tpu.ops import (AggMode, FilterExec,
                                   HashAggregateExec)
        from blaze_tpu.ops.parquet_scan import (FileRange,
                                                ParquetScanExec)
        from blaze_tpu.runtime.executor import run_plan
        from blaze_tpu.runtime.transport import BlockServer

        rng = np.random.default_rng(3)
        df = pd.DataFrame({
            "k": rng.integers(0, 9, 5000).astype(np.int64),
            "v": rng.random(5000),
        })
        local = tmp_path / "remote_fact.parquet"
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False),
                       str(local), row_group_size=1024)

        srv = BlockServer([str(tmp_path)]).start()
        try:
            host, port = srv.address
            remote_path = f"blz://{host}:{port}{local}"
            plan = HashAggregateExec(
                FilterExec(
                    ParquetScanExec([[FileRange(remote_path)]]),
                    Col("v") > 0.25,
                ),
                keys=[(Col("k"), "k")],
                aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
                      (AggExpr(AggFn.COUNT_STAR, None), "n")],
                mode=AggMode.COMPLETE,
            )
            got = (run_plan(plan).to_pandas()
                   .sort_values("k").reset_index(drop=True))
            m = df[df.v > 0.25]
            want = (m.groupby("k").agg(s=("v", "sum"), n=("v", "size"))
                    .reset_index())
            np.testing.assert_array_equal(got["k"], want["k"])
            np.testing.assert_allclose(got["s"], want["s"])
            np.testing.assert_array_equal(got["n"], want["n"])
        finally:
            srv.stop()

    def test_stat_and_range(self, tmp_path):
        from blaze_tpu.io.object_store import store_for
        from blaze_tpu.runtime.transport import BlockServer

        p = tmp_path / "blob.bin"
        p.write_bytes(bytes(range(256)) * 4)
        srv = BlockServer([str(tmp_path)]).start()
        try:
            host, port = srv.address
            path = f"blz://{host}:{port}{p}"
            st = store_for(path)
            assert st.size(path) == 1024
            assert st.get_range(path, 10, 6) == bytes(range(10, 16))
        finally:
            srv.stop()

    def test_transient_failures_retry_then_succeed(self, tmp_path,
                                                   monkeypatch):
        import socket as socket_mod

        from blaze_tpu.io.object_store import RemoteBlockStore
        from blaze_tpu.runtime import transport

        p = tmp_path / "flaky.bin"
        p.write_bytes(b"payload-bytes")
        srv = transport.BlockServer([str(tmp_path)]).start()
        try:
            host, port = srv.address
            real_connect = socket_mod.create_connection
            fails = {"n": 2}

            def flaky(*a, **kw):
                if fails["n"] > 0:
                    fails["n"] -= 1
                    raise ConnectionRefusedError("injected")
                return real_connect(*a, **kw)

            monkeypatch.setattr(transport.socket,
                                "create_connection", flaky)
            st = RemoteBlockStore(retries=3, base_delay=0.01)
            got = st.get_range(f"blz://{host}:{port}{p}", 0, 7)
            assert got == b"payload"
            assert fails["n"] == 0

            # exhausted retries surface a clean IOError
            fails["n"] = 99
            with pytest.raises(IOError, match="after 3 attempts"):
                st.get_range(f"blz://{host}:{port}{p}", 0, 7)
        finally:
            srv.stop()

    def test_scoping_still_enforced_remotely(self, tmp_path):
        from blaze_tpu.io.object_store import RemoteBlockStore
        from blaze_tpu.runtime.transport import BlockServer

        served = tmp_path / "served"
        served.mkdir()
        secret = tmp_path / "secret.bin"
        secret.write_bytes(b"no")
        srv = BlockServer([str(served)]).start()
        try:
            host, port = srv.address
            st = RemoteBlockStore(retries=1)
            with pytest.raises(Exception):
                st.size(f"blz://{host}:{port}{secret}")
        finally:
            srv.stop()
