"""Mini-cluster tests: a full two-stage distributed query across real
worker PROCESSES (separate interpreters), coordinated only through
protobuf tasks + segmented-IPC shuffle files - the multi-host execution
contract end to end."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.ops import (
    AggMode,
    FilterExec,
    HashAggregateExec,
    IpcReaderExec,
    IpcReadMode,
    ShuffleWriterExec,
)
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.plan.serde import task_to_proto
from blaze_tpu.runtime.cluster import MiniCluster
from blaze_tpu.types import DataType, Field, Schema

pytestmark = pytest.mark.skipif(
    os.environ.get("BLZ_SKIP_CLUSTER") == "1",
    reason="cluster tests disabled",
)

# workers must not pick up an accelerator-plugin sitecustomize from the
# parent env (it can block on remote init); force plain CPU jax
CLUSTER_ENV = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""}


def test_two_stage_distributed_query(tmp_path):
    # data: two parquet "splits"
    n = 4000
    rng = np.random.default_rng(5)
    paths = []
    for i in range(2):
        p = str(tmp_path / f"part{i}.parquet")
        pq.write_table(
            pa.table(
                {
                    "k": rng.integers(0, 20, n),
                    "v": rng.integers(0, 100, n),
                }
            ),
            p,
        )
        paths.append(p)
    n_reduce = 3
    shuffle_dir = str(tmp_path / "shuffle")
    os.makedirs(shuffle_dir)

    with MiniCluster(num_workers=2, env=CLUSTER_ENV) as cluster:
        # ---- stage 1: map tasks (scan -> filter -> shuffle write) ----
        map_tasks = []
        outputs = []
        for mid, path in enumerate(paths):
            data = os.path.join(shuffle_dir, f"m{mid}.data")
            index = os.path.join(shuffle_dir, f"m{mid}.index")
            outputs.append((data, index))
            plan = ShuffleWriterExec(
                FilterExec(
                    ParquetScanExec([[FileRange(path)]]),
                    Col("v") < 90,
                ),
                [Col("k")], n_reduce, data, index,
            )
            map_tasks.append(task_to_proto(plan, 0, f"map-{mid}"))
        cluster.run_tasks(map_tasks)
        for data, index in outputs:
            assert os.path.exists(data) and os.path.exists(index)

        # ---- stage 2: reduce tasks (read segments -> final agg) ----
        from blaze_tpu.io.ipc import partition_ranges
        from blaze_tpu.ops.ipc_reader import FileSegment

        in_schema = Schema(
            [Field("k", DataType.int64()), Field("v", DataType.int64())]
        )
        reduce_tasks = []
        for r in range(n_reduce):
            segs = []
            for data, index in outputs:
                off, length = partition_ranges(index)[r]
                if length:
                    segs.append(FileSegment(data, off, length))
            reader = IpcReaderExec(
                f"shuffle-r{r}", in_schema, n_reduce,
                IpcReadMode.CHANNEL_AND_FILE_SEGMENT,
            )
            plan = HashAggregateExec(
                reader,
                keys=[(Col("k"), "k")],
                aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
                      (AggExpr(AggFn.COUNT_STAR, None), "n")],
                mode=AggMode.COMPLETE,
            )
            reduce_tasks.append(
                task_to_proto(
                    plan, r, f"reduce-{r}",
                    file_resources={f"shuffle-r{r}": segs},
                )
            )
        tables = cluster.run_tasks(reduce_tasks)

    rows = {}
    for t in tables:
        if t.num_rows == 0:
            continue
        d = t.to_pydict()
        for k, s, c in zip(d["k"], d["s"], d["n"]):
            assert k not in rows, "group appeared in two reducers"
            rows[k] = (s, c)
    # differential reference
    import pandas as pd

    frames = [pq.read_table(p).to_pandas() for p in paths]
    df = pd.concat(frames)
    df = df[df.v < 90]
    ref = df.groupby("k").agg(s=("v", "sum"), n=("v", "size"))
    assert rows == {
        int(k): (int(r.s), int(r.n)) for k, r in ref.iterrows()
    }


def test_worker_error_propagates(tmp_path):
    from blaze_tpu.ops import EmptyPartitionsExec
    from blaze_tpu.types import DataType, Field, Schema

    # a task whose plan reads a nonexistent parquet file
    plan = ParquetScanExec(
        [[FileRange(str(tmp_path / "missing.parquet"))]],
        schema=Schema([Field("a", DataType.int64())]),
    )
    with MiniCluster(num_workers=1, env=CLUSTER_ENV) as cluster:
        with pytest.raises(RuntimeError, match="worker task failed"):
            cluster.run_tasks([task_to_proto(plan, 0, "bad")],
                              timeout=60)


def test_cluster_shuffle_exchange(tmp_path):
    """Distributed GROUP BY where the exchange's map stage runs on worker
    processes and the reduce side aggregates in-process."""
    n = 3000
    rng = np.random.default_rng(9)
    paths = []
    for i in range(2):
        p = str(tmp_path / f"t{i}.parquet")
        pq.write_table(
            pa.table(
                {"k": rng.integers(0, 15, n),
                 "v": rng.integers(0, 50, n)}
            ),
            p,
        )
        paths.append(p)
    from blaze_tpu.parallel.exchange import ClusterShuffleExchangeExec
    from blaze_tpu.runtime.executor import run_plan

    scan = ParquetScanExec([[FileRange(p)] for p in paths])
    with MiniCluster(num_workers=2, env=CLUSTER_ENV) as cluster:
        ex = ClusterShuffleExchangeExec(
            scan, [Col("k")], 4, cluster,
            shuffle_dir=str(tmp_path / "sh"),
        )
        agg = HashAggregateExec(
            ex,
            keys=[(Col("k"), "k")],
            aggs=[(AggExpr(AggFn.SUM, Col("v")), "s")],
            mode=AggMode.COMPLETE,
        )
        out = run_plan(agg).to_pandas().sort_values("k")
    import pandas as pd

    df = pd.concat([pq.read_table(p).to_pandas() for p in paths])
    ref = df.groupby("k")["v"].sum().reset_index(name="s")
    np.testing.assert_array_equal(
        out["k"].to_numpy(), ref["k"].to_numpy()
    )
    np.testing.assert_array_equal(
        out["s"].to_numpy(), ref["v" if "v" in ref else "s"].to_numpy()
    )
