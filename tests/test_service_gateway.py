"""Wire-level serving-tier tests: the service verbs over the gateway
socket, concurrent mixed-priority load, session semantics, and the
`python -m blaze_tpu serve` CLI (ISSUE 2 satellites + acceptance)."""

import os
import re
import subprocess
import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.ops import (
    AggMode,
    FilterExec,
    HashAggregateExec,
    MemoryScanExec,
)
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.plan.serde import task_to_proto
from blaze_tpu.runtime.gateway import TaskGatewayServer
from blaze_tpu.service import (
    QueryService,
    QueryState,
    ServiceClient,
    ServiceError,
)
from tests.test_service import GatedScan, wait_for

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def dataset(tmp_path):
    rng = np.random.default_rng(11)
    p = str(tmp_path / "t.parquet")
    pq.write_table(
        pa.table(
            {
                "k": pa.array(rng.integers(0, 25, 6000), pa.int32()),
                "v": pa.array(rng.random(6000), pa.float64()),
            }
        ),
        p,
    )

    def blob(threshold=0.5):
        plan = HashAggregateExec(
            FilterExec(
                ParquetScanExec([[FileRange(p)]]),
                Col("v") > threshold,
            ),
            keys=[(Col("k"), "k")],
            aggs=[
                (AggExpr(AggFn.SUM, Col("v")), "s"),
                (AggExpr(AggFn.COUNT_STAR, None), "n"),
            ],
            mode=AggMode.COMPLETE,
        )
        return task_to_proto(plan, 0)

    return blob


def test_wire_roundtrip_matches_inprocess(dataset):
    from blaze_tpu.runtime.executor import execute_task

    blob = dataset()
    exp = pa.Table.from_batches(list(execute_task(blob)))
    with QueryService(max_concurrency=2) as svc:
        with TaskGatewayServer(service=svc) as srv:
            with ServiceClient(*srv.address) as c:
                batches = c.run(blob)
    got = pa.Table.from_batches(batches)
    g = got.to_pandas().sort_values("k").reset_index(drop=True)
    e = exp.to_pandas().sort_values("k").reset_index(drop=True)
    assert g.k.tolist() == e.k.tolist()
    assert np.allclose(g.s.values, e.s.values)


def test_wire_repeat_hits_cache_zero_dispatches(dataset):
    blob = dataset()
    with QueryService(max_concurrency=1) as svc:
        with TaskGatewayServer(service=svc) as srv:
            with ServiceClient(*srv.address) as c:
                r1 = c.run(blob)
                st2 = c.submit(blob)
                r2 = c.fetch(st2["query_id"])
                poll = c.poll(st2["query_id"])
                assert poll["state"] == "DONE"
                assert poll["dispatches"] == 0
                assert poll["cache_hits"] == 1
                stats = c.stats()
                assert stats["cache"]["hits"] == 1
                report = c.report(st2["query_id"])
                assert "DONE" in report
    assert pa.Table.from_batches(r1).to_pydict() == \
        pa.Table.from_batches(r2).to_pydict()


def test_concurrent_mixed_priority_load(dataset):
    """N client threads over the gateway: admission respects priority,
    repeated plans hit the cache (zero extra dispatches), everything
    completes correctly."""
    hot_blob = dataset(0.5)
    cold_blobs = [dataset(t) for t in (0.2, 0.3, 0.4, 0.6)]
    release = threading.Event()
    blocker = GatedScan(release)
    results = {}
    errors = []

    with QueryService(max_concurrency=1) as svc:
        with TaskGatewayServer(service=svc) as srv:
            host, port = srv.address
            qb = svc.submit_plan(blocker, estimated_bytes=0)
            assert wait_for(lambda: blocker.started.is_set())

            def worker(i, blob, priority):
                try:
                    with ServiceClient(host, port) as c:
                        st = c.submit(blob, priority=priority)
                        qid = st["query_id"]
                        batches = c.fetch(qid)
                        results[i] = (
                            qid,
                            priority,
                            c.poll(qid),
                            pa.Table.from_batches(batches).num_rows,
                        )
                except Exception as e:  # noqa: BLE001
                    errors.append((i, repr(e)))

            jobs = [(0, hot_blob, 5), (1, hot_blob, 5),
                    (2, hot_blob, 5)]
            jobs += [(3 + j, b, 0) for j, b in enumerate(cold_blobs)]
            threads = [
                threading.Thread(target=worker, args=j) for j in jobs
            ]
            for t in threads:
                t.start()
            # let every submission land in the queue, then open the gate
            assert wait_for(
                lambda: svc.admission.queue_depth() == len(jobs),
                timeout=30,
            )
            release.set()
            for t in threads:
                t.join(timeout=120)
            assert not errors, errors
            assert len(results) == len(jobs)
            svc.result(qb.query_id, timeout=60)

            # every query completed with rows
            for qid, prio, poll, rows in results.values():
                assert poll["state"] == "DONE"
                assert rows > 0

            # admission order: priorities non-increasing after the
            # blocker (priority classes drain high-to-low; FIFO within
            # a class is pinned by the single-threaded test in
            # test_service.py)
            prio_by_qid = {
                qid: prio for qid, prio, _, _ in results.values()
            }
            admitted = [
                prio_by_qid[qid]
                for qid in svc.admission_log
                if qid in prio_by_qid
            ]
            assert admitted == sorted(admitted, reverse=True)

            # the hot plan ran once; the other two were pure cache
            # hits with zero device dispatches
            hot = [results[i] for i in (0, 1, 2)]
            dispatch_counts = sorted(
                p["dispatches"] for _, _, p, _ in hot
            )
            assert dispatch_counts[0] == 0
            assert dispatch_counts[1] == 0
            assert dispatch_counts[2] > 0
            assert sum(
                p.get("cache_hits", 0) for _, _, p, _ in hot
            ) == 2


def test_wire_cancel_and_fetch_error_frame():
    release = threading.Event()
    blocker = GatedScan(release)
    try:
        with QueryService(max_concurrency=1, enable_cache=False) as svc:
            with TaskGatewayServer(service=svc) as srv:
                svc.submit_plan(blocker, estimated_bytes=0)
                assert wait_for(lambda: blocker.started.is_set())
                cb = ColumnBatch.from_pydict({"a": [1]})
                queued = svc.submit_plan(
                    MemoryScanExec([[cb]], cb.schema),
                    estimated_bytes=0,
                )
                # cancel from a DIFFERENT connection (query ids are
                # global); fetch then surfaces the error frame
                with ServiceClient(*srv.address) as c:
                    st = c.cancel(queued.query_id)
                    assert st["state"] == "CANCELLED"
                    with pytest.raises(ServiceError) as ei:
                        c.fetch(queued.query_id)
                    assert ei.value.state == "CANCELLED"
    finally:
        release.set()


def test_wire_deadline_times_out_queued():
    release = threading.Event()
    blocker = GatedScan(release)
    try:
        with QueryService(max_concurrency=1, enable_cache=False) as svc:
            with TaskGatewayServer(service=svc) as srv:
                svc.submit_plan(blocker, estimated_bytes=0)
                assert wait_for(lambda: blocker.started.is_set())
                cb = ColumnBatch.from_pydict({"a": [1]})
                with ServiceClient(*srv.address) as c:
                    st = c.submit(
                        tiny_wire_task(cb), deadline_s=0.05
                    )
                    qid = st["query_id"]
                    assert wait_for(
                        lambda: c.poll(qid)["state"] == "TIMED_OUT"
                    )
    finally:
        release.set()


def tiny_wire_task(cb):
    """Smallest serializable task: an empty-partitions scan (no files,
    no device work) - enough to exercise queueing verbs."""
    from blaze_tpu.ops import EmptyPartitionsExec
    from blaze_tpu.plan.serde import task_to_proto

    return task_to_proto(EmptyPartitionsExec(cb.schema, 1), 0)


def test_wire_session_disconnect_cancels_pending():
    """Session semantics: a client that vanishes with queries still
    queued must not keep holding queue slots."""
    release = threading.Event()
    blocker = GatedScan(release)
    try:
        with QueryService(max_concurrency=1, enable_cache=False) as svc:
            with TaskGatewayServer(service=svc) as srv:
                svc.submit_plan(blocker, estimated_bytes=0)
                assert wait_for(lambda: blocker.started.is_set())
                cb = ColumnBatch.from_pydict({"a": [1]})
                c = ServiceClient(*srv.address)
                st = c.submit(tiny_wire_task(cb))
                qid = st["query_id"]
                assert svc.get(qid).state is QueryState.QUEUED
                c.close()  # vanish with the query still queued
                assert wait_for(
                    lambda: svc.get(qid).state
                    is QueryState.CANCELLED
                )
    finally:
        release.set()


def test_serve_cli_repeat_query_hits_cache(dataset, tmp_path):
    """ISSUE 2 acceptance: a repeated identical query served through
    `python -m blaze_tpu serve` hits the result cache (0 device
    dispatches, via the per-query dispatch counters)."""
    blob = dataset()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "blaze_tpu", "serve", "--port", "0",
         "--max-concurrency", "1"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env, cwd=REPO,
    )
    try:
        line = ""
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if "listening on" in line:
                break
            assert proc.poll() is None, "serve exited early"
        m = re.search(r"'([\d.]+)', (\d+)", line)
        assert m, f"no address in: {line!r}"
        host, port = m.group(1), int(m.group(2))
        with ServiceClient(host, port, timeout=300.0) as c:
            r1 = c.run(blob)
            st2 = c.submit(blob)
            r2 = c.fetch(st2["query_id"])
            poll = c.poll(st2["query_id"])
            assert poll["state"] == "DONE"
            assert poll["dispatches"] == 0, poll
            assert poll["cache_hits"] == 1
        assert pa.Table.from_batches(r1).to_pydict() == \
            pa.Table.from_batches(r2).to_pydict()
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
