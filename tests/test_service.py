"""Serving-tier tests: lifecycle, admission, cancellation, deadlines,
and the plan-fingerprint result cache (ISSUE 2 tentpole)."""

import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.ops import (
    AggMode,
    FilterExec,
    HashAggregateExec,
    LimitExec,
    MemoryScanExec,
)
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.plan.serde import task_to_proto
from blaze_tpu.runtime import dispatch
from blaze_tpu.runtime.memory import DeviceMemoryTracker, MemoryPool
from blaze_tpu.service import (
    QueryCancelled,
    QueryService,
    QueryState,
    ResultCache,
    estimate_plan_device_bytes,
)


def wait_for(cond, timeout=10.0, tick=0.005):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return True
        time.sleep(tick)
    return False


class GatedScan(MemoryScanExec):
    """Yields one-row batches until released: occupies an admission
    slot for as long as the test wants, while giving the service a
    batch boundary every few ms to observe cancel/deadline events."""

    def __init__(self, release: threading.Event, rows=1):
        cb = ColumnBatch.from_pydict({"a": list(range(rows))})
        super().__init__([[cb]], cb.schema)
        self.release = release
        self.started = threading.Event()
        self.closed = threading.Event()

    def execute(self, partition, ctx):
        self.started.set()
        try:
            while not self.release.wait(0.005):
                yield self.partitions[0][0]
            yield self.partitions[0][0]
        finally:
            self.closed.set()


@pytest.fixture
def parquet_task(tmp_path):
    rng = np.random.default_rng(7)
    p = str(tmp_path / "t.parquet")
    pq.write_table(
        pa.table(
            {
                "k": pa.array(rng.integers(0, 20, 4000), pa.int32()),
                "v": pa.array(rng.random(4000), pa.float64()),
            }
        ),
        p,
    )

    def make(threshold=0.5):
        plan = HashAggregateExec(
            FilterExec(
                ParquetScanExec([[FileRange(p)]]),
                Col("v") > threshold,
            ),
            keys=[(Col("k"), "k")],
            aggs=[(AggExpr(AggFn.SUM, Col("v")), "s")],
            mode=AggMode.COMPLETE,
        )
        return plan, task_to_proto(plan, 0)

    return make


# ---------------------------------------------------------------------------
# fingerprints
# ---------------------------------------------------------------------------


def test_fingerprint_stable_across_rebuilds(parquet_task):
    p1, _ = parquet_task()
    p2, _ = parquet_task()
    assert p1.fingerprint() == p2.fingerprint()
    assert p1.fingerprint_is_stable()


def test_fingerprint_distinguishes_plans(parquet_task):
    p1, _ = parquet_task(0.5)
    p2, _ = parquet_task(0.6)
    assert p1.fingerprint() != p2.fingerprint()
    p3, _ = parquet_task(0.5)
    assert LimitExec(p3, 5).fingerprint() != p3.fingerprint()


def test_fingerprint_memory_scan_unstable():
    cb = ColumnBatch.from_pydict({"a": [1, 2]})
    op = MemoryScanExec([[cb]], cb.schema)
    assert not op.fingerprint_is_stable()
    # ... so the service never caches it, but the id-digest still keys
    # jit lookups for THIS object
    assert op.fingerprint() == op.fingerprint()


# ---------------------------------------------------------------------------
# lifecycle + admission
# ---------------------------------------------------------------------------


def test_submit_plan_matches_run_plan():
    from blaze_tpu.runtime.executor import run_plan

    parts = []
    for p in range(3):
        parts.append(
            [ColumnBatch.from_pydict({"a": list(range(p * 10, p * 10 + 10))})]
        )
    op = MemoryScanExec(parts, parts[0][0].schema)
    expected = run_plan(
        MemoryScanExec(parts, parts[0][0].schema)
    ).to_pydict()
    with QueryService(max_concurrency=2) as svc:
        q = svc.submit_plan(FilterExec(op, Col("a") % 2 == 0))
        batches = svc.result(q.query_id, timeout=60)
    got = pa.Table.from_batches(batches).to_pydict()
    assert got["a"] == [a for a in expected["a"] if a % 2 == 0]
    assert q.state is QueryState.DONE


def test_priority_then_fifo_admission_order():
    release = threading.Event()
    blocker = GatedScan(release)
    with QueryService(max_concurrency=1, enable_cache=False) as svc:
        qb = svc.submit_plan(blocker, estimated_bytes=0)
        assert wait_for(lambda: blocker.started.is_set())
        mk = lambda: FilterExec(  # noqa: E731
            MemoryScanExec(
                [[ColumnBatch.from_pydict({"a": [1, 2, 3]})]],
                ColumnBatch.from_pydict({"a": [1]}).schema,
            ),
            Col("a") > 0,
        )
        q_low1 = svc.submit_plan(mk(), priority=0, estimated_bytes=0)
        q_high = svc.submit_plan(mk(), priority=5, estimated_bytes=0)
        q_low2 = svc.submit_plan(mk(), priority=0, estimated_bytes=0)
        assert q_low1.state is QueryState.QUEUED
        release.set()
        for q in (q_low1, q_high, q_low2):
            svc.result(q.query_id, timeout=60)
        assert svc.admission_log == [
            qb.query_id,
            q_high.query_id,   # priority first
            q_low1.query_id,   # then FIFO within the priority class
            q_low2.query_id,
        ]


def test_headroom_queueing_not_oom():
    """ISSUE 2 acceptance: an over-headroom query QUEUES while a
    running query holds the device, and runs after it releases."""
    tracker = DeviceMemoryTracker(budget=1000)
    release = threading.Event()
    blocker = GatedScan(release)
    with QueryService(
        max_concurrency=4, enable_cache=False, device_tracker=tracker
    ) as svc:
        qb = svc.submit_plan(blocker, estimated_bytes=800)
        assert wait_for(lambda: blocker.started.is_set())
        big = svc.submit_plan(
            FilterExec(
                MemoryScanExec(
                    [[ColumnBatch.from_pydict({"a": [1]})]],
                    ColumnBatch.from_pydict({"a": [1]}).schema,
                ),
                Col("a") > 0,
            ),
            estimated_bytes=500,  # 800 + 500 > 1000: must wait
        )
        time.sleep(0.2)
        assert big.state is QueryState.QUEUED
        assert svc.admission.stats()["headroom_waits"] > 0
        release.set()
        svc.result(big.query_id, timeout=60)
        assert big.state is QueryState.DONE
        svc.result(qb.query_id, timeout=60)


def test_larger_than_budget_query_runs_alone():
    tracker = DeviceMemoryTracker(budget=1000)
    with QueryService(
        max_concurrency=2, enable_cache=False, device_tracker=tracker
    ) as svc:
        q = svc.submit_plan(
            MemoryScanExec(
                [[ColumnBatch.from_pydict({"a": [1, 2]})]],
                ColumnBatch.from_pydict({"a": [1]}).schema,
            ),
            estimated_bytes=50_000,  # way over budget; idle device
        )
        svc.result(q.query_id, timeout=60)
        assert q.state is QueryState.DONE


def test_queue_overflow_rejected():
    release = threading.Event()
    blocker = GatedScan(release)
    try:
        with QueryService(
            max_concurrency=1, max_queue_depth=1, enable_cache=False
        ) as svc:
            svc.submit_plan(blocker, estimated_bytes=0)
            assert wait_for(lambda: blocker.started.is_set())
            mk = lambda: MemoryScanExec(  # noqa: E731
                [[ColumnBatch.from_pydict({"a": [1]})]],
                ColumnBatch.from_pydict({"a": [1]}).schema,
            )
            q2 = svc.submit_plan(mk(), estimated_bytes=0)
            q3 = svc.submit_plan(mk(), estimated_bytes=0)
            assert q2.state is QueryState.QUEUED
            assert q3.state is QueryState.REJECTED_OVERLOADED
            assert "queue full" in q3.error
            with pytest.raises(RuntimeError, match="REJECTED"):
                svc.result(q3.query_id, timeout=5)
            release.set()
            svc.result(q2.query_id, timeout=60)
    finally:
        release.set()


def test_cancel_queued_and_running():
    release = threading.Event()
    blocker = GatedScan(release)
    try:
        with QueryService(max_concurrency=1, enable_cache=False) as svc:
            qr = svc.submit_plan(blocker, estimated_bytes=0)
            assert wait_for(lambda: blocker.started.is_set())
            queued = svc.submit_plan(
                MemoryScanExec(
                    [[ColumnBatch.from_pydict({"a": [1]})]],
                    ColumnBatch.from_pydict({"a": [1]}).schema,
                ),
                estimated_bytes=0,
            )
            svc.cancel(queued.query_id)
            assert queued.state is QueryState.CANCELLED
            # running: the gated generator must be CLOSED (the
            # executor's GeneratorExit pass-through), not abandoned
            svc.cancel(qr.query_id)
            assert wait_for(lambda: qr.state is QueryState.CANCELLED)
            assert wait_for(lambda: blocker.closed.is_set())
            with pytest.raises(QueryCancelled):
                svc.result(qr.query_id, timeout=5)
            # the engine is not poisoned: new queries still run
            ok = svc.submit_plan(
                MemoryScanExec(
                    [[ColumnBatch.from_pydict({"a": [7]})]],
                    ColumnBatch.from_pydict({"a": [1]}).schema,
                ),
                estimated_bytes=0,
            )
            svc.result(ok.query_id, timeout=60)
            assert ok.state is QueryState.DONE
    finally:
        release.set()


def test_deadline_queued_and_running():
    release = threading.Event()
    blocker = GatedScan(release)
    try:
        with QueryService(max_concurrency=1, enable_cache=False) as svc:
            svc.submit_plan(blocker, estimated_bytes=0)
            assert wait_for(lambda: blocker.started.is_set())
            queued = svc.submit_plan(
                MemoryScanExec(
                    [[ColumnBatch.from_pydict({"a": [1]})]],
                    ColumnBatch.from_pydict({"a": [1]}).schema,
                ),
                deadline_s=0.05,
                estimated_bytes=0,
            )
            assert wait_for(
                lambda: queued.state is QueryState.TIMED_OUT
            )
            assert "queued" in queued.error
        # running deadline: the query IS the gated scan
        release2 = threading.Event()
        slow = GatedScan(release2)
        with QueryService(max_concurrency=1, enable_cache=False) as svc:
            q = svc.submit_plan(
                slow, deadline_s=0.1, estimated_bytes=0
            )
            assert wait_for(lambda: q.state is QueryState.TIMED_OUT)
            assert slow.closed.is_set()
    finally:
        release.set()


def test_decode_failure_reports_failed():
    with QueryService(max_concurrency=1) as svc:
        q = svc.submit_task(b"\x00garbage")
        assert q.state is QueryState.FAILED
        assert "decode failed" in q.error


def test_illegal_transition_raises():
    from blaze_tpu.service.query import Query

    q = Query(task_bytes=b"x")
    q.transition(QueryState.ADMITTED)
    with pytest.raises(RuntimeError, match="illegal query transition"):
        q.transition(QueryState.DONE)  # must pass through RUNNING


# ---------------------------------------------------------------------------
# result cache
# ---------------------------------------------------------------------------


def test_repeated_task_hits_cache_zero_dispatches(parquet_task):
    """ISSUE 2 acceptance: a repeated identical query is served from
    the result cache with ZERO device dispatches."""
    _, blob = parquet_task()
    with QueryService(max_concurrency=1) as svc:
        q1 = svc.submit_task(blob)
        r1 = svc.result(q1.query_id, timeout=120)
        before = dispatch.snapshot()
        q2 = svc.submit_task(blob)
        r2 = svc.result(q2.query_id, timeout=120)
        delta = {
            k: v - before.get(k, 0)
            for k, v in dispatch.snapshot().items()
            if v - before.get(k, 0)
        }
        assert not any(
            k.startswith(("dispatches", "h2d", "d2h", "kernel"))
            for k in delta
        ), f"cache hit must not touch the device: {delta}"
        assert q2.ctx.metrics.counters.get("cache_hits") == 1
        assert svc.cache.stats()["hits"] == 1
    t1 = pa.Table.from_batches(r1).to_pydict()
    t2 = pa.Table.from_batches(r2).to_pydict()
    assert t1 == t2


def test_cache_bypass_when_disabled(parquet_task):
    _, blob = parquet_task()
    with QueryService(max_concurrency=1, enable_cache=False) as svc:
        q1 = svc.submit_task(blob)
        svc.result(q1.query_id, timeout=120)
        q2 = svc.submit_task(blob)
        svc.result(q2.query_id, timeout=120)
        assert q2.ctx.metrics.counters.get("cache_hits") is None
        assert q2.ctx.metrics.counters.get(
            "dispatch.dispatches", 0
        ) > 0


def test_cache_ttl_expiry():
    pool = MemoryPool(budget=1 << 30)
    cache = ResultCache(max_bytes=1 << 20, ttl_s=0.05, pool=pool)
    rb = pa.record_batch({"a": pa.array([1, 2, 3], pa.int64())})
    cache.put(("fp", 0), [rb])
    assert cache.get(("fp", 0)) is not None
    time.sleep(0.1)
    assert cache.get(("fp", 0)) is None  # expired
    st = cache.stats()
    assert st["evictions"] == 1 and st["entries"] == 0
    cache.close()


def test_cache_lru_eviction():
    pool = MemoryPool(budget=1 << 30)
    rb = pa.record_batch(
        {"a": pa.array(np.arange(100, dtype=np.int64))}
    )
    cache = ResultCache(
        max_bytes=int(rb.nbytes * 2.5), ttl_s=60, pool=pool
    )
    cache.put(("a", 0), [rb])
    cache.put(("b", 0), [rb])
    assert cache.get(("a", 0)) is not None  # 'a' now MRU
    cache.put(("c", 0), [rb])               # evicts LRU = 'b'
    assert cache.get(("b", 0)) is None
    assert cache.get(("a", 0)) is not None
    assert cache.get(("c", 0)) is not None
    cache.close()


def test_cache_spill_restore_through_memory_pool(tmp_path):
    """The cache rides the host->disk rung of the spill ladder: under
    MemoryPool pressure entries move to segmented-IPC files and hits
    restore them transparently."""
    rb = pa.record_batch(
        {"a": pa.array(np.arange(1000, dtype=np.int64))}
    )
    pool = MemoryPool(budget=rb.nbytes // 2)  # any put overflows
    cache = ResultCache(
        max_bytes=1 << 20, ttl_s=60, pool=pool,
        spill_dir=str(tmp_path),
    )
    cache.put(("fp", 0), [rb])
    assert cache.counters["spills"] >= 1
    assert pool.spill_count >= 1
    got = cache.get(("fp", 0))
    assert got is not None and got[0].equals(rb)
    assert cache.counters["restores"] >= 1
    cache.close()


def test_cache_invalidate():
    pool = MemoryPool(budget=1 << 30)
    cache = ResultCache(max_bytes=1 << 20, ttl_s=60, pool=pool)
    rb = pa.record_batch({"a": pa.array([1], pa.int64())})
    cache.put(("plan-x", 0), [rb])
    cache.put(("plan-x", 1), [rb])
    cache.put(("plan-y", 0), [rb])
    assert cache.invalidate("plan-x") == 2
    assert cache.get(("plan-x", 0)) is None
    assert cache.get(("plan-y", 0)) is not None
    assert cache.invalidate() == 1  # everything else
    cache.close()


def test_unstable_fingerprint_never_cached():
    cb = ColumnBatch.from_pydict({"a": [1, 2, 3]})
    op = MemoryScanExec([[cb]], cb.schema)
    with QueryService(max_concurrency=1) as svc:
        q = svc.submit_plan(op)
        svc.result(q.query_id, timeout=60)
        assert svc.cache.stats()["puts"] == 0


def test_estimate_plan_device_bytes(parquet_task):
    plan, _ = parquet_task()
    est = estimate_plan_device_bytes(plan)
    assert est > 0  # parquet file bytes flow up the tree
    cb = ColumnBatch.from_pydict({"a": list(range(100))})
    mem = MemoryScanExec([[cb]], cb.schema)
    assert estimate_plan_device_bytes(mem) > 0


def test_coalescing_second_identical_inflight_submit_waits(parquet_task):
    """ISSUE 5 satellite (ROADMAP scan-sharing first step): a second
    identical stable-fingerprint SUBMIT while the first is in flight
    WAITS on the leader and serves from the cache it populates - it
    never re-executes - and the `coalesced` counter records it."""
    from blaze_tpu.testing import chaos
    from blaze_tpu.testing.chaos import Fault

    with chaos.active(
        [Fault("task.execute", klass="STALL", stall_s=2.0, times=1)],
        seed=7,
    ) as plan:
        with QueryService(max_concurrency=2) as svc:
            p1, _ = parquet_task()
            p2, _ = parquet_task()  # identical content fingerprint
            q1 = svc.submit_plan(p1)
            # the stall fires INSIDE partition execution, i.e. after
            # q1 claimed (fingerprint, partition) leadership - q2 is
            # deterministically the follower
            assert wait_for(lambda: plan.fired("task.execute") >= 1)
            q2 = svc.submit_plan(p2)
            r1 = svc.result(q1.query_id, timeout=60)
            r2 = svc.result(q2.query_id, timeout=60)
            s1, s2 = q1.status(), q2.status()
            assert s1.get("coalesced", 0) == 0
            assert s2["coalesced"] == 1
            assert s2["dispatches"] == 0  # never executed
            assert s2["cache_hits"] == 1
            assert svc.cache.stats()["coalesced"] == 1
            # only the leader ever reached the execution seam
            assert plan.fired("task.execute") == 1
    t1 = pa.Table.from_batches(r1).to_pydict()
    t2 = pa.Table.from_batches(r2).to_pydict()
    assert t1 == t2
