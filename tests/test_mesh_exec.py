"""Mesh execution tier (ISSUE 7): the cost-guarded planner pass, the
mesh-vs-single-device differential battery (skewed keys, empty
partitions, forced 1/2/8 host device counts), the chaos `mesh.exchange`
seam (TRANSIENT retry / degrade-to-single-device), and the QueryService
acceptance pin (mesh mode end to end with `mesh.exchange.*` metrics and
per-device spans in a validate_chrome-clean trace).

Runs under the repo conftest's forced 8-device virtual CPU mesh; the
1/2/8 differential spawns its own subprocesses because the device count
freezes at first backend init.
"""

import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import jax

from blaze_tpu import ColumnBatch
from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.ops import (
    AggMode,
    ExecContext,
    FilterExec,
    HashAggregateExec,
    MemoryScanExec,
    ProjectExec,
)
from blaze_tpu.ops.joins import HashJoinExec, JoinType
from blaze_tpu.parallel.mesh_exec import (
    MeshBroadcastJoinExec,
    MeshPipelineExec,
)
from blaze_tpu.parallel.mesh_ops import MeshGroupByExec
from blaze_tpu.planner.distribute import (
    estimate_rows,
    insert_exchanges,
    lower_plan_to_mesh,
)
from blaze_tpu.runtime.executor import run_plan
from blaze_tpu.testing import chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def scan(n_parts=4, rows=200, keys=10, empty=()):
    """Multi-partition in-memory source; partitions in `empty` carry
    zero rows (the empty-partition edge)."""
    parts, schema = [], None
    for p in range(n_parts):
        n = 0 if p in empty else rows
        cb = ColumnBatch.from_arrow(pa.record_batch({
            "k": np.asarray(
                [(p * rows + i) % keys for i in range(n)],
                dtype=np.int64,
            ),
            "v": np.asarray(
                [p * rows + i for i in range(n)], dtype=np.int64
            ),
        }))
        schema = cb.schema
        parts.append([cb])
    return MemoryScanExec(parts, schema)


def agg_plan(source):
    return HashAggregateExec(
        source,
        keys=[(Col("k"), "k")],
        aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
              (AggExpr(AggFn.COUNT_STAR, None), "n")],
        mode=AggMode.COMPLETE,
    )


def sandwich(source, n=4):
    return insert_exchanges(agg_plan(source),
                            n, shuffle_dir=tempfile.mkdtemp())


def table_sorted(plan, by="k"):
    return (
        run_plan(plan).to_pandas().sort_values(by)
        .reset_index(drop=True)
    )


# ---------------------------------------------------------------------------
# planner pass
# ---------------------------------------------------------------------------


def test_lower_plan_refuses_multi_partition_complete():
    """A bare COMPLETE aggregate over a multi-partition child has
    per-partition grouping semantics; the production pass must not
    silently turn it into a global aggregate."""
    plan = agg_plan(scan())
    assert lower_plan_to_mesh(plan, mode="on") is plan


def test_lower_plan_sandwich_and_modes(monkeypatch):
    sw = sandwich(scan())
    assert isinstance(lower_plan_to_mesh(sw, mode="on"),
                      MeshGroupByExec)
    # off: untouched
    sw2 = sandwich(scan())
    assert lower_plan_to_mesh(sw2, mode="off") is sw2
    # auto + cost guard: this tiny plan stays single-device under a
    # high row floor, lowers under a zero floor
    monkeypatch.setenv("BLAZE_MESH_MIN_ROWS", "10000000")
    sw3 = sandwich(scan())
    assert lower_plan_to_mesh(sw3, mode="auto") is sw3
    monkeypatch.setenv("BLAZE_MESH_MIN_ROWS", "0")
    assert isinstance(
        lower_plan_to_mesh(sandwich(scan()), mode="auto"),
        MeshGroupByExec,
    )


def test_estimate_rows_leaves():
    src = scan(n_parts=3, rows=100)
    assert estimate_rows(src) == 300
    assert estimate_rows(agg_plan(src)) == 300


def test_pick_mesh_axis_from_plan_shape():
    """Partition-axis width follows the child partition count (capped
    by the device pool); a 1-partition child takes the full mesh."""
    sw = sandwich(scan(n_parts=4), n=4)
    low = lower_plan_to_mesh(sw, mode="on")
    assert isinstance(low, MeshGroupByExec)
    assert low.partition_count == 4
    one = lower_plan_to_mesh(agg_plan(scan(n_parts=1)), mode="on")
    assert isinstance(one, MeshGroupByExec)
    assert one.partition_count == len(jax.devices())


# ---------------------------------------------------------------------------
# differential battery (in-process, 8 devices)
# ---------------------------------------------------------------------------


def test_mesh_groupby_differential_vs_single_device():
    want = table_sorted(sandwich(scan()))
    got = table_sorted(lower_plan_to_mesh(sandwich(scan()),
                                          mode="on"))
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_mesh_groupby_skewed_keys():
    """Every row hashes to ONE owner device: the all_to_all exchange
    funnels all partial states to a single shard."""
    parts, schema = [], None
    rng = np.random.default_rng(7)
    for p in range(8):
        k = np.full(300, 7, dtype=np.int64)
        k[:3] = [1, 2, 3]  # a few stragglers
        cb = ColumnBatch.from_arrow(pa.record_batch(
            {"k": k, "v": rng.integers(0, 100, 300).astype(np.int64)}
        ))
        schema = cb.schema
        parts.append([cb])
    src = MemoryScanExec(parts, schema)
    want = table_sorted(sandwich(src, n=8))
    src2 = MemoryScanExec(parts, schema)
    low = lower_plan_to_mesh(sandwich(src2, n=8), mode="on")
    assert isinstance(low, MeshGroupByExec)
    got = table_sorted(low)
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_mesh_groupby_empty_partitions():
    src = scan(n_parts=6, rows=150, empty=(1, 4))
    want = table_sorted(sandwich(src, n=6))
    low = lower_plan_to_mesh(
        sandwich(scan(n_parts=6, rows=150, empty=(1, 4)), n=6),
        mode="on",
    )
    assert isinstance(low, MeshGroupByExec)
    got = table_sorted(low)
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_mesh_pipeline_differential():
    def chain(src):
        return ProjectExec(
            FilterExec(src, Col("v") >= 100),
            [(Col("k"), "k"), (Col("v") * Col("v"), "v2")],
        )

    low = lower_plan_to_mesh(chain(scan()), mode="on")
    assert isinstance(low, MeshPipelineExec)
    got = table_sorted(low, by="v2")
    want = table_sorted(chain(scan()), by="v2")
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_mesh_pipeline_empty_and_fully_filtered_partitions():
    def chain(src):
        # partition 0's rows all fail the predicate (v < 200)
        return FilterExec(src, Col("v") >= 200)

    src = scan(n_parts=5, rows=200, empty=(2,))
    want = table_sorted(chain(src), by="v")
    low = lower_plan_to_mesh(
        chain(scan(n_parts=5, rows=200, empty=(2,))), mode="on"
    )
    assert isinstance(low, MeshPipelineExec)
    got = table_sorted(low, by="v")
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_mesh_broadcast_join_differential():
    items = ColumnBatch.from_arrow(pa.record_batch({
        "ik": np.arange(10, dtype=np.int64),
        "iv": (np.arange(10, dtype=np.int64) * 100),
    }))

    def join(probe):
        return HashJoinExec(
            MemoryScanExec([[items]], items.schema), probe,
            ["ik"], ["k"], JoinType.INNER,
        )

    low = lower_plan_to_mesh(join(scan()), mode="on")
    assert isinstance(low, MeshBroadcastJoinExec)
    got = table_sorted(low, by="v")
    want = table_sorted(join(scan()), by="v")
    pd.testing.assert_frame_equal(
        got[sorted(got.columns)], want[sorted(want.columns)],
        check_dtype=False,
    )


def test_mesh_broadcast_join_duplicate_build_keys_degrade():
    """Duplicate build keys are only detectable at execution: the op
    degrades to the original HashJoinExec and the result is exactly
    the per-partition join's."""
    dup = ColumnBatch.from_arrow(pa.record_batch({
        "ik": np.asarray([1, 2, 2, 3], dtype=np.int64),
        "iv": np.asarray([10, 20, 21, 30], dtype=np.int64),
    }))

    def join(probe):
        return HashJoinExec(
            MemoryScanExec([[dup]], dup.schema), probe,
            ["ik"], ["k"], JoinType.INNER,
        )

    low = lower_plan_to_mesh(join(scan()), mode="on")
    assert isinstance(low, MeshBroadcastJoinExec)
    ctx = ExecContext()
    got = (
        run_plan(low, ctx).to_pandas()
        .sort_values(["v", "iv"]).reset_index(drop=True)
    )
    want = (
        run_plan(join(scan())).to_pandas()
        .sort_values(["v", "iv"]).reset_index(drop=True)
    )
    pd.testing.assert_frame_equal(
        got[sorted(got.columns)], want[sorted(want.columns)],
        check_dtype=False,
    )
    assert ctx.metrics.counters.get("mesh.degraded") == 1


# ---------------------------------------------------------------------------
# chaos: the mesh.exchange seam
# ---------------------------------------------------------------------------


def test_chaos_mesh_exchange_degrades_to_single_device():
    low = lower_plan_to_mesh(sandwich(scan()), mode="on")
    want = table_sorted(sandwich(scan()))
    ctx = ExecContext()
    with chaos.active(
        [chaos.Fault(site="mesh.exchange",
                     klass="RESOURCE_EXHAUSTED", times=1)],
        seed=11,
    ) as plan:
        got = (
            run_plan(low, ctx).to_pandas().sort_values("k")
            .reset_index(drop=True)
        )
    assert plan.fired("mesh.exchange") == 1
    pd.testing.assert_frame_equal(got, want, check_dtype=False)
    assert ctx.metrics.counters.get("mesh.degraded") == 1
    assert "mesh.exchange.all_to_all" not in ctx.metrics.counters


def test_chaos_mesh_exchange_transient_propagates_then_mesh_retries():
    low = lower_plan_to_mesh(sandwich(scan()), mode="on")
    want = table_sorted(sandwich(scan()))
    ctx = ExecContext()
    with chaos.active(
        [chaos.Fault(site="mesh.exchange", klass="TRANSIENT",
                     times=1)],
        seed=11,
    ):
        from blaze_tpu.errors import ErrorClass, classify

        with pytest.raises(Exception) as ei:
            run_plan(low, ctx)
        assert classify(ei.value) is ErrorClass.TRANSIENT
        # the retry (scheduler tier re-runs the task) stays ON the
        # mesh: fault budget consumed, program re-runs clean
        got = (
            run_plan(low, ctx).to_pandas().sort_values("k")
            .reset_index(drop=True)
        )
    pd.testing.assert_frame_equal(got, want, check_dtype=False)
    assert ctx.metrics.counters.get("mesh.degraded") is None
    assert ctx.metrics.counters.get("mesh.exchange.all_to_all") == 1


def test_service_chaos_transient_retry_lands_in_attempt_journal():
    """Through the serving tier: one injected TRANSIENT at
    mesh.exchange retries via the classified policy and the query
    still answers from the mesh."""
    from blaze_tpu.service import QueryService

    svc = QueryService(enable_cache=False, enable_trace=False,
                       mesh_mode="on")
    try:
        with chaos.active(
            [chaos.Fault(site="mesh.exchange", klass="TRANSIENT",
                         times=1)],
            seed=5,
        ):
            q = svc.submit_plan(
                lower_plan_to_mesh(sandwich(scan()), mode="on")
            )
            batches = svc.result(q.query_id, timeout=120)
        got = (
            pa.Table.from_batches(batches).to_pandas()
            .sort_values("k").reset_index(drop=True)
        )
        want = table_sorted(sandwich(scan()))
        pd.testing.assert_frame_equal(got, want, check_dtype=False)
        assert any(a["action"] == "retry" for a in q.attempts)
        assert not q.degraded
        assert q.ctx.metrics.counters.get(
            "mesh.exchange.all_to_all") == 1
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# serving-tier acceptance: mesh mode end to end
# ---------------------------------------------------------------------------


def _grouped_task_blob(path):
    from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
    from blaze_tpu.plan.serde import task_to_proto

    return task_to_proto(
        agg_plan(ParquetScanExec([[FileRange(path)]])), 0
    )


def _canonical_bytes(batches):
    df = (
        pa.Table.from_batches(batches).to_pandas()
        .sort_values("k").reset_index(drop=True)
    )
    tbl = pa.Table.from_pandas(df, preserve_index=False) \
        .combine_chunks()
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, tbl.schema) as w:
        w.write_table(tbl)
    return sink.getvalue().to_pybytes()


def test_service_mesh_acceptance(tmp_path):
    """ISSUE 7 acceptance: a grouped-aggregation query through
    QueryService on the forced 8-device host mesh produces results
    byte-equal to single-device execution, the exchange is visible as
    `mesh.exchange.*` metrics, and the trace carries per-device spans
    in one validate_chrome-clean document."""
    from blaze_tpu.obs.metrics import REGISTRY
    from blaze_tpu.obs.trace import validate_chrome
    from blaze_tpu.service import QueryService

    rng = np.random.default_rng(3)
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({
        "k": rng.integers(0, 97, 20000).astype(np.int64),
        "v": rng.integers(0, 1000, 20000).astype(np.int64),
    }), path)

    def run_service(mode):
        svc = QueryService(enable_cache=False, mesh_mode=mode)
        try:
            q = svc.submit_task(_grouped_task_blob(path))
            batches = svc.result(q.query_id, timeout=120)
            doc = svc.trace(q.query_id)
            return _canonical_bytes(batches), q, doc
        finally:
            svc.close()

    off_bytes, _, _ = run_service("off")
    on_bytes, q, doc = run_service("on")
    assert on_bytes == off_bytes  # byte-equal after canonical order
    # the exchange is visible in the metric tree + process registry
    c = q.ctx.metrics.counters
    assert c.get("mesh.exchange.all_to_all") == 1
    assert c.get("mesh.exchange.rows") == 20000
    assert c.get("mesh.devices") == 8
    assert REGISTRY.get("blaze_mesh_exchange_total",
                        kind="all_to_all") >= 1
    # per-device spans in ONE validate_chrome-clean trace
    names = [s.name for s in q.tracer.spans]
    assert "mesh_execute" in names
    assert names.count("mesh_device") == 8
    dev_tags = sorted(
        s.tags.get("device") for s in q.tracer.spans
        if s.name == "mesh_device"
    )
    assert dev_tags == list(range(8))
    assert validate_chrome(doc) == []


def test_service_mesh_fault_degrades_to_correct_result(tmp_path):
    """ISSUE 7 acceptance: an injected mesh.exchange fault degrades to
    a correct single-device result (not the host engine - `degraded`
    stays False; the mesh op's own fallback absorbed it)."""
    from blaze_tpu.service import QueryService

    rng = np.random.default_rng(9)
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({
        "k": rng.integers(0, 31, 8000).astype(np.int64),
        "v": rng.integers(0, 100, 8000).astype(np.int64),
    }), path)

    def run_service(mode, faults=()):
        svc = QueryService(enable_cache=False, enable_trace=False,
                           mesh_mode=mode)
        try:
            if faults:
                with chaos.active(list(faults), seed=13):
                    q = svc.submit_task(_grouped_task_blob(path))
                    batches = svc.result(q.query_id, timeout=120)
            else:
                q = svc.submit_task(_grouped_task_blob(path))
                batches = svc.result(q.query_id, timeout=120)
            return _canonical_bytes(batches), q
        finally:
            svc.close()

    want, _ = run_service("off")
    got, q = run_service("on", faults=[
        chaos.Fault(site="mesh.exchange", klass="RESOURCE_EXHAUSTED",
                    times=1),
    ])
    assert got == want
    assert not q.degraded  # single-device fallback, not host engine
    assert q.ctx.metrics.counters.get("mesh.degraded") == 1


def test_run_plan_parallel_mesh_mode():
    from blaze_tpu.runtime.scheduler import run_plan_parallel

    want = (
        run_plan_parallel(sandwich(scan()), parallelism=2)
        .to_pandas().sort_values("k").reset_index(drop=True)
    )
    got = (
        run_plan_parallel(sandwich(scan()), parallelism=2, mesh="on")
        .to_pandas().sort_values("k").reset_index(drop=True)
    )
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


# ---------------------------------------------------------------------------
# forced 1/2/8 device-count differential (subprocesses)
# ---------------------------------------------------------------------------

_DIFF_SCRIPT = r"""
import json, sys, tempfile
import os
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import pyarrow as pa
from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.ops import AggMode, HashAggregateExec
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.planner.distribute import (
    insert_exchanges, lower_plan_to_mesh,
)
from blaze_tpu.runtime.executor import run_plan

files = json.loads(sys.argv[1])
out = sys.argv[2]
plan = insert_exchanges(
    HashAggregateExec(
        ParquetScanExec([[FileRange(f)] for f in files]),
        keys=[(Col("k"), "k")],
        aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
              (AggExpr(AggFn.COUNT_STAR, None), "n"),
              (AggExpr(AggFn.MIN, Col("v")), "lo"),
              (AggExpr(AggFn.MAX, Col("v")), "hi")],
        mode=AggMode.COMPLETE),
    len(files), shuffle_dir=tempfile.mkdtemp())
lowered = lower_plan_to_mesh(plan, mode="on")
df = (run_plan(lowered).to_pandas().sort_values("k")
      .reset_index(drop=True))
tbl = pa.Table.from_pandas(df, preserve_index=False).combine_chunks()
sink = pa.BufferOutputStream()
with pa.ipc.new_stream(sink, tbl.schema) as w:
    w.write_table(tbl)
with open(out, "wb") as f:
    f.write(sink.getvalue().to_pybytes())
print("LOWERED:" + type(lowered).__name__)
"""


def test_differential_across_1_2_8_forced_devices(tmp_path):
    """Same query, same rows: results byte-equal across 1, 2 and 8
    forced host devices - with skewed keys and an empty partition in
    the inputs. 1 device executes the single-device exchange tier;
    2 and 8 lower onto the mesh."""
    rng = np.random.default_rng(21)
    skew = np.full(30000, 7, dtype=np.int64)
    skew[:40] = rng.integers(0, 13, 40)
    f0 = str(tmp_path / "p0.parquet")
    pq.write_table(pa.table({
        "k": skew,
        "v": rng.integers(0, 1000, 30000).astype(np.int64),
    }), f0)
    f1 = str(tmp_path / "p1.parquet")  # the empty partition
    pq.write_table(pa.table({
        "k": pa.array([], type=pa.int64()),
        "v": pa.array([], type=pa.int64()),
    }), f1)
    files = json.dumps([f0, f1])

    results = {}
    for n_dev in (1, 2, 8):
        out = str(tmp_path / f"out_{n_dev}.arrow")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={n_dev}"
        )
        env["PYTHONPATH"] = (
            REPO + os.pathsep + env.get("PYTHONPATH", "")
        )
        p = subprocess.run(
            [sys.executable, "-c", _DIFF_SCRIPT, files, out],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=REPO,
        )
        assert p.returncode == 0, p.stderr[-2000:]
        lowered = next(
            ln.split(":", 1)[1] for ln in p.stdout.splitlines()
            if ln.startswith("LOWERED:")
        )
        if n_dev == 1:
            assert lowered == "HashAggregateExec"
        else:
            assert lowered == "MeshGroupByExec", lowered
        with open(out, "rb") as f:
            results[n_dev] = f.read()
    assert results[1] == results[2] == results[8]


@pytest.mark.slow
def test_mesh_dryrun_cli(tmp_path):
    """`python -m blaze_tpu mesh-dryrun` emits the MULTICHIP_r*.json
    artifact shape (the versioned, testable generator)."""
    out = str(tmp_path / "MULTICHIP.json")
    p = subprocess.run(
        [sys.executable, "-m", "blaze_tpu", "mesh-dryrun",
         "--devices", "2", "--timeout", "240", "-o", out],
        capture_output=True, text=True, timeout=300,
        cwd=REPO,
        env={**os.environ,
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
    )
    assert p.returncode == 0, (p.stdout, p.stderr)
    with open(out) as f:
        doc = json.load(f)
    assert set(doc) == {"n_devices", "rc", "ok", "skipped", "tail"}
    assert doc["n_devices"] == 2
    assert doc["ok"] is True and doc["skipped"] is False
    assert "dryrun_multichip OK" in doc["tail"]


def test_mesh_relational_fused_kernels_byte_equal_and_pin():
    """ISSUE 13: the mesh tier inherits the fused relational kernels
    for free. MeshGroupByExec and MeshBroadcastJoinExec results are
    BYTE-equal (canonical total order, serialized IPC) to the mesh-off
    path - which now runs the fused grouped-carry / join kernels - and
    the mesh-stage dispatch pin (ONE program launch per stage) is
    unchanged by the fusion work."""
    from blaze_tpu.ops.fused import fuse_pipelines
    from blaze_tpu.runtime import dispatch

    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device (forced-host) mesh")

    def canon(table):
        df = table.to_pandas()
        df = df.sort_values(list(df.columns)).reset_index(drop=True)
        tbl = pa.Table.from_pandas(df, preserve_index=False) \
            .combine_chunks()
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, tbl.schema) as w:
            w.write_table(tbl)
        return sink.getvalue().to_pybytes()

    # grouped aggregate: mesh-off exchange sandwich (whose per-device
    # sub-plans run the fused grouped-carry kernels) vs MeshGroupByExec
    want = canon(run_plan(fuse_pipelines(sandwich(scan(n_parts=8),
                                                  n=8))))
    low = lower_plan_to_mesh(sandwich(scan(n_parts=8), n=8), mode="on")
    assert isinstance(low, MeshGroupByExec)
    assert canon(run_plan(low)) == want
    low._result = None
    run_plan(low)  # warm

    def run_grouped():
        low._result = None
        return run_plan(low)

    with dispatch.counting() as c:
        run_grouped()
    assert c.counts.get("mesh_dispatches", 0) == 1, c.counts

    # broadcast join: mesh-off fused pipeline vs MeshBroadcastJoinExec
    items = ColumnBatch.from_arrow(pa.record_batch({
        "ik": np.arange(10, dtype=np.int64),
        "iv": (np.arange(10, dtype=np.int64) * 100),
    }))

    def join(probe):
        return HashJoinExec(
            MemoryScanExec([[items]], items.schema), probe,
            ["ik"], ["k"], JoinType.INNER,
        )

    jwant = canon(run_plan(fuse_pipelines(join(scan()))))
    jlow = lower_plan_to_mesh(join(scan()), mode="on")
    assert isinstance(jlow, MeshBroadcastJoinExec)
    assert canon(run_plan(jlow)) == jwant
    jlow._result = None
    run_plan(jlow)  # warm

    def run_join():
        jlow._result = None
        return run_plan(jlow)

    with dispatch.counting() as c:
        run_join()
    assert c.counts.get("mesh_dispatches", 0) == 1, c.counts


# ---------------------------------------------------------------------------
# mesh sort / window shapes (ISSUE 20 satellite)
# ---------------------------------------------------------------------------


def test_mesh_sort_differential_byte_equal():
    """Global sort lowered to the mesh (per-shard device lexsorts +
    host run-merge) is row-for-row equal to the single-device oracle,
    unique keys so the total order is fully determined."""
    from blaze_tpu.ops.sort import SortExec, SortKey
    from blaze_tpu.parallel.mesh_exec import MeshSortExec

    def mk():
        return insert_exchanges(
            SortExec(scan(), [SortKey(Col("v"))]),
            4, shuffle_dir=tempfile.mkdtemp(),
        )

    want = run_plan(mk()).to_pandas()
    low = lower_plan_to_mesh(mk(), mode="on")
    assert isinstance(low, MeshSortExec)
    got = run_plan(low).to_pandas()
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


def test_mesh_sort_ties_keep_oracle_order():
    """Stability pin: duplicate keys keep earlier partitions first,
    matching the single-device stable sort."""
    from blaze_tpu.ops.sort import SortExec, SortKey
    from blaze_tpu.parallel.mesh_exec import MeshSortExec

    def mk(fetch=None):
        return insert_exchanges(
            SortExec(scan(), [SortKey(Col("k"))], fetch=fetch),
            4, shuffle_dir=tempfile.mkdtemp(),
        )

    want = run_plan(mk()).to_pandas()
    low = lower_plan_to_mesh(mk(), mode="on")
    assert isinstance(low, MeshSortExec)
    got = run_plan(low).to_pandas()
    pd.testing.assert_frame_equal(got, want, check_dtype=False)
    # top-n: fetch applies after the merge
    wantn = run_plan(mk(fetch=17)).to_pandas()
    lown = lower_plan_to_mesh(mk(fetch=17), mode="on")
    assert isinstance(lown, MeshSortExec)
    gotn = run_plan(lown).to_pandas()
    assert len(gotn) == 17
    pd.testing.assert_frame_equal(gotn, wantn, check_dtype=False)


def test_mesh_window_repartition_differential():
    """A partitioned window's hash exchange swaps for the mesh
    all_to_all repartition; the frames compute whole and the result
    matches the file-shuffle oracle after canonical order."""
    from blaze_tpu.ops.sort import SortKey
    from blaze_tpu.ops.window import WindowExec, WindowFn
    from blaze_tpu.parallel.mesh_exec import MeshRepartitionExec

    def mk():
        return insert_exchanges(
            WindowExec(
                scan(),
                partition_by=[Col("k")],
                order_by=[SortKey(Col("v"))],
                functions=[
                    WindowFn("row_number", None, "rn"),
                    WindowFn("sum", Col("v"), "run",
                             frame=("rows", None, 0)),
                ],
            ),
            4, shuffle_dir=tempfile.mkdtemp(),
        )

    def canon(t):
        return (t.to_pandas().sort_values(["k", "v"])
                .reset_index(drop=True))

    want = canon(run_plan(mk()))
    low = lower_plan_to_mesh(mk(), mode="on")
    assert isinstance(low.children[0], MeshRepartitionExec)
    got = canon(run_plan(low))
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


# ---------------------------------------------------------------------------
# fingerprint-keyed program cache (ISSUE 20 satellite): a SECOND
# QueryService in the same process reuses the first one's traced mesh
# programs - zero fresh traces, zero retraces, mesh_trace p50 ~ 0
# ---------------------------------------------------------------------------


def test_program_cache_kills_cross_service_retrace():
    from blaze_tpu.obs import meshprof
    from blaze_tpu.obs.metrics import REGISTRY
    from blaze_tpu.service import QueryService

    def run_once():
        with QueryService(enable_cache=False, enable_trace=False,
                          mesh_mode="on") as svc:
            q = svc.submit_plan(
                lower_plan_to_mesh(sandwich(scan()), mode="on")
            )
            return pa.Table.from_batches(
                svc.result(q.query_id, timeout=120)
            )

    t1 = run_once()  # may trace (cold in THIS process order)
    trace0 = REGISTRY.get("blaze_mesh_trace_total", op="mesh.groupby")
    retrace0 = REGISTRY.get("blaze_mesh_retrace_total",
                            op="mesh.groupby")

    t2 = run_once()  # FRESH QueryService, fresh op instances

    assert REGISTRY.get("blaze_mesh_retrace_total",
                        op="mesh.groupby") == retrace0
    # stronger than retrace delta 0: the warm service never traced at
    # all - the fingerprint-keyed program cache handed it the compiled
    # executable
    assert REGISTRY.get("blaze_mesh_trace_total",
                        op="mesh.groupby") == trace0
    # the warm stage's mesh_trace sub-phase is ~0 (no trace ran)
    warm_trace_s = meshprof.ROLLUP._ops["mesh.groupby"]["sub"][
        "mesh_trace"][-1]
    assert warm_trace_s < 0.05, warm_trace_s
    g1 = t1.to_pandas().sort_values("k").reset_index(drop=True)
    g2 = t2.to_pandas().sort_values("k").reset_index(drop=True)
    pd.testing.assert_frame_equal(g1, g2, check_dtype=False)
