"""Async wire data plane (service/wire_async.py): event-loop verb
serving must be protocol-identical to the threaded tier while holding
ZERO OS threads per parked connection.

Covers the PR's acceptance seams:
  * connection churn leaks nothing (fds, loop tasks, the
    blaze_connections{tier} gauge),
  * a slow reader mid-stream parks a coroutine - the process thread
    count stays flat while N clients stall,
  * cancel-on-disconnect and DRAINING rejections behave identically
    under wire="threaded" and wire="async" (the threaded tier is the
    differential oracle),
  * chaos seams (gateway.stream, service.admit) fire on the async
    path,
  * the router's fleet-wide relay budget (--stream-total-bytes)
    blocks over-budget streams (stream_total_waits) and returns the
    buffered-bytes gauge to zero after the streams drain.
"""

import os
import socket
import struct
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.batch import ColumnBatch
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.plan.serde import task_to_proto
from blaze_tpu.runtime.gateway import _FLAG_SERVICE, TaskGatewayServer
from blaze_tpu.runtime.transport import _recv_exact
from blaze_tpu.service import QueryService, ServiceClient
from blaze_tpu.service import wire as wire_mod
from blaze_tpu.service.wire import VERB_FETCH
from blaze_tpu.testing import chaos
from blaze_tpu.testing.chaos import Fault
from tests.test_service import GatedScan, wait_for
from tests.test_service_gateway import tiny_wire_task

_U64 = struct.Struct("<Q")


@pytest.fixture
def big_dataset(tmp_path):
    """A multi-part, multi-MB result: 4 scan partitions over ~1.5MB of
    rows each, plan = bare scan (no aggregation shrinking the
    output), so FETCH streams enough bytes to overflow kernel socket
    buffers and exercise backpressure."""
    rng = np.random.default_rng(7)
    n = 400_000
    p = str(tmp_path / "big.parquet")
    pq.write_table(
        pa.table(
            {
                "k": pa.array(rng.integers(0, 1 << 30, n), pa.int64()),
                "v": pa.array(rng.random(n), pa.float64()),
            }
        ),
        p,
    )

    def blob(parts=4):
        plan = ParquetScanExec([[FileRange(p)] for _ in range(parts)])
        return task_to_proto(plan, 0)

    return blob


def _service_conns() -> int:
    with wire_mod._CONN_LOCK:
        return wire_mod._CONNECTIONS.get("service", 0)


def _open_fds() -> int:
    return len(os.listdir("/proc/self/fd"))


def test_connection_churn_no_leaks():
    """200 connect/verb/close cycles: fd count, thread count, and the
    blaze_connections{tier="service"} gauge all return to baseline."""
    cb = ColumnBatch.from_pydict({"a": [1, 2, 3]})
    blob = tiny_wire_task(cb)
    with QueryService(max_concurrency=2) as svc:
        with TaskGatewayServer(service=svc, wire="async") as srv:
            # warm-up: populate the dispatch pool + loop machinery so
            # the baseline snapshot includes one-time allocations
            with ServiceClient(*srv.address) as c:
                c.run(blob)
            assert wait_for(lambda: _service_conns() == 0)
            fds0 = _open_fds()
            threads0 = threading.active_count()
            for _ in range(200):
                with ServiceClient(*srv.address) as c:
                    st = c.submit(blob)
                    c.fetch(st["query_id"])
            assert wait_for(lambda: _service_conns() == 0)
            # closed fds are reclaimed promptly; allow a little slack
            # for loop-internal churn mid-collection
            assert wait_for(lambda: _open_fds() <= fds0 + 8)
            assert threading.active_count() <= threads0 + 4


def test_slow_reader_parks_threadless(big_dataset):
    """N clients stalling mid-stream park N coroutines, not N OS
    threads: the thread count stays flat while every stream is wedged
    against a full socket buffer (the threaded tier would hold one
    blocked thread per connection here)."""
    blob = big_dataset()
    n_slow = 12
    with QueryService(max_concurrency=2,
                      stream_stall_s=60.0) as svc:
        with TaskGatewayServer(service=svc, wire="async") as srv:
            with ServiceClient(*srv.address) as c:
                st = c.submit(blob, detach=True)
                qid = st["query_id"]
                c.fetch(qid)  # warm-up: result cached + pool threads
            threads0 = threading.active_count()
            socks = []
            try:
                for _ in range(n_slow):
                    s = socket.create_connection(srv.address)
                    # shrink the receive window so a multi-MB part
                    # wedges fast
                    s.setsockopt(socket.SOL_SOCKET,
                                 socket.SO_RCVBUF, 16384)
                    s.sendall(
                        _U64.pack(_FLAG_SERVICE)
                        + ServiceClient._id_verb(VERB_FETCH, qid,
                                                 60_000)
                    )
                    assert len(s.recv(8)) == 8  # first bytes flowed
                    socks.append(s)  # ...then stop reading: parked
                # give every stream time to wedge against the buffers
                time.sleep(1.0)
                assert threading.active_count() <= threads0 + 4, (
                    "parked streams must not hold OS threads"
                )
            finally:
                for s in socks:
                    s.close()
            assert wait_for(lambda: _service_conns() == 0)


@pytest.mark.parametrize("wire", ["threaded", "async"])
def test_cancel_on_disconnect_parity(wire):
    """A vanished client's non-detached queries get cancelled on both
    planes - the wire semantic the router's session tier depends on."""
    release = threading.Event()
    blocker = GatedScan(release)
    try:
        with QueryService(max_concurrency=1,
                          enable_cache=False) as svc:
            with TaskGatewayServer(service=svc, wire=wire) as srv:
                svc.submit_plan(blocker, estimated_bytes=0)
                assert wait_for(lambda: blocker.started.is_set())
                cb = ColumnBatch.from_pydict({"a": [1]})
                c = ServiceClient(*srv.address)
                st = c.submit(tiny_wire_task(cb))
                qid = st["query_id"]
                assert st["state"] == "QUEUED"
                c.close()
                assert wait_for(
                    lambda: svc.poll(qid)["state"] == "CANCELLED"
                )
    finally:
        release.set()


def test_draining_and_error_replies_identical_across_planes():
    """DRAINING rejections, unknown-query errors, and stats shapes are
    reply-identical between the threaded oracle and the async plane
    (zero client-visible protocol change)."""
    cb = ColumnBatch.from_pydict({"a": [1]})
    blob = tiny_wire_task(cb)
    replies = {}
    for wire in ("threaded", "async"):
        with QueryService(max_concurrency=1) as svc:
            svc.draining = True
            with TaskGatewayServer(service=svc, wire=wire) as srv:
                with ServiceClient(*srv.address) as c:
                    # submit_raw: the cooked submit() retries DRAINING
                    # rejections with backoff - here the raw reply IS
                    # the assertion target
                    sub = c.submit_raw(blob, meta={})
                    poll = c.poll("no-such-query")
                    replies[wire] = (sub["state"], sub["error"], poll)
    assert replies["threaded"] == replies["async"]
    state, error, poll = replies["async"]
    assert state == "REJECTED_OVERLOADED"
    assert error.startswith("DRAINING:")
    assert "unknown query" in poll["error"]


def test_chaos_seams_fire_on_async_path(big_dataset):
    """gateway.stream and service.admit chaos seams keep firing when
    the verbs ride the event loop; a DROP on gateway.stream aborts
    the connection but leaves the part for a resume re-FETCH."""
    blob = big_dataset(parts=2)
    with chaos.active([
        Fault("service.admit", klass="STALL", stall_s=0.01, times=1),
        Fault("gateway.stream", klass="STALL", stall_s=0.01,
              times=1),
    ]) as plan:
        with QueryService(max_concurrency=1) as svc:
            with TaskGatewayServer(service=svc, wire="async") as srv:
                with ServiceClient(*srv.address) as c:
                    st = c.submit(blob, detach=True)
                    qid = st["query_id"]
                    parts = c.fetch(qid)
                    assert len(parts) > 0
        assert plan.fired("service.admit") == 1
        assert plan.fired("gateway.stream") == 1

    with QueryService(max_concurrency=1) as svc:
        with TaskGatewayServer(service=svc, wire="async") as srv:
            with ServiceClient(*srv.address) as c:
                st = c.submit(blob, detach=True)
                qid = st["query_id"]
                clean_parts = len(c.fetch(qid))
            with chaos.active([
                Fault("gateway.stream", klass="DROP", times=1),
            ]) as plan:
                with ServiceClient(*srv.address,
                                   reconnect_attempts=0) as c:
                    with pytest.raises((ConnectionError, OSError)):
                        c.fetch(qid)
                # the dropped connection is dead; a fresh one resumes
                # and collects the full retained result
                with ServiceClient(*srv.address) as c:
                    assert len(c.fetch(qid)) == clean_parts
                assert plan.fired("gateway.stream") == 1


def test_router_stream_total_budget(big_dataset):
    """Fleet-wide relay cap: with --stream-total-bytes smaller than
    two concurrent streams' parts, the second stream's reader waits
    (stream_total_waits > 0) instead of buffering past the budget,
    and the buffered-bytes gauge drains back to zero."""
    from blaze_tpu.router.proxy import Router, RouterServer

    blob = big_dataset()
    with QueryService(max_concurrency=2) as svc:
        with TaskGatewayServer(service=svc, wire="async") as srv:
            router = Router(
                ["%s:%d" % srv.address],
                poll_interval_s=0.1,
                heartbeat_timeout_s=2.0,
                start=False,
                stream_window=4,
                stream_total_bytes=2 << 20,
            )
            router.registry.poll_now()
            rsrv = RouterServer(router, wire="async").start()
            try:
                with ServiceClient(*rsrv.address) as c0:
                    qids = [
                        c0.submit(blob, detach=True)["query_id"]
                        for _ in range(2)
                    ]

                def slow_fetch(qid):
                    # raw socket with a tiny receive window (set
                    # BEFORE connect) so kernel buffering cannot
                    # absorb the stream - the relay must park bytes
                    sock = socket.socket(socket.AF_INET,
                                         socket.SOCK_STREAM)
                    sock.setsockopt(socket.SOL_SOCKET,
                                    socket.SO_RCVBUF, 16384)
                    sock.connect(rsrv.address)
                    try:
                        sock.sendall(
                            _U64.pack(_FLAG_SERVICE)
                            + ServiceClient._id_verb(
                                VERB_FETCH, qid, 120_000
                            )
                        )
                        got = 0
                        while True:
                            (ln,) = _U64.unpack(
                                _recv_exact(sock, 8)
                            )
                            if ln == 0:
                                return got
                            _recv_exact(sock, ln)
                            got += 1
                            time.sleep(0.1)  # slow consumer
                    finally:
                        sock.close()

                results = [None, None]
                ts = [
                    threading.Thread(
                        target=lambda i=i, q=q: results.__setitem__(
                            i, slow_fetch(q)
                        )
                    )
                    for i, q in enumerate(qids)
                ]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(timeout=120)
                assert results[0] == results[1]
                assert results[0] and results[0] > 1
                assert router.counters["stream_total_waits"] > 0
                assert wait_for(
                    lambda: router._stream_buffered == 0
                )
            finally:
                rsrv.stop()
                router.close()


def test_router_fanin_exceeding_dispatch_pool_no_deadlock():
    """Cross-tier dispatch-pool regression pin: router verb handlers
    park their pool thread on downstream replica calls, so sharing ONE
    pool across tiers let N >= pool_size concurrent router clients
    starve the replicas they were waiting on (total wire deadlock when
    both tiers share a process - the bench fleet shape). Per-tier
    pools keep the router->service supply graph acyclic: a fan-in
    wider than the pool must still complete promptly."""
    from blaze_tpu.router.proxy import Router, RouterServer
    from blaze_tpu.service.wire_async import dispatch_pool

    pool_width = dispatch_pool("router")._max_workers
    conc = pool_width + 8  # strictly wider than any one pool
    cb = ColumnBatch.from_pydict({"x": list(range(64))})
    blob = tiny_wire_task(cb)
    svcs = [QueryService(max_concurrency=4) for _ in range(2)]
    srvs = [
        TaskGatewayServer(service=s, wire="async").start()
        for s in svcs
    ]
    router = Router(
        ["%s:%d" % s.address for s in srvs],
        poll_interval_s=0.1,
        start=False,
    )
    router.registry.poll_now()
    rsrv = RouterServer(router, wire="async").start()
    errs: list = []
    try:
        host, port = rsrv.address

        def client():
            try:
                # short socket timeout: a recurrence of the deadlock
                # fails the test in seconds, not pytest's global
                # timeout
                with ServiceClient(host, port, timeout=30.0) as cl:
                    for _ in range(2):
                        cl.run(blob)
            except Exception as e:  # noqa: BLE001 - assert below
                errs.append(repr(e))

        ts = [threading.Thread(target=client) for _ in range(conc)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=90)
        assert not any(t.is_alive() for t in ts), "fan-in wedged"
        assert errs == []
    finally:
        rsrv.stop()
        router.close()
        for s in srvs:
            s.stop()
        for s in svcs:
            s.close()
