"""Property-based differential testing of WHOLE PLANS: random
filter/project/aggregate/window/sort plans executed by the native engine
vs the pandas host engine (planner/host_engine) over the same PlanSpec.

The plan-level analog of test_differential_random's expression fuzzing -
together they mirror the reference's differential TPC-DS strategy at both
granularities (SURVEY 4)."""

import numpy as np
import pandas as pd
import pytest

from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.planner import (
    AggSpec,
    ConvertStrategy,
    FilterSpec,
    MemorySpec,
    ProjectSpec,
    SortSpec,
    convert_plan,
)
from blaze_tpu.planner.host_engine import execute_host
from blaze_tpu.runtime.executor import run_plan


def rand_df(rng, n=400):
    return pd.DataFrame(
        {
            "k": rng.integers(0, 8, n),
            "a": rng.integers(-30, 30, n),
            "b": np.round(rng.standard_normal(n) * 10, 3),
        }
    )


def rand_plan(rng, df):
    node = MemorySpec(dataframe=df, partitions=1)
    # random filter
    thr = int(rng.integers(-20, 20))
    node = FilterSpec(children=[node], predicate=Col("a") > thr)
    # random projection
    node = ProjectSpec(
        children=[node],
        exprs=[
            (Col("k"), "k"),
            (Col("a") * 2 + int(rng.integers(0, 5)), "a2"),
            (Col("b"), "b"),
        ],
    )
    kind = rng.integers(0, 2)
    if kind == 0:
        node = AggSpec(
            children=[node],
            keys=[(Col("k"), "k")],
            aggs=[
                (AggExpr(AggFn.SUM, Col("a2")), "s"),
                (AggExpr(AggFn.COUNT_STAR, None), "n"),
                (AggExpr(AggFn.MAX, Col("b")), "mx"),
            ],
            mode="complete",
        )
        sort_cols = ["k"]
    else:
        node = SortSpec(
            children=[node],
            keys=[(Col("a2"), True, True), (Col("b"), True, True)],
            fetch=50,
        )
        sort_cols = None
    return node, sort_cols


@pytest.mark.parametrize("seed", range(12))
def test_plan_native_matches_host(seed):
    rng = np.random.default_rng(1000 + seed)
    df = rand_df(rng)
    plan, sort_cols = rand_plan(rng, df)

    native = run_plan(convert_plan(plan)).to_pandas()
    host = execute_host(plan)

    if sort_cols:
        native = native.sort_values(sort_cols).reset_index(drop=True)
        host = host.sort_values(sort_cols).reset_index(drop=True)
    else:
        native = native.reset_index(drop=True)
        host = host.reset_index(drop=True)
    assert list(native.columns) == list(host.columns)
    assert len(native) == len(host)
    for c in native.columns:
        a = native[c].to_numpy()
        b = host[c].to_numpy()
        if a.dtype.kind == "f" or b.dtype.kind == "f":
            np.testing.assert_allclose(
                a.astype(float), b.astype(float), rtol=1e-9,
                err_msg=f"seed={seed} col={c}",
            )
        else:
            np.testing.assert_array_equal(
                a, b, err_msg=f"seed={seed} col={c}"
            )


# ---------------------------------------------------------------------------
# exchange-tier fuzz: the same random plan with and without real shuffle
# files underneath every join/final aggregate must agree (VERDICT r2
# Weak #4's property, beyond the named TPC-DS queries)
# ---------------------------------------------------------------------------

def _rand_tables(rng):
    n_l, n_r = int(rng.integers(200, 800)), int(rng.integers(300, 1200))
    left = pd.DataFrame({
        "lk": rng.integers(0, 40, n_l),
        "lv": np.round(rng.standard_normal(n_l) * 5, 3),
    })
    right = pd.DataFrame({
        "rk": rng.integers(0, 40, n_r),
        "rv": rng.integers(-100, 100, n_r),
    })
    return left, right


def _join_agg_plan(left, right, jt, rng_state):
    import pyarrow as pa

    from blaze_tpu.batch import ColumnBatch
    from blaze_tpu.ops import (AggMode, HashAggregateExec,
                               MemoryScanExec)
    from blaze_tpu.ops.joins import JoinType, SortMergeJoinExec

    def scan(df):
        cb = ColumnBatch.from_arrow(
            pa.RecordBatch.from_pandas(df, preserve_index=False))
        return MemoryScanExec([[cb]], cb.schema)

    join = SortMergeJoinExec(scan(left), scan(right),
                             ["lk"], ["rk"], jt)
    if jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
        aggs = [(AggExpr(AggFn.SUM, Col("lv")), "s"),
                (AggExpr(AggFn.COUNT_STAR, None), "n")]
    else:
        aggs = [(AggExpr(AggFn.SUM, Col("lv")), "s"),
                (AggExpr(AggFn.COUNT_STAR, None), "n"),
                (AggExpr(AggFn.MIN, Col("rv")), "mn")]
    return HashAggregateExec(
        join, keys=[(Col("lk"), "lk")], aggs=aggs,
        mode=AggMode.COMPLETE,
    )


@pytest.mark.parametrize("seed", range(10))
def test_random_join_agg_through_exchanges(seed, tmp_path):
    from blaze_tpu.ops.joins import JoinType
    from blaze_tpu.planner.distribute import insert_exchanges

    rng = np.random.default_rng(2000 + seed)
    left, right = _rand_tables(rng)
    jt = [JoinType.INNER, JoinType.LEFT, JoinType.LEFT_SEMI,
          JoinType.LEFT_ANTI][seed % 4]
    n_parts = int(rng.integers(2, 6))

    plain = run_plan(
        _join_agg_plan(left, right, jt, rng)
    ).to_pandas().sort_values("lk").reset_index(drop=True)
    exchanged_plan = insert_exchanges(
        _join_agg_plan(left, right, jt, rng), n_parts,
        shuffle_dir=str(tmp_path),
    )
    exchanged = run_plan(exchanged_plan).to_pandas().sort_values(
        "lk").reset_index(drop=True)

    assert len(plain) == len(exchanged), (seed, jt)
    for c in plain.columns:
        a = plain[c].to_numpy()
        b = exchanged[c].to_numpy()
        if a.dtype.kind == "f":
            np.testing.assert_allclose(
                a, b.astype(float), rtol=1e-9,
                err_msg=f"seed={seed} jt={jt} col={c}")
        else:
            np.testing.assert_array_equal(
                a, b, err_msg=f"seed={seed} jt={jt} col={c}")


@pytest.mark.parametrize("seed", range(6))
def test_random_window_through_exchanges(seed, tmp_path):
    """Random rank/running-sum windows agree with and without a hash
    exchange on their PARTITION BY underneath (the distribution rule
    Spark would plant)."""
    import pyarrow as pa

    from blaze_tpu.batch import ColumnBatch
    from blaze_tpu.ops import MemoryScanExec
    from blaze_tpu.ops.sort import SortKey
    from blaze_tpu.ops.window import WindowExec, WindowFn
    from blaze_tpu.planner.distribute import insert_exchanges

    rng = np.random.default_rng(3000 + seed)
    n = int(rng.integers(300, 1200))
    df = pd.DataFrame({
        "p": rng.integers(0, 12, n).astype(np.int64),
        # unique order key: rank/row_number become deterministic
        "o": rng.permutation(n).astype(np.int64),
        "v": rng.integers(-50, 50, n).astype(np.int64),
    })

    def plan(parts):
        cbs = []
        bounds = np.linspace(0, len(df), parts + 1, dtype=int)
        for i in range(parts):
            chunk = df.iloc[bounds[i]:bounds[i + 1]]
            rb = pa.RecordBatch.from_pandas(
                chunk.reset_index(drop=True), preserve_index=False)
            cbs.append([ColumnBatch.from_arrow(rb)])
        scan = MemoryScanExec(cbs, cbs[0][0].schema)
        return WindowExec(
            scan,
            partition_by=[Col("p")],
            order_by=[SortKey(Col("o"), seed % 2 == 0, True)],
            functions=[
                WindowFn("row_number", None, "rn"),
                WindowFn("sum", Col("v"), "run",
                         frame=("rows", None, 0)),
            ],
        )

    plain = run_plan(plan(1)).to_pandas().sort_values(
        ["p", "o"]).reset_index(drop=True)
    # multi-partition scan -> the rule must plant a hash exchange on p
    ex_plan = insert_exchanges(plan(3), 4, shuffle_dir=str(tmp_path))
    exchanged = run_plan(ex_plan).to_pandas().sort_values(
        ["p", "o"]).reset_index(drop=True)
    assert len(plain) == len(exchanged) == n
    for c in ("rn", "run"):
        np.testing.assert_array_equal(
            plain[c].to_numpy(), exchanged[c].to_numpy(),
            err_msg=f"seed={seed} col={c}",
        )
