"""Pallas kernel tests (interpret mode on the CPU test mesh; the same
kernels compile to Mosaic on real TPU - validated in bench/driver runs)."""

import numpy as np
import jax.numpy as jnp
import pytest

from blaze_tpu.exprs.hashing import hash_int_host, hash_long_host
from blaze_tpu.ops.kernels.murmur3_pallas import (
    partition_ids_int32,
    partition_ids_int64,
    supports,
)


def exp_pid(h, n=200):
    r = np.int32(np.uint32(h & 0xFFFFFFFF)) % n
    return int(r + n if r < 0 else r)


def test_pallas_partition_ids_int32_bit_exact():
    rng = np.random.default_rng(1)
    cap = 2048
    vals = rng.integers(-(2**31), 2**31, cap).astype(np.int32)
    got = np.asarray(
        partition_ids_int32(jnp.asarray(vals), 200, interpret=True)
    )
    exp = np.array([exp_pid(hash_int_host(int(v))) for v in vals[:256]])
    np.testing.assert_array_equal(got[:256], exp)


def test_pallas_partition_ids_int64_bit_exact():
    rng = np.random.default_rng(2)
    cap = 2048
    vals = rng.integers(-(2**63), 2**63 - 1, cap, dtype=np.int64)
    got = np.asarray(
        partition_ids_int64(jnp.asarray(vals), 31, interpret=True)
    )
    exp = np.array(
        [exp_pid(hash_long_host(int(v)), 31) for v in vals[:256]]
    )
    np.testing.assert_array_equal(got[:256], exp)


def test_supports():
    assert supports("int64", 4096)
    assert supports("int32", 1024)
    assert not supports("utf8", 4096)
    assert not supports("int64", 1000)  # not block-aligned


def test_masked_stats_interpret():
    """Fused sum/min/max/count over a masked column == numpy, incl. the
    all-masked empty selection (identities + count 0)."""
    import numpy as np
    import jax.numpy as jnp

    from blaze_tpu.ops.kernels import stats_pallas as sp

    rng = np.random.default_rng(17)
    n = 4096
    vals = (rng.random(n).astype(np.float32) - 0.5) * 1000
    mask = (rng.random(n) < 0.7)

    assert sp.supports(n, jnp.float32)
    out = np.asarray(sp.masked_stats(
        jnp.asarray(vals), jnp.asarray(mask), interpret=True))
    sel = vals[mask]
    np.testing.assert_allclose(out[0], sel.sum(), rtol=1e-5)
    assert out[1] == sel.min()
    assert out[2] == sel.max()
    assert out[3] == len(sel)

    empty = np.asarray(sp.masked_stats(
        jnp.asarray(vals), jnp.zeros(n, dtype=bool), interpret=True))
    assert empty[0] == 0.0 and empty[3] == 0.0
    assert np.isinf(empty[1]) and np.isinf(empty[2])

    # int32 values path + multi-chunk shape (> _CHUNK_ROWS)
    big_n = 1 << 20
    ivals = rng.integers(-1000, 1000, big_n).astype(np.int32)
    imask = rng.random(big_n) < 0.5
    got = np.asarray(sp.masked_stats(
        jnp.asarray(ivals), jnp.asarray(imask), interpret=True))
    isel = ivals[imask]
    np.testing.assert_allclose(got[0], isel.sum(), rtol=1e-4)
    assert got[1] == isel.min() and got[2] == isel.max()
    assert got[3] == len(isel)


def test_pallas_segment_sum_interpret():
    from blaze_tpu.ops.kernels import segreduce_pallas as sr

    rng = np.random.default_rng(5)
    cap, k = 4096, 512
    gid = rng.integers(0, k, cap).astype(np.int32)
    # park some rows out of range: they must contribute nowhere
    gid[::97] = k + 3
    v = (rng.random(cap) * 100 - 50).astype(np.float32)
    assert sr.supports(cap, k)
    got = np.asarray(sr.segment_sum(jnp.asarray(gid), jnp.asarray(v), k))
    exp = np.zeros(k, np.float64)
    for g, x in zip(gid, v):
        if g < k:
            exp[g] += x
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-3)


def test_pallas_segment_minmax_interpret():
    from blaze_tpu.ops.kernels import segreduce_pallas as sr

    rng = np.random.default_rng(6)
    cap, k = 2048, 512
    gid = rng.integers(0, k, cap).astype(np.int32)
    v = (rng.random(cap) * 1000 - 500).astype(np.float32)
    lo = np.asarray(
        sr.segment_minmax(jnp.asarray(gid), jnp.asarray(v), k, True)
    )
    hi = np.asarray(
        sr.segment_minmax(jnp.asarray(gid), jnp.asarray(v), k, False)
    )
    for g in range(k):
        sel = v[gid == g]
        if len(sel):
            assert lo[g] == sel.min()
            assert hi[g] == sel.max()
        else:
            assert lo[g] == np.inf and hi[g] == -np.inf


def test_pallas_compact_interpret():
    from blaze_tpu.ops.kernels import compact_pallas as cp

    rng = np.random.default_rng(7)
    cap = 4096
    v = (rng.random(cap) * 100 - 50).astype(np.float32)
    keep = rng.random(cap) < 0.37
    assert cp.supports(cap)
    out, n = cp.compact_column_f32(jnp.asarray(v), jnp.asarray(keep))
    out = np.asarray(out)
    n = int(n)
    exp = v[keep]
    assert n == len(exp)
    np.testing.assert_array_equal(out[:n], exp)
    assert (out[n:] == 0).all()


def test_pallas_compact_i32_exact_full_range():
    from blaze_tpu.ops.kernels import compact_pallas as cp

    rng = np.random.default_rng(8)
    cap = 2048
    v = rng.integers(-(2**31), 2**31, cap).astype(np.int32)
    keep = rng.random(cap) < 0.5
    out, n = cp.compact_column_i32(jnp.asarray(v), jnp.asarray(keep))
    out = np.asarray(out)
    n = int(n)
    np.testing.assert_array_equal(out[:n], v[keep])


def test_pallas_segment_sum_matches_engine_segops():
    """Parity with the aggregate's XLA segment path (the operator-suite
    cross-check VERDICT r3 asked for)."""
    import jax

    from blaze_tpu.ops.kernels import segreduce_pallas as sr

    rng = np.random.default_rng(9)
    cap, k = 8192, 1024
    gid = jnp.asarray(rng.integers(0, k, cap).astype(np.int32))
    v = jnp.asarray((rng.random(cap) * 10).astype(np.float32))
    xla = jax.ops.segment_sum(v, gid, num_segments=k)
    pls = sr.segment_sum(gid, v, k)
    np.testing.assert_allclose(
        np.asarray(pls), np.asarray(xla), rtol=1e-4, atol=1e-3
    )


def test_pallas_segment_sum_nonfinite_isolated():
    """ADVICE r4: a NaN/inf value anywhere in a 1024-row block must
    poison ONLY its own segment, never the whole block's segments
    (IEEE 0*NaN=NaN would leak through a raw one-hot contraction)."""
    from blaze_tpu.ops.kernels import segreduce_pallas as sr

    rng = np.random.default_rng(10)
    cap, k = 2048, 512
    gid = rng.integers(0, k, cap).astype(np.int32)
    v = (rng.random(cap) * 10).astype(np.float32)
    gid[7], v[7] = 3, np.nan           # NaN lands in segment 3
    gid[1500], v[1500] = 5, np.inf     # +inf lands in segment 5
    gid[11], v[11] = k + 2, np.nan     # dead NaN row: contributes nowhere
    got = np.asarray(sr.segment_sum(jnp.asarray(gid), jnp.asarray(v), k))
    exp = np.zeros(k, np.float64)
    for g, x in zip(gid, v):
        if g < k:
            exp[g] += np.float64(x)
    assert np.isnan(got[3]) and np.isnan(exp[3])
    assert got[5] == np.inf
    fin = np.isfinite(exp)
    assert fin.sum() == k - 2
    np.testing.assert_allclose(got[fin], exp[fin], rtol=1e-4, atol=1e-3)


def test_pallas_compact_preserves_nonfinite():
    """ADVICE r4: compacting a float column containing NaN/inf (kept or
    dropped) must move every surviving value bit-exactly."""
    from blaze_tpu.ops.kernels import compact_pallas as cp

    rng = np.random.default_rng(11)
    cap = 2048
    v = (rng.random(cap) * 100 - 50).astype(np.float32)
    v[3] = np.nan
    v[4] = np.inf
    v[5] = -np.inf
    v[1024] = np.nan          # dropped NaN in the second block
    keep = rng.random(cap) < 0.5
    keep[3] = keep[4] = keep[5] = True
    keep[1024] = False
    out, n = cp.compact_column_f32(jnp.asarray(v), jnp.asarray(keep))
    out = np.asarray(out)
    n = int(n)
    exp = v[keep]
    assert n == len(exp)
    np.testing.assert_array_equal(
        out[:n].view(np.uint32), exp.view(np.uint32)
    )
    assert (out[n:] == 0).all()
