"""Pallas kernel tests (interpret mode on the CPU test mesh; the same
kernels compile to Mosaic on real TPU - validated in bench/driver runs)."""

import numpy as np
import jax.numpy as jnp
import pytest

from blaze_tpu.exprs.hashing import hash_int_host, hash_long_host
from blaze_tpu.ops.kernels.murmur3_pallas import (
    partition_ids_int32,
    partition_ids_int64,
    supports,
)


def exp_pid(h, n=200):
    r = np.int32(np.uint32(h & 0xFFFFFFFF)) % n
    return int(r + n if r < 0 else r)


def test_pallas_partition_ids_int32_bit_exact():
    rng = np.random.default_rng(1)
    cap = 2048
    vals = rng.integers(-(2**31), 2**31, cap).astype(np.int32)
    got = np.asarray(
        partition_ids_int32(jnp.asarray(vals), 200, interpret=True)
    )
    exp = np.array([exp_pid(hash_int_host(int(v))) for v in vals[:256]])
    np.testing.assert_array_equal(got[:256], exp)


def test_pallas_partition_ids_int64_bit_exact():
    rng = np.random.default_rng(2)
    cap = 2048
    vals = rng.integers(-(2**63), 2**63 - 1, cap, dtype=np.int64)
    got = np.asarray(
        partition_ids_int64(jnp.asarray(vals), 31, interpret=True)
    )
    exp = np.array(
        [exp_pid(hash_long_host(int(v)), 31) for v in vals[:256]]
    )
    np.testing.assert_array_equal(got[:256], exp)


def test_supports():
    assert supports("int64", 4096)
    assert supports("int32", 1024)
    assert not supports("utf8", 4096)
    assert not supports("int64", 1000)  # not block-aligned


def test_masked_stats_interpret():
    """Fused sum/min/max/count over a masked column == numpy, incl. the
    all-masked empty selection (identities + count 0)."""
    import numpy as np
    import jax.numpy as jnp

    from blaze_tpu.ops.kernels import stats_pallas as sp

    rng = np.random.default_rng(17)
    n = 4096
    vals = (rng.random(n).astype(np.float32) - 0.5) * 1000
    mask = (rng.random(n) < 0.7)

    assert sp.supports(n, jnp.float32)
    out = np.asarray(sp.masked_stats(
        jnp.asarray(vals), jnp.asarray(mask), interpret=True))
    sel = vals[mask]
    np.testing.assert_allclose(out[0], sel.sum(), rtol=1e-5)
    assert out[1] == sel.min()
    assert out[2] == sel.max()
    assert out[3] == len(sel)

    empty = np.asarray(sp.masked_stats(
        jnp.asarray(vals), jnp.zeros(n, dtype=bool), interpret=True))
    assert empty[0] == 0.0 and empty[3] == 0.0
    assert np.isinf(empty[1]) and np.isinf(empty[2])

    # int32 values path + multi-chunk shape (> _CHUNK_ROWS)
    big_n = 1 << 20
    ivals = rng.integers(-1000, 1000, big_n).astype(np.int32)
    imask = rng.random(big_n) < 0.5
    got = np.asarray(sp.masked_stats(
        jnp.asarray(ivals), jnp.asarray(imask), interpret=True))
    isel = ivals[imask]
    np.testing.assert_allclose(got[0], isel.sum(), rtol=1e-4)
    assert got[1] == isel.min() and got[2] == isel.max()
    assert got[3] == len(isel)
