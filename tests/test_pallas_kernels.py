"""Pallas kernel tests (interpret mode on the CPU test mesh; the same
kernels compile to Mosaic on real TPU - validated in bench/driver runs)."""

import numpy as np
import jax.numpy as jnp
import pytest

from blaze_tpu.exprs.hashing import hash_int_host, hash_long_host
from blaze_tpu.ops.kernels.murmur3_pallas import (
    partition_ids_int32,
    partition_ids_int64,
    supports,
)


def exp_pid(h, n=200):
    r = np.int32(np.uint32(h & 0xFFFFFFFF)) % n
    return int(r + n if r < 0 else r)


def test_pallas_partition_ids_int32_bit_exact():
    rng = np.random.default_rng(1)
    cap = 2048
    vals = rng.integers(-(2**31), 2**31, cap).astype(np.int32)
    got = np.asarray(
        partition_ids_int32(jnp.asarray(vals), 200, interpret=True)
    )
    exp = np.array([exp_pid(hash_int_host(int(v))) for v in vals[:256]])
    np.testing.assert_array_equal(got[:256], exp)


def test_pallas_partition_ids_int64_bit_exact():
    rng = np.random.default_rng(2)
    cap = 2048
    vals = rng.integers(-(2**63), 2**63 - 1, cap, dtype=np.int64)
    got = np.asarray(
        partition_ids_int64(jnp.asarray(vals), 31, interpret=True)
    )
    exp = np.array(
        [exp_pid(hash_long_host(int(v)), 31) for v in vals[:256]]
    )
    np.testing.assert_array_equal(got[:256], exp)


def test_supports():
    assert supports("int64", 4096)
    assert supports("int32", 1024)
    assert not supports("utf8", 4096)
    assert not supports("int64", 1000)  # not block-aligned
