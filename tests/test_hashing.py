"""Spark murmur3 bit-compatibility contract tests.

Ground-truth vectors generated with Spark's Murmur3_x86_32 (the same
contract the reference validates in datafusion-ext spark_hash.rs tests).
Device and host implementations are additionally cross-checked on random
data.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from blaze_tpu.types import DataType
from blaze_tpu.exprs.hashing import (
    SPARK_SEED,
    hash_bytes_host,
    hash_columns_device,
    hash_int_host,
    hash_long_host,
    hash_rows_host,
    pmod,
)


def u32(x):
    return np.uint32(int(x) & 0xFFFFFFFF)


def test_spark_vectors_bytes():
    cases = {
        "": 142593372,
        "a": 1485273170,
        "ab": -97053317,
        "abc": 1322437556,
        "abcd": -396302900,
        "abcde": 814637928,
        "hello": 3286402344,
        "bar": 2486176763,
        "😁": 885025535,
        "天地": 2395000894,
    }
    for s, exp in cases.items():
        assert u32(hash_bytes_host(s.encode())) == u32(exp), s


def test_spark_vectors_int():
    vals = [1, 0, -1, 2**31 - 1, -(2**31)]
    exp = [0xDEA578E3, 0x379FAE8F, 0xA0590E3D, 0x07FB67E7, 0x2B1F0FC6]
    for v, e in zip(vals, exp):
        assert u32(hash_int_host(v)) == u32(e)


def test_spark_vectors_long():
    vals = [1, 0, -1, 2**63 - 1, -(2**63)]
    exp = [0x99F0149D, 0x9C67B85D, 0xC8008529, 0xA05B5D7B, 0xCD1E64FB]
    for v, e in zip(vals, exp):
        assert u32(hash_long_host(v)) == u32(e)


def test_pmod_spark_partitions():
    h = np.array(
        [0x99F0149D, 0x9C67B85D, 0xC8008529, 0xA05B5D7B, 0xCD1E64FB],
        dtype=np.uint32,
    ).view(np.int32)
    got = np.asarray(pmod(jnp.asarray(h), 200))
    assert got.tolist() == [69, 5, 193, 171, 115]


def test_device_matches_host_fixed_width():
    rng = np.random.default_rng(0)
    n = 512
    i32 = rng.integers(-(2**31), 2**31, n, dtype=np.int64).astype(np.int32)
    i64 = rng.integers(-(2**63), 2**63 - 1, n, dtype=np.int64)
    f64 = rng.standard_normal(n)
    f64[::17] = 0.0
    f64[1::17] = -0.0
    validity = rng.random(n) > 0.2

    host = hash_rows_host(
        [
            (i32, None, DataType.int32(), None),
            (i64, validity, DataType.int64(), None),
            (f64, None, DataType.float64(), None),
        ],
        n,
    )
    dev = hash_columns_device(
        [
            (jnp.asarray(i32), None, DataType.int32()),
            (jnp.asarray(i64), jnp.asarray(validity), DataType.int64()),
            (jnp.asarray(f64), None, DataType.float64()),
        ],
        n,
    )
    np.testing.assert_array_equal(np.asarray(dev), host)


def test_device_single_int_column_vectors():
    vals = jnp.asarray(np.array([1, 0, -1], dtype=np.int32))
    h = hash_columns_device([(vals, None, DataType.int32())], 3)
    exp = np.array([0xDEA578E3, 0x379FAE8F, 0xA0590E3D], dtype=np.uint32)
    np.testing.assert_array_equal(
        np.asarray(h).view(np.uint32), exp
    )


def test_null_skips_column():
    vals = np.array([7], dtype=np.int32)
    valid = np.array([False])
    h = hash_rows_host([(vals, valid, DataType.int32(), None)], 1)
    # NULL leaves the running hash at the seed
    assert u32(h[0].view(np.uint32) if hasattr(h[0], "view") else h[0]) \
        == SPARK_SEED or np.uint32(h.view(np.uint32)[0]) == SPARK_SEED


def test_string_hash_in_chain():
    import pyarrow as pa

    codes = np.array([0, 1, 0], dtype=np.int32)
    dictionary = pa.array(["hello", "bar"])
    h = hash_rows_host(
        [(codes, None, DataType.utf8(), dictionary)], 3
    ).view(np.uint32)
    assert h[0] == u32(3286402344)
    assert h[1] == u32(2486176763)
    assert h[2] == u32(3286402344)
