"""Decimal arithmetic and cast semantics tests (the engine's i64-unscaled
decimal representation, matching the reference's i64-only decimals,
plan.proto:598-601)."""

from decimal import Decimal

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import ColumnBatch
from blaze_tpu.exprs import Col, ScalarFn
from blaze_tpu.exprs.ir import bind
from blaze_tpu.exprs.eval import DeviceEvaluator
from blaze_tpu.types import DataType


def run_expr(expr, rb):
    cb = ColumnBatch.from_arrow(rb)
    bound = bind(expr, cb.schema)
    ev = DeviceEvaluator(
        cb.schema, [(c.values, c.validity) for c in cb.columns],
        cb.capacity,
    )
    v, m = ev.evaluate(bound)
    n = cb.num_rows
    vals = np.asarray(v)[:n]
    mask = np.asarray(m)[:n] if m is not None else np.ones(n, dtype=bool)
    return [vals[i].item() if mask[i] else None for i in range(n)]


def dec_col(vals, p=10, s=2):
    return pa.array(
        [None if v is None else Decimal(v) for v in vals],
        type=pa.decimal128(p, s),
    )


def test_decimal_add_sub_same_scale():
    rb = pa.RecordBatch.from_arrays(
        [dec_col(["1.50", "2.25", None]), dec_col(["0.50", "1.00", "9.99"])],
        names=["a", "b"],
    )
    # unscaled i64 at scale 2
    assert run_expr(Col("a") + Col("b"), rb) == [200, 325, None]
    assert run_expr(Col("a") - Col("b"), rb) == [100, 125, None]


def test_decimal_mul_rescales():
    rb = pa.RecordBatch.from_arrays(
        [dec_col(["1.50"]), dec_col(["2.00"])], names=["a", "b"]
    )
    # 1.50 * 2.00 = 3.00 -> unscaled 300 at result scale 2
    assert run_expr(Col("a") * Col("b"), rb) == [300]


def test_decimal_div_is_float():
    rb = pa.RecordBatch.from_arrays(
        [dec_col(["3.00"]), dec_col(["2.00"])], names=["a", "b"]
    )
    out = run_expr(Col("a") / Col("b"), rb)
    np.testing.assert_allclose(out, [1.5])


def test_decimal_compare_and_unscaled_roundtrip():
    rb = pa.RecordBatch.from_arrays(
        [dec_col(["1.00", "2.50"]), dec_col(["1.00", "2.49"])],
        names=["a", "b"],
    )
    assert run_expr(Col("a") == Col("b"), rb) == [True, False]
    assert run_expr(Col("a") > Col("b"), rb) == [False, True]
    # spark ext fns: UnscaledValue then MakeDecimal round-trips
    e = ScalarFn(
        "spark_make_decimal",
        (ScalarFn("spark_unscaled_value", (Col("a"),)),),
    )
    assert run_expr(e, rb) == [100, 250]


def test_decimal_rescale_cast():
    rb = pa.RecordBatch.from_arrays(
        [dec_col(["1.25"])], names=["a"]
    )
    up = Col("a").cast(DataType.decimal(12, 4))
    assert run_expr(up, rb) == [12500]
    down = Col("a").cast(DataType.decimal(12, 1))
    assert run_expr(down, rb) == [12]  # truncation toward zero
    to_f = Col("a").cast(DataType.float64())
    np.testing.assert_allclose(run_expr(to_f, rb), [1.25])
    to_i = Col("a").cast(DataType.int64())
    assert run_expr(to_i, rb) == [1]


def test_timestamp_date_casts():
    rb = pa.RecordBatch.from_pydict(
        {
            "t": pa.array([86_400_000_000 + 3_600_000_000, 0]).cast(
                pa.timestamp("us")
            )
        }
    )
    # timestamp -> date truncates to days
    out = run_expr(Col("t").cast(DataType.date32()), rb)
    assert out[1] == 0
    # round-trip back to timestamp lands on midnight
    rt = run_expr(
        Col("t").cast(DataType.date32()).cast(DataType.timestamp_us()),
        rb,
    )
    assert rt == [86_400_000_000, 0]


def test_int_overflow_wraps_like_java():
    rb = pa.RecordBatch.from_pydict(
        {"a": pa.array([2**31 - 1], type=pa.int32())}
    )
    # int32 + int32 stays int32 in Spark (non-ANSI) and wraps:
    # (2^31-1) + (2^31-1) = 2^32 - 2 -> -2
    out = run_expr(
        Col("a").cast(DataType.int32())
        + Col("a").cast(DataType.int32()),
        rb,
    )
    assert out == [-2]
