"""Mesh stage anatomy (ISSUE 19, obs/meshprof.py): sub-phase spans
present and parent-pinned under `mesh_execute` in a
validate_chrome-clean trace, sub-phase p50s reconciling to the
measured stage wall, a chaos STALL at the `mesh.exchange` seam landing
in the RIGHT sub-phase (mesh_launch), obs-off adding zero dispatches
(armed/off budget parity), the warm-repeat retrace pin
(`blaze_mesh_retrace_total` delta 0 on a second execution of the same
lowered plan, >= 1 on a fresh lowering of the same logical plan), and
the `mesh-attr` CLI roundtrip in-process.

Runs under the repo conftest's forced 8-device virtual CPU mesh.
"""

import json
import tempfile

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import ColumnBatch
from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.obs import meshprof
from blaze_tpu.obs import trace as obs_trace
from blaze_tpu.obs.metrics import REGISTRY
from blaze_tpu.ops import (
    AggMode,
    ExecContext,
    HashAggregateExec,
    MemoryScanExec,
)
from blaze_tpu.parallel.mesh_ops import MeshGroupByExec
from blaze_tpu.planner.distribute import (
    insert_exchanges,
    lower_plan_to_mesh,
)
from blaze_tpu.runtime.executor import run_plan
from blaze_tpu.testing import chaos

STAGE_SUBPHASES = meshprof.STAGE_SUBPHASES


def scan(n_parts=4, rows=300, keys=13):
    parts, schema = [], None
    for p in range(n_parts):
        cb = ColumnBatch.from_arrow(pa.record_batch({
            "k": np.asarray(
                [(p * rows + i) % keys for i in range(rows)],
                dtype=np.int64,
            ),
            "v": np.asarray(
                [p * rows + i for i in range(rows)], dtype=np.int64
            ),
        }))
        schema = cb.schema
        parts.append([cb])
    return MemoryScanExec(parts, schema)


def sandwich(source=None, n=4):
    return insert_exchanges(
        HashAggregateExec(
            source or scan(),
            keys=[(Col("k"), "k")],
            aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
                  (AggExpr(AggFn.COUNT_STAR, None), "n")],
            mode=AggMode.COMPLETE,
        ),
        n, shuffle_dir=tempfile.mkdtemp(),
    )


def lowered_groupby():
    low = lower_plan_to_mesh(sandwich(), mode="on")
    assert isinstance(low, MeshGroupByExec)
    return low


# ---------------------------------------------------------------------------
# rollup unit behavior
# ---------------------------------------------------------------------------


def test_rollup_snapshot_and_bounds():
    r = meshprof.MeshStageRollup(max_ops=2, samples=4)
    for op in ("a", "b", "c"):  # LRU-bounded op classes
        for i in range(6):  # ring-bounded samples
            r.observe_stage(
                op, 1.0 + i,
                [("mesh_launch", 0.0, 0.5), ("mesh_sync", 0.5, 0.6)],
                nbytes=10,
            )
    snap = r.snapshot()
    assert "a" not in snap and set(snap) == {"b", "c"}
    assert snap["c"]["stages"] == 6
    assert snap["c"]["bytes_staged"] == 60
    assert snap["c"]["stage_wall"]["n"] == 4  # ring cap
    subs = snap["c"]["subphases"]
    assert subs["mesh_launch"]["p50"] == pytest.approx(0.5)
    assert subs["mesh_sync"]["p50"] == pytest.approx(0.1)
    # canonical sub-phase order in the snapshot
    assert list(subs) == ["mesh_launch", "mesh_sync"]


def test_stage_stopwatch_folds_and_replays_lower_window():
    with meshprof.capture() as rollup:
        st = meshprof.stage("op.x", 8, lower_window=(100.0, 100.25))
        with st.phase("mesh_launch"):
            pass
        st.finish()
        snap = rollup.snapshot()["op.x"]
    assert snap["subphases"]["mesh_lower"]["p50"] == pytest.approx(
        0.25
    )
    assert "mesh_launch" in snap["subphases"]
    # mesh_lower is plan-time: excluded from the stage wall
    assert snap["stage_wall"]["p50"] < 0.2


def test_note_trace_first_vs_retrace():
    with meshprof._tk_lock:
        meshprof._trace_keys.clear()
    t0 = REGISTRY.get("blaze_mesh_trace_total", op="op.y")
    r0 = REGISTRY.get("blaze_mesh_retrace_total", op="op.y")
    assert meshprof.note_trace("op.y", ("k", 1)) is False
    assert meshprof.note_trace("op.y", ("k", 2)) is False
    assert meshprof.note_trace("op.y", ("k", 1)) is True
    assert REGISTRY.get("blaze_mesh_trace_total", op="op.y") - t0 == 3
    assert (
        REGISTRY.get("blaze_mesh_retrace_total", op="op.y") - r0 == 1
    )


# ---------------------------------------------------------------------------
# the instrumented mesh stage
# ---------------------------------------------------------------------------


def test_subphase_spans_parent_pinned_and_chrome_clean():
    """Every stage sub-phase lands as a child span of `mesh_execute`
    on its own track, and the exported document stays
    validate_chrome-clean."""
    low = lowered_groupby()
    ctx = ExecContext()
    obs_trace.enable()
    try:
        rec = obs_trace.begin_trace("meshprof-spans")
        ctx.tracer = rec
        run_plan(low, ctx)
    finally:
        obs_trace.disable()
    rec.finish()
    names = [s.name for s in rec.spans]
    assert "mesh_execute" in names
    parent = next(s for s in rec.spans if s.name == "mesh_execute")
    by_name = {
        s.name: s for s in rec.spans
        if s.name in ("mesh_lower",) + STAGE_SUBPHASES
    }
    # every stage sub-phase (and the planner window) present...
    for sub in ("mesh_lower", "mesh_trace", "mesh_stage_in",
                "mesh_launch", "mesh_sync", "mesh_gather"):
        assert sub in by_name, f"missing sub-phase span {sub}"
        # ...pinned under mesh_execute on the sub-phase track
        assert by_name[sub].parent_id == parent.span_id
        assert by_name[sub].tid == meshprof.MESH_SUB_TID
    # the in-stage sub-phases are sequential, non-overlapping
    spans = sorted(
        (by_name[s] for s in STAGE_SUBPHASES),
        key=lambda s: s.start_ns,
    )
    for a, b in zip(spans, spans[1:]):
        assert a.end_ns <= b.start_ns
    doc = obs_trace.chrome_trace(rec)
    assert obs_trace.validate_chrome(doc) == []


def test_subphases_reconcile_to_stage_wall():
    """The named sub-phases must ACCOUNT for the stage: their sum
    covers >= 80% of the measured stage wall (the acceptance
    tolerance; anything less means an unnamed gap is hiding cost)."""
    low = lowered_groupby()
    with meshprof.capture() as rollup:
        run_plan(low)
        snap = rollup.snapshot()["mesh.groupby"]
    wall = snap["stage_wall"]["p50"]
    sub_sum = sum(
        snap["subphases"].get(n, {}).get("p50", 0.0)
        for n in STAGE_SUBPHASES
    )
    assert wall > 0
    assert sub_sum / wall >= 0.8, (
        f"sub-phases cover {sub_sum:.4f}s of {wall:.4f}s stage wall"
    )
    assert sub_sum <= wall * 1.05  # phases cannot exceed the wall
    assert snap["bytes_staged"] > 0


def test_chaos_stall_lands_in_mesh_launch():
    """An injected STALL at the `mesh.exchange` seam models exchange-
    fabric latency: it must show up in the mesh_launch sub-phase, not
    in staging or trace."""
    stall_s = 0.4
    low = lowered_groupby()
    run_plan(low)  # warm: the trace is paid before chaos arms
    low._result = None
    with meshprof.capture() as rollup:
        with chaos.active(
            [chaos.Fault(site="mesh.exchange", klass="STALL",
                         times=1, stall_s=stall_s)],
            seed=7,
        ):
            run_plan(low)
        snap = rollup.snapshot()["mesh.groupby"]
    subs = snap["subphases"]
    assert subs["mesh_launch"]["p50"] >= stall_s
    for other in ("mesh_stage_in", "mesh_trace"):
        assert subs[other]["p50"] < stall_s


def test_obs_armed_off_budget_parity():
    """The always-on stopwatch is pure host control flow, and span
    emission cannot dispatch either: a WARM mesh stage records a
    byte-identical dispatch-count delta whether tracing is off or
    armed (the absolute budget itself is pinned in
    test_dispatch_budget.py)."""
    from blaze_tpu.runtime import dispatch

    def mesh_counts(traced):
        low = lowered_groupby()
        run_plan(low)  # warm: compile outside the measured window
        low._result = None
        base = dispatch.snapshot()
        if traced:
            obs_trace.enable()
            try:
                ctx = ExecContext()
                ctx.tracer = obs_trace.begin_trace("parity")
                run_plan(low, ctx)
            finally:
                obs_trace.disable()
        else:
            run_plan(low)
        return {
            k: v - base.get(k, 0)
            for k, v in dispatch.snapshot().items()
            if v != base.get(k, 0)
        }

    off = mesh_counts(False)
    armed = mesh_counts(True)
    assert armed == off, (armed, off)
    assert off.get("mesh_dispatches") == 1


def test_warm_repeat_retrace_delta_zero():
    """Satellite pin: a second execution of the SAME lowered plan is
    trace-free (retrace AND trace deltas 0 - the compiled program is
    reused), while a FRESH lowering of the same logical plan re-traces
    and is counted as an avoidable re-trace (cache-key churn)."""
    low = lowered_groupby()
    run_plan(low)
    t0 = REGISTRY.get("blaze_mesh_trace_total", op="mesh.groupby")
    r0 = REGISTRY.get("blaze_mesh_retrace_total", op="mesh.groupby")
    low._result = None  # fresh execution, same lowered plan
    run_plan(low)
    assert REGISTRY.get(
        "blaze_mesh_trace_total", op="mesh.groupby"
    ) - t0 == 0
    assert REGISTRY.get(
        "blaze_mesh_retrace_total", op="mesh.groupby"
    ) - r0 == 0
    # fresh instance, same logical program: avoidable re-trace
    run_plan(lowered_groupby())
    assert REGISTRY.get(
        "blaze_mesh_retrace_total", op="mesh.groupby"
    ) - r0 >= 1


def test_metrics_exposition_carries_subphases():
    low = lowered_groupby()
    run_plan(low)
    text = REGISTRY.render_prometheus()
    assert "blaze_mesh_subphase_seconds_sum" in text
    assert 'subphase="mesh_launch"' in text
    assert "blaze_mesh_stage_wall_seconds_count" in text
    assert "blaze_mesh_trace_total" in text


def test_service_stats_meshprof_section(tmp_path):
    """Both-tiers surface: the service STATS payload carries the
    meshprof section (empty dict before any mesh stage)."""
    from blaze_tpu.service import QueryService

    svc = QueryService(enable_cache=False, enable_trace=False,
                       mesh_mode="off")
    try:
        out = svc.stats()
    finally:
        svc.close()
    assert out["meshprof"] == {}
    run_plan(lowered_groupby())
    svc = QueryService(enable_cache=False, enable_trace=False,
                       mesh_mode="off")
    try:
        out = svc.stats()
    finally:
        svc.close()
    assert "mesh.groupby" in out["meshprof"]
    assert "subphases" in out["meshprof"]["mesh.groupby"]


def test_phases_rollup_folds_mesh_subphases(tmp_path):
    """The obs/phases integration: a traced service query that ran a
    mesh stage folds the sub-phases into the per-phase rollup (the
    terminal hook's trace-driven sweep), under per-phase bands."""
    import pyarrow.parquet as pq

    from blaze_tpu.obs import phases as obs_phases
    from blaze_tpu.plan.serde import task_to_proto
    from blaze_tpu.service import QueryService

    rng = np.random.default_rng(3)
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({
        "k": rng.integers(0, 37, 16000).astype(np.int64),
        "v": rng.integers(0, 500, 16000).astype(np.int64),
    }), path)
    from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec

    blob = task_to_proto(
        HashAggregateExec(
            ParquetScanExec([[FileRange(path)]]),
            keys=[(Col("k"), "k")],
            aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
                  (AggExpr(AggFn.COUNT_STAR, None), "n")],
            mode=AggMode.COMPLETE,
        ),
        0,
    )
    obs_phases.ROLLUP._reset_for_tests()
    svc = QueryService(enable_cache=False, enable_trace=True,
                       mesh_mode="on")
    try:
        q = svc.submit_task(blob)
        svc.result(q.query_id, timeout=120)
    finally:
        svc.close()
    snap = obs_phases.ROLLUP.snapshot()
    assert "_all" in snap
    folded = set(snap["_all"])
    for sub in ("mesh_stage_in", "mesh_launch", "mesh_gather"):
        assert sub in folded, f"{sub} not folded into phases rollup"
    # and the sub-phases carry band wideners for compare()
    for sub in ("mesh_lower",) + STAGE_SUBPHASES:
        assert sub in obs_phases.PHASES
        assert sub in obs_phases.PHASE_BANDS


# ---------------------------------------------------------------------------
# the mesh-attr CLI (in-process roundtrip)
# ---------------------------------------------------------------------------


def test_attr_probe_and_doc_roundtrip(tmp_path):
    """CLI roundtrip without subprocesses: the probe at the CURRENT
    (8) device count reconciles, and build_doc attributes >= 80% of
    the (d8 - d1) gap to named sub-phases with a written verdict."""
    dn = meshprof.run_attr_probe(8, rows=40000, iters=2)
    assert dn["mesh_lowered"] is True
    rec = dn["reconcile"]
    assert rec["coverage"] >= 0.8
    assert dn["warm_retrace_delta"] == 0
    assert dn["retrace_total"] >= 1  # the fresh-lowering demo
    assert dn["bytes_staged"] > 0
    assert "mesh_groupby" in {"mesh_groupby": dn.get("lock")} or True
    # synthetic single-device side: the baseline the gap subtracts
    d1 = {
        "n_devices": 1, "rows": dn["rows"], "iters": 2,
        "mesh_lowered": False,
        "wall": {"median": 0.05, "spread": 0.1, "k": 2},
    }
    doc = meshprof.build_doc(d1, dn)
    assert doc["format"] == "blaze-meshattr-v1"
    gap = doc["gap"]
    assert gap["gap_s"] == pytest.approx(
        gap["d8_wall"] - gap["d1_wall"]
    )
    if gap["gap_s"] > 0:
        assert gap["attributed_frac"] >= 0.8
    assert "verdict" in doc and doc["verdict"]
    # the regress-snapshot shape regress --bench consumes
    snap = doc["phases"]["snapshot"]["_all"]
    assert "mesh_launch" in snap and "p50" in snap["mesh_launch"]
    # artifact roundtrips through json
    path = tmp_path / "MESHATTR_r01.json"
    path.write_text(json.dumps(doc))
    from blaze_tpu.obs.phases import phases_from_bench

    loaded = phases_from_bench(str(path))
    assert loaded is not None and "mesh_launch" in loaded["_all"]


def test_next_round_path(tmp_path):
    assert meshprof.next_round_path(str(tmp_path)).endswith(
        "MESHATTR_r01.json"
    )
    (tmp_path / "MESHATTR_r03.json").write_text("{}")
    assert meshprof.next_round_path(str(tmp_path)).endswith(
        "MESHATTR_r04.json"
    )
