"""Plan serde round-trip + executor + parquet scan tests."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu import ColumnBatch
from blaze_tpu.exprs import AggExpr, AggFn, Col, Literal, ScalarFn
from blaze_tpu.exprs.ir import CaseWhen, InList
from blaze_tpu.ops import (
    AggMode,
    ExecContext,
    FilterExec,
    HashAggregateExec,
    IpcReaderExec,
    IpcReadMode,
    LimitExec,
    MemoryScanExec,
    ProjectExec,
    SortExec,
    SortKey,
    SortMergeJoinExec,
    JoinType,
    UnionExec,
)
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.plan.serde import (
    expr_from_proto,
    expr_to_proto,
    plan_from_proto,
    plan_to_proto,
    task_from_proto,
    task_to_proto,
)
from blaze_tpu.runtime.executor import execute_task, run_plan
from blaze_tpu.types import DataType, Field, Schema


def test_expr_proto_roundtrip():
    exprs = [
        Col("x") + 1,
        (Col("x") > 3) & ~(Col("y") == "s"),
        Col("x").cast(DataType.float64()),
        Col("x").is_null(),
        InList(Col("x"), (Literal.infer(1), Literal.infer(2)), True),
        CaseWhen(((Col("x") > 0, Literal.infer(1)),), Literal.infer(0)),
        ScalarFn("sqrt", (Col("x"),)),
        AggExpr(AggFn.AVG, Col("x")),
        AggExpr(AggFn.COUNT_STAR, None),
        Literal(None, DataType.null()),
        Literal.infer(2**40),
    ]
    for e in exprs:
        rt = expr_from_proto(expr_to_proto(e))
        assert rt == e or repr(rt) == repr(e), e


def test_plan_proto_roundtrip_structure(tmp_path):
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"a": [1, 2, 3], "b": [1.0, 2.0, 3.0]}), path)
    plan = LimitExec(
        SortExec(
            ProjectExec(
                FilterExec(
                    ParquetScanExec([[FileRange(path)]]),
                    Col("a") > 1,
                ),
                [(Col("a"), "a"), (Col("b") * 2, "b2")],
            ),
            [SortKey(Col("a"), ascending=False)],
        ),
        10,
    )
    rt = plan_from_proto(plan_to_proto(plan))
    out = run_plan(rt)
    assert out.to_pydict() == {"a": [3, 2], "b2": [6.0, 4.0]}


def test_task_definition_executes():
    cb = ColumnBatch.from_pydict({"a": [5, 1, 7]})
    # memory scans can't serialize; use IpcReader as the serializable leaf
    from blaze_tpu.ops import collect_ipc

    ctx = ExecContext()
    parts = collect_ipc(MemoryScanExec.from_batches([cb]), ctx)
    reader = IpcReaderExec("src", cb.schema, 1, IpcReadMode.CHANNEL)
    plan = FilterExec(reader, Col("a") > 2)
    blob = task_to_proto(plan, 0, "t-42")
    ctx.resources["src"] = [parts]
    out = list(execute_task(blob, ctx))
    assert pa.Table.from_batches(out).to_pydict() == {"a": [5, 7]}


def test_parquet_scan_projection_and_pruning(tmp_path):
    path = str(tmp_path / "p.parquet")
    n = 10000
    tbl = pa.table(
        {
            "k": np.arange(n, dtype=np.int64),
            "v": np.arange(n, dtype=np.float64) * 0.5,
            "s": [f"s{i % 100}" for i in range(n)],
        }
    )
    pq.write_table(tbl, path, row_group_size=1000)
    scan = ParquetScanExec(
        [[FileRange(path)]], projection=["k", "v"],
        pruning_predicate=Col("k") > 8999,
    )
    ctx = ExecContext()
    rows = 0
    for b in scan.execute(0, ctx):
        rows += b.num_rows
        assert b.schema.names() == ("k", "v")
    # pruning keeps only the last of 10 row groups
    assert rows == 1000
    assert ctx.metrics.counters.get("input_rows", 0) == 1000


def test_parquet_multifile_partitions(tmp_path):
    paths = []
    for i in range(3):
        p = str(tmp_path / f"f{i}.parquet")
        pq.write_table(pa.table({"a": [i * 10 + j for j in range(5)]}), p)
        paths.append(p)
    scan = ParquetScanExec([[FileRange(p)] for p in paths])
    assert scan.partition_count == 3
    out = run_plan(scan)
    assert sorted(out.to_pydict()["a"]) == sorted(
        [i * 10 + j for i in range(3) for j in range(5)]
    )


def test_end_to_end_query_via_serde(tmp_path):
    """q6-shaped slice: scan -> filter -> project -> aggregate through the
    full proto boundary (SURVEY 7 step 4 'minimum end-to-end slice')."""
    path = str(tmp_path / "sales.parquet")
    n = 50000
    rng = np.random.default_rng(7)
    pq.write_table(
        pa.table(
            {
                "item": rng.integers(0, 1000, n),
                "price": rng.random(n) * 100,
                "qty": rng.integers(1, 10, n),
            }
        ),
        path,
        row_group_size=8192,
    )
    plan = HashAggregateExec(
        ProjectExec(
            FilterExec(
                ParquetScanExec([[FileRange(path)]]),
                Col("price") > 50.0,
            ),
            [(Col("item"), "item"),
             ((Col("price") * Col("qty").cast(DataType.float64())),
              "revenue")],
        ),
        keys=[],
        aggs=[
            (AggExpr(AggFn.SUM, Col("revenue")), "total"),
            (AggExpr(AggFn.COUNT_STAR, None), "rows"),
        ],
        mode=AggMode.COMPLETE,
    )
    rt = plan_from_proto(plan_to_proto(plan))
    out = run_plan(rt).to_pydict()
    # differential check vs pandas
    df = pq.read_table(path).to_pandas()
    df = df[df.price > 50.0]
    exp = float((df.price * df.qty).sum())
    np.testing.assert_allclose(out["total"][0], exp, rtol=1e-9)
    assert out["rows"][0] == len(df)


def test_error_wrapping():
    from blaze_tpu.runtime.executor import TaskExecutionError

    class Boom(MemoryScanExec):
        def execute(self, partition, ctx):
            raise ValueError("boom")
            yield

    op = Boom([[ColumnBatch.from_pydict({"a": [1]})]],
              ColumnBatch.from_pydict({"a": [1]}).schema)
    with pytest.raises(TaskExecutionError) as ei:
        run_plan(op)
    assert "boom" in repr(ei.value.__cause__)


def test_window_proto_roundtrip():
    from blaze_tpu.ops.sort import SortKey
    from blaze_tpu.ops.window import WindowExec, WindowFn

    cb = ColumnBatch.from_pydict(
        {"k": [1, 1, 2], "v": [3.0, 1.0, 2.0]}
    )
    from blaze_tpu.ops import IpcReaderExec, IpcReadMode, collect_ipc

    ctx = ExecContext()
    parts = collect_ipc(MemoryScanExec.from_batches([cb]), ctx)
    reader = IpcReaderExec("w", cb.schema, 1, IpcReadMode.CHANNEL)
    plan = WindowExec(
        reader,
        partition_by=[Col("k")],
        order_by=[SortKey(Col("v"))],
        functions=[WindowFn("row_number", None, "rn"),
                   WindowFn("sum", Col("v"), "sv")],
    )
    rt = plan_from_proto(plan_to_proto(plan))
    ctx.resources["w"] = [parts]
    out = pa.Table.from_batches(
        [b for b in __import__("blaze_tpu.runtime.executor",
                               fromlist=["execute_partition"])
         .execute_partition(rt, 0, ctx)]
    ).to_pydict()
    assert sorted(out["rn"]) == [1, 1, 2]
    got = dict(zip(zip(out["k"], out["rn"]), out["sv"]))
    assert got[(1, 1)] == 4.0 and got[(2, 1)] == 2.0
