"""Planner tier tests: convert strategy, per-node fallback, bridges."""

import numpy as np
import pandas as pd
import pyarrow.parquet as pq
import pytest

from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.planner import (
    AggSpec,
    ConvertStrategy,
    ExchangeSpec,
    FilterSpec,
    JoinSpec,
    LimitSpec,
    MemorySpec,
    ProjectSpec,
    ScanSpec,
    SortSpec,
    WindowSpec,
    convert_plan,
)
from blaze_tpu.planner.host_engine import HostFallbackExec
from blaze_tpu.runtime.executor import run_plan


def df_sales(n=1000, seed=5):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "k": rng.integers(0, 9, n),
            "v": rng.integers(0, 100, n),
            "p": np.round(rng.random(n) * 10, 3),
        }
    )


def test_native_pipeline_through_planner():
    df = df_sales()
    plan = AggSpec(
        children=[
            FilterSpec(
                children=[MemorySpec(dataframe=df, partitions=3)],
                predicate=Col("v") > 50,
            )
        ],
        keys=[(Col("k"), "k")],
        aggs=[(AggExpr(AggFn.SUM, Col("p")), "s")],
        mode="complete",
    )
    # grouped agg per partition would split groups; wrap in exchange first
    plan = AggSpec(
        children=[
            ExchangeSpec(
                children=[plan.children[0]], keys=[Col("k")],
                num_partitions=4,
            )
        ],
        keys=[(Col("k"), "k")],
        aggs=[(AggExpr(AggFn.SUM, Col("p")), "s")],
        mode="complete",
    )
    op = convert_plan(plan)
    assert not isinstance(op, HostFallbackExec)
    got = run_plan(op).to_pandas().sort_values("k").reset_index(drop=True)
    ref = (
        df[df.v > 50].groupby("k")["p"].sum().reset_index(name="s")
        .sort_values("k").reset_index(drop=True)
    )
    np.testing.assert_array_equal(got["k"], ref["k"])
    np.testing.assert_allclose(got["s"], ref["s"], rtol=1e-12)


def test_window_native_device():
    """Common window fns now run natively on device (beyond-reference
    capability); unsupported ones still fall back to the host engine."""
    df = df_sales(100)
    plan = WindowSpec(
        children=[MemorySpec(dataframe=df)],
        partition_by=["k"],
        order_by=["v"],
        function="row_number",
        output="rn",
    )
    op = convert_plan(plan)
    from blaze_tpu.ops.window import WindowExec

    assert isinstance(op, WindowExec)
    got = run_plan(op).to_pandas()
    assert "rn" in got.columns
    assert sorted(got[got.k == got.k.iloc[0]].rn)[0] == 1

    unsupported = WindowSpec(
        children=[MemorySpec(dataframe=df)],
        partition_by=["k"], order_by=["v"],
        function="ntile", output="n",
    )
    assert isinstance(convert_plan(unsupported), HostFallbackExec)


def test_native_above_host_window():
    """A native filter over a host-only window: the host subtree bridges
    back into device batches."""
    df = df_sales(200)
    plan = FilterSpec(
        children=[
            WindowSpec(
                children=[MemorySpec(dataframe=df)],
                partition_by=["k"], order_by=["v"],
                function="row_number", output="rn",
            )
        ],
        predicate=Col("rn") == 1,
    )
    op = convert_plan(
        plan, ConvertStrategy(enable_window=False)
    )
    from blaze_tpu.ops import FilterExec

    assert isinstance(op, FilterExec)
    assert isinstance(op.children[0], HostFallbackExec)
    got = run_plan(op).to_pandas()
    assert len(got) == df.k.nunique()


def test_disabled_gate_falls_back():
    df = df_sales(50)
    plan = SortSpec(
        children=[MemorySpec(dataframe=df)],
        keys=[(Col("v"), True, True)],
    )
    op = convert_plan(plan, ConvertStrategy(enable_sort=False))
    assert isinstance(op, HostFallbackExec)
    got = run_plan(op).to_pandas()
    assert got["v"].is_monotonic_increasing


def test_non_equi_join_host_fallback():
    l = pd.DataFrame({"a": [1, 2, 3]})
    r = pd.DataFrame({"b": [2, 3, 4]})
    plan = JoinSpec(
        children=[MemorySpec(dataframe=l), MemorySpec(dataframe=r)],
        kind="smj", left_keys=[], right_keys=[], join_type="inner",
    )
    op = convert_plan(plan)
    assert isinstance(op, HostFallbackExec)


def test_join_condition_becomes_native_filter():
    l = pd.DataFrame({"a": [1, 2, 2], "x": [10, 20, 30]})
    r = pd.DataFrame({"b": [1, 2], "y": [5, 25]})
    plan = JoinSpec(
        children=[MemorySpec(dataframe=l), MemorySpec(dataframe=r)],
        kind="smj", left_keys=["a"], right_keys=["b"],
        join_type="inner", condition=Col("x") > Col("y"),
    )
    op = convert_plan(plan)
    from blaze_tpu.ops import FilterExec, SortMergeJoinExec

    assert isinstance(op, FilterExec)
    assert isinstance(op.children[0], SortMergeJoinExec)
    got = run_plan(op).to_pandas()
    rows = set(map(tuple, got.values.tolist()))
    assert rows == {(1, 10, 1, 5), (2, 30, 2, 25)}


def test_parquet_scan_spec(tmp_path):
    import pyarrow as pa

    from blaze_tpu.ops.parquet_scan import FileRange

    path = str(tmp_path / "t.parquet")
    pq.write_table(
        pa.table({"a": list(range(100)), "b": [i * 2 for i in range(100)]}),
        path,
    )
    plan = ProjectSpec(
        children=[
            ScanSpec(
                file_groups=[[FileRange(path)]],
                projection=["a", "b"],
                predicate=Col("a") >= 95,
            )
        ],
        exprs=[(Col("b") + 1, "b1")],
    )
    op = convert_plan(plan)
    got = run_plan(op).to_pandas()
    assert sorted(got["b1"]) == [191, 193, 195, 197, 199]


def test_broadcast_exchange_spec():
    df = df_sales(60)
    plan = ExchangeSpec(
        children=[MemorySpec(dataframe=df, partitions=2)],
        mode="broadcast",
    )
    op = convert_plan(plan)
    from blaze_tpu.parallel import BroadcastExchangeExec

    assert isinstance(op, BroadcastExchangeExec)


def test_window_functions_host_tier():
    df = pd.DataFrame(
        {"k": [1, 1, 1, 2, 2], "v": [30, 10, 20, 5, 5]}
    )
    for fn, src, exp in [
        ("rank", None, [3, 1, 2, 1, 1]),
        ("dense_rank", None, [3, 1, 2, 1, 1]),
        ("lag", "v", [20.0, None, 10.0, None, 5.0]),
        ("sum", "v", [60, 60, 60, 10, 10]),
        ("avg", "v", [20.0, 20.0, 20.0, 5.0, 5.0]),
    ]:
        plan = WindowSpec(
            children=[MemorySpec(dataframe=df)],
            partition_by=["k"], order_by=["v"], function=fn,
            source=src, output="w",
        )
        # host path (order-preserving) vs pandas expectation
        host_op = convert_plan(
            plan, ConvertStrategy(enable_window=False)
        )
        got = run_plan(host_op).to_pandas()["w"].tolist()
        norm = [None if (isinstance(x, float) and x != x) else x
                for x in got]
        assert norm == exp, (fn, norm)
        # native device path emits (partition, order)-sorted rows;
        # compare as (k, v, w) multisets
        nat = run_plan(convert_plan(plan)).to_pandas()
        keyfn = lambda t: (t[0], t[1], t[2] is None, t[2] or 0.0)
        nat_rows = sorted(
            ((int(r.k), int(r.v),
              None if r.w != r.w else float(r.w))
             for r in nat.itertuples()),
            key=keyfn,
        )
        exp_rows = sorted(
            ((int(k), int(v), None if x is None else float(x))
             for k, v, x in zip(df.k, df.v, exp)),
            key=keyfn,
        )
        assert nat_rows == exp_rows, fn


def test_bhj_over_broadcast_exchange_no_duplication():
    """BHJ composed under a broadcast exchange must not multiply build
    rows by the partition count (SURVEY 3.4 composition)."""
    build_df = pd.DataFrame({"a": [1, 2], "x": [10, 20]})
    probe_df = pd.DataFrame({"b": [1, 1, 2, 3], "y": [1, 2, 3, 4]})
    plan = JoinSpec(
        children=[
            ExchangeSpec(
                children=[MemorySpec(dataframe=build_df, partitions=3)],
                mode="broadcast",
            ),
            MemorySpec(dataframe=probe_df, partitions=2),
        ],
        kind="bhj", left_keys=["a"], right_keys=["b"],
        join_type="inner",
    )
    op = convert_plan(plan)
    got = run_plan(op).to_pandas()
    assert len(got) == 3  # (1,1),(1,1 dup probe rows),(2,2): exactly 3
    assert sorted(got["y"].tolist()) == [1, 2, 3]


def test_skew_join_stays_host():
    l = pd.DataFrame({"a": [1, 2]})
    r = pd.DataFrame({"b": [1, 2]})
    plan = JoinSpec(
        children=[MemorySpec(dataframe=l), MemorySpec(dataframe=r)],
        kind="smj", left_keys=["a"], right_keys=["b"],
        join_type="inner", skewed=True,
    )
    assert isinstance(convert_plan(plan), HostFallbackExec)


# ---------------------------------------------------------------------------
# strategy heuristics (BlazeConvertStrategy.scala:159-265 analogs)
# ---------------------------------------------------------------------------

def _types_in(plan):
    out = []

    def walk(op):
        out.append(type(op).__name__)
        for c in op.children:
            walk(c)

    walk(plan)
    return out


def test_scan_feeding_inconvertible_parent_stays_host():
    """A convertible scan under a host-only parent is tagged host-side
    (no two-crossing native island; the reference rule,
    BlazeConvertStrategy.scala:223-233). The built tree is one host
    fallback covering agg AND scan either way - HostFallbackExec
    absorbs whole subtrees - so the rule shows in the tags."""
    def make_plan():
        return AggSpec(
            children=[MemorySpec(children=[], dataframe=df_sales())],
            keys=[(Col("k"), "k")],
            aggs=[(AggExpr(AggFn.SUM, Col("v")), "s")],
            mode="complete",
            strategy="never",  # force the agg host-side
        )

    plan = make_plan()
    built = convert_plan(plan, fuse=False)
    names = _types_in(built)
    assert "HostFallbackExec" in names
    assert "MemoryScanExec" not in names
    assert plan.children[0].convertible is False  # heuristic applied

    # with the heuristics off the scan keeps its native tag
    plan2 = make_plan()
    st = ConvertStrategy(enable_scan_parent_heuristic=False,
                         enable_agg_child_heuristic=False)
    convert_plan(plan2, strategy=st, fuse=False)
    assert plan2.children[0].convertible is True


def test_codegen_chain_heuristic_gated():
    """The continuous-chain decline mirrors the reference switch; it
    defaults OFF (fused pipelines amortize long chains here)."""
    df = df_sales()
    node = MemorySpec(children=[], dataframe=df)
    for i in range(6):
        node = ProjectSpec(
            children=[node],
            exprs=[(Col("k"), "k"), (Col("v") + i, "v")],
        )
    # default: everything native
    built = convert_plan(node, fuse=False)
    assert "HostFallbackExec" not in _types_in(built)
    # reference-faithful switch: chain >= threshold declines conversion
    st = ConvertStrategy(enable_codegen_chain_heuristic=True)
    built2 = convert_plan(node, strategy=st, fuse=False)
    assert "HostFallbackExec" in _types_in(built2)


def test_range_exchange_spec_converts():
    df = df_sales()
    plan = ExchangeSpec(
        children=[MemorySpec(children=[], dataframe=df)],
        keys=[Col("k")],
        num_partitions=3,
        mode="range",
    )
    built = convert_plan(plan, fuse=False)
    assert "ShuffleExchangeExec" in _types_in(built)
    tbl = run_plan(built)
    assert tbl.num_rows == len(df)
