"""Cancellation/failure races, deadline-aware admission, and client
reconnect (ISSUE 3 satellites).

Race coverage (made deterministic via chaos hooks):
  * cancel landing in the ADMITTED->RUNNING window
  * double-cancel idempotence
  * retry-then-cancel interleaving (cancel interrupts the backoff)
  * client disconnect during FETCH of a cached result
  * server-side drop mid-stream -> ServiceClient reconnect + re-attach

Plus the deadline satellites: EDF ordering within a priority class,
shedding of unmeetable deadlines at admission, and the
_sweep_deadlines fix (cancel-event propagation to running work).
"""

import sys
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.batch import ColumnBatch
from blaze_tpu.config import EngineConfig, set_config
from blaze_tpu.exprs import Col
from blaze_tpu.ops import FilterExec, MemoryScanExec
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.plan.serde import task_to_proto
from blaze_tpu.runtime.gateway import TaskGatewayServer
from blaze_tpu.service import (
    QueryCancelled,
    QueryService,
    QueryState,
    ServiceClient,
)
from blaze_tpu.testing import chaos
from blaze_tpu.testing.chaos import Fault
from tests.test_service import GatedScan, wait_for


def small_plan(rows=6):
    cb = ColumnBatch.from_pydict({"a": list(range(rows))})
    return FilterExec(
        MemoryScanExec([[cb]], cb.schema), Col("a") >= 0
    )


# ---------------------------------------------------------------------------
# cancellation races
# ---------------------------------------------------------------------------


def test_cancel_during_admitted_to_running_window():
    """The chaos STALL in _run_query holds the query between ADMITTED
    and RUNNING; a cancel landing there must win - the query ends
    CANCELLED and the operator tree never starts executing."""
    release = threading.Event()
    scan = GatedScan(release)
    try:
        with chaos.active(
            [Fault("service.admit", klass="STALL", stall_s=0.4,
                   times=1)],
            seed=7,
        ):
            with QueryService(
                max_concurrency=1, enable_cache=False
            ) as svc:
                q = svc.submit_plan(scan, estimated_bytes=0)
                assert wait_for(
                    lambda: q.state is QueryState.ADMITTED
                )
                svc.cancel(q.query_id)
                assert wait_for(
                    lambda: q.state is QueryState.CANCELLED
                )
                assert not scan.started.is_set()
    finally:
        release.set()


def test_double_cancel_idempotent():
    release = threading.Event()
    scan = GatedScan(release)
    try:
        with QueryService(max_concurrency=1, enable_cache=False) as svc:
            q = svc.submit_plan(scan, estimated_bytes=0)
            assert wait_for(lambda: scan.started.is_set())
            st1 = svc.cancel(q.query_id)
            st2 = svc.cancel(q.query_id)  # second cancel: no-op
            assert wait_for(lambda: q.state is QueryState.CANCELLED)
            st3 = svc.cancel(q.query_id)  # cancel AFTER terminal: no-op
            assert st3["state"] == "CANCELLED"
            assert "error" not in st3 or "illegal" not in st3["error"]
            with pytest.raises(QueryCancelled):
                svc.result(q.query_id, timeout=5)
            del st1, st2
    finally:
        release.set()


def test_retry_then_cancel_interleaving():
    """Cancel arriving while a TRANSIENT retry backs off must end the
    query promptly (the backoff wait is cancel-interruptible), not
    after the remaining retry budget drains."""
    with chaos.active(
        [Fault("task.execute", klass="TRANSIENT", times=0)],
        seed=7,
    ):
        with QueryService(
            max_concurrency=1, enable_cache=False,
            max_task_attempts=50, retry_backoff_s=0.4,
        ) as svc:
            q = svc.submit_plan(small_plan())
            # wait until at least one failed attempt is journaled
            assert wait_for(lambda: len(q.attempts) >= 1)
            t0 = time.monotonic()
            svc.cancel(q.query_id)
            assert wait_for(lambda: q.state is QueryState.CANCELLED)
            assert time.monotonic() - t0 < 5.0
            # nowhere near the 50-attempt budget
            assert len(q.attempts) < 10


def test_cancel_vs_completion_race_clean():
    """Cancel racing natural completion must land in exactly one
    terminal state, never raise, never wedge the service."""
    for _ in range(20):
        with QueryService(max_concurrency=2, enable_cache=False) as svc:
            q = svc.submit_plan(small_plan())
            svc.cancel(q.query_id)
            assert wait_for(lambda: q.done)
            assert q.state in (
                QueryState.DONE, QueryState.CANCELLED
            )


# ---------------------------------------------------------------------------
# deadline satellites
# ---------------------------------------------------------------------------


def test_sweep_propagates_cancel_to_running_query():
    """ISSUE 3 satellite bugfix: the deadline sweep marking a RUNNING
    query TIMED_OUT must ALSO fire its cancel event. Deterministic pin:
    the deadline expires while the query sits in a LONG retry backoff
    (which only the cancel event can interrupt) - without the
    propagation the query would not terminate until the multi-second
    backoff drained."""
    with chaos.active(
        [Fault("task.execute", klass="TRANSIENT", times=0)],
        seed=7,
    ):
        with QueryService(
            max_concurrency=1, enable_cache=False,
            max_task_attempts=10, retry_backoff_s=8.0,
        ) as svc:
            q = svc.submit_plan(small_plan(), deadline_s=0.2)
            t0 = time.monotonic()
            assert wait_for(
                lambda: q.state is QueryState.TIMED_OUT, timeout=10
            )
            # the sweep fired the event (backoff_delay(0, 8.0) >= 4s;
            # terminating well under that proves the interrupt)
            assert q.cancel_requested
            assert time.monotonic() - t0 < 3.0


def test_user_cancel_wins_over_concurrent_deadline():
    """A user cancel that precedes QueryCancelled propagation must
    report CANCELLED even when the deadline elapses in the same
    window (the sweep fires the same event for deadline expiry, so
    the terminal state keys on the cancel REASON, not timing)."""
    release = threading.Event()
    scan = GatedScan(release)
    try:
        with QueryService(max_concurrency=1, enable_cache=False) as svc:
            q = svc.submit_plan(
                scan, deadline_s=0.25, estimated_bytes=0
            )
            assert wait_for(lambda: scan.started.is_set())
            svc.cancel(q.query_id)  # user intent, pre-deadline
            time.sleep(0.3)  # deadline passes while unwinding
            assert wait_for(lambda: q.done)
            assert q.state is QueryState.CANCELLED
    finally:
        release.set()


def test_edf_ordering_within_priority_class():
    """Deadline-aware admission (ROADMAP first half): within one
    priority class the queued query with the nearest deadline admits
    first; deadline-less queries go last, FIFO among themselves."""
    release = threading.Event()
    blocker = GatedScan(release)
    try:
        with QueryService(max_concurrency=1, enable_cache=False) as svc:
            qb = svc.submit_plan(blocker, estimated_bytes=0)
            assert wait_for(lambda: blocker.started.is_set())
            q_loose = svc.submit_plan(
                small_plan(), deadline_s=30.0, estimated_bytes=0
            )
            q_tight = svc.submit_plan(
                small_plan(), deadline_s=5.0, estimated_bytes=0
            )
            q_none1 = svc.submit_plan(small_plan(), estimated_bytes=0)
            q_none2 = svc.submit_plan(small_plan(), estimated_bytes=0)
            q_hi = svc.submit_plan(
                small_plan(), priority=5, deadline_s=60.0,
                estimated_bytes=0,
            )
            release.set()
            for q in (q_loose, q_tight, q_none1, q_none2, q_hi):
                svc.result(q.query_id, timeout=60)
            assert svc.admission_log == [
                qb.query_id,
                q_hi.query_id,     # priority class first, even with
                                   # the loosest deadline
                q_tight.query_id,  # then EDF within class 0
                q_loose.query_id,
                q_none1.query_id,  # deadline-less last, FIFO
                q_none2.query_id,
            ]
    finally:
        release.set()


def test_unmeetable_deadline_shed_at_admission():
    with QueryService(max_concurrency=1, enable_cache=False) as svc:
        q = svc.submit_plan(
            small_plan(), deadline_s=-0.5, estimated_bytes=0
        )
        assert q.state is QueryState.TIMED_OUT
        assert "shed" in q.error
        st = svc.admission.stats()
        assert st["shed_deadline"] == 1
        assert st["queued"] == 0  # never occupied queue depth
        with pytest.raises(RuntimeError, match="TIMED_OUT"):
            svc.result(q.query_id, timeout=5)


# ---------------------------------------------------------------------------
# wire: disconnects and reconnect-with-backoff
# ---------------------------------------------------------------------------


@pytest.fixture
def parquet_blob(tmp_path):
    # small batches -> multi-part FETCH streams (mid-stream coverage)
    set_config(EngineConfig(batch_size=512))
    rng = np.random.default_rng(13)
    p = str(tmp_path / "t.parquet")
    pq.write_table(
        pa.table({
            "k": pa.array(rng.integers(0, 50, 4000), pa.int32()),
            "v": pa.array(rng.random(4000), pa.float64()),
        }),
        p,
    )
    plan = FilterExec(
        ParquetScanExec([[FileRange(p)]]), Col("v") >= 0.0
    )
    yield task_to_proto(plan, 0)
    set_config(EngineConfig())


def test_client_disconnect_during_fetch_of_cached_result(
    parquet_blob,
):
    """A client vanishing mid-FETCH of a cached result must not poison
    the service, the cache entry, or the listener."""
    with QueryService(max_concurrency=2) as svc:
        with TaskGatewayServer(service=svc) as srv:
            with ServiceClient(*srv.address) as c1:
                full = c1.run(parquet_blob)
            assert len(full) > 1  # multi-part stream
            assert svc.cache.stats()["puts"] == 1
            # second submission hits the cache; disconnect mid-stream
            c2 = ServiceClient(*srv.address)
            st = c2.submit(parquet_blob)
            it = c2.fetch_stream(st["query_id"])
            first = next(it)
            assert first.num_rows > 0
            c2.close()  # wire-level abandon, stream unfinished
            time.sleep(0.1)
            # service + cache healthy: a third client gets everything
            with ServiceClient(*srv.address) as c3:
                again = c3.run(parquet_blob)
            assert svc.cache.stats()["hits"] >= 1
    t_full = pa.Table.from_batches(full).to_pydict()
    t_again = pa.Table.from_batches(again).to_pydict()
    assert t_full == t_again


def test_server_drop_midstream_reconnect_refetch(parquet_blob):
    """ISSUE 3 satellite: a server-side connection drop mid-FETCH is
    healed by ServiceClient's reconnect-with-backoff - it re-attaches
    by query_id, re-issues FETCH, skips already-delivered parts, and
    the assembled result has no gaps and no duplicates. detach=True:
    with the streaming FETCH path parts ship while the query is still
    RUNNING, so the drop now lands mid-execution - an ATTACHED query
    would be cancelled by the server's session teardown (by design:
    cancel-on-disconnect protects admission slots), and re-attach
    across connection loss is exactly what detach is for (the router
    submits downstream with detach=True for the same reason)."""
    with QueryService(max_concurrency=2, enable_cache=False) as svc:
        with TaskGatewayServer(service=svc) as srv:
            with ServiceClient(*srv.address) as c:
                baseline = c.run(parquet_blob)
            assert len(baseline) > 2
            with chaos.active(
                [Fault("gateway.stream", klass="DROP",
                       partition=2, times=1)],
                seed=7,
            ) as plan:
                with ServiceClient(*srv.address) as c2:
                    st = c2.submit(parquet_blob, detach=True)
                    got = list(c2.fetch_stream(st["query_id"]))
                assert plan.fired("gateway.stream") == 1
    tb = pa.Table.from_batches(baseline).to_pydict()
    tg = pa.Table.from_batches(got).to_pydict()
    assert tb == tg


def test_poll_survives_connection_drop(parquet_blob):
    """Reconnect re-attaches in-flight query HANDLES: a poll after the
    socket died transparently reconnects (query ids are global; the
    detach flag keeps the server's session teardown off the query)."""
    with QueryService(max_concurrency=2, enable_cache=False) as svc:
        with TaskGatewayServer(service=svc) as srv:
            with ServiceClient(*srv.address) as c:
                st = c.submit(parquet_blob, detach=True)
                qid = st["query_id"]
                # simulate a dropped connection under the client
                c._sock.close()
                final = None
                for _ in range(100):
                    final = c.poll(qid)
                    if final["state"] not in (
                        "QUEUED", "ADMITTED", "RUNNING"
                    ):
                        break
                    time.sleep(0.05)
                assert final["state"] == "DONE"
                got = c.fetch(qid)
    assert sum(rb.num_rows for rb in got) == 4000


def test_error_class_and_attempts_on_the_wire(parquet_blob):
    """The wire protocol carries the failure taxonomy: error_class and
    the attempt journal ride the status JSON."""
    with chaos.active(
        [Fault("task.execute", klass="PLAN_INVALID", times=0)],
        seed=7,
    ):
        with QueryService(max_concurrency=1, enable_cache=False) as svc:
            with TaskGatewayServer(service=svc) as srv:
                with ServiceClient(*srv.address) as c:
                    st = c.submit(parquet_blob)
                    qid = st["query_id"]
                    final = None
                    for _ in range(100):
                        final = c.poll(qid)
                        if final["state"] == "FAILED":
                            break
                        time.sleep(0.05)
                    assert final["state"] == "FAILED"
                    assert final["error_class"] == "PLAN_INVALID"
                    assert final["attempts"][0]["action"] == "fail"
                    report = c.report(qid)
    assert "error_class=PLAN_INVALID" in report
    assert "PLAN_INVALID -> fail" in report


class PartitionGatedScan(MemoryScanExec):
    """MemoryScanExec whose chosen partitions block on an Event until
    the test releases them: event-gated ordering, no wall-clock
    races. `gates[p] = (started, release)`."""

    def __init__(self, parts, schema, gates):
        super().__init__(parts, schema)
        self.gates = gates

    def execute(self, partition, ctx):
        g = self.gates.get(partition)
        if g is not None:
            g[0].set()
            assert g[1].wait(30), f"partition {partition} gate leaked"
        yield from super().execute(partition, ctx)


def test_degraded_query_releases_bytes_unblocks_waiter():
    """ISSUE 5 satellite (degradation-aware admission): a partition
    that degrades to the HOST engine releases its SHARE of the
    device-byte reservation (ceil(800/3) = 267 here - the other
    partitions still run on the device against the rest), so a
    headroom-waiting query admits while the degraded one is still
    running - without the release, 800 + 400 > 1000 would hold the
    waiter until the degraded query finished; with it,
    533 + 400 <= 1000 admits. Every ordering point is event-gated
    (p0 and p2 block on explicit gates), never wall-clock."""
    from blaze_tpu.runtime.memory import DeviceMemoryTracker

    def gated(n_parts, gates, rows=40):
        parts, schema = [], None
        for p in range(n_parts):
            cb = ColumnBatch.from_pydict(
                {"a": list(range(p * rows, (p + 1) * rows))}
            )
            schema = cb.schema
            parts.append([cb])
        return PartitionGatedScan(parts, schema, gates)

    g0 = (threading.Event(), threading.Event())
    g2 = (threading.Event(), threading.Event())
    tracker = DeviceMemoryTracker(budget=1000)
    try:
        with chaos.active(
            # p1: degrade -> release_bytes frees its 267-byte share
            [Fault("task.execute", klass="RESOURCE_EXHAUSTED",
                   partition=1, times=1)],
            seed=7,
        ):
            with QueryService(
                max_concurrency=4, enable_cache=False,
                device_tracker=tracker,
            ) as svc:
                qa = svc.submit_plan(
                    gated(3, {0: g0, 2: g2}), estimated_bytes=800
                )
                # p0 holds the full reservation until released
                assert wait_for(lambda: g0[0].is_set())
                qb = svc.submit_plan(small_plan(),
                                     estimated_bytes=400)
                # over headroom while qa holds 800: qb WAITS
                assert wait_for(
                    lambda: svc.admission.stats()["headroom_waits"]
                    >= 1
                )
                assert qb.state is QueryState.QUEUED
                g0[1].set()
                # qa's p1 degrades -> its share (267) frees -> qb
                # admits and finishes while qa sits gated at p2 ON
                # THE DEVICE against the remaining 533-byte
                # reservation
                svc.result(qb.query_id, timeout=30)
                assert wait_for(lambda: g2[0].is_set())
                assert qa.state is QueryState.RUNNING
                assert (
                    svc.admission.stats()["degraded_released"] == 1
                )
                assert svc.admission.stats()["reserved_bytes"] == 533
                g2[1].set()
                svc.result(qa.query_id, timeout=60)
                assert qa.degraded
                assert qa.state is QueryState.DONE
    finally:
        g0[1].set()
        g2[1].set()


# ---------------------------------------------------------------------------
# orphan reaping (ISSUE 11 satellite): detached queries a dead router
# abandoned must not pin replica retention forever
# ---------------------------------------------------------------------------


def test_orphan_sweep_reaps_terminal_never_fetched_queries():
    """A terminal query nobody ever fetched or polled past the
    orphan TTL - the replica-side footprint of a router that died and
    never came back - is reaped: removed from retention, counted on
    `orphans_reaped`."""
    with QueryService(max_concurrency=1, orphan_ttl_s=0.3) as svc:
        q = svc.submit_plan(small_plan())
        assert q.wait(30) and q.state is QueryState.DONE
        qid = q.query_id
        # nobody polls, nobody fetches: the dead-router scenario

        def reaped():
            try:
                svc.get(qid)
                return False
            except KeyError:
                return True

        assert wait_for(reaped, timeout=10)
        assert svc.stats()["queries"]["orphans_reaped"] == 1
        assert svc.stats()["service"]["orphan_ttl_s"] == 0.3


def test_poll_activity_defers_orphan_sweep():
    """An attentive owner (a live router POLLs on the client's
    behalf) keeps the query out of the sweep indefinitely; reaping
    begins only once the polls stop."""
    with QueryService(max_concurrency=1, orphan_ttl_s=0.4) as svc:
        q = svc.submit_plan(small_plan())
        assert q.wait(30) and q.state is QueryState.DONE
        qid = q.query_id
        deadline = time.monotonic() + 1.2
        while time.monotonic() < deadline:
            assert svc.poll(qid)["state"] == "DONE"  # still owned
            time.sleep(0.05)

        def reaped():
            try:
                svc.get(qid)
                return False
            except KeyError:
                return True

        assert wait_for(reaped, timeout=10)  # polls stopped -> reaped
        assert svc.stats()["queries"]["orphans_reaped"] == 1


def test_fetch_of_reaped_query_is_classified_not_found(parquet_blob):
    """Regression (ISSUE 11 satellite): a FETCH of a reaped query
    answers the classified UNKNOWN not-found error frame, never a
    hang - the late-returning router (or a confused client) gets a
    clean terminal answer."""
    svc = QueryService(max_concurrency=1, orphan_ttl_s=0.3)
    srv = TaskGatewayServer(service=svc).start()
    try:
        with ServiceClient(*srv.address) as c:
            st = c.submit(parquet_blob, detach=True)
            qid = st["query_id"]
            assert wait_for(
                lambda: c.poll(qid)["state"] == "DONE", timeout=30
            )

            def reaped():
                try:
                    svc.get(qid)
                    return False
                except KeyError:
                    return True

            assert wait_for(reaped, timeout=10)
            t0 = time.monotonic()
            with pytest.raises(Exception) as ei:
                c.fetch(qid)
            assert time.monotonic() - t0 < 5.0  # answered, not hung
            assert "UNKNOWN" in str(ei.value)
            # a fetched-before-TTL sibling is NOT reaped: collection
            # is what the sweep exists to preserve
            st2 = c.submit(parquet_blob, detach=True)
            assert c.fetch(st2["query_id"])
            time.sleep(0.8)
            assert svc.poll(st2["query_id"])["state"] == "DONE"
    finally:
        srv.stop()
        svc.close()


def test_fetch_guard_counter_survives_concurrent_fetches():
    """Review regression: `fetchers` is the in-progress-fetch guard
    the orphan sweep consults before reaping; its updates are
    read-modify-writes and MUST be locked - two concurrent FETCHes
    interleaving an unlocked `+= 1` can lose an increment, letting
    the sweep reap a query mid-collection."""
    from blaze_tpu.service.query import Query

    q = Query(task_bytes=b"x")
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-6)  # make lost updates likely if racy
    try:
        def hammer():
            for _ in range(20_000):
                q.begin_fetch()
                q.end_fetch()

        ts = [threading.Thread(target=hammer) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    finally:
        sys.setswitchinterval(old)
    assert q.fetchers == 0


def test_result_cache_refuses_partial_entries():
    """ISSUE 14 satellite: with incremental delivery, parts leave the
    building while execution is still running - the ResultCache must
    finalize an entry only after the LAST part was produced. A
    partial put is refused and counted; a probe of the key stays a
    clean miss, never a truncated prefix."""
    from blaze_tpu.service import ResultCache

    rc = ResultCache(max_bytes=1 << 20, ttl_s=60.0)
    try:
        rbs = [
            pa.record_batch([pa.array([1, 2, 3])], names=["a"]),
            pa.record_batch([pa.array([4, 5, 6])], names=["a"]),
        ]
        key = ("fp-stream", 0)
        assert rc.put(key, rbs[:1], complete=False) is False
        assert rc.counters["partial_puts_refused"] == 1
        assert rc.get(key) is None  # miss, not a 1-of-2 prefix
        assert rc.put(key, rbs, complete=True) is True
        assert len(rc.get(key)) == 2
    finally:
        rc.close()


def test_cache_probe_mid_stream_misses_then_hits(parquet_blob):
    """Integration half of the same satellite: while a query's parts
    are mid-flight the cache has no entry for its fingerprint (a
    concurrent identical submit coalesces on the leader instead);
    after the stream's last part the entry appears complete."""
    with QueryService(max_concurrency=2) as svc:
        with TaskGatewayServer(service=svc) as srv:
            with ServiceClient(*srv.address) as c:
                st = c.submit(parquet_blob, detach=True)
                qid = st["query_id"]
                it = c.fetch_stream(qid)
                first = next(it)  # stream opened, parts in flight
                assert first.num_rows > 0
                q = svc.get(qid)
                if not q.done:
                    # mid-stream probe: nothing cached yet for an
                    # in-progress partition set
                    assert svc.cache.stats()["entries"] == 0
                rest = list(it)
            assert wait_for(
                lambda: svc.cache.stats()["entries"] > 0
            )
            with ServiceClient(*srv.address) as c2:
                again = c2.run(parquet_blob)
    t1 = pa.Table.from_batches([first] + rest)
    t2 = pa.Table.from_batches(again)
    assert t1.equals(t2)
    assert svc.cache.counters["hits"] >= 1


# ---------------------------------------------------------------------------
# tenant-budget rejection surfacing (ISSUE 18 satellite 3)
# ---------------------------------------------------------------------------


def test_tenant_budget_rejection_classified_on_wire(parquet_blob):
    """A budget rejection mirrors the DRAINING contract: TRANSIENT on
    the wire, retried inside the client's reconnect/backoff budget,
    then surfaced as a classified TenantBudgetError - never a bare
    ServiceError, never a breaker-style failure."""
    from blaze_tpu.errors import (
        ErrorClass,
        TenantBudgetError,
        TransientError,
        classify,
    )

    with QueryService(
        max_concurrency=2,
        tenant_config={"capped": {"max_queued": 0}},
    ) as svc:
        with TaskGatewayServer(service=svc) as srv:
            with ServiceClient(*srv.address, tenant="capped",
                               reconnect_attempts=1,
                               reconnect_backoff_s=0.01) as c:
                with pytest.raises(TenantBudgetError) as ei:
                    c.submit(parquet_blob)
    assert issubclass(TenantBudgetError, TransientError)
    assert classify(ei.value) is ErrorClass.TRANSIENT
    assert "REJECTED_TENANT_BUDGET" in str(ei.value)
    # the raw rejection stayed in the routing table as a terminal
    # REJECTED_OVERLOADED (the DRAINING shape - spillable upstream)
    assert svc.admission.counters["rejected_tenant_budget"] > 0


def test_tenant_budget_retry_honors_backoff_budget(parquet_blob):
    """The retry loop is the existing bounded reconnect budget, not a
    new unbounded spin: the number of raw submits the service sees is
    reconnect_attempts + 1."""
    with QueryService(
        max_concurrency=2,
        tenant_config={"capped": {"max_queued": 0}},
    ) as svc:
        with TaskGatewayServer(service=svc) as srv:
            from blaze_tpu.errors import TenantBudgetError

            with ServiceClient(*srv.address, tenant="capped",
                               reconnect_attempts=2,
                               reconnect_backoff_s=0.01) as c:
                with pytest.raises(TenantBudgetError):
                    c.submit(parquet_blob)
            ts = svc.stats()["tenants"]
            assert ts["capped"]["submitted"] == 3  # 1 + 2 retries
            # other tenants' admission was never touched
            assert set(ts) == {"capped"}
