"""Elastic fleet membership tests (ISSUE 9): JOIN/LEAVE protocol,
graceful drain, hot-result replication, and membership churn chaos.

Coverage map:
  * wire tier: the MEMBER verb end to end (join ack, leave ack, a
    bare serve instance refusing membership authority), announcer
    retry across a chaos-dropped JOIN (`router.membership` seam)
  * registry tier: dynamic add/remove spinning pollers up/down, the
    membership state ladder (joining/alive/draining/quarantined/gone)
    on STATS and the `blaze_router_replica_membership` gauge, the
    `blaze_router_membership_events{kind}` counter
  * drain: QueryService.drain finishes in-flight work while refusing
    new SUBMITs with the classified DRAINING rejection; the router
    treats that rejection as a placement miss (spill, zero breaker
    strikes); a bare ServiceClient retries it with backoff and
    surfaces TRANSIENT (`ReplicaDrainingError`)
  * departure: LEAVE (and heartbeat death) eagerly evicts the
    departed replica's AffinityMap entries; flapping join/leave
    neither thrashes other replicas' affinity nor leaks poller
    threads
  * replication: the hot ranking from polled runtime-history data,
    tick() double-placing the top-K, and promotion of the confirmed
    secondary to affinity home on death - the repeat serves warm
    (0 dispatches) from the survivor.
"""

import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.errors import ErrorClass, ReplicaDrainingError, classify
from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.obs.metrics import REGISTRY
from blaze_tpu.ops import AggMode, FilterExec, HashAggregateExec
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.plan.serde import task_to_proto
from blaze_tpu.router import (
    MembershipAnnouncer,
    Router,
    RouterServer,
)
from blaze_tpu.runtime.gateway import TaskGatewayServer
from blaze_tpu.service import QueryService, ServiceClient
from blaze_tpu.service.wire import _is_draining_rejection
from blaze_tpu.testing import chaos
from blaze_tpu.testing.chaos import Fault
from tests.test_router import Fleet, wait_done
from tests.test_service import GatedScan, wait_for


@pytest.fixture
def dataset(tmp_path):
    rng = np.random.default_rng(41)
    p = str(tmp_path / "m.parquet")
    pq.write_table(
        pa.table(
            {
                "k": pa.array(rng.integers(0, 25, 5000), pa.int32()),
                "v": pa.array(rng.random(5000), pa.float64()),
            }
        ),
        p,
    )

    def blob(threshold=0.5):
        plan = HashAggregateExec(
            FilterExec(
                ParquetScanExec([[FileRange(p)]]),
                Col("v") > threshold,
            ),
            keys=[(Col("k"), "k")],
            aggs=[(AggExpr(AggFn.SUM, Col("v")), "s")],
            mode=AggMode.COMPLETE,
        )
        return task_to_proto(plan, 0)

    return blob


def _join(router, spec):
    host, _, port = spec.rpartition(":")
    return router.membership(
        {"op": "join", "host": host, "port": int(port)}
    )


def _leave(router, spec, reason="leave"):
    host, _, port = spec.rpartition(":")
    return router.membership(
        {"op": "leave", "host": host, "port": int(port),
         "reason": reason}
    )


# ---------------------------------------------------------------------------
# JOIN/LEAVE protocol
# ---------------------------------------------------------------------------


def test_join_from_empty_bootstrap_and_leave(dataset):
    """The --replica list is only a bootstrap hint: a router started
    EMPTY serves traffic as soon as replicas JOIN, and a LEAVE retires
    one (state=gone on STATS) without a restart."""
    router = Router([], poll_interval_s=0.1,
                    heartbeat_timeout_s=0.8, start=False)
    svcs, srvs, specs = [], [], []
    try:
        for _ in range(2):
            svc = QueryService(max_concurrency=2)
            srv = TaskGatewayServer(service=svc).start()
            svcs.append(svc)
            srvs.append(srv)
            specs.append("%s:%d" % srv.address)
            resp = _join(router, specs[-1])
            assert resp["ok"] and resp["created"]
            # the JOIN ack already implies routability (sync probe)
            assert resp["state"] == "alive"
        assert len(router.registry.routable()) == 2
        st = router.submit({"use_cache": True}, dataset())
        p = wait_done(router, st["query_id"])
        assert p["state"] == "DONE"
        # idempotent re-JOIN (the announcer re-announces forever)
        resp = _join(router, specs[0])
        assert resp["ok"] and not resp["created"]
        assert len(router.registry.replicas) == 2
        # LEAVE retires the replica and the fleet keeps serving
        gone = p["replica"]
        resp = _leave(router, gone, reason="drained")
        assert resp["ok"] and resp["known"]
        assert len(router.registry.routable()) == 1
        snap = router.registry.snapshot()
        assert snap[gone]["state"] == "gone"
        st2 = router.submit({"use_cache": True}, dataset())
        p2 = wait_done(router, st2["query_id"])
        assert p2["state"] == "DONE" and p2["replica"] != gone
        # LEAVE of an unknown replica acks (desired state holds)
        assert router.membership(
            {"op": "leave", "host": "h", "port": 1}
        )["ok"]
        assert "error" in router.membership(
            {"op": "flap", "host": "h", "port": 1}
        )
    finally:
        router.close()
        for srv in srvs:
            srv.stop()
        for svc in svcs:
            svc.close()


def test_member_verb_over_wire_and_announcer(dataset):
    """The MEMBER verb end to end: an announcer JOINs through the
    router's listener; a bare serve instance refuses membership
    authority in-band."""
    with Fleet() as fl:
        with RouterServer(fl.router) as rs:
            svc = QueryService(max_concurrency=1)
            srv = TaskGatewayServer(service=svc).start()
            try:
                spec = "%s:%d" % srv.address
                ann = MembershipAnnouncer(
                    "%s:%d" % rs.address, spec, interval_s=30.0,
                )
                assert ann.announce_now()
                assert ann.joins_acked == 1
                assert spec in fl.router.registry.replicas
                assert ann.leave()
                assert spec not in fl.router.registry.replicas
                ann.close()
                # a serve instance is NOT a membership authority
                with ServiceClient(*srv.address) as c:
                    resp = c.member({"op": "join", "host": "x",
                                     "port": 1})
                assert "error" in resp
            finally:
                srv.stop()
                svc.close()


def test_registry_dynamic_pollers_spin_up_and_down():
    """add() on a STARTED registry spawns exactly one poller for the
    joiner; remove() stops it at the next tick (no thread leak)."""
    with Fleet() as fl:
        reg = fl.router.registry
        reg.start()
        assert set(reg._threads) == set(fl.specs)
        svc = QueryService(max_concurrency=1)
        srv = TaskGatewayServer(service=svc).start()
        try:
            spec = "%s:%d" % srv.address
            r, created = reg.add(spec)
            assert created
            assert spec in reg._threads
            t = reg._threads[spec]
            # the poller's first round makes it alive without poll_now
            assert wait_for(lambda: r.alive, timeout=10)
            reg.remove(spec, reason="leave")
            assert spec not in reg._threads
            assert wait_for(lambda: not t.is_alive(), timeout=10)
            assert spec in reg.departed
        finally:
            srv.stop()
            svc.close()


def test_membership_chaos_dropped_join_retries(dataset):
    """`router.membership` chaos seam: a DROPped JOIN never acks - the
    announcer's next tick retries and succeeds (the loop IS the
    retry); the fleet converges despite the fault."""
    with Fleet() as fl:
        with RouterServer(fl.router) as rs:
            svc = QueryService(max_concurrency=1)
            srv = TaskGatewayServer(service=svc).start()
            try:
                spec = "%s:%d" % srv.address
                ann = MembershipAnnouncer(
                    "%s:%d" % rs.address, spec, interval_s=30.0,
                )
                with chaos.active(
                    [Fault("router.membership", klass="DROP",
                           times=1)],
                    seed=11,
                ) as plan:
                    assert not ann.announce_now()  # dropped
                    assert plan.fired("router.membership") == 1
                    assert spec not in fl.router.registry.replicas
                    assert ann.announce_now()  # the retry lands
                assert spec in fl.router.registry.replicas
                assert ann.join_failures == 1
                ann.close()
            finally:
                srv.stop()
                svc.close()


def test_flapping_replica_no_affinity_thrash_no_poller_leak(dataset):
    """Satellite: repeated quick join/leave of ONE replica neither
    thrashes the OTHER replicas' affinity placement nor leaks poller
    threads."""
    with Fleet() as fl:
        reg = fl.router.registry
        reg.start()
        # pin an affinity home on a stable replica first
        st = fl.router.submit({"use_cache": True}, dataset())
        p = wait_done(fl.router, st["query_id"])
        home = p["replica"]
        key = fl.router.get(st["query_id"]).key
        svc = QueryService(max_concurrency=1)
        srv = TaskGatewayServer(service=svc).start()
        try:
            spec = "%s:%d" % srv.address
            flapped = []
            for _ in range(6):
                _join(fl.router, spec)
                flapped.append(reg._threads.get(spec))
                _leave(fl.router, spec)
            # the stable replica's affinity never moved
            assert fl.router.affinity.lookup(key)[0] == home
            st2 = fl.router.submit({"use_cache": True}, dataset())
            p2 = wait_done(fl.router, st2["query_id"])
            assert p2["replica"] == home
            assert p2["dispatches"] == 0  # still the warm cache
            # every flap cycle's poller exits; at most the live
            # entry's thread remains
            assert spec not in reg._threads
            assert wait_for(
                lambda: all(
                    t is None or not t.is_alive() for t in flapped
                ),
                timeout=10,
            )
            assert len(reg._retired) <= 64
        finally:
            srv.stop()
            svc.close()


# ---------------------------------------------------------------------------
# graceful drain
# ---------------------------------------------------------------------------


def test_drain_finishes_inflight_and_rejects_new(dataset):
    """QueryService.drain: in-flight queries run to completion while
    new SUBMITs get the classified DRAINING rejection (TRANSIENT, so
    clients retry instead of failing)."""
    release = threading.Event()
    svc = QueryService(max_concurrency=2)
    try:
        blocker = GatedScan(release)
        q = svc.submit_plan(blocker)
        assert wait_for(lambda: blocker.started.is_set())
        out = []
        t = threading.Thread(
            target=lambda: out.append(svc.drain(timeout_s=30))
        )
        t.start()
        assert wait_for(lambda: svc.draining)
        rej = svc.submit_plan(GatedScan(release))
        assert rej.state.value == "REJECTED_OVERLOADED"
        assert rej.error.startswith("DRAINING")
        assert rej.error_class == "TRANSIENT"
        assert _is_draining_rejection(rej.status())
        assert t.is_alive()  # still waiting on the in-flight query
        release.set()
        t.join(timeout=30)
        assert out == [True]
        assert q.state.value == "DONE"
        # the STATS surface carries the drain flag for the registry
        assert svc.stats()["service"]["draining"] is True
    finally:
        release.set()
        svc.close()


def test_drain_timeout_reports_false():
    release = threading.Event()
    svc = QueryService(max_concurrency=1)
    try:
        blocker = GatedScan(release)
        svc.submit_plan(blocker)
        assert wait_for(lambda: blocker.started.is_set())
        assert svc.drain(timeout_s=0.2) is False
    finally:
        release.set()
        svc.close()


def test_router_spills_draining_rejection_no_strikes(dataset):
    """The router treats a DRAINING rejection as a placement miss: the
    query spills to the next replica with ZERO breaker strikes, the
    replica is marked draining immediately (before the next STATS
    poll), and the drain lands on the membership counter."""
    blob = dataset()
    with Fleet() as fl:
        st = fl.router.submit({"use_cache": True}, blob)
        p = wait_done(fl.router, st["query_id"])
        home = p["replica"]
        before = REGISTRY.get("blaze_router_membership_events",
                              kind="drain_reject")
        # drain announced but NOT yet polled: affinity still points at
        # the draining replica, so the submit bounces off it
        fl.by_id[home][0].draining = True
        st2 = fl.router.submit({"use_cache": True}, blob)
        p2 = wait_done(fl.router, st2["query_id"])
        assert p2["state"] == "DONE"
        assert p2["replica"] == fl.other(home)
        assert fl.router.counters["drain_spills"] == 1
        assert REGISTRY.get("blaze_router_membership_events",
                            kind="drain_reject") == before + 1
        # zero breaker strikes: draining is not sickness
        assert fl.router.breaker.strikes(home) == 0
        assert not fl.router.registry.get(home).quarantined()
        # the direct observation marked it draining -> unroutable for
        # NEW placements, and STATS shows the state
        assert not fl.router.registry.get(home).routable()
        fl.router.registry.poll_now()
        assert fl.router.registry.snapshot()[home]["state"] \
            == "draining"
        assert fl.router.stats()["fleet"]["draining"] == 1


def test_bare_client_submit_retries_draining_then_classifies(dataset):
    """Satellite: a bare ServiceClient (no router) maps the DRAINING
    rejection to a TRANSIENT classified error after retrying with the
    existing backoff - a rolling restart never surfaces as an opaque
    failure."""
    blob = dataset()
    svc = QueryService(max_concurrency=1)
    srv = TaskGatewayServer(service=svc).start()
    try:
        svc.draining = True
        # fail-fast client: classified error immediately
        with ServiceClient(*srv.address,
                           reconnect_attempts=0) as c:
            with pytest.raises(ReplicaDrainingError) as ei:
                c.submit(blob)
        assert classify(ei.value) is ErrorClass.TRANSIENT
        # retrying client: the replica comes back mid-backoff and the
        # SAME submit call succeeds
        def _undrain():
            time.sleep(0.15)
            svc.draining = False

        threading.Thread(target=_undrain, daemon=True).start()
        with ServiceClient(*srv.address) as c:
            st = c.submit(blob)
            assert st["state"] in ("QUEUED", "ADMITTED", "RUNNING",
                                   "DONE")
    finally:
        srv.stop()
        svc.close()


# ---------------------------------------------------------------------------
# departure bookkeeping + hot-result replication
# ---------------------------------------------------------------------------


def test_leave_evicts_affinity_eagerly(dataset):
    """Departure (LEAVE) evicts the leaver's AffinityMap entries NOW -
    the next repeat places fresh instead of decaying into a failed
    placement + failover."""
    blob = dataset()
    with Fleet() as fl:
        st = fl.router.submit({"use_cache": True}, blob)
        p = wait_done(fl.router, st["query_id"])
        rq = fl.router.get(st["query_id"])
        home = p["replica"]
        assert fl.router.affinity.lookup(rq.key)[0] == home
        before = len(fl.router.affinity)
        _leave(fl.router, home)
        assert fl.router.affinity.lookup(rq.key) == (None, None)
        assert len(fl.router.affinity) < before
        assert REGISTRY.get("blaze_router_affinity_evictions_total") \
            >= 2  # blob key + learned fingerprint


def test_hot_replication_ranks_places_and_promotes(dataset):
    """Tentpole arm 3: repeats make a fingerprint hot (runtime-history
    samples the registry polls); tick() double-places it on the second
    replica (confirmed DONE = warm ResultCache copy); killing the home
    promotes the secondary to affinity home and the next repeat
    serves WARM - 0 dispatches - from the survivor."""
    blob = dataset()
    with Fleet(router_kw={"quarantine_s": 30.0}) as fl:
        r = fl.router
        qid = None
        for _ in range(3):  # accumulate history samples
            st = r.submit({"use_cache": True}, blob)
            p = wait_done(r, st["query_id"])
            assert p["state"] == "DONE"
            qid = st["query_id"]
        home = p["replica"]
        other = fl.other(home)
        fp = r.get(qid).fingerprint
        r.registry.poll_now()  # deliver the history snapshots
        assert fp in r.hot.rank_hot()
        assert r.hot.tick() == 1
        snap = r.hot.snapshot()
        assert snap["replicated"] == 1
        assert fp in snap["replicated_fps"]
        # the copy is REAL: the secondary's cache holds the result
        other_svc = fl.by_id[other][0]
        assert other_svc.cache.stats()["entries"] >= 1
        # a second tick is a no-op (already replicated + healthy)
        assert r.hot.tick() == 0
        # kill the home replica; heartbeat death -> eviction +
        # promotion of the confirmed secondary
        fl.kill_gateway(home)

        def dead():
            r.registry.poll_now()
            return not r.registry.get(home).alive

        assert wait_for(dead, timeout=10)
        assert wait_for(
            lambda: r.affinity.lookup(
                r.get(qid).key
            )[0] == other,
            timeout=10,
        )
        assert r.hot.snapshot()["promoted"] == 1
        # the acceptance pin: the repeat is served warm from the
        # survivor holding the replicated result - zero dispatches
        st2 = r.submit({"use_cache": True}, blob)
        p2 = wait_done(r, st2["query_id"])
        assert p2["state"] == "DONE"
        assert p2["replica"] == other
        assert p2["dispatches"] == 0, p2
        assert p2["cache_hits"] == 1


def test_hot_replicator_skips_unknown_payload_and_fleet_of_one():
    """rank_hot can name fingerprints the router never placed (payload
    predates it) and a fleet of one has nowhere to replicate - both
    are clean no-ops."""
    with Fleet() as fl:
        # no submissions: nothing tracked, nothing hot
        assert fl.router.hot.tick() == 0
        assert fl.router.hot.rank_hot() == []
        assert fl.router.hot.on_replica_gone(fl.specs[0]) == []


def test_conn_pool_checkin_across_leave_closes_stale_client(
        monkeypatch):
    """A verb client checked OUT while its replica LEAVEs is invisible
    to the leave-time pool purge - the epoch bump makes its check-in
    close it instead of pooling a socket to the dead process for
    whoever re-joins at the same address (and its release must not
    corrupt the next epoch's connection count)."""
    from tests.test_router import _stub_wire

    made = _stub_wire(monkeypatch)
    r = Router(["127.0.0.1:19999"], start=False, conn_pool_size=2)
    try:
        rep = next(iter(r.registry.replicas.values()))
        rid = rep.replica_id
        hold = threading.Event()
        entered = threading.Event()
        out = []

        def slow(c):
            entered.set()
            assert hold.wait(10)
            return c

        t = threading.Thread(
            target=lambda: out.append(r._call(rep, slow))
        )
        t.start()
        assert entered.wait(10)
        # the replica LEAVEs while the verb is in flight
        assert r._member_leave(rid, "leave")["ok"]
        hold.set()
        t.join(10)
        assert out and out[0].closed  # closed at check-in, not pooled
        assert r._clients.get(rid, []) == []
        # the next epoch starts clean: fresh client, count from zero
        c2 = r._call(rep, lambda c: c)
        assert c2 is not out[0] and not c2.closed
        assert r._client_counts[rid] == 1
        assert len(made) == 2
    finally:
        r.close()


def test_membership_events_counter_and_state_gauge(dataset):
    """Satellite: churn is visible on the scrape surface - the
    membership `state` label per replica and the
    blaze_router_membership_events{kind} counter."""
    with Fleet() as fl:
        svc = QueryService(max_concurrency=1)
        srv = TaskGatewayServer(service=svc).start()
        try:
            spec = "%s:%d" % srv.address
            joins = REGISTRY.get("blaze_router_membership_events",
                                 kind="join")
            _join(fl.router, spec)
            assert REGISTRY.get("blaze_router_membership_events",
                                kind="join") == joins + 1
            _leave(fl.router, spec)
            assert REGISTRY.get("blaze_router_membership_events",
                                kind="leave") >= 1
            text = REGISTRY.render_prometheus()
            assert "blaze_router_membership_events" in text
            assert 'blaze_router_replica_membership{' in text
            assert f'replica="{spec}",state="gone"' in text
            assert 'state="alive"' in text
            # STATS carries the same states
            snap = fl.router.stats()["replicas"]
            assert snap[spec]["state"] == "gone"
            assert all(
                snap[s]["state"] == "alive" for s in fl.specs
            )
        finally:
            srv.stop()
            svc.close()
