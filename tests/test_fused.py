"""Pipeline fusion tests: fused chains must match unfused results."""

import numpy as np
import pandas as pd
import pytest

from blaze_tpu import ColumnBatch
from blaze_tpu.exprs import Col, ScalarFn
from blaze_tpu.ops import (
    FilterExec,
    MemoryScanExec,
    ProjectExec,
    RenameColumnsExec,
)
from blaze_tpu.ops.fused import FusedPipelineExec, fuse_pipelines
from blaze_tpu.runtime.executor import run_plan


def chain(scan):
    return ProjectExec(
        RenameColumnsExec(
            FilterExec(
                ProjectExec(
                    scan,
                    [(Col("a"), "a"), (Col("a") * Col("b"), "ab")],
                ),
                Col("ab") > 10,
            ),
            ["a", "prod"],
        ),
        [(Col("prod") + 1, "p1"), (Col("a"), "a")],
    )


def test_fusion_rewrites_and_matches():
    cb = ColumnBatch.from_pydict(
        {"a": list(range(20)), "b": [2] * 20}
    )
    scan = MemoryScanExec.from_batches([cb])
    unfused = chain(scan)
    ref = run_plan(unfused).to_pydict()

    fused = fuse_pipelines(chain(scan))
    assert isinstance(fused, FusedPipelineExec)
    assert len(fused.stages) == 4
    got = run_plan(fused).to_pydict()
    assert got == ref
    assert got["p1"] == [2 * a + 1 for a in range(20) if 2 * a > 10]


def test_string_stage_not_fused():
    cb = ColumnBatch.from_pydict({"s": ["x", "yy"], "v": [1, 2]})
    scan = MemoryScanExec.from_batches([cb])
    plan = FilterExec(
        ProjectExec(scan, [(Col("s"), "s"), (Col("v"), "v")]),
        Col("s") == "x",
    )
    out = fuse_pipelines(plan)
    # the string filter stays unfused; result still correct
    assert isinstance(out, FilterExec)
    assert run_plan(out).to_pydict() == {"s": ["x"], "v": [1]}


def test_string_passthrough_fuses():
    cb = ColumnBatch.from_pydict({"s": ["x", "yy", "z"], "v": [1, 2, 3]})
    scan = MemoryScanExec.from_batches([cb])
    plan = ProjectExec(
        FilterExec(scan, Col("v") > 1),
        [(Col("s"), "s"), (Col("v") * 10, "v10")],
    )
    out = fuse_pipelines(plan)
    assert isinstance(out, FusedPipelineExec)
    got = run_plan(out).to_pydict()
    assert got == {"s": ["yy", "z"], "v10": [20, 30]}


def test_fused_inside_larger_plan():
    from blaze_tpu.exprs import AggExpr, AggFn
    from blaze_tpu.ops import AggMode, HashAggregateExec

    cb = ColumnBatch.from_pydict(
        {"k": [1, 2, 1, 2, 1], "v": [1, 2, 3, 4, 100]}
    )
    scan = MemoryScanExec.from_batches([cb])
    plan = HashAggregateExec(
        ProjectExec(
            FilterExec(scan, Col("v") < 50),
            [(Col("k"), "k"), (Col("v") * 2, "v2")],
        ),
        keys=[(Col("k"), "k")],
        aggs=[(AggExpr(AggFn.SUM, Col("v2")), "s")],
        mode=AggMode.COMPLETE,
    )
    fused = fuse_pipelines(plan)
    # COMPLETE rewrites to device-PARTIAL (fused over the chain) wrapped
    # in a host finalizer
    from blaze_tpu.ops.fused import FusedAggregateExec, HostFinalAggExec

    assert isinstance(fused, HostFinalAggExec)
    inner = fused.children[0]
    assert isinstance(inner, FusedAggregateExec)
    assert isinstance(inner.pipeline, FusedPipelineExec)
    out = run_plan(fused).to_pydict()
    assert dict(zip(out["k"], out["s"])) == {1: 8, 2: 12}


def test_fused_partial_aggregate():
    from blaze_tpu.exprs import AggExpr, AggFn
    from blaze_tpu.ops import AggMode, HashAggregateExec
    from blaze_tpu.ops.fused import FusedAggregateExec

    batches = [
        ColumnBatch.from_pydict(
            {"k": [1, 2, 1, 3], "v": [10.0, 20.0, 30.0, 40.0]}
        ),
        ColumnBatch.from_pydict({"k": [2, 3], "v": [5.0, 5.0]}),
    ]
    scan = MemoryScanExec([batches], batches[0].schema)

    def plan():
        return HashAggregateExec(
            ProjectExec(
                FilterExec(scan, Col("v") < 40.0),
                [(Col("k"), "k"), (Col("v") * 2, "v2")],
            ),
            keys=[(Col("k"), "k")],
            aggs=[(AggExpr(AggFn.SUM, Col("v2")), "s"),
                  (AggExpr(AggFn.COUNT_STAR, None), "n")],
            mode=AggMode.PARTIAL,
        )

    fused = fuse_pipelines(plan())
    assert isinstance(fused, FusedAggregateExec)
    ref_batches = [b.to_pydict() for b in plan().execute(0, __import__(
        "blaze_tpu.ops.base", fromlist=["ExecContext"]).ExecContext())]
    got_batches = [b.to_pydict() for b in fused.execute(0, __import__(
        "blaze_tpu.ops.base", fromlist=["ExecContext"]).ExecContext())]

    def merge(bs):
        out = {}
        for d in bs:
            for k, s, n in zip(d["k"], d["s#sum"], d["n#count"]):
                acc = out.get(k, (0.0, 0))
                out[k] = (acc[0] + s, acc[1] + n)
        return out

    assert merge(got_batches) == merge(ref_batches)
    assert merge(got_batches) == {
        1: (80.0, 2), 2: (50.0, 2), 3: (10.0, 1),
    }
