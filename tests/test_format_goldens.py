"""Byte-literal goldens for the two bit-compatibility contracts.

VERDICT r3 item 6: the wire decoder (plan/refcompat.py) and the shuffle
file format were only tested against bytes this repo itself generates,
so a mutual format drift could pass every roundtrip test. These goldens
pin LITERAL bytes:

1. A reference-format TaskDefinition hand-encoded field-by-field from
   the protobuf wire rules against the reference schema
   (/root/reference/native-engine/plan-serde/proto/plan.proto:26-43,
   335-343, 456-460, 508-531, 676-691) - no protoc, no generated code,
   so a drift in refpb regeneration or decoder dispatch fails here.
2. A .data/.index segmented-IPC shuffle pair with the framing spans
   asserted byte-for-byte (util/ipc.rs:20-49 part framing,
   shuffle_writer_exec.rs:437-506 index layout). The zstd payload is
   pinned as produced-once bytes (zstd DEcompression is stable across
   versions; compression output is not, so writer-side checks assert
   framing + decoded equality rather than compressed-byte equality).
"""

import struct

import pyarrow as pa


# ---------------------------------------------------------------------------
# 1. reference-format TaskDefinition, hand-encoded
# ---------------------------------------------------------------------------

# Plan: RenameColumnsExec(renames=["a","b"]) over
#       EmptyPartitionsExec(schema=[k:int64 nullable, v:int32], n=3),
# task_id = PartitionId(job_id="j", stage_id=1, partition_id=2).
#
# Protobuf wire encoding, derived by hand (tag = field_no<<3 | wire_type;
# wire_type 2 = length-delimited, 0 = varint):
_ARROW_INT64 = bytes([0x52, 0x00])        # ArrowType.INT64: field 10, len 0
_ARROW_INT32 = bytes([0x42, 0x00])        # ArrowType.INT32: field 8, len 0
_FIELD_K = (
    bytes([0x0A, 0x01]) + b"k"            # Field.name (1): "k"
    + bytes([0x12, 0x02]) + _ARROW_INT64  # Field.arrow_type (2)
    + bytes([0x18, 0x01])                 # Field.nullable (3): true
)
_FIELD_V = (
    bytes([0x0A, 0x01]) + b"v"            # Field.name (1): "v"
    + bytes([0x12, 0x02]) + _ARROW_INT32  # Field.arrow_type (2)
)                                         # nullable false: omitted
_SCHEMA = (
    bytes([0x0A, len(_FIELD_K)]) + _FIELD_K   # Schema.columns (1)
    + bytes([0x0A, len(_FIELD_V)]) + _FIELD_V
)
_EMPTY_PARTS = (
    bytes([0x0A, len(_SCHEMA)]) + _SCHEMA     # EmptyPartitions.schema (1)
    + bytes([0x10, 0x03])                     # .num_partitions (2): 3
)
_PLAN_EMPTY = (
    # PhysicalPlanNode.empty_partitions (13): tag 13<<3|2 = 0x6A
    bytes([0x6A, len(_EMPTY_PARTS)]) + _EMPTY_PARTS
)
_RENAME = (
    bytes([0x0A, len(_PLAN_EMPTY)]) + _PLAN_EMPTY  # Rename.input (1)
    + bytes([0x12, 0x01]) + b"a"      # .renamed_column_names (2): "a"
    + bytes([0x12, 0x01]) + b"b"      # .renamed_column_names (2): "b"
)
_PLAN_RENAME = (
    # PhysicalPlanNode.rename_columns (12): tag 12<<3|2 = 0x62
    bytes([0x62, len(_RENAME)]) + _RENAME
)
_PARTITION_ID = (
    bytes([0x0A, 0x01]) + b"j"        # PartitionId.job_id (1): "j"
    + bytes([0x10, 0x01])             # .stage_id (2): 1
    + bytes([0x20, 0x02])             # .partition_id (4 - NOT 3): 2
)
GOLDEN_TASK = (
    bytes([0x0A, len(_PARTITION_ID)]) + _PARTITION_ID  # task_id (1)
    + bytes([0x12, len(_PLAN_RENAME)]) + _PLAN_RENAME  # plan (2)
)


def test_reference_taskdefinition_golden_decodes():
    from blaze_tpu.ops.empty import EmptyPartitionsExec
    from blaze_tpu.ops.rename import RenameColumnsExec
    from blaze_tpu.plan.refcompat import task_from_reference_proto
    from blaze_tpu.types import TypeId

    op, partition, task_id, _resources = task_from_reference_proto(
        GOLDEN_TASK
    )
    assert partition == 2
    assert "j" in task_id and "1" in task_id
    assert isinstance(op, RenameColumnsExec)
    child = op.children[0]
    assert isinstance(child, EmptyPartitionsExec)
    assert child.partition_count == 3
    assert [f.name for f in op.schema.fields] == ["a", "b"]
    assert op.schema.fields[0].dtype.id is TypeId.INT64
    assert op.schema.fields[1].dtype.id is TypeId.INT32
    assert op.schema.fields[0].nullable
    assert not op.schema.fields[1].nullable


def test_reference_taskdefinition_golden_matches_refpb():
    """The generated refpb parser must read the hand bytes identically
    (a regeneration drift in refplan_pb2 fails here)."""
    from blaze_tpu.plan.refpb import refplan_pb2 as rp

    t = rp.TaskDefinition()
    t.ParseFromString(GOLDEN_TASK)
    assert t.task_id.job_id == "j"
    assert t.task_id.stage_id == 1
    assert t.task_id.partition_id == 2
    assert t.plan.WhichOneof("PhysicalPlanType") == "rename_columns"
    rn = t.plan.rename_columns
    assert list(rn.renamed_column_names) == ["a", "b"]
    ep = rn.input.empty_partitions
    assert ep.num_partitions == 3
    cols = ep.schema.columns
    assert [c.name for c in cols] == ["k", "v"]
    assert cols[0].arrow_type.WhichOneof("arrow_type_enum") == "INT64"
    assert cols[1].arrow_type.WhichOneof("arrow_type_enum") == "INT32"
    assert cols[0].nullable and not cols[1].nullable
    # canonical re-serialization (ascending field order) reproduces the
    # hand encoding byte-for-byte
    assert t.SerializeToString() == GOLDEN_TASK


# ---------------------------------------------------------------------------
# 2. .data/.index segmented-IPC shuffle pair
# ---------------------------------------------------------------------------

# Three partitions: p0 = 3 rows (k:int64 [1,2,3], v:int32 [10,NULL,30]),
# p1 = empty (zero bytes - empty batches write NOTHING, not a zero
# header; IpcInputStreamIterator.scala:54-100), p2 = 2 rows ([7,8] /
# [70,80]). Payload bytes pinned from a one-time zstd-1 encode.
DATA_HEX = (
    "b90000000000000028b52ffd60a8007d0500420a181eb027cd010c030854"
    "022c4926d8059340a09c1a96a4719452c9bdb7dc52a6bf6d01a9204c2946"
    "3d2c6b1de28227754d17f87a88ca507dc2b67da0fc882a0300e468449343"
    "f936851f3c3962cadb16086e3e47d1d5533e458b46357de408db162520d0"
    "02a1a21eb2c84766b3cc5766ce32090e60bb6f0b316ea0c19645cb2e1a59"
    "f7e284ef46ff4d074570f15c86f7d6cac65b7e9aee04765dfe036ef26635"
    "e35c59bf0bdcf3ffb259b25c06b00000000000000028b52ffd6090003505"
    "005249151bc0a739ff43df6bebff2ab4ca15ddfe5bbb6d519492c9dd2dc9"
    "96298ff82129481a25aed6a67a13012ec1f58d6d7be15c992ec560512653"
    "8670becdf1c5992fc879dbc37064f4347d45e7d53430b9aabf9cb16d0122"
    "005b9315640632998900016ca10fd800f96612011880c59319027c501997"
    "9338da803e4e074d53206cbfcda2b19ab9ed0dee83e81e2e06ba7c1d7047"
    "de2c9a710659af2e7033ff976c96ac9401"
)
# (num_partitions + 1) i64 LE start offsets: [0, 193, 193, 377] -
# partition 1 is the zero-length [193, 193) range
INDEX_HEX = (
    "0000000000000000c100000000000000c100000000000000790100000000"
    "0000"
)


def _expected_tables():
    t0 = pa.table(
        {"k": pa.array([1, 2, 3], pa.int64()),
         "v": pa.array([10, None, 30], pa.int32())}
    )
    t2 = pa.table(
        {"k": pa.array([7, 8], pa.int64()),
         "v": pa.array([70, 80], pa.int32())}
    )
    return t0, t2


def test_segmented_ipc_golden_framing_spans():
    data = bytes.fromhex(DATA_HEX)
    index = bytes.fromhex(INDEX_HEX)
    # index: 4 offsets for 3 partitions, i64 LE, monotonic, last = file
    # size (shuffle_writer_exec.rs:437-506)
    offs = struct.unpack("<4q", index)
    assert offs == (0, 193, 193, 377)
    assert offs[-1] == len(data)
    # part framing: u64 LE length prefix then exactly that many zstd
    # bytes (util/ipc.rs:20-49); zstd magic 0xFD2FB528 LE leads the
    # frame
    (l0,) = struct.unpack_from("<Q", data, 0)
    assert l0 == 193 - 8
    assert data[8:12] == bytes.fromhex("28b52ffd")
    (l2,) = struct.unpack_from("<Q", data, 193)
    assert l2 == 377 - 193 - 8
    assert data[201:205] == bytes.fromhex("28b52ffd")


def test_segmented_ipc_golden_decodes():
    from blaze_tpu.io.ipc import decode_ipc_parts

    data = bytes.fromhex(DATA_HEX)
    offs = struct.unpack("<4q", bytes.fromhex(INDEX_HEX))
    t0, t2 = _expected_tables()
    got0 = pa.Table.from_batches(
        list(decode_ipc_parts(data[offs[0]:offs[1]]))
    )
    assert got0.equals(t0)
    assert list(decode_ipc_parts(data[offs[1]:offs[2]])) == []
    got2 = pa.Table.from_batches(
        list(decode_ipc_parts(data[offs[2]:offs[3]]))
    )
    assert got2.equals(t2)


def test_segmented_ipc_writer_reproduces_golden_contract(tmp_path):
    """The engine's own writer must produce files the golden's framing
    rules describe (compressed bytes may differ across zstd versions;
    framing and decoded content must not)."""
    from blaze_tpu.io.ipc import (
        decode_ipc_parts,
        encode_ipc_segment,
        partition_ranges,
    )

    t0, t2 = _expected_tables()
    seg0 = encode_ipc_segment(t0.to_batches()[0])
    seg2 = encode_ipc_segment(t2.to_batches()[0])
    data = seg0 + seg2
    index = struct.pack(
        "<4q", 0, len(seg0), len(seg0), len(seg0) + len(seg2)
    )
    (l0,) = struct.unpack_from("<Q", seg0, 0)
    assert l0 == len(seg0) - 8
    assert seg0[8:12] == bytes.fromhex("28b52ffd")
    # empty batches write NOTHING
    empty_rb = pa.RecordBatch.from_arrays(
        [pa.array([], pa.int64()), pa.array([], pa.int32())],
        names=["k", "v"],
    )
    assert encode_ipc_segment(empty_rb) == b""
    dpath = tmp_path / "w.data"
    ipath = tmp_path / "w.index"
    dpath.write_bytes(data)
    ipath.write_bytes(index)
    ranges = partition_ranges(str(ipath))
    assert ranges == [
        (0, len(seg0)), (len(seg0), 0), (len(seg0), len(seg2))
    ]
    got0 = pa.Table.from_batches(
        list(decode_ipc_parts(data[: len(seg0)]))
    )
    assert got0.equals(t0)
