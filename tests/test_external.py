"""External (grace) execution tests: oversized aggregates and joins run
bucket-wise through the spill format and stay correct."""

import numpy as np
import pytest

from blaze_tpu import ColumnBatch
from blaze_tpu.config import EngineConfig, get_config, set_config
from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.ops import (
    AggMode,
    ExecContext,
    HashAggregateExec,
    JoinType,
    MemoryScanExec,
    SortMergeJoinExec,
)
from blaze_tpu.runtime.executor import run_plan


@pytest.fixture
def tiny_limit():
    old = get_config()
    cfg = EngineConfig(
        max_materialize_rows=500, external_buckets=4,
        shape_buckets=old.shape_buckets,
    )
    set_config(cfg)
    yield cfg
    set_config(old)


def multi_batch_scan(n_batches=10, rows=200, seed=3):
    rng = np.random.default_rng(seed)
    parts = []
    schema = None
    for _ in range(n_batches):
        cb = ColumnBatch.from_pydict(
            {
                "k": rng.integers(0, 37, rows).astype(int).tolist(),
                "v": rng.integers(0, 100, rows).astype(int).tolist(),
            }
        )
        schema = cb.schema
        parts.append(cb)
    return MemoryScanExec([parts], schema)


def test_external_grouped_aggregate(tiny_limit):
    scan = multi_batch_scan()
    ctx = ExecContext(config=tiny_limit)
    op = HashAggregateExec(
        scan,
        keys=[(Col("k"), "k")],
        aggs=[
            (AggExpr(AggFn.SUM, Col("v")), "s"),
            (AggExpr(AggFn.COUNT_STAR, None), "n"),
            (AggExpr(AggFn.MIN, Col("v")), "mn"),
        ],
        mode=AggMode.COMPLETE,
    )
    rows = {}
    for b in op.execute(0, ctx):
        d = b.to_pydict()
        for k, s, n, mn in zip(d["k"], d["s"], d["n"], d["mn"]):
            assert k not in rows, "group split across buckets"
            rows[k] = (s, n, mn)
    # >= initial bucket count: overflowing buckets may re-bucket
    # recursively and each level adds its fanout to the counter
    assert ctx.metrics.counters.get("external_agg_buckets", 0) >= 4
    # differential reference
    import collections

    ref = collections.defaultdict(lambda: [0, 0, 10**9])
    rng = np.random.default_rng(3)
    for _ in range(10):
        ks = rng.integers(0, 37, 200)
        vs = rng.integers(0, 100, 200)
        for k, v in zip(ks, vs):
            r = ref[int(k)]
            r[0] += int(v)
            r[1] += 1
            r[2] = min(r[2], int(v))
    assert rows == {k: tuple(v) for k, v in ref.items()}


def test_external_keyless_aggregate(tiny_limit):
    scan = multi_batch_scan()
    ctx = ExecContext(config=tiny_limit)
    op = HashAggregateExec(
        scan,
        keys=[],
        aggs=[
            (AggExpr(AggFn.SUM, Col("v")), "s"),
            (AggExpr(AggFn.AVG, Col("v")), "a"),
            (AggExpr(AggFn.COUNT_STAR, None), "n"),
        ],
        mode=AggMode.COMPLETE,
    )
    out = [b.to_pydict() for b in op.execute(0, ctx)]
    assert len(out) == 1
    # recompute reference
    total, count = 0, 0
    rng = np.random.default_rng(3)
    for _ in range(10):
        rng.integers(0, 37, 200)
        v = rng.integers(0, 100, 200)
        total += int(v.sum())
        count += len(v)
    assert out[0]["s"] == [total]
    assert out[0]["n"] == [count]
    np.testing.assert_allclose(out[0]["a"][0], total / count)


def test_external_smj(tiny_limit):
    l = multi_batch_scan(6, 150, seed=5)
    r = multi_batch_scan(6, 150, seed=8)
    ctx = ExecContext(config=tiny_limit)
    op = SortMergeJoinExec(l, r, ["k"], ["k"], JoinType.INNER)
    got = 0
    for b in op.execute(0, ctx):
        got += b.to_arrow().num_rows
    assert ctx.metrics.counters.get("external_join_buckets", 0) == 4
    # reference count via pandas
    import pandas as pd

    def frame(seed):
        rng = np.random.default_rng(seed)
        ks, vs = [], []
        for _ in range(6):
            ks += rng.integers(0, 37, 150).tolist()
            vs += rng.integers(0, 100, 150).tolist()
        return pd.DataFrame({"k": ks, "v": vs})

    ref = len(frame(5).merge(frame(8), on="k"))
    assert got == ref


def test_external_smj_outer(tiny_limit):
    l = multi_batch_scan(4, 150, seed=5)
    r = multi_batch_scan(4, 150, seed=8)
    ctx = ExecContext(config=tiny_limit)
    op = SortMergeJoinExec(l, r, ["v"], ["v"], JoinType.LEFT)
    got = 0
    for b in op.execute(0, ctx):
        got += b.to_arrow().num_rows
    import pandas as pd

    def frame(seed, n=4):
        rng = np.random.default_rng(seed)
        ks, vs = [], []
        for _ in range(n):
            ks += rng.integers(0, 37, 150).tolist()
            vs += rng.integers(0, 100, 150).tolist()
        return pd.DataFrame({"k": ks, "v": vs})

    ref = len(frame(5).merge(frame(8), on="v", how="left"))
    assert got == ref


def test_external_sort_topk_and_host(tiny_limit):
    from blaze_tpu.ops import SortExec, SortKey

    scan = multi_batch_scan(8, 150, seed=9)
    ctx = ExecContext(config=tiny_limit)
    # top-k path
    op = SortExec(scan, [SortKey(Col("v"), ascending=False)], fetch=10)
    got = []
    for b in op.execute(0, ctx):
        got += b.to_pydict()["v"]
    rng = np.random.default_rng(9)
    allv = []
    for _ in range(8):
        rng.integers(0, 37, 150)
        allv += rng.integers(0, 100, 150).tolist()
    assert got == sorted(allv, reverse=True)[:10]
    # host-sort fallback path (string keys cannot run-merge on codes)
    rng2 = np.random.default_rng(9)
    sbatches = []
    alls = []
    for _ in range(8):
        rng2.integers(0, 37, 150)
        vs = rng2.integers(0, 100, 150)
        ss = [f"s{v:03d}" for v in vs]
        alls += ss
        sbatches.append(ColumnBatch.from_pydict({"s": ss}))
    scan2 = MemoryScanExec([sbatches], sbatches[0].schema)
    op2 = SortExec(scan2, [SortKey(Col("s"))])
    ctx2 = ExecContext(config=tiny_limit)
    got2 = []
    for b in op2.execute(0, ctx2):
        got2 += b.to_pydict()["s"]
    assert got2 == sorted(alls)
    assert ctx2.metrics.counters.get("host_sorts") == 1


def test_external_run_merge_sort(tiny_limit):
    from blaze_tpu.ops import SortExec, SortKey

    scan = multi_batch_scan(8, 150, seed=13)
    ctx = ExecContext(config=tiny_limit)
    op = SortExec(scan, [SortKey(Col("v")), SortKey(Col("k"))])
    got = []
    for b in op.execute(0, ctx):
        d = b.to_pydict()
        got += list(zip(d["k"], d["v"]))
    assert ctx.metrics.counters.get("sort_spilled_runs", 0) >= 2
    rng = np.random.default_rng(13)
    allrows = []
    for _ in range(8):
        ks = rng.integers(0, 37, 150).tolist()
        vs = rng.integers(0, 100, 150).tolist()
        allrows += list(zip(ks, vs))
    exp = sorted(allrows, key=lambda t: (t[1], t[0]))
    assert [(v,) for _, v in got] == [(v,) for _, v in exp]
    # full (k within v) ordering as well
    assert sorted(got) == sorted(exp)
    assert got == exp


def test_external_run_merge_sort_desc_nulls(tiny_limit):
    import pyarrow as pa

    from blaze_tpu.ops import MemoryScanExec as MS, SortExec, SortKey

    rng = np.random.default_rng(17)
    batches = []
    allv = []
    for _ in range(6):
        vals = [
            None if rng.random() < 0.1 else int(rng.integers(0, 1000))
            for _ in range(150)
        ]
        allv += vals
        batches.append(
            ColumnBatch.from_arrow(
                pa.RecordBatch.from_pydict(
                    {"v": pa.array(vals, type=pa.int64())}
                )
            )
        )
    scan = MS([batches], batches[0].schema)
    ctx = ExecContext(config=tiny_limit)
    op = SortExec(
        scan, [SortKey(Col("v"), ascending=False, nulls_first=False)]
    )
    got = []
    for b in op.execute(0, ctx):
        got += b.to_pydict()["v"]
    nn = sorted([v for v in allv if v is not None], reverse=True)
    exp = nn + [None] * (len(allv) - len(nn))
    assert got == exp


def test_hbm_budget_drives_bucket_count():
    """Regression (VERDICT r1 weak-11): an oversized join sizes its
    grace-bucket count from the device-memory budget, not a fixed
    constant - a tiny budget forces more, smaller buckets."""
    from blaze_tpu.runtime.memory import choose_external_bucket_count

    old = get_config()
    try:
        # ~1 KB working budget per bucket -> est 64 KB needs many buckets
        cfg = EngineConfig(
            max_materialize_rows=500, external_buckets=4,
            device_memory_budget=16 << 10, memory_fraction=1.0,
            shape_buckets=old.shape_buckets,
        )
        set_config(cfg)
        assert choose_external_bucket_count(64 << 10, cfg) == 16
        assert choose_external_bucket_count(100, cfg) == 4  # floor
        assert choose_external_bucket_count(1 << 40, cfg) == 1024  # cap

        # end-to-end: the oversized join records the budget-derived count
        left = multi_batch_scan(n_batches=8, rows=200, seed=5)
        right = multi_batch_scan(n_batches=8, rows=200, seed=6)
        j = SortMergeJoinExec(left, right, ["k"], ["k"], JoinType.INNER)
        ctx = ExecContext()
        rows = 0
        for p in range(j.partition_count):
            for cb in j.execute(p, ctx):
                rows += sum(
                    1 for x in cb.to_arrow().column(0).to_pylist()
                )
        assert rows > 0
        buckets = ctx.metrics.flatten()["root"].get(
            "external_join_buckets", 0
        )
        assert buckets > cfg.external_buckets, buckets
    finally:
        set_config(old)


def test_device_tracker_accounting():
    from blaze_tpu.runtime.memory import DeviceMemoryTracker

    t = DeviceMemoryTracker(budget=1000)
    t.track(1, 400)
    t.track(2, 300)
    assert t.total_used() == 700
    assert t.headroom() == 300
    assert t.high_water == 700
    t.release(1, 100)
    assert t.total_used() == 600
    t.release(2)
    assert t.total_used() == 300


def test_grace_join_rebucket_and_hot_key():
    """Many-key bucket overflow re-buckets recursively; a single hot
    key that can't split still joins correctly (materialized)."""
    old = get_config()
    try:
        cfg = EngineConfig(
            max_materialize_rows=300, external_buckets=2,
            shape_buckets=old.shape_buckets,
        )
        set_config(cfg)
        rng = np.random.default_rng(21)
        # left: 2000 rows over 50 keys -> buckets overflow by key count
        lk = rng.integers(0, 50, 2000).astype(int)
        lv = np.arange(2000)
        rk = np.arange(50).astype(int)
        rv = rng.integers(0, 10, 50)

        def scan(k, v, batch=250):
            parts = [
                ColumnBatch.from_pydict(
                    {"k": k[i: i + batch].tolist(),
                     "v": v[i: i + batch].tolist()}
                )
                for i in range(0, len(k), batch)
            ]
            return MemoryScanExec([parts], parts[0].schema)

        j = SortMergeJoinExec(
            scan(lk, lv), scan(rk, rv), ["k"], ["k"], JoinType.INNER
        )
        ctx = ExecContext()
        got = 0
        for cb in j.execute(0, ctx):
            got += cb.to_arrow().num_rows
        assert got == 2000  # FK join: every left row matches once
        m = ctx.metrics.flatten()["root"]
        assert m.get("external_join_rebuckets", 0) > 0

        # hot key: everything is key 7 on both sides
        hk = np.full(1200, 7)
        j2 = SortMergeJoinExec(
            scan(hk, np.arange(1200)), scan(np.full(40, 7),
                                            np.arange(40)),
            ["k"], ["k"], JoinType.INNER,
        )
        ctx2 = ExecContext()
        got2 = sum(
            cb.to_arrow().num_rows for cb in j2.execute(0, ctx2)
        )
        assert got2 == 1200 * 40
        m2 = ctx2.metrics.flatten()["root"]
        assert m2.get("external_join_hot_buckets", 0) > 0
    finally:
        set_config(old)


def test_grace_agg_hot_bucket_chunked():
    """A skewed COMPLETE aggregate over one hot key aggregates
    chunk-wise (partial per chunk + final merge) instead of
    materializing the whole bucket."""
    from blaze_tpu.exprs import AggExpr, AggFn

    old = get_config()
    try:
        cfg = EngineConfig(
            max_materialize_rows=300, external_buckets=2,
            shape_buckets=old.shape_buckets,
        )
        set_config(cfg)
        n = 2400
        ks = [7] * n  # one hot key
        vs = list(range(n))
        parts = [
            ColumnBatch.from_pydict(
                {"k": ks[i: i + 200], "v": vs[i: i + 200]}
            )
            for i in range(0, n, 200)
        ]
        scan = MemoryScanExec([parts], parts[0].schema)
        agg = HashAggregateExec(
            scan,
            keys=[(Col("k"), "k")],
            aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
                  (AggExpr(AggFn.AVG, Col("v")), "a"),
                  (AggExpr(AggFn.COUNT_STAR, None), "n")],
            mode=AggMode.COMPLETE,
        )
        ctx = ExecContext()
        rows = []
        for cb in agg.execute(0, ctx):
            rows += list(zip(*[
                cb.to_arrow().column(i).to_pylist() for i in range(4)
            ]))
        assert rows == [(7, sum(vs), sum(vs) / n, n)]
        m = ctx.metrics.flatten()["root"]
        assert m.get("external_agg_hot_buckets", 0) > 0
    finally:
        set_config(old)
