"""Contention observability (ISSUE 15): TimedLock/TimedRLock wait-hold
accounting, the thread-stack sampling profiler, the PROFILE wire verb
through both tiers, and the `python -m blaze_tpu profile` CLI.

The off-mode contract (accounting disarmed = bare-lock pass-through)
is pinned where the budgets live: test_dispatch_budget.py extends its
obs-off pin with contention armed/disarmed."""

import json
import re
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.obs import contention, sampler
from blaze_tpu.obs.metrics import REGISTRY
from blaze_tpu.ops import AggMode, FilterExec, HashAggregateExec
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.plan.serde import task_to_proto
from blaze_tpu.runtime.gateway import TaskGatewayServer
from blaze_tpu.service import QueryService, ServiceClient


# ---------------------------------------------------------------------------
# TimedLock / TimedRLock accounting
# ---------------------------------------------------------------------------


def test_timedlock_records_wait_and_hold_under_contention():
    contention.enable()
    try:
        lk = contention.TimedLock("t_contended")
        release = threading.Event()

        def holder():
            with lk:
                release.wait(2.0)

        t = threading.Thread(target=holder)
        t.start()
        # wait until the holder owns the lock, then contend
        for _ in range(200):
            if lk.locked():
                break
            time.sleep(0.001)
        assert lk.locked()
        t0 = time.perf_counter()
        threading.Timer(0.05, release.set).start()
        with lk:
            waited = time.perf_counter() - t0
        t.join()
        snap = contention.snapshot()["t_contended"]
        assert snap["waits"] == 2  # holder's free acquire + ours
        assert snap["holds"] == 2
        # our acquire really parked behind the holder
        assert snap["wait_max_s"] >= min(0.04, waited * 0.5)
        # the holder held for the release wait
        assert snap["hold_max_s"] >= 0.04
        assert snap["wait_hold_ratio"] > 0
    finally:
        contention.disable()


def test_timedrlock_reentrant_is_one_boundary():
    contention.enable()
    try:
        lk = contention.TimedRLock("t_rlock")
        with lk:
            with lk:
                with lk:
                    pass
        snap = contention.snapshot()["t_rlock"]
        assert snap["waits"] == 1
        assert snap["holds"] == 1
    finally:
        contention.disable()


def test_off_mode_records_nothing():
    assert not contention.ACTIVE
    lk = contention.TimedLock("t_off")
    rl = contention.TimedRLock("t_off_r")
    with lk:
        pass
    with rl:
        with rl:
            pass
    snap = contention.snapshot()
    assert snap.get("t_off", {"waits": 0})["waits"] == 0
    assert snap.get("t_off_r", {"holds": 0})["holds"] == 0


def test_condition_over_timedlock_accounts_cv_waits():
    contention.enable()
    try:
        cv = threading.Condition(contention.TimedLock("t_cv"))
        ready = threading.Event()
        got = []

        def waiter():
            with cv:
                ready.set()
                cv.wait(2.0)
                got.append(True)

        t = threading.Thread(target=waiter)
        t.start()
        assert ready.wait(2.0)
        with cv:
            cv.notify()
        t.join(2.0)
        assert got == [True]
        snap = contention.snapshot()["t_cv"]
        # waiter acquire + notifier acquire + post-notify reacquire
        assert snap["waits"] >= 3
        assert snap["holds"] >= 3
    finally:
        contention.disable()


def test_enable_is_refcounted():
    assert not contention.ACTIVE
    contention.enable()
    contention.enable()
    contention.disable()
    assert contention.ACTIVE  # one enable still outstanding
    contention.disable()
    assert not contention.ACTIVE


def test_lock_name_overflow_folds_bounded():
    contention.enable()
    try:
        for i in range(contention._MAX_LOCKS + 8):
            with contention.TimedLock(f"t_mint_{i}"):
                pass
        snap = contention.snapshot()
        assert len(snap) <= contention._MAX_LOCKS + 1
        assert contention._OVERFLOW in snap
        assert snap[contention._OVERFLOW]["holds"] >= 8
    finally:
        contention.disable()


def test_top_locks_orders_by_wait():
    contention.enable()
    try:
        contention.stat_for("t_small").record_wait(0.001)
        contention.stat_for("t_small").record_hold(0.001)
        contention.stat_for("t_big").record_wait(0.5)
        contention.stat_for("t_big").record_hold(0.01)
        top = contention.top_locks(2)
        assert top[0]["lock"] == "t_big"
        assert top[0]["wait_hold_ratio"] == pytest.approx(50.0)
    finally:
        contention.disable()


def test_lock_histograms_reach_metrics_exposition():
    contention.enable()
    try:
        with contention.TimedLock("t_expo"):
            pass
        text = REGISTRY.render_prometheus()
        assert 'blaze_lock_wait_seconds_bucket{le="+Inf",lock="t_expo"}' \
            in text.replace("', '", "")
        assert "blaze_lock_hold_seconds_count" in text
        # bucket counts are cumulative: +Inf >= first bucket
        pat = re.compile(
            r'blaze_lock_wait_seconds_bucket\{le="([^"]+)",'
            r'lock="t_expo"\} (\d+)'
        )
        counts = [int(m[1]) for m in pat.findall(text)]
        assert counts and counts[-1] == max(counts)
    finally:
        contention.disable()


# ---------------------------------------------------------------------------
# stack sampler
# ---------------------------------------------------------------------------


def test_sampler_start_stop_hygiene():
    s = sampler.start(hz=200.0)
    assert s.running
    assert any(t.name == "blaze-sampler"
               for t in threading.enumerate())
    # same hz: no-op, same instance
    assert sampler.start(hz=200.0) is s
    sampler.stop()
    assert not s.running
    time.sleep(0.05)
    assert not any(t.name == "blaze-sampler"
                   for t in threading.enumerate())
    # retune: a different hz replaces the sampler
    s2 = sampler.start(hz=97.0)
    assert s2 is not s and s2.hz == 97.0
    sampler._reset_for_tests()
    assert sampler.current() is None


def test_sampler_bounds_distinct_stacks():
    s = sampler.StackSampler(hz=100.0, max_stacks=2, max_depth=4)
    for _ in range(30):
        s.sample_once()
    snap = s.snapshot(include_collapsed=False)
    assert snap["samples"] == 30
    # bounded: at most max_stacks keys plus per-role overflow bins
    assert snap["distinct_stacks"] <= 2 + len(
        {r for r, _ in s._stacks}
    )
    stacks = list(s._stacks)
    assert all(len(st) <= 4 for _, st in stacks)


def test_collapsed_export_is_flamegraph_valid():
    # sample_once excludes the CALLING thread (in production, the
    # sampler thread excludes itself) - park a worker to be sampled
    s = sampler.StackSampler(hz=100.0)
    stop = threading.Event()
    w = threading.Thread(target=stop.wait, args=(5.0,),
                         name="blaze-query-w")
    w.start()
    try:
        for _ in range(5):
            s.sample_once()
    finally:
        stop.set()
        w.join()
    text = s.collapsed()
    assert text
    line_re = re.compile(r"^[^ ]+(;[^ ]+)+ \d+$")
    for line in text.splitlines():
        assert line_re.match(line), line
    # role filter keeps only that role's stacks
    roles = {ln.split(";", 1)[0] for ln in text.splitlines()}
    for role in roles:
        sub = s.collapsed(role=role)
        assert all(ln.startswith(role + ";")
                   for ln in sub.splitlines())
    top = s.top(5)
    assert top and all(
        set(e) == {"frame", "role", "samples", "pct"} for e in top
    )


def test_role_tagging():
    assert sampler.role_of("blaze-verb-service") == "verb-loop"
    assert sampler.role_of("blaze-dispatch") == "dispatcher"
    assert sampler.role_of("blaze-query-3") == "executor"
    assert sampler.role_of("blaze-router-poll-x") == "poller"
    assert sampler.role_of("blaze-router-stream-reader") == "relay"
    assert sampler.role_of("Thread-7") == "other"


# ---------------------------------------------------------------------------
# PROFILE verb + STATS/METRICS surfaces through both tiers
# ---------------------------------------------------------------------------


@pytest.fixture
def dataset(tmp_path):
    rng = np.random.default_rng(5)
    p = str(tmp_path / "c.parquet")
    pq.write_table(
        pa.table({
            "k": pa.array(rng.integers(0, 16, 4000), pa.int32()),
            "v": pa.array(rng.random(4000), pa.float64()),
        }),
        p,
    )
    plan = HashAggregateExec(
        FilterExec(ParquetScanExec([[FileRange(p)]]),
                   Col("v") > 0.25),
        keys=[],
        aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
              (AggExpr(AggFn.COUNT_STAR, None), "n")],
        mode=AggMode.COMPLETE,
    )
    return task_to_proto(plan, 0)


def test_profile_verb_roundtrip_service_tier(dataset):
    with QueryService(max_concurrency=2) as svc:
        with TaskGatewayServer(service=svc) as srv:
            with ServiceClient(*srv.address) as c:
                started = c.profile({"op": "start", "hz": 101.0})
                assert started == {
                    "ok": True, "tier": "service",
                    "profiling": True,
                }
                assert contention.ACTIVE
                assert sampler.current().running
                c.run(dataset)
                snap = c.profile({"op": "snapshot"})
                assert snap["tier"] == "service"
                assert snap["profile"]["hz"] == 101.0
                assert "service_state" in snap["contention"]
                assert isinstance(snap["top_locks"], list)
                # per-verb wire latency rode the same roundtrips
                assert "submit" in snap["verbs"]
                assert set(snap["verbs"]["submit"]) == {
                    "decode", "dispatch", "reply",
                }
                c.profile({"op": "reset"})
                snap2 = c.profile({"op": "snapshot",
                                   "collapsed": False})
                assert snap2["profile"]["samples"] \
                    <= snap["profile"]["samples"]
                stopped = c.profile({"op": "stop"})
                assert stopped["profiling"] is False
                assert not contention.ACTIVE
                # STATS carries the contention section on this tier
                stats = c.stats()
                assert "contention" in stats
            # scrape self-metric: the second exposition carries the
            # first scrape's cost
            with ServiceClient(*srv.address) as c:
                c.metrics()
                assert "blaze_scrape_seconds" in c.metrics()


def test_profile_verb_roundtrip_router_tier(dataset):
    from blaze_tpu.router.proxy import Router, RouterServer

    with QueryService(max_concurrency=2) as svc:
        with TaskGatewayServer(service=svc) as srv:
            router = Router(["%s:%d" % srv.address],
                            start=False)
            router.registry.poll_now()
            try:
                with RouterServer(router) as rsrv:
                    with ServiceClient(*rsrv.address) as c:
                        started = c.profile({"op": "start"})
                        assert started["tier"] == "router"
                        c.run(dataset)
                        snap = c.profile({"op": "snapshot"})
                        assert snap["tier"] == "router"
                        assert "router_table" in snap["contention"]
                        stats = c.stats()
                        assert "contention" in stats
                        c.profile({"op": "stop"})
            finally:
                router.close()
    assert not contention.ACTIVE


def test_router_stream_buffered_bytes_gauge():
    from blaze_tpu.router.proxy import Router

    r = Router([], start=False)
    try:
        samples = list(r._collect_metrics())
        gauges = [s for s in samples
                  if s[0] == "blaze_router_stream_buffered_bytes"]
        assert gauges == [
            ("blaze_router_stream_buffered_bytes", {}, 0, "gauge")
        ]
    finally:
        r.close()


def test_profile_verb_repeated_start_balances():
    """N starts then one stop must fully disarm (the armed flag, not
    a runaway refcount, owns the contention enable)."""
    from blaze_tpu.service.wire import handle_profile_frame

    for _ in range(3):
        handle_profile_frame("service", {"op": "start", "hz": 251.0})
    assert contention.ACTIVE
    handle_profile_frame("service", {"op": "stop"})
    assert not contention.ACTIVE
    assert not sampler.current().running


# ---------------------------------------------------------------------------
# profile CLI end-to-end (in-process)
# ---------------------------------------------------------------------------


def test_profile_cli_end_to_end(tmp_path):
    from blaze_tpu.__main__ import main

    out = str(tmp_path / "report.json")
    rc = main([
        "profile", "--concurrency", "1,2", "--rounds", "1",
        "--per-client", "2", "--rows", "4096", "-o", out,
    ])
    assert rc == 0
    report = json.loads(open(out).read())
    assert report["format"] == "blaze-profile-v1"
    assert report["tier"] == "service"
    assert [e["concurrency"] for e in report["levels"]] == [1, 2]
    for entry in report["levels"]:
        assert entry["qps"] > 0
        assert entry["contention"], "empty lock section"
        assert entry["stacks"]["samples"] > 0
    assert report["top_locks"], "no wait-dominated locks reported"
    for lock in report["top_locks"]:
        assert {"lock", "wait_s", "wait_hold_ratio"} <= set(lock)
    assert report["per_verb_seconds"].get("submit")
    # the acceptance bar: >= 1 collapsed stack for the verb-loop role
    assert "verb-loop" in report["roles"]
    assert any(ln.startswith("verb-loop;")
               for ln in report["collapsed"].splitlines())
    # the CLI disarms on exit
    assert not contention.ACTIVE
