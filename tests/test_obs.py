"""Observability-layer tests (ISSUE 4): span trees + Chrome-trace
export validity, the Prometheus metrics registry, the runtime-history
store, predicted-unmeetability shedding, the structured STATS payload,
the slow-query log, the METRICS/REPORT wire surface, cross-process
trace stitching, and the obs-off wall-overhead guarantee.

`run_tests.py --trace` selects the `trace`-named subset: the
chaos-retried multi-partition query whose exported trace must validate
against the minimal Chrome-trace-event schema (matched B/E pairs,
monotonic ts, attempt spans present)."""

import json
import logging
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.obs import trace
from blaze_tpu.obs.history import RuntimeHistory
from blaze_tpu.obs.metrics import MetricsRegistry, REGISTRY
from blaze_tpu.ops import (
    AggMode,
    FilterExec,
    HashAggregateExec,
)
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.service import QueryService
from blaze_tpu.testing import chaos


def wait_for(cond, timeout=10.0, tick=0.005):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return True
        time.sleep(tick)
    return False


@pytest.fixture
def two_part_plan(tmp_path):
    """A 2-partition parquet aggregate with a STABLE fingerprint (so
    the cache probes and the runtime history both engage)."""
    rng = np.random.default_rng(7)
    paths = []
    for i in range(2):
        p = str(tmp_path / f"t{i}.parquet")
        pq.write_table(pa.table({"v": rng.random(2000)}), p)
        paths.append(p)

    def make():
        return HashAggregateExec(
            FilterExec(
                ParquetScanExec([[FileRange(p)] for p in paths]),
                Col("v") > 0.5,
            ),
            keys=[],
            aggs=[(AggExpr(AggFn.SUM, Col("v")), "s")],
            mode=AggMode.COMPLETE,
        )

    return make


# ---------------------------------------------------------------------------
# span tree + export primitives
# ---------------------------------------------------------------------------


def test_span_tree_nests_and_exports_valid_chrome_trace():
    rec = trace.begin_trace("t-unit")
    with trace.span("outer", rec=rec, partition=0) as outer:
        with trace.span("inner") as inner:  # thread-current recorder
            inner.event("tick", n=1)
        outer.tag(done=True)
    rec.finish(state="DONE")
    assert trace.get_trace("t-unit") is rec
    # structure: inner's parent is outer, outer's parent is root
    by_name = {s.name: s for s in rec.spans}
    assert by_name["inner"].parent_id == by_name["outer"].span_id
    assert by_name["outer"].parent_id == rec.root.span_id
    doc = trace.chrome_trace(rec)
    assert trace.validate_chrome(doc) == []
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "B"}
    assert {"query", "outer", "inner"} <= names
    assert any(e["ph"] == "i" and e["name"] == "tick"
               for e in doc["traceEvents"])


def test_span_exit_tags_error_class():
    from blaze_tpu.errors import TransientError

    rec = trace.begin_trace("t-err")
    with pytest.raises(TransientError):
        with trace.span("attempt", rec=rec, attempt=0):
            raise TransientError("flaky")
    sp = next(s for s in rec.spans if s.name == "attempt")
    assert sp.tags["error_class"] == "TRANSIENT"
    assert sp.end_ns is not None


def test_chrome_validator_rejects_malformed():
    bad = {"traceEvents": [
        {"ph": "B", "name": "a", "pid": 1, "tid": 1, "ts": 5},
        {"ph": "E", "name": "a", "pid": 1, "tid": 1, "ts": 2},
        {"ph": "E", "name": "b", "pid": 1, "tid": 1, "ts": 9},
        {"ph": "B", "name": "c", "pid": 1, "tid": 2, "ts": 1},
    ]}
    problems = trace.validate_chrome(bad)
    assert any("non-monotonic" in p for p in problems)
    assert any("without matching B" in p for p in problems)
    assert any("unclosed B" in p for p in problems)
    assert trace.validate_chrome({}) != []


def test_span_cap_degrades_to_null_spans():
    old = trace.MAX_SPANS_PER_TRACE
    trace.MAX_SPANS_PER_TRACE = 3
    try:
        rec = trace.begin_trace("t-cap")
        for i in range(6):
            with trace.span(f"s{i}", rec=rec):
                pass
        assert len(rec.spans) == 3
        assert rec.dropped == 4
        assert trace.validate_chrome(trace.chrome_trace(rec)) == []
    finally:
        trace.MAX_SPANS_PER_TRACE = old


def test_attach_subtree_stitches_remote_spans():
    worker = trace.TraceRecorder("task-1", root_name="worker_task")
    with trace.span("execute", rec=worker):
        with trace.span("kernel_dispatch"):
            pass
    worker.finish(state="DONE")
    dicts = worker.to_dicts()
    # simulate the wire: JSON round trip
    dicts = json.loads(json.dumps(dicts))

    driver = trace.begin_trace("q-driver")
    n = driver.attach_subtree(dicts)
    assert n == len(dicts)
    by_name = {s.name: s for s in driver.spans}
    # subtree root re-parents under the driver root; inner links hold
    assert by_name["worker_task"].parent_id == driver.root.span_id
    assert by_name["execute"].parent_id == by_name["worker_task"].span_id
    assert trace.validate_chrome(trace.chrome_trace(driver)) == []


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_metrics_registry_prometheus_exposition():
    r = MetricsRegistry()
    r.inc("blaze_queries_total", state="DONE")
    r.inc("blaze_queries_total", 2, state="FAILED")
    r.observe("blaze_query_wall_seconds", 0.004)
    r.observe("blaze_query_wall_seconds", 3.0)
    r.register_collector(
        "t", lambda: [("blaze_admission_queued", {}, 5, "gauge")]
    )
    txt = r.render_prometheus()
    assert '# TYPE blaze_queries_total counter' in txt
    assert 'blaze_queries_total{state="DONE"} 1' in txt
    assert 'blaze_queries_total{state="FAILED"} 2' in txt
    assert 'blaze_admission_queued 5' in txt
    assert 'blaze_query_wall_seconds_count 2' in txt
    assert 'le="+Inf"} 2' in txt
    # bucket counts are cumulative
    assert 'blaze_query_wall_seconds_sum 3.004' in txt
    r.unregister_collector("t")
    assert "blaze_admission_queued" not in r.render_prometheus()
    # a crashing collector degrades to a CUMULATIVE error counter
    # (a literal 1 would make rate() blind to persistent failure)
    r.register_collector("boom", lambda: 1 / 0)
    assert ('blaze_collector_errors_total{collector="boom"} 1'
            in r.render_prometheus())
    assert ('blaze_collector_errors_total{collector="boom"} 2'
            in r.render_prometheus())


def test_two_live_services_render_distinct_series():
    """Two QueryServices share the process registry; their samples
    must stay distinct series (the instance label) - duplicate
    name+labelset pairs would fail a whole Prometheus scrape."""
    with QueryService(max_concurrency=1):
        with QueryService(max_concurrency=1):
            txt = REGISTRY.render_prometheus()
    series = [ln.rsplit(" ", 1)[0] for ln in txt.splitlines()
              if ln and not ln.startswith("#")]
    dupes = {s for s in series if series.count(s) > 1}
    assert not dupes, dupes


def test_global_registry_folds_dispatch_counters():
    from blaze_tpu.runtime import dispatch

    dispatch.record("dispatches", 0)  # ensure the family exists
    txt = REGISTRY.render_prometheus()
    assert 'blaze_dispatch_total{kind="dispatches"}' in txt


# ---------------------------------------------------------------------------
# runtime history
# ---------------------------------------------------------------------------


def test_runtime_history_estimates_and_bounds():
    h = RuntimeHistory(max_fingerprints=2, samples_per_fp=4)
    assert h.estimate("fp0") is None
    assert h.p50("fp0") is None
    for v in (0.1, 0.2, 0.3):
        h.record("fp0", v)
    assert h.p50("fp0") == pytest.approx(0.2)
    assert h.p50("fp0", min_samples=4) is None  # sample floor
    for v in (1.0, 1.0, 9.0, 9.0, 9.0, 9.0):
        h.record("fp0", v)  # ring: only the last 4 remain
    assert h.p50("fp0") == pytest.approx(9.0)
    h.record("fp1", 1.0)
    h.record("fp2", 1.0)  # LRU-evicts fp0 (capacity 2)
    assert h.estimate("fp0") is None
    s = h.summary()
    assert s["fingerprints"] == 2
    assert all("p50" in t for t in s["top"])


# ---------------------------------------------------------------------------
# the service trace: chaos-retried multi-partition export (CI --trace)
# ---------------------------------------------------------------------------


def test_trace_chaos_retried_query_exports_valid_perfetto_json(
    two_part_plan,
):
    """ISSUE 4 acceptance: a chaos-retried multi-partition query's
    exported trace is schema-valid Chrome JSON containing queue-wait,
    per-attempt execution (one span per attempt, failures tagged with
    error_class), and cache-probe spans, with the injected fault
    visible as a span event carrying the plan seed."""
    with chaos.active(
        [chaos.Fault(site="task.execute", klass="TRANSIENT",
                     partition=1, times=1)],
        seed=42,
    ) as plan:
        with QueryService(max_concurrency=2,
                          retry_backoff_s=0.005) as svc:
            q = svc.submit_plan(two_part_plan())
            svc.result(q.query_id, timeout=60)
            doc = svc.trace(q.query_id)
        assert plan.fired("task.execute") == 1
    assert doc is not None
    assert trace.validate_chrome(doc) == []
    begins = [e for e in doc["traceEvents"] if e["ph"] == "B"]
    names = {e["name"] for e in begins}
    assert {"query", "queue_wait", "admission", "attempt",
            "cache_probe", "execute_partition"} <= names
    # partition 1 ran twice: a failed attempt tagged TRANSIENT + the
    # retry (partition 0 contributes its own single attempt)
    attempts = [e for e in begins if e["name"] == "attempt"]
    assert len(attempts) == 3
    failed = [e for e in attempts
              if e.get("args", {}).get("error_class") == "TRANSIENT"]
    assert len(failed) == 1
    faults = [e for e in doc["traceEvents"]
              if e["ph"] == "i" and e["name"] == "chaos.fault"]
    assert len(faults) == 1
    assert faults[0]["args"]["seed"] == 42
    # the root span covers the WHOLE query: its exported E must not be
    # truncated below the last attempt's end (the retroactive
    # queue_wait span starts at SUBMIT, before the root was built -
    # the recorder backdates the root so the nesting sweep cannot
    # clamp it)
    ends = {}
    for e in doc["traceEvents"]:
        if e["ph"] == "E":
            ends.setdefault(e["name"], e["ts"])
            ends[e["name"]] = max(ends[e["name"]], e["ts"])
    assert ends["query"] >= ends["attempt"]
    # the trace is genuinely Perfetto-loadable JSON (round-trips)
    assert trace.validate_chrome(json.loads(json.dumps(doc))) == []


def test_trace_parquet_decode_fault_lands_on_its_span(two_part_plan):
    """A chaos fault injected at the parquet.decode seam (which runs
    on the prefetch thread) must still land as a chaos.fault event
    inside the parquet_decode span's trace."""
    with chaos.active(
        [chaos.Fault(site="parquet.decode", klass="TRANSIENT",
                     times=1)],
        seed=11,
    ) as plan:
        with QueryService(max_concurrency=1,
                          retry_backoff_s=0.005) as svc:
            q = svc.submit_plan(two_part_plan())
            svc.result(q.query_id, timeout=60)
            doc = svc.trace(q.query_id)
        assert plan.fired("parquet.decode") == 1
    assert trace.validate_chrome(doc) == []
    faults = [e for e in doc["traceEvents"]
              if e["ph"] == "i" and e["name"] == "chaos.fault"]
    assert len(faults) == 1
    assert faults[0]["args"]["site"] == "parquet.decode"


def test_trace_off_records_nothing(two_part_plan):
    assert not trace.ACTIVE
    with QueryService(max_concurrency=1, enable_trace=False) as svc:
        q = svc.submit_plan(two_part_plan())
        svc.result(q.query_id, timeout=60)
        assert q.tracer is None
        assert svc.trace(q.query_id) is None


# ---------------------------------------------------------------------------
# predicted-unmeetability shedding
# ---------------------------------------------------------------------------


def test_predicted_unmeetable_shed(two_part_plan):
    # cache OFF: shedding semantics without cache interference
    with QueryService(max_concurrency=1, enable_cache=False) as svc:
        plan = two_part_plan()
        fp = plan.fingerprint()
        # fewer than 3 samples: never shed on prediction
        svc.history.record(fp, 60.0)
        svc.history.record(fp, 60.0)
        q_ok = svc.submit_plan(two_part_plan(), deadline_s=30.0)
        assert wait_for(lambda: q_ok.done)
        assert q_ok.state.value == "DONE"
        # >= 3 samples of a p50 far beyond the slack: shed at
        # admission with the DISTINCT counter, before any execution
        for _ in range(3):
            svc.history.record(fp, 60.0)
        q = svc.submit_plan(two_part_plan(), deadline_s=5.0)
        assert wait_for(lambda: q.done)
        assert q.state.value == "TIMED_OUT"
        assert "predicted unmeetable" in q.error
        st = svc.stats()
        assert st["admission"]["shed_predicted"] == 1
        assert st["admission"]["shed_deadline"] == 0
        # the shed query must NOT count as admitted (next_admissible
        # popped it, but the shed path takes the admit back) - only
        # q_ok has genuinely been admitted at this point
        assert st["admission"]["admitted"] == 1
        # a deadline-less query with the same fingerprint still runs
        q2 = svc.submit_plan(two_part_plan())
        assert wait_for(lambda: q2.done)
        assert q2.state.value == "DONE"


def test_predicted_shed_skipped_when_cache_covers(two_part_plan):
    """A fully-cached fingerprint must NOT be shed on its (slow)
    runtime estimate: the cache serves it inside any deadline, and a
    shed would pin the stale estimate forever (sheds never execute,
    so no faster sample could ever be recorded)."""
    with QueryService(max_concurrency=1) as svc:
        warm = svc.submit_plan(two_part_plan())
        svc.result(warm.query_id, timeout=60)  # populates the cache
        fp = two_part_plan().fingerprint()
        for _ in range(3):
            svc.history.record(fp, 60.0)  # p50 far beyond any slack
        q = svc.submit_plan(two_part_plan(), deadline_s=2.0)
        assert wait_for(lambda: q.done)
        assert q.state.value == "DONE"  # served from cache, not shed
        st = svc.stats()
        assert st["admission"]["shed_predicted"] == 0
        assert st["cache"]["hits"] == 2  # both partitions


def test_queued_deadline_timeout_snapshots_error(two_part_plan,
                                                 caplog):
    """The terminal hook fires INSIDE the transition, so q.error must
    be assigned before it: a query timed out while QUEUED has the
    deadline message in its slow-query log line, not null."""
    with caplog.at_level(logging.WARNING, logger="blaze_tpu.slowlog"):
        with chaos.active(
            [chaos.Fault(site="service.admit", klass="STALL",
                         stall_s=0.6)],
            seed=2,
        ):
            with QueryService(max_concurrency=1,
                              slow_query_s=1e-6) as svc:
                blocker = svc.submit_plan(two_part_plan())
                # the blocker must HOLD the single slot (stalled at
                # the service.admit seam) before the deadlined query
                # is enqueued, else the dispatcher may admit the
                # deadlined query first and it times out "before
                # start" instead of "while queued"
                assert wait_for(
                    lambda: blocker.state.value != "QUEUED",
                    timeout=20,
                )
                q = svc.submit_plan(two_part_plan(), deadline_s=0.15)
                assert wait_for(lambda: q.done, timeout=20)
                assert q.state.value == "TIMED_OUT"
                assert q.error == "deadline exceeded while queued"
                svc.result(blocker.query_id, timeout=60)
    lines = [json.loads(r.message) for r in caplog.records
             if r.name == "blaze_tpu.slowlog"]
    timed_out = [p for p in lines if p["query_id"] == q.query_id]
    assert timed_out and timed_out[0]["error"] == (
        "deadline exceeded while queued"
    )


def test_runtime_history_records_service_executions(two_part_plan):
    with QueryService(max_concurrency=1, enable_cache=False) as svc:
        for _ in range(3):
            q = svc.submit_plan(two_part_plan())
            svc.result(q.query_id, timeout=60)
        fp = two_part_plan().fingerprint()
        est = svc.history.estimate(fp)
        assert est is not None and est["n"] == 3
        assert svc.history.p50(fp) is not None


# ---------------------------------------------------------------------------
# structured STATS
# ---------------------------------------------------------------------------


def test_stats_structured_payload(two_part_plan):
    with QueryService(max_concurrency=1) as svc:
        q = svc.submit_plan(two_part_plan())
        svc.result(q.query_id, timeout=60)
        st = svc.stats()
    assert isinstance(st["admission"]["headroom"], int)
    assert "queued" in st["admission"]
    assert st["queries"]["by_state"].get("DONE") == 1
    assert st["queries"]["live"] == 0
    for k in ("degraded_queries", "retried_queries", "slow_queries"):
        assert k in st["queries"]
    assert st["cache"]["hits"] == 0
    assert st["runtime_history"]["fingerprints"] == 1
    assert "workers_total" in st["quarantine"]
    assert st["service"]["trace_enabled"] is True
    json.dumps(st)  # the whole payload is wire-serializable


# ---------------------------------------------------------------------------
# slow-query log
# ---------------------------------------------------------------------------


def test_slow_query_log_emits_one_json_line(two_part_plan, caplog):
    with caplog.at_level(logging.WARNING, logger="blaze_tpu.slowlog"):
        with QueryService(max_concurrency=1,
                          slow_query_s=0.000001) as svc:
            q = svc.submit_plan(two_part_plan())
            svc.result(q.query_id, timeout=60)
            assert wait_for(
                lambda: svc.obs_counters["slow_queries"] >= 1
            )
    lines = [r.message for r in caplog.records
             if r.name == "blaze_tpu.slowlog"]
    assert len(lines) == 1
    payload = json.loads(lines[0])
    assert payload["event"] == "slow_query"
    assert payload["query_id"] == q.query_id
    assert payload["state"] == "DONE"
    assert payload["wall_s"] > 0
    assert "execution_s" in payload["phases"]
    assert "queue_wait_s" in payload["phases"]
    assert "fingerprint" in payload
    # the per-span rollup: where execution time went
    assert payload["spans"]["attempt"]["count"] == 2


def test_slow_query_log_flags_retries_and_threshold_off(
    two_part_plan, caplog,
):
    with caplog.at_level(logging.WARNING, logger="blaze_tpu.slowlog"):
        with chaos.active(
            [chaos.Fault(site="task.execute", klass="TRANSIENT",
                         partition=0, times=1)],
            seed=3,
        ):
            with QueryService(max_concurrency=1, slow_query_s=1e-6,
                              retry_backoff_s=0.005) as svc:
                q = svc.submit_plan(two_part_plan())
                svc.result(q.query_id, timeout=60)
                assert wait_for(
                    lambda: svc.obs_counters["slow_queries"] >= 1
                )
    payload = json.loads(
        [r.message for r in caplog.records
         if r.name == "blaze_tpu.slowlog"][0]
    )
    assert payload["retries"] == 1
    caplog.clear()
    # threshold <= 0 disables the log entirely
    with caplog.at_level(logging.WARNING, logger="blaze_tpu.slowlog"):
        with QueryService(max_concurrency=1, slow_query_s=0.0) as svc:
            q = svc.submit_plan(two_part_plan())
            svc.result(q.query_id, timeout=60)
    assert not [r for r in caplog.records
                if r.name == "blaze_tpu.slowlog"]


# ---------------------------------------------------------------------------
# wire surface: METRICS verb + trace-through-REPORT + the trace CLI
# ---------------------------------------------------------------------------


def test_wire_metrics_verb_and_trace_report(two_part_plan, tmp_path):
    from blaze_tpu.plan.serde import task_to_proto
    from blaze_tpu.runtime.gateway import TaskGatewayServer
    from blaze_tpu.service import ServiceClient

    blob = task_to_proto(two_part_plan(), 0)
    with QueryService(max_concurrency=2) as svc:
        with TaskGatewayServer(service=svc) as srv:
            host, port = srv.address
            with ServiceClient(host, port) as c:
                st = c.submit(blob)
                qid = st["query_id"]
                c.fetch(qid)
                # METRICS verb: Prometheus text with dispatch.* and
                # admission counters (ISSUE 4 acceptance)
                txt = c.metrics()
                assert 'blaze_dispatch_total{kind="dispatches"}' in txt
                # admission samples carry a service instance label
                # (several services may share the process registry)
                assert ('blaze_admission_events_total'
                        '{event="admitted",service="') in txt
                assert 'blaze_queries_total{state="DONE"}' in txt
                # trace rides the REPORT verb, OPT-IN via flags bit 0:
                # a text-only report poll must not pay the span-tree
                # serialization
                assert "trace" not in c.report_full(
                    qid, include_trace=False
                )
                full = c.report_full(qid)
                assert "DONE" in full["report"]
                doc = full["trace"]
                assert trace.validate_chrome(doc) == []
                names = {e["name"] for e in doc["traceEvents"]
                         if e["ph"] == "B"}
                assert {"queue_wait", "attempt",
                        "result_stream"} <= names
            # the CLI export path writes the same document
            from blaze_tpu.__main__ import main as cli_main

            out = str(tmp_path / "q.trace.json")
            rc = cli_main(["trace", qid, "--host", host,
                           "--port", str(port), "-o", out])
            assert rc == 0
            with open(out) as f:
                assert trace.validate_chrome(json.load(f)) == []
            # unknown id: the CLI surfaces the server's in-band
            # error, not a misleading tracing diagnosis
            rc = cli_main(["trace", "no-such-query", "--host", host,
                           "--port", str(port), "-o", out])
            assert rc == 1


def test_wire_report_raw_span_dicts_flag(two_part_plan):
    """REPORT flags bit 1 (ISSUE 6): the RAW span dicts ride the wire
    for the router's cross-hop graft - id/parent links intact, NOT
    the rendered Chrome document (and not unless asked)."""
    from blaze_tpu.plan.serde import task_to_proto
    from blaze_tpu.runtime.gateway import TaskGatewayServer
    from blaze_tpu.service import ServiceClient

    blob = task_to_proto(two_part_plan(), 0)
    with QueryService(max_concurrency=2) as svc:
        with TaskGatewayServer(service=svc) as srv:
            host, port = srv.address
            with ServiceClient(host, port) as c:
                st = c.submit(blob)
                qid = st["query_id"]
                c.fetch(qid)
                plain = c.report_full(qid, include_trace=False)
                assert "trace_spans" not in plain
                resp = c.report_full(qid, include_trace=False,
                                     include_spans=True)
                spans = resp["trace_spans"]
                assert "trace" not in resp
                assert isinstance(spans, list) and spans
                ids = {s["span_id"] for s in spans}
                # a self-consistent subtree: every parent link
                # resolves inside the payload (root's parent is 0)
                assert all(
                    s["parent_id"] in ids or s["parent_id"] == 0
                    for s in spans
                )
                names = {s["name"] for s in spans}
                assert {"query", "queue_wait", "attempt"} <= names
                # and it grafts cleanly into another recorder
                rec = trace.TraceRecorder("re-graft")
                assert rec.attach_subtree(spans) == len(spans)
                rec.finish(state="DONE")
                assert trace.validate_chrome(
                    trace.chrome_trace(rec)
                ) == []


# ---------------------------------------------------------------------------
# cross-process stitching (cluster workers)
# ---------------------------------------------------------------------------


def test_trace_cluster_worker_spans_stitch_into_driver(tmp_path):
    from blaze_tpu.ops import LimitExec
    from blaze_tpu.plan.serde import task_to_proto
    from blaze_tpu.runtime.cluster import MiniCluster

    p = str(tmp_path / "c.parquet")
    pq.write_table(pa.table({"v": np.arange(100, dtype=np.int64)}), p)
    blob = task_to_proto(
        LimitExec(ParquetScanExec([[FileRange(p)]]), 10), 0
    )
    trace.enable()
    try:
        driver = trace.begin_trace("q-cluster")
        with trace.span("cluster_run", rec=driver):
            with MiniCluster(
                num_workers=1, env={"BLAZE_TRACE": "1"}
            ) as mc:
                tables = mc.run_tasks([blob], timeout=120)
        driver.finish(state="DONE")
    finally:
        trace.disable()
    assert tables[0].num_rows == 10
    pids = {s.pid for s in driver.spans}
    assert len(pids) == 2  # driver + worker process
    names = {s.name for s in driver.spans}
    assert "worker_task" in names and "execute" in names
    doc = trace.chrome_trace(driver)
    assert trace.validate_chrome(doc) == []
    # worker spans keep their own pid track in the export
    assert len({e["pid"] for e in doc["traceEvents"]}) == 2


# ---------------------------------------------------------------------------
# the disabled-path guarantee: wall overhead (budget pins live in
# test_dispatch_budget.py)
# ---------------------------------------------------------------------------


def test_obs_wall_overhead_under_2_percent():
    """ISSUE 4 satellite: the wall-overhead smoke. Strong form of the
    disabled-path guarantee: even tracing ON (recorder installed, all
    seams live) must cost <2% wall on a battery-style shape - so the
    off path, which only pays the attribute checks, certainly does.
    Interleaved best-of-k pairs with a small absolute slack absorb
    shared-host scheduling noise; the comparison retries before
    failing so one noisy window cannot redden the suite."""
    from blaze_tpu.batch import ColumnBatch
    from blaze_tpu.ops import MemoryScanExec, ProjectExec
    from blaze_tpu.ops.fused import fuse_pipelines
    from blaze_tpu.runtime.executor import run_plan

    assert not trace.ACTIVE
    rng = np.random.default_rng(11)
    n = 1 << 16
    cb = ColumnBatch.from_arrow(pa.record_batch({
        "price": (rng.random(n) * 100).astype(np.float32),
        "qty": rng.integers(1, 10, n).astype(np.int32),
    }))

    def mk():
        return fuse_pipelines(HashAggregateExec(
            ProjectExec(
                MemoryScanExec([[cb]], cb.schema),
                [(Col("price"), "p")],
            ),
            keys=[],
            aggs=[(AggExpr(AggFn.SUM, Col("p")), "s")],
            mode=AggMode.COMPLETE,
        ))

    def once():
        run_plan(mk())

    def once_traced():
        trace.enable()
        try:
            rec = trace.begin_trace("overhead-probe")
            with trace.span("battery", rec=rec):
                run_plan(mk())
            rec.finish(state="DONE")
        finally:
            trace.disable()

    once()  # warm: compile + kernel-cache fill
    once_traced()
    for attempt in range(3):
        k = 7 * (attempt + 1)
        off = [0.0] * k
        on = [0.0] * k
        for i in range(k):  # interleaved: drift hits both sides
            t0 = time.perf_counter()
            once()
            off[i] = time.perf_counter() - t0
            t0 = time.perf_counter()
            once_traced()
            on[i] = time.perf_counter() - t0
        best_off, best_on = min(off), min(on)
        if best_on <= best_off * 1.02 + 0.002:
            assert not trace.ACTIVE
            return
    raise AssertionError(
        f"obs wall overhead over budget: obs-off best {best_off:.6f}s"
        f" vs obs-on best {best_on:.6f}s (> 2% + 2ms)"
    )
