"""Streaming SMJ semantics matrix with PINNED expected rows.

Port of the reference's in-file SMJ unit-test suite
(sort_merge_join_exec.rs:965-1896: inner/one-key, inner/two-key,
null keys, left/right/full outer padding, semi, anti, empty sides,
equal-key cartesian runs, multi-batch streams) to the streaming
operator, plus the plan wiring: proto round-trip of the streaming flag
and planner selection on sort-guaranteed inputs.
"""

import numpy as np
import pytest

from blaze_tpu import ColumnBatch
from blaze_tpu.batch import empty_batch
from blaze_tpu.ops import ExecContext, JoinType, MemoryScanExec
from blaze_tpu.ops.streaming_smj import StreamingSortMergeJoinExec


def scan(cols: dict, batch_rows=2):
    n = len(next(iter(cols.values())))
    if n == 0:
        sch = ColumnBatch.from_pydict(
            {k: [0] for k in cols}
        ).schema
        return MemoryScanExec([[empty_batch(sch)]], sch)
    batches = [
        ColumnBatch.from_pydict(
            {k: v[s: s + batch_rows] for k, v in cols.items()}
        )
        for s in range(0, n, batch_rows)
    ]
    return MemoryScanExec([batches], batches[0].schema)


def rows(op):
    out = []
    for b in op.execute(0, ExecContext()):
        arr = b.to_arrow()
        out += list(zip(*[arr.column(i).to_pylist()
                          for i in range(arr.num_columns)]))
    return sorted(out, key=lambda r: tuple((x is None, x) for x in r))


L = {"a": [1, 2, 2, 3, 5], "b": [10, 20, 21, 30, 50]}
R = {"a2": [2, 2, 3, 4], "c": [200, 201, 300, 400]}


def smj(left_cols, right_cols, lk, rk, jt, batch_rows=2):
    return StreamingSortMergeJoinExec(
        scan(left_cols, batch_rows), scan(right_cols, batch_rows),
        lk, rk, jt,
    )


def test_inner_one_key_with_duplicate_runs():
    got = rows(smj(L, R, ["a"], ["a2"], JoinType.INNER))
    assert got == sorted([
        (2, 20, 2, 200), (2, 20, 2, 201),
        (2, 21, 2, 200), (2, 21, 2, 201),
        (3, 30, 3, 300),
    ])


def test_left_outer_padding():
    got = rows(smj(L, R, ["a"], ["a2"], JoinType.LEFT))
    assert got == sorted(
        [
            (2, 20, 2, 200), (2, 20, 2, 201),
            (2, 21, 2, 200), (2, 21, 2, 201),
            (3, 30, 3, 300),
            (1, 10, None, None), (5, 50, None, None),
        ],
        key=lambda r: tuple((x is None, x) for x in r),
    )


def test_right_outer_padding():
    got = rows(smj(L, R, ["a"], ["a2"], JoinType.RIGHT))
    assert (None, None, 4, 400) in got
    assert len(got) == 6


def test_full_outer():
    got = rows(smj(L, R, ["a"], ["a2"], JoinType.FULL))
    assert len(got) == 8
    assert (1, 10, None, None) in got
    assert (5, 50, None, None) in got
    assert (None, None, 4, 400) in got


def test_left_semi_and_anti():
    semi = rows(smj(L, R, ["a"], ["a2"], JoinType.LEFT_SEMI))
    assert semi == [(2, 20), (2, 21), (3, 30)]
    anti = rows(smj(L, R, ["a"], ["a2"], JoinType.LEFT_ANTI))
    assert anti == [(1, 10), (5, 50)]


def test_two_key_join():
    l2 = {"k1": [1, 1, 2], "k2": [1, 2, 1], "v": [7, 8, 9]}
    r2 = {"j1": [1, 1, 2], "j2": [1, 3, 1], "w": [70, 71, 72]}
    got = rows(smj(l2, r2, ["k1", "k2"], ["j1", "j2"], JoinType.INNER))
    assert got == [(1, 1, 7, 1, 1, 70), (2, 1, 9, 2, 1, 72)]


def test_null_keys_never_match():
    ln = {"a": [1, 2, None], "b": [10, 12, 11]}
    rn = {"a2": [2, None], "c": [200, 99]}
    # ascending with nulls: engine sorts null-first per sorted_scan
    # convention; keys arrive ascending with None last here, so place
    # them explicitly in sorted position for the streaming contract
    ln = {"a": [None, 1, 2], "b": [11, 10, 12]}
    rn = {"a2": [None, 2], "c": [99, 200]}
    inner = rows(smj(ln, rn, ["a"], ["a2"], JoinType.INNER))
    assert inner == [(2, 12, 2, 200)]
    left = rows(smj(ln, rn, ["a"], ["a2"], JoinType.LEFT))
    assert (None, 11, None, None) in left and len(left) == 3
    full = rows(smj(ln, rn, ["a"], ["a2"], JoinType.FULL))
    assert (None, None, None, 99) in full and len(full) == 4


def test_empty_right_side():
    er = {"a2": [], "c": []}
    assert rows(smj(L, er, ["a"], ["a2"], JoinType.INNER)) == []
    left = rows(smj(L, er, ["a"], ["a2"], JoinType.LEFT))
    assert len(left) == 5 and all(r[2] is None for r in left)
    anti = rows(smj(L, er, ["a"], ["a2"], JoinType.LEFT_ANTI))
    assert len(anti) == 5


def test_empty_left_side():
    el = {"a": [], "b": []}
    assert rows(smj(el, R, ["a"], ["a2"], JoinType.INNER)) == []
    right = rows(smj(el, R, ["a"], ["a2"], JoinType.RIGHT))
    assert len(right) == 4 and all(r[0] is None for r in right)


@pytest.mark.parametrize("batch_rows", [1, 2, 3, 100])
def test_batch_granularity_invariance(batch_rows):
    """Output must not depend on how the sorted streams are batched
    (the reference's output-batch-splitting tests)."""
    ref = rows(smj(L, R, ["a"], ["a2"], JoinType.FULL, batch_rows=100))
    got = rows(smj(L, R, ["a"], ["a2"], JoinType.FULL,
                   batch_rows=batch_rows))
    assert got == ref


def test_naaj_rejected():
    with pytest.raises(NotImplementedError):
        smj(L, R, ["a"], ["a2"], JoinType.LEFT_ANTI_NULL_AWARE)


# ---------------------------------------------------------------------------
# plan wiring
# ---------------------------------------------------------------------------

def test_serde_streaming_flag_roundtrip(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
    from blaze_tpu.plan.serde import plan_from_proto, plan_to_proto

    p = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"a": [1, 2], "b": [3, 4]}), p)
    left = ParquetScanExec([[FileRange(p)]])
    right = ParquetScanExec([[FileRange(p)]])
    op = StreamingSortMergeJoinExec(
        left, right, ["a"], ["a"], JoinType.INNER
    )
    proto = plan_to_proto(op)
    assert proto.sort_merge_join.streaming is True
    back = plan_from_proto(proto)
    assert isinstance(back, StreamingSortMergeJoinExec)


def test_planner_picks_streaming_when_sort_guaranteed():
    import pandas as pd

    from blaze_tpu.exprs import Col
    from blaze_tpu.planner import spec as S
    from blaze_tpu.planner.convert import convert_plan

    def mem(df):
        return S.MemorySpec(children=[], dataframe=df)

    ldf = pd.DataFrame({"a": [2, 1], "b": [20, 10]})
    rdf = pd.DataFrame({"a2": [2, 3], "c": [200, 300]})
    join = S.JoinSpec(
        children=[
            S.SortSpec(children=[mem(ldf)], keys=[(Col("a"), True, True)]),
            S.SortSpec(children=[mem(rdf)],
                       keys=[(Col("a2"), True, True)]),
        ],
        kind="smj",
        left_keys=["a"],
        right_keys=["a2"],
        join_type="inner",
    )
    plan = convert_plan(join, fuse=False)
    found = []

    def walk(op):
        found.append(type(op).__name__)
        for c in op.children:
            walk(c)

    walk(plan)
    assert "StreamingSortMergeJoinExec" in found

    # unsorted children stay on the materializing SMJ
    join2 = S.JoinSpec(
        children=[mem(ldf), mem(rdf)],
        kind="smj",
        left_keys=["a"],
        right_keys=["a2"],
        join_type="inner",
    )
    plan2 = convert_plan(join2, fuse=False)
    found2 = []

    def walk2(op):
        found2.append(type(op).__name__)
        for c in op.children:
            walk2(c)

    walk2(plan2)
    assert "StreamingSortMergeJoinExec" not in found2
    assert "SortMergeJoinExec" in found2
