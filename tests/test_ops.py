"""Operator tests: project/filter/sort/union/limit/rename + aggregates."""

import numpy as np
import pyarrow as pa
import pytest

from blaze_tpu import ColumnBatch
from blaze_tpu.exprs import AggExpr, AggFn, Col, ScalarFn
from blaze_tpu.ops import (
    AggMode,
    DebugExec,
    EmptyPartitionsExec,
    ExecContext,
    FilterExec,
    HashAggregateExec,
    LimitExec,
    MemoryScanExec,
    ProjectExec,
    RenameColumnsExec,
    SortExec,
    SortKey,
    UnionExec,
)


def scan_of(data: dict, **kw) -> MemoryScanExec:
    cb = ColumnBatch.from_pydict(data, **kw)
    return MemoryScanExec.from_batches([cb])


def collect(op, partition=0):
    ctx = ExecContext()
    out = [b.to_arrow() for b in op.execute(partition, ctx)]
    out = [b for b in out if b.num_rows >= 0]
    if not out:
        return {}
    tbl = pa.Table.from_batches(out)
    return tbl.to_pydict()


def test_project_expressions():
    op = ProjectExec(
        scan_of({"a": [1, 2, 3], "b": [10.0, 20.0, 30.0]}),
        [(Col("a") * 2, "a2"), (Col("b") + Col("a"), "s")],
    )
    assert collect(op) == {"a2": [2, 4, 6], "s": [11.0, 22.0, 33.0]}


def test_project_string_passthrough_and_host_fn():
    op = ProjectExec(
        scan_of({"s": ["ab", "CD", None]}),
        [(Col("s"), "s"), (ScalarFn("upper", (Col("s"),)), "u")],
    )
    assert collect(op) == {"s": ["ab", "CD", None], "u": ["AB", "CD", None]}


def test_filter_defers_then_compacts():
    op = FilterExec(
        scan_of({"a": [1, 2, 3, 4, 5], "b": [1, 0, 1, 0, 1]}),
        Col("b") == 1,
    )
    assert collect(op) == {"a": [1, 3, 5], "b": [1, 1, 1]}


def test_filter_string_predicate():
    op = FilterExec(
        scan_of({"s": ["x", "yy", "x", None], "v": [1, 2, 3, 4]}),
        Col("s") == "x",
    )
    assert collect(op) == {"s": ["x", "x"], "v": [1, 3]}


def test_sort_multi_key_nulls():
    op = SortExec(
        scan_of(
            {"a": [2, 1, 2, None, 1], "b": [5.0, 4.0, 3.0, 2.0, 1.0]}
        ),
        [SortKey(Col("a"), ascending=True, nulls_first=True),
         SortKey(Col("b"), ascending=False)],
    )
    out = collect(op)
    assert out["a"] == [None, 1, 1, 2, 2]
    assert out["b"] == [2.0, 4.0, 1.0, 5.0, 3.0]


def test_sort_strings():
    op = SortExec(
        scan_of({"s": ["pear", "apple", "fig", "apple"]}),
        [SortKey(Col("s"))],
    )
    assert collect(op)["s"] == ["apple", "apple", "fig", "pear"]


def test_sort_desc_nulls_last_fetch():
    op = SortExec(
        scan_of({"a": [3, None, 5, 1]}),
        [SortKey(Col("a"), ascending=False, nulls_first=False)],
        fetch=2,
    )
    assert collect(op)["a"] == [5, 3]


def test_union_and_rename():
    s1 = scan_of({"a": [1, 2]})
    s2 = scan_of({"a": [3]})
    u = UnionExec([s1, s2])
    assert u.partition_count == 2
    got = collect(u, 0)["a"] + collect(u, 1)["a"]
    assert got == [1, 2, 3]
    r = RenameColumnsExec(u, ["x"])
    assert collect(r, 0) == {"x": [1, 2]}


def test_limit():
    op = LimitExec(scan_of({"a": list(range(10))}), 4)
    assert collect(op)["a"] == [0, 1, 2, 3]


def test_empty_partitions():
    from blaze_tpu.types import DataType, Field, Schema

    op = EmptyPartitionsExec(
        Schema([Field("a", DataType.int64())]), 3
    )
    assert op.partition_count == 3
    assert collect(op, 1) == {}


# ---------------------------------------------------------------------------
# aggregates
# ---------------------------------------------------------------------------

def agg(fn, col=None):
    return AggExpr(fn, Col(col) if col else None)


def test_complete_aggregate_grouped():
    op = HashAggregateExec(
        scan_of(
            {
                "k": [1, 2, 1, 2, 1],
                "v": [10, 20, 30, None, 50],
            }
        ),
        keys=[(Col("k"), "k")],
        aggs=[
            (agg(AggFn.SUM, "v"), "s"),
            (agg(AggFn.COUNT, "v"), "c"),
            (agg(AggFn.COUNT_STAR), "n"),
            (agg(AggFn.MIN, "v"), "mn"),
            (agg(AggFn.MAX, "v"), "mx"),
            (agg(AggFn.AVG, "v"), "av"),
        ],
        mode=AggMode.COMPLETE,
    )
    out = collect(op)
    rows = sorted(zip(*(out[k] for k in ["k", "s", "c", "n", "mn", "mx", "av"])))
    assert rows == [
        (1, 90, 3, 3, 10, 50, 30.0),
        (2, 20, 1, 2, 20, 20, 20.0),
    ]


def test_group_by_with_null_key():
    op = HashAggregateExec(
        scan_of({"k": [1, None, 1, None], "v": [1, 2, 3, 4]}),
        keys=[(Col("k"), "k")],
        aggs=[(agg(AggFn.SUM, "v"), "s")],
        mode=AggMode.COMPLETE,
    )
    out = collect(op)
    got = {k: s for k, s in zip(out["k"], out["s"])}
    assert got == {1: 4, None: 6}


def test_group_by_strings():
    op = HashAggregateExec(
        scan_of({"k": ["a", "b", "a", "c", "b"], "v": [1, 2, 3, 4, 5]}),
        keys=[(Col("k"), "k")],
        aggs=[(agg(AggFn.SUM, "v"), "s")],
        mode=AggMode.COMPLETE,
    )
    out = collect(op)
    got = dict(zip(out["k"], out["s"]))
    assert got == {"a": 4, "b": 7, "c": 4}


def test_partial_final_two_phase():
    scan = MemoryScanExec(
        [
            [ColumnBatch.from_pydict({"k": [1, 2, 1], "v": [1.0, 2.0, 3.0]})],
            [ColumnBatch.from_pydict({"k": [2, 3], "v": [4.0, 5.0]})],
        ],
        ColumnBatch.from_pydict({"k": [1], "v": [1.0]}).schema,
    )
    partial = HashAggregateExec(
        scan,
        keys=[(Col("k"), "k")],
        aggs=[
            (agg(AggFn.SUM, "v"), "s"),
            (agg(AggFn.AVG, "v"), "a"),
            (agg(AggFn.VAR_SAMP, "v"), "var"),
        ],
        mode=AggMode.PARTIAL,
    )
    # exchange elided: merge both partial partitions in one final
    merged = MemoryScanExec(
        [
            [b for p in range(2) for b in partial.execute(p, ExecContext())]
        ],
        partial.schema,
    )
    final = HashAggregateExec(
        merged,
        keys=[(Col("k"), "k")],
        aggs=[
            (agg(AggFn.SUM, "v"), "s"),
            (agg(AggFn.AVG, "v"), "a"),
            (agg(AggFn.VAR_SAMP, "v"), "var"),
        ],
        mode=AggMode.FINAL,
    )
    out = collect(final)
    rows = {k: (s, a, v) for k, s, a, v in
            zip(out["k"], out["s"], out["a"], out["var"])}
    assert rows[1][0] == 4.0 and rows[1][1] == 2.0
    assert rows[2][0] == 6.0 and rows[2][1] == 3.0
    assert rows[3][0] == 5.0 and rows[3][1] == 5.0
    np.testing.assert_allclose(rows[1][2], np.var([1.0, 3.0], ddof=1))
    np.testing.assert_allclose(rows[2][2], np.var([2.0, 4.0], ddof=1))
    assert rows[3][2] is None  # var_samp of 1 sample is NULL


def test_global_aggregate_no_keys():
    op = HashAggregateExec(
        scan_of({"v": [1, 2, 3, 4]}),
        keys=[],
        aggs=[(agg(AggFn.SUM, "v"), "s"), (agg(AggFn.COUNT_STAR), "n")],
        mode=AggMode.COMPLETE,
    )
    assert collect(op) == {"s": [10], "n": [4]}


def test_aggregate_after_filter_uses_selection():
    f = FilterExec(
        scan_of({"k": [1, 1, 2, 2], "v": [1, 100, 2, 200]}),
        Col("v") < 100,
    )
    op = HashAggregateExec(
        f,
        keys=[(Col("k"), "k")],
        aggs=[(agg(AggFn.SUM, "v"), "s")],
        mode=AggMode.COMPLETE,
    )
    out = collect(op)
    assert dict(zip(out["k"], out["s"])) == {1: 1, 2: 2}


def test_sort_nan_ordering():
    """Spark: NaN sorts greater than any double (asc -> last before
    padding; desc -> first)."""
    nan = float("nan")
    data = {"x": [1.0, nan, -5.0, 2.0]}
    asc = SortExec(scan_of(data), [SortKey(Col("x"))])
    vals = collect(asc)["x"]
    assert vals[:3] == [-5.0, 1.0, 2.0] and vals[3] != vals[3]
    desc = SortExec(scan_of(data), [SortKey(Col("x"), ascending=False)])
    vals = collect(desc)["x"]
    assert vals[0] != vals[0] and vals[1:] == [2.0, 1.0, -5.0]


def test_narrow_key_grouping_collision_fallback(monkeypatch):
    """The narrow-key hash-grouping fast path detects hash collisions
    between distinct keys and re-runs the exact lexsort kernel. Forcing
    every hash to collide must still produce exact results."""
    import blaze_tpu.exprs.hashing as H
    from blaze_tpu.exprs import AggExpr, AggFn, Col
    from blaze_tpu.runtime.executor import run_plan
    from blaze_tpu.ops import AggMode, HashAggregateExec
    from blaze_tpu.runtime import dispatch

    def constant_hash(cols, capacity, precomputed=()):
        import jax.numpy as jnp

        return jnp.zeros(capacity, dtype=jnp.int32)

    # the kernel imports hash_columns_device from exprs.hashing at
    # build time - patch at the source (monkeypatch auto-restores);
    # caches cleared around the patch so other tests never see kernels
    # traced with the degenerate hash
    monkeypatch.setattr(H, "hash_columns_device", constant_hash)
    dispatch.clear_kernel_cache()
    try:
        cb = ColumnBatch.from_pydict(
            {"k": [3, 1, 2, 1, 3, 3], "v": [1, 2, 3, 4, 5, 6]}
        )
        scan = MemoryScanExec.from_batches([cb])
        agg = HashAggregateExec(
            scan,
            keys=[(Col("k"), "k")],
            aggs=[(AggExpr(AggFn.SUM, Col("v")), "s")],
            mode=AggMode.COMPLETE,
        )
        out = run_plan(agg).to_pydict()
        got = dict(zip(out["k"], out["s"]))
        # the fallback lexsort kernel sorts keys directly, so results
        # are exact even with the degenerate all-collide hash
        assert got == {1: 6, 2: 3, 3: 12}
    finally:
        dispatch.clear_kernel_cache()


def test_narrow_key_grouping_matches_lexsort():
    """Fast-path grouping (int/string/null keys) must equal the lexsort
    kernel's results exactly."""
    from blaze_tpu.runtime.executor import run_plan
    import numpy as np
    import pyarrow as pa

    from blaze_tpu.exprs import AggExpr, AggFn, Col
    from blaze_tpu.ops import AggMode, HashAggregateExec

    rng = np.random.default_rng(31)
    n = 5000
    k1 = rng.integers(-50, 50, n)
    k1_null = rng.random(n) < 0.05
    k2 = rng.integers(0, 5, n)
    v = rng.integers(0, 1000, n)
    rb = pa.record_batch(
        {
            "k1": pa.array(
                [None if nn else int(x) for x, nn in zip(k1, k1_null)],
                pa.int64(),
            ),
            "k2": pa.array([f"g{x}" for x in k2], pa.utf8()),
            "v": pa.array(v, pa.int64()),
        }
    )
    cb = ColumnBatch.from_arrow(rb)
    scan = MemoryScanExec([[cb]], cb.schema)
    agg = HashAggregateExec(
        scan,
        keys=[(Col("k1"), "k1"), (Col("k2"), "k2")],
        aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
              (AggExpr(AggFn.COUNT_STAR, None), "n")],
        mode=AggMode.COMPLETE,
    )
    out = run_plan(agg).to_pandas()
    import pandas as pd

    df = pd.DataFrame(
        {"k1": [None if nn else int(x) for x, nn in zip(k1, k1_null)],
         "k2": [f"g{x}" for x in k2], "v": v}
    )
    ref = (
        df.groupby(["k1", "k2"], dropna=False)
        .agg(s=("v", "sum"), n=("v", "size")).reset_index()
    )
    got = out.sort_values(["k2", "k1"], na_position="first").reset_index(
        drop=True)
    ref = ref.sort_values(["k2", "k1"], na_position="first").reset_index(
        drop=True)
    assert len(got) == len(ref)
    assert got["s"].tolist() == ref["s"].tolist()
    assert got["n"].tolist() == ref["n"].tolist()


def test_float_group_keys_scatter_core_matches_sort_core():
    """Float GROUP BY keys run on the scatter core (exact-equality
    probing: NaN groups with NaN, -0.0 == 0.0); results must match the
    lexsort core bit-for-bit."""
    import os

    import numpy as np
    import pyarrow as pa

    from blaze_tpu import ColumnBatch
    from blaze_tpu.exprs import AggExpr, AggFn, Col
    from blaze_tpu.ops import AggMode, HashAggregateExec, MemoryScanExec
    from blaze_tpu.runtime.executor import run_plan

    rng = np.random.default_rng(23)
    n = 5000
    keys = rng.choice(
        [1.5, -0.0, 0.0, np.nan, 2.25, -7.5, np.inf], n
    ).astype(np.float32)
    vals = rng.integers(0, 100, n).astype(np.int64)
    mask = rng.random(n) < 0.1
    rb = pa.record_batch({
        "k": pa.array(
            [None if m else float(k) for k, m in zip(keys, mask)],
            pa.float32(),
        ),
        "v": pa.array(vals, pa.int64()),
    })

    def agg():
        cb = ColumnBatch.from_arrow(rb)
        return run_plan(HashAggregateExec(
            MemoryScanExec([[cb]], cb.schema),
            keys=[(Col("k"), "k")],
            aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
                  (AggExpr(AggFn.COUNT_STAR, None), "c")],
            mode=AggMode.COMPLETE,
        ))

    def as_dict(t):
        # NULL and NaN are DISTINCT groups: read through Arrow, where
        # to_pylist preserves None vs float('nan')
        out = {}
        for k, s, c in zip(
            t.column("k").to_pylist(),
            t.column("s").to_pylist(),
            t.column("c").to_pylist(),
        ):
            key = (
                "null" if k is None
                else "nan" if k != k
                else float(k)
            )
            assert key not in out, (key, out)
            out[key] = (int(s), int(c))
        return out

    outs = {}
    prior = os.environ.get("BLAZE_GROUP_CORE")
    for core in ("scatter", "sort"):
        os.environ["BLAZE_GROUP_CORE"] = core
        try:
            outs[core] = as_dict(agg())
        finally:
            # RESTORE (not pop): an externally pinned core must stay
            # pinned for the rest of the process
            if prior is None:
                os.environ.pop("BLAZE_GROUP_CORE", None)
            else:
                os.environ["BLAZE_GROUP_CORE"] = prior
    assert outs["scatter"] == outs["sort"]
    # -0.0 and 0.0 must be ONE group
    assert sum(1 for k in outs["scatter"] if k == 0.0) == 1


def test_group_capacity_ladder():
    """The tiered group-capacity ladder (run_grouped_kernel): an
    aggregate whose group count exceeds the small first tier must climb
    to the configured capacity and still produce exact results, and a
    few-groups aggregate must resolve inside the first tier. Runs with
    the production default (BLAZE_AGG_TIER1 unset -> 4096) regardless
    of the suite runner's override."""
    import dataclasses
    import os

    import pandas as pd

    from blaze_tpu.config import get_config, set_config
    from blaze_tpu.runtime.executor import run_plan

    prior = os.environ.get("BLAZE_AGG_TIER1")
    os.environ.pop("BLAZE_AGG_TIER1", None)
    prior_cfg = get_config()
    # the ladder only engages when gcap < batch capacity: pin a config
    # where 40000 rows pad to a 65536 bucket and the configured group
    # capacity sits BETWEEN the 4096 first tier and that capacity, so
    # tiers resolve to [4096, 16384, None] (otherwise gcap collapses
    # to None and a single unsliced kernel runs - no ladder at all)
    set_config(dataclasses.replace(
        prior_cfg, batch_size=1 << 16, shape_buckets=(1 << 16,),
        agg_group_capacity=16384,
    ))
    try:
        rng = np.random.default_rng(13)
        n = 40000
        for n_groups in (300, 9000):   # below / above the 4096 tier
            g = rng.integers(0, n_groups, n).astype(np.int64)
            v = rng.integers(0, 1000, n).astype(np.int64)
            cb = ColumnBatch.from_arrow(
                pa.record_batch({"g": g, "v": v})
            )
            plan = HashAggregateExec(
                MemoryScanExec([[cb]], cb.schema),
                keys=[(Col("g"), "g")],
                aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
                      (AggExpr(AggFn.COUNT_STAR, None), "c")],
                mode=AggMode.COMPLETE,
            )
            got = (
                run_plan(plan).to_pandas()
                .sort_values("g").reset_index(drop=True)
            )
            exp = (
                pd.DataFrame({"g": g, "v": v}).groupby("g")
                .agg(s=("v", "sum"), c=("v", "size")).reset_index()
            )
            assert len(got) == len(exp) == len(np.unique(g))
            assert (got["g"].to_numpy() == exp["g"].to_numpy()).all()
            assert (got["s"].to_numpy() == exp["s"].to_numpy()).all()
            assert (got["c"].to_numpy() == exp["c"].to_numpy()).all()
    finally:
        set_config(prior_cfg)
        if prior is not None:
            os.environ["BLAZE_AGG_TIER1"] = prior
