"""A full TPC-DS query distributed across 2 real worker PROCESSES with
REMOTE shuffle reads.

VERDICT r4 item 7: compose what exists - MiniCluster workers (separate
interpreters, disjoint private data dirs), __WORKER_LOCAL__ shuffle
outputs, and blz:// RemoteSegment block streams - into one multi-stage
TPC-DS query (q3: store_sales x date_dim x item -> brand revenue
rollup, tpcds_support.q3). Map tasks join map-side and hash-shuffle
into their claiming worker's PRIVATE directory; reduce tasks receive
RemoteSegment sources serialized INSIDE the TaskDefinition
(plan.proto ResourceSegmentsProto.remote_segments) and stream every
block over the writers' BlockServers - the reference's netty remote
shuffle-read path (SURVEY 2.4), with no shared data filesystem.
"""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.ops import (
    AggMode,
    FilterExec,
    HashAggregateExec,
    IpcReaderExec,
    IpcReadMode,
    ProjectExec,
    ShuffleWriterExec,
)
from blaze_tpu.ops.joins import HashJoinExec, JoinType
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.plan.serde import task_to_proto
from blaze_tpu.runtime.cluster import WORKER_LOCAL_PREFIX, MiniCluster
from blaze_tpu.runtime.transport import RemoteSegment

pytestmark = pytest.mark.skipif(
    os.environ.get("BLZ_SKIP_CLUSTER") == "1",
    reason="cluster tests disabled",
)

CLUSTER_ENV = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""}

N_REDUCE = 3


def test_q3_two_processes_remote_shuffle_reads(tmp_path):
    from tests.tpcds_support import gen_tables
    from tests.test_tpcds_queries import ORACLES

    tables = gen_tables()
    # two store_sales splits -> two map tasks (one per worker when both
    # are idle); dims replicated to every map (the reference's
    # broadcast-join distribution for q3)
    ss = tables["store_sales"]
    halves = np.array_split(np.arange(len(ss)), 2)
    paths = {}
    for name in ("date_dim", "item"):
        p = str(tmp_path / f"{name}.parquet")
        pq.write_table(
            pa.Table.from_pandas(tables[name], preserve_index=False), p
        )
        paths[name] = p
    ss_paths = []
    for i, idx in enumerate(halves):
        p = str(tmp_path / f"ss{i}.parquet")
        pq.write_table(
            pa.Table.from_pandas(
                ss.iloc[idx], preserve_index=False
            ), p,
        )
        ss_paths.append(p)

    def map_plan(mid: int):
        """q3 map side: BHJ date_dim + item onto one store_sales split,
        project the rollup columns, hash-shuffle by brand_id into the
        claiming worker's PRIVATE directory."""
        dates = FilterExec(
            ParquetScanExec([[FileRange(paths["date_dim"])]]),
            Col("d_moy") == 11,
        )
        items = FilterExec(
            ParquetScanExec([[FileRange(paths["item"])]]),
            Col("i_manufact_id") == 128,
        )
        j = HashJoinExec(
            dates, ParquetScanExec([[FileRange(ss_paths[mid])]]),
            ["d_date_sk"], ["ss_sold_date_sk"], JoinType.INNER,
        )
        j2 = HashJoinExec(
            items, j, ["i_item_sk"], ["ss_item_sk"], JoinType.INNER,
        )
        proj = ProjectExec(
            j2,
            [(Col("d_year"), "d_year"),
             (Col("i_brand_id"), "brand_id"),
             (Col("i_brand"), "brand"),
             (Col("ss_ext_sales_price"), "price")],
        )
        return ShuffleWriterExec(
            proj, [Col("brand_id")], N_REDUCE,
            WORKER_LOCAL_PREFIX + f"/q3-m{mid}.data",
            WORKER_LOCAL_PREFIX + f"/q3-m{mid}.index",
        )

    with MiniCluster(num_workers=2, env=CLUSTER_ENV) as cluster:
        plans = [map_plan(m) for m in range(2)]
        mid_schema = plans[0].children[0].schema
        _tables, metas = cluster.run_tasks(
            [task_to_proto(p, 0, f"q3-map-{m}")
             for m, p in enumerate(plans)],
            timeout=600, return_metas=True,
        )
        # every map wrote into a PRIVATE worker dir, exported only via
        # its BlockServer
        assert all(m and m["outputs"] for m in metas)
        for m in metas:
            for out in m["outputs"]:
                assert "blz-worker" in out["data"]

        # reduce tasks: the shuffle blocks ride the task proto as
        # RemoteSegments; whichever worker claims a reduce streams them
        # from BOTH writers' block servers over the blz:// fabric
        reduce_tasks = []
        for r in range(N_REDUCE):
            segs = []
            for m in metas:
                for out in m["outputs"]:
                    off, length = out["ranges"][r]
                    if length:
                        segs.append(RemoteSegment(
                            m["host"], m["port"], out["data"],
                            off, length,
                        ))
            reader = IpcReaderExec(
                f"q3-r{r}", mid_schema, N_REDUCE,
                IpcReadMode.CHANNEL_AND_FILE_SEGMENT,
            )
            agg = HashAggregateExec(
                reader,
                keys=[(Col("d_year"), "d_year"),
                      (Col("brand_id"), "brand_id"),
                      (Col("brand"), "brand")],
                aggs=[(AggExpr(AggFn.SUM, Col("price")), "sum_agg")],
                mode=AggMode.COMPLETE,
            )
            reduce_tasks.append(task_to_proto(
                agg, r, f"q3-reduce-{r}",
                file_resources={f"q3-r{r}": segs},
            ))
        parts = cluster.run_tasks(reduce_tasks, timeout=600)

    got = pd.concat(
        [t.to_pandas() for t in parts if t.num_rows], ignore_index=True
    )
    # hash(brand_id) partitioning keeps each (year, brand) group in
    # exactly one reducer
    assert not got.duplicated(["d_year", "brand_id", "brand"]).any()
    # driver-side final order: q3's ORDER BY d_year, sum_agg DESC,
    # brand_id LIMIT 100 over the handful of surviving groups
    got = got.sort_values(
        ["d_year", "sum_agg", "brand_id"],
        ascending=[True, False, True],
    ).head(100).reset_index(drop=True)

    exp = ORACLES["q3"](tables).reset_index(drop=True)
    exp_cols = list(exp.columns)
    got = got[exp_cols]
    assert len(got) == len(exp)
    for c in exp_cols:
        if exp[c].dtype.kind == "f" or got[c].dtype.kind == "f":
            assert np.allclose(
                got[c].astype(float).to_numpy(),
                exp[c].astype(float).to_numpy(),
                rtol=1e-6, equal_nan=True,
            ), c
        else:
            assert got[c].tolist() == exp[c].tolist(), c
