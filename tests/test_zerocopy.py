"""Zero-copy serve path (ISSUE 17): decoded-plan cache, shared-memory
Arrow arena, scatter-gather streaming.

The differential oracle throughout: the arena paths (scatter-gather
frames, leased handle) must be BYTE-IDENTICAL on the wire - and
batch-identical after decode - to the socket byte path they replace,
including mid-stream resume, and every arena failure (chaos seams
`zerocopy.map` / `zerocopy.lease`, stale leases, missing segments)
must degrade to the byte path with zero client-visible failures."""

import os
import socket
import struct
import threading
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.ops import AggMode, FilterExec, HashAggregateExec
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.plan.serde import task_to_proto
from blaze_tpu.runtime.gateway import TaskGatewayServer, _FLAG_SERVICE
from blaze_tpu.service import QueryService, ServiceClient
from blaze_tpu.service import wire
from blaze_tpu.testing import chaos
from blaze_tpu.testing.chaos import Fault
from blaze_tpu.zerocopy import (
    ArrowArena,
    DecodedPlanCache,
    PlanEntry,
    map_handle_frames,
    plan_digest,
)
from tests.test_service import GatedScan, wait_for

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")


@pytest.fixture
def dataset(tmp_path):
    rng = np.random.default_rng(29)
    p = str(tmp_path / "zc.parquet")
    pq.write_table(
        pa.table({
            "k": pa.array(rng.integers(0, 16, 4000), pa.int32()),
            "v": pa.array(rng.random(4000), pa.float64()),
        }),
        p,
    )
    return p


def agg_blob(path, threshold=0.5):
    plan = HashAggregateExec(
        FilterExec(ParquetScanExec([[FileRange(path)]]),
                   Col("v") > threshold),
        keys=[(Col("k"), "k")],
        aggs=[(AggExpr(AggFn.SUM, Col("v")), "s")],
        mode=AggMode.COMPLETE,
    )
    return task_to_proto(plan, 0)


def multipart_blob(path):
    """A 2-partition filter plan: its result has one part per
    partition, which the resume tests need."""
    plan = FilterExec(
        ParquetScanExec([[FileRange(path)], [FileRange(path)]]),
        Col("v") > 0.5,
    )
    return task_to_proto(plan, 0)


def table_of(batches):
    return pa.Table.from_batches(list(batches)).sort_by(
        [(c, "ascending") for c in batches[0].schema.names]
    )


# ---------------------------------------------------------------------------
# plan digest + decoded-plan cache units
# ---------------------------------------------------------------------------


def test_plan_digest_is_the_router_affinity_key():
    """One digest, two caches: the router's routing key and the
    service's decoded-plan-cache key must stay the same function, or
    the forwarded meta["plan_digest"] would miss every probe."""
    from blaze_tpu.router.placement import affinity_key

    blob = b"\x01\x02task-bytes"
    assert affinity_key(blob, False) == plan_digest(blob, False)
    assert affinity_key(blob, True) == plan_digest(blob, True)
    assert plan_digest(blob, True) != plan_digest(blob, False)
    assert plan_digest(blob, False) != plan_digest(blob + b"x", False)


def test_plan_cache_lru_eviction_and_counters():
    pc = DecodedPlanCache(max_entries=2)
    for i in range(3):
        pc.put(f"k{i}", PlanEntry(fingerprint=f"f{i}",
                                  fingerprint_stable=True,
                                  estimated_bytes=10, partition=0))
    assert len(pc) == 2
    st = pc.stats()
    assert st["evictions"] == 1 and st["puts"] == 3
    assert pc.get("k0") is None  # the LRU victim
    assert pc.get("k2") is not None
    st = pc.stats()
    assert st["misses"] == 1 and st["hits"] == 1


def test_plan_entry_tree_loan_is_exclusive():
    """The decoded tree is mutated in place by plan preparation, so
    the cache loans it to at most ONE borrower; a consumed tree never
    returns and later hits re-decode lazily."""
    e = PlanEntry(fingerprint="f", fingerprint_stable=True,
                  estimated_bytes=1, partition=0)
    tree = object()
    e.restore_tree(tree)
    assert e.borrow_tree() is tree
    assert e.borrow_tree() is None  # loaned out: second borrower misses
    other = object()
    e.restore_tree(other)
    assert e.borrow_tree() is other


def test_plan_cache_put_first_writer_wins():
    pc = DecodedPlanCache()
    a = PlanEntry(fingerprint="fa", fingerprint_stable=True,
                  estimated_bytes=1, partition=0)
    b = PlanEntry(fingerprint="fb", fingerprint_stable=True,
                  estimated_bytes=1, partition=0)
    assert pc.put("k", a) is a
    assert pc.put("k", b) is a  # racing writer adopts the winner


# ---------------------------------------------------------------------------
# arena units: publish / serve / evict / lease / reap
# ---------------------------------------------------------------------------


def _frames(n=3, size=100):
    return [bytes([i]) * (size + i) for i in range(n)]


def test_arena_publish_buffers_roundtrip(tmp_path):
    ar = ArrowArena(directory=str(tmp_path / "a"), max_bytes=1 << 20)
    frames = _frames()
    assert ar.publish("key", frames)
    views = ar.buffers("key")
    assert [bytes(v) for v in views] == frames
    assert ar.buffers("key", start_part=2) == [
        memoryview(frames[2])
    ]
    assert ar.buffers("missing") is None
    assert "key" in ar and "missing" not in ar
    assert not ar.publish("key", frames)  # idempotent: first wins
    assert ar.stats()["publish_skipped"] == 1
    ar.close()


def test_arena_lru_eviction_spares_leased_segments(tmp_path):
    frames = [b"x" * 100]
    ar = ArrowArena(directory=str(tmp_path / "a"), max_bytes=250)
    assert ar.publish("k1", frames)
    h = ar.handle("k1")
    assert h is not None
    assert ar.publish("k2", frames)
    assert ar.publish("k3", frames)  # over budget: k2 (unleased) goes
    assert "k1" in ar  # pinned by the lease
    assert "k2" not in ar
    assert ar.stats()["evictions"] == 1
    ar.release(h["lease"])
    assert ar.publish("k4", frames)  # now k1 is evictable
    assert "k1" not in ar
    ar.close()


def test_arena_orphaned_lease_is_ttl_reaped(tmp_path):
    """A client that crashed before RELEASE must not pin its segment
    forever: the TTL reap expires the lease and the segment becomes
    evictable again."""
    ar = ArrowArena(directory=str(tmp_path / "a"), max_bytes=1 << 20,
                    lease_ttl_s=0.05)
    assert ar.publish("k", _frames())
    h = ar.handle("k")
    assert h is not None and ar.stats()["active_leases"] == 1
    time.sleep(0.08)
    assert ar.reap() == 1
    st = ar.stats()
    assert st["active_leases"] == 0
    assert st["lease_orphans_reaped"] == 1
    # the reaped lease id is dead: release answers False
    assert not ar.release(h["lease"])
    ar.close()


def test_map_handle_frames_roundtrip_and_stale_lease(tmp_path):
    ar = ArrowArena(directory=str(tmp_path / "a"))
    frames = _frames()
    assert ar.publish("k", frames)
    h = ar.handle("k")
    assert map_handle_frames(h) == frames
    assert h["start_part"] == 0
    # a skip handle carries only the remaining frames
    h2 = ar.handle("k", start_part=1)
    assert map_handle_frames(h2) == frames[1:]
    # stale lease: segment file gone or truncated -> raise, never
    # silently serve wrong bytes
    with open(h["path"], "wb") as f:
        f.write(b"tiny")
    with pytest.raises(Exception):
        map_handle_frames(h)
    os.unlink(h["path"])
    with pytest.raises(Exception):
        map_handle_frames(h)
    ar.close()


def test_arena_close_removes_segment_files(tmp_path):
    d = str(tmp_path / "a")
    ar = ArrowArena(directory=d)
    ar.publish("k", _frames())
    paths = [s.path for s in ar._segments.values()]
    ar.close()
    assert ar.buffers("k") is None
    for p in paths:
        assert not os.path.exists(p)


# ---------------------------------------------------------------------------
# plan-cache service integration: exact decode-span counters
# ---------------------------------------------------------------------------


def test_plan_cache_hit_zero_plan_decode_spans(dataset):
    """The acceptance pin, dispatch-budget style: the FIRST submit of
    a blob pays exactly one plan_decode span; a byte-identical repeat
    pays exactly ZERO (no protobuf walk at all on the hit path)."""
    blob = agg_blob(dataset)

    def plan_decode_spans(q):
        return sum(1 for s in q.tracer.to_dicts()
                   if s["name"] == "plan_decode")

    with QueryService(max_concurrency=1, enable_trace=True) as svc:
        q1 = svc.submit_task(blob)
        assert q1.wait(60.0) and q1.state.value == "DONE", q1.error
        assert plan_decode_spans(q1) == 1
        q2 = svc.submit_task(blob)
        assert q2.wait(60.0) and q2.state.value == "DONE", q2.error
        assert plan_decode_spans(q2) == 0
        st = svc.stats()["plan_cache"]
        assert st["misses"] == 1 and st["hits"] == 1
        assert st["puts"] == 1 and st["entries"] == 1


def test_plan_cache_repeat_executes_correctly_without_result_cache(
    dataset,
):
    """With the ResultCache off, a plan-cache hit still EXECUTES - via
    the loaned tree or a lazy re-decode - and must produce the same
    result as the first run."""
    blob = agg_blob(dataset)
    with QueryService(max_concurrency=1, enable_cache=False) as svc:
        q1 = svc.submit_task(blob, use_cache=False)
        assert q1.wait(60.0) and q1.state.value == "DONE", q1.error
        q2 = svc.submit_task(blob, use_cache=False)
        assert q2.wait(60.0) and q2.state.value == "DONE", q2.error
        assert table_of(q1.result).equals(table_of(q2.result))
        st = svc.stats()["plan_cache"]
        assert st["hits"] == 1 and st["misses"] == 1


def test_plan_digest_forwarded_by_router(dataset):
    """The router forwards its routing key as meta["plan_digest"]; the
    replica's plan cache probes with it (hit on the repeat) without
    re-hashing the blob."""
    from blaze_tpu.router.proxy import Router

    blob = agg_blob(dataset)
    svc = QueryService(max_concurrency=2)
    srv = TaskGatewayServer(service=svc).start()
    router = Router(["%s:%d" % srv.address], poll_interval_s=0.1,
                    start=False)
    router.registry.poll_now()
    try:
        for _ in range(2):
            resp = router.submit({"use_cache": True}, blob)
            qid = resp["query_id"]
            assert wait_for(
                lambda: router.poll(qid)["state"] == "DONE", 60.0
            ), router.poll(qid)
        st = svc.stats()["plan_cache"]
        assert st["hits"] == 1 and st["misses"] == 1
    finally:
        router.close()
        srv.stop()
        svc.close()


# ---------------------------------------------------------------------------
# the differential oracle: arena wire bytes == socket wire bytes
# ---------------------------------------------------------------------------


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        b = sock.recv(n - len(buf))
        if not b:
            raise ConnectionError("eof")
        buf += b
    return buf


def _raw_fetch(addr, qid, arena_bit=False):
    """One FETCH over a raw socket, returning the exact byte stream
    (every length-framed part + the terminator). Arena-handle escapes
    fail the calling test - this helper is the BYTE path oracle."""
    s = socket.create_connection(addr, timeout=30)
    try:
        s.sendall(_U64.pack(_FLAG_SERVICE))
        q = qid.encode("utf-8")
        t = wire._FETCH_ARENA if arena_bit else 0
        s.sendall(bytes([wire.VERB_FETCH]) + _U32.pack(len(q)) + q
                  + _U32.pack(t))
        out = b""
        while True:
            head = _recv_exact(s, 8)
            (ln,) = _U64.unpack(head)
            out += head
            if ln == 0:
                return out
            assert ln not in (wire._ERR, wire._ARENA), hex(ln)
            out += _recv_exact(s, ln)
    finally:
        s.close()


@pytest.mark.parametrize("wire_mode", ["threaded", "async"])
def test_sg_fetch_byte_identical_to_socket_fetch(dataset, wire_mode):
    """The scatter-gather arena path must put the EXACT same bytes on
    the wire as the per-batch re-encode path it short-circuits - same
    frames, same framing, same terminator - on both wire planes."""
    blob = multipart_blob(dataset)
    svc = QueryService(max_concurrency=1, arena_bytes=32 << 20)
    with TaskGatewayServer(service=svc, wire=wire_mode) as srv:
        with ServiceClient(*srv.address) as c:
            qid = c.submit(blob)["query_id"]
            assert wait_for(
                lambda: c.poll(qid)["state"] == "DONE", 60.0
            )
            # wait for the terminal hook's arena publish
            assert wait_for(
                lambda: svc.arena.stats()["segments"] > 0, 10.0
            )
        arena_stream = _raw_fetch(srv.address, qid)
        assert svc.arena.stats()["sg_serves"] >= 1
        saved, svc.arena = svc.arena, None
        try:
            byte_stream = _raw_fetch(srv.address, qid)
        finally:
            svc.arena = saved
        assert arena_stream == byte_stream
    svc.close()


def test_handle_fetch_batches_identical_to_socket(dataset):
    """The shm handle path decodes to exactly the batches the socket
    path yields, and the lease is released after the map."""
    blob = multipart_blob(dataset)
    svc = QueryService(max_concurrency=1, arena_bytes=32 << 20)
    with TaskGatewayServer(service=svc) as srv:
        with ServiceClient(*srv.address) as c:
            qid = c.submit(blob)["query_id"]
            socket_batches = c.fetch(qid)
        assert wait_for(
            lambda: svc.arena.stats()["segments"] > 0, 10.0
        )
        with ServiceClient(*srv.address, use_arena=True) as c:
            shm_batches = c.fetch(qid)
        st = svc.arena.stats()
        assert st["handle_hits"] >= 1
        assert st["lease_releases"] >= 1 and st["active_leases"] == 0
        assert table_of(socket_batches).equals(table_of(shm_batches))
    svc.close()


def test_handle_fetch_resumes_mid_stream(dataset):
    """Count-based resume onto the handle path: the handle always
    covers ALL parts; a client that already yielded k parts on the
    byte path skips the first k frames itself."""
    blob = multipart_blob(dataset)
    svc = QueryService(max_concurrency=1, arena_bytes=32 << 20)
    with TaskGatewayServer(service=svc) as srv:
        with ServiceClient(*srv.address) as c:
            qid = c.submit(blob)["query_id"]
            full = c.fetch(qid)
        assert wait_for(
            lambda: svc.arena.stats()["segments"] > 0, 10.0
        )
        with ServiceClient(*srv.address, use_arena=True) as c:
            resumed = list(c._fetch_parts(qid, 0, skip=1))
        assert svc.arena.stats()["handle_hits"] >= 1
        # part 0's batches are skipped, the rest byte-identical
        n_skipped = len(full) - len(resumed)
        assert n_skipped >= 1
        for a, b in zip(full[n_skipped:], resumed):
            assert a.equals(b)
    svc.close()


def test_client_map_failure_falls_back_to_byte_refetch(dataset):
    """A handle the client cannot map (segment file vanished - the
    not-co-located / stale-lease case) degrades to a byte-path
    re-FETCH on the same connection: same batches, zero errors."""
    blob = multipart_blob(dataset)
    svc = QueryService(max_concurrency=1, arena_bytes=32 << 20)
    with TaskGatewayServer(service=svc) as srv:
        with ServiceClient(*srv.address) as c:
            qid = c.submit(blob)["query_id"]
            expect = c.fetch(qid)
        assert wait_for(
            lambda: svc.arena.stats()["segments"] > 0, 10.0
        )
        # yank the segment file out from under the client's mmap;
        # the server's own mapping (already open) keeps serving sg
        for seg in svc.arena._segments.values():
            os.rename(seg.path, seg.path + ".gone")
        try:
            with ServiceClient(*srv.address, use_arena=True) as c:
                got = c.fetch(qid)
        finally:
            for seg in svc.arena._segments.values():
                os.rename(seg.path + ".gone", seg.path)
        assert table_of(expect).equals(table_of(got))
        # the failed lease was still released (no orphan left behind)
        assert svc.arena.stats()["active_leases"] == 0
    svc.close()


# ---------------------------------------------------------------------------
# admission fast path: cached repeats bypass the byte-reservation queue
# ---------------------------------------------------------------------------


def test_queued_fleet_still_serves_cached_repeat(dataset):
    """The acceptance pin: a fleet saturated with queued work (both
    admission slots held, more queued behind them) still answers a
    cached repeat immediately - the fast path bypasses the
    byte-reservation queue entirely."""
    blob = agg_blob(dataset)
    release = threading.Event()
    blocker = GatedScan(release)
    with QueryService(max_concurrency=1) as svc:
        # warm the result cache while the fleet is idle
        q0 = svc.submit_task(blob)
        assert q0.wait(60.0) and q0.state.value == "DONE", q0.error
        # saturate: one RUNNING (gated), one QUEUED behind it
        qb = svc.submit_plan(blocker, estimated_bytes=0,
                             use_cache=False)
        assert blocker.started.wait(10.0)
        qq = svc.submit_plan(GatedScan(threading.Event()),
                             estimated_bytes=0, use_cache=False)
        try:
            assert qq.state.value == "QUEUED"
            q2 = svc.submit_task(blob)
            # served from cache while the queue is wedged
            assert q2.wait(10.0) and q2.state.value == "DONE", (
                q2.state, q2.error
            )
            assert svc.obs_counters["fast_path_serves"] == 1
            assert table_of(q0.result).equals(table_of(q2.result))
            # the blocker is still running, the queue untouched
            assert qb.state.value == "RUNNING"
            assert qq.state.value == "QUEUED"
        finally:
            release.set()
            svc.cancel(qq.query_id)
            qb.wait(30.0)
            qq.wait(30.0)


def test_fast_path_skipped_when_cache_cannot_cover(dataset):
    """A first-seen plan (no cached result) never takes the fast
    path - it queues like any other submission."""
    blob = agg_blob(dataset, threshold=0.123)
    with QueryService(max_concurrency=1) as svc:
        q = svc.submit_task(blob)
        assert q.wait(60.0) and q.state.value == "DONE", q.error
        assert svc.obs_counters["fast_path_serves"] == 0


# ---------------------------------------------------------------------------
# chaos: the zerocopy seams degrade to the byte path
# ---------------------------------------------------------------------------


def test_chaos_map_fault_degrades_publish_to_byte_path(dataset):
    """`zerocopy.map` firing at publish time means NO arena segment -
    and the serve path silently stays on the socket byte path with
    zero client-visible failures."""
    blob = agg_blob(dataset)
    svc = QueryService(max_concurrency=1, arena_bytes=32 << 20)
    with chaos.active([
        Fault(site="zerocopy.map", klass="TRANSIENT", times=0),
    ], seed=17):
        with TaskGatewayServer(service=svc) as srv:
            with ServiceClient(*srv.address, use_arena=True) as c:
                qid = c.submit(blob)["query_id"]
                got = c.fetch(qid)
            assert got
    st = svc.arena.stats()
    assert st["segments"] == 0
    assert st["map_failures"] >= 1
    svc.close()


def test_chaos_lease_fault_degrades_handle_to_sg_bytes(dataset):
    """`zerocopy.lease` firing at handle-grant time: the server
    answers scatter-gather bytes instead of a handle - the client
    (which asked for a handle) never notices."""
    blob = agg_blob(dataset)
    svc = QueryService(max_concurrency=1, arena_bytes=32 << 20)
    with TaskGatewayServer(service=svc) as srv:
        with ServiceClient(*srv.address) as c:
            qid = c.submit(blob)["query_id"]
            expect = c.fetch(qid)
        assert wait_for(
            lambda: svc.arena.stats()["segments"] > 0, 10.0
        )
        with chaos.active([
            Fault(site="zerocopy.lease", klass="TRANSIENT", times=0),
        ], seed=19):
            with ServiceClient(*srv.address, use_arena=True) as c:
                got = c.fetch(qid)
        st = svc.arena.stats()
        assert st["lease_faults"] >= 1
        assert st["sg_serves"] >= 1
        assert table_of(expect).equals(table_of(got))
    svc.close()


def test_parquet_mmap_falls_back_under_chaos(tmp_path):
    """LocalStore.open_input serves an mmap'd parquet page buffer by
    default; the `zerocopy.map` seam (or BLAZE_PARQUET_MMAP=0)
    degrades it to the buffered-read path - both read identically."""
    import pyarrow.lib as palib

    from blaze_tpu.io.object_store import LocalStore

    p = str(tmp_path / "m.parquet")
    pq.write_table(pa.table({"a": list(range(64))}), p)
    store = LocalStore()
    f = store.open_input(p)
    assert isinstance(f, palib.MemoryMappedFile)
    assert pq.read_table(f).equals(pq.read_table(p))
    with chaos.active([
        Fault(site="zerocopy.map", klass="TRANSIENT", times=0),
    ], seed=23):
        f2 = store.open_input(p)
    assert not isinstance(f2, palib.MemoryMappedFile)
    with f2:
        assert pq.read_table(f2).equals(pq.read_table(p))
    os.environ["BLAZE_PARQUET_MMAP"] = "0"
    try:
        f3 = store.open_input(p)
        assert not isinstance(f3, palib.MemoryMappedFile)
        f3.close()
    finally:
        del os.environ["BLAZE_PARQUET_MMAP"]


# ---------------------------------------------------------------------------
# obs surfaces
# ---------------------------------------------------------------------------


def test_stats_and_metrics_carry_zerocopy_counters(dataset):
    blob = agg_blob(dataset)
    svc = QueryService(max_concurrency=1, arena_bytes=32 << 20)
    with TaskGatewayServer(service=svc) as srv:
        with ServiceClient(*srv.address) as c:
            qid = c.submit(blob)["query_id"]
            c.fetch(qid)
            c.fetch(c.submit(blob)["query_id"])
            st = c.stats()
            assert st["plan_cache"]["hits"] == 1
            assert st["arena"]["published"] >= 1
            text = c.metrics()
    assert "blaze_plan_cache_events_total" in text
    assert "blaze_arena_events_total" in text
    assert "blaze_service_fast_path_serves_total" in text
    svc.close()


def test_plan_decode_phase_rolls_up_split_from_arrow_decode(dataset):
    """The decode phase split: plan_decode (protobuf walk) and
    arrow_decode (parquet pages) roll up as SEPARATE phases."""
    from blaze_tpu.obs import phases

    blob = agg_blob(dataset, threshold=0.31)
    phases.ROLLUP._reset_for_tests()
    with QueryService(max_concurrency=1, enable_cache=False,
                      enable_trace=True) as svc:
        for _ in range(2):
            q = svc.submit_task(blob, use_cache=False)
            assert q.wait(60.0) and q.state.value == "DONE", q.error
    snap = phases.ROLLUP.snapshot()[phases.ALL_CLASS]
    assert "plan_decode" in snap and "arrow_decode" in snap
    assert "decode" not in snap
