"""Native device window function tests (differential vs pandas)."""

import numpy as np
import pandas as pd
import pytest

from blaze_tpu import ColumnBatch
from blaze_tpu.exprs import Col
from blaze_tpu.ops import ExecContext, MemoryScanExec
from blaze_tpu.ops.sort import SortKey
from blaze_tpu.ops.window import WindowExec, WindowFn
from blaze_tpu.runtime.executor import run_plan


def scan_of(df):
    import pyarrow as pa

    return MemoryScanExec.from_batches(
        [ColumnBatch.from_arrow(
            pa.RecordBatch.from_pandas(df, preserve_index=False)
        )]
    )


@pytest.fixture
def df():
    rng = np.random.default_rng(77)
    return pd.DataFrame(
        {
            "k": rng.integers(0, 5, 60),
            "o": rng.integers(0, 20, 60),
            "v": rng.integers(-10, 50, 60),
        }
    )


def run_window(df, fns):
    op = WindowExec(
        scan_of(df),
        partition_by=[Col("k")],
        order_by=[SortKey(Col("o"))],
        functions=fns,
    )
    return run_plan(op).to_pandas()


def test_multiple_functions_one_pass(df):
    out = run_window(
        df,
        [
            WindowFn("row_number", None, "rn"),
            WindowFn("rank", None, "rk"),
            WindowFn("dense_rank", None, "dr"),
            WindowFn("sum", Col("v"), "sv"),
            WindowFn("min", Col("v"), "mn"),
            WindowFn("max", Col("v"), "mx"),
            WindowFn("count", Col("v"), "cnt"),
            WindowFn("avg", Col("v"), "av"),
        ],
    )
    g = df.sort_values(["k", "o"], kind="stable")
    ref = g.copy()
    grp = g.groupby("k", sort=False)
    ref["rn"] = grp.cumcount() + 1
    ref["rk"] = grp["o"].rank(method="min").astype(int)
    ref["dr"] = grp["o"].rank(method="dense").astype(int)
    ref["sv"] = grp["v"].transform("sum")
    ref["mn"] = grp["v"].transform("min")
    ref["mx"] = grp["v"].transform("max")
    ref["cnt"] = grp["v"].transform("count")
    ref["av"] = grp["v"].transform("mean")

    # align by (k, o, rn) - unique per row
    out_s = out.sort_values(["k", "o", "rn"]).reset_index(drop=True)
    ref_s = ref.sort_values(["k", "o", "rn"]).reset_index(drop=True)
    for c in ["rn", "rk", "dr", "sv", "mn", "mx", "cnt"]:
        np.testing.assert_array_equal(
            out_s[c].to_numpy(), ref_s[c].to_numpy(), err_msg=c
        )
    np.testing.assert_allclose(out_s["av"], ref_s["av"])


def test_lag_lead(df):
    out = run_window(
        df,
        [WindowFn("lag", Col("v"), "lg"), WindowFn("lead", Col("v"), "ld")],
    )
    g = df.sort_values(["k", "o"], kind="stable")
    grp = g.groupby("k", sort=False)
    ref = g.copy()
    ref["lg"] = grp["v"].shift(1)
    ref["ld"] = grp["v"].shift(-1)
    out_s = out.sort_values(["k", "o", "v"]).reset_index(drop=True)
    ref_s = ref.sort_values(["k", "o", "v"]).reset_index(drop=True)
    # lag/lead within ties of (k,o,v) may reorder; compare per-partition
    # multisets instead
    for k in df.k.unique():
        a = sorted(
            (x for x in out_s[out_s.k == k]["lg"].tolist()
             if x == x), key=float,
        )
        b = sorted(
            (x for x in ref_s[ref_s.k == k]["lg"].tolist()
             if x == x), key=float,
        )
        assert a == b, k


def test_global_window_no_partition(df):
    op = WindowExec(
        scan_of(df),
        partition_by=[],
        order_by=[SortKey(Col("o")), SortKey(Col("v"))],
        functions=[WindowFn("row_number", None, "rn")],
    )
    out = run_plan(op).to_pandas()
    assert sorted(out["rn"]) == list(range(1, 61))
    srt = out.sort_values("rn")
    assert srt["o"].is_monotonic_increasing or True
    # rn order must follow (o, v) order
    ov = list(zip(srt.o, srt.v))
    assert ov == sorted(ov)


# ---------------------------------------------------------------------------
# round-2 surface: frames, ntile/percent_rank/cume_dist, lag/lead(k)
# ---------------------------------------------------------------------------

def _frame_df(seed=9, n=200):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "g": rng.integers(0, 5, n),
            "o": rng.permutation(n),
            "v": rng.integers(-50, 100, n).astype(np.int64),
        }
    )


def _run_window(df, fns):
    cb = ColumnBatch.from_pydict(
        {c: df[c].tolist() for c in df.columns}
    )
    op = WindowExec(
        MemoryScanExec.from_batches([cb]),
        partition_by=[Col("g")],
        order_by=[SortKey(Col("o"), True, True)],
        functions=fns,
    )
    out = run_plan(op).to_pandas()
    return out.sort_values(["g", "o"]).reset_index(drop=True)


def test_ntile_percent_rank_cume_dist():
    df = _frame_df()
    got = _run_window(
        df,
        [
            WindowFn("ntile", None, "nt", offset=4),
            WindowFn("percent_rank", None, "pr"),
            WindowFn("cume_dist", None, "cd"),
        ],
    )
    s = df.sort_values(["g", "o"]).reset_index(drop=True)
    gb = s.groupby("g")["o"]
    sizes = s.groupby("g")["o"].transform("size")
    exp_pr = (gb.rank(method="min") - 1) / (sizes - 1).clip(lower=1)
    exp_pr = exp_pr.where(sizes > 1, 0.0)
    exp_cd = gb.rank(method="max") / sizes
    assert np.allclose(got["pr"].values, exp_pr.values)
    assert np.allclose(got["cd"].values, exp_cd.values)

    def ntile_ref(size, rn, n=4):
        base, rem = size // n, size % n
        cutoff = rem * (base + 1)
        if rn <= cutoff:
            return (rn - 1) // (base + 1) + 1
        return rem + (rn - 1 - cutoff) // max(base, 1) + 1

    rns = gb.rank(method="first").astype(int).values
    exp_nt = [ntile_ref(s_, r_) for s_, r_ in zip(sizes.values, rns)]
    assert got["nt"].tolist() == exp_nt


def test_lag_lead_offset_k():
    df = _frame_df(seed=4)
    got = _run_window(
        df,
        [
            WindowFn("lag", Col("v"), "l2", offset=2),
            WindowFn("lead", Col("v"), "f3", offset=3),
        ],
    )
    s = df.sort_values(["g", "o"]).reset_index(drop=True)
    exp_l2 = s.groupby("g")["v"].shift(2)
    exp_f3 = s.groupby("g")["v"].shift(-3)
    assert (
        got["l2"].fillna(-999).tolist()
        == exp_l2.fillna(-999).astype(np.int64).tolist()
    )
    assert (
        got["f3"].fillna(-999).tolist()
        == exp_f3.fillna(-999).astype(np.int64).tolist()
    )


def test_rows_frame_bounded_sum_avg_count():
    df = _frame_df(seed=12)
    got = _run_window(
        df,
        [
            WindowFn("sum", Col("v"), "s", frame=("rows", 2, 1)),
            WindowFn("avg", Col("v"), "a", frame=("rows", 2, 1)),
            WindowFn("count", Col("v"), "c", frame=("rows", 2, 1)),
        ],
    )
    s = df.sort_values(["g", "o"]).reset_index(drop=True)
    exp = []
    for _, grp in s.groupby("g"):
        vs = grp["v"].tolist()
        for i in range(len(vs)):
            window = vs[max(0, i - 2): min(len(vs), i + 2)]
            exp.append((sum(window), len(window)))
    exp_sum = [e[0] for e in exp]
    exp_cnt = [e[1] for e in exp]
    assert got["s"].tolist() == exp_sum
    assert got["c"].tolist() == exp_cnt
    assert np.allclose(
        got["a"].values, np.array(exp_sum) / np.array(exp_cnt)
    )


def test_running_and_range_frames():
    # ROWS UNBOUNDED..CURRENT (running) and RANGE UNBOUNDED..CURRENT
    # (ties share) for sum/min/max
    df = pd.DataFrame(
        {
            "g": [1, 1, 1, 1, 2, 2],
            "o": [10, 20, 20, 30, 5, 5],
            "v": [1, 2, 3, 4, 10, 20],
        }
    )
    got = _run_window(
        df,
        [
            WindowFn("sum", Col("v"), "rs", frame=("rows", None, 0)),
            WindowFn("min", Col("v"), "rm", frame=("rows", None, 0)),
            WindowFn("sum", Col("v"), "gs", frame=("range", None, 0)),
            WindowFn("max", Col("v"), "gm", frame=("range", None, 0)),
        ],
    )
    assert got["rs"].tolist() == [1, 3, 6, 10, 10, 30]
    assert got["rm"].tolist() == [1, 1, 1, 1, 10, 10]
    # RANGE: ties share the run-end frame
    assert got["gs"].tolist() == [1, 6, 6, 10, 30, 30]
    assert got["gm"].tolist() == [1, 3, 3, 4, 20, 20]


def test_window_fn_serde_roundtrip(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
    from blaze_tpu.plan.serde import plan_from_proto, plan_to_proto

    p = str(tmp_path / "w.parquet")
    pq.write_table(pa.table({"g": [1, 1, 2], "v": [1.0, 2.0, 3.0]}), p)
    op = WindowExec(
        ParquetScanExec([[FileRange(p)]]),
        partition_by=[Col("g")],
        order_by=[SortKey(Col("v"), True, True)],
        functions=[
            WindowFn("lag", Col("v"), "l", offset=3),
            WindowFn("sum", Col("v"), "s", frame=("rows", 2, None)),
            WindowFn("ntile", None, "n", offset=5),
        ],
    )
    back = plan_from_proto(plan_to_proto(op))
    fns = back.functions
    assert fns[0].offset == 3
    assert fns[1].frame == ("rows", 2, None)
    assert fns[2].offset == 5
