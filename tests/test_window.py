"""Native device window function tests (differential vs pandas)."""

import numpy as np
import pandas as pd
import pytest

from blaze_tpu import ColumnBatch
from blaze_tpu.exprs import Col
from blaze_tpu.ops import ExecContext, MemoryScanExec
from blaze_tpu.ops.sort import SortKey
from blaze_tpu.ops.window import WindowExec, WindowFn
from blaze_tpu.runtime.executor import run_plan


def scan_of(df):
    import pyarrow as pa

    return MemoryScanExec.from_batches(
        [ColumnBatch.from_arrow(
            pa.RecordBatch.from_pandas(df, preserve_index=False)
        )]
    )


@pytest.fixture
def df():
    rng = np.random.default_rng(77)
    return pd.DataFrame(
        {
            "k": rng.integers(0, 5, 60),
            "o": rng.integers(0, 20, 60),
            "v": rng.integers(-10, 50, 60),
        }
    )


def run_window(df, fns):
    op = WindowExec(
        scan_of(df),
        partition_by=[Col("k")],
        order_by=[SortKey(Col("o"))],
        functions=fns,
    )
    return run_plan(op).to_pandas()


def test_multiple_functions_one_pass(df):
    out = run_window(
        df,
        [
            WindowFn("row_number", None, "rn"),
            WindowFn("rank", None, "rk"),
            WindowFn("dense_rank", None, "dr"),
            WindowFn("sum", Col("v"), "sv"),
            WindowFn("min", Col("v"), "mn"),
            WindowFn("max", Col("v"), "mx"),
            WindowFn("count", Col("v"), "cnt"),
            WindowFn("avg", Col("v"), "av"),
        ],
    )
    g = df.sort_values(["k", "o"], kind="stable")
    ref = g.copy()
    grp = g.groupby("k", sort=False)
    ref["rn"] = grp.cumcount() + 1
    ref["rk"] = grp["o"].rank(method="min").astype(int)
    ref["dr"] = grp["o"].rank(method="dense").astype(int)
    ref["sv"] = grp["v"].transform("sum")
    ref["mn"] = grp["v"].transform("min")
    ref["mx"] = grp["v"].transform("max")
    ref["cnt"] = grp["v"].transform("count")
    ref["av"] = grp["v"].transform("mean")

    # align by (k, o, rn) - unique per row
    out_s = out.sort_values(["k", "o", "rn"]).reset_index(drop=True)
    ref_s = ref.sort_values(["k", "o", "rn"]).reset_index(drop=True)
    for c in ["rn", "rk", "dr", "sv", "mn", "mx", "cnt"]:
        np.testing.assert_array_equal(
            out_s[c].to_numpy(), ref_s[c].to_numpy(), err_msg=c
        )
    np.testing.assert_allclose(out_s["av"], ref_s["av"])


def test_lag_lead(df):
    out = run_window(
        df,
        [WindowFn("lag", Col("v"), "lg"), WindowFn("lead", Col("v"), "ld")],
    )
    g = df.sort_values(["k", "o"], kind="stable")
    grp = g.groupby("k", sort=False)
    ref = g.copy()
    ref["lg"] = grp["v"].shift(1)
    ref["ld"] = grp["v"].shift(-1)
    out_s = out.sort_values(["k", "o", "v"]).reset_index(drop=True)
    ref_s = ref.sort_values(["k", "o", "v"]).reset_index(drop=True)
    # lag/lead within ties of (k,o,v) may reorder; compare per-partition
    # multisets instead
    for k in df.k.unique():
        a = sorted(
            (x for x in out_s[out_s.k == k]["lg"].tolist()
             if x == x), key=float,
        )
        b = sorted(
            (x for x in ref_s[ref_s.k == k]["lg"].tolist()
             if x == x), key=float,
        )
        assert a == b, k


def test_global_window_no_partition(df):
    op = WindowExec(
        scan_of(df),
        partition_by=[],
        order_by=[SortKey(Col("o")), SortKey(Col("v"))],
        functions=[WindowFn("row_number", None, "rn")],
    )
    out = run_plan(op).to_pandas()
    assert sorted(out["rn"]) == list(range(1, 61))
    srt = out.sort_values("rn")
    assert srt["o"].is_monotonic_increasing or True
    # rn order must follow (o, v) order
    ov = list(zip(srt.o, srt.v))
    assert ov == sorted(ov)
