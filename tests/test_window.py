"""Native device window function tests (differential vs pandas)."""

import numpy as np
import pandas as pd
import pytest

from blaze_tpu import ColumnBatch
from blaze_tpu.exprs import Col
from blaze_tpu.ops import ExecContext, MemoryScanExec
from blaze_tpu.ops.sort import SortKey
from blaze_tpu.ops.window import WindowExec, WindowFn
from blaze_tpu.runtime.executor import run_plan


def scan_of(df):
    import pyarrow as pa

    return MemoryScanExec.from_batches(
        [ColumnBatch.from_arrow(
            pa.RecordBatch.from_pandas(df, preserve_index=False)
        )]
    )


@pytest.fixture
def df():
    rng = np.random.default_rng(77)
    return pd.DataFrame(
        {
            "k": rng.integers(0, 5, 60),
            "o": rng.integers(0, 20, 60),
            "v": rng.integers(-10, 50, 60),
        }
    )


def run_window(df, fns):
    op = WindowExec(
        scan_of(df),
        partition_by=[Col("k")],
        order_by=[SortKey(Col("o"))],
        functions=fns,
    )
    return run_plan(op).to_pandas()


def test_multiple_functions_one_pass(df):
    out = run_window(
        df,
        [
            WindowFn("row_number", None, "rn"),
            WindowFn("rank", None, "rk"),
            WindowFn("dense_rank", None, "dr"),
            WindowFn("sum", Col("v"), "sv"),
            WindowFn("min", Col("v"), "mn"),
            WindowFn("max", Col("v"), "mx"),
            WindowFn("count", Col("v"), "cnt"),
            WindowFn("avg", Col("v"), "av"),
        ],
    )
    g = df.sort_values(["k", "o"], kind="stable")
    ref = g.copy()
    grp = g.groupby("k", sort=False)
    ref["rn"] = grp.cumcount() + 1
    ref["rk"] = grp["o"].rank(method="min").astype(int)
    ref["dr"] = grp["o"].rank(method="dense").astype(int)
    ref["sv"] = grp["v"].transform("sum")
    ref["mn"] = grp["v"].transform("min")
    ref["mx"] = grp["v"].transform("max")
    ref["cnt"] = grp["v"].transform("count")
    ref["av"] = grp["v"].transform("mean")

    # align by (k, o, rn) - unique per row
    out_s = out.sort_values(["k", "o", "rn"]).reset_index(drop=True)
    ref_s = ref.sort_values(["k", "o", "rn"]).reset_index(drop=True)
    for c in ["rn", "rk", "dr", "sv", "mn", "mx", "cnt"]:
        np.testing.assert_array_equal(
            out_s[c].to_numpy(), ref_s[c].to_numpy(), err_msg=c
        )
    np.testing.assert_allclose(out_s["av"], ref_s["av"])


def test_lag_lead(df):
    out = run_window(
        df,
        [WindowFn("lag", Col("v"), "lg"), WindowFn("lead", Col("v"), "ld")],
    )
    g = df.sort_values(["k", "o"], kind="stable")
    grp = g.groupby("k", sort=False)
    ref = g.copy()
    ref["lg"] = grp["v"].shift(1)
    ref["ld"] = grp["v"].shift(-1)
    out_s = out.sort_values(["k", "o", "v"]).reset_index(drop=True)
    ref_s = ref.sort_values(["k", "o", "v"]).reset_index(drop=True)
    # lag/lead within ties of (k,o,v) may reorder; compare per-partition
    # multisets instead
    for k in df.k.unique():
        a = sorted(
            (x for x in out_s[out_s.k == k]["lg"].tolist()
             if x == x), key=float,
        )
        b = sorted(
            (x for x in ref_s[ref_s.k == k]["lg"].tolist()
             if x == x), key=float,
        )
        assert a == b, k


def test_global_window_no_partition(df):
    op = WindowExec(
        scan_of(df),
        partition_by=[],
        order_by=[SortKey(Col("o")), SortKey(Col("v"))],
        functions=[WindowFn("row_number", None, "rn")],
    )
    out = run_plan(op).to_pandas()
    assert sorted(out["rn"]) == list(range(1, 61))
    srt = out.sort_values("rn")
    assert srt["o"].is_monotonic_increasing or True
    # rn order must follow (o, v) order
    ov = list(zip(srt.o, srt.v))
    assert ov == sorted(ov)


# ---------------------------------------------------------------------------
# round-2 surface: frames, ntile/percent_rank/cume_dist, lag/lead(k)
# ---------------------------------------------------------------------------

def _frame_df(seed=9, n=200):
    rng = np.random.default_rng(seed)
    return pd.DataFrame(
        {
            "g": rng.integers(0, 5, n),
            "o": rng.permutation(n),
            "v": rng.integers(-50, 100, n).astype(np.int64),
        }
    )


def _run_window(df, fns):
    cb = ColumnBatch.from_pydict(
        {c: df[c].tolist() for c in df.columns}
    )
    op = WindowExec(
        MemoryScanExec.from_batches([cb]),
        partition_by=[Col("g")],
        order_by=[SortKey(Col("o"), True, True)],
        functions=fns,
    )
    out = run_plan(op).to_pandas()
    return out.sort_values(["g", "o"]).reset_index(drop=True)


def test_ntile_percent_rank_cume_dist():
    df = _frame_df()
    got = _run_window(
        df,
        [
            WindowFn("ntile", None, "nt", offset=4),
            WindowFn("percent_rank", None, "pr"),
            WindowFn("cume_dist", None, "cd"),
        ],
    )
    s = df.sort_values(["g", "o"]).reset_index(drop=True)
    gb = s.groupby("g")["o"]
    sizes = s.groupby("g")["o"].transform("size")
    exp_pr = (gb.rank(method="min") - 1) / (sizes - 1).clip(lower=1)
    exp_pr = exp_pr.where(sizes > 1, 0.0)
    exp_cd = gb.rank(method="max") / sizes
    assert np.allclose(got["pr"].values, exp_pr.values)
    assert np.allclose(got["cd"].values, exp_cd.values)

    def ntile_ref(size, rn, n=4):
        base, rem = size // n, size % n
        cutoff = rem * (base + 1)
        if rn <= cutoff:
            return (rn - 1) // (base + 1) + 1
        return rem + (rn - 1 - cutoff) // max(base, 1) + 1

    rns = gb.rank(method="first").astype(int).values
    exp_nt = [ntile_ref(s_, r_) for s_, r_ in zip(sizes.values, rns)]
    assert got["nt"].tolist() == exp_nt


def test_lag_lead_offset_k():
    df = _frame_df(seed=4)
    got = _run_window(
        df,
        [
            WindowFn("lag", Col("v"), "l2", offset=2),
            WindowFn("lead", Col("v"), "f3", offset=3),
        ],
    )
    s = df.sort_values(["g", "o"]).reset_index(drop=True)
    exp_l2 = s.groupby("g")["v"].shift(2)
    exp_f3 = s.groupby("g")["v"].shift(-3)
    assert (
        got["l2"].fillna(-999).tolist()
        == exp_l2.fillna(-999).astype(np.int64).tolist()
    )
    assert (
        got["f3"].fillna(-999).tolist()
        == exp_f3.fillna(-999).astype(np.int64).tolist()
    )


def test_rows_frame_bounded_sum_avg_count():
    df = _frame_df(seed=12)
    got = _run_window(
        df,
        [
            WindowFn("sum", Col("v"), "s", frame=("rows", 2, 1)),
            WindowFn("avg", Col("v"), "a", frame=("rows", 2, 1)),
            WindowFn("count", Col("v"), "c", frame=("rows", 2, 1)),
        ],
    )
    s = df.sort_values(["g", "o"]).reset_index(drop=True)
    exp = []
    for _, grp in s.groupby("g"):
        vs = grp["v"].tolist()
        for i in range(len(vs)):
            window = vs[max(0, i - 2): min(len(vs), i + 2)]
            exp.append((sum(window), len(window)))
    exp_sum = [e[0] for e in exp]
    exp_cnt = [e[1] for e in exp]
    assert got["s"].tolist() == exp_sum
    assert got["c"].tolist() == exp_cnt
    assert np.allclose(
        got["a"].values, np.array(exp_sum) / np.array(exp_cnt)
    )


def test_running_and_range_frames():
    # ROWS UNBOUNDED..CURRENT (running) and RANGE UNBOUNDED..CURRENT
    # (ties share) for sum/min/max
    df = pd.DataFrame(
        {
            "g": [1, 1, 1, 1, 2, 2],
            "o": [10, 20, 20, 30, 5, 5],
            "v": [1, 2, 3, 4, 10, 20],
        }
    )
    got = _run_window(
        df,
        [
            WindowFn("sum", Col("v"), "rs", frame=("rows", None, 0)),
            WindowFn("min", Col("v"), "rm", frame=("rows", None, 0)),
            WindowFn("sum", Col("v"), "gs", frame=("range", None, 0)),
            WindowFn("max", Col("v"), "gm", frame=("range", None, 0)),
        ],
    )
    assert got["rs"].tolist() == [1, 3, 6, 10, 10, 30]
    assert got["rm"].tolist() == [1, 1, 1, 1, 10, 10]
    # RANGE: ties share the run-end frame
    assert got["gs"].tolist() == [1, 6, 6, 10, 30, 30]
    assert got["gm"].tolist() == [1, 3, 3, 4, 20, 20]


def test_window_fn_serde_roundtrip(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
    from blaze_tpu.plan.serde import plan_from_proto, plan_to_proto

    p = str(tmp_path / "w.parquet")
    pq.write_table(pa.table({"g": [1, 1, 2], "v": [1.0, 2.0, 3.0]}), p)
    op = WindowExec(
        ParquetScanExec([[FileRange(p)]]),
        partition_by=[Col("g")],
        order_by=[SortKey(Col("v"), True, True)],
        functions=[
            WindowFn("lag", Col("v"), "l", offset=3),
            WindowFn("sum", Col("v"), "s", frame=("rows", 2, None)),
            WindowFn("ntile", None, "n", offset=5),
        ],
    )
    back = plan_from_proto(plan_to_proto(op))
    fns = back.functions
    assert fns[0].offset == 3
    assert fns[1].frame == ("rows", 2, None)
    assert fns[2].offset == 5


def _py_frame_ref(df, kind, frame, asc=True):
    """Brute-force per-row frame evaluation over (k, o)-sorted rows."""
    s = df.sort_values(["k", "o"], ascending=[True, asc],
                       kind="stable").reset_index(drop=True)
    ftype, lo, hi = frame
    out = []
    for i, row in s.iterrows():
        part = s[s.k == row.k]
        if ftype == "rows":
            pstart, pend = part.index[0], part.index[-1]
            l = pstart if lo is None else max(i - lo, pstart)
            r = pend if hi is None else min(i + hi, pend)
            win = s.loc[l:r, "v"]
        else:  # range with value offsets on the order column
            # lo = PRECEDING offset, hi = FOLLOWING offset; under desc
            # ordering "preceding" means larger order values
            if asc:
                lo_b = -np.inf if lo is None else (row.o - lo)
                hi_b = np.inf if hi is None else (row.o + hi)
            else:
                lo_b = -np.inf if hi is None else (row.o - hi)
                hi_b = np.inf if lo is None else (row.o + lo)
            win = part[(part.o >= lo_b) & (part.o <= hi_b)]["v"]
        if len(win) == 0:
            out.append(None)
        elif kind == "sum":
            out.append(int(win.sum()))
        elif kind == "min":
            out.append(int(win.min()))
        elif kind == "max":
            out.append(int(win.max()))
        elif kind == "count":
            out.append(len(win))
        else:
            out.append(float(win.mean()))
    return out


def test_bounded_sliding_minmax_rows_frames(df):
    """min/max over ROWS a PRECEDING..b FOLLOWING (sparse-table RMQ) -
    previously only the running frame was supported."""
    for frame in [("rows", 2, 2), ("rows", 0, 3), ("rows", 5, 0),
                  ("rows", None, 2), ("rows", 1, None)]:
        got = run_window(
            df,
            [WindowFn("min", Col("v"), "lo", frame=frame),
             WindowFn("max", Col("v"), "hi", frame=frame)],
        )
        assert got["lo"].tolist() == _py_frame_ref(df, "min", frame)
        assert got["hi"].tolist() == _py_frame_ref(df, "max", frame)


def test_range_value_offset_frames(df):
    """RANGE BETWEEN x PRECEDING AND y FOLLOWING with VALUE offsets on
    the order column: sum/avg/count/min/max; ties share frames. The
    order key must be narrow (int<=32/f32/date32) - int64 order keys
    stay host-tier."""
    df = df.assign(o=df["o"].astype(np.int32))
    for frame in [("range", 3, 3), ("range", 0, 5), ("range", 2, 0),
                  ("range", None, 4), ("range", 1, None)]:
        got = run_window(
            df,
            [WindowFn("sum", Col("v"), "s", frame=frame),
             WindowFn("count", Col("v"), "c", frame=frame),
             WindowFn("min", Col("v"), "lo", frame=frame),
             WindowFn("max", Col("v"), "hi", frame=frame),
             WindowFn("avg", Col("v"), "a", frame=frame)],
        )
        assert got["s"].tolist() == _py_frame_ref(df, "sum", frame)
        assert got["c"].tolist() == _py_frame_ref(df, "count", frame)
        assert got["lo"].tolist() == _py_frame_ref(df, "min", frame)
        assert got["hi"].tolist() == _py_frame_ref(df, "max", frame)
        ref_avg = _py_frame_ref(df, "avg", frame)
        for g, r in zip(got["a"].tolist(), ref_avg):
            assert (g is None) == (r is None)
            if r is not None:
                assert abs(g - r) < 1e-9


def test_range_value_offsets_descending_order():
    """DESC ordering: PRECEDING means larger order values."""
    df = pd.DataFrame({
        "k": [1, 1, 1, 1, 1],
        "o": np.array([10, 8, 8, 5, 1], np.int32),
        "v": [1, 2, 3, 4, 5],
    })
    op = WindowExec(
        scan_of(df),
        partition_by=[Col("k")],
        order_by=[SortKey(Col("o"), ascending=False)],
        functions=[WindowFn("sum", Col("v"), "s",
                            frame=("range", 2, 0))],
    )
    got = run_plan(op).to_pandas()
    # sorted desc by o: [10, 8, 8, 5, 1]; frame = o in [row.o, row.o+2]
    # o=10: {10} -> 1; o=8 (both): {10,8,8} -> 6; o=5: {5} -> 4;
    # o=1: {1} -> 5
    assert got["s"].tolist() == [1, 6, 6, 4, 5]


def test_range_value_offsets_float_order_key():
    df = pd.DataFrame({
        "k": np.ones(7, np.int32),
        "o": np.array([0.5, 1.0, 1.5, 2.5, 2.5, 4.0, 100.0],
                      np.float32),
        "v": np.arange(1, 8, dtype=np.int64),
    })
    op = WindowExec(
        scan_of(df),
        partition_by=[Col("k")],
        order_by=[SortKey(Col("o"))],
        functions=[WindowFn("sum", Col("v"), "s",
                            frame=("range", 1.0, 1.0))],
    )
    got = run_plan(op).to_pandas()
    exp = []
    for o in df["o"]:
        sel = df[(df.o >= o - 1.0) & (df.o <= o + 1.0)]
        exp.append(int(sel.v.sum()))
    assert got["s"].tolist() == exp


def test_range_value_offsets_with_null_order_rows():
    """Nulls-first NULL order rows with negative values after them:
    without the null-rank bit in the packed search keys the binary
    search corrupts every frame in the partition (review r4 repro)."""
    import pyarrow as pa

    df = pa.table({
        "k": pa.array([1, 1, 1], pa.int32()),
        "o": pa.array([None, -5, 3], pa.int32()),
        "v": pa.array([7, 1, 1], pa.int64()),
    })
    cb = ColumnBatch.from_arrow(df.to_batches()[0])
    op = WindowExec(
        MemoryScanExec([[cb]], cb.schema),
        partition_by=[Col("k")],
        order_by=[SortKey(Col("o"), ascending=True, nulls_first=True)],
        functions=[WindowFn("sum", Col("v"), "s",
                            frame=("range", 1, 1))],
    )
    got = run_plan(op).to_pandas()
    # null row's frame = its null peers (just itself); -5's frame =
    # {-5} only; 3's frame = {3}
    assert got["s"].tolist() == [7, 1, 1]


def test_range_value_offsets_int32_extreme_no_wrap():
    """Bounds saturate instead of wrapping at the dtype edge."""
    df = pd.DataFrame({
        "k": np.ones(3, np.int32),
        "o": np.array([2147483640, 2147483646, -2147483648],
                      np.int32),
        "v": np.array([1, 2, 4], np.int64),
    })
    op = WindowExec(
        scan_of(df),
        partition_by=[Col("k")],
        order_by=[SortKey(Col("o"))],
        functions=[WindowFn("sum", Col("v"), "s",
                            frame=("range", 0, 10))],
    )
    got = run_plan(op).to_pandas()
    # sorted: [-2^31, 2147483640, 2147483646]; frames: {-2^31}'s
    # [v, v+10] -> itself; 2147483640's [., +10] saturates at the max
    # and includes 2147483646; 2147483646's frame includes itself only
    assert got["s"].tolist() == [4, 3, 2]


def test_range_mixed_offset_unbounded_with_null_rows():
    """A NULL order row's OFFSET bound collapses to its null peer run,
    but an UNBOUNDED side still reaches the partition edge (review r4:
    both sides were wrongly clamped to the peer run)."""
    import pyarrow as pa

    df = pa.table({
        "k": pa.array([1, 1, 1, 1], pa.int32()),
        "o": pa.array([None, None, 2, 5], pa.int32()),
        "v": pa.array([10, 20, 1, 2], pa.int64()),
    })
    cb = ColumnBatch.from_arrow(df.to_batches()[0])
    op = WindowExec(
        MemoryScanExec([[cb]], cb.schema),
        partition_by=[Col("k")],
        order_by=[SortKey(Col("o"), ascending=True, nulls_first=True)],
        functions=[
            WindowFn("sum", Col("v"), "s",
                     frame=("range", 1, None)),   # x PREC .. UNB FOLL
            WindowFn("sum", Col("v"), "t",
                     frame=("range", None, 1)),   # UNB PREC .. y FOLL
        ],
    )
    got = run_plan(op).to_pandas()
    # rows sorted: [NULL(10), NULL(20), 2(1), 5(2)]
    # frame (1 PREC, UNB FOLL): null rows -> [peer-run start .. part
    # end] = 10+20+1+2 = 33; o=2 -> [2-1, end] = 3; o=5 -> [4, end] = 2
    assert got["s"].tolist() == [33, 33, 3, 2]
    # frame (UNB PREC, 1 FOLL): null rows -> [part start .. peer-run
    # end] = 30; o=2 -> [start, 3] = 31; o=5 -> [start, 6] = 33
    assert got["t"].tolist() == [30, 30, 31, 33]
