"""Reference wire-format compatibility: TaskDefinition bytes built with
the REFERENCE's own proto schema (plan.protobuf, reference
plan.proto:26-43/:508-513) decode and execute on this engine, matching
the engine-native-proto result — the SURVEY §7 "Spark tier stays
untouched" contract, proven the way the reference's own decoder tests
would (from_proto.rs:162-560 arms)."""

import os
import struct

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from blaze_tpu.exprs import AggExpr, AggFn, Col
from blaze_tpu.ops import (
    AggMode,
    FilterExec,
    HashAggregateExec,
    ProjectExec,
)
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.plan.refcompat import (
    execute_reference_task,
    plan_from_ref,
    task_from_reference_proto,
)
from blaze_tpu.plan.refpb import refplan_pb2 as rp
from blaze_tpu.plan.serde import task_to_proto
from blaze_tpu.runtime.executor import execute_task
from blaze_tpu.types import DataType


# ---------------------------------------------------------------------------
# reference-format message builders (what the Spark tier's proto emission
# produces, NativeParquetScanExec.scala:61-107 / NativeProjectExec.scala:61-77)
# ---------------------------------------------------------------------------

def _col(name):
    e = rp.PhysicalExprNode()
    e.column.name = name
    return e


def _lit_f32(v):
    e = rp.PhysicalExprNode()
    e.literal.float32_value = v
    return e


def _lit_i32(v):
    e = rp.PhysicalExprNode()
    e.literal.int32_value = v
    return e


def _bin(op, l, r):
    e = rp.PhysicalExprNode()
    e.binary_expr.op = op
    e.binary_expr.l.CopyFrom(l)
    e.binary_expr.r.CopyFrom(r)
    return e


def _cast_f32(inner):
    e = rp.PhysicalExprNode()
    e.cast.expr.CopyFrom(inner)
    e.cast.arrow_type.FLOAT32.SetInParent()
    return e


def _agg(fn, arg):
    e = rp.PhysicalExprNode()
    e.aggregate_expr.aggr_function = fn
    e.aggregate_expr.expr.CopyFrom(arg)
    return e


def _ref_schema(fields):
    s = rp.Schema()
    for name, ty in fields:
        f = s.columns.add()
        f.name = name
        f.nullable = True
        getattr(f.arrow_type, ty).SetInParent()
    return s


def _scan_node(path, fields, projection=None):
    node = rp.PhysicalPlanNode()
    conf = node.parquet_scan.base_conf
    g = conf.file_groups.add()
    f = g.files.add()
    f.path = path
    f.size = os.path.getsize(path)
    conf.schema.CopyFrom(_ref_schema(fields))
    if projection is not None:
        conf.projection.extend(projection)
    return node


@pytest.fixture(scope="module")
def store_sales(tmp_path_factory):
    rng = np.random.default_rng(7)
    n = 50_000
    item = rng.integers(0, 40, n).astype(np.int32)
    qty = rng.integers(1, 10, n).astype(np.int32)
    price = (rng.random(n) * 100).astype(np.float32)
    path = str(tmp_path_factory.mktemp("ref") / "store_sales.parquet")
    pq.write_table(
        pa.table({"item": item, "qty": qty, "price": price}),
        path,
        row_group_size=8192,
    )
    return path, item, qty, price


FIELDS = [("item", "INT32"), ("qty", "INT32"), ("price", "FLOAT32")]


def _q6_reference_task(path):
    """FINAL agg <- PARTIAL agg <- Projection <- Filter <- ParquetScan,
    the reference's canonical single-stage aggregation stack (DataFusion
    partial/final pair, from_proto.rs:452-545)."""
    scan = _scan_node(path, FIELDS)

    filt = rp.PhysicalPlanNode()
    filt.filter.input.CopyFrom(scan)
    filt.filter.expr.CopyFrom(
        _bin(
            "And",
            _bin("Gt", _col("price"), _lit_f32(50.0)),
            _bin("Lt", _col("qty"), _lit_i32(8)),
        )
    )

    proj = rp.PhysicalPlanNode()
    proj.projection.input.CopyFrom(filt)
    proj.projection.expr.append(
        _bin("Multiply", _col("price"), _cast_f32(_col("qty")))
    )
    proj.projection.expr_name.append("rev")
    proj.projection.expr.append(_col("item"))
    proj.projection.expr_name.append("item")

    partial = rp.PhysicalPlanNode()
    hp = partial.hash_aggregate
    hp.mode = rp.PARTIAL
    hp.input.CopyFrom(proj)
    hp.group_expr.append(_col("item"))
    hp.group_expr_name.append("item")
    hp.aggr_expr.append(_agg(rp.SUM, _col("rev")))
    hp.aggr_expr_name.append("total")
    hp.aggr_expr.append(_agg(rp.COUNT, _col("rev")))
    hp.aggr_expr_name.append("cnt")

    final = rp.PhysicalPlanNode()
    hf = final.hash_aggregate
    hf.mode = rp.FINAL
    hf.input.CopyFrom(partial)
    hf.group_expr.append(_col("item"))
    hf.group_expr_name.append("item")
    hf.aggr_expr.append(_agg(rp.SUM, _col("rev")))
    hf.aggr_expr_name.append("total")
    hf.aggr_expr.append(_agg(rp.COUNT, _col("cnt")))
    hf.aggr_expr_name.append("cnt")

    task = rp.TaskDefinition()
    task.task_id.job_id = "ref-q6"
    task.task_id.stage_id = 0
    task.task_id.partition_id = 0
    task.plan.CopyFrom(final)
    return task.SerializeToString()


def _q6_engine_task(path):
    scan = ParquetScanExec([[FileRange(path)]])
    plan = HashAggregateExec(
        ProjectExec(
            FilterExec(
                scan, (Col("price") > 50.0) & (Col("qty") < 8)
            ),
            [
                (Col("price") * Col("qty").cast(DataType.float32()),
                 "rev"),
                (Col("item"), "item"),
            ],
        ),
        keys=[(Col("item"), "item")],
        aggs=[
            (AggExpr(AggFn.SUM, Col("rev")), "total"),
            (AggExpr(AggFn.COUNT, Col("rev")), "cnt"),
        ],
        mode=AggMode.COMPLETE,
    )
    return task_to_proto(plan, 0)


def _rows(batches):
    tbl = pa.Table.from_batches(list(batches))
    d = {}
    for item, total, cnt in zip(
        tbl.column("item").to_pylist(),
        tbl.column("total").to_pylist(),
        tbl.column("cnt").to_pylist(),
    ):
        d[item] = (total, cnt)
    return d


def test_q6_reference_task_matches_engine_native(store_sales):
    path, item, qty, price = store_sales
    got = _rows(execute_reference_task(_q6_reference_task(path)))
    exp = _rows(execute_task(_q6_engine_task(path)))
    assert set(got) == set(exp)
    for k in exp:
        assert got[k][1] == exp[k][1], k
        np.testing.assert_allclose(got[k][0], exp[k][0], rtol=1e-6)
    # and both match the direct computation
    live = (price > 50.0) & (qty < 8)
    assert sum(c for _, c in got.values()) == int(live.sum())


def test_shuffle_writer_reference_task(store_sales, tmp_path):
    path, item, qty, price = store_sales
    data_file = str(tmp_path / "shuffle.data")
    index_file = str(tmp_path / "shuffle.index")

    node = rp.PhysicalPlanNode()
    sw = node.shuffle_writer
    sw.input.CopyFrom(_scan_node(path, FIELDS))
    sw.output_partitioning.hash_expr.append(_col("item"))
    sw.output_partitioning.partition_count = 4
    sw.output_data_file = data_file
    sw.output_index_file = index_file

    task = rp.TaskDefinition()
    task.task_id.job_id = "ref-shuffle"
    task.task_id.partition_id = 0
    task.plan.CopyFrom(node)
    task.output_partitioning.CopyFrom(sw.output_partitioning)

    list(execute_reference_task(task.SerializeToString()))

    assert os.path.exists(data_file) and os.path.exists(index_file)
    # the index is the reference's i64-LE offsets format
    # (shuffle_writer_exec.rs:437-506); partitions concatenated in .data
    raw = open(index_file, "rb").read()
    offsets = struct.unpack(f"<{len(raw) // 8}q", raw)
    assert offsets[0] == 0
    assert offsets[-1] == os.path.getsize(data_file)
    assert len(offsets) == 4 + 1

    # read every partition back through the engine's segmented-IPC
    # reader and check the shuffle moved every row exactly once
    from blaze_tpu.io.ipc import decode_ipc_parts

    total = 0
    items = []
    for p in range(4):
        lo, hi = offsets[p], offsets[p + 1]
        with open(data_file, "rb") as fh:
            fh.seek(lo)
            raw_segment = fh.read(hi - lo)
        for rb in decode_ipc_parts(raw_segment):
            total += rb.num_rows
            items.extend(rb.column("item").to_pylist())
    assert total == len(item)
    assert sorted(set(items)) == sorted(set(item.tolist()))


def test_sort_and_join_reference_nodes_decode(store_sales):
    """SMJ / HJ / sort / union / rename / empty-partitions arms decode to
    the engine's operators with the right shapes."""
    path, *_ = store_sales
    scan = _scan_node(path, FIELDS)

    sort = rp.PhysicalPlanNode()
    sort.sort.input.CopyFrom(scan)
    se = sort.sort.expr.add()
    se.sort.expr.CopyFrom(_col("item"))
    se.sort.asc = True
    se.sort.nulls_first = True

    join = rp.PhysicalPlanNode()
    sj = join.sort_merge_join
    sj.left.CopyFrom(sort)
    sj.right.CopyFrom(sort)
    on = sj.on.add()
    on.left.name = "item"
    on.right.name = "item"
    sj.join_type = rp.SEMI
    op = plan_from_ref(join)
    from blaze_tpu.ops import SortMergeJoinExec as EngineSMJ

    assert isinstance(op, EngineSMJ)
    assert op.join_type.name == "LEFT_SEMI"

    hj = rp.PhysicalPlanNode()
    h = hj.hash_join
    h.left.CopyFrom(scan)
    h.right.CopyFrom(scan)
    jon = h.on.add()
    jon.left.name = "item"
    jon.right.name = "item"
    h.join_type = rp.INNER
    h.partition_mode = rp.COLLECT_LEFT
    from blaze_tpu.ops import HashJoinExec as EngineHJ

    assert isinstance(plan_from_ref(hj), EngineHJ)

    ren = rp.PhysicalPlanNode()
    ren.rename_columns.input.CopyFrom(scan)
    ren.rename_columns.renamed_column_names.extend(["a", "b", "c"])
    assert list(plan_from_ref(ren).schema.names()) == ["a", "b", "c"]

    un = rp.PhysicalPlanNode()
    un.union.children.append(scan)
    un.union.children.append(scan)
    u = plan_from_ref(un)
    assert u.partition_count == 2

    ep = rp.PhysicalPlanNode()
    ep.empty_partitions.schema.CopyFrom(_ref_schema(FIELDS))
    ep.empty_partitions.num_partitions = 3
    e = plan_from_ref(ep)
    assert e.partition_count == 3


def test_unsupported_nodes_raise_not_implemented(store_sales):
    """Unknown constructs raise NotImplementedError (the fallback
    trigger), never a silent wrong decode."""
    path, *_ = store_sales
    e = rp.PhysicalExprNode()
    e.scalar_function.fun = rp.MD5  # no engine kernel
    node = rp.PhysicalPlanNode()
    node.filter.input.CopyFrom(_scan_node(path, FIELDS))
    node.filter.expr.CopyFrom(e)
    with pytest.raises(NotImplementedError):
        plan_from_ref(node)


def test_projection_with_indices_and_pruning(store_sales):
    """Scan projection by field index (NativeParquetScanExec.scala:
    105-107) + logical pruning predicate decode."""
    path, item, qty, price = store_sales
    node = _scan_node(path, FIELDS, projection=[2, 1])
    ps = node.parquet_scan
    # pruning: price >= 0 (keeps everything; exercises the arm)
    pe = ps.pruning_predicate
    pe.binary_expr.op = "GtEq"
    pe.binary_expr.l.column.name = "price"
    pe.binary_expr.r.literal.float32_value = 0.0

    op = plan_from_ref(node)
    # projected scan's output schema is exactly the projection, in
    # projection order (full-schema-plus-indices contract)
    assert list(op.schema.names()) == ["price", "qty"]

    task = rp.TaskDefinition()
    task.plan.CopyFrom(node)
    out = pa.Table.from_batches(
        list(execute_reference_task(task.SerializeToString()))
    )
    assert out.num_rows == len(price)
    np.testing.assert_allclose(
        np.sort(out.column("price").to_numpy(zero_copy_only=False)),
        np.sort(price),
        rtol=1e-6,
    )
