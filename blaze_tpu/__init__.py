"""blaze-tpu: a TPU-native columnar query execution framework.

A brand-new framework with the capabilities of blaze-init/blaze (a Spark SQL
accelerator: protobuf plan-serde boundary -> vectorized native operators over
Arrow columnar batches -> segmented Arrow-IPC columnar shuffle), re-designed
TPU-first:

- Columnar batches are fixed-capacity device arrays (one array per column plus
  a validity bitmask), padded into shape buckets so XLA compiles once per
  (plan fingerprint, bucket).
- Operators are pure functions over batch pytrees, composed per pipeline and
  `jax.jit`-compiled; elementwise expressions fuse straight into XLA.
- Hash partitioning is bit-exact Spark murmur3 (seed 42) evaluated on-device
  for fixed-width columns and in the C++ host runtime for strings.
- Exchange (shuffle / broadcast) spills to the reference-compatible segmented
  Arrow-IPC format (8-byte LE length + zstd Arrow IPC stream per segment,
  little-endian i64 offsets index), so a Spark executor can fetch our output.
- Multi-chip scaling uses `jax.sharding.Mesh` + `shard_map` with XLA
  collectives (all_to_all for repartition, all_gather for broadcast) over ICI.

Reference layer map: /root/reference SURVEY.md section 1; this package provides
TPU-native equivalents of native-engine/{blaze,datafusion-ext,plan-serde}.
"""

import jax as _jax

# SQL semantics need real 64-bit integers (bigint sums, timestamps, decimal
# unscaled values); JAX's default 32-bit mode would silently truncate them.
_jax.config.update("jax_enable_x64", True)

from blaze_tpu.config import EngineConfig, get_config, set_config
from blaze_tpu.types import DataType, Field, Schema
from blaze_tpu.batch import Column, ColumnBatch

__version__ = "0.1.0"

__all__ = [
    "EngineConfig",
    "get_config",
    "set_config",
    "DataType",
    "Field",
    "Schema",
    "Column",
    "ColumnBatch",
]
