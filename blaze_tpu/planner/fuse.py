"""Plan-level pipeline-fusion pass: maximal operator chains -> ONE
device dispatch per task batch.

The reference executes one native call per task (exec.rs:196-255) -
DataFusion streams the whole operator chain inside it - so dispatch
count, not operator count, is its per-query overhead model. This pass
gives the engine the same shape at the XLA level (SURVEY 7): it walks a
physical plan top-down and rewrites every maximal chain of
row-count-compatible operators into a node whose entire chain traces
into a single jitted, `dispatch.cached_kernel`-cached XLA executable:

- stateless chains (Filter -> Project -> Rename, any length) become
  `FusedPipelineExec` - one program evaluating every stage over the
  deferred selection vector;
- a PARTIAL hash aggregate folds into the chain below it
  (`FusedAggregateExec`): stage evaluation + sort/scatter grouping +
  segmented reduction in one program per input batch;
- a COMPLETE aggregate rewrites into device-PARTIAL + host-FINAL
  (`HostFinalAggExec`), and - keyless - into the streaming-carry form
  whose per-batch kernel also merges the running state and packs it for
  the single end-of-stream fetch (one dispatch per batch, zero extra
  for the final merge);
- an INNER hash join directly under a fused aggregate probes and
  gathers the build side inside the same program
  (`FusedAggregateExec._execute_join_fused`);
- a Window over a Project/Rename chain folds the chain into its own
  kernel (`WindowExec._fused_pipeline`): stages + the shared
  (partition, order) argsort + gather + every frame pass in one
  program, with the sort permutation cached across executions on
  input-buffer identity.

Batches still packed in the H2D wire buffer (batch.PackedColumnBatch)
feed fused kernels WITHOUT the separate unpack dispatch: the buffer
splitter traces into the consuming kernel, so a parquet-scan chunk costs
exactly one dispatch end to end.

Execution nodes live in ops/fused.py; this module owns the rewrite
rules. `ops.fused.fuse_pipelines` re-exports the pass for callers that
predate the split.
"""

from __future__ import annotations

from typing import List, Tuple

from blaze_tpu.ops.base import PhysicalOp
from blaze_tpu.ops.filter import FilterExec
from blaze_tpu.ops.project import ProjectExec
from blaze_tpu.ops.rename import RenameColumnsExec


def _stage_fusable(op: PhysicalOp) -> bool:
    from blaze_tpu.ops.fused import _expr_needs_host

    if isinstance(op, RenameColumnsExec):
        return True
    if isinstance(op, FilterExec):
        return not _expr_needs_host(op.predicate, op.children[0].schema)
    if isinstance(op, ProjectExec):
        child_schema = op.children[0].schema
        return not any(
            _expr_needs_host(e, child_schema) for e, _ in op.exprs
        )
    return False


def _agg_exprs_fusable(agg) -> bool:
    from blaze_tpu.exprs.typing import infer_dtype
    from blaze_tpu.ops.fused import _expr_needs_host

    child_schema = agg.children[0].schema
    exprs = [e for e, _ in agg.keys] + [
        a.child for a, _ in agg.aggs if a.child is not None
    ]
    for e in exprs:
        if _expr_needs_host(e, child_schema):
            return False
        try:
            if infer_dtype(e, child_schema).is_string_like:
                return False
        except Exception:
            return False
    return True


def _collect_chain(op: PhysicalOp, allow_filter: bool = True
                   ) -> Tuple[List[PhysicalOp], PhysicalOp]:
    """Peel the maximal fusable stateless chain below `op`'s child.
    `allow_filter=False` restricts to row-count-preserving stages
    (Project/Rename) - what a Window fold can absorb, since its
    in-kernel argsort sees every input row."""
    chain: List[PhysicalOp] = []
    t = op
    while (
        isinstance(t, (FilterExec, ProjectExec, RenameColumnsExec))
        and len(t.children) == 1
        and (allow_filter or not isinstance(t, FilterExec))
        and _stage_fusable(t)
    ):
        chain.append(t)
        t = t.children[0]
    return chain, t


def _window_agg_fusable(win) -> bool:
    """A window qualifies for whole-task window+aggregate fusion when
    its sort runs fully on device (no dictionary-key host remap)."""
    from blaze_tpu.ops.sort import SortKey

    keys = [
        SortKey(e, True, True) for e in win.partition_by
    ] + list(win.order_by)
    return win._sort_fusable(keys)


def _fuse_join_under_agg(join) -> None:
    """Relational-core fusion for an INNER hash join that feeds a fused
    aggregate. Both join-side chains collapse:

    - the BUILD side's scan->filter->project chain becomes one
      FusedPipelineExec (even a single stage - the collected build
      relation then lands in the device hash table with one stage
      dispatch per batch plus the cached insert, no intermediate
      materialization between stages);
    - the PROBE side's chain is recorded on the join as
      ``_fused_probe = (leaf, pipeline)`` so
      FusedAggregateExec._execute_join_fused can fold the stages INTO
      the lookup+aggregate kernel (scan -> filter -> project -> probe
      -> aggregate as ONE program over the raw leaf batch). The probe
      child is ALSO replaced by the same pipeline object, so shapes the
      folded form rejects at runtime (dictionary-encoded keys, the
      sorted join core, packed wire batches) fall back to one
      stage-chain dispatch per batch instead of one per stage.

    The join node itself is left in place - outer-join types, the
    unfused HashJoinExec.execute path and mesh fallback plans read none
    of the attachments and keep their existing ladder."""
    from blaze_tpu.ops.fused import FusedPipelineExec

    bchain, bleaf = _collect_chain(join.children[0])
    if bchain:
        join.children[0] = FusedPipelineExec(
            fuse_pipelines(bleaf), list(reversed(bchain))
        )
    else:
        join.children[0] = fuse_pipelines(join.children[0])
    pchain, pleaf = _collect_chain(join.children[1])
    if pchain:
        pleaf = fuse_pipelines(pleaf)
        pipe = FusedPipelineExec(pleaf, list(reversed(pchain)))
        join.children[1] = pipe
        join._fused_probe = (pleaf, pipe)
    else:
        join.children[1] = fuse_pipelines(join.children[1])


def _fuse_agg_leaf(leaf: PhysicalOp) -> PhysicalOp:
    """Recurse below a fused aggregate's chain leaf. An INNER hash join
    gets its input chains fused around the join (see
    _fuse_join_under_agg); anything else takes the generic pass."""
    from blaze_tpu.ops.joins import HashJoinExec, JoinType

    if (
        isinstance(leaf, HashJoinExec)
        and leaf.join_type is JoinType.INNER
    ):
        _fuse_join_under_agg(leaf)
        return leaf
    return fuse_pipelines(leaf)


def fuse_pipelines(op: PhysicalOp) -> PhysicalOp:
    """Top-down rewrite collapsing maximal fusable chains (>= 2 stages),
    folding PARTIAL aggregates into the chain below them, rewriting
    COMPLETE aggregates into device-PARTIAL + host-FINAL, and folding
    Project/Rename chains into Window kernels."""
    from blaze_tpu.ops.fused import (
        FusedAggregateExec,
        FusedPipelineExec,
        FusedWindowAggExec,
        HostFinalAggExec,
    )
    from blaze_tpu.ops.hash_aggregate import AggMode, HashAggregateExec
    from blaze_tpu.ops.window import WindowExec

    if (
        isinstance(op, HashAggregateExec)
        and len(op.children) == 1
        and op.mode in (AggMode.PARTIAL, AggMode.COMPLETE)
        and _agg_exprs_fusable(op)
    ):
        chain, leaf = _collect_chain(op.children[0])
        if op.mode is AggMode.PARTIAL:
            if chain:
                pipeline = FusedPipelineExec(
                    _fuse_agg_leaf(leaf), list(reversed(chain))
                )
                return FusedAggregateExec(pipeline, op)
            # no chain to fold - leave the plain streaming partial
        else:  # COMPLETE -> fused device PARTIAL + host FINAL
            if not chain and not op.keys and isinstance(leaf, WindowExec):
                # keyless rollup directly over a window: fold the whole
                # task - window chain + argsort + frames + aggregate -
                # into ONE kernel (FusedWindowAggExec); XLA dead-codes
                # sorted columns the aggregate never reads
                win = fuse_pipelines(leaf)
                if isinstance(win, WindowExec) and _window_agg_fusable(
                    win
                ):
                    partial = HashAggregateExec(
                        win,
                        keys=[],
                        aggs=[(a, n) for a, n in op.aggs],
                        mode=AggMode.PARTIAL,
                    )
                    return HostFinalAggExec(
                        FusedWindowAggExec(win, partial), op
                    )
                leaf = win
            pipeline = FusedPipelineExec(
                _fuse_agg_leaf(leaf), list(reversed(chain))
            )
            partial = HashAggregateExec(
                pipeline,
                keys=[(e, n) for e, n in op.keys],
                aggs=[(a, n) for a, n in op.aggs],
                mode=AggMode.PARTIAL,
            )
            return HostFinalAggExec(
                FusedAggregateExec(pipeline, partial, fetch_host=True),
                op,
            )
    if (
        isinstance(op, WindowExec)
        and op._fused_pipeline is None
    ):
        chain, leaf = _collect_chain(
            op.children[0], allow_filter=False
        )
        if chain:
            leaf = fuse_pipelines(leaf)
            op.children = [leaf]
            op._fused_pipeline = FusedPipelineExec(
                leaf, list(reversed(chain))
            )
            return op
    chain, t = _collect_chain(op)
    if len(chain) >= 2:
        return FusedPipelineExec(fuse_pipelines(t), list(reversed(chain)))
    op.children = [fuse_pipelines(c) for c in op.children]
    return op
