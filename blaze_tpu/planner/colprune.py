"""Scan-level column pruning + filter pushdown.

Reference counterpart: the reference's ParquetExec receives an explicit
projection (field indices picked by Spark, NativeParquetScanExec.scala:
105-107) and a pruning predicate evaluated against parquet statistics
(from_proto.rs:202-212); DataFusion additionally re-evaluates pushed-down
row filters on the CPU inside the scan. This engine's plans arrive as
whole subtrees (the proto carries the full operator chain), so the
equivalent decisions are made here by analysis:

- `install(root)` walks the physical plan top-down computing, for every
  `ParquetScanExec`, the set of column positions any ancestor can ever
  read. Unreferenced columns are neither decoded from parquet nor
  transferred to the device - the scan substitutes shared device-resident
  zero placeholders so schema positions (and therefore every BoundCol in
  the plan) stay valid. On a network-attached TPU this directly cuts the
  H2D byte volume, which is the dominant e2e cost for IO-heavy queries.

- With `with_filters=True` (only safe on freshly-decoded trees - the
  executor's `decode_task` path, where no scan object is shared with
  another live plan), conjuncts of a `FilterExec` sitting directly above
  a scan that are exactly evaluable by pyarrow (`col <cmp> literal`) are
  attached to the scan. The scan evaluates them on the host during decode
  (vectorized C++), BEFORE padding/transfer, and also reuses them for
  row-group statistics pruning. The device `FilterExec` still re-applies
  the full predicate, so a conservative mismatch can only cost work,
  never correctness; conjuncts are chosen so pyarrow's NULL/NaN
  comparison semantics drop exactly the rows the device mask would.

Correctness invariant: a column is prunable only if NO ancestor reads it.
The analysis is conservative - any operator it does not understand marks
all of its children's columns as required.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from blaze_tpu.exprs import ir
from blaze_tpu.types import Schema


# ---------------------------------------------------------------------------
# expression column references
# ---------------------------------------------------------------------------

def expr_cols(e: Optional[ir.Expr], schema: Schema) -> Set[int]:
    out: Set[int] = set()
    stack = [e]
    while stack:
        x = stack.pop()
        if x is None:
            continue
        if isinstance(x, ir.BoundCol):
            out.add(x.index)
        elif isinstance(x, ir.Col):
            out.add(schema.index_of(x.name))
        else:
            stack.extend(ir.children(x))
    return out


def split_conjuncts(e: ir.Expr) -> List[ir.Expr]:
    if isinstance(e, ir.BinaryOp) and e.op is ir.Op.AND:
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


_CMPS = (ir.Op.LT, ir.Op.LTE, ir.Op.GT, ir.Op.GTE, ir.Op.EQ, ir.Op.NEQ)
_FLIP = {ir.Op.LT: ir.Op.GT, ir.Op.GT: ir.Op.LT,
         ir.Op.LTE: ir.Op.GTE, ir.Op.GTE: ir.Op.LTE}


def _cast_is_widening(src, dst) -> bool:
    """True when comparing the uncast column equals comparing the cast
    value under arrow/device promotion: exact value-preserving widenings
    only. Narrowing/truncating casts (float->int, int64->int32, ...)
    change comparison results and must NOT be stripped."""
    import numpy as np

    try:
        s = np.dtype(src.physical_dtype())
        d = np.dtype(dst.physical_dtype())
    except Exception:
        return False
    if s.kind == "b" and d.kind in "if":
        return True
    if s.kind in "iu" and d.kind in "iu":
        return d.itemsize >= s.itemsize and s.kind == d.kind
    if s.kind in "iu" and d.kind == "f":
        # int->float: both pyarrow's promotion and the device cast go
        # through double, so the comparison agrees even where float64
        # cannot represent the int exactly
        return d.itemsize == 8
    if s.kind == "f" and d.kind == "f":
        return d.itemsize >= s.itemsize
    return False


def _strip_numeric_cast(e: ir.Expr, schema: Schema) -> ir.Expr:
    """Peel value-preserving widening casts off a column ref (the device
    filter re-checks survivors, but host-dropped rows are unrecoverable,
    so only casts that provably keep the comparison identical qualify)."""
    from blaze_tpu.exprs.typing import infer_dtype

    while isinstance(e, ir.Cast):
        try:
            src = infer_dtype(e.child, schema)
        except Exception:
            return e
        if src.id.name in ("DECIMAL",) or e.to.id.name in ("DECIMAL",):
            return e
        if not _cast_is_widening(src, e.to):
            return e
        e = e.child
    return e


def pushable_conjunct(e: ir.Expr, schema: Schema
                      ) -> Optional[Tuple[str, ir.Op, object]]:
    """`(column_name, cmp, literal)` if pyarrow can evaluate this conjunct
    with SQL-compatible semantics, else None."""
    if not (isinstance(e, ir.BinaryOp) and e.op in _CMPS):
        return None
    lhs, rhs, op = e.left, e.right, e.op
    lc = _strip_numeric_cast(lhs, schema)
    rc = _strip_numeric_cast(rhs, schema)
    col, lit = None, None
    if isinstance(lc, (ir.Col, ir.BoundCol)) and isinstance(rc, ir.Literal):
        col, lit = lc, rc
    elif isinstance(rc, (ir.Col, ir.BoundCol)) and isinstance(
        lc, ir.Literal
    ):
        col, lit = rc, lc
        op = _FLIP.get(op, op)
    if col is None or lit.value is None:
        return None
    v = lit.value
    if isinstance(v, float) and v != v:  # NaN literal: never pushable
        return None
    if not isinstance(v, (int, float, bool, str)):
        return None
    idx = col.index if isinstance(col, ir.BoundCol) else (
        schema.index_of(col.name)
    )
    field = schema.fields[idx]
    # engine literals for these types are internal representations
    # (i64-unscaled decimals, epoch ints) that pyarrow would compare
    # against the REAL arrow values - never pushable as-is
    if field.dtype.id.name in ("DECIMAL", "TIMESTAMP_US", "DATE32"):
        return None
    lit_id = getattr(lit.dtype, "id", None)
    if lit_id is not None and lit_id.name in (
        "DECIMAL", "TIMESTAMP_US", "DATE32"
    ):
        return None
    return (field.name, op, v)


# ---------------------------------------------------------------------------
# plan walk
# ---------------------------------------------------------------------------

def _walk(op, req: Optional[Set[int]], acc: "_Acc") -> None:
    from blaze_tpu.ops.filter import FilterExec
    from blaze_tpu.ops.fused import FusedAggregateExec, FusedPipelineExec
    from blaze_tpu.ops.hash_aggregate import HashAggregateExec
    from blaze_tpu.ops.joins import HashJoinExec, SortMergeJoinExec
    from blaze_tpu.ops.limit import LimitExec
    from blaze_tpu.ops.parquet_scan import ParquetScanExec
    from blaze_tpu.ops.project import ProjectExec
    from blaze_tpu.ops.rename import RenameColumnsExec
    from blaze_tpu.ops.sort import SortExec
    from blaze_tpu.ops.streaming_smj import StreamingSortMergeJoinExec
    from blaze_tpu.ops.union import CoalescePartitionsExec, UnionExec
    from blaze_tpu.ops.window import WindowExec
    from blaze_tpu.ops.debug import DebugExec

    if isinstance(op, ParquetScanExec):
        acc.record_scan(op, req, [])
        return
    if isinstance(op, (FusedPipelineExec, FusedAggregateExec)):
        _walk_fused(op, req, acc)
        return
    if isinstance(op, FilterExec):
        child = op.children[0]
        pred_cols = expr_cols(op.predicate, child.schema)
        child_req = None if req is None else set(req) | pred_cols
        if isinstance(child, ParquetScanExec):
            filters = _scan_filters([op.predicate], child.schema)
            acc.record_scan(child, child_req, filters)
            return
        _walk(child, child_req, acc)
        return
    if isinstance(op, ProjectExec):
        child = op.children[0]
        idxs = (
            range(len(op.exprs)) if req is None else sorted(req)
        )
        child_req: Set[int] = set()
        for i in idxs:
            child_req |= expr_cols(op.exprs[i][0], child.schema)
        _walk(child, child_req, acc)
        return
    if isinstance(op, (RenameColumnsExec, LimitExec,
                       CoalescePartitionsExec)):
        _walk(op.children[0], None if req is None else set(req), acc)
        return
    if isinstance(op, DebugExec):
        # DebugExec materializes EVERY batch via to_arrow() for logging
        # (the reference logs full batches too, debug_exec.rs:44-58), so
        # a pruned placeholder column would crash the log path - require
        # all child columns
        _walk(op.children[0], None, acc)
        return
    if isinstance(op, SortExec):
        child = op.children[0]
        kc: Set[int] = set()
        for k in op.keys:
            kc |= expr_cols(k.expr, child.schema)
        _walk(child, None if req is None else set(req) | kc, acc)
        return
    if isinstance(op, UnionExec):
        for c in op.children:
            _walk(c, None if req is None else set(req), acc)
        return
    if isinstance(op, HashAggregateExec):
        child = op.children[0]
        need: Set[int] = set()
        for e, _ in op.keys:
            need |= expr_cols(e, child.schema)
        for a, _ in op.aggs:
            need |= expr_cols(a, child.schema)
        if op.mode.name == "FINAL":
            # FINAL locates states positionally across the whole partial
            # schema - everything is required
            need = None  # type: ignore[assignment]
        _walk(child, need, acc)
        return
    if isinstance(op, (HashJoinExec, SortMergeJoinExec,
                       StreamingSortMergeJoinExec)):
        from blaze_tpu.ops.joins import JoinType

        left, right = op.children
        n_l = len(left.schema)
        semi = op.join_type in (
            JoinType.LEFT_SEMI, JoinType.LEFT_ANTI,
            JoinType.LEFT_ANTI_NULL_AWARE,
        )  # semi/anti: output is the left side only
        if req is None:
            lr: Optional[Set[int]] = None
            rr: Optional[Set[int]] = None
        elif semi:
            lr = set(req) | set(op.left_keys)
            rr = set(op.right_keys)
        else:
            lr = {i for i in req if i < n_l} | set(op.left_keys)
            rr = {i - n_l for i in req if i >= n_l} | set(op.right_keys)
        _walk(left, lr, acc)
        _walk(right, rr, acc)
        return
    if isinstance(op, WindowExec):
        child = op.children[0]
        n_in = len(child.schema)
        need = (
            set(range(n_in)) if req is None
            else {i for i in req if i < n_in}
        )
        for e in op.partition_by:
            need |= expr_cols(e, child.schema)
        for k in op.order_by:
            need |= expr_cols(k.expr, child.schema)
        for f in op.functions:
            if f.source is not None:
                need |= expr_cols(f.source, child.schema)
        _walk(child, need, acc)
        return
    # unknown operator: conservative - children fully required
    for c in getattr(op, "children", []):
        _walk(c, None, acc)


def _walk_fused(op, req: Optional[Set[int]], acc: "_Acc") -> None:
    """FusedPipelineExec / FusedAggregateExec: replay the stage chain
    in reverse to push requirements down to the fused leaf; collect
    pushable filters from the leading Filter stages (whose input schema
    is still the leaf's - Filter and Rename preserve positions)."""
    from blaze_tpu.ops.filter import FilterExec
    from blaze_tpu.ops.fused import FusedAggregateExec
    from blaze_tpu.ops.parquet_scan import ParquetScanExec
    from blaze_tpu.ops.project import ProjectExec
    from blaze_tpu.ops.rename import RenameColumnsExec

    if isinstance(op, FusedAggregateExec):
        pipeline = op.pipeline
        agg = op.agg
        need: Optional[Set[int]] = set()
        pipe_schema = pipeline.schema
        for e, _ in agg.keys:
            need |= expr_cols(e, pipe_schema)
        for a, _ in agg.aggs:
            need |= expr_cols(a, pipe_schema)
        if agg.mode.name == "FINAL":
            need = None  # states located positionally: all required
    else:
        pipeline = op
        need = None if req is None else set(req)

    leaf = pipeline.children[0]
    stages = pipeline.stages
    for st in reversed(stages):
        child_schema = st.children[0].schema
        if isinstance(st, ProjectExec):
            idxs = range(len(st.exprs)) if need is None else sorted(need)
            nxt: Set[int] = set()
            for i in idxs:
                nxt |= expr_cols(st.exprs[i][0], child_schema)
            need = nxt
        elif isinstance(st, FilterExec):
            if need is not None:
                need |= expr_cols(st.predicate, child_schema)
        elif isinstance(st, RenameColumnsExec):
            pass  # positions preserved
        else:
            need = None
            break

    if isinstance(leaf, ParquetScanExec):
        preds = []
        for st in stages:
            if isinstance(st, FilterExec):
                preds.append(st.predicate)
            elif isinstance(st, RenameColumnsExec):
                continue
            else:
                break
        acc.record_scan(leaf, need, _scan_filters(preds, leaf.schema))
    else:
        _walk(leaf, need, acc)


def _scan_filters(predicates: Sequence[ir.Expr], schema: Schema
                  ) -> List[Tuple[str, ir.Op, object]]:
    out = []
    for p in predicates:
        for c in split_conjuncts(p):
            t = pushable_conjunct(c, schema)
            if t is not None:
                out.append(t)
    return out


# ---------------------------------------------------------------------------
# accumulation + installation
# ---------------------------------------------------------------------------

class _Acc:
    def __init__(self):
        self.required: Dict[int, Optional[Set[int]]] = {}
        self.filters: Dict[int, List] = {}
        self.scans: Dict[int, object] = {}

    def record_scan(self, scan, req: Optional[Set[int]],
                    filters: List) -> None:
        sid = id(scan)
        self.scans[sid] = scan
        if sid in self.required:
            prev = self.required[sid]
            self.required[sid] = (
                None if (prev is None or req is None) else prev | req
            )
        else:
            self.required[sid] = None if req is None else set(req)
        prev_f = self.filters.get(sid)
        if prev_f is None:
            self.filters[sid] = list(filters)
        elif prev_f != list(filters):
            # same scan object reached through two different filter
            # contexts: pushing either filter would drop the other
            # branch's rows
            self.filters[sid] = []


import threading

_INSTALL_LOCK = threading.Lock()


def install(root, with_filters: bool = False) -> None:
    """Attach pruning/pushdown hints to every ParquetScanExec in `root`.

    Required-column hints only ever GROW on a scan instance (union with
    anything previously installed, under a lock - scheduler threads
    install concurrently), so a scan shared across plans stays correct -
    stale entries just prune less. Filter hints are attached only with
    `with_filters=True`, which callers must reserve for trees whose
    scans are not shared with any other live plan (the per-task decode
    path)."""
    if getattr(root, "_colprune_installed", False) and not with_filters:
        return  # hints never shrink; this tree was already analyzed
    acc = _Acc()
    _walk(root, None, acc)
    try:
        root._colprune_installed = True
    except Exception:
        pass  # exotic roots without attribute support just re-walk
    with _INSTALL_LOCK:
        for sid, scan in acc.scans.items():
            req = acc.required[sid]
            if not hasattr(scan, "_hint_required"):
                scan._hint_required = (
                    None if req is None else frozenset(req)
                )
            elif scan._hint_required is None or req is None:
                scan._hint_required = None
            else:
                scan._hint_required = frozenset(
                    scan._hint_required | req
                )
            if with_filters:
                scan._hint_filters = tuple(acc.filters.get(sid, []))
