"""Exchange insertion: lower a single-process plan onto the shuffle tier.

The reference never executes a join or final aggregate without an
exchange underneath - Spark's planner guarantees co-partitioning via
ArrowShuffleExchangeExec and broadcast via ArrowBroadcastExchangeExec
(ArrowShuffleExchangeExec301.scala:78, ArrowBroadcastExchangeExec.scala:
139-256), and the TPC-DS CI exercises every query through those real
shuffles (tpcds.yml:139-147). This rule is that planner step engine-side:

- sort-merge joins get HASH ShuffleExchangeExec on BOTH children, keyed
  by the join keys with the same partition count -> co-partitioned,
  partition-wise join (SURVEY 2.3 "partition-wise join alignment");
- broadcast hash joins get BroadcastExchangeExec on the build side;
- COMPLETE hash aggregates split into PARTIAL -> hash exchange on the
  group keys -> FINAL (keyless: single-partition exchange), the
  reference's NativeHashAggregateExec mode mapping
  (NativeHashAggregateExec.scala:98-161);
- a global Limit(Sort(...)) root coalesces partitions below the sort so
  top-N stays global.

Exchanges preserve schema exactly, so children are swapped in place and
bound column indices stay valid.
"""

from __future__ import annotations

from typing import Dict, Optional

from blaze_tpu.exprs import ir
from blaze_tpu.exprs.ir import AggExpr
from blaze_tpu.ops import (
    AggMode,
    HashAggregateExec,
    HashJoinExec,
    LimitExec,
    SortExec,
    SortMergeJoinExec,
)
from blaze_tpu.ops.base import PhysicalOp
from blaze_tpu.ops.streaming_smj import StreamingSortMergeJoinExec
from blaze_tpu.ops.union import CoalescePartitionsExec
from blaze_tpu.ops.window import WindowExec
from blaze_tpu.parallel.exchange import (
    BroadcastExchangeExec,
    ShuffleExchangeExec,
)


def _hash_exchange(child: PhysicalOp, key_indices, num_partitions,
                   shuffle_dir) -> ShuffleExchangeExec:
    keys = [
        ir.BoundCol(i, child.schema.fields[i].dtype)
        for i in key_indices
    ]
    return ShuffleExchangeExec(
        child, keys, num_partitions, mode="hash",
        shuffle_dir=shuffle_dir,
    )


def insert_exchanges(op: PhysicalOp, num_partitions: int = 4,
                     shuffle_dir: Optional[str] = None) -> PhysicalOp:
    """Rewrite `op` so every join/final-aggregate runs over the shuffle
    tier. Returns the (possibly new) root."""
    seen: Dict[int, PhysicalOp] = {}
    root = _rewrite(op, num_partitions, shuffle_dir, seen)
    return _fix_global_limit(root)


def _rewrite(op: PhysicalOp, n: int, shuffle_dir,
             seen: Dict[int, PhysicalOp]) -> PhysicalOp:
    if id(op) in seen:  # shared subtree (CTE reuse): rewrite once
        return seen[id(op)]
    seen[id(op)] = op  # break cycles while recursing
    for i, c in enumerate(op.children):
        op.children[i] = _rewrite(c, n, shuffle_dir, seen)

    new: PhysicalOp = op
    if isinstance(op, (SortMergeJoinExec, StreamingSortMergeJoinExec)):
        from blaze_tpu.ops.joins import JoinType

        if op.join_type is JoinType.LEFT_ANTI_NULL_AWARE:
            # NAAJ semantics are GLOBAL (any build-side NULL empties the
            # whole result, joins.py:574); hash bucketing would evaluate
            # them per partition. Run it single-partition instead.
            for i in (0, 1):
                if op.children[i].partition_count > 1:
                    op.children[i] = CoalescePartitionsExec(
                        op.children[i]
                    )
        else:
            for i, keys in ((0, op.left_keys), (1, op.right_keys)):
                ex: PhysicalOp = _hash_exchange(
                    op.children[i], keys, n, shuffle_dir
                )
                if isinstance(op, StreamingSortMergeJoinExec):
                    # the streaming join's window eviction assumes both
                    # inputs arrive key-sorted; a hash exchange orders
                    # by partition id only, so restore sortedness per
                    # partition (Spark plants the same per-partition
                    # sort under SMJ after its exchanges)
                    from blaze_tpu.ops.sort import SortKey

                    ex = SortExec(
                        ex,
                        [SortKey(ir.BoundCol(
                            k, ex.schema.fields[k].dtype
                        )) for k in keys],
                    )
                op.children[i] = ex
    elif isinstance(op, HashJoinExec):
        if not getattr(op.children[0], "is_broadcast", False):
            op.children[0] = BroadcastExchangeExec(op.children[0])
    elif isinstance(op, WindowExec):
        child = op.children[0]
        if child.partition_count > 1:
            if op.partition_by and all(
                isinstance(e, ir.BoundCol) for e in op.partition_by
            ):
                # Spark plants a hash exchange on the window's
                # PARTITION BY so each frame is computed whole
                op.children[0] = _hash_exchange(
                    child, [e.index for e in op.partition_by], n,
                    shuffle_dir,
                )
            else:
                # no partition keys (global frames): single partition
                op.children[0] = CoalescePartitionsExec(child)
    elif isinstance(op, SortExec):
        # a pre-existing sort in the plan is a GLOBAL ordering
        # requirement (top-n inputs, order-sensitive windows); sorts
        # this pass itself plants under streaming SMJ are created after
        # recursion and are never revisited, so they stay per-partition
        if op.children[0].partition_count > 1:
            op.children[0] = CoalescePartitionsExec(op.children[0])
    elif (
        isinstance(op, HashAggregateExec)
        and op.mode is AggMode.COMPLETE
    ):
        partial = HashAggregateExec(
            op.children[0], keys=op.keys, aggs=op.aggs,
            mode=AggMode.PARTIAL,
        )
        if op.keys:
            exchange: PhysicalOp = _hash_exchange(
                partial, list(range(len(op.keys))), n, shuffle_dir
            )
        else:
            exchange = ShuffleExchangeExec(
                partial, [], 1, mode="single", shuffle_dir=shuffle_dir
            )
        key_names = [name for _, name in op.keys]
        new = HashAggregateExec(
            exchange,
            keys=[(ir.Col(kn), kn) for kn in key_names],
            aggs=[(AggExpr(a.fn, None), name) for a, name in op.aggs],
            mode=AggMode.FINAL,
        )
    seen[id(op)] = new
    return new


# ---------------------------------------------------------------------------
# Mesh execution tier: the cost-guarded planner pass
# ---------------------------------------------------------------------------


def estimate_rows(op: PhysicalOp) -> int:
    """Leaf-driven row estimate for the mesh cost guard: memory scans
    count resident rows, parquet scans approximate rows from file-range
    bytes (~16 B/row, the battery tables' order of magnitude), interior
    nodes sum their leaves. Deliberately coarse - the guard needs an
    order of magnitude, not a cost model (same contract as
    admission.estimate_plan_device_bytes)."""
    from blaze_tpu.ops.memory_scan import MemoryScanExec
    from blaze_tpu.ops.parquet_scan import ParquetScanExec

    if isinstance(op, MemoryScanExec):
        return sum(
            cb.num_rows for part in op.partitions for cb in part
        )
    if isinstance(op, ParquetScanExec):
        import os

        total = 0
        for group in op.file_groups:
            for fr in group:
                if fr.length:
                    total += fr.length
                else:
                    try:
                        total += os.path.getsize(fr.path)
                    except OSError:
                        pass
        return total // 16
    if not op.children:
        return 0
    return sum(estimate_rows(c) for c in op.children)


def resolve_mesh_mode(ctx=None) -> str:
    """Mesh execution mode: explicit per-context override (the serving
    tier's `mesh_mode` knob / `serve --mesh`) beats the
    BLAZE_MESH_LOWERING env, default "auto".

      off   never lower onto the mesh
      auto  lower when the mesh exists AND the cost guard passes
            (single-controller only - in a multi-process group ranks
            decode DIFFERENT tasks and a one-sided collective would
            deadlock the group)
      on    force lowering (bypasses the row-count guard; asserts the
            caller decodes rank-symmetric tasks in a multi-process
            group)
    """
    import os

    mode = getattr(ctx, "mesh_mode", None) if ctx is not None else None
    mode = mode or os.environ.get("BLAZE_MESH_LOWERING", "auto")
    if mode not in ("auto", "on", "off"):
        raise ValueError(
            f"mesh mode must be auto|on|off, got {mode!r}"
        )
    return mode


def _mesh_min_rows(mode: str) -> int:
    """Cost guard: plans below this row estimate stay single-device
    (staging + program launch would dominate). `on` forces."""
    import os

    if mode == "on":
        return 0
    try:
        return int(os.environ.get("BLAZE_MESH_MIN_ROWS", 4096))
    except ValueError:
        return 4096


def _pick_mesh(n_parts: int, mesh=None):
    """Partition-axis selection from plan shape: a k-partition child
    lands one partition per device on a k-wide 'data' axis (k capped
    by the device pool and BLAZE_MESH_DEVICES); a single-partition
    child takes the FULL mesh - its groups still spread across every
    device at the exchange. Returns None when no multi-device mesh is
    possible."""
    import os

    from blaze_tpu.parallel.mesh import device_count, get_mesh

    if mesh is not None:
        return mesh
    n_dev = device_count()
    try:
        cap = int(os.environ.get("BLAZE_MESH_DEVICES", n_dev))
    except ValueError:
        cap = n_dev
    n_dev = max(1, min(n_dev, cap))
    if n_dev <= 1:
        return None
    width = n_dev if n_parts <= 1 else min(n_dev, max(2, n_parts))
    return get_mesh((width,))


def lower_plan_to_mesh(op: PhysicalOp, mode: Optional[str] = None,
                       mesh=None, ctx=None) -> PhysicalOp:
    """The mesh execution tier's planner pass (ROADMAP item 2): lower
    the ROOT of a plan onto the device mesh when its shape shards and
    the cost guard passes, else return the plan untouched (single-
    device execution). Three recognized shapes:

      grouped aggregate (COMPLETE, or the FINAL/exchange/PARTIAL
        sandwich)            -> MeshGroupByExec (ICI all_to_all)
      inner broadcast hash join with a small unique-key build side
                             -> MeshBroadcastJoinExec (ICI all_gather)
      filter/project chain over a multi-partition source
                             -> MeshPipelineExec (partition-parallel)

    Root-only by design: a mid-tree rewrite would hand Sort/Limit/
    Window parents n_dev partitions where the plan promised fewer,
    silently turning global semantics per-partition. Every lowered op
    carries the ORIGINAL node as its runtime fallback (tryConvert
    semantics, both halves).

    A lowered op is stamped with its `_mesh_lower = (t0, t1)` planner
    window (monotonic seconds) so the execution stage replays the
    planner pass as the `mesh_lower` sub-phase of the stage anatomy
    (obs/meshprof.py)."""
    import time as _time

    _lower_t0 = _time.monotonic()
    new = _lower_plan_to_mesh(op, mode, mesh, ctx)
    if new is not op:
        new._mesh_lower = (_lower_t0, _time.monotonic())
    return new


def _lower_plan_to_mesh(op: PhysicalOp, mode: Optional[str],
                        mesh, ctx) -> PhysicalOp:
    mode = mode if mode is not None else resolve_mesh_mode(ctx)
    if mode == "off":
        return op
    if mode == "auto":
        # single-controller only: in a multi-process group, ranks
        # execute DIFFERENT plans and a one-sided collective would
        # deadlock the group. "on" asserts rank-symmetric callers
        # (the launcher's SPMD workload). Guarded HERE so every entry
        # (service driver plans, run_plan_parallel, decoded tasks)
        # shares it, not just prepare_decoded_task.
        try:
            import jax

            if jax.process_count() > 1:
                return op
        except Exception:  # noqa: BLE001 - uninitialized distributed
            pass
    from blaze_tpu.parallel.mesh import device_count

    if mesh is None and device_count() <= 1:
        return op
    from blaze_tpu.parallel.mesh_ops import MeshGroupByExec

    min_rows = _mesh_min_rows(mode)
    new = _try_mesh_groupby(op, mesh, MeshGroupByExec,
                            min_rows=min_rows, global_only=True)
    if new is not op:
        return new
    new = _try_mesh_broadcast_join(op, mesh, min_rows)
    if new is not op:
        return new
    new = _try_mesh_sort(op, mesh, min_rows)
    if new is not op:
        return new
    new = _try_mesh_window(op, mesh, min_rows)
    if new is not op:
        return new
    return _try_mesh_pipeline(op, mesh, min_rows)


def _try_mesh_broadcast_join(node: PhysicalOp, mesh,
                             min_rows: int) -> PhysicalOp:
    """HashJoinExec (CollectLeft broadcast join) -> mesh broadcast
    join: INNER, one integer key pair, build side small enough to
    replicate into every device's HBM, multi-partition probe."""
    import os

    from blaze_tpu.ops.joins import JoinType

    if not isinstance(node, HashJoinExec):
        return node
    if node.join_type is not JoinType.INNER:
        return node
    if len(node.left_keys) != 1 or len(node.right_keys) != 1:
        return node
    build, probe = node.children
    if getattr(build, "is_broadcast", False):
        # already wrapped for the file tier; unwrap the real relation
        build = build.children[0] if build.children else build
    for side, keys in ((build, node.left_keys),
                       (probe, node.right_keys)):
        dt = side.schema.fields[keys[0]].dtype
        if not dt.is_integer:
            return node
    if probe.partition_count < 2:
        return node
    try:
        bcast_max = int(
            os.environ.get("BLAZE_MESH_BCAST_MAX_ROWS", 1 << 17)
        )
    except ValueError:
        bcast_max = 1 << 17
    if estimate_rows(build) > bcast_max:
        return node
    if estimate_rows(probe) < min_rows:
        return node
    m = _pick_mesh(probe.partition_count, mesh)
    if m is None or probe.partition_count > int(m.shape["data"]):
        return node
    try:
        from blaze_tpu.parallel.mesh_exec import MeshBroadcastJoinExec

        return MeshBroadcastJoinExec(
            build, probe,
            build_key=node.left_keys[0],
            probe_key=node.right_keys[0],
            mesh=m, fallback=node,
        )
    except (NotImplementedError, AssertionError):
        return node


def _try_mesh_pipeline(node: PhysicalOp, mesh,
                       min_rows: int) -> PhysicalOp:
    """A root filter/project chain over a multi-partition source
    executes all partitions in one shard_map program."""
    from blaze_tpu.ops.filter import FilterExec
    from blaze_tpu.ops.project import ProjectExec

    chain = []
    cur = node
    while isinstance(cur, (FilterExec, ProjectExec)):
        chain.append(cur)
        cur = cur.children[0]
    if not chain:
        return node
    source = cur
    if source.partition_count < 2:
        return node
    if estimate_rows(source) < min_rows:
        return node
    m = _pick_mesh(source.partition_count, mesh)
    if m is None or source.partition_count > int(m.shape["data"]):
        return node
    try:
        from blaze_tpu.parallel.mesh_exec import MeshPipelineExec

        return MeshPipelineExec(node, chain, source, mesh=m,
                                fallback=node)
    except (NotImplementedError, AssertionError):
        return node


def _try_mesh_sort(node: PhysicalOp, mesh,
                   min_rows: int) -> PhysicalOp:
    """A root SortExec (a GLOBAL ordering - insert_exchanges plants a
    CoalescePartitions under it) runs as N simultaneous per-shard
    device sorts + a host run-merge: single ascending integer bound-
    column key, multi-partition source."""
    if not isinstance(node, SortExec):
        return node
    if len(node.keys) != 1:
        return node
    k = node.keys[0]
    if not k.ascending or not isinstance(k.expr, ir.BoundCol):
        return node
    source = node.children[0]
    if isinstance(source, CoalescePartitionsExec):
        source = source.children[0]
    if source.partition_count < 2:
        return node
    if not source.schema.fields[k.expr.index].dtype.is_integer:
        return node
    if estimate_rows(source) < min_rows:
        return node
    m = _pick_mesh(source.partition_count, mesh)
    if m is None or source.partition_count > int(m.shape["data"]):
        return node
    try:
        from blaze_tpu.parallel.mesh_exec import MeshSortExec

        return MeshSortExec(source, node.keys, fetch=node.fetch,
                            mesh=m, fallback=node)
    except (NotImplementedError, AssertionError):
        return node


def _try_mesh_window(node: PhysicalOp, mesh,
                     min_rows: int) -> PhysicalOp:
    """A root WindowExec over the hash exchange insert_exchanges
    plants on its PARTITION BY keeps its (device-based) frame
    computation and swaps the exchange for a mesh hash repartition:
    rows reach their key-hash owner over ICI all_to_all instead of the
    file fabric, and the window computes each key-disjoint partition
    whole. Root-only: the partition-count change is safe at the true
    root."""
    if not isinstance(node, WindowExec):
        return node
    ex = node.children[0]
    if not isinstance(ex, ShuffleExchangeExec) or ex.mode != "hash":
        return node
    if not ex.keys or not all(
        isinstance(e, ir.BoundCol) for e in ex.keys
    ):
        return node
    source = ex.children[0]
    if source.partition_count < 2:
        return node
    if estimate_rows(source) < min_rows:
        return node
    m = _pick_mesh(source.partition_count, mesh)
    if m is None or source.partition_count > int(m.shape["data"]):
        return node
    try:
        from blaze_tpu.parallel.mesh_exec import MeshRepartitionExec

        node.children[0] = MeshRepartitionExec(
            source, ex.keys, mesh=m, fallback=ex,
        )
        return node
    except (NotImplementedError, AssertionError):
        return node


def lower_to_mesh(op: PhysicalOp, mesh=None,
                  root_only: bool = False) -> PhysicalOp:
    """Lower aggregate shapes onto the ICI tier: a grouped aggregate
    whose inputs are slice-resident becomes one `MeshGroupByExec` pjit
    program (partial agg -> all_to_all key exchange over ICI -> owner
    merge) instead of a host shuffle. Two recognized shapes:

      FINAL-agg over hash-ShuffleExchange over PARTIAL-agg  (the
        sandwich insert_exchanges plants; VERDICT r3 item 8)
      COMPLETE agg  (what a decoded single-stage TaskDefinition carries
        - the reference splits stages at exchanges, plan.proto has no
        exchange node, so in-task sandwiches only exist pre-serde)

    tryConvert semantics (BlazeConverters.scala:137-157): any gate
    failure - string keys, unsupported agg fn, more child partitions
    than devices, no mesh - leaves the node untouched."""
    from blaze_tpu.parallel.mesh import device_count
    from blaze_tpu.parallel.mesh_ops import MeshGroupByExec

    if mesh is None and device_count() <= 1:
        return op
    if root_only:
        # task-boundary mode: only a ROOT aggregate may change its
        # partitioning - a mid-tree rewrite would hand Sort/Limit/
        # Window parents n_dev partitions where the plan promised one,
        # silently turning global semantics per-partition
        return _try_mesh_groupby(op, mesh, MeshGroupByExec)
    seen: Dict[int, PhysicalOp] = {}

    def rewrite(node: PhysicalOp) -> PhysicalOp:
        if id(node) in seen:
            return seen[id(node)]
        seen[id(node)] = node
        for i, c in enumerate(node.children):
            node.children[i] = rewrite(c)
        new = _try_mesh_groupby(node, mesh, MeshGroupByExec)
        seen[id(node)] = new
        return new

    return rewrite(op)


def _try_mesh_groupby(node: PhysicalOp, mesh, MeshGroupByExec,
                      min_rows: int = 0,
                      global_only: bool = False) -> PhysicalOp:
    from blaze_tpu.exprs.ir import AggFn

    shapes = _match_agg_shape(node)
    if shapes is None:
        return node
    child, keys, aggs = shapes
    if (global_only and node.mode is AggMode.COMPLETE
            and child.partition_count > 1):
        # a bare COMPLETE aggregate over a multi-partition child has
        # PER-PARTITION grouping semantics engine-side (the global
        # form is the FINAL/exchange/PARTIAL sandwich); the mesh op
        # computes the global aggregate, so lowering here would
        # silently change results. The production pass refuses; the
        # dryrun/test entry (lower_to_mesh) keeps the old behavior
        # where callers assert global intent.
        return node
    supported = {AggFn.SUM, AggFn.COUNT, AggFn.COUNT_STAR,
                 AggFn.MIN, AggFn.MAX, AggFn.AVG}
    if any(a.fn not in supported for a, _ in aggs):
        return node
    if min_rows and estimate_rows(child) < min_rows:
        return node  # cost guard: staging would dominate
    # partition-axis selection + cheap partition gates BEFORE
    # constructing the (pjit-program-building) mesh op: a sandwich
    # with more reducers than devices is the common insert_exchanges
    # default and must not pay plan-time construction just to be
    # discarded
    mesh = _pick_mesh(
        max(child.partition_count, node.partition_count), mesh
    )
    if mesh is None:
        return node
    n_dev = int(mesh.shape["data"])
    if child.partition_count > n_dev or node.partition_count > n_dev:
        return node
    try:
        # `fallback=node`: ineligibility that only shows at execution
        # (actual validity masks on nullable inputs) re-runs the
        # original aggregate - tryConvert's runtime half
        mg = MeshGroupByExec(child, keys, aggs, mesh=mesh,
                             fallback=node)
        if child.partition_count > mg.partition_count:
            return node
        if node.partition_count > mg.partition_count:
            # consumers pull mg.partition_count partitions; a fallback
            # wider than the mesh (FINAL sandwich whose exchange has
            # more reducers than devices) would silently lose the
            # groups hashed to the excess partitions
            return node
        return mg
    except (NotImplementedError, AssertionError):
        return node  # per-node fallback, reference tryConvert semantics


def _match_agg_shape(node: PhysicalOp):
    """Returns (source_child, keys, complete_aggs) for the two
    recognized aggregate shapes, else None."""
    if not isinstance(node, HashAggregateExec) or not node.keys:
        return None
    if node.mode is AggMode.COMPLETE:
        return node.children[0], node.keys, node.aggs
    if node.mode is not AggMode.FINAL:
        return None
    ex = node.children[0]
    if not isinstance(ex, ShuffleExchangeExec) or ex.mode != "hash":
        return None
    partial = ex.children[0]
    if (not isinstance(partial, HashAggregateExec)
            or partial.mode is not AggMode.PARTIAL
            or len(partial.keys) != len(node.keys)):
        return None
    # reconstruct the COMPLETE aggregate list: the FINAL node merges
    # positionally (child=None), the PARTIAL node holds the original
    # input-bound expressions
    aggs = [
        (AggExpr(pa_.fn, pa_.child), name)
        for (pa_, _), (_, name) in zip(partial.aggs, node.aggs)
    ]
    return partial.children[0], partial.keys, aggs


def _fix_global_limit(root: PhysicalOp) -> PhysicalOp:
    """Top-N and global limits must see ONE partition (Spark plants the
    single-partition exchange the same way for CollectLimit /
    TakeOrdered)."""
    if isinstance(root, LimitExec):
        inner = root.children[0]
        if isinstance(inner, SortExec):
            if inner.children[0].partition_count > 1:
                inner.children[0] = CoalescePartitionsExec(
                    inner.children[0]
                )
        elif inner.partition_count > 1:
            root.children[0] = CoalescePartitionsExec(inner)
    elif isinstance(root, SortExec):
        if root.children[0].partition_count > 1:
            root.children[0] = CoalescePartitionsExec(root.children[0])
    return root


# ---------------------------------------------------------------------------
# fleet tier (ISSUE 20): hybrid ICI x DCN lowering
# ---------------------------------------------------------------------------


def _bound_index(e, schema) -> Optional[int]:
    if isinstance(e, ir.BoundCol):
        return int(e.index)
    if isinstance(e, ir.Col):
        try:
            return int(schema.index_of(e.name))
        except (KeyError, ValueError):
            return None
    return None


def lower_plan_to_fleet(op: PhysicalOp, fleet, mode: Optional[str] = None,
                        mesh=None, ctx=None) -> PhysicalOp:
    """The fleet mesh tier's planner pass: split an eligible grouped
    aggregate across the fleet's hosts (fleet/exec.FleetMeshExec) -
    per-host ICI partial stages joined by DCN key-hash exchanges -
    falling through to the single-host mesh pass for everything else.

    Eligibility is STRICTER than the single-host mesh tier: the
    partial states cross hosts finalized, so only aggregates whose
    finalized form merges losslessly ship (SUM/COUNT/COUNT_STAR by
    SUM, MIN/MAX by themselves). AVG stays single-host - a merge of
    finalized averages loses the weights. Keys must be plain columns
    (the DCN bucket hash runs host-side over fixed-width arrays) and
    the same COMPLETE-over-multi-partition semantics guard as
    _try_mesh_groupby applies.

    The fallback chain IS the failure ladder: the FleetMeshExec's
    fallback is this same plan's single-host mesh lowering (coalesced
    when wider than the fleet, so a degraded run loses no partitions),
    which itself falls back to single-device."""
    import time as _time

    from blaze_tpu.exprs.ir import AggFn

    mode = mode if mode is not None else resolve_mesh_mode(ctx)

    def single() -> PhysicalOp:
        return lower_plan_to_mesh(op, mode, mesh=mesh, ctx=ctx)

    if mode == "off" or fleet is None or fleet.width() < 2:
        return single()
    _t0 = _time.monotonic()
    shapes = _match_agg_shape(op)
    if shapes is None:
        return single()
    child, keys, aggs = shapes
    if op.mode is AggMode.COMPLETE and child.partition_count > 1:
        # per-partition grouping semantics (see _try_mesh_groupby)
        return single()
    fleet_fns = {AggFn.SUM, AggFn.COUNT, AggFn.COUNT_STAR,
                 AggFn.MIN, AggFn.MAX}
    if any(a.fn not in fleet_fns for a, _ in aggs):
        return single()
    min_rows = _mesh_min_rows(mode)
    if min_rows and estimate_rows(child) < min_rows:
        return single()  # cost guard: two DCN rounds would dominate
    kspec = []
    for e, name in keys:
        idx = _bound_index(e, child.schema)
        if idx is None \
                or child.schema.fields[idx].dtype.is_string_like:
            return single()
        kspec.append((idx, name))
    aspec = []
    for a, name in aggs:
        if a.child is None:
            aspec.append((a.fn.value, None, name))
            continue
        idx = _bound_index(a.child, child.schema)
        if idx is None:
            return single()
        aspec.append((a.fn.value, idx, name))
    fb = single()
    if fb.partition_count > fleet.width():
        # consumers pull fleet.width() partitions; a wider fallback
        # would silently lose the partitions past the fleet width
        fb = CoalescePartitionsExec(fb)
    from blaze_tpu.fleet.exec import FleetMeshExec

    new = FleetMeshExec(child, kspec, aspec, fleet=fleet,
                        schema=op.schema, fallback=fb,
                        mesh_mode=mode if mode else "auto")
    new._mesh_lower = (_t0, _time.monotonic())
    return new
