"""PlanSpec: the embedder-facing physical-plan description.

The neutral tree an embedding system hands to the planner - playing the
role Spark's physical `SparkPlan` tree plays for the reference's converters
(BlazeConverters.scala per-op convertXxxExec surface). Node set mirrors the
operators the reference can offload plus the ones it deliberately leaves on
the host (Window - BlazeConverters inserts row barriers before those,
BlazeConverters.scala:93-107)."""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from blaze_tpu.exprs import ir
from blaze_tpu.exprs.ir import AggExpr


@dataclasses.dataclass
class PlanSpec:
    children: List["PlanSpec"] = dataclasses.field(default_factory=list)
    # filled by the strategy pass (reference tags blaze.convertible /
    # blaze.convert.strategy on every node, BlazeConvertStrategy.scala:84-86)
    convertible: Optional[bool] = None
    strategy: str = "default"  # default | always | never


@dataclasses.dataclass
class ScanSpec(PlanSpec):
    """Parquet file scan (FileSourceScanExec analog)."""

    file_groups: Sequence[Sequence] = ()
    projection: Optional[Sequence[str]] = None
    predicate: Optional[ir.Expr] = None  # data filter -> pruning + filter


@dataclasses.dataclass
class MemorySpec(PlanSpec):
    """In-memory table (tests / local embedders)."""

    dataframe: object = None  # pandas DataFrame
    partitions: int = 1


@dataclasses.dataclass
class ProjectSpec(PlanSpec):
    exprs: Sequence[Tuple[ir.Expr, str]] = ()


@dataclasses.dataclass
class FilterSpec(PlanSpec):
    predicate: Optional[ir.Expr] = None


@dataclasses.dataclass
class SortSpec(PlanSpec):
    keys: Sequence[Tuple[ir.Expr, bool, bool]] = ()  # expr, asc, nulls_first
    fetch: Optional[int] = None


@dataclasses.dataclass
class UnionSpec(PlanSpec):
    pass


@dataclasses.dataclass
class LimitSpec(PlanSpec):
    limit: int = 0


@dataclasses.dataclass
class AggSpec(PlanSpec):
    keys: Sequence[Tuple[ir.Expr, str]] = ()
    aggs: Sequence[Tuple[AggExpr, str]] = ()
    mode: str = "complete"  # partial | final | complete


@dataclasses.dataclass
class JoinSpec(PlanSpec):
    kind: str = "smj"  # smj | bhj
    left_keys: Sequence[str] = ()
    right_keys: Sequence[str] = ()
    join_type: str = "inner"
    condition: Optional[ir.Expr] = None  # post-join filter
    # AQE-detected skew joins stay host-side, like the reference's
    # strategy (BlazeConvertStrategy.scala:159 "never convert skew joins")
    skewed: bool = False


@dataclasses.dataclass
class ExchangeSpec(PlanSpec):
    keys: Sequence[ir.Expr] = ()
    num_partitions: int = 1
    mode: str = "hash"  # hash | single | round_robin | range | broadcast


@dataclasses.dataclass
class WindowSpec(PlanSpec):
    """Host-only in the reference too (row barrier inserted before it)."""

    partition_by: Sequence[str] = ()
    order_by: Sequence[str] = ()
    function: str = "row_number"
    source: Optional[str] = None  # input column for lag/lead/agg-over
    output: str = "w"
