"""Planner tier: the engine-side analog of the reference's Spark session
extension (spark-extension L1/L2, SURVEY 2.2).

An embedder (a Spark extension, a SQL frontend, tests) describes its
already-optimized physical plan as a `PlanSpec` tree; the planner then does
what BlazeSparkSessionExtension + BlazeConvertStrategy + BlazeConverters do
(BlazeSparkSessionExtension.scala:41-62, BlazeConvertStrategy.scala:84-148,
BlazeConverters.scala:93-157):

1. tag every node convertible/not by DRY-RUNNING its conversion
2. apply strategy heuristics + per-op enable gates to pick native vs host
3. convert bottom-up with tryConvert per-node fallback - a conversion
   error falls back to the host engine for that node, never fails the query
4. splice conversion bridges where native and host subtrees meet

The host tier here is a pandas interpreter of PlanSpec (planner/host_engine)
standing in for the JVM row-based execution the reference falls back to.
"""

from blaze_tpu.planner.spec import (
    AggSpec,
    ExchangeSpec,
    FilterSpec,
    JoinSpec,
    LimitSpec,
    MemorySpec,
    PlanSpec,
    ProjectSpec,
    ScanSpec,
    SortSpec,
    UnionSpec,
    WindowSpec,
)
from blaze_tpu.planner.convert import ConvertStrategy, convert_plan

__all__ = [
    "PlanSpec",
    "MemorySpec",
    "ScanSpec",
    "ProjectSpec",
    "FilterSpec",
    "SortSpec",
    "UnionSpec",
    "LimitSpec",
    "AggSpec",
    "JoinSpec",
    "ExchangeSpec",
    "WindowSpec",
    "ConvertStrategy",
    "convert_plan",
]
