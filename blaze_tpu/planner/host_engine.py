"""Host fallback engine: executes PlanSpec subtrees in pandas.

The role the JVM row-based execution plays for the reference: any node the
convert strategy rejects (disabled op, unsupported expression, Window, ...)
runs here, and `HostFallbackExec` bridges the result back into device
batches - the ConvertToNative analog (ConvertToNativeExec.scala:61-95);
the reverse bridge (native subtree consumed by a host node) is a plain
`to_arrow()/to_pandas()` - the ConvertToUnsafeRow analog
(ConvertToUnsafeRowExec.scala:50-90)."""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np
import pandas as pd
import pyarrow as pa

from blaze_tpu.types import Schema, from_arrow_schema
from blaze_tpu.batch import ColumnBatch
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.host_eval import HostEvaluator
from blaze_tpu.exprs.ir import AggFn
from blaze_tpu.ops.base import ExecContext, PhysicalOp
from blaze_tpu.planner import spec as S


def _eval_expr_pd(df: pd.DataFrame, e: ir.Expr) -> pa.Array:
    rb = pa.RecordBatch.from_pandas(df, preserve_index=False)
    schema = from_arrow_schema(rb.schema)
    bound = ir.bind(e, schema)
    ev = HostEvaluator(schema, [rb.column(i) for i in range(rb.num_columns)])
    out = ev.evaluate(bound)
    if isinstance(out, pa.ChunkedArray):
        out = out.combine_chunks()
    return out


_PD_AGG = {
    AggFn.SUM: "sum",
    AggFn.MIN: "min",
    AggFn.MAX: "max",
    AggFn.AVG: "mean",
    AggFn.COUNT: "count",
    AggFn.COUNT_STAR: "size",
    AggFn.VAR_SAMP: "var",
    AggFn.STDDEV_SAMP: "std",
    AggFn.FIRST: "first",
    AggFn.LAST: "last",
}


def execute_host(node: S.PlanSpec) -> pd.DataFrame:
    """Interpret a PlanSpec subtree in pandas."""
    if isinstance(node, S.MemorySpec):
        return node.dataframe.copy()
    if isinstance(node, S.ScanSpec):
        import pyarrow.parquet as pq

        frames = []
        for group in node.file_groups:
            for fr in group:
                path = fr.path if hasattr(fr, "path") else fr
                frames.append(
                    pq.read_table(path, columns=list(node.projection)
                                  if node.projection else None).to_pandas()
                )
        df = pd.concat(frames, ignore_index=True)
        if node.predicate is not None:
            mask = _eval_expr_pd(df, node.predicate).to_pandas()
            df = df[mask.fillna(False).to_numpy(dtype=bool)]
        return df.reset_index(drop=True)
    if isinstance(node, S.ProjectSpec):
        df = execute_host(node.children[0])
        out = {}
        for e, name in node.exprs:
            out[name] = _eval_expr_pd(df, e).to_pandas()
        return pd.DataFrame(out)
    if isinstance(node, S.FilterSpec):
        df = execute_host(node.children[0])
        mask = _eval_expr_pd(df, node.predicate).to_pandas()
        return df[mask.fillna(False).to_numpy(dtype=bool)].reset_index(
            drop=True
        )
    if isinstance(node, S.SortSpec):
        df = execute_host(node.children[0])
        cols, ascs, poss = [], [], []
        tmp = df.copy()
        for i, (e, asc, nf) in enumerate(node.keys):
            cname = f"__sk{i}"
            tmp[cname] = _eval_expr_pd(df, e).to_pandas()
            cols.append(cname)
            ascs.append(asc)
            poss.append("first" if nf else "last")
        tmp = tmp.sort_values(
            cols, ascending=ascs, kind="stable",
            na_position=poss[0] if poss else "first",
        ).drop(columns=cols)
        if node.fetch:
            tmp = tmp.head(node.fetch)
        return tmp.reset_index(drop=True)
    if isinstance(node, S.UnionSpec):
        return pd.concat(
            [execute_host(c) for c in node.children], ignore_index=True
        )
    if isinstance(node, S.LimitSpec):
        return execute_host(node.children[0]).head(node.limit).reset_index(
            drop=True
        )
    if isinstance(node, S.AggSpec):
        df = execute_host(node.children[0])
        key_names = []
        tmp = pd.DataFrame(index=df.index)
        for e, name in node.keys:
            tmp[name] = _eval_expr_pd(df, e).to_pandas()
            key_names.append(name)
        agg_cols = {}
        for i, (a, name) in enumerate(node.aggs):
            if a.child is not None:
                tmp[f"__a{i}"] = _eval_expr_pd(df, a.child).to_pandas()
            else:
                tmp[f"__a{i}"] = 1
        if key_names:
            g = tmp.groupby(key_names, dropna=False, sort=False)
            out = pd.DataFrame()
            parts = {}
            for i, (a, name) in enumerate(node.aggs):
                fn = _PD_AGG[a.fn]
                col = g[f"__a{i}"]
                parts[name] = getattr(col, fn)() if fn != "size" \
                    else col.size()
            out = pd.DataFrame(parts).reset_index()
            return out
        parts = {}
        for i, (a, name) in enumerate(node.aggs):
            fn = _PD_AGG[a.fn]
            col = tmp[f"__a{i}"]
            parts[name] = [
                getattr(col, fn)() if fn != "size" else len(col)
            ]
        return pd.DataFrame(parts)
    if isinstance(node, S.JoinSpec):
        l = execute_host(node.children[0])
        r = execute_host(node.children[1])
        how = {
            "inner": "inner", "left": "left", "right": "right",
            "full": "outer",
        }.get(node.join_type)
        if how is None:
            lk = list(node.left_keys)
            rk = list(node.right_keys)
            matched = l.merge(
                r[rk].drop_duplicates(), left_on=lk, right_on=rk,
                how="inner",
            )[l.columns]
            if node.join_type == "left_semi":
                out = matched.drop_duplicates()
            elif node.join_type == "left_anti_null_aware":
                if r[rk].isna().any().any():
                    out = l.iloc[0:0]  # any build NULL -> empty (NOT IN)
                else:
                    key = l[lk].apply(tuple, axis=1)
                    mkey = set(matched[lk].apply(tuple, axis=1))
                    out = l[~key.isin(mkey) & l[lk].notna().all(axis=1)]
            else:  # left_anti
                key = l[lk].apply(tuple, axis=1)
                mkey = set(matched[lk].apply(tuple, axis=1))
                out = l[~key.isin(mkey)]
            df = out.reset_index(drop=True)
        else:
            df = l.merge(
                r, left_on=list(node.left_keys),
                right_on=list(node.right_keys), how=how,
            )
        if node.condition is not None:
            mask = _eval_expr_pd(df, node.condition).to_pandas()
            df = df[mask.fillna(False).to_numpy(dtype=bool)]
        return df.reset_index(drop=True)
    if isinstance(node, S.ExchangeSpec):
        # partitioning is a no-op for the single-frame host engine
        return execute_host(node.children[0])
    if isinstance(node, S.WindowSpec):
        return _execute_window(node)
    raise NotImplementedError(type(node))


def _execute_window(node: S.WindowSpec) -> pd.DataFrame:
    """Window functions stay host-tier (the reference keeps Window on the
    JVM too, inserting row barriers before it - BlazeConverters.scala:
    93-107). Supported: row_number, rank, dense_rank, lag, lead, and
    sum/min/max/avg/count over the whole partition frame."""
    df = execute_host(node.children[0])
    out = df.copy()
    pb = list(node.partition_by)
    ob = list(node.order_by)
    fn = node.function
    ordered = df.sort_values(ob, kind="stable") if ob else df

    def grouped(frame):
        return frame.groupby(pb, sort=False) if pb else None

    if fn == "row_number":
        g = grouped(ordered)
        rn = (g.cumcount() + 1) if g is not None else pd.Series(
            np.arange(1, len(ordered) + 1), index=ordered.index
        )
        out[node.output] = rn.sort_index()
        return out
    if fn in ("rank", "dense_rank"):
        method = "min" if fn == "rank" else "dense"
        key = df[ob[0]] if len(ob) == 1 else df[ob].apply(tuple, axis=1)
        if pb:
            r = key.groupby(
                [df[c] for c in pb], sort=False
            ).rank(method=method)
        else:
            r = key.rank(method=method)
        out[node.output] = r.astype(np.int64)
        return out
    if fn in ("lag", "lead"):
        shift = 1 if fn == "lag" else -1
        src = node.source or ob[0]
        g = grouped(ordered)
        s = (
            g[src].shift(shift) if g is not None
            else ordered[src].shift(shift)
        )
        out[node.output] = s.sort_index()
        return out
    if fn in ("sum", "min", "max", "mean", "avg", "count"):
        src = node.source or ob[0]
        agg = "mean" if fn == "avg" else fn
        if pb:
            s = df.groupby(pb, sort=False)[src].transform(agg)
        else:
            s = pd.Series(
                getattr(df[src], agg)(), index=df.index
            )
        out[node.output] = s
        return out
    raise NotImplementedError(fn)


# ---------------------------------------------------------------------------
# graceful degradation: native partition -> host re-execution
# ---------------------------------------------------------------------------


def op_to_spec(op: PhysicalOp,
               partition: Optional[int] = None) -> Optional[S.PlanSpec]:
    """Best-effort reverse mapping PhysicalOp -> PlanSpec so a partition
    that failed RESOURCE_EXHAUSTED on device can re-run through the
    pandas host engine (the native->Spark degradation analog,
    SURVEY 5.3). `partition` narrows leaf scans to ONE partition's
    inputs; interior ops keep their (already bound) expressions - the
    host evaluator resolves BoundCol positionally against the same
    child schema order the device tier used.

    Returns None when any node has no host equivalent (fused pipelines,
    partial/final aggregates, exchanges); the caller then re-raises the
    original device error instead of degrading."""
    from blaze_tpu.ops.filter import FilterExec
    from blaze_tpu.ops.hash_aggregate import AggMode, HashAggregateExec
    from blaze_tpu.ops.limit import LimitExec
    from blaze_tpu.ops.memory_scan import MemoryScanExec
    from blaze_tpu.ops.parquet_scan import ParquetScanExec
    from blaze_tpu.ops.project import ProjectExec
    from blaze_tpu.ops.sort import SortExec
    from blaze_tpu.ops.union import CoalescePartitionsExec, UnionExec

    if isinstance(op, HostFallbackExec):
        return op.node
    if isinstance(op, ParquetScanExec):
        groups = op.file_groups
        if partition is not None:
            if partition >= len(groups):
                # partition index does not line up with this leaf -
                # refusing beats silently un-narrowing (which would
                # duplicate every other partition's rows)
                return None
            groups = [groups[partition]]
        # the pruning predicate is an OPTIMIZATION derived from the
        # filter above the scan; dropping it is safe (the filter
        # re-applies), keeping it as a data filter would not be
        return S.ScanSpec(
            file_groups=groups, projection=op.projection,
        )
    if isinstance(op, MemoryScanExec):
        parts = op.partitions
        if partition is not None:
            if partition >= len(parts):
                return None  # see the parquet-leaf guard above
            parts = [parts[partition]]
        frames = [
            cb.to_arrow().to_pandas() for part in parts for cb in part
        ]
        if frames:
            df = pd.concat(frames, ignore_index=True)
        else:
            from blaze_tpu.types import to_arrow_schema

            df = pa.Table.from_batches(
                [], to_arrow_schema(op.schema)
            ).to_pandas()
        return S.MemorySpec(dataframe=df)
    if isinstance(op, CoalescePartitionsExec):
        # coalesce = every child partition, concatenated
        return op_to_spec(op.children[0], None)
    if isinstance(op, UnionExec):
        if partition is None:
            kids = [op_to_spec(c, None) for c in op.children]
            if any(k is None for k in kids):
                return None
            return S.UnionSpec(children=kids)
        # a union partition IS one child partition (positional append,
        # ops/union.py execute): translate the union-global index to
        # (child, local partition) and degrade just that subtree
        for child in op.children:
            n = child.partition_count
            if partition < n:
                return op_to_spec(child, partition)
            partition -= n
        return None  # index out of range: refuse
    child = (
        op_to_spec(op.children[0], partition) if op.children else None
    )
    if op.children and child is None:
        return None
    if isinstance(op, FilterExec):
        return S.FilterSpec(children=[child], predicate=op.predicate)
    if isinstance(op, ProjectExec):
        return S.ProjectSpec(children=[child], exprs=list(op.exprs))
    if isinstance(op, SortExec):
        return S.SortSpec(
            children=[child],
            keys=[(k.expr, k.ascending, k.nulls_first)
                  for k in op.keys],
            fetch=op.fetch,
        )
    if isinstance(op, LimitExec):
        return S.LimitSpec(children=[child], limit=op.limit)
    if isinstance(op, HashAggregateExec):
        if op.mode is not AggMode.COMPLETE:
            return None  # partial/final splice states positionally
        return S.AggSpec(
            children=[child], keys=list(op.keys),
            aggs=list(op.aggs), mode="complete",
        )
    return None


def execute_partition_host(op: PhysicalOp, partition: int,
                           ctx: ExecContext) -> List[pa.RecordBatch]:
    """Degradation entry: re-execute ONE partition of a native plan on
    the host engine, returning Arrow batches cast to the plan's
    schema. Raises NotImplementedError when the tree has no host
    mapping - callers treat that as 'degradation unavailable' and
    surface the original device error."""
    spec = op_to_spec(op, partition)
    if spec is None:
        raise NotImplementedError(
            f"no host mapping for {type(op).__name__} tree"
        )
    from blaze_tpu.types import to_arrow_schema

    df = execute_host(spec)
    ctx.metrics.add("degraded_rows", len(df))
    target = to_arrow_schema(op.schema)
    tbl = pa.Table.from_pandas(df, preserve_index=False)
    if tbl.schema != target:
        tbl = tbl.rename_columns(target.names).cast(target)
    out = []
    for rb in tbl.to_batches(max_chunksize=ctx.config.batch_size):
        if rb.num_rows:
            ctx.metrics.add("output_rows", rb.num_rows)
            ctx.metrics.add("output_batches", 1)
            out.append(rb)
    return out


class HostFallbackExec(PhysicalOp):
    """Run a PlanSpec subtree on the host engine and re-enter the native
    tier as device batches (ConvertToNative analog)."""

    def __init__(self, node: S.PlanSpec, num_partitions: int = 1):
        self.children = []
        self.node = node
        self._n = num_partitions
        self._df: Optional[pd.DataFrame] = None
        self._schema: Optional[Schema] = None

    def _frame(self) -> pd.DataFrame:
        if self._df is None:
            self._df = execute_host(self.node)
        return self._df

    @property
    def schema(self) -> Schema:
        if self._schema is None:
            rb = pa.RecordBatch.from_pandas(
                self._frame(), preserve_index=False
            )
            self._schema = from_arrow_schema(rb.schema)
        return self._schema

    @property
    def partition_count(self) -> int:
        return self._n

    def execute(self, partition: int, ctx: ExecContext
                ) -> Iterator[ColumnBatch]:
        df = self._frame()
        n = len(df)
        per = (n + self._n - 1) // self._n if self._n else n
        lo = partition * per
        hi = min(n, lo + per)
        if hi <= lo:
            return
        rb = pa.RecordBatch.from_pandas(
            df.iloc[lo:hi], preserve_index=False
        )
        bs = ctx.config.batch_size
        for start in range(0, rb.num_rows, bs):
            yield ColumnBatch.from_arrow(
                rb.slice(start, min(bs, rb.num_rows - start))
            )
