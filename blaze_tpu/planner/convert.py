"""Convert strategy + converters: PlanSpec -> native operator tree with
per-node fallback.

Reference behavior being reproduced (SURVEY 2.2, 3.1):
- every node is tagged convertible/not by DRY-RUNNING its conversion
  (BlazeConvertStrategy.scala:93-101)
- per-op enable gates (spark.blaze.enable.*, BlazeConverters.scala:76-91)
- exchanges and scans always convert when possible
  (BlazeConvertStrategy.scala:118-123)
- conversion errors fall back per node, never failing the query
  (tryConvert, BlazeConverters.scala:137-157)
- join conditions become a native Filter above the join
  (BlazeConverters.scala:244-301)
- host<->native boundaries get explicit bridges: HostFallbackExec wraps
  host subtrees under native parents (ConvertToNative analog), and native
  subtrees under host parents are collected through run_plan (the
  ConvertToUnsafeRow direction)
"""

from __future__ import annotations

import dataclasses
import logging
from typing import List, Optional

from blaze_tpu.exprs import ir
from blaze_tpu.ops import (
    AggMode,
    FilterExec,
    HashAggregateExec,
    HashJoinExec,
    JoinType,
    LimitExec,
    ProjectExec,
    SortExec,
    SortKey,
    SortMergeJoinExec,
    UnionExec,
)
from blaze_tpu.ops.base import PhysicalOp
from blaze_tpu.ops.memory_scan import MemoryScanExec
from blaze_tpu.ops.parquet_scan import ParquetScanExec
from blaze_tpu.parallel.exchange import (
    BroadcastExchangeExec,
    ShuffleExchangeExec,
)
from blaze_tpu.planner import spec as S
from blaze_tpu.planner.host_engine import HostFallbackExec, execute_host

log = logging.getLogger("blaze_tpu.planner")

_JT = {
    "inner": JoinType.INNER,
    "left": JoinType.LEFT,
    "right": JoinType.RIGHT,
    "full": JoinType.FULL,
    "left_semi": JoinType.LEFT_SEMI,
    "left_anti": JoinType.LEFT_ANTI,
    "left_anti_null_aware": JoinType.LEFT_ANTI_NULL_AWARE,
}

_MODE = {
    "partial": AggMode.PARTIAL,
    "final": AggMode.FINAL,
    "complete": AggMode.COMPLETE,
}


@dataclasses.dataclass
class ConvertStrategy:
    """Per-op enable gates + heuristics (reference
    spark.blaze.enable.{scan,project,filter,sort,union,smj,bhj,aggr} and
    strategy switches, BlazeConverters.scala:76-91 /
    BlazeConvertStrategy.scala:43-82)."""

    enable_scan: bool = True
    enable_project: bool = True
    enable_filter: bool = True
    enable_sort: bool = True
    enable_union: bool = True
    enable_limit: bool = True
    enable_smj: bool = True
    enable_bhj: bool = True
    enable_aggr: bool = True
    enable_exchange: bool = True
    # the reference keeps Window host-side; this engine runs the common
    # window functions natively on device (ops/window.py) and falls back
    # to the host engine for the rest
    enable_window: bool = True

    # ---- strategy heuristics (BlazeConvertStrategy.scala:159-265) ----
    # Long continuously-fusable chains: the reference DECLINES to convert
    # them (length >= threshold) because JVM whole-stage codegen amortizes
    # long chains well (scala:191-221). This engine's fused pipelines
    # amortize even better (one XLA program), so the switch defaults OFF;
    # the mechanism is here for parity and for embedders whose host tier
    # is codegen-strong.
    continuous_codegen_threshold: int = 5
    enable_codegen_chain_heuristic: bool = False
    # A convertible scan whose PARENT stays host-side only buys a native
    # island plus two extra boundary crossings - keep it host-side
    # (scala:223-233).
    enable_scan_parent_heuristic: bool = True
    # Children of a non-convertible aggregate: the boundary would land
    # mid-aggregation; keep the subtree together (scala:234-265).
    enable_agg_child_heuristic: bool = True

    def gate(self, node: S.PlanSpec) -> bool:
        table = {
            S.ScanSpec: self.enable_scan,
            S.MemorySpec: True,
            S.ProjectSpec: self.enable_project,
            S.FilterSpec: self.enable_filter,
            S.SortSpec: self.enable_sort,
            S.UnionSpec: self.enable_union,
            S.LimitSpec: self.enable_limit,
            S.AggSpec: self.enable_aggr,
            S.ExchangeSpec: self.enable_exchange,
            S.WindowSpec: self.enable_window,
        }
        if isinstance(node, S.JoinSpec):
            return self.enable_smj if node.kind == "smj" else self.enable_bhj
        return table.get(type(node), False)


def convert_plan(root: S.PlanSpec,
                 strategy: Optional[ConvertStrategy] = None,
                 fuse: bool = True) -> PhysicalOp:
    """Convert a PlanSpec tree to an executable operator tree with
    per-node host fallback, then fuse stateless chains into single
    device programs (ops/fused.py)."""
    strategy = strategy or ConvertStrategy()
    _tag(root, strategy)
    _apply_heuristics(root, strategy)
    op = _build(root, strategy)
    if fuse:
        from blaze_tpu.ops.fused import fuse_pipelines

        op = fuse_pipelines(op)
    return op


# ---------------------------------------------------------------------------

def _tag(node: S.PlanSpec, strategy: ConvertStrategy) -> None:
    """Bottom-up dry-run tagging (convertibleTag analog)."""
    for c in node.children:
        _tag(c, strategy)
    if node.strategy == "never" or not strategy.gate(node):
        node.convertible = False
        return
    try:
        _check_convertible(node)
        node.convertible = True
    except Exception as e:
        log.debug("node %s not convertible: %s", type(node).__name__, e)
        node.convertible = False


def _apply_heuristics(root: S.PlanSpec,
                      strategy: ConvertStrategy) -> None:
    """Post-tagging strategy heuristics (BlazeConvertStrategy.scala:
    159-265): refine the convertible tags using PARENT context, which
    the bottom-up dry run cannot see."""

    def walk(node: S.PlanSpec, parent: Optional[S.PlanSpec]) -> None:
        if (
            strategy.enable_scan_parent_heuristic
            and isinstance(node, (S.ScanSpec, S.MemorySpec))
            and node.convertible
            and parent is not None
            and not parent.convertible
        ):
            # a native scan island under a host parent costs two extra
            # boundary crossings for zero fused work
            node.convertible = False
        if (
            strategy.enable_agg_child_heuristic
            and isinstance(node, S.AggSpec)
            and not node.convertible
        ):
            # keep the WHOLE aggregation subtree together (down to the
            # next exchange, which is a legitimate boundary anyway) -
            # a native island mid-aggregation costs two crossings
            def demote(n: S.PlanSpec) -> None:
                if isinstance(n, S.ExchangeSpec):
                    return
                n.convertible = False
                for cc in n.children:
                    demote(cc)

            for c in node.children:
                demote(c)
        for c in node.children:
            walk(c, node)

    def chain_pass(node: S.PlanSpec) -> None:
        # maximal chains of fusable narrow ops: the reference declines
        # chains >= threshold (JVM codegen amortizes them); gated OFF by
        # default here - see ConvertStrategy
        chain: list = []
        t = node
        while isinstance(t, (S.ProjectSpec, S.FilterSpec)) and \
                t.convertible and len(t.children) == 1:
            chain.append(t)
            t = t.children[0]
        if len(chain) >= strategy.continuous_codegen_threshold:
            for n in chain:
                n.convertible = False
        for c in t.children if chain else node.children:
            chain_pass(c)

    walk(root, None)
    if strategy.enable_codegen_chain_heuristic:
        chain_pass(root)


def _check_convertible(node: S.PlanSpec) -> None:
    """Cheap structural dry-run (full conversion happens in _build under
    tryConvert anyway)."""
    if isinstance(node, S.JoinSpec):
        if node.join_type not in _JT:
            raise NotImplementedError(node.join_type)
        if not node.left_keys or not node.right_keys:
            raise NotImplementedError("non-equi joins run on host")
        if node.skewed:
            raise NotImplementedError(
                "skew joins stay host-side (reference strategy)"
            )
    if isinstance(node, S.AggSpec) and node.mode not in _MODE:
        raise NotImplementedError(node.mode)
    if isinstance(node, S.ExchangeSpec) and node.mode not in (
        "hash", "single", "round_robin", "range", "broadcast"
    ):
        raise NotImplementedError(node.mode)
    if isinstance(node, S.WindowSpec):
        if node.function not in (
            "row_number", "rank", "dense_rank", "lag", "lead",
            "sum", "min", "max", "count", "avg",
        ):
            raise NotImplementedError(
                f"window fn {node.function} runs on host"
            )


def _smj_inputs_sorted(node: "S.JoinSpec") -> bool:
    """True when both join inputs carry a sort guarantee whose leading
    keys are exactly the join keys ascending - Spark plants SortExec
    under SMJ the same way, so a SortSpec child is the guarantee."""
    from blaze_tpu.exprs import ir

    def guaranteed(child: S.PlanSpec, keys) -> bool:
        if not isinstance(child, S.SortSpec) or child.convertible is False:
            return False
        lead = list(child.keys)[: len(keys)]
        if len(lead) < len(keys):
            return False
        for (e, asc, _nf), name in zip(lead, keys):
            if not asc:
                return False
            if not (isinstance(e, ir.Col) and e.name == name):
                return False
        return True

    return guaranteed(node.children[0], list(node.left_keys)) and \
        guaranteed(node.children[1], list(node.right_keys))


def _build(node: S.PlanSpec, strategy: ConvertStrategy) -> PhysicalOp:
    if not node.convertible:
        return HostFallbackExec(node)
    try:
        return _convert_native(node, strategy)
    except Exception as e:  # tryConvert: per-node fallback
        log.warning(
            "conversion of %s failed, falling back to host: %s",
            type(node).__name__, e,
        )
        return HostFallbackExec(node)


def _child(node: S.PlanSpec, strategy: ConvertStrategy, i: int = 0
           ) -> PhysicalOp:
    return _build(node.children[i], strategy)


def _convert_native(node: S.PlanSpec, strategy: ConvertStrategy
                    ) -> PhysicalOp:
    if isinstance(node, S.MemorySpec):
        import pyarrow as pa

        from blaze_tpu.batch import ColumnBatch

        rb = pa.RecordBatch.from_pandas(
            node.dataframe, preserve_index=False
        )
        n = rb.num_rows
        per = (n + node.partitions - 1) // node.partitions
        parts = []
        schema = None
        for p in range(node.partitions):
            sl = rb.slice(p * per, max(0, min(per, n - p * per)))
            cb = ColumnBatch.from_arrow(sl)
            schema = cb.schema
            parts.append([cb] if sl.num_rows else [])
        return MemoryScanExec(parts, schema)
    if isinstance(node, S.ScanSpec):
        scan = ParquetScanExec(
            node.file_groups,
            projection=list(node.projection) if node.projection else None,
            pruning_predicate=node.predicate,
        )
        if node.predicate is not None:
            # pruning skips row groups; exact filtering still applies
            return FilterExec(scan, node.predicate)
        return scan
    if isinstance(node, S.ProjectSpec):
        return ProjectExec(_child(node, strategy), list(node.exprs))
    if isinstance(node, S.FilterSpec):
        return FilterExec(_child(node, strategy), node.predicate)
    if isinstance(node, S.SortSpec):
        return SortExec(
            _child(node, strategy),
            [SortKey(e, asc, nf) for e, asc, nf in node.keys],
            fetch=node.fetch,
        )
    if isinstance(node, S.UnionSpec):
        return UnionExec(
            [_build(c, strategy) for c in node.children]
        )
    if isinstance(node, S.LimitSpec):
        return LimitExec(_child(node, strategy), node.limit)
    if isinstance(node, S.AggSpec):
        return HashAggregateExec(
            _child(node, strategy),
            keys=list(node.keys),
            aggs=list(node.aggs),
            mode=_MODE[node.mode],
        )
    if isinstance(node, S.JoinSpec):
        left = _child(node, strategy, 0)
        right = _child(node, strategy, 1)
        jt = _JT[node.join_type]
        if node.kind == "bhj":
            out: PhysicalOp = HashJoinExec(
                left, right, list(node.left_keys),
                list(node.right_keys), jt,
            )
        else:
            out = None
            if _smj_inputs_sorted(node):
                # sort-guaranteed inputs take the streaming merge (the
                # reference's flagship operator, sort_merge_join_exec.rs:
                # 293-601); string keys fall through to materializing
                from blaze_tpu.ops.streaming_smj import (
                    StreamingSortMergeJoinExec,
                )

                try:
                    out = StreamingSortMergeJoinExec(
                        left, right, list(node.left_keys),
                        list(node.right_keys), jt,
                    )
                except NotImplementedError:
                    out = None
            if out is None:
                out = SortMergeJoinExec(
                    left, right, list(node.left_keys),
                    list(node.right_keys), jt,
                )
        if node.condition is not None:
            # join conditions become a native filter above the join
            out = FilterExec(out, node.condition)
        return out
    if isinstance(node, S.ExchangeSpec):
        child = _child(node, strategy)
        if node.mode == "broadcast":
            return BroadcastExchangeExec(child)
        return ShuffleExchangeExec(
            child, list(node.keys), node.num_partitions, node.mode
        )
    if isinstance(node, S.WindowSpec):
        from blaze_tpu.exprs.ir import Col
        from blaze_tpu.ops.sort import SortKey
        from blaze_tpu.ops.window import WindowExec, WindowFn

        child = _child(node, strategy)
        src = (
            Col(node.source) if node.source
            else (Col(node.order_by[0]) if node.order_by else None)
        )
        return WindowExec(
            child,
            partition_by=[Col(c) for c in node.partition_by],
            order_by=[SortKey(Col(c)) for c in node.order_by],
            functions=[WindowFn(node.function, src, node.output)],
        )
    raise NotImplementedError(type(node))
