"""Shared-memory Arrow arena (zero-copy serve path).

Finalized result parts are stored ONCE, already encoded in the exact
wire framing FETCH streams (`u64 len | zstd(Arrow IPC)` per part,
io/ipc.encode_ipc_segment), inside mmap'd segment files. Two serve
modes read them:

  scatter-gather -- the socket byte path sends the segment's frames as
                    a buffer list of mmap-backed memoryviews
                    (writev-style, runtime/transport.sendmsg_all): no
                    re-encode, no concatenated reply, bytes identical
                    to the per-batch encode path by construction.
  handle         -- a co-located client receives {path, offsets,
                    lengths, lease} instead of bytes and maps the
                    segment itself. Leases are refcounted with a TTL:
                    an orphaned lease (client crashed before RELEASE)
                    is reaped and the segment becomes evictable again.

Degradation is the contract: every failure inside the arena (mmap or
write failure, stale lease, chaos seams `zerocopy.map` and
`zerocopy.lease`) answers None and the caller falls back to the
socket byte path - zero client-visible failures.
"""

from __future__ import annotations

import itertools
import logging
import mmap
import os
import tempfile
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from blaze_tpu.testing import chaos

log = logging.getLogger("blaze_tpu.zerocopy.arena")


class _Segment:
    __slots__ = ("key", "path", "mm", "file", "offsets", "lengths",
                 "nbytes", "generation", "leases", "last_used")

    def __init__(self, key, path, mm, file, offsets, lengths, nbytes,
                 generation):
        self.key = key
        self.path = path
        self.mm = mm
        self.file = file
        self.offsets = offsets
        self.lengths = lengths
        self.nbytes = nbytes
        self.generation = generation
        self.leases = 0
        self.last_used = time.monotonic()


class ArrowArena:
    """Bounded mmap segment store: result key -> encoded part frames.

    Keys are result-cache fingerprints (content-addressed over the
    plan), so a segment can never serve stale bytes - the same
    determinism assumption the ResultCache already makes. Budget
    eviction is LRU over UNLEASED segments; a leased segment is pinned
    until every lease is released or TTL-reaped."""

    def __init__(self, directory: Optional[str] = None,
                 max_bytes: int = 256 << 20,
                 lease_ttl_s: float = 30.0):
        self.max_bytes = max(0, int(max_bytes))
        self.lease_ttl_s = float(lease_ttl_s)
        self._own_dir = directory is None
        self.directory = (
            directory if directory is not None
            else tempfile.mkdtemp(prefix="blaze-arena-")
        )
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.Lock()
        self._segments: "OrderedDict[str, _Segment]" = OrderedDict()
        self._leases: Dict[int, Tuple[str, float]] = {}
        self._lease_ids = itertools.count(1)
        self._generations = itertools.count(1)
        self._bytes = 0
        self._closed = False
        self.counters = {
            "published": 0,
            "publish_skipped": 0,
            "evictions": 0,
            "handle_hits": 0,
            "handle_misses": 0,
            "sg_serves": 0,
            "lease_releases": 0,
            "lease_orphans_reaped": 0,
            "map_failures": 0,
            "lease_faults": 0,
        }

    # -- publish --------------------------------------------------------
    def publish(self, key: str, frames: Sequence[bytes]) -> bool:
        """Store one result's encoded part frames under `key`.
        Idempotent (first publish wins); False means the arena
        declined (present, over budget, closed, or the `zerocopy.map`
        seam / a real mmap failure fired) and the caller keeps the
        byte path."""
        if self._closed or self.max_bytes <= 0 or not key:
            return False
        frames = [f for f in frames if f]
        nbytes = sum(len(f) for f in frames)
        if not frames or nbytes > self.max_bytes:
            with self._lock:
                self.counters["publish_skipped"] += 1
            return False
        with self._lock:
            if key in self._segments:
                self.counters["publish_skipped"] += 1
                return False
        gen = next(self._generations)
        path = os.path.join(self.directory, f"seg-{gen}.arena")
        try:
            if chaos.ACTIVE:
                chaos.fire("zerocopy.map", key=key, nbytes=nbytes,
                           path=path)
            with open(path, "wb") as f:
                for frame in frames:
                    f.write(frame)
            file = open(path, "rb")  # noqa: SIM115 - lives in segment
            mm = mmap.mmap(file.fileno(), 0, access=mmap.ACCESS_READ)
        except Exception as e:  # noqa: BLE001 - degrade to byte path
            with self._lock:
                self.counters["map_failures"] += 1
            try:
                os.unlink(path)
            except OSError:
                pass
            log.debug("arena publish degraded for %s: %s", key, e)
            return False
        offsets: List[int] = []
        lengths: List[int] = []
        off = 0
        for frame in frames:
            offsets.append(off)
            lengths.append(len(frame))
            off += len(frame)
        seg = _Segment(key, path, mm, file, offsets, lengths, nbytes,
                       gen)
        drop: List[_Segment] = []
        with self._lock:
            if self._closed or key in self._segments:
                drop.append(seg)
            else:
                self._segments[key] = seg
                self._bytes += nbytes
                self.counters["published"] += 1
                drop.extend(self._evict_locked())
        for s in drop:
            self._destroy(s)
        return not (drop and drop[0] is seg)

    def _evict_locked(self) -> List[_Segment]:
        """LRU-evict unleased segments until under budget. Caller
        holds the lock; actual unmap/unlink happens outside it."""
        out: List[_Segment] = []
        while self._bytes > self.max_bytes:
            victim_key = None
            for k, seg in self._segments.items():
                if seg.leases <= 0:
                    victim_key = k
                    break
            if victim_key is None:
                break  # everything pinned by leases
            seg = self._segments.pop(victim_key)
            self._bytes -= seg.nbytes
            self.counters["evictions"] += 1
            out.append(seg)
        return out

    @staticmethod
    def _destroy(seg: _Segment) -> None:
        try:
            seg.mm.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            seg.file.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            os.unlink(seg.path)
        except OSError:
            pass

    # -- serve ----------------------------------------------------------
    def buffers(self, key: str,
                start_part: int = 0) -> Optional[List[memoryview]]:
        """Scatter-gather source: one mmap-backed memoryview per frame
        from `start_part` on, or None (caller re-encodes). The views
        alias the segment mmap; the GIL plus the fact that segments
        are destroyed only via _destroy AFTER eviction keeps them
        valid for the duration of a send loop - callers must not hold
        them across requests."""
        with self._lock:
            seg = self._segments.get(key)
            if seg is None:
                return None
            if start_part >= len(seg.offsets):
                return []
            seg.last_used = time.monotonic()
            self._segments.move_to_end(key)
            self.counters["sg_serves"] += 1
            view = memoryview(seg.mm)
            return [
                view[seg.offsets[i]:seg.offsets[i] + seg.lengths[i]]
                for i in range(start_part, len(seg.offsets))
            ]

    def handle(self, key: str,
               start_part: int = 0) -> Optional[dict]:
        """Lease the segment to a co-located client: returns the
        JSON-serializable handle (path + frame geometry + lease id) or
        None when the key is absent / the lease seam fired (degrade to
        bytes)."""
        self.reap()
        with self._lock:
            seg = self._segments.get(key)
            if seg is None or self._closed:
                self.counters["handle_misses"] += 1
                return None
        try:
            if chaos.ACTIVE:
                chaos.fire("zerocopy.lease", key=key)
        except Exception:  # noqa: BLE001 - stale-lease seam
            with self._lock:
                self.counters["lease_faults"] += 1
            return None
        with self._lock:
            seg = self._segments.get(key)
            if seg is None:
                self.counters["handle_misses"] += 1
                return None
            lease = next(self._lease_ids)
            seg.leases += 1
            seg.last_used = time.monotonic()
            self._segments.move_to_end(key)
            self._leases[lease] = (
                key, time.monotonic() + self.lease_ttl_s
            )
            self.counters["handle_hits"] += 1
            return {
                "path": seg.path,
                "offsets": list(seg.offsets[start_part:]),
                "lengths": list(seg.lengths[start_part:]),
                "generation": seg.generation,
                "lease": lease,
                "start_part": int(start_part),
            }

    def release(self, lease: int) -> bool:
        with self._lock:
            ent = self._leases.pop(int(lease), None)
            if ent is None:
                return False
            self.counters["lease_releases"] += 1
            seg = self._segments.get(ent[0])
            if seg is not None and seg.leases > 0:
                seg.leases -= 1
        return True

    def reap(self, now: Optional[float] = None) -> int:
        """Expire orphaned leases (client died before RELEASE) so
        their segments become evictable again. Called opportunistically
        from handle() and by the service's periodic sweeps."""
        now = time.monotonic() if now is None else now
        reaped = 0
        with self._lock:
            expired = [lid for lid, (_, exp) in self._leases.items()
                       if exp <= now]
            for lid in expired:
                key, _ = self._leases.pop(lid)
                seg = self._segments.get(key)
                if seg is not None and seg.leases > 0:
                    seg.leases -= 1
                reaped += 1
            self.counters["lease_orphans_reaped"] += reaped
        return reaped

    # -- lifecycle ------------------------------------------------------
    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._segments

    def stats(self) -> dict:
        with self._lock:
            return {
                "segments": len(self._segments),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "active_leases": len(self._leases),
                "lease_ttl_s": self.lease_ttl_s,
                **self.counters,
            }

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            segs = list(self._segments.values())
            self._segments.clear()
            self._leases.clear()
            self._bytes = 0
        for seg in segs:
            self._destroy(seg)
        if self._own_dir:
            try:
                os.rmdir(self.directory)
            except OSError:
                pass


def map_handle_frames(handle: dict) -> List[bytes]:
    """Client side of the shm path: map the leased segment and slice
    out the encoded part frames. Raises on ANY problem (missing file,
    truncated segment, chaos seams) - the caller treats every raise as
    a stale lease and falls back to a byte-path FETCH."""
    path = handle["path"]
    offsets = [int(o) for o in handle["offsets"]]
    lengths = [int(n) for n in handle["lengths"]]
    if len(offsets) != len(lengths):
        raise ValueError("malformed arena handle")
    if chaos.ACTIVE:
        chaos.fire("zerocopy.map", path=path)
    with open(path, "rb") as f:
        with mmap.mmap(f.fileno(), 0,
                       access=mmap.ACCESS_READ) as mm:
            if chaos.ACTIVE:
                chaos.fire("zerocopy.lease",
                           lease=handle.get("lease"))
            end = max(
                (o + n for o, n in zip(offsets, lengths)), default=0
            )
            if end > len(mm):
                raise ValueError(
                    f"arena segment truncated: need {end} bytes, "
                    f"have {len(mm)} (stale lease)"
                )
            return [bytes(mm[o:o + n])
                    for o, n in zip(offsets, lengths)]
