"""Content-addressed decoded-plan cache (zero-copy serve path).

The serve-path profile (ROADMAP item 3) says plan decode dominates the
hot path: re-parsing the SUBMIT blob costs ~9 ms of a ~10.4 ms e2e on
the probe workload while dispatch costs 0.1 ms. Repeat plans are the
common case behind a router (affinity placement sends a digest's
repeats to the same replica on purpose), so the service keeps a small
LRU of decode RESULTS keyed by the blake2b digest of the raw blob -
the exact digest `router.placement.affinity_key` already computes, so
the router can forward it in submit meta (`plan_digest`) and the
replica never re-hashes the bytes it already paid to receive.

What a hit buys:

  metadata  -- fingerprint, fingerprint stability, the admission byte
               estimate, and the task's partition are ALWAYS reusable.
               A repeat whose result is in the ResultCache therefore
               never decodes at all (and, via the admission fast path,
               never queues for a reservation either).
  tree      -- the decoded operator tree is MUTATED in place by
               `prepare_decoded_task` (fusion / mesh lowering), so it
               is loaned to at most ONE executing query at a time via
               `borrow_tree`. A borrower that never executes (full
               cache hit) returns the pristine tree on terminal;
               a borrower that executed consumed it, and the next
               cache-missing repeat re-decodes lazily.

Thread-safe; every surface is counters-first (hits / misses /
evictions feed STATS and METRICS on both tiers).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Optional


def plan_digest(task_bytes: bytes, is_ref: bool) -> str:
    """Content digest of a raw SUBMIT blob. MUST stay byte-identical
    to `router.placement.affinity_key` (which delegates here): the
    router's placement key doubles as the replica's plan-cache key, so
    the digest travels in submit meta instead of being recomputed."""
    h = hashlib.blake2b(task_bytes, digest_size=16)
    h.update(b"ref" if is_ref else b"native")
    return h.hexdigest()


class PlanEntry:
    """One decoded plan: always-reusable metadata plus an exclusively
    loaned decoded tuple (see module docstring for the loan rule)."""

    __slots__ = ("fingerprint", "fingerprint_stable", "estimated_bytes",
                 "partition", "_tree", "_lock")

    def __init__(self, *, fingerprint: str, fingerprint_stable: bool,
                 estimated_bytes: Optional[int], partition: int,
                 tree: Any = None):
        self.fingerprint = fingerprint
        self.fingerprint_stable = bool(fingerprint_stable)
        self.estimated_bytes = estimated_bytes
        self.partition = int(partition)
        self._tree = tree
        self._lock = threading.Lock()

    def borrow_tree(self) -> Any:
        """Take the decoded tuple out of the entry (or None when a
        concurrent borrower holds it / an execution consumed it)."""
        with self._lock:
            tree, self._tree = self._tree, None
            return tree

    def restore_tree(self, tree: Any) -> None:
        """Return a PRISTINE (never-prepared) decoded tuple. Callers
        must not restore a tree that went through
        `prepare_decoded_task` - fusion mutated it in place."""
        if tree is None:
            return
        with self._lock:
            if self._tree is None:
                self._tree = tree

    @property
    def has_tree(self) -> bool:
        with self._lock:
            return self._tree is not None


class DecodedPlanCache:
    """Bounded thread-safe LRU: digest -> PlanEntry."""

    def __init__(self, max_entries: int = 256):
        self.max_entries = max(1, int(max_entries))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, PlanEntry]" = OrderedDict()
        self.counters = {
            "hits": 0,
            "misses": 0,
            "evictions": 0,
            "puts": 0,
            # metadata hit whose tree was already loaned/consumed: the
            # repeat still skips decode unless it must execute
            "tree_unavailable": 0,
        }

    def get(self, key: str) -> Optional[PlanEntry]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.counters["misses"] += 1
                return None
            self._entries.move_to_end(key)
            self.counters["hits"] += 1
            return entry

    def put(self, key: str, entry: PlanEntry) -> PlanEntry:
        """Insert (first writer wins: a concurrent duplicate decode
        keeps the existing entry so an outstanding loan is not
        orphaned). Returns the entry that is IN the cache."""
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                self._entries.move_to_end(key)
                return existing
            self._entries[key] = entry
            self.counters["puts"] += 1
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.counters["evictions"] += 1
            return entry

    def note_tree_unavailable(self) -> None:
        with self._lock:
            self.counters["tree_unavailable"] += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "max_entries": self.max_entries,
                **self.counters,
            }
