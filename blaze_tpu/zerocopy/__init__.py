"""Zero-copy serve path (ROADMAP item 3, docs/ARCHITECTURE.md).

Two data-plane caches that remove the decode/copy phases from the
serving hot path on both tiers:

  plan_cache -- content-addressed decoded-plan cache keyed by the
                blake2b digest of the raw SUBMIT blob (the same digest
                the router's AffinityMap computes), so a repeat plan
                skips protobuf decode and plan translation entirely.
  arena      -- shared-memory Arrow arena: finalized, already-encoded
                result part frames live in mmap'd segment files with
                refcounted TTL leases. Co-located clients FETCH a
                handle and map the bytes instead of reading them off
                the socket; remote clients are served the SAME frames
                as a scatter-gather buffer list (no re-encode, no
                concatenated reply).

Both degrade: any mmap/lease failure (chaos seams `zerocopy.map` and
`zerocopy.lease`) falls back to the socket byte path with zero
client-visible failures.
"""

from blaze_tpu.zerocopy.arena import ArrowArena, map_handle_frames
from blaze_tpu.zerocopy.plan_cache import (
    DecodedPlanCache,
    PlanEntry,
    plan_digest,
)

__all__ = [
    "ArrowArena",
    "DecodedPlanCache",
    "PlanEntry",
    "map_handle_frames",
    "plan_digest",
]
