"""Multi-query serving tier: admission control, priority scheduling,
cancellation, and a plan-fingerprint result cache over the executor.

Code map (details in docs/SERVICE.md):
  query.py     - Query record + lifecycle state machine
  admission.py - bounded priority queue, headroom + concurrency gates
  cache.py     - (fingerprint, partition) result cache, TTL/LRU/spill
  service.py   - QueryService: submit/poll/result/cancel/report, with
                 classified retries / host degradation
                 (blaze_tpu/errors.py taxonomy, docs/ROBUSTNESS.md)
  wire.py      - service verbs over the gateway socket + ServiceClient
                 (reconnect-with-backoff, re-attach by query_id)
"""

from blaze_tpu.service.admission import (
    AdmissionController,
    estimate_plan_device_bytes,
)
from blaze_tpu.service.cache import ResultCache
from blaze_tpu.service.query import (
    Query,
    QueryCancelled,
    QueryRejected,
    QueryState,
)
from blaze_tpu.service.service import QueryService
from blaze_tpu.service.wire import ServiceClient, ServiceError

__all__ = [
    "AdmissionController",
    "estimate_plan_device_bytes",
    "ResultCache",
    "Query",
    "QueryCancelled",
    "QueryRejected",
    "QueryState",
    "QueryService",
    "ServiceClient",
    "ServiceError",
]
