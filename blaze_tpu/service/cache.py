"""Plan-fingerprint result cache: serve repeated queries from memory.

Zerrow's argument (PAPERS.md) applied to the serving tier: a repeated
identical plan should cost zero device dispatches - the materialized
Arrow result IS the zero-copy currency, so cache it keyed on plan
identity. Keys are (PhysicalOp.fingerprint(), partition): fingerprints
are content-addressed (ops/base.py), so two independent decodes of the
same TaskDefinition hit the same entry. Plans containing any op that
cannot prove stable identity (the '@' marker) are never cached.

Placement follows the engine's HBM -> host -> disk ladder
(runtime/memory.py): entries hold host-side Arrow batches and register
as a spillable consumer in the MemoryPool - under host-memory pressure
the pool asks the cache to spill, and entries move to disk as
segmented-IPC files (io/ipc.py - the shuffle wire format, so a spilled
entry streams back out through the same decode path). A hit on a
spilled entry restores it transparently.

Freshness: TTL per entry plus explicit `invalidate()` (a scan's file
content can change under an unchanged path - the fingerprint cannot
see that, the TTL bounds the staleness window, invalidation closes it
on demand). Capacity: LRU on logical bytes.
"""

from __future__ import annotations

import collections
import os
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

from blaze_tpu.obs.contention import TimedRLock
from blaze_tpu.testing import chaos

CacheKey = Tuple[str, int]  # (plan fingerprint, partition id)


class _Entry:
    __slots__ = ("batches", "path", "nbytes", "expires_at")

    def __init__(self, batches, nbytes: int, expires_at: float):
        self.batches = batches          # list[pa.RecordBatch] | None
        self.path: Optional[str] = None  # spill file when batches None
        self.nbytes = nbytes
        self.expires_at = expires_at


class ResultCache:
    """TTL + LRU cache of materialized partition results, spillable
    through the engine MemoryPool."""

    def __init__(
        self,
        max_bytes: int = 256 << 20,
        ttl_s: float = 300.0,
        pool=None,
        spill_dir: Optional[str] = None,
    ):
        from blaze_tpu.config import get_config
        from blaze_tpu.runtime.memory import get_pool

        self.max_bytes = int(max_bytes)
        self.ttl_s = float(ttl_s)
        self._pool = pool if pool is not None else get_pool()
        self._dir = spill_dir or tempfile.mkdtemp(
            prefix="blaze_result_cache_",
            dir=get_config().tmp_dirs[0],
        )
        # RLock: put() -> pool.grow() may call back into _spill_some()
        # on the same thread under host-memory pressure
        self._lock = TimedRLock("result_cache")
        self._entries: "collections.OrderedDict[CacheKey, _Entry]" = (
            collections.OrderedDict()
        )
        self._spill_seq = 0
        self.counters: Dict[str, int] = {
            "hits": 0,
            "misses": 0,
            "evictions": 0,
            "spills": 0,
            "spill_errors": 0,
            "restores": 0,
            "puts": 0,
            # streaming-integrity guard: put(complete=False) attempts
            # refused - a cache entry must never hold a truncated
            # prefix of a partition (a later hit would silently serve
            # a short result)
            "partial_puts_refused": 0,
            # request coalescing (service/service.py, ROADMAP scan-
            # sharing first step): identical in-flight plans that
            # WAITED on the leader instead of re-executing
            "coalesced": 0,
        }
        self._pool.register(id(self), self._spill_some)

    # ------------------------------------------------------------------
    def get(self, key: CacheKey) -> Optional[List]:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.counters["misses"] += 1
                return None
            if time.monotonic() >= e.expires_at:
                self._evict(key, e)
                self.counters["misses"] += 1
                return None
            self._entries.move_to_end(key)
            # hold a local reference: under pool pressure the grow()
            # inside _restore may re-spill this very entry before we
            # return
            batches = (
                self._restore(e) if e.batches is None
                else list(e.batches)
            )
            self.counters["hits"] += 1
            return batches

    def note_coalesced(self) -> None:
        """Recorded by the coalescing layer in front of get(): a
        second identical in-flight submission waited on the first
        instead of re-executing. Lives on the cache's counter surface
        because coalescing IS a cache-population optimization - the
        follower's eventual get() is a hit the leader paid for."""
        with self._lock:
            self.counters["coalesced"] += 1

    def contains(self, key: CacheKey) -> bool:
        """Non-mutating presence probe (no hit/miss counters, no LRU
        touch, no spill restore): admission-time checks (predicted-
        unmeetability shedding) must not distort cache telemetry or
        recency just by asking."""
        with self._lock:
            e = self._entries.get(key)
            return e is not None and time.monotonic() < e.expires_at

    def put(self, key: CacheKey, batches: List,
            complete: bool = True) -> bool:
        """Store one partition's materialized batches. Returns False
        when the entry is larger than the whole cache (never stored).

        `complete` is the streaming-integrity contract: entries are
        finalized only after the partition's LAST part was produced.
        With incremental FETCH delivery (service/stream.py) parts
        leave the building while execution is still running - but the
        cache population point stays after the partition loop drains,
        so a concurrent probe of an in-progress query MISSES (and
        coalesces on the leader) rather than ever seeing a truncated
        prefix. Callers that only hold a partial result must say so;
        the put is refused and counted, never stored."""
        if not complete:
            with self._lock:
                self.counters["partial_puts_refused"] += 1
            return False
        nbytes = sum(rb.nbytes for rb in batches)
        if nbytes > self.max_bytes:
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._release(old)
            while (
                self._entries
                and self._logical_bytes() + nbytes > self.max_bytes
            ):
                k, e = next(iter(self._entries.items()))  # LRU head
                self._evict(k, e)
            entry = _Entry(
                list(batches), nbytes, time.monotonic() + self.ttl_s
            )
            self._entries[key] = entry
            self.counters["puts"] += 1
            # account host bytes AFTER insertion: under pool pressure
            # grow() may immediately spill this very entry to disk
            self._pool.grow(id(self), nbytes)
            return True

    def invalidate(self, fingerprint: Optional[str] = None) -> int:
        """Drop entries whose fingerprint matches (prefix match), or
        everything when None. Returns the number evicted."""
        with self._lock:
            keys = [
                k
                for k in self._entries
                if fingerprint is None or k[0].startswith(fingerprint)
            ]
            for k in keys:
                self._evict(k, self._entries[k])
            return len(keys)

    def stats(self) -> dict:
        with self._lock:
            return {
                **self.counters,
                "entries": len(self._entries),
                "bytes": self._logical_bytes(),
                "spilled_entries": sum(
                    1 for e in self._entries.values()
                    if e.batches is None
                ),
            }

    def close(self) -> None:
        with self._lock:
            for k in list(self._entries):
                self._evict(k, self._entries[k])
            self._pool.unregister(id(self))

    # ------------------------------------------------------------------
    def _logical_bytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    def _release(self, e: _Entry) -> None:
        if e.batches is not None:
            self._pool.shrink(id(self), e.nbytes)
        if e.path:
            try:
                os.unlink(e.path)
            except OSError:
                pass
            e.path = None

    def _evict(self, key: CacheKey, e: _Entry) -> None:
        self._entries.pop(key, None)
        self._release(e)
        self.counters["evictions"] += 1

    def _spill_some(self) -> int:
        """MemoryPool spill callback: move in-memory entries to disk
        LRU-first, stopping once the released bytes cover the pool's
        current overage (spilling the whole cache for a few-byte
        overshoot would cold-start every hot entry). Returns the host
        bytes released; the pool adjusts its accounting from that
        (memory.py grow)."""
        with self._lock:
            need = max(
                0, self._pool.total_used() - self._pool.budget
            )
            freed = 0
            for e in list(self._entries.values()):  # LRU head first
                if freed >= need and freed > 0:
                    break
                if e.batches is None:
                    continue
                try:
                    self._spill_entry(e)
                except Exception as err:  # noqa: BLE001 - degrade
                    # a spill IO failure (disk full, transient FS
                    # error) must not fail the serving path: the entry
                    # simply STAYS in memory and the pool gets less
                    # relief - graceful degradation, observable via
                    # the counter
                    self.counters["spill_errors"] += 1
                    import logging

                    logging.getLogger("blaze_tpu.service").warning(
                        "result-cache spill failed (entry kept in "
                        "memory): %s", err,
                    )
                    continue
                freed += e.nbytes
            return freed

    def _spill_entry(self, e: _Entry) -> None:
        from blaze_tpu.io.ipc import encode_ipc_segment

        if chaos.ACTIVE:
            # chaos seam: spill-file write failure
            chaos.fire("cache.spill", dir=self._dir)
        self._spill_seq += 1
        path = os.path.join(self._dir, f"rc-{self._spill_seq}.seg")
        try:
            with open(path, "wb") as f:
                for rb in e.batches:
                    f.write(encode_ipc_segment(rb))
        except Exception:
            # never leave a truncated spill file behind: a later
            # restore would decode garbage
            try:
                os.unlink(path)
            except OSError:
                pass
            raise
        e.path = path
        e.batches = None
        self.counters["spills"] += 1

    def _restore(self, e: _Entry) -> List:
        from blaze_tpu.io.ipc import decode_ipc_parts

        with open(e.path, "rb") as f:
            batches = list(decode_ipc_parts(f.read()))
        try:
            os.unlink(e.path)
        except OSError:
            pass
        e.path = None
        e.batches = batches
        self.counters["restores"] += 1
        self._pool.grow(id(self), e.nbytes)
        return batches
