"""QueryService: the multi-query serving tier over the executor.

What the reference inherits from Spark (driver scheduling, task slots,
result handling - SURVEY 2.2), a standalone TPU engine must own. The
service composes the pieces this package provides:

  submit  -> Query (service/query.py state machine), bounded priority
             admission (service/admission.py), or REJECTED_OVERLOADED
  dispatch-> one dispatcher thread admits by priority/FIFO/headroom and
             hands queries to a worker pool sized to max_concurrency
  run     -> the UNCHANGED executor path (prepare_decoded_task ->
             execute_partition), with cooperative cancel/deadline
             checks between batches (the executor's GeneratorExit
             cancellation contract, runtime/executor.py)
  reuse   -> materialized results cached by (plan fingerprint,
             partition) when the fingerprint is stable
             (service/cache.py); a full cache hit dispatches NOTHING
  observe -> per-query queue/admission/execution timings + the
             dispatch.* counters + the mirrored operator metric tree,
             one report via runtime/instrument.render_metrics

Wire surface lives in service/wire.py; `python -m blaze_tpu serve`
starts both.
"""

from __future__ import annotations

import concurrent.futures as cf
import logging
import threading
import time
from typing import Dict, List, Optional

from blaze_tpu.service.admission import (
    AdmissionController,
    estimate_plan_device_bytes,
)
from blaze_tpu.service.cache import ResultCache
from blaze_tpu.service.query import (
    Query,
    QueryCancelled,
    QueryState,
)

log = logging.getLogger("blaze_tpu.service")

_MAX_RETAINED = 1024  # terminal queries kept for poll/report


class QueryService:
    def __init__(
        self,
        max_concurrency: int = 2,
        max_queue_depth: int = 64,
        cache: Optional[ResultCache] = None,
        enable_cache: bool = True,
        device_tracker=None,
        default_deadline_s: Optional[float] = None,
    ):
        self.admission = AdmissionController(
            device_tracker=device_tracker,
            max_concurrency=max_concurrency,
            max_queue_depth=max_queue_depth,
        )
        self.cache = (
            cache if cache is not None
            else (ResultCache() if enable_cache else None)
        )
        self.default_deadline_s = default_deadline_s
        self._queries: Dict[str, Query] = {}
        self._order: List[str] = []  # retention ring
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # admission order journal (query ids, in admission sequence):
        # the load tests assert priority/FIFO semantics from this
        self.admission_log: List[str] = []
        self._stop = False
        self._workers = cf.ThreadPoolExecutor(
            max_workers=max(1, max_concurrency),
            thread_name_prefix="blaze-query",
        )
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="blaze-dispatch",
        )
        self._dispatcher.start()

    # -- submission -----------------------------------------------------
    def submit_task(
        self,
        task_bytes: bytes,
        *,
        is_ref: bool = False,
        resources: Optional[dict] = None,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        estimated_bytes: Optional[int] = None,
        use_cache: bool = True,
    ) -> Query:
        """Wire entry: one serialized TaskDefinition (engine-native or
        reference format), decoded eagerly so admission sees a cost
        estimate and the cache sees a fingerprint."""
        q = Query(
            task_bytes=task_bytes,
            is_ref=is_ref,
            resources=resources,
            priority=priority,
            deadline_s=(
                deadline_s if deadline_s is not None
                else self.default_deadline_s
            ),
            estimated_bytes=estimated_bytes,
            use_cache=use_cache,
        )
        try:
            if is_ref:
                from blaze_tpu.plan.refcompat import (
                    task_from_reference_proto,
                )

                decoded = task_from_reference_proto(task_bytes)
            else:
                from blaze_tpu.plan.serde import task_from_proto

                decoded = task_from_proto(task_bytes)
        except Exception as e:  # noqa: BLE001 - reported via state
            q.error = f"decode failed: {e!r}"
            q.transition(QueryState.FAILED)
            self._register(q)
            return q
        q._decoded = decoded
        op = decoded[0]
        if q.estimated_bytes is None:
            # a wire task executes ONE partition of its stage - cost
            # only that partition's leaves, or sibling tasks of a
            # partitioned scan would serialize behind each other
            q.estimated_bytes = estimate_plan_device_bytes(
                op, partition=decoded[1]
            )
        q._fingerprint = op.fingerprint()
        q._fingerprint_stable = op.fingerprint_is_stable()
        return self._enqueue(q)

    def submit_plan(
        self,
        plan,
        *,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        estimated_bytes: Optional[int] = None,
        use_cache: bool = True,
    ) -> Query:
        """Driver entry: run every partition of an in-process plan."""
        q = Query(
            plan=plan,
            priority=priority,
            deadline_s=(
                deadline_s if deadline_s is not None
                else self.default_deadline_s
            ),
            estimated_bytes=(
                estimated_bytes if estimated_bytes is not None
                else estimate_plan_device_bytes(plan)
            ),
            use_cache=use_cache,
        )
        q._decoded = None
        q._fingerprint = plan.fingerprint()
        q._fingerprint_stable = plan.fingerprint_is_stable()
        return self._enqueue(q)

    def _enqueue(self, q: Query) -> Query:
        self._register(q)
        if not self.admission.offer(q):
            q.error = (
                f"queue full ({self.admission.max_queue_depth}); "
                "retry with backoff"
            )
            q.transition(QueryState.REJECTED_OVERLOADED)
            return q
        with self._cv:
            self._cv.notify_all()
        return q

    def _register(self, q: Query) -> None:
        with self._lock:
            self._queries[q.query_id] = q
            self._order.append(q.query_id)
            while len(self._order) > _MAX_RETAINED:
                old = self._order[0]
                oq = self._queries.get(old)
                if oq is not None and not oq.done:
                    break  # never drop a live query
                self._order.pop(0)
                self._queries.pop(old, None)

    # -- lifecycle API --------------------------------------------------
    def get(self, query_id: str) -> Query:
        with self._lock:
            q = self._queries.get(query_id)
        if q is None:
            raise KeyError(f"unknown query {query_id}")
        return q

    def poll(self, query_id: str) -> dict:
        return self.get(query_id).status()

    def cancel(self, query_id: str) -> dict:
        """Request cancellation. QUEUED queries die here; ADMITTED and
        RUNNING ones observe the event at the next batch boundary (the
        executor's cancellation pass-through keeps the engine clean)."""
        q = self.get(query_id)
        q.request_cancel()
        if q.state is QueryState.QUEUED:
            q.try_transition(QueryState.CANCELLED)
        with self._cv:
            self._cv.notify_all()
        return q.status()

    def result(self, query_id: str, timeout: Optional[float] = None):
        """Block until terminal; return the materialized RecordBatch
        list on DONE, raise on every other terminal state."""
        q = self.get(query_id)
        if not q.wait(timeout):
            raise TimeoutError(f"query {query_id} still {q.state.value}")
        if q.state is QueryState.DONE:
            return q.result
        if q.state is QueryState.CANCELLED:
            raise QueryCancelled(query_id)
        raise RuntimeError(
            f"query {query_id} {q.state.value}: {q.error or ''}"
        )

    def report(self, query_id: str) -> str:
        """Per-query observability rollup: lifecycle timings, cache and
        dispatch counters, and the mirrored operator metric tree."""
        from blaze_tpu.runtime.instrument import render_metrics

        q = self.get(query_id)
        st = q.status()
        head = [
            f"query {q.query_id}: {st['state']} "
            f"(priority={q.priority}, est_bytes={q.estimated_bytes})"
        ]
        for k in ("queue_wait_s", "admission_s", "execution_s",
                  "stream_s"):
            if k in st:
                head.append(f"  {k}={st[k]}")
        body = render_metrics(q.metrics_root, indent="  ")
        return "\n".join(head) + ("\n" + body if body else "")

    def stats(self) -> dict:
        out = {"admission": self.admission.stats()}
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        return out

    def close(self) -> None:
        self._stop = True
        # shutdown cancels every live query: queued ones die here,
        # running ones observe the event at their next batch boundary -
        # otherwise worker shutdown would wait on them forever
        with self._lock:
            live = [q for q in self._queries.values() if not q.done]
        for q in live:
            q.request_cancel()
            if q.state is QueryState.QUEUED:
                q.try_transition(QueryState.CANCELLED)
        with self._cv:
            self._cv.notify_all()
        self._dispatcher.join(timeout=5)
        self._workers.shutdown(wait=True, cancel_futures=True)
        if self.cache is not None:
            self.cache.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- dispatcher -----------------------------------------------------
    def _dispatch_loop(self) -> None:
        while not self._stop:
            with self._cv:
                self._cv.wait(timeout=0.05)
            if self._stop:
                return
            self._sweep_deadlines()
            while True:
                q = self.admission.next_admissible()
                if q is None:
                    break
                if not q.try_transition(QueryState.ADMITTED):
                    # cancelled / timed out between queue and admit
                    self.admission.release(q)
                    continue
                q.timings["admitted"] = time.monotonic()
                with self._lock:
                    self.admission_log.append(q.query_id)
                self._workers.submit(self._run_query, q)

    def _sweep_deadlines(self) -> None:
        now = time.monotonic()
        with self._lock:
            queued = [
                q for q in self._queries.values()
                if q.state is QueryState.QUEUED
            ]
        for q in queued:
            if q.deadline_exceeded(now):
                if q.try_transition(QueryState.TIMED_OUT):
                    q.error = "deadline exceeded while queued"

    # -- execution ------------------------------------------------------
    def _run_query(self, q: Query) -> None:
        try:
            if q.cancel_requested:
                if q.try_transition(QueryState.CANCELLED):
                    return
            if q.deadline_exceeded():
                if q.try_transition(QueryState.TIMED_OUT):
                    q.error = "deadline exceeded before start"
                    return
            if not q.try_transition(QueryState.RUNNING):
                return
            q.timings["run_start"] = time.monotonic()
            try:
                q.result = self._execute(q)
            except QueryCancelled:
                if q.cancel_requested:
                    q.try_transition(QueryState.CANCELLED)
                else:
                    q.error = "deadline exceeded while running"
                    q.try_transition(QueryState.TIMED_OUT)
                return
            except Exception as e:  # noqa: BLE001 - reported via state
                q.error = f"{type(e).__name__}: {e}"
                q.try_transition(QueryState.FAILED)
                log.warning("query %s failed: %s", q.query_id, q.error)
                return
            q.try_transition(QueryState.DONE)
        finally:
            self.admission.release(q)
            with self._cv:
                self._cv.notify_all()

    def _execute(self, q: Query) -> List:
        """Run (or reuse) every partition of the query's plan."""
        from blaze_tpu.runtime.executor import prepare_decoded_task
        from blaze_tpu.runtime.instrument import instrument

        # wire-manifest resources first (the gateway's resource
        # registry contract); decoded-task resources setdefault under
        # them in prepare_decoded_task
        q.ctx.resources.update(q.resources)

        cache = (
            self.cache
            if (self.cache is not None and q.use_cache
                and q._fingerprint_stable)
            else None
        )
        if q.plan is not None:
            op = q.plan
            partitions = list(range(op.partition_count))
            exec_op = op  # driver plans run as-built (run_plan parity)
        else:
            op = None
            partitions = [q._decoded[1]]
            exec_op = None  # prepared lazily: a full cache hit must
            # not pay fusion/mesh lowering (and must dispatch nothing)

        out: List = []
        for p in partitions:
            q.check_interrupt()
            key = (q._fingerprint, p)
            if cache is not None:
                hit = cache.get(key)
                if hit is not None:
                    q.ctx.metrics.add("cache_hits", 1)
                    for rb in hit:
                        q.ctx.metrics.add("output_rows", rb.num_rows)
                    out.extend(hit)
                    continue
                q.ctx.metrics.add("cache_misses", 1)
            if exec_op is None:
                prepared, _ = prepare_decoded_task(q._decoded, q.ctx)
                if q.ctx.config.collect_metrics:
                    prepared = instrument(prepared, q.metrics_root)
                exec_op = prepared
            part_batches = self._drain(q, exec_op, p)
            if cache is not None:
                cache.put(key, part_batches)
            out.extend(part_batches)
        return out

    def _drain(self, q: Query, op, partition: int) -> List:
        """Materialize one partition with cooperative interrupt checks
        between batches; closing the generator routes through the
        executor's cancellation pass-through (GeneratorExit), so a
        cancelled query never poisons the engine."""
        from blaze_tpu.runtime.executor import execute_partition

        it = execute_partition(op, partition, q.ctx)
        batches: List = []
        try:
            for rb in it:
                batches.append(rb)
                if q.cancel_requested or q.deadline_exceeded():
                    it.close()
                    raise QueryCancelled(q.query_id)
        finally:
            it.close()
        return batches
