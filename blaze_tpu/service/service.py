"""QueryService: the multi-query serving tier over the executor.

What the reference inherits from Spark (driver scheduling, task slots,
result handling - SURVEY 2.2), a standalone TPU engine must own. The
service composes the pieces this package provides:

  submit  -> Query (service/query.py state machine), bounded priority
             admission (service/admission.py), or REJECTED_OVERLOADED
  dispatch-> one dispatcher thread admits by priority/FIFO/headroom and
             hands queries to a worker pool sized to max_concurrency
  run     -> the UNCHANGED executor path (prepare_decoded_task ->
             execute_partition), with cooperative cancel/deadline
             checks between batches (the executor's GeneratorExit
             cancellation contract, runtime/executor.py)
  reuse   -> materialized results cached by (plan fingerprint,
             partition) when the fingerprint is stable
             (service/cache.py); a full cache hit dispatches NOTHING
  observe -> per-query queue/admission/execution timings + the
             dispatch.* counters + the mirrored operator metric tree,
             one report via runtime/instrument.render_metrics

Wire surface lives in service/wire.py; `python -m blaze_tpu serve`
starts both.
"""

from __future__ import annotations

import concurrent.futures as cf
import logging
import threading
import time
from typing import Dict, List, Optional

from blaze_tpu.errors import ErrorClass, classify, retry_action
from blaze_tpu.service.admission import (
    AdmissionController,
    estimate_plan_device_bytes,
)
from blaze_tpu.service.cache import ResultCache
from blaze_tpu.service.query import (
    Query,
    QueryCancelled,
    QueryState,
)
from blaze_tpu.testing import chaos

log = logging.getLogger("blaze_tpu.service")

_MAX_RETAINED = 1024  # terminal queries kept for poll/report


class QueryService:
    def __init__(
        self,
        max_concurrency: int = 2,
        max_queue_depth: int = 64,
        cache: Optional[ResultCache] = None,
        enable_cache: bool = True,
        device_tracker=None,
        default_deadline_s: Optional[float] = None,
        max_task_attempts: int = 3,
        retry_backoff_s: float = 0.05,
        degrade_to_host: bool = True,
    ):
        self.admission = AdmissionController(
            device_tracker=device_tracker,
            max_concurrency=max_concurrency,
            max_queue_depth=max_queue_depth,
        )
        # failure policy (blaze_tpu/errors.py taxonomy): TRANSIENT
        # partition failures retry up to max_task_attempts with
        # exponential backoff; RESOURCE_EXHAUSTED degrades to the host
        # engine; PLAN_INVALID/INTERNAL fail fast
        self.max_task_attempts = max(1, int(max_task_attempts))
        self.retry_backoff_s = float(retry_backoff_s)
        self.degrade_to_host = degrade_to_host
        self.cache = (
            cache if cache is not None
            else (ResultCache() if enable_cache else None)
        )
        self.default_deadline_s = default_deadline_s
        self._queries: Dict[str, Query] = {}
        self._order: List[str] = []  # retention ring
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # admission order journal (query ids, in admission sequence):
        # the load tests assert priority/FIFO semantics from this
        self.admission_log: List[str] = []
        self._stop = False
        self._workers = cf.ThreadPoolExecutor(
            max_workers=max(1, max_concurrency),
            thread_name_prefix="blaze-query",
        )
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="blaze-dispatch",
        )
        self._dispatcher.start()

    # -- submission -----------------------------------------------------
    def submit_task(
        self,
        task_bytes: bytes,
        *,
        is_ref: bool = False,
        resources: Optional[dict] = None,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        estimated_bytes: Optional[int] = None,
        use_cache: bool = True,
    ) -> Query:
        """Wire entry: one serialized TaskDefinition (engine-native or
        reference format), decoded eagerly so admission sees a cost
        estimate and the cache sees a fingerprint."""
        q = Query(
            task_bytes=task_bytes,
            is_ref=is_ref,
            resources=resources,
            priority=priority,
            deadline_s=(
                deadline_s if deadline_s is not None
                else self.default_deadline_s
            ),
            estimated_bytes=estimated_bytes,
            use_cache=use_cache,
        )
        try:
            if is_ref:
                from blaze_tpu.plan.refcompat import (
                    task_from_reference_proto,
                )

                decoded = task_from_reference_proto(task_bytes)
            else:
                from blaze_tpu.plan.serde import task_from_proto

                decoded = task_from_proto(task_bytes)
        except Exception as e:  # noqa: BLE001 - reported via state
            q.error = f"decode failed: {e!r}"
            # undecodable bytes are a malformed plan by definition
            q.error_class = ErrorClass.PLAN_INVALID.value
            q.transition(QueryState.FAILED)
            self._register(q)
            return q
        q._decoded = decoded
        op = decoded[0]
        if q.estimated_bytes is None:
            # a wire task executes ONE partition of its stage - cost
            # only that partition's leaves, or sibling tasks of a
            # partitioned scan would serialize behind each other
            q.estimated_bytes = estimate_plan_device_bytes(
                op, partition=decoded[1]
            )
        q._fingerprint = op.fingerprint()
        q._fingerprint_stable = op.fingerprint_is_stable()
        return self._enqueue(q)

    def submit_plan(
        self,
        plan,
        *,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        estimated_bytes: Optional[int] = None,
        use_cache: bool = True,
    ) -> Query:
        """Driver entry: run every partition of an in-process plan."""
        q = Query(
            plan=plan,
            priority=priority,
            deadline_s=(
                deadline_s if deadline_s is not None
                else self.default_deadline_s
            ),
            estimated_bytes=(
                estimated_bytes if estimated_bytes is not None
                else estimate_plan_device_bytes(plan)
            ),
            use_cache=use_cache,
        )
        q._decoded = None
        q._fingerprint = plan.fingerprint()
        q._fingerprint_stable = plan.fingerprint_is_stable()
        return self._enqueue(q)

    def _enqueue(self, q: Query) -> Query:
        self._register(q)
        if q.deadline_at is not None and q.deadline_exceeded():
            # deadline shedding: a deadline that has already passed
            # cannot be met - refuse up front instead of queueing work
            # that the sweep will kill anyway
            self.admission.note_shed()
            q.error = "deadline unmeetable at admission (shed)"
            q.transition(QueryState.TIMED_OUT)
            return q
        if not self.admission.offer(q):
            q.error = (
                f"queue full ({self.admission.max_queue_depth}); "
                "retry with backoff"
            )
            q.transition(QueryState.REJECTED_OVERLOADED)
            return q
        with self._cv:
            self._cv.notify_all()
        return q

    def _register(self, q: Query) -> None:
        with self._lock:
            self._queries[q.query_id] = q
            self._order.append(q.query_id)
            while len(self._order) > _MAX_RETAINED:
                old = self._order[0]
                oq = self._queries.get(old)
                if oq is not None and not oq.done:
                    break  # never drop a live query
                self._order.pop(0)
                self._queries.pop(old, None)

    # -- lifecycle API --------------------------------------------------
    def get(self, query_id: str) -> Query:
        with self._lock:
            q = self._queries.get(query_id)
        if q is None:
            raise KeyError(f"unknown query {query_id}")
        return q

    def poll(self, query_id: str) -> dict:
        return self.get(query_id).status()

    def cancel(self, query_id: str) -> dict:
        """Request cancellation. QUEUED queries die here; ADMITTED and
        RUNNING ones observe the event at the next batch boundary (the
        executor's cancellation pass-through keeps the engine clean)."""
        q = self.get(query_id)
        q.request_cancel()
        if q.state is QueryState.QUEUED:
            q.try_transition(QueryState.CANCELLED)
        with self._cv:
            self._cv.notify_all()
        return q.status()

    def result(self, query_id: str, timeout: Optional[float] = None):
        """Block until terminal; return the materialized RecordBatch
        list on DONE, raise on every other terminal state."""
        q = self.get(query_id)
        if not q.wait(timeout):
            raise TimeoutError(f"query {query_id} still {q.state.value}")
        if q.state is QueryState.DONE:
            return q.result
        if q.state is QueryState.CANCELLED:
            raise QueryCancelled(query_id)
        raise RuntimeError(
            f"query {query_id} {q.state.value}: {q.error or ''}"
        )

    def report(self, query_id: str) -> str:
        """Per-query observability rollup: lifecycle timings, cache and
        dispatch counters, and the mirrored operator metric tree."""
        from blaze_tpu.runtime.instrument import render_metrics

        q = self.get(query_id)
        st = q.status()
        head = [
            f"query {q.query_id}: {st['state']} "
            f"(priority={q.priority}, est_bytes={q.estimated_bytes})"
        ]
        if st.get("error_class"):
            head.append(f"  error_class={st['error_class']}")
        if st.get("degraded"):
            head.append("  degraded=True (host-engine fallback)")
        for k in ("queue_wait_s", "admission_s", "execution_s",
                  "stream_s"):
            if k in st:
                head.append(f"  {k}={st[k]}")
        for a in st.get("attempts", ()):
            head.append(
                f"  attempt p{a['partition']}#{a['attempt']}: "
                f"{a['error_class']} -> {a['action']} ({a['error']})"
            )
        body = render_metrics(q.metrics_root, indent="  ")
        return "\n".join(head) + ("\n" + body if body else "")

    def stats(self) -> dict:
        out = {"admission": self.admission.stats()}
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        return out

    def close(self) -> None:
        self._stop = True
        # shutdown cancels every live query: queued ones die here,
        # running ones observe the event at their next batch boundary -
        # otherwise worker shutdown would wait on them forever
        with self._lock:
            live = [q for q in self._queries.values() if not q.done]
        for q in live:
            q.request_cancel(reason="shutdown")
            if q.state is QueryState.QUEUED:
                q.try_transition(QueryState.CANCELLED)
        with self._cv:
            self._cv.notify_all()
        self._dispatcher.join(timeout=5)
        self._workers.shutdown(wait=True, cancel_futures=True)
        if self.cache is not None:
            self.cache.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- dispatcher -----------------------------------------------------
    def _dispatch_loop(self) -> None:
        while not self._stop:
            with self._cv:
                self._cv.wait(timeout=0.05)
            if self._stop:
                return
            self._sweep_deadlines()
            while True:
                q = self.admission.next_admissible()
                if q is None:
                    break
                if not q.try_transition(QueryState.ADMITTED):
                    # cancelled / timed out between queue and admit
                    self.admission.release(q)
                    continue
                q.timings["admitted"] = time.monotonic()
                with self._lock:
                    self.admission_log.append(q.query_id)
                self._workers.submit(self._run_query, q)

    def _sweep_deadlines(self) -> None:
        now = time.monotonic()
        with self._lock:
            live = [
                q for q in self._queries.values() if not q.done
            ]
        for q in live:
            if not q.deadline_exceeded(now):
                continue
            if q.state is QueryState.QUEUED:
                if q.try_transition(QueryState.TIMED_OUT):
                    q.error = "deadline exceeded while queued"
            elif q.state in (QueryState.ADMITTED, QueryState.RUNNING):
                # propagate the cancel event so the run loop (or a
                # retry-backoff wait) observes it promptly; the run
                # loop itself performs the TIMED_OUT transition AFTER
                # closing the operator generator, preserving the
                # invariant that a terminal state implies cleaned-up
                # execution resources
                q.request_cancel(reason="deadline")

    # -- execution ------------------------------------------------------
    def _run_query(self, q: Query) -> None:
        try:
            if chaos.ACTIVE:
                # chaos seam (STALL widens the ADMITTED->RUNNING window
                # so cancellation races become deterministic tests); a
                # RAISED fault here goes through the same taxonomy
                # surfacing as any pre-execution failure
                try:
                    chaos.fire("service.admit", query_id=q.query_id)
                except Exception as e:  # noqa: BLE001 - classified
                    q.error = f"{type(e).__name__}: {e}"
                    q.error_class = classify(e).value
                    q.try_transition(QueryState.FAILED)
                    return
            # an explicit user/shutdown cancel wins over a deadline
            # that elapsed concurrently; a sweep-fired ('deadline')
            # cancel - or a bare deadline expiry - reports TIMED_OUT
            if q.cancel_requested and q.cancel_reason in (
                "user", "shutdown"
            ):
                if q.try_transition(QueryState.CANCELLED):
                    return
            if q.deadline_exceeded():
                if q.try_transition(QueryState.TIMED_OUT):
                    q.error = "deadline exceeded before start"
                    return
            if q.cancel_requested:
                if q.try_transition(QueryState.CANCELLED):
                    return
            if not q.try_transition(QueryState.RUNNING):
                return
            q.timings["run_start"] = time.monotonic()
            try:
                q.result = self._execute(q)
            except QueryCancelled:
                if q.cancel_requested and q.cancel_reason in (
                    "user", "shutdown"
                ):
                    q.try_transition(QueryState.CANCELLED)
                elif q.deadline_exceeded():
                    q.error = "deadline exceeded while running"
                    q.try_transition(QueryState.TIMED_OUT)
                else:
                    q.try_transition(QueryState.CANCELLED)
                return
            except Exception as e:  # noqa: BLE001 - reported via state
                q.error = f"{type(e).__name__}: {e}"
                q.error_class = classify(e).value
                q.try_transition(QueryState.FAILED)
                log.warning(
                    "query %s failed [%s]: %s",
                    q.query_id, q.error_class, q.error,
                )
                return
            q.try_transition(QueryState.DONE)
        finally:
            self.admission.release(q)
            with self._cv:
                self._cv.notify_all()

    def _execute(self, q: Query) -> List:
        """Run (or reuse) every partition of the query's plan."""
        from blaze_tpu.runtime.executor import prepare_decoded_task
        from blaze_tpu.runtime.instrument import instrument

        # wire-manifest resources first (the gateway's resource
        # registry contract); decoded-task resources setdefault under
        # them in prepare_decoded_task
        q.ctx.resources.update(q.resources)

        cache = (
            self.cache
            if (self.cache is not None and q.use_cache
                and q._fingerprint_stable)
            else None
        )
        if q.plan is not None:
            op = q.plan
            partitions = list(range(op.partition_count))
            exec_op = op  # driver plans run as-built (run_plan parity)
        else:
            op = None
            partitions = [q._decoded[1]]
            exec_op = None  # prepared lazily: a full cache hit must
            # not pay fusion/mesh lowering (and must dispatch nothing)

        out: List = []
        for p in partitions:
            q.check_interrupt()
            key = (q._fingerprint, p)
            if cache is not None:
                hit = cache.get(key)
                if hit is not None:
                    q.ctx.metrics.add("cache_hits", 1)
                    for rb in hit:
                        q.ctx.metrics.add("output_rows", rb.num_rows)
                    out.extend(hit)
                    continue
                q.ctx.metrics.add("cache_misses", 1)
            if exec_op is None:
                prepared, _ = prepare_decoded_task(q._decoded, q.ctx)
                if q.ctx.config.collect_metrics:
                    prepared = instrument(prepared, q.metrics_root)
                exec_op = prepared
            part_batches, degraded = self._run_partition(
                q, exec_op, p
            )
            if cache is not None and not degraded:
                # degraded results are correct but host-produced;
                # keeping them out of the cache preserves device-result
                # provenance and lets a healthy re-run repopulate it
                cache.put(key, part_batches)
            out.extend(part_batches)
        return out

    def _run_partition(self, q: Query, op, partition: int):
        """One partition with CLASSIFIED failure handling
        (blaze_tpu/errors.py): TRANSIENT retries with exponential
        backoff + jitter (cancel-interruptible), RESOURCE_EXHAUSTED
        degrades through the host engine, PLAN_INVALID/INTERNAL fail
        fast with zero retries. Returns (batches, degraded)."""
        from blaze_tpu.runtime.scheduler import backoff_delay

        for attempt in range(self.max_task_attempts):
            q.check_interrupt()
            try:
                return self._drain(q, op, partition), False
            except QueryCancelled:
                raise
            except Exception as e:  # noqa: BLE001 - classified below
                ec = classify(e)
                action = retry_action(
                    ec, attempt, self.max_task_attempts,
                    self.degrade_to_host,
                )
                if action == "cancel":
                    raise QueryCancelled(q.query_id) from e
                q.record_attempt(partition, attempt, ec.value, e,
                                 action)
                if action == "degrade":
                    return self._degrade_partition(q, partition, e), \
                        True
                if action == "fail":
                    raise
                q.ctx.metrics.add("task_retries", 1)
                q.ctx.metrics.add("retries.transient", 1)
                log.warning(
                    "query %s partition %d failed transiently "
                    "(attempt %d), backing off: %s",
                    q.query_id, partition, attempt + 1, e,
                )
                if q.wait_cancel(
                    backoff_delay(attempt, self.retry_backoff_s)
                ):
                    raise QueryCancelled(q.query_id) from e
        raise AssertionError("unreachable: attempt loop fell through")

    def _degrade_partition(self, q: Query, partition: int,
                           cause: BaseException) -> List:
        """RESOURCE_EXHAUSTED degradation: re-execute the partition
        through the pandas host engine against an UNFUSED plan (fused
        pipelines have no host mapping). Wire tasks re-decode from the
        original bytes - prepare_decoded_task fuses the decoded tree
        IN PLACE, so q._decoded is already fused by the time a
        partition fails. Surfaces the ORIGINAL device error when no
        host mapping exists."""
        from blaze_tpu.planner.host_engine import execute_partition_host

        try:
            if q.plan is not None:
                base = q.plan  # driver plans run as-built (never fused)
            elif q.is_ref:
                from blaze_tpu.plan.refcompat import (
                    task_from_reference_proto,
                )

                base = task_from_reference_proto(q.task_bytes)[0]
            else:
                from blaze_tpu.plan.serde import task_from_proto

                base = task_from_proto(q.task_bytes)[0]
            batches = execute_partition_host(base, partition, q.ctx)
        except Exception as host_err:  # noqa: BLE001 - original wins
            log.warning(
                "query %s: host degradation of partition %d "
                "unavailable (%s); surfacing original error",
                q.query_id, partition, host_err,
            )
            raise cause
        q.degraded = True
        q.ctx.metrics.add("degraded_partitions", 1)
        log.warning(
            "query %s partition %d degraded to host engine after "
            "RESOURCE_EXHAUSTED: %s", q.query_id, partition, cause,
        )
        return batches

    def _drain(self, q: Query, op, partition: int) -> List:
        """Materialize one partition with cooperative interrupt checks
        between batches; closing the generator routes through the
        executor's cancellation pass-through (GeneratorExit), so a
        cancelled query never poisons the engine."""
        from blaze_tpu.runtime.executor import execute_partition

        it = execute_partition(op, partition, q.ctx)
        batches: List = []
        try:
            for rb in it:
                batches.append(rb)
                if q.cancel_requested or q.deadline_exceeded():
                    it.close()
                    raise QueryCancelled(q.query_id)
        except BaseException:
            # an abandoned attempt's partial output must not stay in
            # the query counters - a retry (or the host degradation)
            # re-counts the partition from scratch
            if batches:
                q.ctx.metrics.add(
                    "output_rows", -sum(rb.num_rows for rb in batches)
                )
                q.ctx.metrics.add("output_batches", -len(batches))
            raise
        finally:
            it.close()
        return batches
