"""QueryService: the multi-query serving tier over the executor.

What the reference inherits from Spark (driver scheduling, task slots,
result handling - SURVEY 2.2), a standalone TPU engine must own. The
service composes the pieces this package provides:

  submit  -> Query (service/query.py state machine), bounded priority
             admission (service/admission.py), or REJECTED_OVERLOADED
  dispatch-> one dispatcher thread admits by priority/FIFO/headroom and
             hands queries to a worker pool sized to max_concurrency
  run     -> the UNCHANGED executor path (prepare_decoded_task ->
             execute_partition), with cooperative cancel/deadline
             checks between batches (the executor's GeneratorExit
             cancellation contract, runtime/executor.py)
  reuse   -> materialized results cached by (plan fingerprint,
             partition) when the fingerprint is stable
             (service/cache.py); a full cache hit dispatches NOTHING
  observe -> per-query queue/admission/execution timings + the
             dispatch.* counters + the mirrored operator metric tree,
             one report via runtime/instrument.render_metrics

Wire surface lives in service/wire.py; `python -m blaze_tpu serve`
starts both.
"""

from __future__ import annotations

import concurrent.futures as cf
import itertools
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from blaze_tpu.errors import ErrorClass, classify, retry_action
from blaze_tpu.obs import contention as obs_contention
from blaze_tpu.obs import meshprof as obs_meshprof
from blaze_tpu.obs import phases as obs_phases
from blaze_tpu.obs import slowlog
from blaze_tpu.obs import trace as obs_trace
from blaze_tpu.obs.history import RuntimeHistory
from blaze_tpu.obs.metrics import REGISTRY
from blaze_tpu.service.admission import (
    AdmissionController,
    estimate_plan_device_bytes,
)
from blaze_tpu.service.cache import ResultCache
from blaze_tpu.service.query import (
    Query,
    QueryCancelled,
    QueryState,
)
from blaze_tpu.testing import chaos

log = logging.getLogger("blaze_tpu.service")

_MAX_RETAINED = 1024  # terminal queries kept for poll/report

# monotonically assigned `service` label values for the process-wide
# metrics registry (see QueryService._collect_metrics)
_service_instance_ids = itertools.count()


class QueryService:
    def __init__(
        self,
        max_concurrency: int = 2,
        max_queue_depth: int = 64,
        cache: Optional[ResultCache] = None,
        enable_cache: bool = True,
        device_tracker=None,
        default_deadline_s: Optional[float] = None,
        max_task_attempts: int = 3,
        retry_backoff_s: float = 0.05,
        degrade_to_host: bool = True,
        enable_trace: bool = True,
        slow_query_s: Optional[float] = None,
        history: Optional[RuntimeHistory] = None,
        fold_phases: bool = True,
        mesh_mode: Optional[str] = None,
        orphan_ttl_s: Optional[float] = 900.0,
        stream_buffer_bytes: int = 32 << 20,
        stream_stall_s: float = 30.0,
        plan_cache=None,
        plan_cache_entries: int = 256,
        arena=None,
        arena_bytes: int = 0,
        arena_dir: Optional[str] = None,
        tenant_config: Optional[dict] = None,
        fleet_peers: Optional[list] = None,
        fleet_router=None,
        fleet_devices: Optional[int] = None,
    ):
        # multi-tenant isolation (docs/SERVICE.md "Tenancy"):
        # per-tenant admission budgets + weighted-fair ordering live
        # in the AdmissionController; None keeps the zero-config
        # single-heap behavior byte-identical
        self.admission = AdmissionController(
            device_tracker=device_tracker,
            max_concurrency=max_concurrency,
            max_queue_depth=max_queue_depth,
            tenant_config=tenant_config,
        )
        # failure policy (blaze_tpu/errors.py taxonomy): TRANSIENT
        # partition failures retry up to max_task_attempts with
        # exponential backoff; RESOURCE_EXHAUSTED degrades to the host
        # engine; PLAN_INVALID/INTERNAL fail fast
        self.max_task_attempts = max(1, int(max_task_attempts))
        self.retry_backoff_s = float(retry_backoff_s)
        self.degrade_to_host = degrade_to_host
        # mesh execution tier (planner/distribute, docs/MESH.md):
        # "auto" = cost-guarded lowering, "on" = forced (`serve
        # --mesh`), "off" = single-device; None defers to the
        # BLAZE_MESH_LOWERING env per task. Threaded into every
        # query's ExecContext so prepare_decoded_task resolves it
        # without env mutation
        if mesh_mode not in (None, "auto", "on", "off"):
            raise ValueError(
                f"mesh_mode must be auto|on|off, got {mesh_mode!r}"
            )
        self.mesh_mode = mesh_mode
        # fleet mesh tier (fleet/, docs/MESH.md "Fleet tier"): with
        # peers configured, eligible driver plans lower across the
        # fleet (per-host ICI stages joined by DCN exchanges) instead
        # of this host's mesh alone. Claims route through fleet_router
        # when set (the membership/claim authority), else a local
        # ledger over this host's share. None = single-host behavior
        # byte-identical.
        self._fleet = None
        if fleet_peers:
            from blaze_tpu.fleet.exec import FleetContext

            self._fleet = FleetContext(
                fleet_peers, devices=fleet_devices,
                router=fleet_router, tenant_config=tenant_config,
            )
        self.cache = (
            cache if cache is not None
            else (ResultCache() if enable_cache else None)
        )
        # zero-copy serve path (blaze_tpu/zerocopy, docs/SERVICE.md):
        # the decoded-plan cache makes a repeat SUBMIT skip protobuf
        # decode entirely (keyed by the router's affinity digest over
        # the raw blob); the Arrow arena holds finalized ENCODED part
        # frames in mmap segments so FETCH serves them scatter-gather
        # (or as a shm handle to a co-located client) instead of
        # re-encoding per request. plan_cache_entries <= 0 /
        # arena_bytes <= 0 disable each independently
        if plan_cache is not None:
            self.plan_cache = plan_cache
        elif plan_cache_entries and plan_cache_entries > 0:
            from blaze_tpu.zerocopy.plan_cache import DecodedPlanCache

            self.plan_cache = DecodedPlanCache(plan_cache_entries)
        else:
            self.plan_cache = None
        if arena is not None:
            self.arena = arena
        elif arena_bytes and arena_bytes > 0:
            from blaze_tpu.zerocopy.arena import ArrowArena

            self.arena = ArrowArena(directory=arena_dir,
                                    max_bytes=arena_bytes)
        else:
            self.arena = None
        self.default_deadline_s = default_deadline_s
        # observability (blaze_tpu/obs): refcounted tracing for the
        # service lifetime, per-fingerprint runtime history (the
        # deadline-prediction input), slow-query log threshold, and
        # a per-instance collector on the process metrics registry
        self._trace_enabled = bool(enable_trace)
        if self._trace_enabled:
            obs_trace.enable()
        self.history = history if history is not None else RuntimeHistory()
        # threshold precedence: explicit arg > BLAZE_SLOW_QUERY_S env
        # (validated - a typo must not kill serve at startup) > 5s
        if slow_query_s is None:
            env = os.environ.get("BLAZE_SLOW_QUERY_S")
            try:
                slow_query_s = float(env) if env else 5.0
            except ValueError:
                log.warning(
                    "ignoring malformed BLAZE_SLOW_QUERY_S=%r", env
                )
                slow_query_s = 5.0
        self.slow_query_s = float(slow_query_s)
        # fold_phases=False keeps this instance out of the process
        # rollup: the regress probe runs a synthetic workload inside
        # what may be a LIVE serving process, and its samples must
        # not skew the production STATS `phases` payload
        self._fold_phases = bool(fold_phases)
        self.obs_counters = {
            "degraded_queries": 0,
            "retried_queries": 0,
            "slow_queries": 0,
            "orphans_reaped": 0,
            # streaming data plane (service/stream.py): stall aborts
            # and producer backpressure episodes, aggregated across
            # per-query ring buffers by _note_stream_event
            "stream_stalls": 0,
            "stream_backpressure_waits": 0,
            # admission fast path (zero-copy serve path): SUBMITs
            # whose fingerprint the ResultCache fully covers bypass
            # the byte-reservation queue and serve on the dedicated
            # fast-path pool
            "fast_path_serves": 0,
        }
        # end-to-end streaming (service/stream.py, docs/SERVICE.md):
        # per-query bounded result rings FETCH drains while RUNNING.
        # stream_buffer_bytes <= 0 disables streaming (legacy
        # materialize-then-stream); stream_stall_s bounds how long a
        # non-draining consumer may pin a full ring before the query
        # aborts with the classified STREAM_STALLED outcome
        self.stream_buffer_bytes = int(stream_buffer_bytes)
        self.stream_stall_s = float(stream_stall_s)
        self._stream_high_water = 0  # max pending bytes, any query
        # orphan reaping (docs/SERVICE.md): a detach=True query whose
        # ROUTER died holds its result in retention forever - nothing
        # will ever POLL or FETCH it, and _MAX_RETAINED eviction only
        # helps under fresh traffic. The sweep reaps terminal,
        # never-fetched queries with no client activity for
        # orphan_ttl_s (None/<=0 disables); a reaped query's FETCH
        # answers the classified UNKNOWN not-found, never a hang
        self.orphan_ttl_s = (
            float(orphan_ttl_s)
            if orphan_ttl_s and orphan_ttl_s > 0 else None
        )
        self._next_orphan_sweep = 0.0
        # instance label: the registry is process-wide and several
        # services may be alive at once - unlabeled samples would
        # collide into duplicate series and fail the whole scrape
        self._instance = str(next(_service_instance_ids))
        self._collector_key = f"service:{self._instance}"
        REGISTRY.register_collector(
            self._collector_key, self._collect_metrics
        )
        self._closed = False
        # DRAINING (rolling-restart shutdown, docs/ROUTER.md): new
        # SUBMITs are refused with a classified TRANSIENT rejection
        # while in-flight queries run to completion; drain() flips it
        self.draining = False
        self._queries: Dict[str, Query] = {}
        self._order: List[str] = []  # retention ring
        # request coalescing (ROADMAP scan-sharing first step): one
        # event per (fingerprint, partition) currently EXECUTING, so a
        # second identical stable-fingerprint submission waits on the
        # leader and serves from the cache it populates instead of
        # re-executing the same plan concurrently
        self._inflight: Dict = {}
        self._inflight_lock = threading.Lock()
        self._lock = obs_contention.TimedLock("service_state")
        self._cv = threading.Condition(self._lock)
        # admission order journal (query ids, in admission sequence):
        # the load tests assert priority/FIFO semantics from this
        self.admission_log: List[str] = []
        self._stop = False
        # dispatcher wakeup batching: N submit/release events between
        # dispatcher passes collapse into ONE pending flag (and one CV
        # round-trip) instead of N notify_all calls contending the
        # service lock at high concurrency
        self._kick_pending = False
        self._workers = cf.ThreadPoolExecutor(
            max_workers=max(1, max_concurrency),
            thread_name_prefix="blaze-query",
        )
        # fast-path pool: cache-covered repeats run here, NOT inline
        # on the submit thread (a cached result larger than the ring
        # cap would deadlock submit against its own future FETCH) and
        # NOT on _workers (a queued fleet must not starve cached
        # repeats - the whole point of the bypass)
        self._fast_pool = cf.ThreadPoolExecutor(
            max_workers=max(2, max_concurrency),
            thread_name_prefix="blaze-fastpath",
        )
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="blaze-dispatch",
        )
        self._dispatcher.start()

    # -- submission -----------------------------------------------------
    def submit_task(
        self,
        task_bytes: bytes,
        *,
        is_ref: bool = False,
        resources: Optional[dict] = None,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        estimated_bytes: Optional[int] = None,
        use_cache: bool = True,
        plan_digest: Optional[str] = None,
        tenant: str = "default",
    ) -> Query:
        """Wire entry: one serialized TaskDefinition (engine-native or
        reference format), decoded eagerly so admission sees a cost
        estimate and the cache sees a fingerprint - UNLESS the
        decoded-plan cache already knows this blob (zero-copy serve
        path): a hit reuses fingerprint/estimate/partition and defers
        any re-decode to execution time, so a result-cache-covered
        repeat never decodes at all. `plan_digest` is the router's
        precomputed affinity digest over these exact bytes (submit
        meta `plan_digest`); absent, the service hashes locally."""
        q = Query(
            task_bytes=task_bytes,
            is_ref=is_ref,
            resources=resources,
            priority=priority,
            deadline_s=(
                deadline_s if deadline_s is not None
                else self.default_deadline_s
            ),
            estimated_bytes=estimated_bytes,
            use_cache=use_cache,
            tenant=tenant,
        )
        self._attach_obs(q)
        if self.draining:
            return self._reject_draining(q)
        pc = self.plan_cache
        entry = None
        if pc is not None:
            from blaze_tpu.zerocopy.plan_cache import plan_digest as _pd

            q._plan_key = plan_digest or _pd(task_bytes, is_ref)
            entry = pc.get(q._plan_key)
        if entry is not None:
            # plan-cache hit: NO decode (and no plan_decode span). The
            # decoded tree is loaned exclusively (prepare_decoded_task
            # mutates it); when it is already out, metadata still
            # serves and a cache-missing execution re-decodes lazily
            q._plan_entry = entry
            q._decoded = entry.borrow_tree()
            if q._decoded is None:
                pc.note_tree_unavailable()
            q._plan_partition = entry.partition
            if q.estimated_bytes is None:
                q.estimated_bytes = entry.estimated_bytes
            q._fingerprint = entry.fingerprint
            q._fingerprint_stable = entry.fingerprint_stable
            return self._enqueue(q)
        decoded = self._decode_task(q)
        if decoded is None:
            return q  # decode failed: FAILED + registered
        q._decoded = decoded
        op = decoded[0]
        q._plan_partition = decoded[1]
        if q.estimated_bytes is None:
            # a wire task executes ONE partition of its stage - cost
            # only that partition's leaves, or sibling tasks of a
            # partitioned scan would serialize behind each other
            q.estimated_bytes = estimate_plan_device_bytes(
                op, partition=decoded[1]
            )
        q._fingerprint = op.fingerprint()
        q._fingerprint_stable = op.fingerprint_is_stable()
        if pc is not None:
            from blaze_tpu.zerocopy.plan_cache import PlanEntry

            # publish the metadata now; the TREE belongs to THIS query
            # (it may fuse it in place) and returns pristine via the
            # terminal hook only if it never executed
            q._plan_entry = pc.put(q._plan_key, PlanEntry(
                fingerprint=q._fingerprint,
                fingerprint_stable=q._fingerprint_stable,
                estimated_bytes=q.estimated_bytes,
                partition=decoded[1],
            ))
        return self._enqueue(q)

    def _decode_bytes(self, q: Query):
        """Decode q.task_bytes under a `plan_decode` span; raises on
        malformed bytes. Submit-time AND the lazy re-decode a
        plan-cache hit pays when it must execute but its entry's tree
        is loaned out."""
        t0 = time.monotonic()
        if q.is_ref:
            from blaze_tpu.plan.refcompat import (
                task_from_reference_proto,
            )

            decoded = task_from_reference_proto(q.task_bytes)
        else:
            from blaze_tpu.plan.serde import task_from_proto

            decoded = task_from_proto(q.task_bytes)
        if q.tracer is not None:
            q.tracer.record_span("plan_decode", t0, time.monotonic())
        return decoded

    def _decode_task(self, q: Query):
        """Submit-time decode. On failure the query is FAILED +
        registered and None returns."""
        try:
            return self._decode_bytes(q)
        except Exception as e:  # noqa: BLE001 - reported via state
            q.error = f"decode failed: {e!r}"
            # undecodable bytes are a malformed plan by definition
            q.error_class = ErrorClass.PLAN_INVALID.value
            q.transition(QueryState.FAILED)
            self._register(q)
            return None

    def submit_plan(
        self,
        plan,
        *,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        estimated_bytes: Optional[int] = None,
        use_cache: bool = True,
        tenant: str = "default",
    ) -> Query:
        """Driver entry: run every partition of an in-process plan."""
        q = Query(
            plan=plan,
            priority=priority,
            deadline_s=(
                deadline_s if deadline_s is not None
                else self.default_deadline_s
            ),
            estimated_bytes=(
                estimated_bytes if estimated_bytes is not None
                else estimate_plan_device_bytes(plan)
            ),
            use_cache=use_cache,
            tenant=tenant,
        )
        self._attach_obs(q)
        if self.draining:
            return self._reject_draining(q)
        q._decoded = None
        q._fingerprint = plan.fingerprint()
        q._fingerprint_stable = plan.fingerprint_is_stable()
        return self._enqueue(q)

    def _reject_draining(self, q: Query) -> Query:
        """DRAINING rejection: classified TRANSIENT so a bare client
        retries with backoff (the replica or its rolling-restart
        replacement comes back) and a fronting router treats it as a
        placement miss (spill to the next replica, zero breaker
        strikes). The 'DRAINING:' error prefix is the wire marker both
        consumers key on."""
        q.error = (
            "DRAINING: replica is draining (rolling restart); "
            "resubmit elsewhere or retry with backoff"
        )
        q.error_class = ErrorClass.TRANSIENT.value
        q.transition(QueryState.REJECTED_OVERLOADED)
        self._register(q)
        return q

    def _reject_tenant_budget(self, q: Query) -> Query:
        """Tenant-budget rejection (the DRAINING pattern one tenant
        over): classified TRANSIENT so a bare client retries with
        backoff (the tenant's own in-flight work draining frees the
        budget) and a fronting router treats it as a placement miss
        (spill to the next replica, zero breaker strikes - the
        replica is healthy, the TENANT is over budget). The
        'REJECTED_TENANT_BUDGET:' error prefix is the wire marker
        both consumers key on. The query is already registered by
        _enqueue."""
        q.error = (
            f"REJECTED_TENANT_BUDGET: tenant {q.tenant!r} is over "
            "its admission budget; retry with backoff as its own "
            "work drains"
        )
        q.error_class = ErrorClass.TRANSIENT.value
        q.transition(QueryState.REJECTED_OVERLOADED)
        REGISTRY.inc("blaze_tenant_rejections_total", tenant=q.tenant)
        return q

    def drain(self, timeout_s: Optional[float] = None,
              poll_s: float = 0.05) -> bool:
        """Enter DRAINING and block until every live query reached a
        terminal state (True) or `timeout_s` elapsed (False, still
        draining - the caller decides whether to hard-stop). New
        SUBMITs are refused from the moment this is called; POLL /
        FETCH / CANCEL keep working so clients can collect results
        already in flight.

        OPEN STREAMS are live work: a query with an in-progress FETCH
        (fetchers > 0) holds the drain even when it is already
        terminal, so a rolling restart finishes delivering the parts a
        client is actively reading instead of severing the stream. A
        consumer that stops draining cannot pin the drain past the
        grace - the stream stall budget aborts it, and a grace expiry
        hands the stream off to the router's journal/failover resume
        path (the client re-FETCHes the re-placed query and skips the
        delivered prefix)."""
        self.draining = True
        REGISTRY.inc("blaze_service_drains_total")
        log.info("service draining: refusing new submits, waiting "
                 "for in-flight queries and open streams")
        deadline = (
            time.monotonic() + timeout_s
            if timeout_s is not None else None
        )
        while True:
            with self._lock:
                live = sum(
                    1 for q in self._queries.values()
                    if not q.done or q.fetchers > 0
                )
            if not live:
                return True
            if deadline is not None and time.monotonic() >= deadline:
                log.warning(
                    "drain timed out with %d live queries/streams",
                    live,
                )
                return False
            time.sleep(poll_s)

    def _attach_obs(self, q: Query) -> None:
        """Arm per-query observability BEFORE any transition can fire:
        the span tree (root opens at submit) and the terminal hook
        (runtime history / metrics / slow-query log)."""
        if obs_trace.ACTIVE:
            q.tracer = obs_trace.begin_trace(q.query_id)
            q.ctx.tracer = q.tracer
        if self.mesh_mode is not None:
            q.ctx.mesh_mode = self.mesh_mode
        # fleet claims are per-tenant (fleet/claims): the coordinator
        # reads the identity off the ExecContext
        q.ctx.tenant = q.tenant
        if self.stream_buffer_bytes > 0:
            from blaze_tpu.service.stream import StreamBuffer

            q.stream = StreamBuffer(
                self.stream_buffer_bytes,
                self.stream_stall_s,
                on_pending=(
                    lambda delta, _q=q:
                    self.admission.adjust_reservation(_q, delta)
                ),
                on_event=self._note_stream_event,
            )
        q.on_terminal = self._on_query_terminal

    def _note_stream_event(self, name: str, value: int = 1) -> None:
        """StreamBuffer observability fan-in (per-query rings, one
        service-level rollup): stall/backpressure counters + the
        high-water gauge STATS and METRICS expose."""
        with self._lock:
            if name == "stall":
                self.obs_counters["stream_stalls"] += 1
            elif name == "backpressure_wait":
                self.obs_counters["stream_backpressure_waits"] += 1
            elif name == "high_water":
                if value > self._stream_high_water:
                    self._stream_high_water = value

    def _enqueue(self, q: Query) -> Query:
        self._register(q)
        if q.deadline_at is not None and q.deadline_exceeded():
            # deadline shedding: a deadline that has already passed
            # cannot be met - refuse up front instead of queueing work
            # that the sweep will kill anyway
            self.admission.note_shed()
            q.error = "deadline unmeetable at admission (shed)"
            q.transition(QueryState.TIMED_OUT)
            return q
        if self._fast_path_eligible(q):
            # admission fast path (zero-copy serve path): the result
            # cache fully covers this fingerprint, so serving it
            # dispatches nothing and reserves nothing - bypass the
            # byte-reservation queue entirely. A queued fleet cannot
            # starve cached repeats (and past c16 the reservation
            # round-trip itself was the cached-qps wall). The rare
            # eviction between this probe and the execution probe
            # falls through to an unreserved execution - bounded by
            # the fast pool, and the cache re-populates
            if q.try_transition(QueryState.ADMITTED):
                q.timings["admitted"] = time.monotonic()
                if q.tracer is not None:
                    q.tracer.record_span(
                        "queue_wait", q.timings["submitted"],
                        q.timings["admitted"], fast_path=True,
                    )
                with self._lock:
                    self.admission_log.append(q.query_id)
                    self.obs_counters["fast_path_serves"] += 1
                self._fast_pool.submit(self._run_query, q)
            return q
        if chaos.ACTIVE:
            # DROP = the tenant budget check itself fails (fail
            # CLOSED: a broken check must reject, never admit - the
            # rejection is TRANSIENT and spillable, an admit would
            # breach the budget); STALL = a slow budget path
            try:
                chaos.fire("service.tenant", tenant=q.tenant,
                           query=q.query_id)
            except ConnectionError:
                return self._reject_tenant_budget(q)
        verdict = self.admission.offer(q)
        if verdict == "tenant_budget":
            return self._reject_tenant_budget(q)
        if verdict != "ok":
            q.error = (
                f"queue full ({self.admission.max_queue_depth}); "
                "retry with backoff"
            )
            q.transition(QueryState.REJECTED_OVERLOADED)
            return q
        self._kick()
        return q

    def _register(self, q: Query) -> None:
        with self._lock:
            self._queries[q.query_id] = q
            self._order.append(q.query_id)
            while len(self._order) > _MAX_RETAINED:
                old = self._order[0]
                oq = self._queries.get(old)
                if oq is not None and not oq.done:
                    break  # never drop a live query
                self._order.pop(0)
                self._queries.pop(old, None)

    # -- lifecycle API --------------------------------------------------
    def get(self, query_id: str) -> Query:
        with self._lock:
            q = self._queries.get(query_id)
        if q is None:
            raise KeyError(f"unknown query {query_id}")
        return q

    def poll(self, query_id: str) -> dict:
        q = self.get(query_id)
        q.note_activity()  # a polled query has an attentive owner
        return q.status()

    def cancel(self, query_id: str) -> dict:
        """Request cancellation. QUEUED queries die here; ADMITTED and
        RUNNING ones observe the event at the next batch boundary (the
        executor's cancellation pass-through keeps the engine clean)."""
        q = self.get(query_id)
        q.request_cancel()
        if q.state is QueryState.QUEUED:
            q.try_transition(QueryState.CANCELLED)
        self._kick()
        return q.status()

    def result(self, query_id: str, timeout: Optional[float] = None):
        """Block until terminal; return the materialized RecordBatch
        list on DONE, raise on every other terminal state."""
        q = self.get(query_id)
        if not q.wait(timeout):
            raise TimeoutError(f"query {query_id} still {q.state.value}")
        if q.state is QueryState.DONE:
            return q.result
        if q.state is QueryState.CANCELLED:
            raise QueryCancelled(query_id)
        raise RuntimeError(
            f"query {query_id} {q.state.value}: {q.error or ''}"
        )

    def report(self, query_id: str) -> str:
        """Per-query observability rollup: lifecycle timings, cache and
        dispatch counters, and the mirrored operator metric tree."""
        from blaze_tpu.runtime.instrument import render_metrics

        q = self.get(query_id)
        q.note_activity()
        st = q.status()
        head = [
            f"query {q.query_id}: {st['state']} "
            f"(priority={q.priority}, est_bytes={q.estimated_bytes})"
        ]
        if st.get("error_class"):
            head.append(f"  error_class={st['error_class']}")
        if st.get("degraded"):
            head.append("  degraded=True (host-engine fallback)")
        for k in ("queue_wait_s", "admission_s", "execution_s",
                  "stream_s"):
            if k in st:
                head.append(f"  {k}={st[k]}")
        for a in st.get("attempts", ()):
            head.append(
                f"  attempt p{a['partition']}#{a['attempt']}: "
                f"{a['error_class']} -> {a['action']} ({a['error']})"
            )
        body = render_metrics(q.metrics_root, indent="  ")
        return "\n".join(head) + ("\n" + body if body else "")

    def stats(self) -> dict:
        """Structured service snapshot (the STATS verb payload): the
        machine-readable form replica routing consumes - admission
        headroom + queue depth, cache hit/miss/evictions, degradation
        and quarantine counts, and the runtime-history summary."""
        with self._lock:
            by_state: Dict[str, int] = {}
            live = 0
            for q in self._queries.values():
                by_state[q.state.value] = (
                    by_state.get(q.state.value, 0) + 1
                )
                if not q.done:
                    live += 1
        out = {
            "admission": self.admission.stats(),
            "queries": {
                "live": live,
                "by_state": by_state,
                **self.obs_counters,
            },
            "runtime_history": self.history.summary(),
            # per-phase rollup (bounded classes; regress CLI diffs it)
            "phases": obs_phases.ROLLUP.snapshot(max_classes=6),
            "quarantine": {
                # cluster drivers in this process record quarantines
                # on the shared registry (runtime/cluster.py)
                "workers_total": int(
                    REGISTRY.get("blaze_worker_quarantines_total")
                ),
            },
            "service": {
                "max_concurrency": self.admission.max_concurrency,
                "max_queue_depth": self.admission.max_queue_depth,
                "slow_query_s": self.slow_query_s,
                "trace_enabled": self._trace_enabled,
                "mesh_mode": self.mesh_mode or "env",
                # membership signal: the router's registry poller
                # reads this to mark the replica DRAINING (unroutable
                # for NEW placements) before any submit bounces
                "draining": self.draining,
                # orphan sweep (serve --orphan-ttl): retention held
                # by a dead router's abandoned detached queries is
                # reclaimed after this long (null = disabled)
                "orphan_ttl_s": self.orphan_ttl_s,
            },
            # streaming data plane (service/stream.py): the ring cap +
            # stall budget, and the high-water gauge the slow-consumer
            # acceptance pin asserts against
            "streaming": {
                "enabled": self.stream_buffer_bytes > 0,
                "buffer_bytes": self.stream_buffer_bytes,
                "stall_s": self.stream_stall_s,
                "buffer_high_water_bytes": self._stream_high_water,
            },
        }
        tenants = self.admission.tenant_stats()
        if tenants:
            # per-tenant admission view (docs/SERVICE.md "Tenancy"):
            # queued/running/reserved_bytes live gauges + lifetime
            # submit/admit/reject counts; the router sums these
            # fleet-wide. Empty (and absent) until a tenant submits.
            out["tenants"] = tenants
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        # zero-copy serve path (blaze_tpu/zerocopy): decoded-plan
        # cache hit/miss/eviction counters and arena segment/lease
        # accounting - the replica surface the router's plan_cache
        # rollup and the zerocopy tests read
        if self.plan_cache is not None:
            out["plan_cache"] = self.plan_cache.stats()
        if self.arena is not None:
            out["arena"] = self.arena.stats()
        # lock-wait accounting (obs/contention.py): empty dict when
        # the gate is off or nothing contended yet
        out["contention"] = obs_contention.snapshot()
        # mesh stage anatomy (obs/meshprof.py): per-op sub-phase
        # percentiles + bytes staged; empty until a mesh stage runs
        out["meshprof"] = obs_meshprof.snapshot()
        return out

    def trace(self, query_id: str) -> Optional[dict]:
        """Chrome-trace-event JSON for one query (Perfetto-loadable),
        or None when tracing was off for it. Served through the
        REPORT verb and `python -m blaze_tpu trace <query_id>`."""
        q = self.get(query_id)
        rec = q.tracer or obs_trace.get_trace(query_id)
        return obs_trace.chrome_trace(rec) if rec is not None else None

    def trace_spans(self, query_id: str) -> Optional[list]:
        """One query's RAW span dicts (TraceRecorder.to_dicts), or
        None when tracing was off for it. The replica router's REPORT
        path requests these (flags bit 1) instead of the rendered
        Chrome document so it can graft the subtree into its OWN
        recorder via attach_subtree - re-parsing an exported trace
        back into spans would lose ids and parent links."""
        q = self.get(query_id)
        rec = q.tracer or obs_trace.get_trace(query_id)
        return rec.to_dicts() if rec is not None else None

    # -- observability hooks -------------------------------------------
    def _on_query_terminal(self, q: Query) -> None:
        """Exactly-once per query (Query._fire_terminal): fold the
        outcome into the process metrics registry, the per-fingerprint
        runtime history, and (over threshold) the slow-query log."""
        if q.stream is not None:
            # stream finalization rides the exactly-once terminal hook
            # so EVERY terminal path (run-loop exits, queued cancels,
            # deadline sweeps, decode failures) resolves the ring:
            # DONE finishes it (fetchers drain the tail and get the
            # terminator), anything else aborts it and frees retained
            # parts - there is no result to collect
            if q.state is QueryState.DONE:
                q.stream.finish()
            else:
                q.stream.abort(q.state.value)
        if q._plan_entry is not None and not q._tree_consumed \
                and q._decoded is not None:
            # zero-copy plan cache: this query borrowed the entry's
            # decoded tree but never executed it (full cache hit /
            # early terminal), so the tree is still pristine - return
            # it for the next repeat. A consumed (fused) tree stays
            # out forever
            q._plan_entry.restore_tree(q._decoded)
            q._decoded = None  # the entry owns it again
        self._maybe_publish_arena(q)
        t = q.timings
        wall = t.get("finished", time.monotonic()) - t["submitted"]
        REGISTRY.inc("blaze_queries_total", state=q.state.value)
        # per-tenant lifecycle counter: a NEW series (not a label on
        # blaze_queries_total) so zero-config dashboards keep their
        # exact pre-tenancy series shape
        REGISTRY.inc("blaze_tenant_queries_total",
                     tenant=q.tenant, state=q.state.value)
        REGISTRY.observe("blaze_query_wall_seconds", wall)
        retried = any(a.get("action") == "retry" for a in q.attempts)
        slow = 0 < self.slow_query_s < wall
        with self._lock:  # concurrent worker threads reach terminal
            if retried:
                self.obs_counters["retried_queries"] += 1
            if q.degraded:
                self.obs_counters["degraded_queries"] += 1
            if slow:
                self.obs_counters["slow_queries"] += 1
        if q.degraded:
            REGISTRY.inc("blaze_degraded_queries_total")
        if (
            q.state is QueryState.DONE
            and q._fingerprint is not None
            and q._fingerprint_stable
            and not q.degraded
            and "run_start" in t and "finished" in t
        ):
            # clean device executions only: degraded runs measure the
            # host fallback, not the plan
            self.history.record(
                q._fingerprint, t["finished"] - t["run_start"]
            )
        if slow:
            REGISTRY.inc("blaze_slow_queries_total")
            slowlog.emit(q, self.slow_query_s)
        # per-phase rollup (obs/phases.py): fold the finished query's
        # lifecycle timings + span tree into the duration rings the
        # regress CLI diffs - terminal-hook time, never the hot path
        if self._fold_phases:
            try:
                obs_phases.ROLLUP.fold_query(q)
            except Exception:  # noqa: BLE001 - obs must not raise
                log.exception("phase rollup fold failed for %s",
                              q.query_id)

    def _maybe_publish_arena(self, q: Query) -> None:
        """Zero-copy arena publish (terminal-hook time, never the hot
        path): a clean DONE with a stable cacheable fingerprint gets
        its result encoded ONCE into an mmap segment; every later
        FETCH of the same fingerprint serves those frames scatter-
        gather (or as a shm handle) instead of re-encoding. Idempotent
        per fingerprint; the membership test keeps repeats free."""
        arena = self.arena
        if (
            arena is None or q.state is not QueryState.DONE
            or q._fingerprint is None or not q._fingerprint_stable
            or not q.use_cache or q.degraded or not q.result
        ):
            return
        if q._fingerprint in arena:
            return
        try:
            from blaze_tpu.io.ipc import encode_ipc_segment

            arena.publish(
                q._fingerprint,
                [encode_ipc_segment(rb) for rb in q.result],
            )
        except Exception:  # noqa: BLE001 - arena is best-effort
            log.exception("arena publish failed for %s", q.query_id)

    def _collect_metrics(self):
        """Scrape-time samples for the process registry (METRICS verb):
        live admission/cache/history state as gauges, cumulative event
        counts as counters. A generator: the registry consumes it
        directly, so no per-scrape sample list is materialized here."""
        sid = {"service": self._instance}  # series-disambiguating
        a = self.admission.stats()
        for k in ("submitted", "admitted", "rejected_overloaded",
                  "shed_deadline", "shed_predicted",
                  "headroom_waits"):
            yield ("blaze_admission_events_total",
                   {"event": k, **sid}, a.get(k, 0), "counter")
        for k in ("queued", "running", "reserved_bytes", "headroom"):
            yield (f"blaze_admission_{k}", sid, a.get(k, 0), "gauge")
        for t, ts in self.admission.tenant_stats().items():
            tl = {"tenant": t, **sid}
            for k in ("queued", "running", "reserved_bytes"):
                yield (f"blaze_tenant_{k}", tl, ts.get(k, 0), "gauge")
            yield ("blaze_tenant_rejections",
                   tl, ts.get("rejected_budget", 0), "counter")
        if self.cache is not None:
            c = self.cache.stats()
            for k in ("hits", "misses", "evictions", "puts", "spills",
                      "restores", "spill_errors", "coalesced"):
                yield ("blaze_result_cache_events_total",
                       {"event": k, **sid}, c.get(k, 0), "counter")
            for k in ("entries", "bytes", "spilled_entries"):
                yield (f"blaze_result_cache_{k}", sid,
                       c.get(k, 0), "gauge")
        if self.plan_cache is not None:
            pc = self.plan_cache.stats()
            for k in ("hits", "misses", "evictions", "puts"):
                yield ("blaze_plan_cache_events_total",
                       {"event": k, **sid}, pc.get(k, 0), "counter")
            yield ("blaze_plan_cache_entries", sid,
                   pc.get("entries", 0), "gauge")
        if self.arena is not None:
            ar = self.arena.stats()
            for k in ("published", "evictions", "handle_hits",
                      "handle_misses", "sg_serves", "lease_releases",
                      "lease_orphans_reaped", "map_failures",
                      "lease_faults"):
                yield ("blaze_arena_events_total",
                       {"event": k, **sid}, ar.get(k, 0), "counter")
            for k in ("segments", "bytes", "active_leases"):
                yield (f"blaze_arena_{k}", sid, ar.get(k, 0), "gauge")
        with self._lock:
            orphans = self.obs_counters["orphans_reaped"]
            stalls = self.obs_counters["stream_stalls"]
            bp_waits = self.obs_counters["stream_backpressure_waits"]
            high_water = self._stream_high_water
            fast_path = self.obs_counters["fast_path_serves"]
        yield ("blaze_service_fast_path_serves_total",
               sid, fast_path, "counter")
        yield ("blaze_service_orphans_reaped_total",
               sid, orphans, "counter")
        yield ("blaze_service_stream_stalls_total",
               sid, stalls, "counter")
        yield ("blaze_service_stream_backpressure_waits_total",
               sid, bp_waits, "counter")
        yield ("blaze_service_stream_buffer_high_water_bytes",
               sid, high_water, "gauge")
        h = self.history.summary(top=0)
        yield ("blaze_runtime_history_fingerprints",
               sid, h["fingerprints"], "gauge")
        yield ("blaze_runtime_history_samples_total",
               sid, h["total_samples"], "counter")

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        REGISTRY.unregister_collector(self._collector_key)
        if self._trace_enabled:
            obs_trace.disable()
        self._stop = True
        # shutdown cancels every live query: queued ones die here,
        # running ones observe the event at their next batch boundary -
        # otherwise worker shutdown would wait on them forever
        with self._lock:
            live = [q for q in self._queries.values() if not q.done]
        for q in live:
            q.request_cancel(reason="shutdown")
            if q.state is QueryState.QUEUED:
                q.try_transition(QueryState.CANCELLED)
        with self._cv:
            self._cv.notify_all()
        self._dispatcher.join(timeout=5)
        self._workers.shutdown(wait=True, cancel_futures=True)
        self._fast_pool.shutdown(wait=True, cancel_futures=True)
        if self.cache is not None:
            self.cache.close()
        if self.arena is not None:
            self.arena.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- dispatcher -----------------------------------------------------
    def _kick(self) -> None:
        """Request a dispatcher pass. Batched: if a kick is already
        pending the dispatcher will see our event on the same pass, so
        skip the lock round-trip entirely (the flag is monotone until
        the dispatcher clears it - a stale read only costs one extra
        notify, never a lost wakeup)."""
        if self._kick_pending:
            return
        with self._cv:
            self._kick_pending = True
            self._cv.notify_all()

    def _dispatch_loop(self) -> None:
        while not self._stop:
            with self._cv:
                if not self._kick_pending:
                    self._cv.wait(timeout=0.05)
                self._kick_pending = False
            if self._stop:
                return
            self._sweep_deadlines()
            self._sweep_orphans()
            while True:
                q = self.admission.next_admissible()
                if q is None:
                    break
                # predicted-unmeetability shedding: queue-wait is
                # already spent; if the fingerprint's p50 runtime
                # (>= 3 samples, obs/history.py) cannot fit the
                # remaining slack, running the query only burns
                # device time to miss the deadline anyway
                reason = self._predicted_unmeetable(q)
                if reason is not None:
                    self.admission.release(q)
                    self.admission.note_shed_predicted()
                    if q.tracer is not None:
                        q.tracer.event("shed_predicted",
                                       reason=reason)
                    prev = q.error
                    q.error = reason
                    if not q.try_transition(QueryState.TIMED_OUT):
                        q.error = prev  # lost the race (cancelled)
                    continue
                if not q.try_transition(QueryState.ADMITTED):
                    # cancelled / timed out between queue and admit
                    self.admission.release(q)
                    continue
                self.admission.note_admitted()
                q.timings["admitted"] = time.monotonic()
                if q.tracer is not None:
                    q.tracer.record_span(
                        "queue_wait", q.timings["submitted"],
                        q.timings["admitted"],
                    )
                with self._lock:
                    self.admission_log.append(q.query_id)
                self._workers.submit(self._run_query, q)

    def _predicted_unmeetable(self, q: Query) -> Optional[str]:
        """Shed message when the runtime-history p50 estimate says the
        deadline cannot be met from here, else None. Conservative by
        construction: needs a deadline, a stable fingerprint, and >= 3
        recorded samples - one cold-compile outlier never sheds."""
        if q.deadline_at is None or q._fingerprint is None:
            return None
        if not q._fingerprint_stable:
            return None
        est = self.history.p50(q._fingerprint, min_samples=3)
        if est is None:
            return None
        if time.monotonic() + est < q.deadline_at:
            return None
        # a fully-cached query serves in milliseconds regardless of
        # its recorded runtime - shedding it on the estimate would
        # refuse work the cache answers inside any deadline (and,
        # since sheds never execute, would pin the slow estimate
        # forever)
        if (
            self.cache is not None and q.use_cache
            and self._cache_covers(q)
        ):
            return None
        return (
            f"predicted unmeetable at admission (shed): p50 runtime "
            f"{est:.3f}s exceeds remaining slack"
        )

    def _fast_path_eligible(self, q: Query) -> bool:
        """Admission-bypass guard: cache-covered stable repeats only,
        and never while draining/closing (the drain path owns live
        accounting) or after a pre-admission cancel."""
        if (
            self.cache is None or not q.use_cache or self.draining
            or self._closed or q.cancel_requested
            or q._fingerprint is None or not q._fingerprint_stable
        ):
            return False
        try:
            return self._cache_covers(q)
        except Exception:  # noqa: BLE001 - fall back to the queue
            return False

    def _cache_covers(self, q: Query) -> bool:
        """True when every partition the query would run is present
        (and fresh) in the result cache."""
        if q.plan is not None:
            partitions = range(q.plan.partition_count)
        elif q._decoded is not None:
            partitions = [q._decoded[1]]
        elif q._plan_partition is not None:
            # plan-cache metadata hit without the decoded tree: the
            # entry's recorded partition stands in for it
            partitions = [q._plan_partition]
        else:
            return False
        return all(
            self.cache.contains((q._fingerprint, p))
            for p in partitions
        )

    def _sweep_deadlines(self) -> None:
        now = time.monotonic()
        with self._lock:
            live = [
                q for q in self._queries.values() if not q.done
            ]
        for q in live:
            if not q.deadline_exceeded(now):
                continue
            if q.state is QueryState.QUEUED:
                # error BEFORE the transition: the exactly-once
                # terminal hook (trace root tags, slow-query log)
                # snapshots the query as the transition fires
                prev = q.error
                q.error = "deadline exceeded while queued"
                if not q.try_transition(QueryState.TIMED_OUT):
                    q.error = prev  # lost the race to another state
            elif q.state in (QueryState.ADMITTED, QueryState.RUNNING):
                # propagate the cancel event so the run loop (or a
                # retry-backoff wait) observes it promptly; the run
                # loop itself performs the TIMED_OUT transition AFTER
                # closing the operator generator, preserving the
                # invariant that a terminal state implies cleaned-up
                # execution resources
                q.request_cancel(reason="deadline")

    def _sweep_orphans(self) -> None:
        """Reap terminal queries no router will ever collect: never
        fetched, no POLL/REPORT activity for orphan_ttl_s. Closes the
        replica-side leak of a permanently-dead router - the detached
        downstream runs it abandoned must not pin retention (and their
        materialized results) forever. Throttled to ~4 sweeps per TTL
        so the dispatcher loop stays cheap."""
        ttl = self.orphan_ttl_s
        if ttl is None:
            return
        now = time.monotonic()
        if now < self._next_orphan_sweep:
            return
        self._next_orphan_sweep = now + max(0.05, ttl / 4.0)
        reaped = []
        with self._lock:
            for qid, q in self._queries.items():
                if not q.done or q.fetched or q.fetchers > 0:
                    continue
                idle_since = max(q.last_activity,
                                 q.timings.get("finished", 0.0))
                if now - idle_since > ttl:
                    reaped.append(qid)
            for qid in reaped:
                self._queries.pop(qid, None)
            if reaped:
                gone = set(reaped)
                self._order = [
                    qid for qid in self._order if qid not in gone
                ]
                self.obs_counters["orphans_reaped"] += len(reaped)
        for qid in reaped:
            log.info("reaped orphaned query %s (terminal, never "
                     "fetched, idle > %.1fs)", qid, ttl)

    # -- execution ------------------------------------------------------
    def _run_query(self, q: Query) -> None:
        try:
            if chaos.ACTIVE:
                # chaos seam (STALL widens the ADMITTED->RUNNING window
                # so cancellation races become deterministic tests); a
                # RAISED fault here goes through the same taxonomy
                # surfacing as any pre-execution failure
                try:
                    with (obs_trace.span("service_admit",
                                         rec=q.tracer)
                          if obs_trace.ACTIVE else obs_trace.NULL):
                        chaos.fire("service.admit",
                                   query_id=q.query_id)
                except Exception as e:  # noqa: BLE001 - classified
                    q.error = f"{type(e).__name__}: {e}"
                    q.error_class = classify(e).value
                    q.try_transition(QueryState.FAILED)
                    return
            # an explicit user/shutdown cancel wins over a deadline
            # that elapsed concurrently; a sweep-fired ('deadline')
            # cancel - or a bare deadline expiry - reports TIMED_OUT
            if q.cancel_requested and q.cancel_reason in (
                "user", "shutdown"
            ):
                if q.try_transition(QueryState.CANCELLED):
                    return
            if q.deadline_exceeded():
                prev = q.error
                q.error = "deadline exceeded before start"
                if q.try_transition(QueryState.TIMED_OUT):
                    return
                q.error = prev
            if q.cancel_requested:
                if q.try_transition(QueryState.CANCELLED):
                    return
            if not q.try_transition(QueryState.RUNNING):
                return
            q.timings["run_start"] = time.monotonic()
            if q.tracer is not None and "admitted" in q.timings:
                q.tracer.record_span(
                    "admission", q.timings["admitted"],
                    q.timings["run_start"],
                )
            try:
                q.result = self._execute(q)
            except QueryCancelled:
                if q.cancel_requested and q.cancel_reason in (
                    "user", "shutdown"
                ):
                    q.try_transition(QueryState.CANCELLED)
                elif q.cancel_requested and q.cancel_reason == (
                    "stream_stalled"
                ):
                    # slow-consumer abort (service/stream.py): the
                    # ring already stamped the classified
                    # STREAM_STALLED error; CANCELLED-class keeps it
                    # strike-free for replica circuit breakers even
                    # when a deadline lapsed during the stall wait
                    q.try_transition(QueryState.CANCELLED)
                elif q.deadline_exceeded():
                    q.error = "deadline exceeded while running"
                    q.try_transition(QueryState.TIMED_OUT)
                else:
                    q.try_transition(QueryState.CANCELLED)
                return
            except Exception as e:  # noqa: BLE001 - reported via state
                q.error = f"{type(e).__name__}: {e}"
                q.error_class = classify(e).value
                q.try_transition(QueryState.FAILED)
                log.warning(
                    "query %s failed [%s]: %s",
                    q.query_id, q.error_class, q.error,
                )
                return
            q.try_transition(QueryState.DONE)
        finally:
            self.admission.release(q)
            self._kick()

    def _execute(self, q: Query) -> List:
        """Run (or reuse) every partition of the query's plan."""
        from blaze_tpu.runtime.executor import prepare_decoded_task
        from blaze_tpu.runtime.instrument import instrument

        # wire-manifest resources first (the gateway's resource
        # registry contract); decoded-task resources setdefault under
        # them in prepare_decoded_task
        q.ctx.resources.update(q.resources)

        cache = (
            self.cache
            if (self.cache is not None and q.use_cache
                and q._fingerprint_stable)
            else None
        )
        if q.plan is not None:
            op = q.plan
            if self.mesh_mode in ("auto", "on"):
                # mesh tier for driver plans: root-only cost-guarded
                # lowering. Partition geometry may change (one
                # partition per device), which is consistent per
                # service instance - cache keys stay (fingerprint,
                # partition) over the LOWERED geometry, and the mode
                # is fixed for the process lifetime
                from blaze_tpu.planner.distribute import (
                    lower_plan_to_fleet,
                    lower_plan_to_mesh,
                )

                if self._fleet is not None:
                    # fleet tier first: eligible grouped aggregates
                    # span the whole fleet; everything else falls
                    # through to the single-host pass inside
                    op = lower_plan_to_fleet(
                        op, self._fleet, mode=self.mesh_mode
                    )
                else:
                    op = lower_plan_to_mesh(op, mode=self.mesh_mode)
            partitions = list(range(op.partition_count))
            exec_op = op  # driver plans run as-built (run_plan parity)
        else:
            op = None
            partitions = [
                q._decoded[1] if q._decoded is not None
                else q._plan_partition
            ]
            exec_op = None  # prepared lazily: a full cache hit must
            # not pay fusion/mesh lowering (and must dispatch nothing)

        def run_one(p):
            nonlocal exec_op
            if exec_op is None:
                if q._decoded is None:
                    # plan-cache metadata hit whose tree was loaned
                    # out AND the result cache missed: the lazy
                    # re-decode (still cheaper than the old world -
                    # only cache-missing repeats pay it)
                    q._decoded = self._decode_bytes(q)
                # the tree is about to be fused/lowered IN PLACE:
                # it can never go back into the plan cache
                q._tree_consumed = True
                prepared, _ = prepare_decoded_task(q._decoded, q.ctx)
                if q.ctx.config.collect_metrics:
                    prepared = instrument(prepared, q.metrics_root)
                exec_op = prepared
            return self._run_partition(q, exec_op, p)

        out: List = []
        for p in partitions:
            q.check_interrupt()
            key = (q._fingerprint, p)
            if cache is None:
                out.extend(run_one(p)[0])
                continue
            followed = False
            while True:
                probe_cm = (
                    obs_trace.span("cache_probe", rec=q.tracer,
                                   partition=p)
                    if obs_trace.ACTIVE else obs_trace.NULL
                )
                with probe_cm as sp:
                    hit = cache.get(key)
                    sp.tag(hit=hit is not None,
                           coalesced=followed or None)
                if hit is not None:
                    q.ctx.metrics.add("cache_hits", 1)
                    if followed:
                        # the leader populated the entry while we
                        # waited: this execution was COALESCED away
                        cache.note_coalesced()
                        q.ctx.metrics.add("coalesced", 1)
                    for rb in hit:
                        q.ctx.metrics.add("output_rows", rb.num_rows)
                        if q.stream is not None:
                            # cached partitions feed the ring too -
                            # part order must equal q.result order for
                            # the delivered-prefix resume contract
                            q.stream.put(q, rb)
                    out.extend(hit)
                    break
                # miss: claim leadership of this (fingerprint,
                # partition) or wait on whoever holds it
                with self._inflight_lock:
                    ev = self._inflight.get(key)
                    claimed = ev is None
                    if claimed:
                        ev = threading.Event()
                        self._inflight[key] = ev
                if not claimed:
                    followed = True
                    # interruptible wait: a cancel/deadline during the
                    # coalesce wait must still kill THIS query promptly
                    while not ev.wait(0.02):
                        q.check_interrupt()
                    continue  # leader finished (or failed): re-probe
                q.ctx.metrics.add("cache_misses", 1)
                try:
                    part_batches, degraded = run_one(p)
                    if not degraded:
                        # degraded results are correct but host-
                        # produced; keeping them out of the cache
                        # preserves device-result provenance and lets
                        # a healthy re-run repopulate it
                        cache.put(key, part_batches)
                finally:
                    # release followers even on failure - each re-
                    # probes, misses, and applies its OWN retry policy
                    with self._inflight_lock:
                        self._inflight.pop(key, None)
                    ev.set()
                out.extend(part_batches)
                break
        if getattr(q.ctx, "fleet_degraded", False):
            # the fleet coordinator fell down its ladder (dead peer,
            # denied claim, injected fault): the answer is correct
            # but single-host-produced - q.degraded must say so
            q.degraded = True
        return out

    def _run_partition(self, q: Query, op, partition: int):
        """One partition with CLASSIFIED failure handling
        (blaze_tpu/errors.py): TRANSIENT retries with exponential
        backoff + jitter (cancel-interruptible), RESOURCE_EXHAUSTED
        degrades through the host engine, PLAN_INVALID/INTERNAL fail
        fast with zero retries. Returns (batches, degraded)."""
        from blaze_tpu.runtime.scheduler import backoff_delay

        for attempt in range(self.max_task_attempts):
            q.check_interrupt()
            # obs seam: one span per attempt; a failing attempt is
            # auto-tagged with its error_class by the span exit, so a
            # retried query renders as N attempt spans with N-1 tagged
            # failures
            span_cm = (
                obs_trace.span("attempt", rec=q.tracer,
                               partition=partition, attempt=attempt)
                if obs_trace.ACTIVE else obs_trace.NULL
            )
            try:
                with span_cm:
                    return self._drain(q, op, partition), False
            except QueryCancelled:
                raise
            except Exception as e:  # noqa: BLE001 - classified below
                ec = classify(e)
                action = retry_action(
                    ec, attempt, self.max_task_attempts,
                    self.degrade_to_host,
                )
                if action == "cancel":
                    raise QueryCancelled(q.query_id) from e
                q.record_attempt(partition, attempt, ec.value, e,
                                 action)
                if action == "degrade":
                    batches = self._degrade_partition(q, partition, e)
                    if q.stream is not None:
                        # the failed device attempt's parts were
                        # rolled back in _drain; the host re-run feeds
                        # the ring on success (replay-verified against
                        # any prefix already delivered)
                        for rb in batches:
                            q.stream.put(q, rb)
                    return batches, True
                if action == "fail":
                    raise
                q.ctx.metrics.add("task_retries", 1)
                q.ctx.metrics.add("retries.transient", 1)
                log.warning(
                    "query %s partition %d failed transiently "
                    "(attempt %d), backing off: %s",
                    q.query_id, partition, attempt + 1, e,
                )
                if q.wait_cancel(
                    backoff_delay(attempt, self.retry_backoff_s)
                ):
                    raise QueryCancelled(q.query_id) from e
        raise AssertionError("unreachable: attempt loop fell through")

    def _degrade_partition(self, q: Query, partition: int,
                           cause: BaseException) -> List:
        """RESOURCE_EXHAUSTED degradation: re-execute the partition
        through the pandas host engine against an UNFUSED plan (fused
        pipelines have no host mapping). Wire tasks re-decode from the
        original bytes - prepare_decoded_task fuses the decoded tree
        IN PLACE, so q._decoded is already fused by the time a
        partition fails. Surfaces the ORIGINAL device error when no
        host mapping exists."""
        from blaze_tpu.planner.host_engine import execute_partition_host

        try:
            if q.plan is not None:
                base = q.plan  # driver plans run as-built (never fused)
            elif q.is_ref:
                from blaze_tpu.plan.refcompat import (
                    task_from_reference_proto,
                )

                base = task_from_reference_proto(q.task_bytes)[0]
            else:
                from blaze_tpu.plan.serde import task_from_proto

                base = task_from_proto(q.task_bytes)[0]
            with (obs_trace.span("host_degrade", rec=q.tracer,
                                 partition=partition)
                  if obs_trace.ACTIVE else obs_trace.NULL):
                batches = execute_partition_host(base, partition,
                                                 q.ctx)
        except Exception as host_err:  # noqa: BLE001 - original wins
            log.warning(
                "query %s: host degradation of partition %d "
                "unavailable (%s); surfacing original error",
                q.query_id, partition, host_err,
            )
            raise cause
        q.degraded = True
        q.ctx.metrics.add("degraded_partitions", 1)
        # degradation-aware admission (ROADMAP): THIS partition now
        # runs on the HOST engine - its share of the device-byte
        # reservation gates nothing real anymore, so release it (and
        # wake the dispatcher) to let headroom-waiting device work
        # admit while the host fallback grinds on. Only the share: a
        # multi-partition driver plan's remaining partitions still
        # execute on the device against the rest of the reservation
        nparts = (q.plan.partition_count
                  if q.plan is not None else 1)
        self.admission.release_bytes(q, share_of=max(1, nparts))
        self._kick()
        log.warning(
            "query %s partition %d degraded to host engine after "
            "RESOURCE_EXHAUSTED: %s", q.query_id, partition, cause,
        )
        return batches

    def _drain(self, q: Query, op, partition: int) -> List:
        """Materialize one partition with cooperative interrupt checks
        between batches; closing the generator routes through the
        executor's cancellation pass-through (GeneratorExit), so a
        cancelled query never poisons the engine."""
        from blaze_tpu.runtime.executor import execute_partition

        it = execute_partition(op, partition, q.ctx)
        batches: List = []
        sb = q.stream
        start_pos = sb.position() if sb is not None else 0
        try:
            for rb in it:
                batches.append(rb)
                if sb is not None:
                    # stream-as-produced: the part is visible to an
                    # in-progress FETCH the moment the executor yields
                    # it; put() blocks on the ring's byte cap, so a
                    # slow consumer backpressures THIS loop instead of
                    # growing host memory (StreamStalled/QueryCancelled
                    # propagate through the rollback below)
                    sb.put(q, rb)
                if q.cancel_requested or q.deadline_exceeded():
                    it.close()
                    raise QueryCancelled(q.query_id)
        except BaseException:
            # an abandoned attempt's partial output must not stay in
            # the query counters - a retry (or the host degradation)
            # re-counts the partition from scratch. Same for the ring:
            # undelivered parts truncate; delivered ones stay and the
            # retry replays against them (delivered-prefix verify)
            if sb is not None:
                sb.rollback(start_pos)
            if batches:
                q.ctx.metrics.add(
                    "output_rows", -sum(rb.num_rows for rb in batches)
                )
                q.ctx.metrics.add("output_batches", -len(batches))
            raise
        finally:
            it.close()
        return batches
