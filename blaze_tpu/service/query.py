"""Query lifecycle: per-query record + state machine.

The serving tier's unit of work. A Query wraps either a serialized
TaskDefinition (the wire entry - one partition of one stage, the
reference's callNative currency) or a driver-built plan (every
partition), and carries the scheduling metadata the reference inherits
from Spark's scheduler: priority, deadline, admission cost estimate.

State machine (service/service.py drives it):

    QUEUED -> ADMITTED -> RUNNING -> DONE
       |          |          |-----> FAILED
       |          |          |-----> CANCELLED
       |          |          '-----> TIMED_OUT
       |          |-> CANCELLED | TIMED_OUT | FAILED
       |-> CANCELLED | TIMED_OUT
    (submit may also refuse outright: REJECTED_OVERLOADED)

Transitions are validated; an illegal transition is a bug in the
service, not a recoverable condition, so it raises.
"""

from __future__ import annotations

import enum
import itertools
import threading
import time
from typing import Dict, List, Optional

from blaze_tpu.obs.contention import TimedLock
from blaze_tpu.ops.base import ExecContext


class QueryState(enum.Enum):
    QUEUED = "QUEUED"
    ADMITTED = "ADMITTED"
    RUNNING = "RUNNING"
    DONE = "DONE"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"
    TIMED_OUT = "TIMED_OUT"
    REJECTED_OVERLOADED = "REJECTED_OVERLOADED"


TERMINAL_STATES = frozenset(
    {
        QueryState.DONE,
        QueryState.FAILED,
        QueryState.CANCELLED,
        QueryState.TIMED_OUT,
        QueryState.REJECTED_OVERLOADED,
    }
)

_ALLOWED = {
    QueryState.QUEUED: {
        QueryState.ADMITTED,
        QueryState.CANCELLED,
        QueryState.TIMED_OUT,
        QueryState.REJECTED_OVERLOADED,
        QueryState.FAILED,  # submit-time decode failure
    },
    QueryState.ADMITTED: {
        QueryState.RUNNING,
        QueryState.CANCELLED,
        QueryState.TIMED_OUT,
        QueryState.FAILED,  # admission-window failure (pre-execution)
    },
    QueryState.RUNNING: {
        QueryState.DONE,
        QueryState.FAILED,
        QueryState.CANCELLED,
        QueryState.TIMED_OUT,
    },
}


class QueryRejected(RuntimeError):
    """Submit-time backpressure: the admission queue is full."""


class QueryCancelled(RuntimeError):
    """Raised inside a query's run loop when its cancel event fires."""


_qid_counter = itertools.count()


def _new_query_id() -> str:
    return f"q-{next(_qid_counter)}-{threading.get_ident():x}"


class Query:
    """One submitted query: payload + scheduling metadata + outcome."""

    def __init__(
        self,
        *,
        task_bytes: Optional[bytes] = None,
        plan=None,
        is_ref: bool = False,
        resources: Optional[dict] = None,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        estimated_bytes: Optional[int] = None,
        use_cache: bool = True,
        query_id: Optional[str] = None,
        tenant: str = "default",
    ):
        assert (task_bytes is None) != (plan is None), \
            "exactly one of task_bytes/plan"
        self.query_id = query_id or _new_query_id()
        # multi-tenant identity (docs/SERVICE.md "Tenancy"): rides
        # SUBMIT meta end to end - admission budgets, weighted-fair
        # ordering, per-tenant metrics and the router's rate limits
        # all key on it; "default" = untagged traffic
        self.tenant = str(tenant or "default")
        self.task_bytes = task_bytes
        self.plan = plan
        self.is_ref = is_ref
        self.resources = resources or {}
        self.priority = int(priority)
        self.submitted_at = time.monotonic()
        self.deadline_at = (
            self.submitted_at + deadline_s if deadline_s else None
        )
        self.estimated_bytes = estimated_bytes
        self.use_cache = use_cache

        self.state = QueryState.QUEUED
        self.error: Optional[str] = None
        # failure taxonomy (blaze_tpu/errors.py): the class of the
        # error that terminated the query, and the per-attempt journal
        # the REPORT/wire surface ({partition, attempt, error_class,
        # error, action: retry|degrade|fail})
        self.error_class: Optional[str] = None
        self.attempts: List[Dict] = []
        # True when any partition re-executed through the host engine
        # after RESOURCE_EXHAUSTED (the native->Spark fallback analog)
        self.degraded = False
        self.result: Optional[List] = None  # pa.RecordBatch list
        # observability (blaze_tpu/obs): the per-query TraceRecorder
        # (service-filled when tracing is on; root span opens at
        # submit, closes at the terminal transition) and a terminal
        # callback the service uses for runtime-history recording,
        # metrics, and the slow-query log
        self.tracer = None
        self.on_terminal = None
        self.ctx = ExecContext(task_id=self.query_id)
        # ONE metric tree per query: the executor adds `dispatch.*`
        # deltas to ctx.metrics' root counters, instrument() mirrors
        # the operator tree under the same root, so render_metrics
        # shows both in one per-query report
        self.metrics_root = self.ctx.metrics
        # wall-clock phase timestamps (monotonic), service-filled:
        # submitted / admitted / run_start / finished (+ stream_ns
        # accumulated by the wire tier)
        self.timings: Dict[str, float] = {"submitted": self.submitted_at}
        # orphan detection (service._sweep_orphans): last client
        # touch (poll/report/fetch) and whether the result was ever
        # streamed - a terminal query nobody polls or fetches past
        # the orphan TTL is reaped (its router died; retention must
        # not pin its result forever)
        self.last_activity = self.submitted_at
        self.fetched = False
        # live FETCH streams against this query: the sweep must never
        # reap under an in-progress collection, no matter how slowly
        # the parts pace out relative to the TTL
        self.fetchers = 0
        # incremental result ring (service/stream.py), service-filled
        # when streaming is enabled: the executor feeds it as batches
        # complete and FETCH drains it while the query is RUNNING.
        # None = pre-streaming materialize-then-stream behavior
        self.stream = None

        self._lock = TimedLock("query_state")
        self._cancel = threading.Event()
        self._cancel_reason: Optional[str] = None
        self._done = threading.Event()
        # service-filled (submit-time decode): the decoded task tuple,
        # plan fingerprint, and whether the fingerprint is
        # content-stable (cacheable)
        self._decoded = None
        self._fingerprint: Optional[str] = None
        self._fingerprint_stable = False
        # zero-copy plan cache (blaze_tpu/zerocopy/plan_cache.py),
        # service-filled: the blob digest, the task's partition when
        # known WITHOUT a decoded tuple (a plan-cache hit skips decode
        # entirely), the cache entry whose tree this query borrowed,
        # and whether the borrowed tree went through
        # prepare_decoded_task (fusion mutates it in place - a
        # consumed tree is never returned to the entry)
        self._plan_key: Optional[str] = None
        self._plan_partition: Optional[int] = None
        self._plan_entry = None
        self._tree_consumed = False

    # -- state machine --------------------------------------------------
    def transition(self, new: QueryState) -> None:
        with self._lock:
            if new not in _ALLOWED.get(self.state, ()):  # terminal too
                raise RuntimeError(
                    f"illegal query transition {self.state.name} -> "
                    f"{new.name} ({self.query_id})"
                )
            self.state = new
            fire = False
            if new in TERMINAL_STATES:
                self.timings.setdefault("finished", time.monotonic())
                fire = not self._done.is_set()
                self._done.set()
        if fire:
            self._fire_terminal(new)

    def try_transition(self, new: QueryState) -> bool:
        """Transition if legal from the current state; False otherwise
        (the racy cancel-vs-finish edges use this)."""
        with self._lock:
            if new not in _ALLOWED.get(self.state, ()):
                return False
            self.state = new
            fire = False
            if new in TERMINAL_STATES:
                self.timings.setdefault("finished", time.monotonic())
                fire = not self._done.is_set()
                self._done.set()
        if fire:
            self._fire_terminal(new)
        return True

    def _fire_terminal(self, new: QueryState) -> None:
        """Exactly-once terminal hook, OUTSIDE the state lock (the
        service's observability callback touches its own locks): close
        the trace root span, then notify the service."""
        if self.tracer is not None:
            try:
                self.tracer.finish(
                    state=new.value, error_class=self.error_class,
                    degraded=self.degraded or None,
                )
            except Exception:  # noqa: BLE001 - obs must not raise
                pass
        cb = self.on_terminal
        if cb is not None:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 - obs must not raise
                import logging

                logging.getLogger("blaze_tpu.service").exception(
                    "terminal observability hook failed for %s",
                    self.query_id,
                )

    def note_activity(self) -> None:
        """A client touched this query (POLL/REPORT/FETCH): defer the
        orphan sweep. Unlocked monotonic-float store - races only
        jitter the TTL by one touch."""
        self.last_activity = time.monotonic()

    def begin_fetch(self) -> None:
        """Locked, unlike note_activity: fetchers is a counter, and a
        lost increment under two concurrent FETCHes would let the
        orphan sweep reap this query mid-collection."""
        with self._lock:
            self.fetchers += 1

    def end_fetch(self) -> None:
        with self._lock:
            self.fetchers -= 1

    # -- cancellation / deadline ---------------------------------------
    def request_cancel(self, reason: str = "user") -> None:
        """reason: 'user' | 'shutdown' | 'deadline'. The FIRST reason
        wins - it decides whether the terminal state is CANCELLED
        (user/shutdown intent) or TIMED_OUT (the deadline sweep fires
        the same event, and a user cancel that narrowly precedes the
        deadline must still report CANCELLED)."""
        with self._lock:
            first = not self._cancel.is_set()
            if first:
                self._cancel_reason = reason
            self._cancel.set()
        if first and self.tracer is not None:
            # cancellation lands in the trace as a root-span event
            try:
                self.tracer.event("cancel_requested", reason=reason)
            except Exception:  # noqa: BLE001 - obs must not raise
                pass

    @property
    def cancel_requested(self) -> bool:
        return self._cancel.is_set()

    @property
    def cancel_reason(self) -> Optional[str]:
        return self._cancel_reason

    def deadline_exceeded(self, now: Optional[float] = None) -> bool:
        return (
            self.deadline_at is not None
            and (now if now is not None else time.monotonic())
            >= self.deadline_at
        )

    def check_interrupt(self) -> None:
        """Between-batch cooperative check inside the run loop."""
        if self._cancel.is_set():
            raise QueryCancelled(self.query_id)
        if self.deadline_exceeded():
            raise QueryCancelled(f"{self.query_id}: deadline")

    def wait_cancel(self, timeout: float) -> bool:
        """Interruptible sleep (retry backoff): returns True when the
        cancel event fired during the wait."""
        return self._cancel.wait(timeout)

    # -- failure journal ------------------------------------------------
    def record_attempt(self, partition: int, attempt: int,
                       error_class: str, error: BaseException,
                       action: str) -> None:
        """Journal one failed execution attempt; travels the wire in
        status() and renders in the REPORT."""
        with self._lock:
            self.attempts.append({
                "partition": partition,
                "attempt": attempt,
                "error_class": error_class,
                "error": str(error)[:300],
                "action": action,
            })

    # -- completion -----------------------------------------------------
    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    @property
    def done(self) -> bool:
        return self.state in TERMINAL_STATES

    def status(self) -> dict:
        """Poll payload: state + timings + per-query counters."""
        m = self.ctx.metrics.counters
        t = dict(self.timings)
        out = {
            "query_id": self.query_id,
            "state": self.state.value,
            "priority": self.priority,
        }
        if self.tenant != "default":
            # zero-config payloads stay byte-identical: only tagged
            # traffic carries the tenant field back
            out["tenant"] = self.tenant
        if self.error:
            out["error"] = self.error
        if self.error_class:
            out["error_class"] = self.error_class
        if self.degraded:
            out["degraded"] = True
        if self.attempts:
            with self._lock:
                out["attempts"] = list(self.attempts)
            out["retries"] = sum(
                1 for a in out["attempts"] if a["action"] == "retry"
            )
        if "admitted" in t:
            out["queue_wait_s"] = round(t["admitted"] - t["submitted"], 6)
        if "run_start" in t and "admitted" in t:
            out["admission_s"] = round(t["run_start"] - t["admitted"], 6)
        if "finished" in t and "run_start" in t:
            out["execution_s"] = round(t["finished"] - t["run_start"], 6)
        if "stream_ns" in t:
            out["stream_s"] = round(t["stream_ns"] / 1e9, 6)
        if self.stream is not None and self.stream.consumers_seen:
            # in-progress stream visibility (POLL while FETCHing):
            # parts produced vs delivered + the backpressure signal
            out["stream_parts"] = self.stream.total_parts()
            out["stream_consumed"] = self.stream.consumed
        for k in ("output_rows", "output_batches", "cache_hits",
                  "cache_misses", "coalesced"):
            if k in m:
                out[k] = m[k]
        out["dispatches"] = m.get("dispatch.dispatches", 0)
        if self._fingerprint is not None and self._fingerprint_stable:
            # stable content fingerprint: the affinity key replica
            # routing and the runtime-history store share
            out["fingerprint"] = self._fingerprint
        return out
