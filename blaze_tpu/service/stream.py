"""Per-query bounded incremental result buffer (the streaming tentpole).

The execution path used to materialize a whole partition list before
FETCH moved the first byte, and an unbounded retention list was the
only thing between a slow consumer and host OOM. StreamBuffer turns
the result path into a flow-controlled ring:

  * the PRODUCER (service._drain / cache hits / host degradation)
    appends RecordBatches as they come off the executor generator and
    BLOCKS once the un-consumed ("pending") bytes exceed the byte cap
    - backpressure propagates into execution instead of growing host
    memory. Blocking only engages once a consumer has ever attached:
    driver-side `service.result()` users and detached never-fetched
    queries keep today's materialize-everything behavior (nothing
    would ever drain the ring, so blocking on it would deadlock).
  * the CONSUMER (wire-tier FETCH) delivers parts while the query is
    still RUNNING and marks them consumed, which releases pending
    bytes and wakes the producer. Delivered parts are RETAINED (they
    are the same RecordBatch objects that become q.result), so the
    count-based part-skip resume protocol and double-FETCH both work
    on in-progress streams with zero wire changes.
  * a consumer that stops draining for longer than the stall budget
    while the producer sits at the cap aborts the stream with the
    classified STREAM_STALLED outcome: CANCELLED-class (never a
    breaker strike at the router - errors.FATAL_FOR_REPLICA excludes
    CANCELLED by construction), buffer freed, and the query's device
    reservation released by the normal terminal path.

Pending bytes are accounted against the query's admission reservation
(AdmissionController.adjust_reservation) while a consumer is attached,
so buffered-but-undelivered output gates new admissions exactly like
the device bytes it mirrors - the DeviceMemoryTracker headroom check
sees it through the existing `reserved_bytes` path.

Delivered-prefix consistency: a retry/degrade after parts were already
delivered re-produces the partition. `rollback()` truncates only the
UNDELIVERED suffix; re-produced parts overlapping the delivered prefix
are verified batch-equal against what was sent (put() replay mode). A
divergent re-execution poisons the stream with the same
"re-executed result diverged" contract the router's blake2b splice
check enforces across replicas - failing loudly beats silently
splicing inconsistent data.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from blaze_tpu.errors import ErrorClass, PlanInvalidError
from blaze_tpu.obs.contention import TimedLock
from blaze_tpu.service.query import QueryCancelled


class StreamStalled(QueryCancelled):
    """Consumer made no progress past the stall budget with the buffer
    at its cap. QueryCancelled subclass: the run loop's cancel ladder
    surfaces it as terminal CANCELLED (reason 'stream_stalled'), which
    keeps it strike-free for replica circuit breakers."""


class StreamSpliceError(PlanInvalidError):
    """A retried execution diverged from parts already delivered to a
    live consumer. PLAN_INVALID-class: fail fast, zero further retries
    (retrying cannot un-deliver the stale prefix)."""


class StreamBuffer:
    """Bounded, ordered, multi-consumer result ring for one query.

    max_pending_bytes caps PENDING (produced, not yet consumed) bytes;
    consumed parts stay retained for resume/re-FETCH but stop counting
    against the cap. A single part larger than the cap is always
    admitted when the ring is empty (progress beats the bound)."""

    def __init__(
        self,
        max_pending_bytes: int,
        stall_s: float,
        on_pending: Optional[Callable[[int], None]] = None,
        on_event: Optional[Callable[[str, int], None]] = None,
    ):
        self.max_pending = max(1, int(max_pending_bytes))
        self.stall_s = float(stall_s)
        self._on_pending = on_pending
        self._on_event = on_event
        self._cv = threading.Condition(TimedLock("stream_ring"))
        # async-consumer bridge: the event-loop FETCH path
        # (service/wire_async.py) cannot park in self._cv.wait - it
        # registers a waker callback instead, fired on every state
        # change alongside the CV notify. Callbacks must be cheap and
        # thread-safe (loop.call_soon_threadsafe(ev.set)).
        self._wakers: List[Callable[[], None]] = []
        self.parts: List = []  # produced pa.RecordBatch refs, in order
        self._nbytes: List[int] = []
        # producer cursor: == len(parts) normally; behind it while
        # replaying a rolled-back attempt over the delivered prefix
        self._pos = 0
        self.consumed = 0  # delivery floor: max part index sent + 1
        self.finished = False
        self.aborted: Optional[str] = None
        self.consumers_seen = 0
        self.pending_bytes = 0
        self.high_water = 0  # max pending bytes ever observed
        self.backpressure_waits = 0
        self.stalls = 0
        self._held = 0  # bytes currently reported via on_pending
        self._last_progress = time.monotonic()

    # -- accounting (caller holds self._cv) ----------------------------
    def _account_locked(self) -> None:
        """Reconcile the admission hold with pending bytes. Holds are
        only live while a consumer is attached: a never-fetched query
        must keep byte-identical admission behavior with the
        pre-streaming service."""
        want = self.pending_bytes if self.consumers_seen > 0 else 0
        delta, self._held = want - self._held, want
        if delta and self._on_pending is not None:
            try:
                self._on_pending(delta)
            except Exception:  # noqa: BLE001 - accounting best-effort
                pass

    def _event(self, name: str, value: int = 1) -> None:
        if self._on_event is not None:
            try:
                self._on_event(name, value)
            except Exception:  # noqa: BLE001 - obs must not raise
                pass

    def _wake_locked(self) -> None:
        """Wake every waiter: threaded consumers via the CV, async
        consumers via their registered wakers (caller holds _cv)."""
        self._cv.notify_all()
        for w in self._wakers:
            try:
                w()
            except Exception:  # noqa: BLE001 - a dead loop must not
                pass           # poison producer progress

    def add_waker(self, waker: Callable[[], None]) -> None:
        with self._cv:
            self._wakers.append(waker)

    def remove_waker(self, waker: Callable[[], None]) -> None:
        with self._cv:
            try:
                self._wakers.remove(waker)
            except ValueError:
                pass

    # -- producer side --------------------------------------------------
    def position(self) -> int:
        with self._cv:
            return self._pos

    def put(self, q, rb) -> None:
        """Append one produced part, blocking while the ring is over
        its byte cap and a consumer is attached. Raises StreamStalled
        (after cancelling `q`) when the consumer makes no progress for
        stall_s, QueryCancelled on cancel/deadline during the wait,
        StreamSpliceError when a replayed attempt diverges from the
        delivered prefix."""
        nbytes = int(getattr(rb, "nbytes", 0) or 0)
        waited = False
        with self._cv:
            if self.aborted is not None:
                # a stall already killed the stream; the producer is
                # being cancelled - surface the same classified exit
                raise StreamStalled(getattr(q, "query_id", "?"))
            if self._pos < len(self.parts):
                # replay after rollback(): this part was produced by a
                # failed attempt and possibly already delivered -
                # verify the re-execution matches what went out
                prev = self.parts[self._pos]
                if not _batches_equal(prev, rb):
                    self.aborted = "SPLICE_BROKEN"
                    self._clear_locked()
                    self._wake_locked()
                    raise StreamSpliceError(
                        "re-executed result diverged from parts "
                        "already delivered mid-stream; resubmit the "
                        "query"
                    )
                self._pos += 1
                self._wake_locked()
                return
            while (
                self.consumers_seen > 0
                and self.pending_bytes > 0
                and self.pending_bytes + nbytes > self.max_pending
            ):
                if not waited:
                    waited = True
                    self.backpressure_waits += 1
                    self._event("backpressure_wait")
                if q.cancel_requested or q.deadline_exceeded():
                    raise QueryCancelled(q.query_id)
                stalled_for = time.monotonic() - self._last_progress
                if self.stall_s > 0 and stalled_for >= self.stall_s:
                    self._stall_abort_locked(q, stalled_for)
                self._cv.wait(
                    min(0.05, self.stall_s or 0.05)
                    if self.stall_s > 0 else 0.05
                )
            self.parts.append(rb)
            self._nbytes.append(nbytes)
            self._pos = len(self.parts)
            self.pending_bytes += nbytes
            if self.pending_bytes > self.high_water:
                self.high_water = self.pending_bytes
                self._event("high_water", self.high_water)
            self._account_locked()
            self._wake_locked()

    def _stall_abort_locked(self, q, stalled_for: float) -> None:
        """The classified slow-consumer exit: cancel the query with the
        STREAM_STALLED outcome and free the ring."""
        self.stalls += 1
        self._event("stall")
        q.error = (
            f"STREAM_STALLED: consumer made no progress for "
            f"{stalled_for:.2f}s with the stream buffer at its "
            f"{self.max_pending}-byte cap; stream aborted, buffer "
            f"and reservation freed"
        )
        q.error_class = ErrorClass.CANCELLED.value
        q.request_cancel(reason="stream_stalled")
        self.aborted = "STREAM_STALLED"
        self._clear_locked()
        self._wake_locked()
        raise StreamStalled(q.query_id)

    def rollback(self, to_pos: int) -> None:
        """Abandoned-attempt cleanup (service._drain): truncate parts
        the failed attempt produced beyond `to_pos` - except the
        already-delivered prefix, which cannot be un-sent and is
        instead verified against the retry's output (put() replay
        mode)."""
        with self._cv:
            if self.aborted is not None or self.finished:
                return
            keep = max(int(to_pos), self.consumed)
            if len(self.parts) > keep:
                freed = sum(self._nbytes[keep:])
                del self.parts[keep:]
                del self._nbytes[keep:]
                self.pending_bytes -= freed
                self._account_locked()
            self._pos = min(int(to_pos), len(self.parts))
            self._wake_locked()

    def finish(self) -> None:
        with self._cv:
            self.finished = True
            self._wake_locked()

    def abort(self, reason: str) -> None:
        """Terminal non-DONE exit: free the ring (retention keeps
        nothing for a query that has no result to collect)."""
        with self._cv:
            if self.finished:
                return
            if self.aborted is None:
                self.aborted = str(reason)
            self._clear_locked()
            self._wake_locked()

    def _clear_locked(self) -> None:
        self.parts.clear()
        self._nbytes.clear()
        self._pos = 0
        self.consumed = 0
        self.pending_bytes = 0
        self._account_locked()

    # -- consumer side --------------------------------------------------
    def attach(self) -> None:
        """A FETCH opened against this stream. Counts as consumer
        progress (a reconnecting client must not inherit the previous
        connection's stall clock) and arms both backpressure and the
        admission hold for already-pending bytes."""
        with self._cv:
            self.consumers_seen += 1
            self._last_progress = time.monotonic()
            self._account_locked()
            self._wake_locked()

    def next_ready(self, i: int, timeout: float):
        """Wait up to `timeout` for part `i`. Returns one of
        ('part', rb) | ('finished', None) | ('aborted', reason) |
        ('timeout', None). Parts win over terminal markers so a
        finished stream drains completely before the terminator."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                if i < len(self.parts):
                    return "part", self.parts[i]
                if self.aborted is not None:
                    return "aborted", self.aborted
                if self.finished:
                    return "finished", None
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return "timeout", None
                self._cv.wait(min(rem, 0.05))

    def mark_consumed(self, i: int) -> None:
        """Part `i` is committed for delivery (called BEFORE the send:
        a part handed to the wire can never be truncated by a rollback,
        so the replay-verify boundary is conservative). Releases its
        pending bytes and resets the stall clock."""
        with self._cv:
            if i + 1 > self.consumed:
                freed = sum(self._nbytes[self.consumed:i + 1])
                self.consumed = i + 1
                self.pending_bytes -= freed
                self._account_locked()
            self._last_progress = time.monotonic()
            self._wake_locked()

    def total_parts(self) -> int:
        with self._cv:
            return len(self.parts)


def _batches_equal(a, b) -> bool:
    try:
        return bool(a.equals(b))
    except Exception:  # noqa: BLE001 - incomparable means divergent
        return False
