"""Admission control: who runs next, and whether they fit.

The reference engine never needed this - Spark's scheduler + the
executor's task slots gate concurrency, and MemoryManagerConfig gates
bytes (exec.rs:79-94). A standalone serving tier must grow both knobs:

  * concurrency: at most `max_concurrency` queries RUNNING at once
    (one process shares one device; extra threads buy host/device
    overlap, not compute - runtime/dispatch.task_threads rationale);
  * memory: a query is admitted only when its estimated device bytes
    fit the DeviceMemoryTracker's CURRENT headroom minus what already-
    admitted queries reserved. An over-headroom query WAITS instead of
    OOMing the device; when the device is idle it runs alone (a query
    larger than the whole budget must still be servable - the spill
    ladder, not admission, handles its overflow).

Ordering is strict: priority descending; WITHIN a priority class,
earliest deadline first (EDF - the query with the least slack runs
first, ROADMAP "deadline-aware scheduling"), with deadline-less
queries after deadlined ones, FIFO among themselves (submission
sequence). The head of the queue blocks lower entries even when they
would fit - bypass ("backfill") would starve big queries under a
stream of small ones, and predictable ordering is worth more to a
serving tier than peak packing.

Shedding: a query whose deadline has ALREADY passed at submit time
cannot be met no matter what - the service refuses it up front
(TIMED_OUT with a shed marker) instead of letting it occupy queue
depth only to die in the deadline sweep. The service additionally
sheds at ADMISSION time on PREDICTED unmeetability: when the
runtime-history store (obs/history.py) has >= 3 samples for the
query's fingerprint and now + p50 estimate already overshoots the
deadline, running it would only burn device time to miss anyway
(`shed_predicted` counter; service/service.py drives the check).

Backpressure is explicit: a full queue rejects at submit time
(REJECTED_OVERLOADED) instead of building an unbounded pileup.

Tenancy (docs/SERVICE.md "Tenancy"): with a tenant config - or the
moment a second tenant shows up - the controller switches to
weighted-fair mode: one heap PER TENANT (each keeping the exact
priority/EDF/FIFO entry order above), deficit-round-robin across
tenants WITHIN the top priority class, so no tenant's backlog can
shadow another's, and per-tenant caps on queued entries, RUNNING
slots, and reserved bytes. An over-budget submit is rejected with the
`REJECTED_TENANT_BUDGET` marker (TRANSIENT - the tenant's own
in-flight work draining frees the budget); a tenant at its RUNNING or
byte cap is simply invisible to the scheduler until it drains, so its
backlog never blocks other tenants. With no config and a single
tenant (the default), every path below short-circuits to the exact
single-heap behavior documented above - zero-config ordering is
byte-identical to the pre-tenancy controller.
"""

from __future__ import annotations

import heapq
import itertools
import math
import threading
from typing import Dict, List, Optional, Tuple

from blaze_tpu.obs.contention import TimedLock
from blaze_tpu.service.query import Query


def estimate_plan_device_bytes(op, partition: Optional[int] = None) -> int:
    """Admission cost estimate: bytes the plan plausibly materializes
    on device. Leaf-driven heuristic - parquet scans count file-range
    bytes, memory scans count resident buffer bytes; interior nodes
    take the sum of their children (joins/aggregates hold their inputs
    resident in the worst case). `partition` narrows leaves to ONE
    partition's inputs - a wire TaskDefinition executes a single
    partition of its stage, so costing the whole scan would serialize
    sibling tasks behind each other. Deliberately coarse: admission
    needs a gate, not a cost model, and callers can override per
    query."""
    from blaze_tpu.ops.memory_scan import MemoryScanExec
    from blaze_tpu.ops.parquet_scan import ParquetScanExec

    if isinstance(op, ParquetScanExec):
        import os

        groups = op.file_groups
        if partition is not None and partition < len(groups):
            groups = [groups[partition]]
        total = 0
        for group in groups:
            for fr in group:
                if fr.length:
                    total += fr.length
                else:
                    try:
                        total += os.path.getsize(fr.path)
                    except OSError:
                        pass
        return total
    if isinstance(op, MemoryScanExec):
        from blaze_tpu.runtime.memory import batch_device_bytes

        parts = op.partitions
        if partition is not None and partition < len(parts):
            parts = [parts[partition]]
        return sum(
            batch_device_bytes(cb) for part in parts for cb in part
        )
    return sum(
        estimate_plan_device_bytes(c, partition) for c in op.children
    )


class TenantBudgets:
    """Per-tenant budget config: caps on queued entries, RUNNING
    slots, and reserved bytes, plus the deficit-round-robin weight.
    Config shape (the `serve --tenant-config` JSON):

        {"acme": {"max_queued": 8, "max_running": 1,
                  "max_reserved_bytes": 1 << 28, "weight": 2.0},
         "*":    {"max_queued": 16}}

    `"*"` holds defaults for tenants not named; a named entry's keys
    override the defaults key-by-key. Missing caps are unlimited;
    missing weight is 1.0."""

    _CAPS = ("max_queued", "max_running", "max_reserved_bytes")

    def __init__(self, config: Optional[dict] = None):
        cfg = dict(config or {})
        self._default = dict(cfg.pop("*", {}))
        self._tenants = {str(k): dict(v) for k, v in cfg.items()}
        self.configured = bool(self._default or self._tenants)

    def for_tenant(self, tenant: str) -> dict:
        out = dict(self._default)
        out.update(self._tenants.get(tenant, {}))
        return out

    def cap(self, tenant: str, key: str) -> Optional[int]:
        v = self.for_tenant(tenant).get(key)
        return int(v) if v is not None else None

    def weight(self, tenant: str) -> float:
        try:
            w = float(self.for_tenant(tenant).get("weight", 1.0))
        except (TypeError, ValueError):
            w = 1.0
        return w if w > 0 else 1.0


class AdmissionController:
    """Bounded priority queue + headroom gate for the QueryService."""

    def __init__(
        self,
        device_tracker=None,
        max_concurrency: int = 2,
        max_queue_depth: int = 64,
        tenant_config: Optional[dict] = None,
    ):
        from blaze_tpu.runtime.memory import get_device_tracker

        self._tracker = device_tracker or get_device_tracker()
        self.max_concurrency = max(1, int(max_concurrency))
        self.max_queue_depth = max(1, int(max_queue_depth))
        self._lock = TimedLock("admission")
        self._seq = itertools.count()
        # heap entries: (-priority, deadline, seq, query) -
        # max-priority first; within a priority class earliest
        # deadline first (EDF; no deadline sorts last as +inf), FIFO
        # via the submission sequence among equals
        self._heap: List[Tuple[int, float, int, Query]] = []
        # reservations for admitted-but-not-yet-tracked device bytes
        self._reserved: Dict[str, int] = {}
        self.counters = {
            "submitted": 0,
            "admitted": 0,
            "rejected_overloaded": 0,
            "rejected_tenant_budget": 0,
            "shed_deadline": 0,
            "shed_predicted": 0,
            "headroom_waits": 0,
            "tenant_budget_waits": 0,
            "degraded_released": 0,
        }
        # -- tenancy ----------------------------------------------------
        self.budgets = TenantBudgets(tenant_config)
        # weighted-fair mode: ON from construction when a config
        # exists, flipped (sticky) the moment a SECOND tenant appears.
        # While off, offer/next_admissible run the exact single-heap
        # code path above - zero-config behavior is byte-identical.
        self._fair = self.budgets.configured
        self._tenant_heaps: Dict[str, List[Tuple[int, float, int, Query]]] = {}
        # deficit-round-robin state: tenants in first-seen order, a
        # cyclic pointer, and per-tenant deficit credit (quantum = the
        # tenant's weight per visit, cost = 1 per admitted query; an
        # emptied tenant's deficit resets so idle tenants cannot bank
        # credit - classic DRR)
        self._drr_order: List[str] = []
        self._drr_next = 0
        self._drr_deficit: Dict[str, float] = {}
        # per-tenant live accounting (all modes - O(1) dict bumps):
        # queued entries, RUNNING slots, reserved bytes, and the
        # lifetime counters STATS/metrics export
        self._t_queued: Dict[str, int] = {}
        self._t_running: Dict[str, int] = {}
        self._t_reserved: Dict[str, int] = {}
        self._t_counters: Dict[str, Dict[str, int]] = {}

    # -- tenancy helpers -----------------------------------------------
    def _t_count(self, tenant: str, key: str, n: int = 1) -> None:
        c = self._t_counters.get(tenant)
        if c is None:
            c = self._t_counters[tenant] = {
                "submitted": 0, "admitted": 0,
                "rejected_budget": 0,
            }
        c[key] += n

    def _note_tenant(self, tenant: str) -> None:
        """First-seen bookkeeping; flips weighted-fair mode when a
        second tenant appears (migrating the single heap into
        per-tenant heaps - entry tuples keep their order)."""
        if tenant not in self._drr_deficit:
            self._drr_deficit[tenant] = 0.0
            self._drr_order.append(tenant)
        if not self._fair and len(self._drr_order) > 1:
            self._fair = True
            for e in self._heap:
                q = e[-1]
                if q.done:
                    # migration IS this entry's prune: balance the
                    # per-tenant queued gauge like _prune would
                    self._t_queued[q.tenant] = max(
                        0, self._t_queued.get(q.tenant, 0) - 1
                    )
                    continue
                heapq.heappush(
                    self._tenant_heaps.setdefault(q.tenant, []), e
                )
            self._heap = []

    def _live_depth(self) -> int:
        heaps = ([self._heap] if not self._fair
                 else self._tenant_heaps.values())
        return sum(
            1 for h in heaps for e in h if not e[-1].done
        )

    # ------------------------------------------------------------------
    def offer(self, q: Query) -> str:
        """Enqueue; returns "ok", "overloaded" (queue full - the
        caller marks the query REJECTED_OVERLOADED, explicit
        backpressure), or "tenant_budget" (the tenant is over its
        queued-entries cap - the caller rejects with the
        REJECTED_TENANT_BUDGET marker)."""
        with self._lock:
            self.counters["submitted"] += 1
            tenant = q.tenant
            self._note_tenant(tenant)
            self._t_count(tenant, "submitted")
            mq = self.budgets.cap(tenant, "max_queued")
            if mq is not None and \
                    self._t_queued.get(tenant, 0) >= mq:
                self.counters["rejected_tenant_budget"] += 1
                self._t_count(tenant, "rejected_budget")
                return "tenant_budget"
            if self._live_depth() >= self.max_queue_depth:
                self.counters["rejected_overloaded"] += 1
                return "overloaded"
            deadline = (
                q.deadline_at if q.deadline_at is not None else math.inf
            )
            entry = (-q.priority, deadline, next(self._seq), q)
            if self._fair:
                heapq.heappush(
                    self._tenant_heaps.setdefault(tenant, []), entry
                )
            else:
                heapq.heappush(self._heap, entry)
            self._t_queued[tenant] = (
                self._t_queued.get(tenant, 0) + 1
            )
            return "ok"

    def note_shed(self) -> None:
        """The service shed a query at admission (deadline already
        unmeetable); recorded here so stats() tells the whole story."""
        with self._lock:
            self.counters["submitted"] += 1
            self.counters["shed_deadline"] += 1

    def note_shed_predicted(self) -> None:
        """Predicted-unmeetability shed (ROADMAP deadline item, second
        half): queue-wait already spent + the fingerprint's p50 runtime
        estimate exceed the query's remaining slack. Distinct counter -
        prediction sheds are tunable (history quality), hard-deadline
        sheds are not. The query was already counted `submitted` at
        enqueue; `admitted` is counted only when the ADMITTED
        transition lands (note_admitted), so a shed never touches it
        and completion-rate math (done/admitted) stays honest."""
        with self._lock:
            self.counters["shed_predicted"] += 1

    def queue_depth(self) -> int:
        with self._lock:
            return self._live_depth()

    def running_count(self) -> int:
        with self._lock:
            return len(self._reserved)

    # ------------------------------------------------------------------
    def _prune(self, heap: List[Tuple[int, float, int, Query]]) -> None:
        """Drop already-terminal heads (cancelled/timed out while
        queued), keeping the per-tenant queued gauge honest."""
        while heap and heap[0][-1].done:
            e = heapq.heappop(heap)
            t = e[-1].tenant
            self._t_queued[t] = max(
                0, self._t_queued.get(t, 0) - 1
            )

    def _admit(self, heap: List[Tuple[int, float, int, Query]]
               ) -> Optional[Query]:
        """Headroom-gate + pop the head of `heap`. Strict: a head that
        does not fit while others hold the device WAITS (no backfill -
        see module docstring); an idle device admits anything."""
        q = heap[0][-1]
        est = q.estimated_bytes or 0
        headroom = self._tracker.headroom() - sum(
            self._reserved.values()
        )
        if self._reserved and est > headroom:
            self.counters["headroom_waits"] += 1
            return None
        heapq.heappop(heap)
        t = q.tenant
        self._t_queued[t] = max(0, self._t_queued.get(t, 0) - 1)
        self._reserved[q.query_id] = est
        self._t_running[t] = self._t_running.get(t, 0) + 1
        self._t_reserved[t] = self._t_reserved.get(t, 0) + est
        self._t_count(t, "admitted")
        return q

    def _tenant_eligible(self, tenant: str) -> bool:
        """A tenant at its RUNNING or reserved-bytes cap is invisible
        to the scheduler until its own work drains - its backlog must
        never block other tenants (isolation beats strict global
        head-of-queue across tenants; WITHIN a tenant the policy is
        unchanged)."""
        mr = self.budgets.cap(tenant, "max_running")
        if mr is not None and \
                self._t_running.get(tenant, 0) >= mr:
            self.counters["tenant_budget_waits"] += 1
            return False
        mb = self.budgets.cap(tenant, "max_reserved_bytes")
        if mb is not None:
            head = self._tenant_heaps[tenant][0][-1]
            est = head.estimated_bytes or 0
            if self._t_reserved.get(tenant, 0) + est > mb:
                self.counters["tenant_budget_waits"] += 1
                return False
        return True

    def next_admissible(self) -> Optional[Query]:
        """Pop the query that may start now, or None. Strict head-of-
        queue policy (see module docstring); in weighted-fair mode the
        "head" is chosen by deficit-round-robin across tenants within
        the top priority class among budget-eligible tenants."""
        with self._lock:
            if not self._fair:
                self._prune(self._heap)
                if not self._heap:
                    return None
                if len(self._reserved) >= self.max_concurrency:
                    return None
                return self._admit(self._heap)
            # weighted-fair mode -------------------------------------
            heads: Dict[str, Tuple[int, float, int, Query]] = {}
            for t, h in self._tenant_heaps.items():
                self._prune(h)
                if h:
                    heads[t] = h[0]
                else:
                    # classic DRR: an emptied tenant banks no credit
                    self._drr_deficit[t] = 0.0
            if not heads:
                return None
            if len(self._reserved) >= self.max_concurrency:
                return None
            eligible = {
                t for t in heads if self._tenant_eligible(t)
            }
            if not eligible:
                return None
            # top priority class among ELIGIBLE tenants only: a
            # capped tenant's high-priority backlog must not shadow
            # other tenants' admissible work
            top = min(heads[t][0] for t in eligible)
            cands = [t for t in eligible if heads[t][0] == top]
            if len(cands) == 1:
                return self._admit(self._tenant_heaps[cands[0]])
            # deficit round robin: walk the first-seen tenant order
            # cyclically from the saved pointer. Arriving at a
            # candidate earns its weight in credit (once per arrival:
            # mid-burst revisits - deficit still >= 1 - do not
            # re-credit), one credit unit buys one admission, and the
            # pointer HOLDS on the served tenant while its credit
            # lasts - weighted bursts, classic DRR.
            order = self._drr_order
            n = len(order)
            max_visits = n * (2 + int(math.ceil(
                1.0 / min(self.budgets.weight(t) for t in cands)
            )))
            for _ in range(max_visits):
                t = order[self._drr_next % n]
                if t not in cands:
                    self._drr_next = (self._drr_next + 1) % n
                    continue
                if self._drr_deficit[t] < 1.0:
                    self._drr_deficit[t] += self.budgets.weight(t)
                if self._drr_deficit[t] >= 1.0:
                    got = self._admit(self._tenant_heaps[t])
                    if got is None:
                        # headroom wait: the selected head blocks
                        # (strict policy) - pointer and credit hold,
                        # so the retry serves the SAME head
                        return None
                    self._drr_deficit[t] -= 1.0
                    if self._drr_deficit[t] < 1.0:
                        # credit spent: the next arrival re-credits
                        self._drr_next = (self._drr_next + 1) % n
                    return got
                self._drr_next = (self._drr_next + 1) % n
            return None  # unreachable with positive weights

    def note_admitted(self) -> None:
        """Counted by the SERVICE once the ADMITTED transition lands -
        not at the next_admissible pop - so predicted-unmeetability
        sheds and admit-races never touch it and the counter stays
        monotonic (it is exported with Prometheus TYPE counter; a
        decrement would read as a counter reset and corrupt rate())."""
        with self._lock:
            self.counters["admitted"] += 1

    def release(self, q: Query) -> None:
        with self._lock:
            cur = self._reserved.pop(q.query_id, None)
            if cur is None:
                return
            t = q.tenant
            self._t_running[t] = max(
                0, self._t_running.get(t, 0) - 1
            )
            self._t_reserved[t] = max(
                0, self._t_reserved.get(t, 0) - cur
            )

    def release_bytes(self, q: Query, share_of: int = 1) -> None:
        """Degradation-aware admission (ROADMAP): a partition that fell
        back to the HOST engine holds device bytes for nothing - free
        its share so queued device work admits against the released
        headroom. Degradation is per-PARTITION: with `share_of` = the
        query's partition count, each degraded partition releases only
        ceil(est / share_of) - sound because the estimator SUMS leaf
        inputs across partitions - while the query's OTHER partitions
        still execute on the device against the rest of the
        reservation (wire tasks are single-partition, so the whole
        reservation frees at once). `degraded_released` counts release
        events: one per degraded partition that freed bytes. Idempotent
        at zero; the final release() still clears the slot."""
        with self._lock:
            cur = self._reserved.get(q.query_id)
            if not cur:
                return
            est = q.estimated_bytes or cur
            share = cur if share_of <= 1 else min(
                cur, -(-est // share_of)  # ceil: n shares fully drain
            )
            self._reserved[q.query_id] = cur - share
            self._t_reserved[q.tenant] = max(
                0, self._t_reserved.get(q.tenant, 0) - share
            )
            self.counters["degraded_released"] += 1

    def adjust_reservation(self, q: Query, delta: int) -> None:
        """Stream-buffer accounting (service/stream.py): pending
        (produced-but-undelivered) result bytes of an actively-FETCHed
        query count against its reservation, so a consumer slower than
        the producer gates new admissions exactly like the device
        bytes it mirrors. No-op once the query released its slot -
        post-terminal retention is bounded by the ring's own byte cap,
        not by admission."""
        with self._lock:
            cur = self._reserved.get(q.query_id)
            if cur is None:
                return
            nxt = max(0, cur + int(delta))
            self._reserved[q.query_id] = nxt
            self._t_reserved[q.tenant] = max(
                0, self._t_reserved.get(q.tenant, 0) + (nxt - cur)
            )

    def stats(self) -> dict:
        with self._lock:
            return {
                **self.counters,
                "queued": self._live_depth(),
                "running": len(self._reserved),
                "reserved_bytes": sum(self._reserved.values()),
                "headroom": self._tracker.headroom(),
                "fair": self._fair,
            }

    def tenant_stats(self) -> Dict[str, dict]:
        """{tenant: {queued, running, reserved_bytes, submitted,
        admitted, rejected_budget, weight}} - the STATS `tenants`
        section and the blaze_tenant_* gauge source. Empty until a
        tenant submits."""
        with self._lock:
            out: Dict[str, dict] = {}
            for t in self._drr_order:
                c = self._t_counters.get(t, {})
                out[t] = {
                    "queued": self._t_queued.get(t, 0),
                    "running": self._t_running.get(t, 0),
                    "reserved_bytes": self._t_reserved.get(t, 0),
                    "submitted": c.get("submitted", 0),
                    "admitted": c.get("admitted", 0),
                    "rejected_budget": c.get("rejected_budget", 0),
                    "weight": self.budgets.weight(t),
                }
            return out
