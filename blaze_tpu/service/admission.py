"""Admission control: who runs next, and whether they fit.

The reference engine never needed this - Spark's scheduler + the
executor's task slots gate concurrency, and MemoryManagerConfig gates
bytes (exec.rs:79-94). A standalone serving tier must grow both knobs:

  * concurrency: at most `max_concurrency` queries RUNNING at once
    (one process shares one device; extra threads buy host/device
    overlap, not compute - runtime/dispatch.task_threads rationale);
  * memory: a query is admitted only when its estimated device bytes
    fit the DeviceMemoryTracker's CURRENT headroom minus what already-
    admitted queries reserved. An over-headroom query WAITS instead of
    OOMing the device; when the device is idle it runs alone (a query
    larger than the whole budget must still be servable - the spill
    ladder, not admission, handles its overflow).

Ordering is strict: priority descending; WITHIN a priority class,
earliest deadline first (EDF - the query with the least slack runs
first, ROADMAP "deadline-aware scheduling"), with deadline-less
queries after deadlined ones, FIFO among themselves (submission
sequence). The head of the queue blocks lower entries even when they
would fit - bypass ("backfill") would starve big queries under a
stream of small ones, and predictable ordering is worth more to a
serving tier than peak packing.

Shedding: a query whose deadline has ALREADY passed at submit time
cannot be met no matter what - the service refuses it up front
(TIMED_OUT with a shed marker) instead of letting it occupy queue
depth only to die in the deadline sweep. The service additionally
sheds at ADMISSION time on PREDICTED unmeetability: when the
runtime-history store (obs/history.py) has >= 3 samples for the
query's fingerprint and now + p50 estimate already overshoots the
deadline, running it would only burn device time to miss anyway
(`shed_predicted` counter; service/service.py drives the check).

Backpressure is explicit: a full queue rejects at submit time
(REJECTED_OVERLOADED) instead of building an unbounded pileup.
"""

from __future__ import annotations

import heapq
import itertools
import math
import threading
from typing import Dict, List, Optional, Tuple

from blaze_tpu.obs.contention import TimedLock
from blaze_tpu.service.query import Query


def estimate_plan_device_bytes(op, partition: Optional[int] = None) -> int:
    """Admission cost estimate: bytes the plan plausibly materializes
    on device. Leaf-driven heuristic - parquet scans count file-range
    bytes, memory scans count resident buffer bytes; interior nodes
    take the sum of their children (joins/aggregates hold their inputs
    resident in the worst case). `partition` narrows leaves to ONE
    partition's inputs - a wire TaskDefinition executes a single
    partition of its stage, so costing the whole scan would serialize
    sibling tasks behind each other. Deliberately coarse: admission
    needs a gate, not a cost model, and callers can override per
    query."""
    from blaze_tpu.ops.memory_scan import MemoryScanExec
    from blaze_tpu.ops.parquet_scan import ParquetScanExec

    if isinstance(op, ParquetScanExec):
        import os

        groups = op.file_groups
        if partition is not None and partition < len(groups):
            groups = [groups[partition]]
        total = 0
        for group in groups:
            for fr in group:
                if fr.length:
                    total += fr.length
                else:
                    try:
                        total += os.path.getsize(fr.path)
                    except OSError:
                        pass
        return total
    if isinstance(op, MemoryScanExec):
        from blaze_tpu.runtime.memory import batch_device_bytes

        parts = op.partitions
        if partition is not None and partition < len(parts):
            parts = [parts[partition]]
        return sum(
            batch_device_bytes(cb) for part in parts for cb in part
        )
    return sum(
        estimate_plan_device_bytes(c, partition) for c in op.children
    )


class AdmissionController:
    """Bounded priority queue + headroom gate for the QueryService."""

    def __init__(
        self,
        device_tracker=None,
        max_concurrency: int = 2,
        max_queue_depth: int = 64,
    ):
        from blaze_tpu.runtime.memory import get_device_tracker

        self._tracker = device_tracker or get_device_tracker()
        self.max_concurrency = max(1, int(max_concurrency))
        self.max_queue_depth = max(1, int(max_queue_depth))
        self._lock = TimedLock("admission")
        self._seq = itertools.count()
        # heap entries: (-priority, deadline, seq, query) -
        # max-priority first; within a priority class earliest
        # deadline first (EDF; no deadline sorts last as +inf), FIFO
        # via the submission sequence among equals
        self._heap: List[Tuple[int, float, int, Query]] = []
        # reservations for admitted-but-not-yet-tracked device bytes
        self._reserved: Dict[str, int] = {}
        self.counters = {
            "submitted": 0,
            "admitted": 0,
            "rejected_overloaded": 0,
            "shed_deadline": 0,
            "shed_predicted": 0,
            "headroom_waits": 0,
            "degraded_released": 0,
        }

    # ------------------------------------------------------------------
    def offer(self, q: Query) -> bool:
        """Enqueue; False = queue full (caller marks the query
        REJECTED_OVERLOADED - explicit backpressure)."""
        with self._lock:
            self.counters["submitted"] += 1
            live = [e for e in self._heap if not e[-1].done]
            if len(live) >= self.max_queue_depth:
                self.counters["rejected_overloaded"] += 1
                return False
            deadline = (
                q.deadline_at if q.deadline_at is not None else math.inf
            )
            heapq.heappush(
                self._heap,
                (-q.priority, deadline, next(self._seq), q),
            )
            return True

    def note_shed(self) -> None:
        """The service shed a query at admission (deadline already
        unmeetable); recorded here so stats() tells the whole story."""
        with self._lock:
            self.counters["submitted"] += 1
            self.counters["shed_deadline"] += 1

    def note_shed_predicted(self) -> None:
        """Predicted-unmeetability shed (ROADMAP deadline item, second
        half): queue-wait already spent + the fingerprint's p50 runtime
        estimate exceed the query's remaining slack. Distinct counter -
        prediction sheds are tunable (history quality), hard-deadline
        sheds are not. The query was already counted `submitted` at
        enqueue; `admitted` is counted only when the ADMITTED
        transition lands (note_admitted), so a shed never touches it
        and completion-rate math (done/admitted) stays honest."""
        with self._lock:
            self.counters["shed_predicted"] += 1

    def queue_depth(self) -> int:
        with self._lock:
            return sum(1 for e in self._heap if not e[-1].done)

    def running_count(self) -> int:
        with self._lock:
            return len(self._reserved)

    # ------------------------------------------------------------------
    def next_admissible(self) -> Optional[Query]:
        """Pop the query that may start now, or None. Strict head-of-
        queue policy (see module docstring); already-terminal entries
        (cancelled/timed out while queued) are dropped on the way."""
        with self._lock:
            while self._heap:
                q = self._heap[0][-1]
                if q.done:  # cancelled / timed out while queued
                    heapq.heappop(self._heap)
                    continue
                if len(self._reserved) >= self.max_concurrency:
                    return None
                est = q.estimated_bytes or 0
                headroom = self._tracker.headroom() - sum(
                    self._reserved.values()
                )
                if self._reserved and est > headroom:
                    # over headroom while others hold the device:
                    # wait (queue, don't OOM). An idle device admits
                    # anything - the spill ladder owns true overflow.
                    self.counters["headroom_waits"] += 1
                    return None
                heapq.heappop(self._heap)
                self._reserved[q.query_id] = est
                return q
            return None

    def note_admitted(self) -> None:
        """Counted by the SERVICE once the ADMITTED transition lands -
        not at the next_admissible pop - so predicted-unmeetability
        sheds and admit-races never touch it and the counter stays
        monotonic (it is exported with Prometheus TYPE counter; a
        decrement would read as a counter reset and corrupt rate())."""
        with self._lock:
            self.counters["admitted"] += 1

    def release(self, q: Query) -> None:
        with self._lock:
            self._reserved.pop(q.query_id, None)

    def release_bytes(self, q: Query, share_of: int = 1) -> None:
        """Degradation-aware admission (ROADMAP): a partition that fell
        back to the HOST engine holds device bytes for nothing - free
        its share so queued device work admits against the released
        headroom. Degradation is per-PARTITION: with `share_of` = the
        query's partition count, each degraded partition releases only
        ceil(est / share_of) - sound because the estimator SUMS leaf
        inputs across partitions - while the query's OTHER partitions
        still execute on the device against the rest of the
        reservation (wire tasks are single-partition, so the whole
        reservation frees at once). `degraded_released` counts release
        events: one per degraded partition that freed bytes. Idempotent
        at zero; the final release() still clears the slot."""
        with self._lock:
            cur = self._reserved.get(q.query_id)
            if not cur:
                return
            est = q.estimated_bytes or cur
            share = cur if share_of <= 1 else min(
                cur, -(-est // share_of)  # ceil: n shares fully drain
            )
            self._reserved[q.query_id] = cur - share
            self.counters["degraded_released"] += 1

    def adjust_reservation(self, q: Query, delta: int) -> None:
        """Stream-buffer accounting (service/stream.py): pending
        (produced-but-undelivered) result bytes of an actively-FETCHed
        query count against its reservation, so a consumer slower than
        the producer gates new admissions exactly like the device
        bytes it mirrors. No-op once the query released its slot -
        post-terminal retention is bounded by the ring's own byte cap,
        not by admission."""
        with self._lock:
            cur = self._reserved.get(q.query_id)
            if cur is None:
                return
            self._reserved[q.query_id] = max(0, cur + int(delta))

    def stats(self) -> dict:
        with self._lock:
            return {
                **self.counters,
                "queued": sum(1 for e in self._heap if not e[-1].done),
                "running": len(self._reserved),
                "reserved_bytes": sum(self._reserved.values()),
                "headroom": self._tracker.headroom(),
            }
