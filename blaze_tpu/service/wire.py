"""Service wire protocol: multi-query serving over the gateway socket.

The legacy gateway connection (runtime/gateway.py) is one-shot: one
TaskDefinition in, one batch stream out. A serving tier needs verbs -
submit several queries over one connection, poll them, stream results,
cancel mid-flight. The framing extends the gateway's, so one listener
serves both: a connection whose FIRST u64 header has bit 61
(_FLAG_SERVICE) set switches to this protocol; anything else is a
legacy single-task connection.

Service framing (all integers LE):

  hello:    u64 header with _FLAG_SERVICE set (rest of the bits 0)
  verb:     u8   SUBMIT=1 POLL=2 FETCH=3 CANCEL=4 REPORT=5 STATS=6
                 METRICS=7 MEMBER=8 PROFILE=9
  SUBMIT:   u32 meta_len | meta JSON | u64 blob_header | [u32 mlen |
            manifest JSON] | blob
            blob_header reuses the legacy bits: bit 63 = reference wire
            format, bit 62 = resource manifest present, low bits = len.
            meta: {priority, deadline_s, estimated_bytes, use_cache}
            -> JSON frame {query_id, state, ...}
  POLL:     u32 id_len | id   -> JSON frame (Query.status())
  FETCH:    u32 id_len | id | u32 timeout_ms
            -> segmented-IPC parts (u64 len | zstd Arrow IPC) as the
               executor PRODUCES them - delivery starts while the
               query is still RUNNING - then u64 0 once the query is
               DONE and the ring drained (the shuffle/gateway wire
               format, io/ipc.py)
            -> u64 ERR | u32 len | "STATE: detail" utf8 when the
               query is terminal non-DONE before the first part;
               timeout_ms (0 = wait forever) bounds the wait for the
               FIRST part. After parts are on the wire a failure
               aborts the connection (never an in-band frame - it
               would desync the u64 framing); the client resumes by
               re-FETCHing and skipping delivered parts. Producer
               flow control + the slow-consumer stall budget:
               service/stream.py, docs/SERVICE.md.
               Bit 31 of timeout_ms opts INTO the shared-memory arena
               (zerocopy/arena.py): when the finalized result lives in
               an arena segment the server answers u64 ARENA | u32 len
               | handle JSON {path, offsets, lengths, lease, ...}
               INSTEAD of the part stream - the co-located client maps
               the segment and reads the identical frames, then
               RELEASEs the lease. A client that cannot map the path
               (remote, stale lease, chaos) re-FETCHes with the bit
               clear and gets plain bytes - degradation is always
               client-invisible. Without the bit, an arena-resident
               result still skips re-encoding: the frames go out as a
               scatter-gather buffer list, byte-identical to the
               per-batch encode path
  RELEASE:  u32 len | lease-id utf8 | u32 0 -> JSON frame
            {released: bool} - returns a shared-memory arena lease
            (zerocopy/arena.py); an unreleased lease is TTL-reaped
  CANCEL:   u32 id_len | id   -> JSON frame
  REPORT:   u32 id_len | id | u32 flags -> JSON frame {report: text,
            trace?: Chrome-trace-event JSON, trace_spans?: [span
            dicts]} - `trace` included only when flags bit 0 is set
            AND tracing was on for the query (obs/trace.py); it is
            the Perfetto-loadable document `python -m blaze_tpu
            trace` writes out. flags bit 1 requests the RAW span
            dicts (TraceRecorder.to_dicts) instead: the replica
            router grafts those into its own recorder
            (attach_subtree) to render ONE cross-hop trace
  STATS:    u32 0             -> JSON frame (service stats: admission
            headroom/queue depth, cache counters, degradation +
            quarantine counts, runtime-history summary)
  METRICS:  u32 0             -> JSON frame {metrics: text} -
            Prometheus text exposition from the process registry
            (obs/metrics.py), folding dispatch.*, admission, cache,
            and query-lifecycle counters
  MEMBER:   u32 len | JSON    -> JSON frame - fleet membership
            (router/membership.py): {"op": "join"|"leave", "host",
            "port", ...}. A freshly started serve replica JOINs the
            router it fronts for (re-announced periodically, so a
            restarted router re-learns the fleet); a drained replica
            LEAVEs when empty. Only the router tier is a membership
            authority - a serve instance answers with an in-band
            error.
  PROFILE:  u32 len | JSON    -> JSON frame - live contention +
            sampling profiler control (obs/contention.py,
            obs/sampler.py): {"op": "start"|"stop"|"snapshot"|
            "reset", "hz"?, "top"?, "collapsed"?}. `start` arms lock
            accounting and the stack sampler on the receiving
            process; `snapshot` answers {profile: {top, collapsed,
            samples, ...}, contention: {lock: {waits, wait_s,
            hold_s, ...}}} - so a live fleet is profiled without
            restart. Both tiers answer for their own process.
  JSON frame: u32 len | utf8 JSON

Session semantics: queries submitted on a connection belong to it;
when the connection drops (EOF, broken pipe) every non-terminal
session query is cancelled - a vanished client must not keep holding
device admission slots. Poll/cancel/fetch work from ANY connection
(query ids are global), so detached orchestration is still possible
via a second connection. A submit whose meta carries "detach": true
opts OUT of cancel-on-disconnect: the query survives connection loss
so a reconnecting client can re-attach by query_id (the deadline
sweep and result TTL still bound an abandoned detached query's
lifetime).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Iterator, List, Optional

from blaze_tpu.obs import trace as obs_trace
from blaze_tpu.testing import chaos

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")
_ERR = 0xFFFFFFFFFFFFFFFF
# arena-handle escape (zero-copy serve path): like _ERR it can never
# collide with a real part length (MAX_TASK_BYTES bounds frames)
_ARENA = 0xFFFFFFFFFFFFFFFE
# FETCH timeout_ms bit 31: client accepts a shared-memory arena handle
# in place of the byte stream (real timeouts are millisecond values,
# so the high bit is free)
_FETCH_ARENA = 1 << 31

VERB_SUBMIT = 1
VERB_POLL = 2
VERB_FETCH = 3
VERB_CANCEL = 4
VERB_REPORT = 5
VERB_STATS = 6
VERB_METRICS = 7
VERB_MEMBER = 8
VERB_PROFILE = 9
VERB_RELEASE = 10
VERB_MESH_EXCHANGE = 11

VERB_NAMES = {
    VERB_SUBMIT: "submit", VERB_POLL: "poll", VERB_FETCH: "fetch",
    VERB_CANCEL: "cancel", VERB_REPORT: "report", VERB_STATS: "stats",
    VERB_METRICS: "metrics", VERB_MEMBER: "member",
    VERB_PROFILE: "profile", VERB_RELEASE: "release",
    VERB_MESH_EXCHANGE: "mesh_exchange",
}

MAX_META_BYTES = 1 << 20
# response JSON frames may carry a whole trace document (REPORT);
# request-side frames keep the tighter MAX_META_BYTES bound
MAX_JSON_BYTES = 8 << 20
# MESH_EXCHANGE part frames carry whole stage boundaries (encoded
# Arrow-IPC segments); bound each frame the same way MAX_TASK_BYTES
# bounds a submitted plan
MAX_EXCHANGE_PART_BYTES = 256 << 20


class ServiceError(RuntimeError):
    """Error frame surfaced client-side; `.state` carries the query's
    terminal state name when the server included one."""

    def __init__(self, msg: str):
        super().__init__(msg)
        self.state = msg.split(":", 1)[0] if ":" in msg else ""


def _is_draining_rejection(resp: dict) -> bool:
    """True for the serving tier's DRAINING refusal (the 'DRAINING:'
    error prefix is the wire marker; service._reject_draining)."""
    return (
        resp.get("state") == "REJECTED_OVERLOADED"
        and str(resp.get("error", "")).startswith("DRAINING")
    )


def _is_tenant_budget_rejection(resp: dict) -> bool:
    """True for a tenant-budget refusal (the 'REJECTED_TENANT_BUDGET:'
    error prefix is the wire marker; service._reject_tenant_budget and
    the router's rate limiter both emit it). Mirrors the DRAINING
    contract: TRANSIENT, retried with the same bounded backoff, and a
    router spills it with zero breaker strikes."""
    return (
        resp.get("state") == "REJECTED_OVERLOADED"
        and str(resp.get("error", ""))
        .startswith("REJECTED_TENANT_BUDGET")
    )


# ---------------------------------------------------------------------------
# server side: ONE table-driven verb loop for both tiers
# ---------------------------------------------------------------------------
#
# The replica router re-implemented this loop's whole skeleton (verb
# decode, framing, the error-handling ladder, session teardown) with
# only the object behind the verbs changed. Factoring the skeleton
# around a small backend surface keeps the two protocol speakers
# byte-identical by construction - the same reason decode_submit_frame
# is shared. A backend provides:
#
#   submit(meta, task_bytes, is_ref, manifest_bytes) -> status dict
#   poll(qid) / cancel(qid) -> status dict
#   report_frame(qid, flags) -> REPORT response dict
#   stats() / metrics_frame() -> response dict
#   member_frame(payload) -> membership response dict (router tier)
#   fetch(sock, qid, timeout_ms)   owns its own framing (part stream)
#   abandon(qid)                   session teardown for one query


# POLL/CANCEL/REPORT/RELEASE share one frame shape: u32 id_len | id |
# u32 (RELEASE carries the arena lease id in the string slot)
_ID_VERBS = {
    VERB_POLL: lambda b, qid, flags: b.poll(qid),
    VERB_CANCEL: lambda b, qid, flags: b.cancel(qid),
    VERB_REPORT: lambda b, qid, flags: b.report_frame(qid, flags),
    VERB_RELEASE: lambda b, qid, flags: b.release_lease(qid),
}
# STATS/METRICS share the bare u32-reserved frame
_NOARG_VERBS = {
    VERB_STATS: lambda b: b.stats(),
    VERB_METRICS: lambda b: b.metrics_frame(),
}

# live connection-count gauges per tier, exported through the metrics
# collector surface (open/close only - never per verb)
_CONN_LOCK = threading.Lock()
_CONNECTIONS = {"service": 0, "router": 0}


def _conn_samples():
    with _CONN_LOCK:
        counts = dict(_CONNECTIONS)
    for tier, n in counts.items():
        yield ("blaze_connections", {"tier": tier}, n, "gauge")


def _observe_verb(tier: str, verb: int, t0: float, t_decoded: float,
                  t_dispatched: float, t_done: float) -> None:
    from blaze_tpu.obs.metrics import REGISTRY

    name = VERB_NAMES.get(verb, str(verb))
    REGISTRY.observe("blaze_verb_seconds", t_decoded - t0,
                     tier=tier, verb=name, segment="decode")
    REGISTRY.observe("blaze_verb_seconds", t_dispatched - t_decoded,
                     tier=tier, verb=name, segment="dispatch")
    REGISTRY.observe("blaze_verb_seconds", t_done - t_dispatched,
                     tier=tier, verb=name, segment="reply")


def serve_verb_connection(sock, backend) -> None:
    """Drive one service-protocol connection until EOF against any
    verb backend (the QueryService adapter below, or the router's).
    Owns the shared skeleton: verb dispatch, the error-handling ladder
    (protocol violations close, id misses report in-band),
    cancel-on-disconnect session teardown - and the per-verb wire
    latency surface: every verb round trip records decode / dispatch /
    reply segment histograms (blaze_verb_seconds{tier,verb,segment}),
    the FIRST verb byte records accept-to-first-byte queueing delay,
    and live connections gauge per tier."""
    from blaze_tpu.obs.metrics import REGISTRY
    from blaze_tpu.runtime.transport import _recv_exact

    tier = getattr(backend, "tier", "service")
    # role tag for the sampling profiler (obs/sampler.py): the
    # socketserver default Thread-N name would hide the wire tier
    t = threading.current_thread()
    if not t.name.startswith("blaze-verb"):
        t.name = f"blaze-verb-{tier}"
    with _CONN_LOCK:
        _CONNECTIONS[tier] = _CONNECTIONS.get(tier, 0) + 1
    REGISTRY.register_collector("wire_connections", _conn_samples)
    t_accept = time.perf_counter()
    first_verb = True
    session_qids: List[str] = []
    try:
        while True:
            try:
                verb = _recv_exact(sock, 1)[0]
            except (ConnectionError, OSError):
                return  # clean EOF / client gone
            t0 = time.perf_counter()
            if first_verb:
                # accept-to-first-byte: how long an accepted
                # connection queued before its first request reached
                # this handler (the c16 backlog measure)
                first_verb = False
                REGISTRY.observe("blaze_accept_first_byte_seconds",
                                 t0 - t_accept, tier=tier)
            try:
                if verb == VERB_SUBMIT:
                    meta, blob, is_ref, manifest_bytes = (
                        decode_submit_frame(sock)
                    )
                    t1 = time.perf_counter()
                    resp = backend.submit(
                        meta, blob, is_ref, manifest_bytes
                    )
                    t2 = time.perf_counter()
                    if not meta.get("detach") \
                            and "query_id" in resp:
                        # attached (default): cancel-on-disconnect
                        # session semantics; detached queries survive
                        # connection loss for re-attach
                        session_qids.append(resp["query_id"])
                    _send_json(sock, resp)
                elif verb == VERB_FETCH:
                    qid = _read_str(sock)
                    timeout_ms = _read_u32(sock)
                    t1 = time.perf_counter()
                    # fetch owns its own framing: the part stream is
                    # the dispatch segment, reply is the terminator
                    backend.fetch(sock, qid, timeout_ms)
                    t2 = time.perf_counter()
                elif verb in _ID_VERBS:
                    qid = _read_str(sock)
                    flags = _read_u32(sock)
                    t1 = time.perf_counter()
                    resp = _ID_VERBS[verb](backend, qid, flags)
                    t2 = time.perf_counter()
                    _send_json(sock, resp)
                elif verb == VERB_MEMBER:
                    payload = json.loads(_read_str(sock) or "{}")
                    t1 = time.perf_counter()
                    resp = backend.member_frame(payload)
                    t2 = time.perf_counter()
                    _send_json(sock, resp)
                elif verb == VERB_PROFILE:
                    payload = json.loads(_read_str(sock) or "{}")
                    t1 = time.perf_counter()
                    resp = backend.profile_frame(payload)
                    t2 = time.perf_counter()
                    _send_json(sock, resp)
                elif verb == VERB_MESH_EXCHANGE:
                    # fleet DCN plane: u32 JSON control frame + u64
                    # framed Arrow-IPC parts, zero-terminated. The
                    # parts are drained BEFORE dispatch no matter
                    # what the op is, so a handler error leaves the
                    # connection in sync (in-band error JSON, no
                    # part stream follows it)
                    payload = json.loads(_read_str(sock) or "{}")
                    parts: List[bytes] = []
                    while True:
                        (plen,) = _U64.unpack(
                            _recv_exact(sock, _U64.size)
                        )
                        if plen == 0:
                            break
                        if plen > MAX_EXCHANGE_PART_BYTES:
                            raise ValueError(
                                "oversized exchange part"
                            )
                        parts.append(_recv_exact(sock, plen))
                    t1 = time.perf_counter()
                    resp, out_parts = backend.mesh_exchange_frame(
                        payload, parts
                    )
                    t2 = time.perf_counter()
                    _send_json(sock, resp)
                    for p in out_parts:
                        sock.sendall(_U64.pack(len(p)) + p)
                    sock.sendall(_U64.pack(0))
                elif verb in _NOARG_VERBS:
                    _read_u32(sock)
                    t1 = time.perf_counter()
                    resp = _NOARG_VERBS[verb](backend)
                    t2 = time.perf_counter()
                    _send_json(sock, resp)
                else:
                    raise ValueError(f"unknown service verb {verb}")
                _observe_verb(tier, verb, t0, t1, t2,
                              time.perf_counter())
            except (ConnectionError, BrokenPipeError, OSError):
                return  # mid-verb disconnect: session cleanup below
            except ValueError as e:
                # protocol violation (oversized frame, unknown verb,
                # bad manifest): the connection may hold unread payload
                # bytes that would be misparsed as verbs - report
                # best-effort and CLOSE instead of desyncing
                try:
                    _send_json(
                        sock,
                        {"error": f"protocol error: {e}"[:65536],
                         "fatal": True},
                    )
                except OSError:
                    pass
                return
            except KeyError as e:
                # id lookups fail AFTER their frame is fully read -
                # the connection is still in sync, report in-band
                _send_json(sock, {"error": f"unknown query: {e}"})
            except Exception as e:  # noqa: BLE001 - reported in-band
                _send_json(
                    sock,
                    {"error": f"{type(e).__name__}: {e}"[:65536]},
                )
    finally:
        with _CONN_LOCK:
            _CONNECTIONS[tier] = max(0, _CONNECTIONS.get(tier, 1) - 1)
        # session teardown: a disconnected client's pending queries
        # must not keep occupying the queue or the device
        for qid in session_qids:
            try:
                backend.abandon(qid)
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass


# PROFILE verb ops, shared by both tier backends. `start` enables
# contention accounting + the stack sampler exactly once no matter how
# many starts arrive (the refcount must balance the eventual stop);
# `snapshot` serves both surfaces; `reset` zeroes them between
# measurement windows (the profile CLI's per-concurrency sections).
_PROFILE_LOCK = threading.Lock()
_PROFILE_ARMED = False


def verb_latency_summary() -> dict:
    """Per-verb wire latency from this process's registry, folded as
    {verb: {segment: {count, sum, mean}}} - the profile report's
    per-verb section (decode / dispatch / reply segments)."""
    from blaze_tpu.obs.metrics import REGISTRY

    out: dict = {}
    for labels, summ in REGISTRY.histogram_summaries(
        "blaze_verb_seconds"
    ):
        verb = labels.get("verb", "?")
        seg = labels.get("segment", "?")
        out.setdefault(verb, {})[seg] = summ
    return out


def handle_profile_frame(tier: str, payload: dict) -> dict:
    global _PROFILE_ARMED
    from blaze_tpu.obs import contention, sampler

    op = str(payload.get("op", "snapshot"))
    if op == "start":
        with _PROFILE_LOCK:
            if not _PROFILE_ARMED:
                contention.enable()
                _PROFILE_ARMED = True
        sampler.start(hz=float(payload.get("hz", 67.0)))
        return {"ok": True, "tier": tier, "profiling": True}
    if op == "stop":
        sampler.stop()
        with _PROFILE_LOCK:
            if _PROFILE_ARMED:
                contention.disable()
                _PROFILE_ARMED = False
        return {"ok": True, "tier": tier, "profiling": False}
    if op == "reset":
        contention.reset_stats()
        s = sampler.current()
        if s is not None:
            s.reset()
        return {"ok": True, "tier": tier}
    if op == "snapshot":
        return {
            "ok": True,
            "tier": tier,
            "profile": sampler.snapshot(
                top_n=int(payload.get("top", 20)),
                include_collapsed=bool(
                    payload.get("collapsed", True)
                ),
            ),
            "contention": contention.snapshot(),
            "top_locks": contention.top_locks(
                int(payload.get("top_locks", 3))
            ),
            "verbs": verb_latency_summary(),
        }
    raise ValueError(f"unknown profile op {op!r}")


class ServiceVerbBackend:
    """The QueryService behind the shared verb loop."""

    tier = "service"

    def __init__(self, service):
        self.service = service

    def submit(self, meta: dict, task_bytes: bytes, is_ref: bool,
               manifest_bytes: Optional[bytes]) -> dict:
        from blaze_tpu.runtime.gateway import _manifest_resources

        resources = {}
        if manifest_bytes is not None:
            resources = _manifest_resources(
                json.loads(manifest_bytes)
            )
        q = self.service.submit_task(
            task_bytes,
            is_ref=is_ref,
            resources=resources,
            priority=int(meta.get("priority", 0)),
            deadline_s=meta.get("deadline_s"),
            estimated_bytes=meta.get("estimated_bytes"),
            use_cache=bool(meta.get("use_cache", True)),
            # plan-cache key forwarded by the router (the affinity
            # digest it already computed over these exact bytes) so
            # the replica never re-hashes the blob
            plan_digest=meta.get("plan_digest"),
            tenant=str(meta.get("tenant") or "default"),
        )
        return q.status()

    def poll(self, qid: str) -> dict:
        return self.service.poll(qid)

    def cancel(self, qid: str) -> dict:
        return self.service.cancel(qid)

    def report_frame(self, qid: str, flags: int) -> dict:
        resp = {"report": self.service.report(qid)}
        # trace is OPT-IN (flags bit 0 = rendered Chrome doc, bit 1 =
        # raw span dicts for the router's cross-hop graft):
        # serializing a multi-MB span tree on every text-report poll
        # would tax exactly the hot path observability must not
        trace_of = getattr(self.service, "trace", None)
        if flags & 1 and trace_of is not None:
            doc = trace_of(qid)
            if doc is not None:
                resp["trace"] = doc
        spans_of = getattr(self.service, "trace_spans", None)
        if flags & 2 and spans_of is not None:
            spans = spans_of(qid)
            if spans is not None:
                resp["trace_spans"] = spans
        return resp

    def stats(self) -> dict:
        return self.service.stats()

    def metrics_frame(self) -> dict:
        from blaze_tpu.obs.metrics import REGISTRY

        t0 = time.perf_counter()
        text = REGISTRY.render_prometheus()
        # self-metric: scrape cost is itself observable (lands in the
        # NEXT exposition - the standard self-scrape semantics)
        REGISTRY.observe("blaze_scrape_seconds",
                         time.perf_counter() - t0, tier="service")
        return {"metrics": text}

    def member_frame(self, payload: dict) -> dict:
        # a single serve instance is not a membership authority - the
        # router tier (router/proxy.RouterVerbBackend) owns the fleet
        return {"error": "membership: this endpoint is not a router"}

    def mesh_exchange_frame(self, payload: dict, parts: list):
        """Fleet mesh DCN plane (fleet/exchange.py): a peer host's
        stage request - run a mesh stage over shipped partitions,
        answer with the stage's output segments."""
        from blaze_tpu.fleet.exchange import handle_mesh_exchange

        return handle_mesh_exchange(self.service, payload, parts)

    def profile_frame(self, payload: dict) -> dict:
        return handle_profile_frame(self.tier, payload)

    def abandon(self, qid: str) -> None:
        try:
            q = self.service.get(qid)
        except KeyError:
            return
        if not q.done:
            self.service.cancel(qid)

    def release_lease(self, lease: str) -> dict:
        arena = getattr(self.service, "arena", None)
        if arena is None:
            return {"released": False}
        try:
            return {"released": arena.release(int(lease))}
        except (TypeError, ValueError):
            return {"released": False}

    async def fetch_async(self, writer, qid: str,
                          timeout_ms: int) -> None:
        """Event-loop FETCH (service/wire_async.py): same semantics as
        fetch(), parts written drain-aware on the wire loop."""
        from blaze_tpu.service.wire_async import service_fetch_async

        await service_fetch_async(self, writer, qid, timeout_ms)

    def fetch(self, sock, qid: str, timeout_ms: int) -> None:
        try:
            q = self.service.get(qid)
        except KeyError:
            # includes queries the orphan sweep reaped: a dead
            # router's abandoned handle answers classified not-found,
            # never a hang
            _send_err(sock, f"UNKNOWN: no query {qid}")
            return
        # bit 31 of timeout_ms: the client accepts an arena handle
        arena_ok = bool(timeout_ms & _FETCH_ARENA)
        timeout_ms &= _FETCH_ARENA - 1
        q.note_activity()  # a FETCH defers the orphan sweep
        # in-progress-fetch guard: the orphan sweep must not reap a
        # query mid-collection (a slow first part or a long DONE-wait
        # could otherwise out-idle a short TTL); released in the
        # finally below
        q.begin_fetch()
        try:
            self._fetch_stream(sock, q, timeout_ms, arena_ok)
        finally:
            q.end_fetch()
            q.note_activity()

    def _fetch_stream(self, sock, q, timeout_ms: int,
                      arena_ok: bool = False) -> None:
        if self._serve_arena(sock, q, arena_ok):
            return
        sb = getattr(q, "stream", None)
        if sb is not None:
            # streaming service (the default): deliver parts as the
            # executor produces them - FETCH no longer waits for DONE
            self._fetch_incremental(sock, q, sb, timeout_ms)
            return
        self._fetch_materialized(sock, q, timeout_ms)

    def _serve_arena(self, sock, q, arena_ok: bool) -> bool:
        """Zero-copy FETCH of a finalized result (zerocopy/arena.py).
        When the query is DONE and its encoded part frames live in an
        arena segment, either lease the segment to the client (arena
        handle escape, `arena_ok`) or stream the frames as a
        scatter-gather buffer list - no Arrow re-encode either way,
        bytes identical to the per-batch path by construction. Returns
        False (and sends NOTHING) whenever the arena does not cover
        the query, so every fallback stays on the ordinary paths."""
        from blaze_tpu.service.query import QueryState

        arena = getattr(self.service, "arena", None)
        if (
            arena is None or not q.done
            or q.state is not QueryState.DONE
            or q._fingerprint is None or not q._fingerprint_stable
            or not q.use_cache or q.degraded
        ):
            return False
        key = q._fingerprint
        stream_start = time.monotonic()
        if arena_ok:
            handle = arena.handle(key)
            if handle is not None:
                data = json.dumps(handle).encode("utf-8")
                sock.sendall(
                    _U64.pack(_ARENA) + _U32.pack(len(data)) + data
                )
                q.fetched = True
                self._note_arena_stream(
                    q, stream_start, len(handle["offsets"]),
                    mode="handle",
                )
                return True
        views = arena.buffers(key)
        if views is None:
            return False
        from blaze_tpu.runtime.transport import sendmsg_all

        if chaos.ACTIVE:
            # mid-stream drop/stall seam: the whole buffer list goes
            # out in one scatter-gather burst, so the seam fires once
            # up front (a DROP aborts the stream before any bytes)
            chaos.fire("gateway.stream", query_id=q.query_id,
                       partition=0)
        sendmsg_all(sock, [*views, _U64.pack(0)])
        q.fetched = True
        q.note_activity()
        self._note_arena_stream(q, stream_start, len(views),
                                mode="sg")
        return True

    def _note_arena_stream(self, q, stream_start: float, parts: int,
                           mode: str) -> None:
        stream_s = time.monotonic() - stream_start
        q.timings["stream_ns"] = (
            q.timings.get("stream_ns", 0) + int(stream_s * 1e9)
        )
        if getattr(self.service, "_fold_phases", True):
            from blaze_tpu.obs import phases as obs_phases

            obs_phases.ROLLUP.observe(
                "stream", stream_s,
                klass=obs_phases.class_key(
                    q._fingerprint, q._fingerprint_stable
                ),
            )
        if obs_trace.ACTIVE and getattr(q, "tracer", None) is not None:
            q.tracer.record_span(
                "result_stream", stream_start, time.monotonic(),
                parts=parts, arena=mode,
            )

    def _fetch_incremental(self, sock, q, sb, timeout_ms: int) -> None:
        """Stream-as-produced FETCH (service/stream.py): drain the
        query's ring while it is still RUNNING. `timeout_ms` bounds
        the wait for the FIRST part (time-to-first-byte); once parts
        flow, production is bounded by the query's own deadline/cancel
        machinery and delivery by the stall budget. The wire format is
        UNCHANGED (u64-framed parts, u64 0 terminator, u64 ERR escape
        before the first part), so clients - and the router relay -
        need no new protocol: the count-based part-skip resume simply
        starts working mid-query."""
        from blaze_tpu.io.ipc import encode_ipc_segment

        service = self.service
        qid = q.query_id
        deadline = (
            time.monotonic() + timeout_ms / 1000.0
            if timeout_ms else None
        )
        sb.attach()
        t0 = time.perf_counter_ns()
        stream_start = time.monotonic()
        sent = 0
        live_parts = 0  # parts shipped while the query was RUNNING
        complete = False
        stall_s = getattr(service, "stream_stall_s", 0.0) or 0.0
        prev_timeout = sock.gettimeout()
        if stall_s > 0:
            # send-side slow-consumer bound: a stalled reader of a
            # DONE query's stream has no producer left to
            # backpressure, so the socket send timeout is the stall
            # budget on this half of the pipe
            sock.settimeout(stall_s)
        try:
            i = 0
            while True:
                if sent == 0 and deadline is not None:
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        _send_err(
                            sock, f"{q.state.value}: fetch timed out"
                        )
                        return
                    kind, payload = sb.next_ready(i, min(0.25, rem))
                else:
                    kind, payload = sb.next_ready(i, 0.25)
                if kind == "timeout":
                    continue
                if kind == "part":
                    if chaos.ACTIVE:
                        # chaos seam: drop/stall mid-result-stream,
                        # now covering the IN-PROGRESS window (the
                        # part may ship while the query is RUNNING)
                        chaos.fire("gateway.stream", query_id=qid,
                                   partition=i)
                    if not q.done:
                        live_parts += 1
                    # committed-for-delivery BEFORE the send: a part
                    # on the wire can never be truncated by a retry
                    # rollback (delivered-prefix consistency)
                    sb.mark_consumed(i)
                    try:
                        sock.sendall(encode_ipc_segment(payload))
                    except (socket.timeout, TimeoutError) as e:
                        service._note_stream_event("stall")
                        raise ConnectionError(
                            f"fetch send stalled past {stall_s}s"
                        ) from e
                    sent += 1
                    i += 1
                    # per-part activity: a slow COLLECTING client is
                    # not a dead router (orphan sweep)
                    q.note_activity()
                    continue
                if kind == "finished":
                    # the ring finishes at the DONE transition, so
                    # the terminal state is already set; the
                    # terminator closes the part stream
                    sock.sendall(_U64.pack(0))
                    complete = True
                    q.fetched = True
                    return
                # aborted: terminal (or about to be) with no result.
                # Parts already on the wire -> a JSON/ERR frame would
                # desync the u64 framing: abort the connection and
                # let the client's resume path re-FETCH the
                # classified outcome. Zero parts -> wait out the tiny
                # abort->terminal window so the state prefix the
                # router keys on is the real terminal state, then
                # answer in-band
                if sent:
                    raise ConnectionError(
                        f"fetch stream aborted: {payload}"
                    )
                q.wait(5.0)
                _send_err(
                    sock,
                    f"{q.state.value}: {q.error or 'not completed'}",
                )
                return
        finally:
            if stall_s > 0:
                try:
                    sock.settimeout(prev_timeout)
                except OSError:
                    pass
            stream_s = (time.perf_counter_ns() - t0) / 1e9
            q.timings["stream_ns"] = (
                q.timings.get("stream_ns", 0)
                + (time.perf_counter_ns() - t0)
            )
            if complete and getattr(service, "_fold_phases", True):
                from blaze_tpu.obs import phases as obs_phases

                obs_phases.ROLLUP.observe(
                    "stream", stream_s,
                    klass=obs_phases.class_key(
                        q._fingerprint, q._fingerprint_stable
                    ),
                )
            if obs_trace.ACTIVE \
                    and getattr(q, "tracer", None) is not None:
                # the `stream` span now covers the INCREMENTAL window:
                # it may open while the root span is still live (parts
                # shipping during RUNNING); live_parts says how much
                # of the stream overlapped execution
                tags = {"parts": sent, "total": sb.total_parts(),
                        "live_parts": live_parts}
                if not complete:
                    tags["aborted"] = True
                q.tracer.record_span(
                    "result_stream", stream_start, time.monotonic(),
                    **tags,
                )

    def _fetch_materialized(self, sock, q, timeout_ms: int) -> None:
        """Legacy materialize-then-stream FETCH: only reachable when
        the service runs with streaming disabled
        (stream_buffer_bytes <= 0)."""
        from blaze_tpu.io.ipc import encode_ipc_segment
        from blaze_tpu.service.query import QueryState

        service = self.service
        qid = q.query_id
        if not q.wait(timeout_ms / 1000.0 if timeout_ms else None):
            _send_err(sock, f"{q.state.value}: fetch timed out")
            return
        if q.state is not QueryState.DONE:
            _send_err(
                sock, f"{q.state.value}: {q.error or 'not completed'}"
            )
            return
        t0 = time.perf_counter_ns()
        stream_start = time.monotonic()
        sent = 0
        complete = False
        try:
            for i, rb in enumerate(q.result or ()):
                if chaos.ACTIVE:
                    # chaos seam: connection drop mid-result-stream
                    # (the client's reconnect-and-refetch path covers
                    # it)
                    chaos.fire("gateway.stream", query_id=qid,
                               partition=i)
                sock.sendall(encode_ipc_segment(rb))
                sent += 1
                # per-part activity: a stream slower than the orphan
                # TTL is still a COLLECTING client, not a dead router
                q.note_activity()
            sock.sendall(_U64.pack(0))
            complete = True
            # a fully-streamed result was COLLECTED: it is no orphan
            # candidate no matter how long it then sits in retention
            q.fetched = True
        except Exception as e:
            # once parts are on the wire the client reads u64 frames;
            # a JSON error frame here would desync it - abort the
            # connection (truncated stream surfaces client-side as
            # ConnectionError)
            raise ConnectionError(
                f"fetch stream aborted: {e!r}"
            ) from e
        finally:
            stream_s = (time.perf_counter_ns() - t0) / 1e9
            q.timings["stream_ns"] = (
                q.timings.get("stream_ns", 0)
                + (time.perf_counter_ns() - t0)
            )
            if complete and getattr(service, "_fold_phases", True):
                # stream phase rolls up at FETCH end (it happens
                # after the terminal-hook fold); aborted streams are
                # re-fetched and would double-count. Gated by the
                # same fold_phases switch as the terminal hook (the
                # regress probe must not skew the live rollup)
                from blaze_tpu.obs import phases as obs_phases

                obs_phases.ROLLUP.observe(
                    "stream", stream_s,
                    klass=obs_phases.class_key(
                        q._fingerprint, q._fingerprint_stable
                    ),
                )
            if obs_trace.ACTIVE \
                    and getattr(q, "tracer", None) is not None:
                # result streaming happens AFTER the root span closed
                # (terminal state), so it records as a sibling span on
                # the lifecycle track; `parts` counts what was
                # ACTUALLY sent - an aborted stream (and the client's
                # re-FETCH, which records its own span) must not claim
                # full delivery
                tags = {"parts": sent, "total": len(q.result or ())}
                if not complete:
                    tags["aborted"] = True
                q.tracer.record_span(
                    "result_stream", stream_start, time.monotonic(),
                    **tags,
                )


def handle_service_connection(sock, service) -> None:
    """Drive one service connection until EOF. Called from the gateway
    handler after it consumed the hello header."""
    serve_verb_connection(sock, ServiceVerbBackend(service))


def _read_u32(sock) -> int:
    from blaze_tpu.runtime.transport import _recv_exact

    (v,) = _U32.unpack(_recv_exact(sock, _U32.size))
    return v


def _read_str(sock) -> str:
    from blaze_tpu.runtime.transport import _recv_exact

    n = _read_u32(sock)
    if n > MAX_META_BYTES:
        raise ValueError("string frame too large")
    return _recv_exact(sock, n).decode("utf-8")


def _send_json(sock, obj: dict) -> None:
    data = json.dumps(obj).encode("utf-8")
    sock.sendall(_U32.pack(len(data)) + data)


def _send_err(sock, msg: str) -> None:
    data = msg.encode("utf-8")[:65536]
    sock.sendall(_U64.pack(_ERR) + _U32.pack(len(data)) + data)


# ---------------------------------------------------------------------------
# frame encoding (shared by ServiceClient and the replica router, which
# forwards a client's SUBMIT downstream byte-compatibly)
# ---------------------------------------------------------------------------


def decode_submit_frame(sock):
    """Read one SUBMIT verb frame off `sock` (verb byte already
    consumed) -> (meta, task_bytes, is_ref, manifest_bytes). The
    single decode used by BOTH the service handler and the replica
    router's proxy, so the frame format (flag bits, bounds) cannot
    drift between tiers; `manifest_bytes` stays un-parsed for
    forwarding."""
    from blaze_tpu.runtime.gateway import (
        MAX_TASK_BYTES,
        _FLAG_MANIFEST,
        _FLAG_REF,
    )
    from blaze_tpu.runtime.transport import _recv_exact

    (meta_len,) = _U32.unpack(_recv_exact(sock, _U32.size))
    if meta_len > MAX_META_BYTES:
        raise ValueError("submit meta too large")
    meta = json.loads(_recv_exact(sock, meta_len) or b"{}")
    (header,) = _U64.unpack(_recv_exact(sock, _U64.size))
    is_ref = bool(header & _FLAG_REF)
    has_manifest = bool(header & _FLAG_MANIFEST)
    blob_len = header & ~(_FLAG_REF | _FLAG_MANIFEST)
    if blob_len > MAX_TASK_BYTES:
        raise ValueError("task too large")
    manifest_bytes = None
    if has_manifest:
        (mlen,) = _U32.unpack(_recv_exact(sock, _U32.size))
        if mlen > MAX_TASK_BYTES:
            raise ValueError("manifest too large")
        manifest_bytes = _recv_exact(sock, mlen)
    return meta, _recv_exact(sock, blob_len), is_ref, manifest_bytes


def encode_submit_frame(
    meta: dict,
    task_bytes: bytes,
    *,
    is_ref: bool = False,
    manifest_bytes: Optional[bytes] = None,
) -> bytes:
    """One SUBMIT verb frame. `meta` is forwarded verbatim (unknown
    keys travel untouched - the router relies on this to stay out of
    the meta schema's way); `manifest_bytes` is the already-encoded
    manifest JSON, so a proxy never re-serializes what it did not
    parse."""
    from blaze_tpu.runtime.gateway import _FLAG_MANIFEST, _FLAG_REF

    meta_b = json.dumps(meta).encode("utf-8")
    header = len(task_bytes)
    if is_ref:
        header |= _FLAG_REF
    payload = b""
    if manifest_bytes is not None:
        header |= _FLAG_MANIFEST
        payload = _U32.pack(len(manifest_bytes)) + manifest_bytes
    return (
        bytes([VERB_SUBMIT])
        + _U32.pack(len(meta_b)) + meta_b
        + _U64.pack(header) + payload + task_bytes
    )


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------


class ServiceClient:
    """Multi-query client for the service protocol. One socket, many
    queries; every call is a synchronous verb round trip.

    Reconnect-with-backoff: on a dropped connection the client
    transparently reconnects (bounded attempts, exponential backoff +
    jitter) and re-attaches by query_id - polls re-issue, and a FETCH
    interrupted mid-stream re-issues and skips the parts already
    delivered (the server streams one materialized part per batch,
    deterministically). What survives the drop server-side: DONE
    results (until retention/TTL) and queries submitted with
    `detach=True`; a default (attached) submit still in flight is
    cancelled by the server's session teardown when it notices the
    disconnect - submit with detach=True when the handle must outlive
    the connection. Submits retry too: a submit whose CONNECTION died
    before the response frame may have registered server-side, but
    re-submitting is safe - the result cache dedupes stable plans and
    a duplicate query is merely wasted work, never a wrong answer.
    Set reconnect_attempts=0 to restore fail-fast behavior."""

    def __init__(self, host: str, port: int, timeout: float = 120.0,
                 reconnect_attempts: int = 4,
                 reconnect_backoff_s: float = 0.05,
                 use_arena: bool = False,
                 tenant: str = "default"):
        self._addr = (host, port)
        self._timeout = timeout
        self._reconnect_attempts = int(reconnect_attempts)
        self._reconnect_backoff_s = float(reconnect_backoff_s)
        # client-level tenant identity: every submit() carries it in
        # SUBMIT meta unless overridden per call (docs/SERVICE.md
        # "Tenancy"); "default" = untagged traffic
        self._tenant = str(tenant or "default")
        # shared-memory FETCH opt-in (zerocopy/arena.py): only a
        # client co-located with the server can map the segment paths
        # a handle names, so the default stays the byte path; a failed
        # map degrades back to bytes transparently either way
        self._use_arena = bool(use_arena)
        self._sock = None
        self._connect()

    def _connect(self) -> None:
        from blaze_tpu.runtime.gateway import _FLAG_SERVICE

        self._sock = socket.create_connection(
            self._addr, timeout=self._timeout
        )
        self._sock.sendall(_U64.pack(_FLAG_SERVICE))

    def _reconnect(self) -> None:
        import random

        self.close()
        last: Optional[Exception] = None
        for attempt in range(self._reconnect_attempts):
            delay = self._reconnect_backoff_s * (2 ** attempt)
            time.sleep(random.uniform(delay * 0.5, delay))
            try:
                self._connect()
                return
            except OSError as e:
                last = e
        raise ServiceError(f"RECONNECT_FAILED: {last!r}")

    def _roundtrip(self, payload: bytes) -> dict:
        """Send one verb frame and read its JSON response, reconnecting
        once on a dropped connection (every verb frame is
        self-contained, so a resend after reconnect is in-sync)."""
        for attempt in (0, 1):
            try:
                if self._sock is None:
                    # closed by close() or a failed reconnect: try a
                    # fresh connection instead of AttributeError-ing
                    self._connect()
                self._sock.sendall(payload)
                return self._read_json()
            except (ConnectionError, OSError):
                if attempt or self._reconnect_attempts <= 0:
                    raise
                self._reconnect()
        raise AssertionError("unreachable")

    # -- verbs ----------------------------------------------------------
    def submit(
        self,
        task_bytes: bytes,
        *,
        is_ref: bool = False,
        manifest: Optional[dict] = None,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        estimated_bytes: Optional[int] = None,
        use_cache: bool = True,
        detach: bool = False,
        tenant: Optional[str] = None,
    ) -> dict:
        """`detach=True` opts the query out of the server's
        cancel-on-disconnect session semantics, so the handle survives
        a connection drop and this client's reconnect can re-attach
        by query_id. `tenant` overrides the client-level tenant for
        this one submit.

        A DRAINING rejection (the replica is mid-rolling-restart) is
        retried with the same bounded backoff as a dropped connection
        - the replica, or its restarted replacement behind the same
        address, comes back - and surfaces as a classified TRANSIENT
        `ReplicaDrainingError` only once the budget is spent
        (`reconnect_attempts=0` restores fail-fast). A tenant-budget
        rejection (REJECTED_TENANT_BUDGET: this tenant is over its
        admission budget or rate limit) follows the exact same
        retry-then-classify contract, surfacing as
        `TenantBudgetError`."""
        import random

        meta = {
            "priority": priority,
            "deadline_s": deadline_s,
            "estimated_bytes": estimated_bytes,
            "use_cache": use_cache,
            "detach": detach,
            "tenant": str(tenant or self._tenant),
        }
        manifest_bytes = (
            json.dumps(manifest).encode("utf-8")
            if manifest is not None else None
        )
        for attempt in range(max(1, self._reconnect_attempts + 1)):
            resp = self.submit_raw(
                task_bytes, meta=meta, is_ref=is_ref,
                manifest_bytes=manifest_bytes,
            )
            if not (_is_draining_rejection(resp)
                    or _is_tenant_budget_rejection(resp)):
                return resp
            if attempt >= self._reconnect_attempts:
                break
            delay = self._reconnect_backoff_s * (2 ** attempt)
            time.sleep(random.uniform(delay * 0.5, delay))
        if _is_tenant_budget_rejection(resp):
            from blaze_tpu.errors import TenantBudgetError

            raise TenantBudgetError(
                resp.get("error",
                         "REJECTED_TENANT_BUDGET: over budget")
            )
        from blaze_tpu.errors import ReplicaDrainingError

        raise ReplicaDrainingError(
            resp.get("error", "DRAINING: replica is draining")
        )

    def submit_raw(
        self,
        task_bytes: bytes,
        *,
        meta: dict,
        is_ref: bool = False,
        manifest_bytes: Optional[bytes] = None,
    ) -> dict:
        """Submit with a caller-built meta dict, forwarded verbatim.
        The router tier uses this to proxy a client's SUBMIT without
        re-interpreting (or dropping) meta keys it does not know."""
        return self._roundtrip(
            encode_submit_frame(
                meta, task_bytes, is_ref=is_ref,
                manifest_bytes=manifest_bytes,
            )
        )

    def poll(self, query_id: str) -> dict:
        return self._roundtrip(self._id_verb(VERB_POLL, query_id))

    def cancel(self, query_id: str) -> dict:
        return self._roundtrip(self._id_verb(VERB_CANCEL, query_id))

    def report(self, query_id: str) -> str:
        return self._roundtrip(
            self._id_verb(VERB_REPORT, query_id)
        )["report"]

    def report_full(self, query_id: str,
                    include_trace: bool = True,
                    include_spans: bool = False) -> dict:
        """The whole REPORT frame: {report: text, trace?: Chrome trace
        JSON, trace_spans?: raw span dicts}. The trace document is
        requested via flags bit 0 (plain `report()` skips it - text
        polling must not pay a multi-MB span-tree serialization);
        `python -m blaze_tpu trace` consumes the trace field. Flags
        bit 1 requests the RAW span dicts instead - the replica
        router's cross-hop graft input (attach_subtree)."""
        flags = (1 if include_trace else 0) \
            | (2 if include_spans else 0)
        return self._roundtrip(
            self._id_verb(VERB_REPORT, query_id, flags)
        )

    def stats(self) -> dict:
        return self._roundtrip(bytes([VERB_STATS]) + _U32.pack(0))

    def metrics(self) -> str:
        """Prometheus text exposition from the server's process
        metrics registry (obs/metrics.py)."""
        return self._roundtrip(
            bytes([VERB_METRICS]) + _U32.pack(0)
        )["metrics"]

    def member(self, payload: dict) -> dict:
        """One membership round trip (MEMBER verb): {"op": "join" |
        "leave", "host", "port", ...} against a router endpoint. The
        announcer (router/membership.py) drives this; a non-router
        endpoint answers with an in-band error."""
        data = json.dumps(payload).encode("utf-8")
        return self._roundtrip(
            bytes([VERB_MEMBER]) + _U32.pack(len(data)) + data
        )

    def mesh_exchange(self, payload: dict, parts=()) -> tuple:
        """One MESH_EXCHANGE round trip (the fleet tier's DCN plane):
        a JSON control frame plus u64-framed encoded Arrow-IPC parts
        each way. Returns (response_dict, out_parts). An in-band
        error response carries NO part stream (the server drained our
        parts before dispatch, so the connection stays in sync). The
        send + JSON read ride the standard one-reconnect retry; a
        drop mid part-stream propagates to the caller (the fleet
        executor's degrade ladder owns that)."""
        from blaze_tpu.runtime.transport import _recv_exact

        data = json.dumps(payload).encode("utf-8")
        buf = bytearray(
            bytes([VERB_MESH_EXCHANGE]) + _U32.pack(len(data)) + data
        )
        for p in parts:
            buf += _U64.pack(len(p))
            buf += p
        buf += _U64.pack(0)
        resp = self._roundtrip(bytes(buf))
        if "error" in resp:
            return resp, []
        out: List[bytes] = []
        while True:
            (n,) = _U64.unpack(_recv_exact(self._sock, _U64.size))
            if n == 0:
                break
            if n > MAX_EXCHANGE_PART_BYTES:
                raise ValueError("oversized exchange part")
            out.append(_recv_exact(self._sock, n))
        return resp, out

    def profile(self, payload: Optional[dict] = None) -> dict:
        """One PROFILE round trip: {"op": "start"|"stop"|"snapshot"|
        "reset", ...} against either tier - arm contention accounting
        + the stack sampler on a LIVE process and pull the folded
        report back, no restart required. Default op is snapshot."""
        data = json.dumps(payload or {}).encode("utf-8")
        return self._roundtrip(
            bytes([VERB_PROFILE]) + _U32.pack(len(data)) + data
        )

    def fetch(self, query_id: str, timeout_ms: int = 0) -> list:
        """Materialize the result stream (list of pa.RecordBatch)."""
        return list(self.fetch_stream(query_id, timeout_ms))

    def fetch_stream(self, query_id: str,
                     timeout_ms: int = 0) -> Iterator:
        """Stream the result parts. Closing the client (or abandoning
        the socket) mid-stream is the wire-level cancel. A connection
        dropped by the SERVER mid-stream triggers reconnect +
        re-FETCH, skipping the parts already yielded (results are
        materialized server-side; the part sequence is stable)."""
        parts_yielded = 0
        refetches = 0
        while True:
            try:
                yield from self._fetch_parts(
                    query_id, timeout_ms, skip=parts_yielded
                )
                return
            except ServiceError:
                raise  # in-band terminal state, not a drop
            except (ConnectionError, OSError):
                if refetches >= max(0, self._reconnect_attempts):
                    raise
                refetches += 1
                self._reconnect()
                parts_yielded = self._parts_done

    def _fetch_parts(self, query_id: str, timeout_ms: int,
                     skip: int) -> Iterator:
        from blaze_tpu.runtime.transport import _recv_exact

        self._parts_done = skip
        arena_ok = self._use_arena
        while True:
            if self._sock is None:
                self._connect()
            self._sock.sendall(
                self._id_verb(
                    VERB_FETCH, query_id,
                    timeout_ms | (_FETCH_ARENA if arena_ok else 0),
                )
            )
            part = 0
            resend = False
            while True:
                (length,) = _U64.unpack(
                    _recv_exact(self._sock, _U64.size)
                )
                if length == 0:
                    return
                if length == _ERR:
                    (mlen,) = _U32.unpack(
                        _recv_exact(self._sock, _U32.size)
                    )
                    msg = _recv_exact(self._sock, mlen).decode("utf-8")
                    raise ServiceError(msg)
                if length == _ARENA:
                    # shared-memory handoff: map the leased segment
                    # and decode the identical frames locally. ANY
                    # failure (not co-located, stale lease, chaos
                    # seams) falls back to a byte-path re-FETCH on the
                    # same connection - the handle replaced the whole
                    # part stream, so the framing is still in sync
                    frames = self._read_arena_handle()
                    if frames is None:
                        arena_ok = False
                        resend = True
                        break
                    for frame in frames:
                        part += 1
                        if part <= skip:
                            continue
                        yield from self._decode_part(frame[8:])
                        self._parts_done = part
                    return
                payload = _recv_exact(self._sock, length)
                if chaos.ACTIVE:
                    # chaos seam `stream.consume`: the CLIENT side of
                    # the pipe - STALL models a slow consumer (the
                    # server's backpressure/stall budget sees it),
                    # DROP a consumer whose connection dies mid-read
                    # (the reconnect + part-skip resume path covers
                    # it). Fired after the payload recv so `part` is
                    # the 0-based index of the part in hand
                    chaos.fire("stream.consume", query_id=query_id,
                               partition=part)
                part += 1
                if part <= skip:
                    continue  # already delivered; drained, not decoded
                yield from self._decode_part(payload)
                self._parts_done = part
            if not resend:
                return

    def _decode_part(self, payload) -> Iterator:
        import pyarrow as pa

        from blaze_tpu.runtime import native

        raw = native.zstd_decompress(bytes(payload))
        if not raw:
            return
        with pa.ipc.open_stream(raw) as reader:
            for rb in reader:
                if rb.num_rows > 0:
                    yield rb

    def _read_arena_handle(self) -> Optional[list]:
        """Consume the arena-handle JSON off the wire and try the shm
        path: map the segment, copy the frames out, release the lease.
        None means fall back to bytes (the caller re-FETCHes); the
        lease is released (or TTL-reaped) either way."""
        from blaze_tpu.runtime.transport import _recv_exact

        (mlen,) = _U32.unpack(_recv_exact(self._sock, _U32.size))
        if mlen > MAX_JSON_BYTES:
            raise ValueError("oversized arena handle")
        handle = json.loads(
            _recv_exact(self._sock, mlen).decode("utf-8")
        )
        frames = None
        try:
            from blaze_tpu.zerocopy.arena import map_handle_frames

            frames = map_handle_frames(handle)
        except Exception:  # noqa: BLE001 - degrade to byte path
            frames = None
        finally:
            lease = handle.get("lease")
            if lease is not None:
                try:
                    self._roundtrip(
                        self._id_verb(VERB_RELEASE, str(lease))
                    )
                except Exception:  # noqa: BLE001 - TTL reap covers it
                    pass
        return frames

    # -- helpers --------------------------------------------------------
    def run(self, task_bytes: bytes, **submit_kw) -> list:
        """submit + fetch in one call (the single-query convenience)."""
        st = self.submit(task_bytes, **submit_kw)
        if st["state"] not in ("QUEUED", "ADMITTED", "RUNNING", "DONE"):
            raise ServiceError(
                f"{st['state']}: {st.get('error', 'rejected')}"
            )
        return self.fetch(st["query_id"])

    @staticmethod
    def _id_verb(verb: int, query_id: str, extra_u32: int = 0) -> bytes:
        qid = query_id.encode("utf-8")
        return (
            bytes([verb]) + _U32.pack(len(qid)) + qid
            + _U32.pack(extra_u32)
        )

    def _read_json(self) -> dict:
        from blaze_tpu.runtime.transport import _recv_exact

        (n,) = _U32.unpack(_recv_exact(self._sock, _U32.size))
        if n > MAX_JSON_BYTES:
            raise ValueError("oversized JSON frame")
        return json.loads(_recv_exact(self._sock, n).decode("utf-8"))

    def close(self) -> None:
        if self._sock is None:
            return
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
