"""Service wire protocol: multi-query serving over the gateway socket.

The legacy gateway connection (runtime/gateway.py) is one-shot: one
TaskDefinition in, one batch stream out. A serving tier needs verbs -
submit several queries over one connection, poll them, stream results,
cancel mid-flight. The framing extends the gateway's, so one listener
serves both: a connection whose FIRST u64 header has bit 61
(_FLAG_SERVICE) set switches to this protocol; anything else is a
legacy single-task connection.

Service framing (all integers LE):

  hello:    u64 header with _FLAG_SERVICE set (rest of the bits 0)
  verb:     u8   SUBMIT=1 POLL=2 FETCH=3 CANCEL=4 REPORT=5 STATS=6
  SUBMIT:   u32 meta_len | meta JSON | u64 blob_header | [u32 mlen |
            manifest JSON] | blob
            blob_header reuses the legacy bits: bit 63 = reference wire
            format, bit 62 = resource manifest present, low bits = len.
            meta: {priority, deadline_s, estimated_bytes, use_cache}
            -> JSON frame {query_id, state, ...}
  POLL:     u32 id_len | id   -> JSON frame (Query.status())
  FETCH:    u32 id_len | id | u32 timeout_ms (0 = wait forever)
            -> on DONE: segmented-IPC parts (u64 len | zstd Arrow IPC),
               then u64 0 (the shuffle/gateway wire format, io/ipc.py)
            -> else: u64 ERR | u32 len | "STATE: detail" utf8
  CANCEL:   u32 id_len | id   -> JSON frame
  REPORT:   u32 id_len | id   -> JSON frame {report: text}
  STATS:    u32 0             -> JSON frame (service stats)
  JSON frame: u32 len | utf8 JSON

Session semantics: queries submitted on a connection belong to it;
when the connection drops (EOF, broken pipe) every non-terminal
session query is cancelled - a vanished client must not keep holding
device admission slots. Poll/cancel/fetch work from ANY connection
(query ids are global), so detached orchestration is still possible
via a second connection.
"""

from __future__ import annotations

import json
import socket
import struct
import time
from typing import Iterator, List, Optional

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")
_ERR = 0xFFFFFFFFFFFFFFFF

VERB_SUBMIT = 1
VERB_POLL = 2
VERB_FETCH = 3
VERB_CANCEL = 4
VERB_REPORT = 5
VERB_STATS = 6

MAX_META_BYTES = 1 << 20


class ServiceError(RuntimeError):
    """Error frame surfaced client-side; `.state` carries the query's
    terminal state name when the server included one."""

    def __init__(self, msg: str):
        super().__init__(msg)
        self.state = msg.split(":", 1)[0] if ":" in msg else ""


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------


def handle_service_connection(sock, service) -> None:
    """Drive one service connection until EOF. Called from the gateway
    handler after it consumed the hello header."""
    from blaze_tpu.runtime.transport import _recv_exact

    session_qids: List[str] = []
    try:
        while True:
            try:
                verb = _recv_exact(sock, 1)[0]
            except (ConnectionError, OSError):
                return  # clean EOF / client gone
            try:
                if verb == VERB_SUBMIT:
                    _handle_submit(sock, service, session_qids)
                elif verb == VERB_POLL:
                    qid = _read_str(sock)
                    _read_u32(sock)  # reserved (always 0)
                    _send_json(sock, service.poll(qid))
                elif verb == VERB_FETCH:
                    _handle_fetch(sock, service)
                elif verb == VERB_CANCEL:
                    qid = _read_str(sock)
                    _read_u32(sock)
                    _send_json(sock, service.cancel(qid))
                elif verb == VERB_REPORT:
                    qid = _read_str(sock)
                    _read_u32(sock)
                    _send_json(
                        sock, {"report": service.report(qid)}
                    )
                elif verb == VERB_STATS:
                    _read_u32(sock)
                    _send_json(sock, service.stats())
                else:
                    raise ValueError(f"unknown service verb {verb}")
            except (ConnectionError, BrokenPipeError, OSError):
                return  # mid-verb disconnect: session cleanup below
            except ValueError as e:
                # protocol violation (oversized frame, unknown verb,
                # bad manifest): the connection may hold unread payload
                # bytes that would be misparsed as verbs - report
                # best-effort and CLOSE instead of desyncing
                try:
                    _send_json(
                        sock,
                        {"error": f"protocol error: {e}"[:65536],
                         "fatal": True},
                    )
                except OSError:
                    pass
                return
            except KeyError as e:
                # id lookups fail AFTER their frame is fully read -
                # the connection is still in sync, report in-band
                _send_json(sock, {"error": f"unknown query: {e}"})
            except Exception as e:  # noqa: BLE001 - reported in-band
                _send_json(
                    sock,
                    {"error": f"{type(e).__name__}: {e}"[:65536]},
                )
    finally:
        # session teardown: a disconnected client's pending queries
        # must not keep occupying the queue or the device
        for qid in session_qids:
            try:
                q = service.get(qid)
                if not q.done:
                    service.cancel(qid)
            except KeyError:
                pass


def _handle_submit(sock, service, session_qids: List[str]) -> None:
    from blaze_tpu.runtime.gateway import (
        MAX_TASK_BYTES,
        _FLAG_MANIFEST,
        _FLAG_REF,
        _manifest_resources,
    )
    from blaze_tpu.runtime.transport import _recv_exact

    (meta_len,) = _U32.unpack(_recv_exact(sock, _U32.size))
    if meta_len > MAX_META_BYTES:
        raise ValueError("submit meta too large")
    meta = json.loads(_recv_exact(sock, meta_len) or b"{}")
    (header,) = _U64.unpack(_recv_exact(sock, _U64.size))
    is_ref = bool(header & _FLAG_REF)
    has_manifest = bool(header & _FLAG_MANIFEST)
    blob_len = header & ~(_FLAG_REF | _FLAG_MANIFEST)
    if blob_len > MAX_TASK_BYTES:
        raise ValueError("task too large")
    resources = {}
    if has_manifest:
        (mlen,) = _U32.unpack(_recv_exact(sock, _U32.size))
        if mlen > MAX_TASK_BYTES:
            raise ValueError("manifest too large")
        resources = _manifest_resources(
            json.loads(_recv_exact(sock, mlen))
        )
    blob = _recv_exact(sock, blob_len)
    q = service.submit_task(
        blob,
        is_ref=is_ref,
        resources=resources,
        priority=int(meta.get("priority", 0)),
        deadline_s=meta.get("deadline_s"),
        estimated_bytes=meta.get("estimated_bytes"),
        use_cache=bool(meta.get("use_cache", True)),
    )
    session_qids.append(q.query_id)
    _send_json(sock, q.status())


def _handle_fetch(sock, service) -> None:
    from blaze_tpu.io.ipc import encode_ipc_segment
    from blaze_tpu.service.query import QueryState

    qid = _read_str(sock)
    timeout_ms = _read_u32(sock)
    try:
        q = service.get(qid)
    except KeyError:
        _send_err(sock, f"UNKNOWN: no query {qid}")
        return
    if not q.wait(timeout_ms / 1000.0 if timeout_ms else None):
        _send_err(sock, f"{q.state.value}: fetch timed out")
        return
    if q.state is not QueryState.DONE:
        _send_err(
            sock, f"{q.state.value}: {q.error or 'not completed'}"
        )
        return
    t0 = time.perf_counter_ns()
    try:
        for rb in q.result or ():
            sock.sendall(encode_ipc_segment(rb))
        sock.sendall(_U64.pack(0))
    except Exception as e:
        # once parts are on the wire the client reads u64 frames; a
        # JSON error frame here would desync it - abort the connection
        # (truncated stream surfaces client-side as ConnectionError)
        raise ConnectionError(f"fetch stream aborted: {e!r}") from e
    finally:
        q.timings["stream_ns"] = (
            q.timings.get("stream_ns", 0)
            + (time.perf_counter_ns() - t0)
        )


def _read_u32(sock) -> int:
    from blaze_tpu.runtime.transport import _recv_exact

    (v,) = _U32.unpack(_recv_exact(sock, _U32.size))
    return v


def _read_str(sock) -> str:
    from blaze_tpu.runtime.transport import _recv_exact

    n = _read_u32(sock)
    if n > MAX_META_BYTES:
        raise ValueError("string frame too large")
    return _recv_exact(sock, n).decode("utf-8")


def _send_json(sock, obj: dict) -> None:
    data = json.dumps(obj).encode("utf-8")
    sock.sendall(_U32.pack(len(data)) + data)


def _send_err(sock, msg: str) -> None:
    data = msg.encode("utf-8")[:65536]
    sock.sendall(_U64.pack(_ERR) + _U32.pack(len(data)) + data)


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------


class ServiceClient:
    """Multi-query client for the service protocol. One socket, many
    queries; every call is a synchronous verb round trip."""

    def __init__(self, host: str, port: int, timeout: float = 120.0):
        from blaze_tpu.runtime.gateway import _FLAG_SERVICE

        self._sock = socket.create_connection(
            (host, port), timeout=timeout
        )
        self._sock.sendall(_U64.pack(_FLAG_SERVICE))

    # -- verbs ----------------------------------------------------------
    def submit(
        self,
        task_bytes: bytes,
        *,
        is_ref: bool = False,
        manifest: Optional[dict] = None,
        priority: int = 0,
        deadline_s: Optional[float] = None,
        estimated_bytes: Optional[int] = None,
        use_cache: bool = True,
    ) -> dict:
        from blaze_tpu.runtime.gateway import (
            _FLAG_MANIFEST,
            _FLAG_REF,
        )

        meta = json.dumps(
            {
                "priority": priority,
                "deadline_s": deadline_s,
                "estimated_bytes": estimated_bytes,
                "use_cache": use_cache,
            }
        ).encode("utf-8")
        header = len(task_bytes)
        if is_ref:
            header |= _FLAG_REF
        payload = b""
        if manifest is not None:
            header |= _FLAG_MANIFEST
            mbytes = json.dumps(manifest).encode("utf-8")
            payload = _U32.pack(len(mbytes)) + mbytes
        self._sock.sendall(
            bytes([VERB_SUBMIT])
            + _U32.pack(len(meta)) + meta
            + _U64.pack(header) + payload + task_bytes
        )
        return self._read_json()

    def poll(self, query_id: str) -> dict:
        self._send_id_verb(VERB_POLL, query_id)
        return self._read_json()

    def cancel(self, query_id: str) -> dict:
        self._send_id_verb(VERB_CANCEL, query_id)
        return self._read_json()

    def report(self, query_id: str) -> str:
        self._send_id_verb(VERB_REPORT, query_id)
        return self._read_json()["report"]

    def stats(self) -> dict:
        self._sock.sendall(bytes([VERB_STATS]) + _U32.pack(0))
        return self._read_json()

    def fetch(self, query_id: str, timeout_ms: int = 0) -> list:
        """Materialize the result stream (list of pa.RecordBatch)."""
        return list(self.fetch_stream(query_id, timeout_ms))

    def fetch_stream(self, query_id: str,
                     timeout_ms: int = 0) -> Iterator:
        """Stream the result parts. Closing the client (or abandoning
        the socket) mid-stream is the wire-level cancel."""
        import pyarrow as pa

        from blaze_tpu.runtime import native
        from blaze_tpu.runtime.transport import _recv_exact

        self._send_id_verb(VERB_FETCH, query_id, timeout_ms)
        while True:
            (length,) = _U64.unpack(_recv_exact(self._sock, _U64.size))
            if length == 0:
                return
            if length == _ERR:
                (mlen,) = _U32.unpack(
                    _recv_exact(self._sock, _U32.size)
                )
                msg = _recv_exact(self._sock, mlen).decode("utf-8")
                raise ServiceError(msg)
            raw = native.zstd_decompress(
                _recv_exact(self._sock, length)
            )
            if not raw:
                continue
            with pa.ipc.open_stream(raw) as reader:
                for rb in reader:
                    if rb.num_rows > 0:
                        yield rb

    # -- helpers --------------------------------------------------------
    def run(self, task_bytes: bytes, **submit_kw) -> list:
        """submit + fetch in one call (the single-query convenience)."""
        st = self.submit(task_bytes, **submit_kw)
        if st["state"] not in ("QUEUED", "ADMITTED", "RUNNING", "DONE"):
            raise ServiceError(
                f"{st['state']}: {st.get('error', 'rejected')}"
            )
        return self.fetch(st["query_id"])

    def _send_id_verb(self, verb: int, query_id: str,
                      extra_u32: int = 0) -> None:
        qid = query_id.encode("utf-8")
        self._sock.sendall(
            bytes([verb]) + _U32.pack(len(qid)) + qid
            + _U32.pack(extra_u32)
        )

    def _read_json(self) -> dict:
        from blaze_tpu.runtime.transport import _recv_exact

        (n,) = _U32.unpack(_recv_exact(self._sock, _U32.size))
        if n > MAX_META_BYTES:
            raise ValueError("oversized JSON frame")
        return json.loads(_recv_exact(self._sock, n).decode("utf-8"))

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
