"""Async wire data plane: event-loop verb serving for both tiers.

PR 15's profiler showed the thread-per-connection wire tier is the
c16+ wall: ~60% of max-pressure stack samples blocked in
`transport:_recv_exact` and every open connection cost an OS thread
whether or not bytes were flowing. This module ports the shared verb
loop (service/wire.serve_verb_connection) onto one process-wide
asyncio event loop:

  * framed reads are non-blocking (`StreamReader.readexactly` on the
    selector loop) - an idle connection costs a parked coroutine, not
    a parked thread;
  * verb DISPATCH still runs on threads (a bounded executor pool):
    admission, cache lookups, and query bookkeeping are lock-shaped
    Python work that must not stall the IO loop;
  * streamed FETCH replies are drain-aware non-blocking writes - a
    slow client parks its writer coroutine against the stall budget
    (`asyncio.wait_for(writer.drain(), stall_s)`) instead of pinning
    a thread in `sendall`;
  * the router's windowed relay rides the same loop (proxy.py's
    `_raw_fetch_async`), so an open relayed stream no longer costs a
    reader thread.

Every wire semantic is preserved by construction: the verb skeleton,
error-handling ladder, session teardown, per-verb latency histograms,
accept-to-first-byte, connection gauges, PROFILE=9, and the chaos
seams all mirror service/wire.py line for line - the threaded loop
stays available (`--wire threaded` / BLAZE_WIRE=threaded) as the
differential oracle for the parity tests.

Loop ownership: ONE daemon loop thread per process ("blaze-wire-loop"),
shared by every AsyncWireServer (gateway and router tiers). Legacy
one-shot task connections (no _FLAG_SERVICE hello bit) are detected on
the loop and handed to a daemon thread: task execution is
thread-shaped work (jax dispatch, file IO) and keeps its existing
blocking path.
"""

from __future__ import annotations

import asyncio
import concurrent.futures as cf
import json
import os
import socket
import struct
import threading
import time
from functools import partial
from typing import Callable, List, Optional

from blaze_tpu.obs import trace as obs_trace
from blaze_tpu.testing import chaos

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")
_ERR = 0xFFFFFFFFFFFFFFFF

# ---------------------------------------------------------------------------
# process-wide loop + bounded dispatch pool
# ---------------------------------------------------------------------------

_LOOP_LOCK = threading.Lock()
_LOOP: Optional[asyncio.AbstractEventLoop] = None
_POOLS: dict = {}


def get_loop() -> asyncio.AbstractEventLoop:
    """The process-wide wire event loop, started lazily on a daemon
    thread. One selector thread serves every wire listener in the
    process (both tiers) - the data plane is IO-bound and the loop
    replaces the per-connection thread army."""
    global _LOOP
    with _LOOP_LOCK:
        if _LOOP is not None and not _LOOP.is_closed():
            return _LOOP
        loop = asyncio.new_event_loop()
        threading.Thread(
            target=loop.run_forever, daemon=True,
            name="blaze-wire-loop",
        ).start()
        _LOOP = loop
        return _LOOP


def dispatch_pool(tier: str = "service") -> cf.ThreadPoolExecutor:
    """Bounded verb-dispatch pool, ONE PER TIER: backend calls
    (submit/poll/cancel/stats/...) hold service or router locks and
    may block briefly - they run here so the IO loop never does. Sized
    to useful work, not to connection count: that is the whole point
    of the port.

    Per-tier isolation is a deadlock invariant, not a tuning knob: a
    router verb handler blocks its pool thread on a downstream replica
    call, and that replica's handler needs a pool thread to answer.
    One shared pool lets N parked router handlers starve the replicas
    they are waiting on (total wire deadlock when both tiers share a
    process, as the bench fleet does); separate pools keep the
    router->service call graph acyclic in thread-supply terms."""
    with _LOOP_LOCK:
        pool = _POOLS.get(tier)
        if pool is None:
            pool = _POOLS[tier] = cf.ThreadPoolExecutor(
                max_workers=max(4, min(32, 4 * (os.cpu_count() or 2))),
                thread_name_prefix=f"blaze-verb-dispatch-{tier}",
            )
        return pool


# ---------------------------------------------------------------------------
# async framing helpers (mirror service/wire.py's blocking ones)
# ---------------------------------------------------------------------------


async def _read_exact(reader: asyncio.StreamReader, n: int) -> bytes:
    """readexactly with the blocking tier's error contract: EOF
    mid-frame is a ConnectionError, so the shared error ladder
    (mid-verb disconnect -> session cleanup) stays byte-identical."""
    try:
        return await reader.readexactly(n)
    except asyncio.IncompleteReadError as e:
        raise ConnectionError("socket closed mid-frame") from e


async def _read_u32(reader) -> int:
    (v,) = _U32.unpack(await _read_exact(reader, _U32.size))
    return v


async def _read_str(reader) -> str:
    from blaze_tpu.service.wire import MAX_META_BYTES

    n = await _read_u32(reader)
    if n > MAX_META_BYTES:
        raise ValueError("string frame too large")
    return (await _read_exact(reader, n)).decode("utf-8")


async def _send_json(writer, obj: dict) -> None:
    data = json.dumps(obj).encode("utf-8")
    writer.write(_U32.pack(len(data)) + data)
    await writer.drain()


async def _send_err(writer, msg: str) -> None:
    data = msg.encode("utf-8")[:65536]
    writer.write(_U64.pack(_ERR) + _U32.pack(len(data)) + data)
    await writer.drain()


async def decode_submit_frame_async(reader):
    """Async twin of wire.decode_submit_frame: same bounds, same flag
    bits, manifest stays un-parsed for forwarding."""
    from blaze_tpu.runtime.gateway import (
        MAX_TASK_BYTES,
        _FLAG_MANIFEST,
        _FLAG_REF,
    )
    from blaze_tpu.service.wire import MAX_META_BYTES

    (meta_len,) = _U32.unpack(await _read_exact(reader, _U32.size))
    if meta_len > MAX_META_BYTES:
        raise ValueError("submit meta too large")
    meta = json.loads(await _read_exact(reader, meta_len) or b"{}")
    (header,) = _U64.unpack(await _read_exact(reader, _U64.size))
    is_ref = bool(header & _FLAG_REF)
    has_manifest = bool(header & _FLAG_MANIFEST)
    blob_len = header & ~(_FLAG_REF | _FLAG_MANIFEST)
    if blob_len > MAX_TASK_BYTES:
        raise ValueError("task too large")
    manifest_bytes = None
    if has_manifest:
        (mlen,) = _U32.unpack(await _read_exact(reader, _U32.size))
        if mlen > MAX_TASK_BYTES:
            raise ValueError("manifest too large")
        manifest_bytes = await _read_exact(reader, mlen)
    return meta, await _read_exact(reader, blob_len), is_ref, \
        manifest_bytes


# ---------------------------------------------------------------------------
# the verb loop, coroutine edition
# ---------------------------------------------------------------------------


async def serve_verb_connection_async(reader, writer, backend,
                                      t_accept: Optional[float] = None
                                      ) -> None:
    """Coroutine twin of wire.serve_verb_connection: same skeleton,
    same ladder, same observability surfaces. Socket reads/writes ride
    the loop; backend verb calls run on the bounded dispatch pool;
    FETCH goes through the backend's `fetch_async` (drain-aware part
    streaming)."""
    from blaze_tpu.obs.metrics import REGISTRY
    from blaze_tpu.service import wire

    loop = asyncio.get_running_loop()
    tier = getattr(backend, "tier", "service")
    pool = dispatch_pool(tier)
    with wire._CONN_LOCK:
        wire._CONNECTIONS[tier] = wire._CONNECTIONS.get(tier, 0) + 1
    REGISTRY.register_collector("wire_connections", wire._conn_samples)
    if t_accept is None:
        t_accept = time.perf_counter()
    first_verb = True
    session_qids: List[str] = []
    try:
        while True:
            try:
                verb = (await _read_exact(reader, 1))[0]
            except (ConnectionError, OSError):
                return  # clean EOF / client gone
            t0 = time.perf_counter()
            if first_verb:
                first_verb = False
                REGISTRY.observe("blaze_accept_first_byte_seconds",
                                 t0 - t_accept, tier=tier)
            try:
                if verb == wire.VERB_SUBMIT:
                    meta, blob, is_ref, manifest_bytes = (
                        await decode_submit_frame_async(reader)
                    )
                    t1 = time.perf_counter()
                    resp = await loop.run_in_executor(
                        pool, partial(backend.submit, meta, blob,
                                      is_ref, manifest_bytes)
                    )
                    t2 = time.perf_counter()
                    if not meta.get("detach") \
                            and "query_id" in resp:
                        session_qids.append(resp["query_id"])
                    await _send_json(writer, resp)
                elif verb == wire.VERB_FETCH:
                    qid = await _read_str(reader)
                    timeout_ms = await _read_u32(reader)
                    t1 = time.perf_counter()
                    await backend.fetch_async(writer, qid, timeout_ms)
                    t2 = time.perf_counter()
                elif verb in wire._ID_VERBS:
                    qid = await _read_str(reader)
                    flags = await _read_u32(reader)
                    t1 = time.perf_counter()
                    resp = await loop.run_in_executor(
                        pool, partial(wire._ID_VERBS[verb], backend,
                                      qid, flags)
                    )
                    t2 = time.perf_counter()
                    await _send_json(writer, resp)
                elif verb == wire.VERB_MEMBER:
                    payload = json.loads(
                        await _read_str(reader) or "{}"
                    )
                    t1 = time.perf_counter()
                    resp = await loop.run_in_executor(
                        pool, partial(backend.member_frame, payload)
                    )
                    t2 = time.perf_counter()
                    await _send_json(writer, resp)
                elif verb == wire.VERB_PROFILE:
                    payload = json.loads(
                        await _read_str(reader) or "{}"
                    )
                    t1 = time.perf_counter()
                    resp = await loop.run_in_executor(
                        pool, partial(backend.profile_frame, payload)
                    )
                    t2 = time.perf_counter()
                    await _send_json(writer, resp)
                elif verb == wire.VERB_MESH_EXCHANGE:
                    # fleet DCN plane: mirror of the blocking branch -
                    # drain the framed input parts BEFORE dispatch so
                    # a handler error leaves the connection in sync
                    payload = json.loads(
                        await _read_str(reader) or "{}"
                    )
                    parts: List[bytes] = []
                    while True:
                        (plen,) = _U64.unpack(
                            await _read_exact(reader, _U64.size)
                        )
                        if plen == 0:
                            break
                        if plen > wire.MAX_EXCHANGE_PART_BYTES:
                            raise ValueError(
                                "oversized exchange part"
                            )
                        parts.append(await _read_exact(reader, plen))
                    t1 = time.perf_counter()
                    resp, out_parts = await loop.run_in_executor(
                        pool, partial(backend.mesh_exchange_frame,
                                      payload, parts)
                    )
                    t2 = time.perf_counter()
                    await _send_json(writer, resp)
                    for p in out_parts:
                        writer.write(_U64.pack(len(p)) + p)
                        await writer.drain()
                    writer.write(_U64.pack(0))
                    await writer.drain()
                elif verb in wire._NOARG_VERBS:
                    await _read_u32(reader)
                    t1 = time.perf_counter()
                    resp = await loop.run_in_executor(
                        pool, partial(wire._NOARG_VERBS[verb], backend)
                    )
                    t2 = time.perf_counter()
                    await _send_json(writer, resp)
                else:
                    raise ValueError(f"unknown service verb {verb}")
                wire._observe_verb(tier, verb, t0, t1, t2,
                                   time.perf_counter())
            except (ConnectionError, BrokenPipeError, OSError):
                return  # mid-verb disconnect: session cleanup below
            except ValueError as e:
                try:
                    await _send_json(
                        writer,
                        {"error": f"protocol error: {e}"[:65536],
                         "fatal": True},
                    )
                except (ConnectionError, OSError):
                    pass
                return
            except KeyError as e:
                await _send_json(
                    writer, {"error": f"unknown query: {e}"}
                )
            except Exception as e:  # noqa: BLE001 - reported in-band
                await _send_json(
                    writer,
                    {"error": f"{type(e).__name__}: {e}"[:65536]},
                )
    finally:
        with wire._CONN_LOCK:
            wire._CONNECTIONS[tier] = max(
                0, wire._CONNECTIONS.get(tier, 1) - 1
            )
        if session_qids:
            # session teardown off the loop: router abandons do a
            # downstream RPC and service cancels take locks - neither
            # may stall the selector. Fire-and-forget keeps teardown
            # running even if this task is being cancelled.
            qids = list(session_qids)

            def _abandon_all():
                for qid in qids:
                    try:
                        backend.abandon(qid)
                    except Exception:  # noqa: BLE001 - best-effort
                        pass

            try:
                pool.submit(_abandon_all)
            except RuntimeError:
                pass  # interpreter shutdown


# ---------------------------------------------------------------------------
# service-tier async FETCH (twin of ServiceVerbBackend._fetch_*)
# ---------------------------------------------------------------------------


async def service_fetch_async(backend, writer, qid: str,
                              timeout_ms: int) -> None:
    from blaze_tpu.service import wire

    try:
        q = backend.service.get(qid)
    except KeyError:
        await _send_err(writer, f"UNKNOWN: no query {qid}")
        return
    # bit 31 of timeout_ms: the client accepts an arena handle
    arena_ok = bool(timeout_ms & wire._FETCH_ARENA)
    timeout_ms &= wire._FETCH_ARENA - 1
    q.note_activity()
    q.begin_fetch()
    try:
        if await _serve_arena_async(backend, writer, q, arena_ok):
            return
        sb = getattr(q, "stream", None)
        if sb is not None:
            await _fetch_incremental_async(
                backend, writer, q, sb, timeout_ms
            )
        else:
            await _fetch_materialized_async(
                backend, writer, q, timeout_ms
            )
    finally:
        q.end_fetch()
        q.note_activity()


async def _serve_arena_async(backend, writer, q,
                             arena_ok: bool) -> bool:
    """Coroutine twin of ServiceVerbBackend._serve_arena: zero-copy
    FETCH of a finalized result. Handle mode writes the arena escape
    frame; scatter-gather mode writes the segment's mmap-backed frame
    views straight into the transport (one drain at the end - the
    frames already carry the wire framing, so no re-encode and no
    per-part drain round trips). Returns False having sent NOTHING
    whenever the arena does not cover the query."""
    from blaze_tpu.service import wire
    from blaze_tpu.service.query import QueryState

    service = backend.service
    arena = getattr(service, "arena", None)
    if (
        arena is None or not q.done
        or q.state is not QueryState.DONE
        or q._fingerprint is None or not q._fingerprint_stable
        or not q.use_cache or q.degraded
    ):
        return False
    key = q._fingerprint
    loop = asyncio.get_running_loop()
    pool = dispatch_pool(getattr(backend, "tier", "service"))
    stream_start = time.monotonic()
    if arena_ok:
        # handle() reaps orphaned leases under the arena lock - keep
        # it off the selector like every other lock-shaped call
        handle = await loop.run_in_executor(
            pool, partial(arena.handle, key)
        )
        if handle is not None:
            data = json.dumps(handle).encode("utf-8")
            writer.write(
                _U64.pack(wire._ARENA) + _U32.pack(len(data)) + data
            )
            await writer.drain()
            q.fetched = True
            wire.ServiceVerbBackend._note_arena_stream(
                backend, q, stream_start, len(handle["offsets"]),
                mode="handle",
            )
            return True
    views = arena.buffers(key)
    if views is None:
        return False
    if chaos.ACTIVE:
        # same contract as the threaded path: the whole buffer list
        # goes out in one burst, so the seam fires once up front
        await loop.run_in_executor(
            pool, partial(chaos.fire, "gateway.stream",
                          query_id=q.query_id, partition=0),
        )
    # write() either sends immediately or copies into the transport
    # buffer before returning, so the views never outlive this call -
    # safe against a concurrent eviction unmapping the segment
    for v in views:
        writer.write(v)
    writer.write(_U64.pack(0))
    await writer.drain()
    q.fetched = True
    q.note_activity()
    wire.ServiceVerbBackend._note_arena_stream(
        backend, q, stream_start, len(views), mode="sg"
    )
    return True


async def _fetch_incremental_async(backend, writer, q, sb,
                                   timeout_ms: int) -> None:
    """Stream-as-produced FETCH on the loop. Ready-part probes are
    non-blocking (`next_ready(i, 0.0)`); between parts the coroutine
    parks on an asyncio.Event fired by the ring's waker bridge
    (StreamBuffer.add_waker -> call_soon_threadsafe), with the
    probe-clear-reprobe-await pattern closing the lost-wakeup window.
    Slow clients park in `drain()` against the stall budget instead of
    a socket send timeout - same classified outcome, no thread."""
    from blaze_tpu.io.ipc import encode_ipc_segment

    service = backend.service
    qid = q.query_id
    loop = asyncio.get_running_loop()
    deadline = (
        time.monotonic() + timeout_ms / 1000.0
        if timeout_ms else None
    )
    sb.attach()
    ev = asyncio.Event()

    def _waker():
        try:
            loop.call_soon_threadsafe(ev.set)
        except RuntimeError:
            pass  # loop torn down at interpreter exit

    sb.add_waker(_waker)
    t0 = time.perf_counter_ns()
    stream_start = time.monotonic()
    sent = 0
    live_parts = 0
    complete = False
    stall_s = getattr(service, "stream_stall_s", 0.0) or 0.0
    try:
        i = 0
        while True:
            if sent == 0 and deadline is not None:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    await _send_err(
                        writer, f"{q.state.value}: fetch timed out"
                    )
                    return
            kind, payload = sb.next_ready(i, 0.0)
            if kind == "timeout":
                # nothing ready: clear, re-probe (a wake between the
                # probe and the clear must not be lost), then park on
                # the waker - bounded so deadline/abort checks and the
                # sync tier's 0.25s cadence are preserved
                ev.clear()
                kind, payload = sb.next_ready(i, 0.0)
                if kind == "timeout":
                    wait_s = 0.25
                    if sent == 0 and deadline is not None:
                        wait_s = min(
                            0.25, max(0.0, deadline - time.monotonic())
                        )
                    try:
                        await asyncio.wait_for(ev.wait(), wait_s)
                    except asyncio.TimeoutError:
                        pass
                    continue
            if kind == "part":
                if chaos.ACTIVE:
                    # chaos seam, same ordering as the threaded loop:
                    # fire BEFORE mark_consumed so a DROP leaves the
                    # part for the resume path. STALL sleeps must not
                    # block the loop -> executor
                    await loop.run_in_executor(
                        dispatch_pool(),
                        partial(chaos.fire, "gateway.stream",
                                query_id=qid, partition=i),
                    )
                if not q.done:
                    live_parts += 1
                sb.mark_consumed(i)
                writer.write(encode_ipc_segment(payload))
                try:
                    if stall_s > 0:
                        await asyncio.wait_for(writer.drain(), stall_s)
                    else:
                        await writer.drain()
                except (asyncio.TimeoutError, TimeoutError) as e:
                    service._note_stream_event("stall")
                    raise ConnectionError(
                        f"fetch send stalled past {stall_s}s"
                    ) from e
                sent += 1
                i += 1
                q.note_activity()
                continue
            if kind == "finished":
                writer.write(_U64.pack(0))
                await writer.drain()
                complete = True
                q.fetched = True
                return
            # aborted: same contract as the threaded loop - abort the
            # connection after parts, else wait out the tiny
            # abort->terminal window and answer in-band
            if sent:
                raise ConnectionError(
                    f"fetch stream aborted: {payload}"
                )
            abort_deadline = time.monotonic() + 5.0
            while not q.wait(0) \
                    and time.monotonic() < abort_deadline:
                await asyncio.sleep(0.02)
            await _send_err(
                writer,
                f"{q.state.value}: {q.error or 'not completed'}",
            )
            return
    finally:
        sb.remove_waker(_waker)
        stream_s = (time.perf_counter_ns() - t0) / 1e9
        q.timings["stream_ns"] = (
            q.timings.get("stream_ns", 0)
            + (time.perf_counter_ns() - t0)
        )
        if complete and getattr(service, "_fold_phases", True):
            from blaze_tpu.obs import phases as obs_phases

            obs_phases.ROLLUP.observe(
                "stream", stream_s,
                klass=obs_phases.class_key(
                    q._fingerprint, q._fingerprint_stable
                ),
            )
        if obs_trace.ACTIVE \
                and getattr(q, "tracer", None) is not None:
            tags = {"parts": sent, "total": sb.total_parts(),
                    "live_parts": live_parts}
            if not complete:
                tags["aborted"] = True
            q.tracer.record_span(
                "result_stream", stream_start, time.monotonic(),
                **tags,
            )


async def _fetch_materialized_async(backend, writer, q,
                                    timeout_ms: int) -> None:
    """Legacy materialize-then-stream FETCH (stream_buffer_bytes <= 0)
    on the loop: the DONE wait is an adaptive poll (no thread parked),
    the part loop is drain-aware."""
    from blaze_tpu.io.ipc import encode_ipc_segment
    from blaze_tpu.service.query import QueryState

    service = backend.service
    qid = q.query_id
    deadline = (
        time.monotonic() + timeout_ms / 1000.0
        if timeout_ms else None
    )
    loop = asyncio.get_running_loop()
    poll = 0.001
    while not q.wait(0):
        if deadline is not None and time.monotonic() >= deadline:
            await _send_err(
                writer, f"{q.state.value}: fetch timed out"
            )
            return
        await asyncio.sleep(poll)
        poll = min(0.05, poll * 2)
    if q.state is not QueryState.DONE:
        await _send_err(
            writer, f"{q.state.value}: {q.error or 'not completed'}"
        )
        return
    t0 = time.perf_counter_ns()
    stream_start = time.monotonic()
    sent = 0
    complete = False
    try:
        for i, rb in enumerate(q.result or ()):
            if chaos.ACTIVE:
                await loop.run_in_executor(
                    dispatch_pool(),
                    partial(chaos.fire, "gateway.stream",
                            query_id=qid, partition=i),
                )
            writer.write(encode_ipc_segment(rb))
            await writer.drain()
            sent += 1
            q.note_activity()
        writer.write(_U64.pack(0))
        await writer.drain()
        complete = True
        q.fetched = True
    except Exception as e:
        raise ConnectionError(f"fetch stream aborted: {e!r}") from e
    finally:
        stream_s = (time.perf_counter_ns() - t0) / 1e9
        q.timings["stream_ns"] = (
            q.timings.get("stream_ns", 0)
            + (time.perf_counter_ns() - t0)
        )
        if complete and getattr(service, "_fold_phases", True):
            from blaze_tpu.obs import phases as obs_phases

            obs_phases.ROLLUP.observe(
                "stream", stream_s,
                klass=obs_phases.class_key(
                    q._fingerprint, q._fingerprint_stable
                ),
            )
        if obs_trace.ACTIVE \
                and getattr(q, "tracer", None) is not None:
            tags = {"parts": sent, "total": len(q.result or ())}
            if not complete:
                tags["aborted"] = True
            q.tracer.record_span(
                "result_stream", stream_start, time.monotonic(),
                **tags,
            )


# ---------------------------------------------------------------------------
# connection routing + the shared listener
# ---------------------------------------------------------------------------


async def _sock_recv_exact(loop, conn, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        b = await loop.sock_recv(conn, n - len(buf))
        if not b:
            raise ConnectionError("socket closed mid-frame")
        buf += b
    return buf


def _run_legacy(legacy, conn, header: int) -> None:
    try:
        legacy(conn, header)
    finally:
        try:
            conn.close()
        except OSError:
            pass


async def handle_wire_connection(
    conn,
    *,
    backend_factory: Optional[Callable[[], object]],
    legacy: Optional[Callable] = None,
    no_service_msg: bytes = b"no query service attached",
    no_legacy_msg: bytes = b"router speaks the service protocol only",
) -> None:
    """Read the hello u64 off an accepted socket and route it: the
    _FLAG_SERVICE bit enters the async verb loop against
    `backend_factory()`; a legacy header hands the (re-blocked) socket
    to `legacy(sock, header)` on a daemon thread - one-shot task
    execution is thread-shaped work. `None` for either side answers
    the documented error frame."""
    from blaze_tpu.runtime.gateway import _FLAG_SERVICE

    loop = asyncio.get_running_loop()
    t_accept = time.perf_counter()
    try:
        try:
            (header,) = _U64.unpack(
                await _sock_recv_exact(loop, conn, _U64.size)
            )
        except (ConnectionError, OSError):
            conn.close()
            return
        if header & _FLAG_SERVICE:
            backend = (
                backend_factory() if backend_factory is not None
                else None
            )
            if backend is None:
                try:
                    await loop.sock_sendall(
                        conn,
                        _U64.pack(_ERR)
                        + _U32.pack(len(no_service_msg))
                        + no_service_msg,
                    )
                except (ConnectionError, OSError):
                    pass
                conn.close()
                return
            reader, writer = await asyncio.open_connection(sock=conn)
            try:
                await serve_verb_connection_async(
                    reader, writer, backend, t_accept=t_accept
                )
            finally:
                # close only - no await here: this finally also runs
                # under GeneratorExit (task GC'd / cancelled at server
                # stop), where suspending again is illegal; the loop
                # outlives the connection and completes the close
                try:
                    writer.close()
                except Exception:  # noqa: BLE001 - teardown
                    pass
            return
        if legacy is None:
            try:
                await loop.sock_sendall(
                    conn,
                    _U64.pack(_ERR) + _U32.pack(len(no_legacy_msg))
                    + no_legacy_msg,
                )
            except (ConnectionError, OSError):
                pass
            conn.close()
            return
        conn.setblocking(True)
        threading.Thread(
            target=_run_legacy, args=(legacy, conn, header),
            daemon=True, name="blaze-legacy-task",
        ).start()
    except asyncio.CancelledError:
        try:
            conn.close()
        except OSError:
            pass
        raise
    except Exception:  # noqa: BLE001 - a bad connection dies alone
        try:
            conn.close()
        except OSError:
            pass


class AsyncWireServer:
    """Event-loop listener with the TaskGatewayServer surface: binds
    in __init__ (so `.address` answers before start), accepts on the
    process loop, one task per connection. `conn_handler` is an async
    callable taking the accepted (non-blocking) socket."""

    def __init__(self, host: str, port: int, conn_handler):
        self._handler = conn_handler
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind((host, port))
        lsock.listen(128)
        lsock.setblocking(False)
        self._lsock = lsock
        self._accept_task: Optional[asyncio.Task] = None
        self._tasks: set = set()
        self._stopped = threading.Event()
        self._started = False

    @property
    def address(self):
        return self._lsock.getsockname()

    def start(self) -> "AsyncWireServer":
        if self._started:
            return self
        self._started = True
        fut = asyncio.run_coroutine_threadsafe(self._arm(), get_loop())
        fut.result(timeout=10)
        return self

    async def _arm(self) -> None:
        self._accept_task = asyncio.get_running_loop().create_task(
            self._accept_loop()
        )

    async def _accept_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            try:
                conn, _addr = await loop.sock_accept(self._lsock)
            except asyncio.CancelledError:
                return
            except OSError:
                return  # listener closed
            t = loop.create_task(self._handler(conn))
            self._tasks.add(t)
            t.add_done_callback(self._tasks.discard)

    def serve_blocking(self) -> None:
        """CLI shape: block the calling thread until shutdown(). The
        accept loop always lives on the wire loop, so (unlike the
        threaded server) this composes with start()."""
        self.start()
        self._stopped.wait()

    def shutdown(self) -> None:
        """Stop accepting without closing the listener (the drain
        path); live connection tasks keep serving until EOF, matching
        the threaded tier's daemon threads."""
        if self._started and self._accept_task is not None:
            async def _cancel():
                self._accept_task.cancel()
                try:
                    await self._accept_task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass

            try:
                asyncio.run_coroutine_threadsafe(
                    _cancel(), get_loop()
                ).result(timeout=5)
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        self._stopped.set()

    def stop(self) -> None:
        self.shutdown()
        # reap live connection tasks: a clean CancelledError now beats
        # a pending task garbage-collected later (whose coroutine gets
        # closed at an arbitrary suspension point)
        tasks = list(self._tasks)
        if tasks:
            async def _reap():
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)

            try:
                asyncio.run_coroutine_threadsafe(
                    _reap(), get_loop()
                ).result(timeout=5)
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        try:
            self._lsock.close()
        except OSError:
            pass
