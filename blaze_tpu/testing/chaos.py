"""Deterministic fault injection: prove the recovery paths work.

The reference engine's robustness story (native->Spark fallback, Spark
task retries - SURVEY 5.3) is exercised by Spark's own chaos: executor
loss, fetch failures, OOM kills. A standalone engine has none of that
ambient chaos, so nothing exercises its retry/degrade/cancel paths
until production does. This module closes that gap: a seeded,
config-activated `FaultPlan` fires named faults at real seams in the
runtime, so every recovery path has a deterministic test.

Design constraints:

  * Production pays ~nothing when chaos is off: every injection point
    is guarded by `if chaos.ACTIVE:` - one module-attribute load and a
    falsy branch. No fault objects are consulted, no strings built.
    (tests/test_dispatch_budget.py pins that chaos-off runs add zero
    dispatches; the hook cannot dispatch by construction.)
  * Determinism: a FaultPlan is seeded; `probability` draws are keyed
    (seed, fault, partition, occurrence) so outcomes do not depend on
    thread interleaving under the parallel scheduler, and the
    fired-fault journal lets tests assert exactly which faults fired
    where.
  * Classification: injected faults raise the same classified
    exceptions (blaze_tpu.errors) the real failures would, so the
    taxonomy path under test is the production path.

Injection sites (each named in docs/ROBUSTNESS.md):

  task.execute      executor.execute_partition entry (any class - the
                    generic "this partition fails" seam)
  parquet.decode    per file-range open in ParquetScanExec.execute
  h2d.transfer      runtime/pack.py put_packed host->device staging
  kernel.dispatch   every compiled-kernel invocation (dispatch.py)
  device.memory     DeviceMemoryTracker.track (HBM accounting)
  gateway.stream    per result part in the service FETCH send loop -
                    with incremental delivery (service/stream.py) the
                    window now covers IN-PROGRESS streams: a fault at
                    partition k can land while the query is still
                    RUNNING, not just on a finished result
  stream.consume    per result part on the CLIENT side of a FETCH
                    (ServiceClient._fetch_parts, after the part is in
                    hand): STALL = a slow consumer holding producer
                    backpressure, DROP = the client connection dying
                    mid-read (resume/re-FETCH paths)
  cache.spill       ResultCache spill-to-disk write
  cluster.heartbeat worker heartbeat tick (STALL silences liveness)
  service.admit     QueryService._run_query before the RUNNING
                    transition (STALL widens the ADMITTED->RUNNING
                    race window for cancellation tests)
  mesh.exchange     before every mesh-tier program launch
                    (parallel/mesh_exec.py): TRANSIENT propagates to
                    the task-retry tier, any other class degrades the
                    op to its single-device fallback plan
  router.membership every MEMBER frame the router handles
                    (router/proxy.py): DROP = a JOIN/LEAVE whose ack
                    never reaches the replica (the announcer's next
                    tick retries), STALL = a slow membership
                    authority widening join/leave race windows
  router.journal    the durable routing journal (router/journal.py)
                    and the recovery pass (router/proxy.py), keyed by
                    the `op` context value: op=append DROP tears the
                    record mid-write (the crash-at-the-worst-moment
                    replay test), op=fsync STALL = slow disk under
                    the batched flusher, op=reconcile_poll DROP = a
                    recovery POLL that never reaches the journaled
                    replica (the pass retries next tick)
  zerocopy.map      every mmap on the zero-copy serve path: arena
                    segment publish (zerocopy/arena.py), client-side
                    handle mapping (map_handle_frames), and the
                    parquet page-buffer mmap (io/object_store.py).
                    Any raise degrades that call to the socket/read
                    byte path - zero client-visible failures
  zerocopy.lease    arena lease grant (ArrowArena.handle) and the
                    client's post-map staleness check: a raise makes
                    the server answer bytes instead of a handle, or
                    the client treat its handle as a stale lease and
                    re-FETCH on the byte path
  service.tenant    the tenant budget check in QueryService._enqueue
                    (ctx: tenant, query): DROP = the budget check
                    itself fails and the submit is rejected
                    REJECTED_TENANT_BUDGET (fail CLOSED - an
                    isolation layer that fails open under stress
                    protects nobody), STALL = a slow budget path
                    widening the admission window (noisy-neighbor
                    chaos in tests/test_tenancy.py)

Activation: programmatic `install()`/`active()` (tests), or the
BLAZE_CHAOS environment variable carrying the plan as JSON - worker
subprocesses inherit it, so cluster-level faults need no RPC:

  BLAZE_CHAOS='{"seed": 7, "faults": [
      {"site": "task.execute", "klass": "TRANSIENT",
       "partition": 3, "times": 1}]}'
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from contextlib import contextmanager
from random import Random
from typing import Any, Dict, List, Optional

from blaze_tpu.errors import (
    PlanInvalidError,
    ResourceExhaustedError,
    TransientError,
)

# fast gate: injection points check this single module attribute and
# fall through when False (the chaos-off production path)
ACTIVE = False
_PLAN: Optional["FaultPlan"] = None


class InjectedTransient(TransientError):
    pass


class InjectedResourceExhausted(ResourceExhaustedError):
    pass


class InjectedPlanInvalid(PlanInvalidError):
    pass


class InjectedDrop(ConnectionError):
    """Wire-level drop: the socket tier treats it like a peer reset."""


_RAISES = {
    "TRANSIENT": InjectedTransient,
    "RESOURCE_EXHAUSTED": InjectedResourceExhausted,
    "PLAN_INVALID": InjectedPlanInvalid,
    "DROP": InjectedDrop,
}


@dataclasses.dataclass
class Fault:
    """One named fault: where it fires, what it raises, how often.

    klass: TRANSIENT | RESOURCE_EXHAUSTED | PLAN_INVALID | DROP | STALL
    times: fire count (0 = unlimited)
    partition: only fire when the site reports this partition
    match: substring that must appear in one of the site's context
      values (e.g. a file path or query id)
    probability: seeded per-candidate draw (1.0 = always)
    stall_s: sleep duration for STALL faults
    """

    site: str
    klass: str = "TRANSIENT"
    times: int = 1
    partition: Optional[int] = None
    match: Optional[str] = None
    probability: float = 1.0
    stall_s: float = 0.1

    def __post_init__(self):
        if self.klass not in _RAISES and self.klass != "STALL":
            raise ValueError(f"unknown fault class {self.klass!r}")


class FaultPlan:
    """A seeded set of faults plus the journal of what actually fired."""

    def __init__(self, faults: List[Fault], seed: int = 0):
        self.seed = seed
        self.faults = list(faults)
        self._remaining = [f.times for f in self.faults]
        # per-(fault, partition) candidate counters: probability draws
        # are keyed (seed, fault index, partition, occurrence) so the
        # outcome for "the Nth time fault i considers partition p" is
        # stable regardless of thread interleaving under the parallel
        # scheduler
        self._draw_counts: Dict[tuple, int] = {}
        self._lock = threading.Lock()
        self.journal: List[Dict[str, Any]] = []

    def fire(self, site: str, **ctx: Any) -> None:
        """Raise/stall if a fault matches this site+context; no-op
        otherwise. Thread-safe; `times` is consumed exactly once per
        firing even under concurrent sites."""
        chosen: Optional[Fault] = None
        with self._lock:
            for i, f in enumerate(self.faults):
                if f.site != site:
                    continue
                if (
                    f.partition is not None
                    and ctx.get("partition") != f.partition
                ):
                    continue
                if f.match is not None and not any(
                    f.match in str(v) for v in ctx.values()
                ):
                    continue
                if f.times and self._remaining[i] <= 0:
                    continue
                if f.probability < 1.0:
                    part = ctx.get("partition")
                    part = -1 if part is None else int(part)
                    dk = (i, part)
                    n = self._draw_counts.get(dk, 0)
                    self._draw_counts[dk] = n + 1
                    mix = (
                        (self.seed & 0xFFFFFFFF) << 32
                    ) ^ (i << 24) ^ ((part & 0xFFFF) << 8) ^ n
                    if Random(mix).random() > f.probability:
                        continue
                if f.times:
                    self._remaining[i] -= 1
                self.journal.append(
                    {"site": site, "klass": f.klass, **ctx}
                )
                chosen = f
                break
        if chosen is None:
            return
        # observability: a fired fault lands in the active trace as a
        # span event carrying the plan seed, so an exported trace
        # explains WHY an attempt failed (docs/OBSERVABILITY.md)
        from blaze_tpu.obs import trace as obs_trace

        if obs_trace.ACTIVE:
            obs_trace.event(
                "chaos.fault", site=site, klass=chosen.klass,
                seed=self.seed,
                **{k: str(v) for k, v in ctx.items()},
            )
        if chosen.klass == "STALL":
            time.sleep(chosen.stall_s)
            return
        raise _RAISES[chosen.klass](
            f"chaos[{site}] injected {chosen.klass}"
            + (f" (partition {ctx['partition']})"
               if "partition" in ctx else "")
        )

    def fired(self, site: Optional[str] = None) -> int:
        with self._lock:
            return sum(
                1 for j in self.journal
                if site is None or j["site"] == site
            )


def install(plan: FaultPlan) -> None:
    global ACTIVE, _PLAN
    _PLAN = plan
    ACTIVE = True


def uninstall() -> None:
    global ACTIVE, _PLAN
    ACTIVE = False
    _PLAN = None


def current() -> Optional[FaultPlan]:
    return _PLAN


def fire(site: str, **ctx: Any) -> None:
    """Injection-point entry. Callers gate on `chaos.ACTIVE` first so
    the off path never enters this function."""
    p = _PLAN
    if p is not None:
        p.fire(site, **ctx)


@contextmanager
def active(faults: List[Fault], seed: int = 0):
    """Install a FaultPlan for the duration of a `with` block,
    restoring whatever was installed before (nesting-safe). The
    caller's seed is shifted by the BLAZE_CHAOS_SEED_OFFSET sweep
    hook (see seed_offset)."""
    prev = _PLAN
    plan = FaultPlan(faults, seed=seed + seed_offset())
    install(plan)
    try:
        yield plan
    finally:
        if prev is None:
            uninstall()
        else:
            install(prev)


def seed_offset() -> int:
    """Seed-sweep hook (`run_tests.py --chaos --seeds N`): a nonzero
    BLAZE_CHAOS_SEED_OFFSET shifts the seed of every FaultPlan
    installed through `active()`, so the same chaos suite hunts race
    regressions under N different probabilistic firing sequences
    instead of the one fixed seed baked into each test. A UNIFORM
    shift preserves the suite's seed invariants (same seed -> same
    sequence, different seeds -> different sequences). Explicit
    BLAZE_CHAOS env plans are deliberately exempt: their seed is part
    of a cross-process contract the installing test asserts on."""
    try:
        return int(os.environ.get("BLAZE_CHAOS_SEED_OFFSET", "0"))
    except ValueError:
        return 0


def plan_from_json(text: str) -> FaultPlan:
    cfg = json.loads(text)
    faults = [Fault(**f) for f in cfg.get("faults", ())]
    return FaultPlan(faults, seed=int(cfg.get("seed", 0)))


def _maybe_activate_from_env() -> None:
    spec = os.environ.get("BLAZE_CHAOS")
    if spec:
        install(plan_from_json(spec))


_maybe_activate_from_env()
