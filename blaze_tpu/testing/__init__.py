"""Test-support subsystems that ship inside the engine package.

chaos.py - deterministic fault injection (the chaos harness). Lives in
the production package (not tests/) because the injection points are
threaded through the runtime and the hooks must be importable wherever
the engine runs - including cluster worker subprocesses, which inherit
a fault plan through the BLAZE_CHAOS environment variable.
"""
