"""Per-fingerprint runtime history: the input deadline prediction and
replica routing need.

The result cache (service/cache.py) already keys materialized results
by content-addressed plan fingerprint; this store records execution
TIME under the same key, so the serving tier can answer "how long does
this plan usually take" before running it. Consumers today:

  * predicted-unmeetability shedding (service/service.py): at
    admission, queue-wait already spent + the fingerprint's p50
    estimate vs the query's remaining slack - a query that cannot
    make its deadline is shed with a distinct `shed_predicted`
    counter instead of burning device time to miss it anyway;
  * STATS: `runtime_history` summary, the machine-readable form the
    ROADMAP's replica-routing item consumes (route big fingerprints
    to the replica with headroom);
  * the slow-query log: "this query was 40x its p50" beats "this
    query took 8s".

Bounded on both axes: at most `max_fingerprints` entries (LRU) and
`samples_per_fp` samples each (ring) - a long-lived server's history
cost is a few hundred KB, forever. Estimates require >= min_samples
(default 3) so one cold-compile outlier never sheds real traffic.
Degraded (host-engine) runs are never recorded: they measure the
fallback, not the plan.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, Optional


class RuntimeHistory:
    """Bounded per-fingerprint execution-time samples + percentiles."""

    def __init__(self, max_fingerprints: int = 512,
                 samples_per_fp: int = 64):
        self.max_fingerprints = int(max_fingerprints)
        self.samples_per_fp = int(samples_per_fp)
        self._lock = threading.Lock()
        self._samples: "collections.OrderedDict[str, collections.deque]" = (
            collections.OrderedDict()
        )
        self._totals: Dict[str, int] = {}  # lifetime sample counts

    def record(self, fingerprint: str, seconds: float) -> None:
        if not fingerprint or seconds < 0:
            return
        with self._lock:
            dq = self._samples.get(fingerprint)
            if dq is None:
                dq = collections.deque(maxlen=self.samples_per_fp)
                self._samples[fingerprint] = dq
                while len(self._samples) > self.max_fingerprints:
                    old, _ = self._samples.popitem(last=False)
                    self._totals.pop(old, None)
            dq.append(float(seconds))
            self._samples.move_to_end(fingerprint)
            self._totals[fingerprint] = (
                self._totals.get(fingerprint, 0) + 1
            )

    @staticmethod
    def _percentile(sorted_xs, q: float) -> float:
        if not sorted_xs:
            return 0.0
        idx = min(len(sorted_xs) - 1,
                  max(0, int(round(q * (len(sorted_xs) - 1)))))
        return sorted_xs[idx]

    def estimate(self, fingerprint: str) -> Optional[Dict[str, Any]]:
        """{"n", "p50", "p95", "mean", "last"} or None when unseen."""
        with self._lock:
            dq = self._samples.get(fingerprint)
            if not dq:
                return None
            xs = sorted(dq)
            return {
                "n": len(xs),
                "p50": round(self._percentile(xs, 0.5), 6),
                "p95": round(self._percentile(xs, 0.95), 6),
                "mean": round(sum(xs) / len(xs), 6),
                "last": round(dq[-1], 6),
            }

    def p50(self, fingerprint: str,
            min_samples: int = 3) -> Optional[float]:
        """The shedding estimate: median runtime, or None below the
        sample floor (a single outlier must never shed traffic)."""
        with self._lock:
            dq = self._samples.get(fingerprint)
            if dq is None or len(dq) < max(1, min_samples):
                return None
            xs = sorted(dq)
            return self._percentile(xs, 0.5)

    def summary(self, top: int = 8) -> Dict[str, Any]:
        """STATS payload: store shape + the `top` hottest fingerprints
        (by lifetime samples) with their estimates."""
        with self._lock:
            fps = list(self._samples)
            total = sum(self._totals.get(f, 0) for f in fps)
            hottest = sorted(
                fps, key=lambda f: -self._totals.get(f, 0)
            )[:max(0, top)]
        return {
            "fingerprints": len(fps),
            "total_samples": total,
            "top": [
                # `fingerprint` stays display-truncated; `fp` carries
                # the FULL key so the replica router can join a
                # replica's reported p50s against the fingerprints it
                # learned from submit responses (prefix joins would
                # collide at fleet scale)
                {"fingerprint": f[:16],
                 "fp": f,
                 "samples": self._totals.get(f, 0),
                 **(self.estimate(f) or {})}
                for f in hottest
            ],
        }
