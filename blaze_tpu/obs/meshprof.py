"""Mesh stage anatomy: sub-phase attribution for the mesh dispatch
overhead.

BENCH_r10 measured `mesh_groupby_d8` at 6.57 s against 0.11 s for the
same 1M rows single-device - a ~60x per-stage overhead - and the
ROADMAP's multi-host tier (open item 2) is explicitly gated on saying
WHERE that time goes. Until this module, the whole stage was one
opaque `mesh_execute` span: host staging, single-flight serialization,
program re-trace, launch, and result fetch were indistinguishable.
Flare (PAPERS.md) lives or dies on where compilation cost lands
relative to execution; Data Path Fusion on host<->device movement
dominating analytical dispatch - this is the instrument that separates
those hypotheses for the mesh tier.

Every mesh stage is split into named sub-phases:

  mesh_lower     the planner pass (lower_plan_to_mesh) that decided to
                 lower this op - recorded at plan time, replayed into
                 the stage's span tree
  mesh_trace     jit/shard_map trace + XLA compile (AOT lower+compile
                 where the installed jax supports it; otherwise the
                 first launch folds the trace and this phase is ~0)
  mesh_stage_in  stack_partitions: host materialize + pad/stack +
                 device_put, with bytes staged
  mesh_launch    the compiled program call (the chaos `mesh.exchange`
                 seam fires at the top of this phase, so an injected
                 STALL lands here - it models exchange-fabric latency)
  mesh_sync      block_until_ready on the program outputs
  mesh_gather    the batched device_get at the mesh boundary

Design points (the trace.ACTIVE / chaos.ACTIVE discipline, adapted):

  * The sub-phase ROLLUP is ALWAYS ON, like the dispatch counters: a
    mesh stage is milliseconds-to-seconds of work and the cost here is
    a dozen monotonic clock reads, so there is no armed/off mode to
    keep byte-identical - the timing code is pure host control flow
    and cannot dispatch by construction
    (tests/test_dispatch_budget.py pins the budgets anyway).
  * Span emission stays gated on `trace.ACTIVE` + a live recorder:
    sub-phases land as child spans under `mesh_execute` on their own
    synthetic tid (validate_chrome-clean - they are sequential, so the
    per-track nesting sweep sees well-formed B/E pairs).
  * Re-trace detection is a process-wide seen-key registry:
    `note_trace` increments `blaze_mesh_trace_total{op}` on the first
    trace of a logical program and `blaze_mesh_retrace_total{op}` when
    the SAME logical program (op kind + structural expressions + arg
    signature) is traced again from a fresh op instance - the silent
    cache-key churn ISSUE 19 calls the likeliest hidden chunk of the
    60x. A warm repeat on one instance reuses its executable and
    records neither (the warm-repeat pin).
  * Bounded memory: at most `_MAX_OPS` op classes, fixed ring sizes,
    a capped trace-key registry.

Surfaces: `snapshot()` is the `meshprof` STATS section on both tiers;
a registered METRICS collector renders
`blaze_mesh_subphase_seconds_{sum,count}{op,subphase}` plus the stage
wall; `python -m blaze_tpu mesh-attr` drives `run_attr_probe` at d1
vs dN in fresh subprocesses and emits the versioned MESHATTR_r*.json
artifact whose sub-phase p50s must reconcile to the measured stage
wall (`build_doc` computes the gap attribution and the written
verdict ROADMAP item 2 needs).
"""

from __future__ import annotations

import collections
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Hashable, List, Optional, Tuple

# canonical sub-phase order (artifact + rendering stability).
SUBPHASES = (
    "mesh_lower",     # planner pass (outside the stage wall)
    "mesh_trace",
    "mesh_stage_in",
    "mesh_launch",
    "mesh_sync",
    "mesh_dcn",       # fleet tier: DCN exchange round trips
    "mesh_gather",
)

# the sub-phases INSIDE the stage wall (stage_in start -> gather end):
# these are what must reconcile - sum to the measured wall within
# tolerance. mesh_lower happens at plan time, before the wall opens.
STAGE_SUBPHASES = (
    "mesh_trace", "mesh_stage_in", "mesh_launch", "mesh_sync",
    "mesh_dcn", "mesh_gather",
)

_MAX_OPS = 16
_SAMPLES = 128
_MAX_TRACE_KEYS = 4096

# synthetic tid for the sub-phase track in exported traces (the mesh
# stage track is 999, per-device tracks 1000+; see parallel/mesh_exec)
MESH_SUB_TID = 998


class MeshStageRollup:
    """Bounded per-(op, sub-phase) duration rings + stage-wall ring +
    bytes-staged totals. Thread-safe; observed at stage end, never on
    a per-row path."""

    def __init__(self, max_ops: int = _MAX_OPS,
                 samples: int = _SAMPLES):
        self.max_ops = int(max_ops)
        self.samples = int(samples)
        self._lock = threading.Lock()
        # op -> {"wall": deque, "bytes": int, "stages": int,
        #        "sub": {subphase: deque}}
        self._ops: "collections.OrderedDict[str, Dict[str, Any]]" = (
            collections.OrderedDict()
        )

    def _slot(self, op: str) -> Dict[str, Any]:
        slot = self._ops.get(op)
        if slot is None:
            slot = self._ops[op] = {
                "wall": collections.deque(maxlen=self.samples),
                "bytes": 0, "stages": 0, "sub": {},
            }
            while len(self._ops) > self.max_ops:
                self._ops.popitem(last=False)
        self._ops.move_to_end(op)
        return slot

    def observe_stage(self, op: str, wall_s: float,
                      phases: List[Tuple[str, float, float]],
                      nbytes: int = 0) -> None:
        """Fold one finished mesh stage: its wall, each sub-phase
        duration, and the bytes staged in."""
        with self._lock:
            slot = self._slot(op)
            slot["wall"].append(float(wall_s))
            slot["bytes"] += int(nbytes)
            slot["stages"] += 1
            for name, p0, p1 in phases:
                if p1 < p0:
                    continue
                dq = slot["sub"].get(name)
                if dq is None:
                    dq = slot["sub"][name] = collections.deque(
                        maxlen=self.samples
                    )
                dq.append(p1 - p0)

    @staticmethod
    def _stats(xs: List[float]) -> Dict[str, Any]:
        xs = sorted(xs)

        def pct(q: float) -> float:
            idx = min(len(xs) - 1,
                      max(0, int(round(q * (len(xs) - 1)))))
            return xs[idx]

        return {
            "n": len(xs),
            "p50": round(pct(0.5), 6),
            "p95": round(pct(0.95), 6),
            "mean": round(sum(xs) / len(xs), 6),
        }

    def snapshot(self) -> Dict[str, Any]:
        """{op: {stages, bytes_staged, stage_wall: {n,p50,p95,mean},
        subphases: {name: {...}}}} - the `meshprof` STATS section and
        the attr-probe measurement form. Empty dict when no mesh
        stage ran."""
        with self._lock:
            ops = {
                op: {
                    "wall": list(slot["wall"]),
                    "bytes": slot["bytes"],
                    "stages": slot["stages"],
                    "sub": {n: list(dq)
                            for n, dq in slot["sub"].items() if dq},
                }
                for op, slot in self._ops.items()
            }
        out: Dict[str, Any] = {}
        for op, slot in ops.items():
            entry: Dict[str, Any] = {
                "stages": slot["stages"],
                "bytes_staged": slot["bytes"],
            }
            if slot["wall"]:
                entry["stage_wall"] = self._stats(slot["wall"])
            subs = {}
            for name in SUBPHASES:
                xs = slot["sub"].get(name)
                if xs:
                    subs[name] = self._stats(xs)
            if subs:
                entry["subphases"] = subs
            out[op] = entry
        return out

    def metrics_samples(self):
        """Prometheus samples: per-(op, subphase) seconds sum/count
        plus the stage wall - the METRICS-tier rendering of the same
        rings (collector surface: the stage hot path never touches
        the registry lock)."""
        with self._lock:
            ops = {
                op: {
                    "wall": list(slot["wall"]),
                    "sub": {n: list(dq)
                            for n, dq in slot["sub"].items()},
                }
                for op, slot in self._ops.items()
            }
        for op, slot in ops.items():
            if slot["wall"]:
                yield ("blaze_mesh_stage_wall_seconds_sum",
                       {"op": op}, round(sum(slot["wall"]), 6),
                       "counter")
                yield ("blaze_mesh_stage_wall_seconds_count",
                       {"op": op}, len(slot["wall"]), "counter")
            for name, xs in slot["sub"].items():
                if not xs:
                    continue
                yield ("blaze_mesh_subphase_seconds_sum",
                       {"op": op, "subphase": name},
                       round(sum(xs), 6), "counter")
                yield ("blaze_mesh_subphase_seconds_count",
                       {"op": op, "subphase": name}, len(xs),
                       "counter")

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._ops.clear()


# the process-wide rollup every mesh stage folds into (swappable via
# capture() for probe/bench measurement windows)
ROLLUP = MeshStageRollup()


@contextmanager
def capture():
    """Route stage folds into a PRIVATE rollup for the duration (the
    attr probe's and bench's measurement window), so a probe inside a
    live process neither pollutes nor reads production rollup state.
    Not re-entrant across threads: the swap is module-global."""
    global ROLLUP
    prev = ROLLUP
    ROLLUP = MeshStageRollup()
    try:
        yield ROLLUP
    finally:
        ROLLUP = prev


def _collector():
    return ROLLUP.metrics_samples()


def _register_collector() -> None:
    # keyed + idempotent, re-asserted on every stage finish: the test
    # registry reset clears collectors, and a stage is seconds of work
    # against one dict set
    from blaze_tpu.obs.metrics import REGISTRY

    REGISTRY.register_collector("meshprof", _collector)


# ---------------------------------------------------------------------------
# re-trace detection: first-trace vs avoidable re-trace
# ---------------------------------------------------------------------------

_trace_keys: set = set()
_tk_lock = threading.Lock()


def note_trace(op: str, key: Hashable) -> bool:
    """Record that `op`'s program was traced+compiled under logical
    identity `key` (op kind + structural expression trees + argument
    shape/dtype signature). Returns True - and increments
    `blaze_mesh_retrace_total{op}` - when this process already traced
    that identity (an AVOIDABLE re-trace: a fresh op instance re-paid
    compilation for a program the process had already built, i.e.
    cache-key churn). First traces count `blaze_mesh_trace_total{op}`.
    Call ONLY when a trace actually ran - a warm executable reuse
    records neither, which is exactly the warm-repeat delta-0 pin."""
    from blaze_tpu.obs.metrics import REGISTRY

    with _tk_lock:
        retrace = key in _trace_keys
        if not retrace:
            if len(_trace_keys) >= _MAX_TRACE_KEYS:
                _trace_keys.clear()  # bounded; worst case undercounts
            _trace_keys.add(key)
    REGISTRY.inc("blaze_mesh_trace_total", op=op)
    if retrace:
        REGISTRY.inc("blaze_mesh_retrace_total", op=op)
    return retrace


def arg_signature(*arrays) -> Tuple:
    """(shape, dtype) signature over a flat sequence of arrays (lists
    flatten one level) - the shape half of a trace key."""
    sig = []
    for a in arrays:
        if isinstance(a, (list, tuple)):
            sig.extend((tuple(x.shape), str(x.dtype)) for x in a)
        else:
            sig.append((tuple(a.shape), str(a.dtype)))
    return tuple(sig)


# ---------------------------------------------------------------------------
# the per-stage stopwatch
# ---------------------------------------------------------------------------


class _PhaseCtx:
    __slots__ = ("_stage", "_name", "_t0")

    def __init__(self, stage: "MeshStage", name: str):
        self._stage = stage
        self._name = name

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._stage.phases.append(
            (self._name, self._t0, time.monotonic())
        )
        return False


class MeshStage:
    """One mesh stage's sub-phase stopwatch. Always-on (see module
    docstring); `finish()` folds into the process rollup, span
    emission happens in mesh_exec.record_mesh_run where the tracer
    lives. The planner's mesh_lower window (stamped on the lowered op
    by lower_plan_to_mesh) replays into the phase list so it lands in
    the same span tree and rollup."""

    __slots__ = ("op", "n_dev", "t0", "t1", "phases", "bytes_staged")

    def __init__(self, op: str, n_dev: int,
                 lower_window: Optional[Tuple[float, float]] = None):
        self.op = op
        self.n_dev = n_dev
        self.t0 = time.monotonic()
        self.t1: Optional[float] = None
        self.phases: List[Tuple[str, float, float]] = []
        self.bytes_staged = 0
        if lower_window is not None:
            self.phases.append(
                ("mesh_lower", lower_window[0], lower_window[1])
            )

    def phase(self, name: str) -> _PhaseCtx:
        return _PhaseCtx(self, name)

    def add_bytes(self, n: int) -> None:
        self.bytes_staged += int(n)

    def finish(self, t1: Optional[float] = None) -> float:
        """Close the stage wall and fold into the process rollup.
        Returns the end timestamp (monotonic seconds)."""
        self.t1 = time.monotonic() if t1 is None else t1
        ROLLUP.observe_stage(
            self.op, self.t1 - self.t0, self.phases,
            nbytes=self.bytes_staged,
        )
        _register_collector()
        return self.t1


def stage(op: str, n_dev: int, lower_window=None) -> MeshStage:
    """Open one mesh stage's stopwatch (mesh_exec call sites)."""
    return MeshStage(op, n_dev, lower_window=lower_window)


def snapshot() -> Dict[str, Any]:
    """The `meshprof` STATS section (both tiers serve it)."""
    return ROLLUP.snapshot()


def _reset_for_tests() -> None:
    ROLLUP._reset_for_tests()
    with _tk_lock:
        _trace_keys.clear()


# ---------------------------------------------------------------------------
# the attribution probe (`mesh-attr` child) + MESHATTR doc builder
# ---------------------------------------------------------------------------


def run_attr_probe(n_dev: int, rows: int = 1 << 20,
                   iters: int = 4) -> Dict[str, Any]:
    """One device-count measurement for `mesh-attr`: build the bench
    `mesh_groupby` shape (8-partition MemoryScan under a FINAL /
    exchange / PARTIAL sandwich), lower it with mode="on", and run
    1 cold + `iters` warm rounds, collecting the sub-phase rollup,
    stage walls, trace/retrace counters, bytes staged, and the mesh
    single-flight lock's wait:hold. At 1 device the planner refuses
    to lower and the rounds time the file-shuffle sandwich instead -
    the single-device baseline wall the gap attribution needs.

    Expects the process device count to already match `n_dev` (the
    parent forces it via XLA_FLAGS before any backend init); runs
    against a PRIVATE rollup (capture()) plus contention accounting
    scoped to the probe window."""
    import tempfile

    import numpy as np

    import jax

    from blaze_tpu.batch import ColumnBatch
    from blaze_tpu.exprs import AggExpr, AggFn, Col
    from blaze_tpu.obs import contention
    from blaze_tpu.obs.metrics import REGISTRY
    from blaze_tpu.ops import (
        AggMode,
        HashAggregateExec,
        MemoryScanExec,
    )
    from blaze_tpu.planner.distribute import (
        insert_exchanges,
        lower_plan_to_mesh,
    )
    from blaze_tpu.runtime.executor import run_plan

    import pyarrow as pa

    assert len(jax.devices()) == n_dev, (
        f"expected {n_dev} devices, saw {len(jax.devices())} "
        "(the device count freezes at first backend init - run the "
        "probe in a fresh subprocess)"
    )
    n_parts = 8
    per = max(1, rows // n_parts)
    rng = np.random.default_rng(17)
    parts, schema = [], None
    for _ in range(n_parts):
        k = rng.integers(0, 4096, per).astype(np.int64)
        v = rng.integers(0, 1000, per).astype(np.int64)
        cb = ColumnBatch.from_arrow(pa.record_batch({"k": k, "v": v}))
        schema = cb.schema
        parts.append([cb])
    shuffle_dir = tempfile.mkdtemp(prefix="blaze_mesh_attr_")

    def sandwich():
        return insert_exchanges(
            HashAggregateExec(
                MemoryScanExec(parts, schema),
                keys=[(Col("k"), "k")],
                aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
                      (AggExpr(AggFn.COUNT_STAR, None), "n")],
                mode=AggMode.COMPLETE,
            ),
            n_parts, shuffle_dir=shuffle_dir,
        )

    lowered = lower_plan_to_mesh(sandwich(), mode="on")
    mesh_lowered = type(lowered).__name__ == "MeshGroupByExec"
    op_key = "mesh.groupby"
    trace0 = REGISTRY.get("blaze_mesh_trace_total", op=op_key)
    retrace0 = REGISTRY.get("blaze_mesh_retrace_total", op=op_key)

    def run_once():
        if mesh_lowered:
            lowered._result = None  # fresh execution, warm program
            return run_plan(lowered)
        return run_plan(sandwich())

    doc: Dict[str, Any] = {
        "n_devices": n_dev, "rows": per * n_parts, "iters": iters,
        "mesh_lowered": mesh_lowered,
    }
    contention.enable()
    try:
        with capture() as cold_rollup:
            t0 = time.perf_counter()
            run_once()  # cold: pays trace+compile
            cold_wall = time.perf_counter() - t0
        cold_snap = cold_rollup.snapshot().get(op_key, {})
        doc["cold"] = {
            "wall": round(cold_wall, 4),
            "subphases": {
                name: st["p50"] for name, st in
                (cold_snap.get("subphases") or {}).items()
            },
        }
        walls = []
        with capture() as rol:
            for _ in range(max(1, iters)):
                t0 = time.perf_counter()
                run_once()
                walls.append(time.perf_counter() - t0)
        warm_trace = REGISTRY.get("blaze_mesh_trace_total", op=op_key)
        warm_retrace = REGISTRY.get(
            "blaze_mesh_retrace_total", op=op_key
        )
        # re-trace demonstration: a FRESH lowering of the SAME logical
        # plan re-pays the trace the process already did - the
        # cache-key-churn cost the retrace counter exists to expose
        if mesh_lowered:
            relowered = lower_plan_to_mesh(sandwich(), mode="on")
            t0 = time.perf_counter()
            run_plan(relowered)
            doc["retrace_demo_wall"] = round(
                time.perf_counter() - t0, 4
            )
    finally:
        contention.disable()
    walls.sort()
    median = walls[len(walls) // 2]
    doc["wall"] = {
        "median": round(median, 4),
        "spread": round(
            (walls[-1] - walls[0]) / median, 3
        ) if median > 0 else 0.0,
        "k": len(walls),
    }
    snap = rol.snapshot().get(op_key)
    if mesh_lowered and snap:
        doc["subphases"] = snap.get("subphases") or {}
        doc["bytes_staged"] = snap.get("bytes_staged", 0)
        wall_stat = snap.get("stage_wall") or {}
        wall_p50 = wall_stat.get("p50", 0.0)
        sub_sum = sum(
            doc["subphases"].get(n, {}).get("p50", 0.0)
            for n in STAGE_SUBPHASES
        )
        doc["reconcile"] = {
            "wall_p50": round(wall_p50, 6),
            "subphase_sum": round(sub_sum, 6),
            "coverage": round(sub_sum / wall_p50, 4)
            if wall_p50 > 0 else 0.0,
        }
    doc["trace_total"] = int(
        REGISTRY.get("blaze_mesh_trace_total", op=op_key) - trace0
    )
    doc["retrace_total"] = int(
        REGISTRY.get("blaze_mesh_retrace_total", op=op_key) - retrace0
    )
    # warm-repeat pin data: trace delta across the warm rounds alone
    doc["warm_trace_delta"] = int(
        warm_trace - trace0
        - (1 if mesh_lowered else 0)  # the cold round's first trace
    )
    doc["warm_retrace_delta"] = int(warm_retrace - retrace0)
    lock = contention.snapshot().get("mesh_groupby")
    if lock:
        doc["lock"] = lock
    return doc


def build_doc(d1: Dict[str, Any], dn: Dict[str, Any]) -> Dict[str, Any]:
    """Fold the two child measurements into the MESHATTR_r*.json doc:
    per-sub-phase p50s (in regress-snapshot shape so `regress --bench`
    can diff consecutive rounds), the (dN - d1) stage-wall gap
    attribution, and the written verdict - which sub-phase dominates -
    that ROADMAP item 2 records."""
    n_dev = int(dn.get("n_devices", 0))
    d1_wall = float((d1.get("wall") or {}).get("median", 0.0))
    dn_wall = float((dn.get("wall") or {}).get("median", 0.0))
    subs = dn.get("subphases") or {}
    gap = dn_wall - d1_wall
    sub_sum = sum(
        subs.get(n, {}).get("p50", 0.0) for n in STAGE_SUBPHASES
    )
    # the stage's sub-phases cover the dN wall; the single-device wall
    # is the equivalent-work baseline, so the portion of the GAP the
    # named sub-phases explain is what they cover beyond that baseline
    attributed = max(0.0, min(sub_sum, dn_wall) - d1_wall)
    shares = {
        n: round(subs.get(n, {}).get("p50", 0.0) / dn_wall, 4)
        if dn_wall > 0 else 0.0
        for n in STAGE_SUBPHASES if n in subs
    }
    doc: Dict[str, Any] = {
        "format": "blaze-meshattr-v1",
        "rows": dn.get("rows"),
        "rounds": {"d1": d1, f"d{n_dev}": dn},
        "gap": {
            "d1_wall": round(d1_wall, 4),
            f"d{n_dev}_wall": round(dn_wall, 4),
            "gap_s": round(gap, 4),
            "ratio": round(dn_wall / d1_wall, 1)
            if d1_wall > 0 else None,
            "attributed_s": round(attributed, 4),
            "attributed_frac": round(attributed / gap, 4)
            if gap > 0 else None,
            "subphase_share_of_wall": shares,
        },
        # regress-snapshot shape: {class: {phase: {n, p50, ...}}} -
        # run_tests.py --smoke diffs the two most recent rounds of
        # THIS through the existing `regress --bench` path
        "phases": {"snapshot": {"_all": {
            **{n: st for n, st in subs.items()},
            **({"stage_wall": dn["reconcile"] and {
                "n": (dn.get("wall") or {}).get("k", 0),
                "p50": dn["reconcile"]["wall_p50"],
                "p95": dn["reconcile"]["wall_p50"],
                "mean": dn["reconcile"]["wall_p50"],
            }} if dn.get("reconcile") else {}),
        }}},
    }
    if subs and dn_wall > 0:
        ranked = sorted(
            ((n, subs[n]["p50"]) for n in STAGE_SUBPHASES
             if n in subs),
            key=lambda kv: -kv[1],
        )
        top, top_s = ranked[0]
        lock = dn.get("lock") or {}
        parts = [
            f"{top} dominates the d{n_dev} stage: "
            f"{top_s:.2f}s of the {dn_wall:.2f}s wall "
            f"({100 * top_s / dn_wall:.0f}%)"
        ]
        rest = ", ".join(
            f"{n} {100 * s / dn_wall:.0f}%" for n, s in ranked[1:]
        )
        if rest:
            parts.append(rest)
        parts.append(
            "warm re-trace "
            + ("avoided (delta 0)"
               if not dn.get("warm_retrace_delta")
               else f"x{dn['warm_retrace_delta']} - cache-key churn")
        )
        wh = lock.get("wait_hold_ratio")
        if wh is not None:
            parts.append(f"lock wait:hold {wh}")
        doc["verdict"] = "; ".join(parts)
    return doc


def next_round_path(dirpath: str) -> str:
    """Next MESHATTR_rNN.json in the versioned-artifact convention
    (MULTICHIP_r*/BENCH_r* siblings)."""
    import glob
    import os
    import re

    n = 0
    for p in glob.glob(os.path.join(dirpath, "MESHATTR_r*.json")):
        m = re.search(r"MESHATTR_r(\d+)\.json$", p)
        if m:
            n = max(n, int(m.group(1)))
    return os.path.join(dirpath, f"MESHATTR_r{n + 1:02d}.json")
