"""Structured slow-query log: one JSON line per offending query.

A query that blows its wall threshold emits a single machine-parseable
log line with everything a human (or a log pipeline) needs to triage
it without replaying: lifecycle phase durations, retry/degradation
flags, the per-phase span rollup from the trace, and the hottest
operators from the mirrored metric tree. One line, not a report -
slow-query logs get grepped and shipped, not read in place.

Threshold: QueryService(slow_query_s=...), default 5s, overridable via
BLAZE_SLOW_QUERY_S. Setting it <= 0 disables the log.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict

log = logging.getLogger("blaze_tpu.slowlog")


def build_payload(q, threshold_s: float) -> Dict[str, Any]:
    """Assemble the slow-query record from a terminal Query (split
    from emit() so tests and the REPORT surface can reuse it)."""
    t = q.timings
    finished = t.get("finished", t["submitted"])
    payload: Dict[str, Any] = {
        "event": "slow_query",
        "query_id": q.query_id,
        "state": q.state.value,
        "wall_s": round(finished - t["submitted"], 6),
        "threshold_s": threshold_s,
        "priority": q.priority,
    }
    if q._fingerprint is not None:
        payload["fingerprint"] = q._fingerprint[:16]
    phases: Dict[str, float] = {}
    if "admitted" in t:
        phases["queue_wait_s"] = round(t["admitted"] - t["submitted"], 6)
    if "run_start" in t and "admitted" in t:
        phases["admission_s"] = round(t["run_start"] - t["admitted"], 6)
    if "run_start" in t:
        phases["execution_s"] = round(finished - t["run_start"], 6)
    if "stream_ns" in t:
        phases["stream_s"] = round(t["stream_ns"] / 1e9, 6)
    payload["phases"] = phases
    retries = sum(1 for a in q.attempts if a.get("action") == "retry")
    if retries:
        payload["retries"] = retries
    if q.degraded:
        payload["degraded"] = True
    if q.error_class:
        payload["error_class"] = q.error_class
    if q.error:
        payload["error"] = str(q.error)[:300]
    tracer = getattr(q, "tracer", None)
    if tracer is not None:
        # per-span-name duration rollup: where inside execution the
        # time went (attempt / parquet_decode / h2d / kernel_dispatch
        # / cache_probe / host_degrade ...)
        rollup: Dict[str, Dict[str, float]] = {}
        for s in list(tracer.spans):
            if s.end_ns is None or s is tracer.root:
                continue
            r = rollup.setdefault(s.name, {"count": 0, "total_ms": 0.0})
            r["count"] += 1
            r["total_ms"] += (s.end_ns - s.start_ns) / 1e6
        payload["spans"] = {
            k: {"count": v["count"],
                "total_ms": round(v["total_ms"], 3)}
            for k, v in sorted(rollup.items())
        }
    try:
        from blaze_tpu.runtime.instrument import operator_summary

        ops = operator_summary(q.metrics_root, limit=5)
        if ops:
            payload["top_operators"] = ops
    except Exception:  # noqa: BLE001 - the log line must still emit
        pass
    return payload


def emit(q, threshold_s: float) -> None:
    try:
        payload = build_payload(q, threshold_s)
    except Exception:  # noqa: BLE001 - observability must not raise
        log.exception("slow-query payload build failed for %s",
                      getattr(q, "query_id", "?"))
        return
    log.warning("%s", json.dumps(payload, sort_keys=True))
