"""Per-phase duration rollup + regression detection.

BENCH_r*.json tracks end-to-end medians, and the ROADMAP's
"trace-driven regression hunting" note records exactly why that is not
enough: queue-wait creep and decode regressions hide inside a flat e2e
median (a 60ms decode slowdown is 4% of a 1.5s query - inside any
realistic noise band - but 10x the decode phase itself). The span
layer (obs/trace.py) already measures every phase of every query; this
module is the aggregation that makes those measurements diffable:

  * `PhaseRollup` folds each FINISHED query into bounded per-phase
    duration rings (queue_wait, admission, plan_decode, arrow_decode,
    h2d, dispatch, execute, stream, router, e2e) keyed by
    *fingerprint class* - the
    first 12 hex chars of the content-addressed plan fingerprint, the
    same identity the result cache and runtime history key on - plus
    the `_all` aggregate class that survives fingerprint drift across
    hosts. The fold is trace-driven where a trace exists (span-name ->
    phase map) and timings-driven where it does not, so obs-off
    serving still rolls up the lifecycle phases.
  * `compare()` diffs two rollup snapshots phase-by-phase with a
    noise band (relative factor + absolute floor, per-phase
    overridable) and returns the regressions - the machine check
    `python -m blaze_tpu regress` builds on.
  * `run_probe()` executes a small fixed workload through a real
    QueryService with tracing on and returns its rollup snapshot:
    the reproducible measurement behind `regress --against
    PHASE_BASELINE.json` (wired into `run_tests.py --smoke`) and
    `regress --emit-baseline`.

Bounded like obs/history.py: at most `max_classes` classes (LRU), at
most `samples_per_phase` samples per (class, phase) ring. The process-
wide instance is `ROLLUP`; the serving tier feeds it from the
exactly-once terminal hook, the router feeds the `router` phase from
its own hop spans, and STATS serves `snapshot()` so the regress CLI
can also diff a LIVE server.
"""

from __future__ import annotations

import collections
import json
import threading
from typing import Any, Dict, List, Optional

# canonical phase order (rendering + artifact stability)
PHASES = (
    "queue_wait",   # SUBMIT -> ADMITTED (admission queue)
    "admission",    # ADMITTED -> RUNNING (worker pickup)
    "plan_decode",  # SUBMIT protobuf -> decoded plan tree (skipped
                    # entirely on a decoded-plan-cache hit)
    "arrow_decode",  # parquet file-range decode (prefetch threads);
                     # pre-split rollups called this "decode"
    "h2d",          # packed host->device staging
    "dispatch",     # compiled-kernel launches
    "join",         # fused join-probe kernel launches
    "group",        # fused grouped-aggregate kernel launches
    # mesh stage anatomy (obs/meshprof.py): the sub-phases of one
    # mesh_execute stage, folded from its child spans when tracing
    "mesh_lower",     # planner pass (lower_plan_to_mesh)
    "mesh_trace",     # jit/shard_map trace + XLA compile
    "mesh_stage_in",  # stack_partitions host stack + device_put
    "mesh_launch",    # the compiled mesh program call
    "mesh_sync",      # block_until_ready on the outputs
    "mesh_gather",    # batched device_get at the mesh boundary
    "execute",      # RUNNING -> terminal (the whole execution)
    "stream",       # FETCH result streaming
    "router",       # router overhead (placement + submit hops)
    "e2e",          # SUBMIT -> terminal wall
)

# span name -> phase (the trace-driven fold); spans not named here
# (attempt, cache_probe, service_admit, ...) are structure, not phase
# cost - their time is already covered by execute/e2e
SPAN_PHASE = {
    "queue_wait": "queue_wait",
    "admission": "admission",
    "plan_decode": "plan_decode",
    "parquet_decode": "arrow_decode",
    "h2d": "h2d",
    "kernel_dispatch": "dispatch",
    "join_dispatch": "join",
    "group_dispatch": "group",
    "execute_partition": "execute",
    "result_stream": "stream",
    "router_place": "router",
    "router_stream": None,  # passthrough time is downstream-bound
    # mesh sub-phase spans fold under their own names (identity map):
    # the terminal hook's phase_totals sweep carries them into the
    # rollup whenever a traced query ran a mesh stage
    "mesh_lower": "mesh_lower",
    "mesh_trace": "mesh_trace",
    "mesh_stage_in": "mesh_stage_in",
    "mesh_launch": "mesh_launch",
    "mesh_sync": "mesh_sync",
    "mesh_gather": "mesh_gather",
}

ALL_CLASS = "_all"


def class_key(fingerprint: Optional[str],
              stable: bool = True,
              tenant: Optional[str] = None) -> str:
    """Fingerprint class: the rollup key. A short DIGEST of the
    content-addressed plan fingerprint (the fingerprint itself is a
    readable nested expression - its prefix is just the root
    operator's name and would fold every hash-aggregate into one
    class), or 'unstable' for plans without content identity. The
    full fingerprint stays in obs/history.

    Tenancy (ROADMAP item 5 follow-up): a NON-default tenant gets its
    own class dimension - `<digest>@<tenant>` - so one tenant's
    phase-duration drift is attributable without polluting another's
    rings. The default tenant's keys (and therefore
    PHASE_BASELINE.json, the regress probe, and every zero-config
    rollup) are unchanged, and the `_all` aggregate still folds every
    query regardless of tenant."""
    if not fingerprint or not stable:
        base = "unstable"
    else:
        import hashlib

        base = hashlib.blake2b(
            str(fingerprint).encode("utf-8"), digest_size=6
        ).hexdigest()
    if tenant and tenant != "default":
        return f"{base}@{tenant}"
    return base


class PhaseRollup:
    """Bounded per-(class, phase) duration rings with percentile
    snapshots. Thread-safe; folds are O(spans) at query-terminal time,
    never on the execution hot path."""

    def __init__(self, max_classes: int = 64,
                 samples_per_phase: int = 128):
        self.max_classes = int(max_classes)
        self.samples_per_phase = int(samples_per_phase)
        self._lock = threading.Lock()
        # class -> phase -> deque of seconds
        self._rings: "collections.OrderedDict[str, Dict[str, collections.deque]]" = (
            collections.OrderedDict()
        )
        self._folded = 0  # lifetime query count

    # -- write path ------------------------------------------------------
    def observe(self, phase: str, seconds: float,
                klass: str = ALL_CLASS) -> None:
        """Record one phase duration for one query under `klass` AND
        under the `_all` aggregate (unless klass IS the aggregate)."""
        if seconds < 0:
            return
        with self._lock:
            for k in ({klass, ALL_CLASS}):
                rings = self._rings.get(k)
                if rings is None:
                    rings = self._rings[k] = {}
                    while len(self._rings) > self.max_classes:
                        # never evict the aggregate class
                        for old in self._rings:
                            if old != ALL_CLASS:
                                del self._rings[old]
                                break
                self._rings.move_to_end(k)
                dq = rings.get(phase)
                if dq is None:
                    dq = rings[phase] = collections.deque(
                        maxlen=self.samples_per_phase
                    )
                dq.append(float(seconds))

    def fold_phases(self, durations: Dict[str, float],
                    klass: str = ALL_CLASS) -> None:
        """One query's phase durations (seconds), one ring sample per
        phase."""
        for phase, s in durations.items():
            if phase in PHASES and s is not None:
                self.observe(phase, s, klass=klass)
        with self._lock:
            self._folded += 1

    def fold_query(self, q) -> None:
        """Fold one FINISHED service Query: lifecycle phases from its
        monotonic timings, execution-interior phases (decode/h2d/
        dispatch) from its span tree when tracing was on. Called from
        the exactly-once terminal hook."""
        t = q.timings
        durations: Dict[str, float] = {}
        sub = t.get("submitted")
        fin = t.get("finished")
        if sub is not None and fin is not None:
            durations["e2e"] = fin - sub
        if "admitted" in t and sub is not None:
            durations["queue_wait"] = t["admitted"] - sub
        if "run_start" in t and "admitted" in t:
            durations["admission"] = t["run_start"] - t["admitted"]
        if fin is not None and "run_start" in t:
            durations["execute"] = fin - t["run_start"]
        if q.tracer is not None:
            # allocation-free span fold (TraceRecorder.phase_totals):
            # the terminal hook runs for EVERY query, and a
            # to_dicts() round trip here was the obs-overhead creep
            # BENCH_r08 caught (dict + tag/event copies per span,
            # discarded immediately)
            for phase, s in q.tracer.phase_totals(SPAN_PHASE).items():
                # timings stay authoritative for lifecycle phases
                durations.setdefault(phase, s)
        self.fold_phases(
            durations,
            klass=class_key(q._fingerprint, q._fingerprint_stable,
                            tenant=getattr(q, "tenant", None)),
        )

    # -- read path -------------------------------------------------------
    @staticmethod
    def _pct(xs: List[float], quantile: float) -> float:
        idx = min(len(xs) - 1,
                  max(0, int(round(quantile * (len(xs) - 1)))))
        return xs[idx]

    def snapshot(self, max_classes: Optional[int] = None
                 ) -> Dict[str, Any]:
        """{class: {phase: {n, p50, p95, mean}}} - the STATS payload
        and the regress artifact form. `_all` always included; other
        classes most-recently-touched first."""
        with self._lock:
            classes = list(self._rings)
            rings = {
                k: {ph: list(dq) for ph, dq in v.items() if dq}
                for k, v in self._rings.items()
            }
        ordered = [ALL_CLASS] if ALL_CLASS in rings else []
        ordered += [k for k in reversed(classes) if k != ALL_CLASS]
        if max_classes is not None:
            ordered = ordered[:max_classes]
        out: Dict[str, Any] = {}
        for k in ordered:
            phases = {}
            for ph in PHASES:
                xs = sorted(rings[k].get(ph, ()))
                if not xs:
                    continue
                phases[ph] = {
                    "n": len(xs),
                    "p50": round(self._pct(xs, 0.5), 6),
                    "p95": round(self._pct(xs, 0.95), 6),
                    "mean": round(sum(xs) / len(xs), 6),
                }
            if phases:
                out[k] = phases
        return out

    @property
    def folded(self) -> int:
        with self._lock:
            return self._folded

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._rings.clear()
            self._folded = 0


def fold_span_dicts(span_dicts) -> Dict[str, float]:
    """Sum one query's span durations into phase totals (seconds).
    Multiple spans of one phase (per-file decode, per-kernel dispatch)
    sum: the result is 'seconds this query spent in that phase'."""
    totals: Dict[str, float] = {}
    for d in span_dicts:
        phase = SPAN_PHASE.get(str(d.get("name", "")))
        if not phase:
            continue
        start, end = d.get("start_ns"), d.get("end_ns")
        if start is None or end is None or end < start:
            continue
        totals[phase] = totals.get(phase, 0.0) + (end - start) / 1e9
    return totals


# the process-wide rollup every tier feeds (service terminal hook,
# wire FETCH streaming, router hop spans)
ROLLUP = PhaseRollup()


# ---------------------------------------------------------------------------
# regression detection
# ---------------------------------------------------------------------------

# default noise band: live p50 regresses when it exceeds
# base_p50 * (1 + rel_band) + abs_floor_s. The CI smoke passes
# deliberately generous values (hosts differ); tests tighten per-phase
# via `bands`.
DEFAULT_REL_BAND = 0.75
DEFAULT_ABS_FLOOR_S = 0.05
DEFAULT_MIN_SAMPLES = 3

# built-in per-phase band WIDENERS for the hop phases the router-hop
# rollups added: `router` (placement ladder + submit round trips) and
# `stream` (FETCH forwarding) measure single-digit-millisecond p50s
# that wobble by integer factors under CI scheduler load - a 3ms->8ms
# jitter is not a regression the way a 3s->8s execute is. compare()
# takes each as max(caller band, widener), so a generous CLI --noise
# still applies and an EXPLICIT bands={...} entry for the phase wins
# outright.
PHASE_BANDS: Dict[str, tuple] = {
    "router": (2.0, 0.05),
    # stream: with incremental delivery (service/stream.py) the
    # result_stream span now OVERLAPS execution - a FETCH that
    # arrives while the query is RUNNING measures stream-wall that
    # includes producer time (plus any consumer-side backpressure
    # parking), not just the forwarding cost the old materialized
    # path measured. Cross-round p50s therefore shift by integer
    # factors with consumer pacing, never by a few percent - the
    # band is widened accordingly (a real regression here is a
    # multiple of the whole stream, e.g. a lost first-part wakeup)
    "stream": (4.0, 0.25),
    # fused join-probe / grouped-carry dispatch phases: one kernel
    # launch per batch, so small-row probes measure low-millisecond
    # p50s with the same scheduler-load wobble as the hop phases
    "join": (2.0, 0.05),
    "group": (2.0, 0.05),
    # plan_decode: protobuf-walk time, tens of microseconds to
    # low-single-digit milliseconds - and ZERO on a decoded-plan-cache
    # hit, so cross-round p50s swing with the cache hit mix, not with
    # decoder speed
    "plan_decode": (4.0, 0.02),
    # mesh sub-phases: mesh_trace is all-or-nothing (a warm stage pays
    # ~0, a cold one pays XLA compile - the p50 swings with warm/cold
    # mix, not with code speed), mesh_lower/sync/gather are sub-
    # millisecond host calls with scheduler-load wobble, and stage_in/
    # launch wobble with virtual-device contention on the CPU test
    # tier. All get the generous integer-factor band; a real
    # regression here is a multiple, caught by the MESHATTR diff.
    "mesh_lower": (3.0, 0.25),
    "mesh_trace": (3.0, 0.25),
    "mesh_stage_in": (3.0, 0.25),
    "mesh_launch": (3.0, 0.25),
    "mesh_sync": (3.0, 0.25),
    "mesh_gather": (3.0, 0.25),
}


def compare(
    live: Dict[str, Any],
    baseline: Dict[str, Any],
    *,
    rel_band: float = DEFAULT_REL_BAND,
    abs_floor_s: float = DEFAULT_ABS_FLOOR_S,
    min_samples: int = DEFAULT_MIN_SAMPLES,
    bands: Optional[Dict[str, tuple]] = None,
) -> List[Dict[str, Any]]:
    """Diff two rollup snapshots ({class: {phase: {n, p50, ...}}}).
    A (class, phase) present in BOTH with >= min_samples on both sides
    regresses when live p50 exceeds the band. Per-phase overrides via
    `bands`: {phase: (rel_band, abs_floor_s)} - explicit entries
    apply verbatim; phases in the built-in PHASE_BANDS wideners
    (router/stream) otherwise get max(caller band, widener) per
    component. Returns regressions sorted worst-ratio-first; [] =
    clean."""
    out: List[Dict[str, Any]] = []
    for klass, base_phases in (baseline or {}).items():
        live_phases = (live or {}).get(klass)
        if not live_phases:
            continue
        for phase, b in base_phases.items():
            lv = live_phases.get(phase)
            if not lv:
                continue
            if (int(b.get("n", 0)) < min_samples
                    or int(lv.get("n", 0)) < min_samples):
                continue
            base_p50 = float(b.get("p50", 0.0))
            live_p50 = float(lv.get("p50", 0.0))
            if bands and phase in bands:
                rel, floor = bands[phase]
            elif phase in PHASE_BANDS:
                wrel, wfloor = PHASE_BANDS[phase]
                rel = max(rel_band, wrel)
                floor = max(abs_floor_s, wfloor)
            else:
                rel, floor = rel_band, abs_floor_s
            limit = base_p50 * (1.0 + rel) + floor
            if live_p50 > limit:
                out.append({
                    "class": klass,
                    "phase": phase,
                    "base_p50": round(base_p50, 6),
                    "live_p50": round(live_p50, 6),
                    "limit": round(limit, 6),
                    "ratio": round(
                        live_p50 / base_p50, 3
                    ) if base_p50 else float("inf"),
                })
    out.sort(key=lambda r: -r["ratio"])
    return out


# ---------------------------------------------------------------------------
# the probe: a fixed workload whose rollup is the regress measurement
# ---------------------------------------------------------------------------


def run_probe(rounds: int = 6, rows: int = 1 << 18,
              warmup: int = 1,
              data_path: Optional[str] = None) -> Dict[str, Any]:
    """Execute `rounds` repeats of a fixed scan->filter->aggregate
    plan through a real QueryService with tracing ON and caching OFF
    (a cache hit would zero the decode/h2d/dispatch phases the probe
    exists to measure), and return the resulting rollup snapshot.

    Runs against a PRIVATE PhaseRollup so a probe inside a live server
    process cannot pollute (or be polluted by) production rollup
    state. Warmup rounds pay the kernel compilation and are excluded.
    The parquet file defaults to a fixed path so its scan fingerprint
    - and therefore the rollup class - is stable run-over-run on one
    host; `_all` carries the cross-host comparison."""
    import os
    import tempfile

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from blaze_tpu.exprs import AggExpr, AggFn, Col
    from blaze_tpu.ops import AggMode, FilterExec, HashAggregateExec
    from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
    from blaze_tpu.plan.serde import task_to_proto
    from blaze_tpu.service import QueryService

    path = data_path or os.path.join(
        tempfile.gettempdir(), f"blaze_phase_probe_{rows}.parquet"
    )
    if not os.path.exists(path):
        # deterministic content per row count (fixed seed), so the
        # cached file is reusable across probe runs on one host
        rng = np.random.default_rng(11)
        pq.write_table(
            pa.table({
                "k": pa.array(
                    rng.integers(0, 64, rows), pa.int32()
                ),
                "v": pa.array(rng.random(rows), pa.float64()),
            }),
            path, compression="zstd",
        )
    # KEYLESS aggregate deliberately: it exercises the same
    # decode -> h2d -> dispatch -> execute pipeline but compiles the
    # cheap fused device-carry kernel - the keyed group ladder's
    # reduce-window kernel costs ~50s of XLA constant folding on the
    # test tier's 8-virtual-device CPU platform, which would make the
    # regress smoke measure COMPILATION, not phases
    plan = HashAggregateExec(
        FilterExec(
            ParquetScanExec([[FileRange(path)]]),
            Col("v") > 0.25,
        ),
        keys=[],
        aggs=[(AggExpr(AggFn.SUM, Col("v")), "s"),
              (AggExpr(AggFn.COUNT_STAR, None), "n")],
        mode=AggMode.COMPLETE,
    )
    blob = task_to_proto(plan, 0)

    probe_rollup = PhaseRollup()
    # fold_phases=False: the probe reads its own private rollup AND
    # keeps its synthetic samples out of the process-global one, so a
    # probe inside a live server cannot skew the STATS `phases` view
    svc = QueryService(max_concurrency=1, enable_cache=False,
                       enable_trace=True, slow_query_s=0.0,
                       fold_phases=False)
    try:
        for i in range(max(0, warmup) + max(1, rounds)):
            q = svc.submit_task(blob, use_cache=False)
            if not q.wait(120.0):
                raise TimeoutError("phase probe query stuck")
            if q.state.value != "DONE":
                raise RuntimeError(
                    f"phase probe query {q.state.value}: {q.error}"
                )
            if i < warmup:
                continue  # compilation round: not a phase sample
            t = q.timings
            durations = {
                "e2e": t["finished"] - t["submitted"],
                "execute": t["finished"] - t["run_start"],
            }
            if "admitted" in t:
                durations["queue_wait"] = (
                    t["admitted"] - t["submitted"]
                )
                durations["admission"] = (
                    t["run_start"] - t["admitted"]
                )
            if q.tracer is not None:
                for phase, s in q.tracer.phase_totals(
                    SPAN_PHASE
                ).items():
                    durations.setdefault(phase, s)
            probe_rollup.fold_phases(
                durations,
                klass=class_key(q._fingerprint,
                                q._fingerprint_stable),
            )
    finally:
        svc.close()
    return probe_rollup.snapshot()


# ---------------------------------------------------------------------------
# baseline / bench-artifact IO (the regress CLI's file formats)
# ---------------------------------------------------------------------------


def save_baseline(path: str, snapshot: Dict[str, Any],
                  meta: Optional[Dict[str, Any]] = None) -> None:
    doc = {"format": "blaze-phase-baseline-v1",
           "meta": dict(meta or {}),
           "phases": snapshot}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


def load_baseline(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "phases" in doc:
        return doc["phases"]
    return doc  # bare snapshot


def phases_from_bench(path: str) -> Optional[Dict[str, Any]]:
    """Extract the per-phase rollup a BENCH_r*.json artifact recorded
    (bench.py's `phases` shape). Handles both the driver wrapper
    ({n, cmd, rc, tail}) and a bare battery result, plus the
    MESHATTR_r*.json mesh-attribution artifacts (obs/meshprof.py),
    which carry their per-sub-phase p50s in the same snapshot shape
    so `regress --bench` diffs consecutive rounds of either family.
    None when the round predates phase recording."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and str(
        doc.get("format", "")
    ).startswith("blaze-meshattr"):
        return (doc.get("phases") or {}).get("snapshot") or None
    if isinstance(doc, dict) and "tail" in doc and "queries" not in doc:
        tail = doc["tail"]
        result = None
        for line in reversed(str(tail).splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    result = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
        doc = result or {}
    shape = (doc.get("queries") or {}).get("phases") or {}
    snap = shape.get("snapshot")
    return snap or None
