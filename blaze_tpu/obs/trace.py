"""Query-lifecycle tracing: a context-propagated span tree per query.

The reference engine's observability stops at per-operator counters
mirrored into the Spark UI (metrics.rs, NativeSupports.scala). This
reproduction outgrew that model: a query now crosses the admission
queue, retry/degradation machinery, the result cache, and cluster
worker processes, and none of those hops were visible in one place.
This module is the span layer that stitches them: one TraceRecorder
per query, opened at SUBMIT and closed at the terminal state, with
child spans for queue-wait, admission, per-attempt partition
execution, parquet decode, H2D staging, per-dispatch kernel
execution, host-engine degradation, cache probes, and result
streaming. Chaos faults and cancellations land as span events.

Design constraints (same discipline as testing/chaos.py):

  * Production pays ~nothing when tracing is off: every seam is
    guarded by `if trace.ACTIVE:` - one module-attribute load and a
    falsy branch. No span objects are built, no clocks read.
    (tests/test_dispatch_budget.py pins that obs-off runs keep the
    exact per-shape dispatch budgets; the seams are pure host-side
    control flow and cannot dispatch by construction.)
  * Context propagation is explicit-or-ambient: a seam may name its
    recorder (`rec=ctx.tracer`) or inherit the thread-current one
    that an enclosing `span(...)` installed; with neither, the seam
    no-ops. Generators inherit whatever their *consumer* thread has
    installed, which is exactly the drain loop's attempt span.
  * Cross-process stitching: cluster workers serialize their span
    subtrees (`to_dicts`) into the task-result/.err payloads; the
    driver grafts them (`attach_subtree`) so one query renders as a
    single trace across processes. time.monotonic_ns is
    CLOCK_MONOTONIC, shared by processes on one host, so worker
    timestamps line up without clock translation.

Export is Chrome-trace-event JSON (`chrome_trace`), loadable in
Perfetto / chrome://tracing: matched B/E duration pairs per
(pid, tid), instant events for faults/cancels, with a minimal
validator (`validate_chrome`) the CI smoke runs against every
exported trace.

Activation: refcounted `enable()`/`disable()` (the serving tier
enables for its lifetime), or the BLAZE_TRACE environment variable -
cluster worker subprocesses inherit it, so cross-process traces need
no RPC.
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional

# fast gate: seams check this single module attribute and fall through
# when False (the tracing-off production path)
ACTIVE = False
_enable_count = 0
_lock = threading.Lock()

# bounded per-trace span count: a runaway query (or a per-dispatch
# span storm) degrades to a truncated trace, never unbounded memory
MAX_SPANS_PER_TRACE = int(os.environ.get("BLAZE_TRACE_MAX_SPANS",
                                         20000))
_MAX_RETAINED_TRACES = 256

# synthetic tid for lifecycle spans (queue-wait, admission, root):
# they start and finish on different threads, so they get their own
# strictly-sequential track instead of a real thread's
LIFECYCLE_TID = 0


def enable() -> None:
    """Refcounted activation (the serving tier enables on construction
    and disables on close; nested enables compose)."""
    global ACTIVE, _enable_count
    with _lock:
        _enable_count += 1
        ACTIVE = True


def disable() -> None:
    global ACTIVE, _enable_count
    with _lock:
        _enable_count = max(0, _enable_count - 1)
        ACTIVE = _enable_count > 0


def _reset_for_tests() -> None:
    """Restore the import-time activation state (test hygiene: a test
    that enables tracing and fails must not leave it armed)."""
    global ACTIVE, _enable_count
    with _lock:
        _enable_count = 1 if os.environ.get("BLAZE_TRACE") else 0
        ACTIVE = _enable_count > 0


class Span:
    __slots__ = ("name", "span_id", "parent_id", "start_ns", "end_ns",
                 "pid", "tid", "tags", "events")

    def __init__(self, name: str, span_id: int, parent_id: int,
                 start_ns: int, pid: int, tid: int,
                 tags: Optional[Dict[str, Any]] = None):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.end_ns: Optional[int] = None
        self.pid = pid
        self.tid = tid
        self.tags: Dict[str, Any] = dict(tags) if tags else {}
        self.events: List[Dict[str, Any]] = []

    def tag(self, **tags: Any) -> None:
        self.tags.update(tags)

    def event(self, name: str, **attrs: Any) -> None:
        self.events.append(
            {"name": name, "ts_ns": time.monotonic_ns(),
             "attrs": attrs}
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "pid": self.pid,
            "tid": self.tid,
            "tags": dict(self.tags),
            "events": list(self.events),
        }


class TraceRecorder:
    """One query's span tree (every process appends; the driver owns
    the stitched whole)."""

    def __init__(self, trace_id: str, root_name: str = "query"):
        self.trace_id = trace_id
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self.spans: List[Span] = []
        self.dropped = 0
        root = self._new_span(root_name, parent_id=0,
                              tid=LIFECYCLE_TID,
                              start_ns=time.monotonic_ns())
        assert root is not None  # cap cannot trip on the first span
        self.root: Span = root

    # -- recording ------------------------------------------------------
    def _new_span(self, name: str, parent_id: int, tid: int,
                  start_ns: int,
                  tags: Optional[Dict[str, Any]] = None
                  ) -> Optional[Span]:
        with self._lock:
            if len(self.spans) >= MAX_SPANS_PER_TRACE:
                self.dropped += 1
                return None
            s = Span(name, next(self._ids), parent_id, start_ns,
                     os.getpid(), tid, tags)
            self.spans.append(s)
            # invariant: the root contains every span. Retroactive
            # spans (queue_wait starts at SUBMIT, microseconds before
            # begin_trace ran) would otherwise sort ahead of the root
            # on the lifecycle track and truncate it in the export's
            # nesting sweep.
            if (self.spans[0] is not s
                    and start_ns < self.spans[0].start_ns):
                self.spans[0].start_ns = start_ns
            return s

    def begin(self, name: str, parent: Optional[Span] = None,
              **tags: Any) -> Optional[Span]:
        """Open a live span on the calling thread's track. Returns None
        past the per-trace cap (callers treat that as a null span)."""
        p = parent if parent is not None else self.root
        return self._new_span(name, p.span_id, threading.get_ident(),
                              time.monotonic_ns(), tags)

    @staticmethod
    def end(span: Span, **tags: Any) -> None:
        if tags:
            span.tags.update(tags)
        span.end_ns = time.monotonic_ns()

    def record_span(self, name: str, start_s: float, end_s: float,
                    parent: Optional[Span] = None,
                    tid: int = LIFECYCLE_TID, **tags: Any
                    ) -> Optional[Span]:
        """Retroactive span from `time.monotonic()` second timestamps
        (the service's phase timings clock - same CLOCK_MONOTONIC
        basis as monotonic_ns)."""
        p = parent if parent is not None else self.root
        s = self._new_span(name, p.span_id, tid, int(start_s * 1e9),
                           tags)
        if s is not None:
            s.end_ns = int(end_s * 1e9)
        return s

    def event(self, name: str, span: Optional[Span] = None,
              **attrs: Any) -> None:
        (span if span is not None else self.root).event(name, **attrs)

    def finish(self, **tags: Any) -> None:
        """Close the root span (terminal query state)."""
        self.root.tags.update(
            {k: v for k, v in tags.items() if v is not None}
        )
        if self.root.end_ns is None:
            self.root.end_ns = time.monotonic_ns()

    # -- cross-process stitching ---------------------------------------
    def to_dicts(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [s.to_dict() for s in self.spans]

    def phase_totals(self, phase_of: Dict[str, Optional[str]]
                     ) -> Dict[str, float]:
        """Sum span durations into phase totals (seconds) keyed by
        `phase_of[span.name]` - the allocation-free form of
        `phases.fold_span_dicts(rec.to_dicts())`. The terminal hook
        folds EVERY finished query through this; to_dicts() would
        materialize a dict (with tag and event copies) per span - for
        a retried multi-partition query that is thousands of
        allocations per query on the serving path, for a result this
        fold immediately throws away. One pass over the live Span
        objects, one small output dict."""
        totals: Dict[str, float] = {}
        with self._lock:
            for s in self.spans:
                phase = phase_of.get(s.name)
                if not phase:
                    continue
                end = s.end_ns
                if end is None or end < s.start_ns:
                    continue
                totals[phase] = (
                    totals.get(phase, 0.0) + (end - s.start_ns) / 1e9
                )
        return totals

    def attach_subtree(self, span_dicts: List[Dict[str, Any]],
                       parent: Optional[Span] = None) -> int:
        """Graft a serialized subtree (a cluster worker's spans) under
        `parent` (default: the root). Span ids are remapped into this
        recorder's id space; parent links inside the subtree are
        preserved, subtree roots re-parent under the graft point.
        Returns the number of spans attached."""
        anchor = parent if parent is not None else self.root
        id_map: Dict[int, int] = {}
        grafted: List[tuple] = []  # (span, old_parent_id)
        with self._lock:
            for d in span_dicts:
                if len(self.spans) >= MAX_SPANS_PER_TRACE:
                    self.dropped += len(span_dicts) - len(grafted)
                    break
                s = Span(
                    str(d.get("name", "span")), next(self._ids),
                    0, int(d.get("start_ns", 0)),
                    int(d.get("pid", 0)), int(d.get("tid", 0)),
                    d.get("tags"),
                )
                end_ns = d.get("end_ns")
                s.end_ns = int(end_ns) if end_ns is not None else None
                s.events = list(d.get("events", ()))
                id_map[int(d.get("span_id", 0))] = s.span_id
                self.spans.append(s)
                grafted.append((s, int(d.get("parent_id", 0))))
            # second pass: remap parents (subtree may arrive in any
            # order); unresolvable parents hang off the graft anchor
            for s, old_parent in grafted:
                s.parent_id = id_map.get(old_parent, anchor.span_id)
                if s.start_ns and s.start_ns < self.spans[0].start_ns:
                    self.spans[0].start_ns = s.start_ns
        return len(grafted)


# ---------------------------------------------------------------------------
# thread-current span stack + context-manager seam API
# ---------------------------------------------------------------------------

_tls = threading.local()


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_recorder() -> Optional[TraceRecorder]:
    st = _stack()
    return st[-1][0] if st else None


def current_span() -> Optional[Span]:
    st = _stack()
    return st[-1][1] if st else None


class _NullSpan:
    """No-op span/context manager: what seams get when no recorder is
    in scope (or the per-trace span cap tripped)."""

    __slots__ = ()

    def tag(self, **tags: Any) -> None:
        pass

    def event(self, name: str, **attrs: Any) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL = _NullSpan()


class _SpanCtx:
    __slots__ = ("_rec", "_name", "_tags", "span", "_pushed")

    def __init__(self, rec: TraceRecorder, name: str,
                 tags: Dict[str, Any]):
        self._rec = rec
        self._name = name
        self._tags = tags
        self.span: Optional[Span] = None
        self._pushed = False

    def __enter__(self):
        st = _stack()
        parent = st[-1][1] if (st and st[-1][0] is self._rec) else None
        sp = self._rec.begin(self._name, parent=parent, **self._tags)
        if sp is None:  # span cap: degrade to a null span
            return NULL
        st.append((self._rec, sp))
        self.span = sp
        self._pushed = True
        return sp

    def __exit__(self, exc_type, exc, tb):
        if not self._pushed:
            return False
        st = _stack()
        if st and st[-1][1] is self.span:
            st.pop()
        else:  # exotic unwind order (generator closed off-stack)
            try:
                st.remove((self._rec, self.span))
            except ValueError:
                pass
        sp = self.span
        if exc_type is not None:
            if exc_type in (GeneratorExit, KeyboardInterrupt):
                sp.tags.setdefault("cancelled", True)
            else:
                sp.tags.setdefault("error", exc_type.__name__)
                try:
                    from blaze_tpu.errors import classify

                    sp.tags.setdefault("error_class",
                                       classify(exc).value)
                except Exception:  # noqa: BLE001 - tagging best-effort
                    pass
        sp.end_ns = time.monotonic_ns()
        return False


def span(name: str, rec: Optional[TraceRecorder] = None, **tags: Any):
    """Seam entry: a context manager recording one span under the
    named (or thread-current) recorder; a no-op with neither. Always
    gate the call site on `trace.ACTIVE` first."""
    r = rec if rec is not None else current_recorder()
    if r is None:
        return NULL
    return _SpanCtx(r, name, tags)


def event(name: str, **attrs: Any) -> None:
    """Attach an instant event to the thread-current span (chaos
    faults, cancellations); no-op outside any span."""
    st = _stack()
    if st:
        st[-1][1].event(name, **attrs)


# ---------------------------------------------------------------------------
# trace registry (export looks traces up by query id)
# ---------------------------------------------------------------------------

_TRACES: "collections.OrderedDict[str, TraceRecorder]" = (
    collections.OrderedDict()
)


def begin_trace(trace_id: str,
                root_name: str = "query") -> TraceRecorder:
    rec = TraceRecorder(trace_id, root_name=root_name)
    with _lock:
        _TRACES[trace_id] = rec
        _TRACES.move_to_end(trace_id)
        while len(_TRACES) > _MAX_RETAINED_TRACES:
            _TRACES.popitem(last=False)
    return rec


def get_trace(trace_id: str) -> Optional[TraceRecorder]:
    with _lock:
        return _TRACES.get(trace_id)


# ---------------------------------------------------------------------------
# Chrome-trace-event export (Perfetto / chrome://tracing loadable)
# ---------------------------------------------------------------------------


def chrome_trace(rec: TraceRecorder) -> Dict[str, Any]:
    """Serialize one recorder as Chrome trace events: matched B/E
    pairs per (pid, tid) track, instant events ('i') for span events,
    process metadata ('M'). Timestamps are microseconds relative to
    the earliest span, so the trace opens at t=0."""
    # deep-enough snapshot under the recorder lock: REPORT may export
    # a still-RUNNING query while worker threads mutate span tags
    with rec._lock:
        spans = []
        for s in rec.spans:
            c = Span(s.name, s.span_id, s.parent_id, s.start_ns,
                     s.pid, s.tid, s.tags)  # Span copies the tags
            c.end_ns = s.end_ns
            c.events = list(s.events)
            spans.append(c)
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    now = time.monotonic_ns()
    t0 = min(s.start_ns for s in spans)

    def us(ns: int) -> float:
        return round((ns - t0) / 1000.0, 3)

    # small per-pid tid indices (raw thread idents are unreadable);
    # the lifecycle track keeps tid 0
    tid_map: Dict[tuple, int] = {}

    def tid_of(s: Span) -> int:
        if s.tid == LIFECYCLE_TID:
            return 0
        key = (s.pid, s.tid)
        if key not in tid_map:
            tid_map[key] = len(tid_map) + 1
        return tid_map[key]

    groups: Dict[tuple, List[Span]] = {}
    for s in spans:
        groups.setdefault((s.pid, tid_of(s)), []).append(s)

    events: List[Dict[str, Any]] = []
    for pid in sorted({s.pid for s in spans}):
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"blaze[{pid}]"},
        })
    for (pid, tid), group in sorted(groups.items()):
        # structural nesting is guaranteed per thread (context
        # managers); the (start, -end) sort + end-clamp turns it into
        # well-nested B/E intervals even with equal timestamps
        group.sort(key=lambda s: (s.start_ns, -((s.end_ns or now))))
        stack: List[tuple] = []  # (span, clamped_end_ns)
        for s in group:
            end = s.end_ns if s.end_ns is not None else now
            while stack and stack[-1][1] <= s.start_ns:
                top, top_end = stack.pop()
                events.append({"ph": "E", "name": top.name,
                               "pid": pid, "tid": tid,
                               "ts": us(top_end)})
            if stack:
                end = min(end, stack[-1][1])  # child within parent
            args = {k: _jsonable(v) for k, v in s.tags.items()}
            if s.end_ns is None:
                args["unfinished"] = True
            b = {"ph": "B", "name": s.name, "pid": pid, "tid": tid,
                 "ts": us(max(s.start_ns, t0))}
            if args:
                b["args"] = args
            events.append(b)
            for ev in s.events:
                ie = {"ph": "i", "name": str(ev.get("name", "event")),
                      "pid": pid, "tid": tid,
                      "ts": us(int(ev.get("ts_ns", s.start_ns))),
                      "s": "t"}
                attrs = ev.get("attrs")
                if attrs:
                    ie["args"] = {k: _jsonable(v)
                                  for k, v in attrs.items()}
                events.append(ie)
            stack.append((s, max(end, s.start_ns)))
        while stack:
            top, top_end = stack.pop()
            events.append({"ph": "E", "name": top.name, "pid": pid,
                           "tid": tid, "ts": us(top_end)})
    out = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"trace_id": rec.trace_id},
    }
    if rec.dropped:
        out["otherData"]["dropped_spans"] = rec.dropped
    return out


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def validate_chrome(doc: Any) -> List[str]:
    """Minimal Chrome-trace-event schema check (the CI trace smoke):
    every event has ph/pid/tid (+name/ts where applicable), B/E pairs
    match per (pid, tid) in stack order, and no span ends before it
    begins. Returns a list of problems; empty = valid."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["trace is not a JSON object"]
    evs = doc.get("traceEvents")
    if not isinstance(evs, list) or not evs:
        return ["no traceEvents"]
    stacks: Dict[tuple, List[tuple]] = {}
    for i, e in enumerate(evs):
        if not isinstance(e, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = e.get("ph")
        if ph not in ("B", "E", "i", "M", "X"):
            problems.append(f"event {i}: unknown ph {ph!r}")
            continue
        if "pid" not in e or "tid" not in e:
            problems.append(f"event {i}: missing pid/tid")
            continue
        if ph == "M":
            continue
        ts = e.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(
                f"event {i}: bad ts {ts!r} (want number >= 0)"
            )
            continue
        key = (e["pid"], e["tid"])
        if ph == "B":
            if not e.get("name"):
                problems.append(f"event {i}: B without name")
            stacks.setdefault(key, []).append((e.get("name"), ts, i))
        elif ph == "E":
            st = stacks.get(key)
            if not st:
                problems.append(
                    f"event {i}: E({e.get('name')!r}) without "
                    f"matching B on {key}"
                )
                continue
            bname, bts, bi = st.pop()
            if e.get("name") and e["name"] != bname:
                problems.append(
                    f"event {i}: E name {e['name']!r} != B name "
                    f"{bname!r} (event {bi})"
                )
            if ts < bts:
                problems.append(
                    f"event {i}: span {bname!r} ends at {ts} before "
                    f"it begins at {bts} (non-monotonic)"
                )
    for key, st in stacks.items():
        for bname, _, bi in st:
            problems.append(
                f"unclosed B {bname!r} (event {bi}) on {key}"
            )
    return problems


def _maybe_activate_from_env() -> None:
    if os.environ.get("BLAZE_TRACE"):
        enable()


_maybe_activate_from_env()
