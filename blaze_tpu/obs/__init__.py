"""blaze_tpu.obs: unified tracing + metrics + runtime history.

Three complementary surfaces over one serving process:

  trace    per-query span trees (obs/trace.py), stitched across
           threads and cluster worker processes, exported as
           Perfetto-loadable Chrome trace JSON via the REPORT verb
           and `python -m blaze_tpu trace <query_id>`;
  metrics  process-wide counters + bounded histograms with Prometheus
           text exposition (obs/metrics.py), folding in the
           `dispatch.*` perf-model counters and live admission/cache
           state, served by the METRICS verb;
  history  per-fingerprint execution-time records (obs/history.py) -
           the estimate feeding predicted-unmeetability shedding and
           (ROADMAP) replica routing;
  phases   per-phase duration rollup keyed by fingerprint class
           (obs/phases.py) - the diffable form behind `python -m
           blaze_tpu regress`, which catches queue-wait creep and
           decode regressions invisible to e2e medians;
  slowlog  one structured JSON log line per over-threshold query
           (obs/slowlog.py).

The disabled path is one module-attribute check per seam
(`trace.ACTIVE`, same discipline as testing/chaos.py): tracing-off
runs add zero dispatches and no per-batch work. docs/OBSERVABILITY.md
has the span taxonomy and export formats.
"""

from blaze_tpu.obs.history import RuntimeHistory
from blaze_tpu.obs.metrics import REGISTRY, MetricsRegistry
from blaze_tpu.obs.phases import ROLLUP, PhaseRollup
from blaze_tpu.obs.trace import (
    TraceRecorder,
    begin_trace,
    chrome_trace,
    get_trace,
    validate_chrome,
)

__all__ = [
    "REGISTRY",
    "ROLLUP",
    "MetricsRegistry",
    "PhaseRollup",
    "RuntimeHistory",
    "TraceRecorder",
    "begin_trace",
    "chrome_trace",
    "get_trace",
    "validate_chrome",
]
