"""Process-wide metrics registry with Prometheus text exposition.

The per-query metric tree (runtime/instrument.py) answers "where did
THIS query's time go"; this registry answers the fleet question -
"what is this PROCESS doing" - in the form every scraper already
speaks. It folds three sources into one exposition:

  * its own counters and bounded histograms (query terminal states,
    wall-time distribution, degradations, worker quarantines),
  * the process-global `dispatch.*` counters (runtime/dispatch.py -
    dispatch count IS the perf model, so it belongs on the scrape
    surface), rendered as `blaze_dispatch_total{kind=...}`,
  * registered collectors: live components (the QueryService's
    admission controller, result cache, runtime-history store)
    contribute samples at scrape time, so gauges are always current
    and dead components stop reporting when they unregister.

Served through the service METRICS verb (service/wire.py) and
`python -m blaze_tpu metrics`. Label cardinality is deliberately
tiny: fingerprints and query ids never become labels - per-query
detail lives in traces (obs/trace.py) and the runtime-history store
(obs/history.py), not the scrape surface.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

# (metric_name, labels_dict, value, type) - what collectors yield
Sample = Tuple[str, Dict[str, str], float, str]

# wall-time buckets: sub-ms serving overhead through minutes-long
# scans (seconds)
DEFAULT_TIME_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    return _NAME_RE.sub("_", name)


def _label_str(labels) -> str:
    """Accepts a dict OR an already-sorted tuple of (k, v) pairs -
    the registry stores label sets as tuples, and rendering them
    directly avoids re-materializing a dict per sample at scrape
    time."""
    if not labels:
        return ""
    items = sorted(labels.items()) if isinstance(labels, dict) \
        else labels
    inner = ",".join(
        f'{_sanitize(k)}="{str(v)}"' for k, v in items
    )
    return "{" + inner + "}"


class _Histogram:
    __slots__ = ("bounds", "counts", "total", "n")

    def __init__(self, bounds: Tuple[float, ...]):
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # +inf bucket last
        self.total = 0.0
        self.n = 0

    def observe(self, value: float) -> None:
        i = 0
        for i, b in enumerate(self.bounds):
            if value <= b:
                break
        else:
            i = len(self.bounds)
        self.counts[i] += 1
        self.total += value
        self.n += 1

    def summary(self) -> Dict[str, Any]:
        return {
            "count": self.n,
            "sum": round(self.total, 6),
            "mean": round(self.total / self.n, 6) if self.n else 0.0,
        }


class MetricsRegistry:
    """Counters + bounded histograms + scrape-time collectors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple], float] = {}
        self._hists: Dict[Tuple[str, Tuple], _Histogram] = {}
        self._hist_bounds: Dict[str, Tuple[float, ...]] = {}
        self._collectors: Dict[str, Callable[[], Iterable[Sample]]] = {}

    # -- write path -----------------------------------------------------
    def inc(self, name: str, n: float = 1, **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def observe(self, name: str, value: float,
                buckets: Optional[Tuple[float, ...]] = None,
                **labels: str) -> None:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                bounds = self._hist_bounds.setdefault(
                    name, tuple(buckets or DEFAULT_TIME_BUCKETS)
                )
                h = self._hists[key] = _Histogram(bounds)
            h.observe(float(value))

    # -- read path ------------------------------------------------------
    def get(self, name: str, **labels: str) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._counters.get(key, 0)

    def histogram_summary(self, name: str,
                          **labels: str) -> Optional[Dict[str, Any]]:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            h = self._hists.get(key)
            return h.summary() if h is not None else None

    def histogram_summaries(
        self, name: str
    ) -> List[Tuple[Dict[str, str], Dict[str, Any]]]:
        """Every labeled series of one histogram family as
        (labels, summary) pairs - the profile report enumerates
        blaze_verb_seconds through this without parsing expositions."""
        with self._lock:
            return [(dict(labels), h.summary())
                    for (n, labels), h in self._hists.items()
                    if n == name]

    # -- collectors -----------------------------------------------------
    def register_collector(
        self, key: str, fn: Callable[[], Iterable[Sample]]
    ) -> None:
        with self._lock:
            self._collectors[key] = fn

    def unregister_collector(self, key: str) -> None:
        with self._lock:
            self._collectors.pop(key, None)

    # -- exposition -----------------------------------------------------
    def render_prometheus(self) -> str:
        """Prometheus text format v0.0.4. Scrape-time work only: the
        write path never formats strings."""
        samples: List[Sample] = []
        # fold 1: the process-global dispatch counters
        try:
            from blaze_tpu.runtime import dispatch

            for k, v in sorted(dispatch.snapshot().items()):
                samples.append(
                    ("blaze_dispatch_total", {"kind": k}, v, "counter")
                )
        except Exception:  # noqa: BLE001 - exposition is best-effort
            pass
        # fold 2: live-component collectors
        with self._lock:
            collectors = list(self._collectors.items())
        for key, fn in collectors:
            try:
                samples.extend(fn())
            except Exception:  # noqa: BLE001 - one bad collector
                # accumulated (not a literal 1): rate()/increase()
                # over a constant would hide a collector failing on
                # every scrape
                self.inc("blaze_collector_errors_total",
                         collector=key)
        # fold 3: own counters + histograms (snapshotted AFTER the
        # collectors ran, so collector-error increments land in THIS
        # exposition)
        with self._lock:
            counters = sorted(self._counters.items())
            hists = sorted(self._hists.items())
        for (name, labels), v in counters:
            # labels is the stored sorted tuple; _label_str renders
            # it as-is, so no per-sample dict materialization
            samples.append((name, labels, v, "counter"))

        lines: List[str] = []
        seen_types: Dict[str, str] = {}

        def emit(name: str, labels: Dict[str, str], value: float,
                 mtype: str) -> None:
            name = _sanitize(name)
            if name not in seen_types:
                seen_types[name] = mtype
                lines.append(f"# TYPE {name} {mtype}")
            if isinstance(value, float) and (
                math.isnan(value) or math.isinf(value)
            ):
                value = 0.0
            v = int(value) if float(value).is_integer() else value
            lines.append(f"{name}{_label_str(labels)} {v}")

        # stable family grouping: all samples of one metric together.
        # An in-place stable sort by family name replaces the old
        # throwaway dict-of-lists grouping - same output (insertion
        # order preserved within a family), no intermediate
        # allocation proportional to the sample count.
        samples.sort(key=lambda s: s[0])
        for name, labels, value, mtype in samples:
            emit(name, labels, value, mtype)

        for (name, labels), h in hists:
            base = _sanitize(name)
            lines.append(f"# TYPE {base} histogram")
            ld = dict(labels)
            acc = 0
            for b, c in zip(h.bounds, h.counts):
                acc += c
                lines.append(
                    f"{base}_bucket"
                    f"{_label_str({**ld, 'le': repr(b)})} {acc}"
                )
            acc += h.counts[-1]
            lines.append(
                f"{base}_bucket{_label_str({**ld, 'le': '+Inf'})} {acc}"
            )
            lines.append(
                f"{base}_sum{_label_str(ld)} {round(h.total, 6)}"
            )
            lines.append(f"{base}_count{_label_str(ld)} {h.n}")
        return "\n".join(lines) + "\n"

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._counters.clear()
            self._hists.clear()
            self._hist_bounds.clear()
            self._collectors.clear()


def merge_expositions(
    base: str,
    per_source: Dict[str, str],
    label: str = "replica",
) -> str:
    """Fold several Prometheus text expositions into one document by
    stamping every sample from `per_source[source_id]` with
    `{label="source_id"}` - the replica router's METRICS verb uses
    this to serve the FLEET view (its own registry plus each replica's
    scrape) without series collisions. `# TYPE` lines are deduplicated
    first-wins; malformed lines are dropped rather than corrupting the
    whole scrape."""
    lines: List[str] = []
    seen_types = set()
    for ln in base.splitlines():
        if ln.startswith("# TYPE "):
            parts = ln.split()
            if len(parts) >= 3:
                seen_types.add(parts[2])
        lines.append(ln)
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$"
    )
    for source_id, text in sorted(per_source.items()):
        stamp = f'{_sanitize(label)}="{source_id}"'
        for ln in (text or "").splitlines():
            if not ln or ln.startswith("#"):
                if ln.startswith("# TYPE "):
                    parts = ln.split()
                    if len(parts) >= 3 and parts[2] not in seen_types:
                        seen_types.add(parts[2])
                        lines.append(ln)
                continue
            m = sample_re.match(ln)
            if m is None:
                continue  # malformed sample: drop, don't corrupt
            name, labels, value = m.groups()
            if labels:
                labels = labels[:-1] + "," + stamp + "}"
            else:
                labels = "{" + stamp + "}"
            lines.append(f"{name}{labels} {value}")
    return "\n".join(lines) + ("\n" if lines else "")


REGISTRY = MetricsRegistry()
