"""Thread-stack sampling profiler: where the serving threads actually are.

Lock accounting (obs/contention.py) says how long threads PARK; this
says what they are DOING the rest of the time - a `sys._current_frames()`
sampler thread at a configurable Hz folds every live thread's stack
into per-(module, function) buckets tagged with the thread's ROLE
(verb-loop / executor / dispatcher / poller / relay / flusher /
other), derived from the thread-name conventions the serving tiers
already use (blaze-dispatch, blaze-query*, blaze-router-poll-*, ...).
Exports: collapsed-stack text (one `role;mod:fn;mod:fn N` line per
distinct stack - flamegraph.pl / speedscope ready) and a top-N
self-time table (leaf-frame sample counts).

Bounded memory: at most `max_stacks` distinct collapsed stacks and
`max_depth` frames per stack; beyond the stack cap samples fold into
a per-role `<overflow>` bucket. The sampler is a daemon thread the
start/stop surface owns; `sys._current_frames()` holds the GIL for
the duration of one sweep, so cost scales with thread count x Hz -
the default 67 Hz prices out under 1% on the serving tiers (priced by
the obs_overhead bench shape).

Start/stop: `serve --profile-hz` / `route --profile-hz` run one for
the process lifetime; the PROFILE wire verb starts/stops/snapshots a
live fleet without restart; the profile CLI drives it per
concurrency level. `_reset_for_tests()` stops the process sampler
and drops its buckets (conftest `_obs_hygiene`).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

# thread-name prefix -> role tag (first match wins; the serving tiers
# name every long-lived thread with a blaze- prefix, and
# serve_verb_connection names its handler thread blaze-verb-loop on
# entry so socketserver's default Thread-N never hides the wire tier)
ROLE_PREFIXES: Tuple[Tuple[str, str], ...] = (
    ("blaze-verb", "verb-loop"),
    ("blaze-dispatch", "dispatcher"),
    ("blaze-query", "executor"),
    ("blaze-router-poll", "poller"),
    ("blaze-router-probe", "poller"),
    ("blaze-router-stream", "relay"),
    ("blaze-router-hot", "replicator"),
    ("blaze-router-recover", "recovery"),
    ("blaze-router-accept", "verb-loop"),
    ("blaze-serve-drain", "drain"),
    ("blaze-journal", "flusher"),
    ("blaze-member", "membership"),
    ("blaze-sampler", "sampler"),
)


def role_of(thread_name: str) -> str:
    for prefix, role in ROLE_PREFIXES:
        if thread_name.startswith(prefix):
            return role
    return "other"


class StackSampler:
    """One sampling session: a daemon thread folding stacks between
    start() and stop(). Instances are cheap; the module-level
    singleton below is the process surface the wire verb drives."""

    def __init__(self, hz: float = 67.0, max_stacks: int = 2048,
                 max_depth: int = 48):
        self.hz = max(1.0, min(997.0, float(hz)))
        self.max_stacks = int(max_stacks)
        self.max_depth = int(max_depth)
        self._mu = threading.Lock()
        # (role, (frame, ...)) -> sample count; frame = "module:func"
        self._stacks: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        # (role, frame) -> leaf (self-time) sample count
        self._self: Dict[Tuple[str, str], int] = {}
        self._samples = 0
        self._overflowed = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_at = 0.0

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "StackSampler":
        with self._mu:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._started_at = time.monotonic()
            self._thread = threading.Thread(
                target=self._loop, name="blaze-sampler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._mu:
            t = self._thread
            self._thread = None
        if t is None:
            return
        self._stop.set()
        t.join(timeout=5.0)

    @property
    def running(self) -> bool:
        return self._thread is not None

    def _loop(self) -> None:
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - a torn frame walk
                # (thread exiting mid-sweep) must not kill the sampler
                continue

    # -- sampling -------------------------------------------------------
    def sample_once(self) -> None:
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        folded: List[Tuple[str, Tuple[str, ...]]] = []
        for ident, frame in frames.items():
            if ident == me:
                continue
            role = role_of(names.get(ident, ""))
            stack: List[str] = []
            f = frame
            while f is not None and len(stack) < self.max_depth:
                code = f.f_code
                mod = f.f_globals.get("__name__", "?")
                stack.append(f"{mod}:{code.co_name}")
                f = f.f_back
            if not stack:
                continue
            stack.reverse()
            folded.append((role, tuple(stack)))
        del frames  # drop the frame references promptly
        with self._mu:
            self._samples += 1
            for role, stack in folded:
                key = (role, stack)
                if key not in self._stacks \
                        and len(self._stacks) >= self.max_stacks:
                    key = (role, ("<overflow>",))
                    self._overflowed += 1
                self._stacks[key] = self._stacks.get(key, 0) + 1
                leaf = (role, stack[-1])
                self._self[leaf] = self._self.get(leaf, 0) + 1

    # -- export ---------------------------------------------------------
    def collapsed(self, role: Optional[str] = None) -> str:
        """Flamegraph-ready collapsed-stack text: one
        `role;frame;frame count` line per distinct sampled stack."""
        with self._mu:
            items = sorted(self._stacks.items(),
                           key=lambda kv: -kv[1])
        lines = []
        for (r, stack), n in items:
            if role is not None and r != role:
                continue
            lines.append(";".join((r,) + stack) + f" {n}")
        return "\n".join(lines)

    def top(self, n: int = 20) -> List[Dict[str, Any]]:
        """Top-N self-time frames: where threads were EXECUTING (leaf
        frames), worst first, with role attribution."""
        with self._mu:
            items = sorted(self._self.items(), key=lambda kv: -kv[1])
            total = sum(self._self.values()) or 1
        return [
            {"frame": frame, "role": role, "samples": c,
             "pct": round(100.0 * c / total, 2)}
            for (role, frame), c in items[:n]
        ]

    def snapshot(self, top_n: int = 20,
                 include_collapsed: bool = True,
                 max_collapsed_bytes: int = 1 << 20) -> Dict[str, Any]:
        with self._mu:
            samples = self._samples
            distinct = len(self._stacks)
            overflowed = self._overflowed
            running = self._thread is not None
        out: Dict[str, Any] = {
            "hz": self.hz,
            "running": running,
            "samples": samples,
            "distinct_stacks": distinct,
            "overflowed": overflowed,
            "top": self.top(top_n),
        }
        if include_collapsed:
            # bounded for the wire: the PROFILE response must fit the
            # JSON frame cap, so the collapsed text truncates at a
            # line boundary
            text = self.collapsed()
            if len(text) > max_collapsed_bytes:
                text = text[:max_collapsed_bytes]
                text = text[:text.rfind("\n")]
                out["collapsed_truncated"] = True
            out["collapsed"] = text
        return out

    def reset(self) -> None:
        with self._mu:
            self._stacks.clear()
            self._self.clear()
            self._samples = 0
            self._overflowed = 0


# ---------------------------------------------------------------------------
# process surface: the singleton the PROFILE verb / --profile-hz drive
# ---------------------------------------------------------------------------

_mu = threading.Lock()
_SAMPLER: Optional[StackSampler] = None


def start(hz: float = 67.0) -> StackSampler:
    """Start (or retune) the process sampler. A second start with a
    different hz restarts the thread; same hz is a no-op."""
    global _SAMPLER
    with _mu:
        s = _SAMPLER
        if s is not None and s.running and s.hz == max(
            1.0, min(997.0, float(hz))
        ):
            return s
        if s is not None:
            s.stop()
        s = _SAMPLER = StackSampler(hz=hz)
        s.start()
    return s


def stop() -> None:
    global _SAMPLER
    with _mu:
        s = _SAMPLER
    if s is not None:
        s.stop()


def current() -> Optional[StackSampler]:
    return _SAMPLER


def snapshot(**kw) -> Dict[str, Any]:
    s = _SAMPLER
    if s is None:
        return {"running": False, "samples": 0, "top": []}
    return s.snapshot(**kw)


def _reset_for_tests() -> None:
    global _SAMPLER
    with _mu:
        s = _SAMPLER
        _SAMPLER = None
    if s is not None:
        s.stop()
