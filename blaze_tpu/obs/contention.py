"""Lock-wait accounting: who is blocking whom on the serving path.

BENCH_r10 shows the c16 cached median at 2x the c1 median with the
spread exploding - and nothing in the obs stack (trace/metrics/phases,
PRs 4/6/9) can say WHERE that time goes: every surface measures
per-query durations, none measures the time a verb-loop thread spends
parked on the admission lock vs the cache lock vs the stream ring.
This module is that measurement: a named `TimedLock`/`TimedRLock`
wrapper the hot locks adopt (admission controller, result cache,
stream ring, query state, service state, router handle table,
registry snapshot swap, connection pool), recording per-lock-name
WAIT time (acquire entry -> lock held) and HOLD time (held ->
released) into bounded histograms.

Design constraints (the chaos.ACTIVE / trace.ACTIVE discipline):

  * Production pays ~nothing when contention accounting is off: every
    acquire/release checks the single `ACTIVE` module attribute and
    falls through to the bare inner lock - no clocks read, no stats
    touched. tests/test_dispatch_budget.py pins that the off path
    keeps the exact per-shape dispatch budgets.
  * Activation is refcounted `enable()`/`disable()` (the profile CLI
    and `--profile-hz` serving flags enable around a measurement
    window; nested enables compose), or the BLAZE_CONTENTION
    environment variable for whole-process runs.
  * Bounded memory: at most `_MAX_LOCKS` distinct lock names (beyond
    that, samples fold into the `_overflow` stat), fixed histogram
    bucket counts per stat - a misbehaving caller minting lock names
    degrades to a lumped stat, never unbounded growth.

Surfaces: `snapshot()` is the `contention` section in STATS on both
tiers; `metrics_samples()` renders `blaze_lock_wait_seconds{lock}` /
`blaze_lock_hold_seconds{lock}` histogram series for METRICS (the
collector registers on first enable). The wrappers implement the
Condition protocol (`_release_save`/`_acquire_restore`/`_is_owned`),
so `threading.Condition(TimedLock(...))` accounts the ring and
connection-pool waits too: a cv.wait ends the hold (the lock really
is released while parked) and the post-notify reacquire records as
wait - which is exactly the contention it is.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Iterable, List, Tuple

# fast gate: acquire/release check this single module attribute and
# fall through to the bare inner lock when False
ACTIVE = False
_enable_count = 0
_lock = threading.Lock()

# lock waits and holds live in the us..ms range; the top bucket
# catches pathological multi-second parks (a stuck flusher holding
# the ring)
BUCKETS: Tuple[float, ...] = (
    0.00001, 0.0001, 0.001, 0.01, 0.1, 1.0,
)

_MAX_LOCKS = 64
_OVERFLOW = "_overflow"


class LockStat:
    """Wait/hold accounting for one lock NAME (many wrapper instances
    - e.g. every per-query state lock - share one stat)."""

    __slots__ = ("name", "waits", "wait_total", "wait_max",
                 "holds", "hold_total", "hold_max",
                 "wait_buckets", "hold_buckets", "_mu")

    def __init__(self, name: str):
        self.name = name
        self.waits = 0
        self.wait_total = 0.0
        self.wait_max = 0.0
        self.holds = 0
        self.hold_total = 0.0
        self.hold_max = 0.0
        self.wait_buckets = [0] * (len(BUCKETS) + 1)
        self.hold_buckets = [0] * (len(BUCKETS) + 1)
        # per-stat mutex, held for a handful of int/float updates:
        # cheaper than racing lost increments, and never nested inside
        # the timed lock itself (wait records after acquire, hold
        # records before/after release)
        self._mu = threading.Lock()

    @staticmethod
    def _bucket(v: float) -> int:
        for i, b in enumerate(BUCKETS):
            if v <= b:
                return i
        return len(BUCKETS)

    def record_wait(self, dt: float) -> None:
        i = self._bucket(dt)
        with self._mu:
            self.waits += 1
            self.wait_total += dt
            if dt > self.wait_max:
                self.wait_max = dt
            self.wait_buckets[i] += 1

    def record_hold(self, dt: float) -> None:
        i = self._bucket(dt)
        with self._mu:
            self.holds += 1
            self.hold_total += dt
            if dt > self.hold_max:
                self.hold_max = dt
            self.hold_buckets[i] += 1

    def snapshot(self) -> Dict[str, Any]:
        with self._mu:
            wait_total = self.wait_total
            hold_total = self.hold_total
            out = {
                "waits": self.waits,
                "wait_s": round(wait_total, 6),
                "wait_max_s": round(self.wait_max, 6),
                "holds": self.holds,
                "hold_s": round(hold_total, 6),
                "hold_max_s": round(self.hold_max, 6),
            }
        out["wait_hold_ratio"] = round(
            wait_total / hold_total, 4
        ) if hold_total > 0 else (float("inf") if wait_total else 0.0)
        return out


_STATS: Dict[str, LockStat] = {}


def stat_for(name: str) -> LockStat:
    """Get-or-create the named stat (bounded: past _MAX_LOCKS names,
    everything folds into the `_overflow` stat)."""
    s = _STATS.get(name)
    if s is not None:
        return s
    with _lock:
        s = _STATS.get(name)
        if s is None:
            if len(_STATS) >= _MAX_LOCKS:
                s = _STATS.get(_OVERFLOW)
                if s is None:
                    s = _STATS[_OVERFLOW] = LockStat(_OVERFLOW)
            else:
                s = _STATS[name] = LockStat(name)
    return s


# ---------------------------------------------------------------------------
# activation (refcounted, trace.py discipline)
# ---------------------------------------------------------------------------


def enable() -> None:
    global ACTIVE, _enable_count
    with _lock:
        _enable_count += 1
        ACTIVE = True
    _register_collector()


def disable() -> None:
    global ACTIVE, _enable_count
    with _lock:
        _enable_count = max(0, _enable_count - 1)
        ACTIVE = _enable_count > 0


def _reset_for_tests() -> None:
    """Restore import-time state AND drop recorded stats (test
    hygiene: a failed test must not leave accounting armed or its
    samples visible to later expositions)."""
    global ACTIVE, _enable_count
    with _lock:
        _enable_count = 1 if os.environ.get("BLAZE_CONTENTION") else 0
        ACTIVE = _enable_count > 0
        _STATS.clear()


def reset_stats() -> None:
    """Zero the recorded stats without touching activation - the
    profile CLI resets between concurrency levels so each report
    section attributes only its own window."""
    with _lock:
        _STATS.clear()


# ---------------------------------------------------------------------------
# the wrappers
# ---------------------------------------------------------------------------


class TimedLock:
    """threading.Lock with named wait/hold accounting. Off path is
    one module-attribute check, then the bare inner lock. Implements
    the Condition protocol so `threading.Condition(TimedLock(n))`
    accounts waiter reacquires as lock waits."""

    __slots__ = ("_inner", "_stat", "_t_acquired")

    def __init__(self, name: str):
        self._inner = threading.Lock()
        self._stat = stat_for(name)
        self._t_acquired = 0.0

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        if not ACTIVE:
            return self._inner.acquire(blocking, timeout)
        t0 = time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            t1 = time.perf_counter()
            self._stat.record_wait(t1 - t0)
            # owner-private between acquire and release: safe on a
            # mutual-exclusion lock
            self._t_acquired = t1
        return ok

    def release(self) -> None:
        # hold records BEFORE the inner release so the next acquirer
        # cannot overwrite _t_acquired under us
        if ACTIVE and self._t_acquired:
            self._stat.record_hold(
                time.perf_counter() - self._t_acquired
            )
            self._t_acquired = 0.0
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition protocol --------------------------------------------
    # Condition(lock) picks these up; without them it falls back to
    # acquire()/release(), which would also work but pays the timed
    # acquire for its _is_owned() probe on every wait/notify
    def _release_save(self):
        self.release()
        return None

    def _acquire_restore(self, state) -> None:
        self.acquire()

    def _is_owned(self) -> bool:
        # a plain Lock has no owner notion; Condition's own fallback
        # probe, against the UNtimed inner lock
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


class TimedRLock:
    """threading.RLock with named wait/hold accounting: outermost
    acquire records the wait, outermost release the hold; reentrant
    acquires pass straight through (no contention boundary)."""

    __slots__ = ("_inner", "_stat", "_t_acquired", "_depth")

    def __init__(self, name: str):
        self._inner = threading.RLock()
        self._stat = stat_for(name)
        self._t_acquired = 0.0
        # owner-maintained recursion depth (only the holding thread
        # moves it between its outermost acquire and release)
        self._depth = 0

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        inner = self._inner
        if not ACTIVE or (self._depth and inner._is_owned()):
            ok = inner.acquire(blocking, timeout)
            if ok:
                self._depth += 1
            return ok
        t0 = time.perf_counter()
        ok = inner.acquire(blocking, timeout)
        if ok:
            t1 = time.perf_counter()
            self._depth += 1
            if self._depth == 1:
                self._stat.record_wait(t1 - t0)
                self._t_acquired = t1
        return ok

    def release(self) -> None:
        self._depth -= 1
        if ACTIVE and self._depth == 0 and self._t_acquired:
            self._stat.record_hold(
                time.perf_counter() - self._t_acquired
            )
            self._t_acquired = 0.0
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition protocol --------------------------------------------
    def _release_save(self):
        # cv.wait releases ALL recursion levels: close the hold and
        # hand the saved state through
        depth = self._depth
        if ACTIVE and self._t_acquired:
            self._stat.record_hold(
                time.perf_counter() - self._t_acquired
            )
            self._t_acquired = 0.0
        self._depth = 0
        state = self._inner._release_save()
        return (state, depth)

    def _acquire_restore(self, state) -> None:
        inner_state, depth = state
        if not ACTIVE:
            self._inner._acquire_restore(inner_state)
            self._depth = depth
            return
        t0 = time.perf_counter()
        self._inner._acquire_restore(inner_state)
        t1 = time.perf_counter()
        self._stat.record_wait(t1 - t0)
        self._t_acquired = t1
        self._depth = depth

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


# ---------------------------------------------------------------------------
# surfaces: STATS section + METRICS collector
# ---------------------------------------------------------------------------


def snapshot(top: int = 0) -> Dict[str, Any]:
    """{lock_name: {waits, wait_s, wait_max_s, holds, hold_s,
    hold_max_s, wait_hold_ratio}} - the `contention` STATS section.
    `top` > 0 keeps only the N most wait-dominated locks."""
    with _lock:
        stats = list(_STATS.values())
    out = {s.name: s.snapshot() for s in stats}
    if top and len(out) > top:
        keep = sorted(
            out, key=lambda n: -out[n]["wait_s"]
        )[:top]
        out = {n: out[n] for n in keep}
    return out


def top_locks(n: int = 3) -> List[Dict[str, Any]]:
    """The N most wait-dominated locks, worst first - the profile
    report's headline list."""
    snap = snapshot()
    names = sorted(snap, key=lambda k: -snap[k]["wait_s"])[:n]
    return [{"lock": name, **snap[name]} for name in names]


def metrics_samples() -> Iterable[tuple]:
    """Prometheus samples for the process registry: expanded
    histogram series blaze_lock_wait_seconds{lock=...} /
    blaze_lock_hold_seconds{lock=...} (bucket/sum/count), emitted
    through the collector surface so the per-acquire hot path never
    touches the registry lock."""
    with _lock:
        stats = list(_STATS.values())
    for s in stats:
        with s._mu:
            wb = list(s.wait_buckets)
            hb = list(s.hold_buckets)
            rows = (
                ("blaze_lock_wait_seconds", wb, s.wait_total, s.waits),
                ("blaze_lock_hold_seconds", hb, s.hold_total, s.holds),
            )
        for base, buckets, total, n in rows:
            acc = 0
            for b, c in zip(BUCKETS, buckets):
                acc += c
                yield (f"{base}_bucket",
                       {"lock": s.name, "le": repr(b)}, acc, "counter")
            acc += buckets[-1]
            yield (f"{base}_bucket",
                   {"lock": s.name, "le": "+Inf"}, acc, "counter")
            yield (f"{base}_sum", {"lock": s.name},
                   round(total, 6), "counter")
            yield (f"{base}_count", {"lock": s.name}, n, "counter")


def _register_collector() -> None:
    """Idempotent: the process registry serves the lock histograms
    once accounting has ever been enabled (registered outside the
    module lock - register_collector takes the registry's own)."""
    from blaze_tpu.obs.metrics import REGISTRY

    REGISTRY.register_collector("contention", metrics_samples)


def _maybe_activate_from_env() -> None:
    if os.environ.get("BLAZE_CONTENTION"):
        enable()


_maybe_activate_from_env()
