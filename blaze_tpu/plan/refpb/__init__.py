"""Generated bindings for the REFERENCE engine's wire format.

`refplan_pb2.py` is protoc output generated from the reference's
plan-serde contract (/root/reference/native-engine/plan-serde/proto/
plan.proto, the `plan.protobuf` package: PhysicalPlanNode :26-43,
TaskDefinition :508-513). It is regenerated — never hand-edited — with:

    cp <reference>/native-engine/plan-serde/proto/plan.proto /tmp/refplan.proto
    protoc --python_out=blaze_tpu/plan/refpb -I /tmp refplan.proto

The engine's own schema (`blaze_tpu/plan/plan.proto`) stays the native
format; this package exists so a deployment already speaking the
reference's protocol (the Spark extension tier emitting TaskDefinition
bytes over JNI, NativeRDD.scala:41-44) can drive this engine without
changes — see `blaze_tpu.plan.refcompat` for the decoder.
"""

from blaze_tpu.plan.refpb import refplan_pb2  # noqa: F401
