"""Decoder for the REFERENCE engine's wire format.

Maps the reference's `plan.protobuf` message set — `TaskDefinition` /
`PhysicalPlanNode` (reference plan.proto:26-43, :508-513) — onto this
engine's operators, mirroring the role of the reference's own decoder
(`TryInto<Arc<dyn ExecutionPlan>> for &PhysicalPlanNode`,
from_proto.rs:162-560). With this layer, a Spark extension tier that
already emits reference-format task bytes over its gateway
(NativeRDD.scala:41-44 → exec.rs:137-153) can drive this engine
unchanged; SURVEY §7 names that proto contract "the compatibility
anchor".

Coverage follows from_proto.rs's dispatch arms: parquet scan (file
groups / byte ranges / projection / pruning predicate), filter,
projection, sort, union, hash join (CollectLeft), sort-merge join,
hash aggregate (PARTIAL / FINAL / FINAL_PARTITIONED), shuffle writer,
ipc reader/writer, rename-columns, empty-partitions, debug. Unsupported
constructs raise NotImplementedError, which triggers the same per-node
host fallback the engine applies to its native format (the reference's
own convention, BlazeConverters.scala:150-156).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from blaze_tpu.types import DataType, Field, Schema
from blaze_tpu.exprs import ir
from blaze_tpu.exprs.ir import AggExpr, AggFn, Op
from blaze_tpu.ops import (
    DebugExec,
    EmptyPartitionsExec,
    FilterExec,
    HashAggregateExec,
    AggMode,
    HashJoinExec,
    IpcReaderExec,
    IpcReadMode,
    IpcWriterExec,
    JoinType,
    LimitExec,
    ProjectExec,
    RenameColumnsExec,
    ShuffleWriterExec,
    SortExec,
    SortKey,
    SortMergeJoinExec,
    UnionExec,
)
from blaze_tpu.ops.base import PhysicalOp
from blaze_tpu.ops.parquet_scan import FileRange, ParquetScanExec
from blaze_tpu.plan.refpb import refplan_pb2 as rp

# ---------------------------------------------------------------------------
# types
# ---------------------------------------------------------------------------

_ARROW_SIMPLE = {
    "BOOL": DataType.bool_,
    "INT8": DataType.int8,
    "INT16": DataType.int16,
    "INT32": DataType.int32,
    "INT64": DataType.int64,
    # unsigned widths widen to the next signed device representation
    # (the reference's Spark tier never emits unsigned types,
    # NativeConverters.scala:117-213); UINT64 cannot widen and is
    # rejected below rather than silently wrapping >= 2^63
    "UINT8": DataType.int16,
    "UINT16": DataType.int32,
    "UINT32": DataType.int64,
    "FLOAT32": DataType.float32,
    "FLOAT64": DataType.float64,
    "UTF8": DataType.utf8,
    "LARGE_UTF8": DataType.utf8,
    "BINARY": DataType.binary,
    "LARGE_BINARY": DataType.binary,
    "DATE32": DataType.date32,
    "NONE": DataType.null,
}


def dtype_from_ref(at: "rp.ArrowType") -> DataType:
    kind = at.WhichOneof("arrow_type_enum")
    if kind is None:
        raise NotImplementedError("ArrowType with no variant")
    if kind in _ARROW_SIMPLE:
        return _ARROW_SIMPLE[kind]()
    if kind == "TIMESTAMP":
        # the Spark tier always emits microseconds
        # (NativeConverters.scala:147-149); any other unit must not
        # silently mis-scale
        if at.TIMESTAMP.time_unit != rp.Microsecond:
            raise NotImplementedError(
                "timestamp unit "
                + rp.TimeUnit.Name(at.TIMESTAMP.time_unit)
            )
        return DataType.timestamp_us()
    if kind == "DECIMAL":
        return DataType.decimal(
            int(at.DECIMAL.whole), int(at.DECIMAL.fractional)
        )
    if kind == "DICTIONARY":
        # engine columns dictionary-encode strings internally; the
        # logical type is the value type
        return dtype_from_ref(at.DICTIONARY.value)
    raise NotImplementedError(f"reference ArrowType {kind}")


def schema_from_ref(s: "rp.Schema") -> Schema:
    return Schema(
        [
            Field(f.name, dtype_from_ref(f.arrow_type), f.nullable)
            for f in s.columns
        ]
    )


# ---------------------------------------------------------------------------
# scalar values / literals
# ---------------------------------------------------------------------------

_SCALAR_DTYPES = {
    "bool_value": DataType.bool_,
    "utf8_value": DataType.utf8,
    "large_utf8_value": DataType.utf8,
    "int8_value": DataType.int8,
    "int16_value": DataType.int16,
    "int32_value": DataType.int32,
    "int64_value": DataType.int64,
    "uint8_value": DataType.int16,
    "uint16_value": DataType.int32,
    "uint32_value": DataType.int64,
    "float32_value": DataType.float32,
    "float64_value": DataType.float64,
    "date_32_value": DataType.date32,
    "time_microsecond_value": DataType.timestamp_us,
}

_NULL_SCALAR_DTYPES = {
    rp.BOOL: DataType.bool_,
    rp.INT8: DataType.int8,
    rp.INT16: DataType.int16,
    rp.INT32: DataType.int32,
    rp.INT64: DataType.int64,
    rp.FLOAT32: DataType.float32,
    rp.FLOAT64: DataType.float64,
    rp.UTF8: DataType.utf8,
    rp.LARGE_UTF8: DataType.utf8,
    rp.DATE32: DataType.date32,
    rp.TIME_MICROSECOND: DataType.timestamp_us,
    rp.NULL: DataType.null,
}


def literal_from_ref(sv: "rp.ScalarValue") -> ir.Literal:
    kind = sv.WhichOneof("value")
    if kind is None:
        return ir.Literal(None, DataType.null())
    if kind in _SCALAR_DTYPES:
        return ir.Literal(getattr(sv, kind), _SCALAR_DTYPES[kind]())
    if kind == "uint64_value":
        v = int(sv.uint64_value)
        if v >= 1 << 63:
            raise NotImplementedError(
                "uint64 scalar beyond int64 range"
            )
        return ir.Literal(v, DataType.int64())
    if kind == "null_value":
        dt = _NULL_SCALAR_DTYPES.get(sv.null_value)
        if dt is None:
            raise NotImplementedError(
                f"null scalar type {sv.null_value}"
            )
        return ir.Literal(None, dt())
    if kind == "decimal_value":
        d = sv.decimal_value
        # "datafusion has i128 decimal value, only use i64 for blaze"
        # (reference plan.proto:598-601): the wire value is the unscaled
        # i64; precision/scale ride in Decimal{whole, fractional}
        prec = int(d.decimal.whole) or 38
        scale = int(d.decimal.fractional)
        return ir.Literal(
            d.long_value, DataType.decimal(prec, scale)
        )
    raise NotImplementedError(f"reference ScalarValue {kind}")


# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------

# from_proto_binary_op's string table (reference lib.rs:70-86)
_BINOPS = {
    "And": Op.AND,
    "Or": Op.OR,
    "Eq": Op.EQ,
    "NotEq": Op.NEQ,
    "Lt": Op.LT,
    "LtEq": Op.LTE,
    "Gt": Op.GT,
    "GtEq": Op.GTE,
    "Plus": Op.ADD,
    "Minus": Op.SUB,
    "Multiply": Op.MUL,
    "Divide": Op.DIV,
    "Modulo": Op.MOD,
}

_AGG_FNS = {
    rp.MIN: AggFn.MIN,
    rp.MAX: AggFn.MAX,
    rp.SUM: AggFn.SUM,
    rp.AVG: AggFn.AVG,
    rp.COUNT: AggFn.COUNT,
    rp.VARIANCE: AggFn.VAR_SAMP,
    rp.VARIANCE_POP: AggFn.VAR_POP,
    rp.STDDEV: AggFn.STDDEV_SAMP,
    rp.STDDEV_POP: AggFn.STDDEV_POP,
}

# ScalarFunction enum -> engine scalar-fn names (the engine evaluates
# these in exprs/eval.py; anything unmapped raises and falls back)
_SCALAR_FNS = {
    rp.Abs: "abs",
    rp.Acos: "acos",
    rp.Asin: "asin",
    rp.Atan: "atan",
    rp.Ceil: "ceil",
    rp.Cos: "cos",
    rp.Exp: "exp",
    rp.Floor: "floor",
    rp.Ln: "ln",
    rp.Log: "log",
    rp.Log10: "log10",
    rp.Log2: "log2",
    rp.Round: "round",
    rp.Signum: "signum",
    rp.Sin: "sin",
    rp.Sqrt: "sqrt",
    rp.Tan: "tan",
    rp.NullIf: "null_if",
    rp.Lower: "lower",
    rp.Upper: "upper",
    rp.Trim: "trim",
    rp.Ltrim: "ltrim",
    rp.Rtrim: "rtrim",
    rp.Substr: "substr",
    rp.Concat: "concat",
    rp.StartsWith: "starts_with",
    rp.CharacterLength: "length",
    rp.DatePart: "date_part",
}


def expr_from_ref(p: "rp.PhysicalExprNode") -> ir.Expr:
    kind = p.WhichOneof("ExprType")
    if kind == "column":
        # bind by name like the reference's executor does against the
        # input schema (from_proto.rs resolves Column{name,index} by name)
        return ir.Col(p.column.name)
    if kind == "literal":
        return literal_from_ref(p.literal)
    if kind == "binary_expr":
        op = _BINOPS.get(p.binary_expr.op)
        if op is None:
            raise NotImplementedError(
                f"binary op {p.binary_expr.op!r}"
            )
        return ir.BinaryOp(
            op,
            expr_from_ref(p.binary_expr.l),
            expr_from_ref(p.binary_expr.r),
        )
    if kind == "is_null_expr":
        return ir.IsNull(expr_from_ref(p.is_null_expr.expr))
    if kind == "is_not_null_expr":
        return ir.IsNotNull(expr_from_ref(p.is_not_null_expr.expr))
    if kind == "not_expr":
        return ir.Not(expr_from_ref(p.not_expr.expr))
    if kind == "negative":
        return ir.Negate(expr_from_ref(p.negative.expr))
    if kind in ("cast", "try_cast"):
        node = p.cast if kind == "cast" else p.try_cast
        return ir.Cast(
            expr_from_ref(node.expr), dtype_from_ref(node.arrow_type)
        )
    if kind == "in_list":
        return ir.InList(
            expr_from_ref(p.in_list.expr),
            tuple(expr_from_ref(e) for e in p.in_list.list),
            p.in_list.negated,
        )
    if kind == "case_":
        c = p.case_
        base = (
            expr_from_ref(c.expr) if c.HasField("expr") else None
        )
        branches = []
        for wt in c.when_then_expr:
            when = expr_from_ref(wt.when_expr)
            if base is not None:
                when = ir.BinaryOp(Op.EQ, base, when)
            branches.append((when, expr_from_ref(wt.then_expr)))
        otherwise = (
            expr_from_ref(c.else_expr)
            if c.HasField("else_expr")
            else None
        )
        return ir.CaseWhen(tuple(branches), otherwise)
    if kind == "scalar_function":
        f = p.scalar_function
        args = tuple(expr_from_ref(a) for a in f.args)
        if f.fun == rp.SparkExtFunctions:
            # dispatched by name (reference lib.rs:69-80 /
            # spark_ext_function.rs:8-59)
            return ir.ScalarFn(f.name, args)
        if f.fun == rp.Coalesce:
            return ir.Coalesce(args)
        name = _SCALAR_FNS.get(f.fun)
        if name is None:
            raise NotImplementedError(
                f"scalar function {rp.ScalarFunction.Name(f.fun)}"
            )
        return ir.ScalarFn(name, args)
    if kind == "aggregate_expr":
        a = p.aggregate_expr
        fn = _AGG_FNS.get(a.aggr_function)
        if fn is None:
            raise NotImplementedError(
                f"aggregate {rp.AggregateFunction.Name(a.aggr_function)}"
            )
        return AggExpr(fn, expr_from_ref(a.expr))
    if kind == "sort":
        # handled structurally inside SortExecNode decoding
        raise NotImplementedError("bare sort expression")
    raise NotImplementedError(f"reference expr {kind}")


def logical_expr_from_ref(p: "rp.LogicalExprNode") -> ir.Expr:
    """Pruning-predicate (logical) expr tree — only the shapes the scan's
    stats pruner understands (reference: DataFusion PruningPredicate fed
    from the same LogicalExprNode, from_proto.rs:202-212)."""
    kind = p.WhichOneof("ExprType")
    if kind == "column":
        return ir.Col(p.column.name)
    if kind == "literal":
        return literal_from_ref(p.literal)
    if kind == "binary_expr":
        op = _BINOPS.get(p.binary_expr.op)
        if op is None:
            raise NotImplementedError(
                f"binary op {p.binary_expr.op!r}"
            )
        return ir.BinaryOp(
            op,
            logical_expr_from_ref(p.binary_expr.l),
            logical_expr_from_ref(p.binary_expr.r),
        )
    if kind == "not_expr":
        return ir.Not(logical_expr_from_ref(p.not_expr.expr))
    if kind == "between":
        b = p.between
        e = logical_expr_from_ref(b.expr)
        rng = ir.BinaryOp(
            Op.AND,
            ir.BinaryOp(Op.GTE, e, logical_expr_from_ref(b.low)),
            ir.BinaryOp(Op.LTE, e, logical_expr_from_ref(b.high)),
        )
        return ir.Not(rng) if b.negated else rng
    if kind == "cast":
        return ir.Cast(
            logical_expr_from_ref(p.cast.expr),
            dtype_from_ref(p.cast.arrow_type),
        )
    raise NotImplementedError(f"reference logical expr {kind}")


# ---------------------------------------------------------------------------
# plan nodes
# ---------------------------------------------------------------------------

_JOIN_TYPES = {
    rp.INNER: JoinType.INNER,
    rp.LEFT: JoinType.LEFT,
    rp.RIGHT: JoinType.RIGHT,
    rp.FULL: JoinType.FULL,
    rp.SEMI: JoinType.LEFT_SEMI,
    rp.ANTI: JoinType.LEFT_ANTI,
}

_AGG_MODES = {
    rp.PARTIAL: AggMode.PARTIAL,
    rp.FINAL: AggMode.FINAL,
    rp.FINAL_PARTITIONED: AggMode.FINAL,
}

_IPC_MODES = {
    rp.CHANNEL_UNCOMPRESSED: IpcReadMode.CHANNEL_UNCOMPRESSED,
    rp.CHANNEL: IpcReadMode.CHANNEL,
    rp.CHANNEL_AND_FILE_SEGMENT: IpcReadMode.CHANNEL_AND_FILE_SEGMENT,
}


def _join_keys(on) -> Tuple[List[str], List[str]]:
    return (
        [j.left.name for j in on],
        [j.right.name for j in on],
    )


def plan_from_ref(p: "rp.PhysicalPlanNode") -> PhysicalOp:
    kind = p.WhichOneof("PhysicalPlanType")
    if kind == "parquet_scan":
        return _decode_parquet_scan(p.parquet_scan)
    if kind == "filter":
        return FilterExec(
            plan_from_ref(p.filter.input),
            expr_from_ref(p.filter.expr),
        )
    if kind == "projection":
        pr = p.projection
        names = list(pr.expr_name)
        return ProjectExec(
            plan_from_ref(pr.input),
            [
                (expr_from_ref(e), names[i] if i < len(names) else f"c{i}")
                for i, e in enumerate(pr.expr)
            ],
        )
    if kind == "sort":
        s = p.sort
        keys = []
        for e in s.expr:
            if e.WhichOneof("ExprType") != "sort":
                raise NotImplementedError(
                    "SortExecNode.expr must be sort expressions"
                )
            keys.append(
                SortKey(
                    expr_from_ref(e.sort.expr),
                    e.sort.asc,
                    e.sort.nulls_first,
                )
            )
        return SortExec(plan_from_ref(s.input), keys)
    if kind == "union":
        return UnionExec([plan_from_ref(c) for c in p.union.children])
    if kind == "hash_join":
        h = p.hash_join
        if h.HasField("filter") and h.filter.HasField("expression"):
            raise NotImplementedError(
                "join post-filter (reference never emits it: the Spark "
                "tier synthesizes a FilterExec instead)"
            )
        if h.partition_mode != rp.COLLECT_LEFT:
            # the Spark tier only emits CollectLeft
            # (NativeBroadcastHashJoinExec.scala:96-123); the engine's
            # HashJoinExec collects one shared build, which would be
            # wrong for co-partitioned inputs
            raise NotImplementedError("partitioned hash join")
        if h.null_equals_null:
            raise NotImplementedError("null-safe join keys")
        lk, rk = _join_keys(h.on)
        return HashJoinExec(
            plan_from_ref(h.left),
            plan_from_ref(h.right),
            lk,
            rk,
            _JOIN_TYPES[h.join_type],
        )
    if kind == "sort_merge_join":
        h = p.sort_merge_join
        if h.null_equals_null:
            raise NotImplementedError("null-safe join keys")
        lk, rk = _join_keys(h.on)
        return SortMergeJoinExec(
            plan_from_ref(h.left),
            plan_from_ref(h.right),
            lk,
            rk,
            _JOIN_TYPES[h.join_type],
        )
    if kind == "hash_aggregate":
        return _decode_hash_aggregate(p.hash_aggregate)
    if kind == "shuffle_writer":
        s = p.shuffle_writer
        part = s.output_partitioning
        keys = [expr_from_ref(e) for e in part.hash_expr]
        count = int(part.partition_count) or 1
        if not keys and count > 1:
            raise NotImplementedError(
                "multi-partition shuffle writer without hash keys "
                "(the reference's native path requires "
                "HashPartitioning, ArrowShuffleExchangeExec301."
                "scala:248-304)"
            )
        return ShuffleWriterExec(
            plan_from_ref(s.input),
            keys,
            count,
            s.output_data_file,
            s.output_index_file,
            mode="hash" if keys else "single",
        )
    if kind == "ipc_reader":
        r = p.ipc_reader
        return IpcReaderExec(
            r.ipc_provider_resource_id,
            schema_from_ref(r.schema),
            r.num_partitions,
            _IPC_MODES[r.mode],
        )
    if kind == "ipc_writer":
        w = p.ipc_writer
        return IpcWriterExec(
            plan_from_ref(w.input), w.ipc_consumer_resource_id
        )
    if kind == "rename_columns":
        return RenameColumnsExec(
            plan_from_ref(p.rename_columns.input),
            list(p.rename_columns.renamed_column_names),
        )
    if kind == "empty_partitions":
        return EmptyPartitionsExec(
            schema_from_ref(p.empty_partitions.schema),
            p.empty_partitions.num_partitions,
        )
    if kind == "debug":
        return DebugExec(
            plan_from_ref(p.debug.input), p.debug.debug_id
        )
    raise NotImplementedError(f"reference plan node {kind}")


def _decode_parquet_scan(ps: "rp.ParquetScanExecNode") -> PhysicalOp:
    conf = ps.base_conf
    if conf.table_partition_cols:
        # Hive-style partition columns are materialized from directory
        # values, not file bytes (NativeParquetScanExec.scala:61-99);
        # decoding without them would silently drop columns
        raise NotImplementedError(
            "table_partition_cols on parquet scan"
        )
    groups = []
    for g in conf.file_groups:
        files = []
        for f in g.files:
            if f.partition_values:
                raise NotImplementedError(
                    "partition_values on scanned file"
                )
            start, length = 0, 0
            if f.HasField("range"):
                start = int(f.range.start)
                length = int(f.range.end) - int(f.range.start)
                if length <= 0:
                    # degenerate split owns no byte range: it must scan
                    # NOTHING (engine length==0 means whole-file, which
                    # would duplicate rows another split owns)
                    continue
            files.append(FileRange(f.path, start, length))
        groups.append(files)
    schema = (
        schema_from_ref(conf.schema)
        if conf.schema.columns
        else None
    )
    projection = (
        [schema.fields[i].name for i in conf.projection]
        if conf.projection and schema is not None
        else None
    )
    pruning = None
    if ps.HasField("pruning_predicate"):
        try:
            pruning = logical_expr_from_ref(ps.pruning_predicate)
        except NotImplementedError:
            # the predicate is a pure row-group-skipping optimization;
            # an undecodable shape (InList, IsNull, ...) must not cost
            # the scan its native execution
            pruning = None
    op: PhysicalOp = ParquetScanExec(groups, schema, projection, pruning)
    if conf.HasField("limit"):
        op = LimitExec(op, int(conf.limit.limit))
    return op


def _decode_hash_aggregate(
    h: "rp.HashAggregateExecNode",
) -> HashAggregateExec:
    child = plan_from_ref(h.input)
    key_names = list(h.group_expr_name)
    keys = [
        (
            expr_from_ref(e),
            key_names[i] if i < len(key_names) else f"k{i}",
        )
        for i, e in enumerate(h.group_expr)
    ]
    agg_names = list(h.aggr_expr_name)
    aggs = []
    for i, e in enumerate(h.aggr_expr):
        a = expr_from_ref(e)
        if not isinstance(a, AggExpr):
            raise NotImplementedError(
                "aggr_expr must be an aggregate expression"
            )
        aggs.append(
            (a, agg_names[i] if i < len(agg_names) else f"a{i}")
        )
    return HashAggregateExec(
        child, keys=keys, aggs=aggs, mode=_AGG_MODES[h.mode]
    )


# ---------------------------------------------------------------------------
# task entry
# ---------------------------------------------------------------------------

def task_from_reference_proto(data: bytes):
    """Decode reference-format TaskDefinition bytes into
    (op, partition, task_id, resources) — the same contract as the
    engine-native `plan.serde.task_from_proto`, so the runtime's
    decode→fuse→hint pipeline applies unchanged."""
    t = rp.TaskDefinition()
    t.ParseFromString(data)
    op = plan_from_ref(t.plan)
    if (
        t.HasField("output_partitioning")
        and t.output_partitioning.partition_count
        and not isinstance(op, ShuffleWriterExec)
    ):
        raise NotImplementedError(
            "TaskDefinition.output_partitioning without a shuffle "
            "writer plan (the reference builds the writer into the "
            "plan, ArrowShuffleExchangeExec301.scala:554-564)"
        )
    tid = t.task_id
    task_id = f"{tid.job_id}/{tid.stage_id}/{tid.partition_id}"
    return op, int(tid.partition_id), task_id, {}


def execute_reference_task(task_bytes: bytes, ctx=None):
    """Run one reference-format task end-to-end; yields Arrow record
    batches exactly like `runtime.executor.execute_task` does for the
    native format (the FFI boundary role, exec.rs:205-255)."""
    from blaze_tpu.runtime.executor import (
        ExecContext,
        execute_partition,
        prepare_decoded_task,
    )

    ctx = ctx or ExecContext()
    op, partition = prepare_decoded_task(
        task_from_reference_proto(task_bytes), ctx
    )
    yield from execute_partition(op, partition, ctx)
