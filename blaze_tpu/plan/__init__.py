"""Plan serde: the protobuf boundary of the engine (reference
native-engine/plan-serde)."""
